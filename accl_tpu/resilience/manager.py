"""The detect -> exclude -> re-synthesize -> re-certify -> hot-swap loop.

At production scale a wedged peer is routine, not exceptional (the
ACCL+ operational report's dominant pain is exactly the post-dispatch
hang), and this repo has had every ingredient except the loop itself:
deadlines from the model (``resilience.deadline``), schedules as data
(the hop-DAG IR), a generator over arbitrary worlds
(``synthesis.search`` + the ring constructors), and the full certifier
stack (semantics ACCL501-504 + exhaustive-interleaving modelcheck
ACCL205-207).  :class:`ResilienceManager` is the loop:

  1. **detect** — deadline-miss verdicts stream in (``record_miss``);
     a retry/backoff budget distinguishes a transient straggler (the
     drift sentinel's department) from a dead peer, so the expensive
     membership change is paid only when retries keep missing;
  2. **exclude** — the suspect leaves the live set (suspect named by
     the verdict's straggler attribution, or by silence: the one live
     rank that never reported the wave every survivor reported);
  3. **re-plan** — a recovery schedule over the surviving P-1 world:
     the committed synthesized library / ``synthesis.search`` where
     the survivor world has entries (power-of-two worlds), else the
     ring constructors (any world extent) — schedules are data, so
     both land in the same certifiable form;
  4. **re-certify** — the winner runs the EXISTING prove stack
     (semantic certification against its declared collective + the
     canonical protocol simulation + the exhaustive-interleaving model
     checker; zero new checker code).  An uncertified recovery plan is
     NEVER installed: :class:`UncertifiedRecoveryError` is a loud
     failure, because shipping an unproven schedule to a cluster that
     just lost a rank is how one outage becomes two;
  5. **hot-swap** — ``install`` publishes the certified plan under the
     manager lock with a bumped generation; executors consult
     ``current_plan``/``generation`` at DISPATCH BOUNDARIES (between
     calls / between sequence dispatches), so in-flight programs drain
     on the old membership and the next dispatch runs the new one.

The SCCL prove-don't-test posture is what makes step 5 safe without a
validation soak: the recovery plan that was never run before is
*proven* to compute its declared collective before the first dispatch.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any

from ..constants import Operation, ReduceFunction, TuningParams
from ..descriptor import CallOptions
from .deadline import DeadlineMissed, DeadlinePolicy


@dataclasses.dataclass(frozen=True)
class IntegrityFault:
    """Structured verdict for a LOSSY link: the suspect's frames are
    arriving-but-damaged (the observers' wire-health counters show CRC
    drops / retransmits / nack round-trips climbing), so the transport's
    reliability sublayer is absorbing the fault below this layer — the
    wrong response is a ~1 s certified reconfiguration.  Raised (as a
    recorded verdict, like :class:`DeadlineMissed`) instead of consuming
    the dead-rank retry budget; only a genuinely dark wire escalates to
    the exclude→replan path (docs/resilience.md escalation policy)."""

    op: str
    count: int
    suspect_rank: int | None
    crc_drops: int = 0
    dup_drops: int = 0
    retransmits: int = 0
    retx_misses: int = 0
    nack_round_trips: int = 0
    elapsed_s: float = 0.0
    post_mortem: dict | None = None

    def verdict(self) -> dict[str, Any]:
        """JSON-ready rendering (the chaos-gate artifact / logs)."""
        out: dict[str, Any] = {
            "kind": "integrity_fault",
            "op": self.op,
            "count": self.count,
            "crc_drops": self.crc_drops,
            "dup_drops": self.dup_drops,
            "retransmits": self.retransmits,
            "retx_misses": self.retx_misses,
            "nack_round_trips": self.nack_round_trips,
            "elapsed_s": self.elapsed_s,
        }
        if self.suspect_rank is not None:
            out["suspect_rank"] = self.suspect_rank
        out["post_mortem_spans"] = (len(self.post_mortem.get("spans", []))
                                    if self.post_mortem else 0)
        return out

    def __str__(self) -> str:
        sus = (f" suspect r{self.suspect_rank};"
               if self.suspect_rank is not None else "")
        return (f"IntegrityFault: {self.op} count={self.count};{sus} "
                f"lossy link absorbed below the resilience layer "
                f"(crc_drops={self.crc_drops} dup_drops={self.dup_drops} "
                f"retransmits={self.retransmits} "
                f"nack_rtt={self.nack_round_trips}) — no reconfiguration")


class UncertifiedRecoveryError(RuntimeError):
    """A candidate recovery plan failed re-certification — refusing to
    install it is the whole point (loud failure, never a silent
    degrade to an unproven schedule)."""

    def __init__(self, message: str, diagnostics: tuple = ()):
        self.diagnostics = tuple(diagnostics)
        lines = [message]
        lines += [f"  {d}" for d in self.diagnostics]
        super().__init__("\n".join(lines))


@dataclasses.dataclass(frozen=True)
class RetryBudget:
    """How long a suspect stays a *straggler* before it is a *corpse*:
    ``max_retries`` re-attempts, each preceded by an exponential
    backoff (transient congestion clears; a dead peer keeps missing),
    before the manager recommends exclusion and the reconfiguration
    cost is paid."""

    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0

    def delay_s(self, attempt: int) -> float:
        return self.backoff_base_s * self.backoff_factor ** max(attempt, 0)


@dataclasses.dataclass(frozen=True)
class RecoveryPlan:
    """One certified recovery schedule over a survivor world.

    ``survivors`` are GLOBAL rank ids (the membership of the recovery
    communicator); ``world`` their count; ``plan`` the XLA-tier Plan
    selection resolved for the survivor world (``synth_key`` set when
    the committed synthesized library serves the cell);
    ``certificate`` records exactly which proofs ran clean — a plan
    object without a clean certificate cannot be constructed through
    ``ResilienceManager.replan``."""

    op: str
    survivors: tuple[int, ...]
    world: int
    count: int
    source: str  # "synthesized" | "ring"
    plan: Any
    synth_key: str = ""
    certificate: dict = dataclasses.field(default_factory=dict)
    generation: int = 0


class ResilienceManager:
    """Membership + recovery-plan state machine (module docstring).

    Thread-safe: verdicts arrive from whatever thread drove the failed
    wait; installs happen under the same lock the readers take."""

    def __init__(self, world: int, *, policy: DeadlinePolicy | None = None,
                 budget: RetryBudget | None = None,
                 rx_buf_bytes: int = 4096,
                 max_eager_size: int = 4096,
                 tuning: TuningParams | None = None,
                 integrity_budget: int = 3):
        self.world = int(world)
        self.policy = policy
        self.budget = budget if budget is not None else RetryBudget()
        self.rx_buf_bytes = int(rx_buf_bytes)
        self.max_eager_size = int(max_eager_size)
        self.tuning = tuning if tuning is not None else TuningParams.default()
        self._mu = threading.Lock()
        self._live: tuple[int, ...] = tuple(range(self.world))
        self._attempts: dict[int | None, int] = {}
        self._misses: list[DeadlineMissed] = []
        self._current: RecoveryPlan | None = None
        self._generation = 0
        # wire-health evidence (the stats2 surface): last snapshot per
        # OBSERVER rank + the lossy-link verdicts that never became
        # reconfigurations. integrity_budget bounds how many CONSECUTIVE
        # lossy verdicts one suspect may bank before assess_miss stops
        # crediting the transport and walks the dead-rank budget anyway:
        # the wire deltas are world-global, so a rank that dies while
        # OTHER links are lossy would otherwise classify lossy forever —
        # a livelock with no path to the certified reconfiguration.
        # Reset by note_recovery (a suspect whose retries succeed was a
        # genuinely lossy link doing its job).
        self.integrity_budget = int(integrity_budget)
        self._wire_snapshots: dict[int, dict] = {}
        self._integrity_faults: list[IntegrityFault] = []
        self._integrity_streak: dict[int | None, int] = {}
        # facade shapes whose first (possibly compiling) call has been
        # seen — observe_call's warm-up exemption
        self._warmed_shapes: set[tuple] = set()

    # -- state -------------------------------------------------------------

    @property
    def live_ranks(self) -> tuple[int, ...]:
        with self._mu:
            return self._live

    @property
    def generation(self) -> int:
        with self._mu:
            return self._generation

    @property
    def current_plan(self) -> RecoveryPlan | None:
        """The installed recovery plan, read at dispatch boundaries."""
        with self._mu:
            return self._current

    @property
    def misses(self) -> tuple[DeadlineMissed, ...]:
        with self._mu:
            return tuple(self._misses)

    # -- detect ------------------------------------------------------------

    def record_miss(self, miss: DeadlineMissed) -> str:
        """Feed one deadline-miss verdict; returns the recommended
        action: ``"retry"`` while the suspect's budget lasts (caller
        sleeps ``retry_delay_s()`` then re-attempts), ``"exclude"``
        once it is exhausted (the suspect is a corpse, pay the
        reconfiguration)."""
        with self._mu:
            self._misses.append(miss)
            key = miss.suspect_rank
            n = self._attempts.get(key, 0) + 1
            self._attempts[key] = n
            return "retry" if n <= self.budget.max_retries else "exclude"

    # -- escalation policy: lossy link vs dead rank ------------------------

    @property
    def integrity_faults(self) -> tuple[IntegrityFault, ...]:
        with self._mu:
            return tuple(self._integrity_faults)

    def observe_wire_health(self, rank: int, stats: dict) -> dict:
        """Feed one OBSERVER rank's wire-health counter snapshot
        (``EmuRank.wire_stats()`` / ``TPUDevice.wire_stats()``; the
        telemetry ``wire_health_report`` rows carry the same dicts) and
        return the delta since that rank's previous snapshot.  The
        deltas are the escalation policy's evidence: survivors watching
        a LOSSY suspect show repair activity (CRC drops, retransmits,
        nack round-trips) climbing; survivors watching a DEAD one show
        silence."""
        with self._mu:
            prev = self._wire_snapshots.get(rank, {})
            delta = {k: int(v) - int(prev.get(k, 0))
                     for k, v in stats.items()
                     if isinstance(v, (int, float))}
            self._wire_snapshots[rank] = dict(stats)
        return delta

    @staticmethod
    def classify_wire_delta(delta: dict | None) -> str:
        """``"lossy"`` when the delta window shows fault-REPAIR activity
        (the transport is absorbing damage: any of
        ``telemetry.export.WIRE_FAULT_KEYS`` moved), else ``"dark"`` —
        frames are not arriving damaged, they are not arriving at all,
        which is what a dead rank's silence looks like to a survivor."""
        from ..telemetry.export import WIRE_FAULT_KEYS

        if not delta:
            return "dark"
        return ("lossy"
                if any(int(delta.get(k, 0)) > 0 for k in WIRE_FAULT_KEYS)
                else "dark")

    def assess_miss(self, miss: DeadlineMissed,
                    wire_delta: dict | None = None) -> str:
        """The escalation decision for one deadline miss, wire-health
        aware (docs/resilience.md decision tree): a LOSSY delta raises
        a structured :class:`IntegrityFault` (flight-recorder
        post-mortem carried over from the miss) and returns
        ``"integrity"`` — the transport's retransmit budget is doing
        its job, the dead-rank retry budget is NOT consumed and no
        reconfiguration is recommended; a DARK delta falls through to
        :meth:`record_miss`'s retry/exclude budget.

        The lossy credit is BOUNDED per suspect (``integrity_budget``
        consecutive verdicts, reset by :meth:`note_recovery`): wire
        deltas are world-global evidence, so a rank that dies while
        other links are lossy would otherwise bank IntegrityFaults
        forever and the certified reconfiguration would never be
        reached — past the budget the miss walks the dead-rank
        retry/exclude path even under a lossy classification."""
        if self.classify_wire_delta(wire_delta) == "lossy":
            with self._mu:
                streak = self._integrity_streak.get(
                    miss.suspect_rank, 0) + 1
                self._integrity_streak[miss.suspect_rank] = streak
            if streak > self.integrity_budget:
                return self.record_miss(miss)
            d = wire_delta or {}
            fault = IntegrityFault(
                op=miss.op, count=miss.count,
                suspect_rank=miss.suspect_rank,
                crc_drops=int(d.get("crc_drops", 0)),
                dup_drops=int(d.get("dup_drops", 0)),
                retransmits=int(d.get("retx_sent", 0)),
                retx_misses=int(d.get("retx_miss", 0)),
                nack_round_trips=int(d.get("nack_rx", 0)),
                elapsed_s=miss.elapsed_s,
                post_mortem=miss.post_mortem)
            with self._mu:
                self._integrity_faults.append(fault)
                self._misses.append(miss)
            return "integrity"
        return self.record_miss(miss)

    def retry_delay_s(self, suspect_rank: int | None = None) -> float:
        with self._mu:
            return self.budget.delay_s(
                self._attempts.get(suspect_rank, 1) - 1)

    def note_recovery(self, suspect_rank: int | None = None) -> None:
        """A retry SUCCEEDED: the suspect was a transient straggler,
        not a corpse — its budget resets (the sentinel, not the
        recovery loop, owns chronic slowness), and so does its
        lossy-credit streak (a lossy link that keeps recovering is the
        transport doing its job, not a masked death)."""
        with self._mu:
            self._attempts.pop(suspect_rank, None)
            self._integrity_streak.pop(suspect_rank, None)

    def reset_warmup(self) -> None:
        """Forget the facade warm-up exemptions — call when compiled
        programs were invalidated (``ACCL.soft_reset`` does): the next
        dispatch of every shape recompiles, and timing it against a
        wire deadline would flag a healthy world."""
        with self._mu:
            self._warmed_shapes.clear()

    def attribute_silent(self, reporters) -> int | None:
        """Straggler attribution by SILENCE: the one live rank that
        never reported the wave every other survivor reported is the
        suspect (a dead peer produces no verdicts — absence is the
        signal).  None unless exactly one rank is silent."""
        with self._mu:
            silent = [r for r in self._live if r not in set(reporters)]
        return silent[0] if len(silent) == 1 else None

    # -- exclude -----------------------------------------------------------

    def exclude(self, rank: int) -> tuple[int, ...]:
        """Remove a dead rank from the live set; returns the
        survivors. At least two members must remain (a 1-rank
        'collective' needs no recovery plan — and losing quorum is an
        operator problem, not a schedule problem)."""
        with self._mu:
            if rank not in self._live:
                raise ValueError(f"rank {rank} is not live ({self._live})")
            survivors = tuple(r for r in self._live if r != rank)
            if len(survivors) < 2:
                raise ValueError(
                    f"excluding rank {rank} leaves {survivors}: below "
                    "the 2-rank floor a recovery plan is meaningless")
            self._live = survivors
            self._attempts.pop(rank, None)
            return survivors

    # -- re-plan + re-certify ----------------------------------------------

    def replan(self, op: Operation = Operation.allreduce, *,
               count: int, elem_bytes: int = 4,
               function: ReduceFunction = ReduceFunction.SUM,
               ) -> RecoveryPlan:
        """Build and CERTIFY a recovery schedule over the current
        survivor world.  The survivor world is dense (communicator-
        relative ranks 0..P'-1; the membership mapping to global ranks
        lives in the returned ``survivors`` — exactly what a recovery
        communicator's rank table encodes).  Selection: the committed
        synthesized library where a certified entry's committed
        winning window covers the (op, world, payload) cell, else the
        ring constructors (any world extent).  EVERY candidate —
        library entries included — re-runs the full prove stack here
        before the plan object exists; failure raises
        :class:`UncertifiedRecoveryError` and nothing is installed."""
        from ..sequencer import synthesis
        from ..sequencer.plan import Algorithm, Plan, Protocol, \
            select_algorithm

        with self._mu:
            survivors = self._live
            generation = self._generation + 1
        new_world = len(survivors)
        source, synth_key = "ring", ""
        plan: Any = None
        # 1. committed library: a certified entry whose window covers
        # the payload on the survivor world (power-of-two worlds ship
        # w2/4/8/16 entries)
        key = synthesis.select_entry(op, new_world, count * elem_bytes)
        if key is not None:
            plan = Plan(Protocol.EAGER, Algorithm.SYNTHESIZED, count, 1,
                        synth_key=key)
            source, synth_key = "synthesized", key
        if plan is None:
            plan = select_algorithm(
                op, count, elem_bytes, new_world,
                max_eager_size=self.max_eager_size,
                eager_rx_buf_size=self.rx_buf_bytes,
                tuning=self.tuning)
        certificate = self._certify(op, plan, new_world, count,
                                    function, source, synth_key)
        return RecoveryPlan(op=op.name, survivors=survivors,
                            world=new_world, count=count, source=source,
                            plan=plan, synth_key=synth_key,
                            certificate=certificate,
                            generation=generation)

    def _certify(self, op: Operation, plan: Any, world: int, count: int,
                 function: ReduceFunction, source: str,
                 synth_key: str) -> dict:
        """The existing prove stack over the candidate's hop-DAG: lift
        (or regenerate, for library entries) the schedule, certify the
        contribution sets against the declared collective
        (ACCL501-504), simulate the canonical protocol run, and
        model-check every legal match order (ACCL205-207). Returns the
        certificate record; raises on ANY diagnostic."""
        from ..analysis import semantics
        from ..analysis.hopdag import rank_programs, validate_order
        from ..analysis.linter import SequenceLinter
        from ..analysis.protocol import simulate
        from ..sequencer import synthesis

        opts = CallOptions(scenario=op, count=count,
                           function=int(function))
        opts.data_type = _f32()
        if source == "synthesized":
            spec = synthesis.entry_for_key(synth_key).spec
            cert_count = synthesis.canonical_count(spec)
            dag = synthesis.instantiate(
                spec, cert_count,
                func="max" if function == ReduceFunction.MAX else "sum")
            cert_opts = dataclasses.replace(opts, count=cert_count)
        else:
            cert_count = count
            dag = semantics.lift_call(opts, plan, world)
            cert_opts = opts
        diags = list(validate_order(dag))
        diags += semantics.certify(
            dag, semantics.collective_spec(cert_opts, world), op.name)
        programs = rank_programs(dag)
        diags += simulate(programs, blocking_sends=False)
        if not diags:
            diags += SequenceLinter(world).check_interleavings(programs)
        if diags:
            raise UncertifiedRecoveryError(
                f"recovery plan ({source}, {op.name} w{world}) failed "
                f"re-certification — NOT installed:",
                tuple(diags))
        return {
            "op": op.name,
            "world": world,
            "count": cert_count,
            "source": source,
            "synth_key": synth_key,
            "checks": ["order", "semantics(ACCL501-504)",
                       "protocol-simulate",
                       "modelcheck(ACCL205-207)"],
            "diagnostics": 0,
        }

    # -- hot-swap ----------------------------------------------------------

    def install(self, plan: RecoveryPlan) -> int:
        """Publish a certified recovery plan at a dispatch boundary:
        the generation bump is what tells executors mid-drain that the
        NEXT dispatch runs the new membership.  Only plans built by
        ``replan`` carry a certificate; installing anything without
        one is refused (the loud-failure contract end to end)."""
        if not plan.certificate or plan.certificate.get("diagnostics") != 0:
            raise UncertifiedRecoveryError(
                "refusing to install a recovery plan without a clean "
                "certificate")
        with self._mu:
            if tuple(plan.survivors) != self._live:
                raise ValueError(
                    f"plan membership {plan.survivors} does not match "
                    f"the live set {self._live}: replan after the "
                    "membership change, not before")
            self._current = plan
            self._generation += 1
            self._attempts.clear()
            return self._generation

    # -- degraded mode -----------------------------------------------------

    def degraded_live_ranks(self) -> tuple[int, ...]:
        """The survivor set in the ORIGINAL world's rank space — the
        ``live_ranks`` argument of ``allreduce(mode="live_subset")``:
        the full-world program keeps running (dead ranks relay masked
        zeros) and the certifier proves exactly whose data is in the
        answer."""
        with self._mu:
            return self._live

    # -- the facade seam (armed deadlines on eager calls) ------------------

    def observe_call(self, op: Operation, count: int, elem_bytes: int,
                     elapsed_s: float) -> DeadlineMissed | None:
        """Post-completion deadline check for a facade call (the
        ``ACCL.arm_resilience`` seam): with a policy armed, a call
        that outlived its derived deadline produces the structured
        verdict (flight-recorder post-mortem attached) and is
        recorded; the call itself already completed — nothing is
        raised on this path.

        The FIRST observation of each (op, count, elem_bytes) shape is
        a warm-up, never checked: the facade's wall time includes the
        one-time XLA compile of a fresh program shape (orders of
        magnitude over any wire deadline), and flagging it would
        freeze a spurious post-mortem and burn retry budget on a
        perfectly healthy world.  Deadlines are a steady-state claim."""
        if self.policy is None:
            return None
        if op in (Operation.config, Operation.nop, Operation.copy,
                  Operation.combine):
            return None  # no wire, no deadline
        shape = (op, int(count), int(elem_bytes))
        with self._mu:
            if shape not in self._warmed_shapes:
                self._warmed_shapes.add(shape)
                return None
        miss = self.policy.check(op, count, elem_bytes, elapsed_s)
        if miss is not None:
            self.record_miss(miss)
        return miss


def _f32():
    from ..constants import DataType

    return DataType.float32
