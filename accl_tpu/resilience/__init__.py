"""Self-healing collectives: predicted deadlines, rank-death detection,
and certified live reconfiguration (docs/resilience.md).

Three pieces, each reusing an existing proof or measurement surface
instead of growing a parallel one:

  - ``deadline``: per-call deadlines DERIVED from ``timing.predict``
    under the calibrated link plus the drift sentinel's residual
    tolerance band — the fixed ``RECEIVE_TIMEOUT`` posture replaced by
    the model the framework already trusts for selection.  A miss is a
    structured :class:`DeadlineMissed` verdict with the flight-recorder
    post-mortem attached and per-rank straggler attribution naming the
    suspect.

  - ``manager``: :class:`ResilienceManager` runs the
    detect -> exclude -> re-synthesize -> re-certify -> hot-swap loop —
    a retry/backoff budget distinguishes transient stragglers from dead
    peers; the recovery schedule over the surviving P-1 world comes
    from the committed synthesized library or the ring constructors and
    is re-proven through the EXISTING semantics + modelcheck stack
    before install (an uncertified recovery plan is a loud
    :class:`UncertifiedRecoveryError`, never a silent degrade).

  - the certified degraded mode rides the facade:
    ``ACCL.allreduce(mode="live_subset", live_ranks=...)`` declares the
    surviving-contributor set in the descriptor, the schedule masks
    non-members to exact zeros at the source, and the semantic
    certifier proves exactly which ranks' data is in the answer (the
    ACCL501-proven alltoallv drop-to-zeros posture generalized to the
    reduction).

Below the loop sits the transport's reliability sublayer (CRC32C
frames + selective retransmit, ``native/src/runtime.cpp``): transient
wire faults are repaired in microseconds at the transport, and the
manager's escalation policy (``assess_miss`` over per-rank wire-health
deltas) tells a LOSSY link — frames arriving-but-damaged, a structured
:class:`IntegrityFault`, no reconfiguration — from a genuinely DARK
one, which alone walks the retry→exclude→replan path.

Measured end to end by ``bench.py --fault-gate`` and ``--chaos-gate``
(CI): a mid-stream rank death on the native emulated world recovers
within the bounded retry+reconfigure budget with zero wrong answers
(armed-deadline control <3% overhead over unarmed waits), and the
seeded loss/corrupt/dup/reorder soak stays bitwise with zero false
dead-rank escalations under <3% no-fault CRC+ack overhead.
"""

from .deadline import (  # noqa: F401
    DEFAULT_DEADLINE_FLOOR_S,
    DEFAULT_UNARMED_REFERENCE,
    DeadlineMissed,
    DeadlineMissedError,
    DeadlinePolicy,
    NativeDeadlineGuard,
)
from .manager import (  # noqa: F401
    IntegrityFault,
    RecoveryPlan,
    ResilienceManager,
    RetryBudget,
    UncertifiedRecoveryError,
)

__all__ = [
    "DEFAULT_DEADLINE_FLOOR_S",
    "DEFAULT_UNARMED_REFERENCE",
    "DeadlineMissed",
    "DeadlineMissedError",
    "DeadlinePolicy",
    "IntegrityFault",
    "NativeDeadlineGuard",
    "RecoveryPlan",
    "ResilienceManager",
    "RetryBudget",
    "UncertifiedRecoveryError",
]
