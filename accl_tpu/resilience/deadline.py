"""Per-call deadlines from the calibrated timing model.

The fixed ``RECEIVE_TIMEOUT`` posture (one configured number for every
call) is the reference driver's: honest for a hardware data plane with
one message size, wrong for a framework whose calibrated cost model
already knows what every planned call *should* take.  This module
replaces the constant with a DERIVED deadline:

    deadline(call) = predicted(call) * (1 + tolerance(op)) + floor_s

where ``predicted`` is ``timing.predict`` under the calibrated link for
the plan the shared selection rules resolve (the same estimate every
traced span carries), and ``tolerance`` reuses the drift sentinel's
band semantics (``telemetry.metrics.DriftSentinel``): a reference
median relative residual — the calibration's honest error in the
current regime, armed from measured spans — widened by the same
``max(ref * band_factor, ref + band_floor)`` rule the sentinel's
band-leave verdict uses.  A call that outlives its band-widened
prediction is not "slow": it is OUT OF MODEL, the same claim the
sentinel makes about a regime change — except here it is actionable
per call, while the data is still recoverable.  ``floor_s`` is an
absolute scheduling-noise floor so microsecond predictions never arm
microsecond deadlines.

A miss produces a structured :class:`DeadlineMissed` verdict — op,
count, predicted vs elapsed, the sticky retcode if the executor
produced one, straggler attribution naming the suspect — with the
flight-recorder post-mortem attached (``recorder.on_deadline_miss``
freezes the span rings on a HOST-side verdict, not only on sticky
native retcodes: a silent hang inside the old tolerance window used to
leave no artifact).

:class:`NativeDeadlineGuard` applies the policy to native EmuRank
calls: it points the rank's in-call recv deadline (``set_timeout``) at
the model-derived value and bounds the host-side wait the same way, so
a wedged peer surfaces as a typed :class:`DeadlineMissedError` within
one band-widened prediction instead of a fixed constant later.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

from ..constants import (
    CfgFunc,
    Operation,
    TuningParams,
    error_code_to_string,
)
from ..descriptor import CallOptions

# the sentinel's band constants are the one source of band semantics
from ..telemetry.metrics import (
    DEFAULT_SENTINEL_BAND_FACTOR,
    DEFAULT_SENTINEL_BAND_FLOOR,
)
from ..telemetry.export import median as _median

# unarmed tolerance reference: before any measured residuals exist the
# policy assumes the model may be off by its own magnitude (rel err
# 1.0) — deliberately loose, never a constant timeout in disguise; arm
# a measured reference to tighten it
DEFAULT_UNARMED_REFERENCE = 1.0
# absolute floor under every deadline: host scheduling noise exists at
# any payload size, so a microsecond prediction never arms a
# microsecond deadline
DEFAULT_DEADLINE_FLOOR_S = 0.05


@dataclasses.dataclass(frozen=True)
class DeadlineMissed:
    """Structured verdict for one missed per-call deadline."""

    op: str
    count: int
    predicted_s: float
    deadline_s: float
    elapsed_s: float
    rank: int | None = None
    retcode: int = 0
    suspect_rank: int | None = None
    attribution: str = ""
    post_mortem: dict | None = None

    def verdict(self) -> dict[str, Any]:
        """JSON-ready rendering (the fault-gate artifact / logs)."""
        out: dict[str, Any] = {
            "kind": "deadline_missed",
            "op": self.op,
            "count": self.count,
            "predicted_s": self.predicted_s,
            "deadline_s": self.deadline_s,
            "elapsed_s": self.elapsed_s,
        }
        if self.rank is not None:
            out["rank"] = self.rank
        if self.retcode:
            out["retcode"] = self.retcode
            out["retcode_str"] = error_code_to_string(self.retcode)
        if self.suspect_rank is not None:
            out["suspect_rank"] = self.suspect_rank
            out["attribution"] = self.attribution
        out["post_mortem_spans"] = (len(self.post_mortem.get("spans", []))
                                    if self.post_mortem else 0)
        return out

    def __str__(self) -> str:
        sus = (f"; suspect r{self.suspect_rank} ({self.attribution})"
               if self.suspect_rank is not None else "")
        rc = (f"; sticky {error_code_to_string(self.retcode)}"
              if self.retcode else "")
        return (f"DeadlineMissed: {self.op} count={self.count} elapsed "
                f"{self.elapsed_s * 1e3:.1f} ms > deadline "
                f"{self.deadline_s * 1e3:.1f} ms (predicted "
                f"{self.predicted_s * 1e3:.1f} ms){rc}{sus}")


class DeadlineMissedError(RuntimeError):
    """Typed raise carrying the structured verdict (guard waits)."""

    def __init__(self, miss: DeadlineMissed):
        self.miss = miss
        super().__init__(str(miss))


class DeadlinePolicy:
    """Derive per-call deadlines from the calibrated link + a residual
    tolerance band (module docstring for the formula).

    ``link`` is a ``timing.LinkParams`` (the calibrated fit the
    predictions and the drift sentinel already ride).  ``aggregate``
    selects the serialized-host cost shape (the emulator tier's
    calibration regime — the default, matching the native worlds the
    guard drives) vs the critical path.  Deadlines are cached per
    (op, count, elem_bytes): the armed hot path is a dict hit.
    """

    def __init__(self, link: Any, world: int, *,
                 rx_buf_bytes: int = 4096,
                 max_eager_size: int = 4096,
                 tuning: TuningParams | None = None,
                 aggregate: bool = True,
                 band_factor: float = DEFAULT_SENTINEL_BAND_FACTOR,
                 band_floor: float = DEFAULT_SENTINEL_BAND_FLOOR,
                 floor_s: float = DEFAULT_DEADLINE_FLOOR_S):
        if link is None:
            raise ValueError(
                "DeadlinePolicy needs a calibrated LinkParams — without "
                "one a 'derived' deadline would be a constant in "
                "disguise (calibrate_from_trace / default_link)")
        self.link = link
        self.world = int(world)
        self.rx_buf_bytes = int(rx_buf_bytes)
        self.max_eager_size = int(max_eager_size)
        self.tuning = tuning if tuning is not None else TuningParams.default()
        self.aggregate = bool(aggregate)
        self.band_factor = float(band_factor)
        self.band_floor = float(band_floor)
        self.floor_s = float(floor_s)
        self._reference: dict[str, float] = {}
        self._cache: dict[tuple, tuple[float, float]] = {}

    # -- tolerance band (the sentinel's semantics) -------------------------

    def arm_reference(self, op: str | Operation,
                      median_rel_err: float) -> None:
        """Pin an op's reference residual — the calibration's honest
        median |pred-meas|/meas in the current regime (the number the
        drift sentinel arms its frozen band from)."""
        self._reference[self._op_name(op)] = float(median_rel_err)
        self._cache.clear()

    def arm_from_residuals(self, op: str | Operation,
                           residuals: list[float]) -> float:
        """Arm from measured residual samples (their median)."""
        ref = float(_median(list(residuals)))
        self.arm_reference(op, ref)
        return ref

    def tolerance(self, op: str | Operation) -> float:
        """Relative tolerance above the prediction: the sentinel's
        ``max(ref * band_factor, ref + band_floor)`` widening of the
        armed reference (an unarmed op uses the deliberately loose
        DEFAULT_UNARMED_REFERENCE)."""
        ref = self._reference.get(self._op_name(op),
                                  DEFAULT_UNARMED_REFERENCE)
        return max(ref * self.band_factor, ref + self.band_floor)

    @staticmethod
    def _op_name(op: str | Operation) -> str:
        return op.name if isinstance(op, Operation) else str(op)

    @staticmethod
    def _op_enum(op: str | Operation) -> Operation:
        return op if isinstance(op, Operation) else Operation[str(op)]

    # -- prediction + deadline ---------------------------------------------

    def _predict_deadline(self, op: Operation, count: int,
                          elem_bytes: int) -> tuple[float, float]:
        key = (op, int(count), int(elem_bytes))
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        from ..sequencer.plan import select_algorithm
        from ..sequencer.timing import predict

        plan = select_algorithm(
            op, int(count), int(elem_bytes), self.world,
            max_eager_size=self.max_eager_size,
            eager_rx_buf_size=self.rx_buf_bytes,
            tuning=self.tuning)
        pred = predict(self.link, op, plan, int(count), int(elem_bytes),
                       self.world, rx_buf_bytes=self.rx_buf_bytes,
                       aggregate=self.aggregate)
        dl = pred * (1.0 + self.tolerance(op)) + self.floor_s
        self._cache[key] = (pred, dl)
        return pred, dl

    def predict_and_deadline(self, op: str | Operation, count: int,
                             elem_bytes: int = 4) -> tuple[float, float]:
        """(predicted_s, deadline_s) in one cached lookup — the armed
        hot path's single call (the <3% overhead budget is measured
        with this on every dispatch)."""
        return self._predict_deadline(self._op_enum(op), count,
                                      elem_bytes)

    def predict_s(self, op: str | Operation, count: int,
                  elem_bytes: int = 4) -> float:
        return self._predict_deadline(self._op_enum(op), count,
                                      elem_bytes)[0]

    def deadline_s(self, op: str | Operation, count: int,
                   elem_bytes: int = 4) -> float:
        return self._predict_deadline(self._op_enum(op), count,
                                      elem_bytes)[1]

    def deadline_ms(self, op: str | Operation, count: int,
                    elem_bytes: int = 4) -> int:
        return max(int(self.deadline_s(op, count, elem_bytes) * 1e3), 1)

    # -- the miss verdict --------------------------------------------------

    def check(self, op: str | Operation, count: int, elem_bytes: int,
              elapsed_s: float, *, rank: int | None = None,
              retcode: int = 0, suspect_rank: int | None = None,
              attribution: str = "") -> DeadlineMissed | None:
        """Post-hoc deadline check for one completed (or failed) call:
        returns the structured verdict when ``elapsed_s`` exceeded the
        derived deadline (with the flight-recorder post-mortem frozen
        and attached — the host-side dump-on-error trigger), else
        None."""
        pred, dl = self._predict_deadline(self._op_enum(op), count,
                                          elem_bytes)
        if elapsed_s <= dl and not retcode:
            return None
        return self.build_miss(op, count, pred, dl, elapsed_s, rank=rank,
                               retcode=retcode, suspect_rank=suspect_rank,
                               attribution=attribution)

    def build_miss(self, op: str | Operation, count: int,
                   predicted_s: float, deadline_s: float,
                   elapsed_s: float, *, rank: int | None = None,
                   retcode: int = 0, suspect_rank: int | None = None,
                   attribution: str = "") -> DeadlineMissed:
        """Assemble the verdict + fire the flight recorder's host-side
        dump (a silent hang leaves an artifact even with no sticky
        native retcode)."""
        from ..telemetry import recorder

        name = self._op_name(op)
        post = recorder.on_deadline_miss(
            name, rank=rank, count=count, predicted_s=predicted_s,
            deadline_s=deadline_s, elapsed_s=elapsed_s,
            suspect_rank=suspect_rank, retcode=retcode)
        return DeadlineMissed(
            op=name, count=int(count), predicted_s=predicted_s,
            deadline_s=deadline_s, elapsed_s=elapsed_s, rank=rank,
            retcode=int(retcode), suspect_rank=suspect_rank,
            attribution=attribution, post_mortem=post)


class NativeDeadlineGuard:
    """Model-derived deadlines applied to native EmuRank calls.

    ``arm(rank, op, count)`` points the rank's in-call recv deadline
    (the ``set_timeout`` config word — the fixed RECEIVE_TIMEOUT
    register of the reference) at the policy's derived value, so the
    sequencer itself times a stalled op out at the band-widened
    prediction.  ``wait(rank, handle, ...)`` bounds the host-side wait
    the same way (with a slack multiple for completion delivery) and
    converts EITHER failure shape — the native sticky RECEIVE_TIMEOUT
    or a host-side wall overrun — into a typed
    :class:`DeadlineMissedError` carrying the structured verdict (with
    the flight-recorder post-mortem attached).  A completing call past
    its deadline also produces a verdict (reported to the manager)
    without raising: the data arrived, the model was wrong — that is
    the drift sentinel's department, not the recovery loop's.
    """

    # host wait bound = slack * deadline: the native in-call deadline
    # fires first (it IS the deadline); the host bound is the backstop
    # for a sequencer that cannot even reach its own timeout check
    HOST_WAIT_SLACK = 3.0

    def __init__(self, policy: DeadlinePolicy, manager: Any = None):
        self.policy = policy
        self.manager = manager

    def arm(self, emu_rank: Any, op: str | Operation, count: int,
            elem_bytes: int = 4) -> int:
        """Configure the rank's native recv deadline from the model;
        returns the applied milliseconds."""
        ms = self.policy.deadline_ms(op, count, elem_bytes)
        emu_rank.call(CallOptions(scenario=Operation.config,
                                  function=int(CfgFunc.set_timeout),
                                  count=ms))
        return ms

    def _notify(self, miss: DeadlineMissed) -> DeadlineMissed:
        if self.manager is not None:
            self.manager.record_miss(miss)
        return miss

    def wait(self, emu_rank: Any, handle: int, op: str | Operation,
             count: int, elem_bytes: int = 4) -> DeadlineMissed | None:
        """Deadline-bounded completion of one started native call.
        Returns None on an in-deadline success, the verdict (without
        raising) on a LATE success, and raises
        :class:`DeadlineMissedError` on a wedged/failed call."""
        from ..constants import ACCLError, ErrorCode

        pol = self.policy
        # ONE cached lookup per wait: this is the armed hot path the
        # fault gate's <3% control budget measures per dispatch
        pred, dl = pol.predict_and_deadline(op, count, elem_bytes)
        t0 = time.perf_counter()
        try:
            emu_rank.wait(handle,
                          timeout_ms=max(int(dl * 1e3 * self.HOST_WAIT_SLACK),
                                         1))
        except TimeoutError:
            elapsed = time.perf_counter() - t0
            miss = pol.build_miss(op, count, pred, dl, elapsed,
                                  rank=emu_rank.rank)
            raise DeadlineMissedError(self._notify(miss)) from None
        except ACCLError as e:
            elapsed = time.perf_counter() - t0
            if e.retcode & int(ErrorCode.RECEIVE_TIMEOUT_ERROR):
                miss = pol.build_miss(
                    op, count, pred, dl, elapsed, rank=emu_rank.rank,
                    retcode=e.retcode)
                raise DeadlineMissedError(self._notify(miss)) from None
            raise  # a non-timeout sticky error is not a deadline event
        elapsed = time.perf_counter() - t0
        if elapsed <= dl:
            return None
        miss = pol.build_miss(op, count, pred, dl, elapsed,
                              rank=emu_rank.rank)
        self._notify(miss)
        return miss

    def run(self, emu_rank: Any, opts: CallOptions, *, op0=None, op1=None,
            res=None, elem_bytes: int = 4) -> DeadlineMissed | None:
        """start + deadline-bounded wait of one descriptor."""
        h = emu_rank.start(opts, op0=op0, op1=op1, res=res)
        return self.wait(emu_rank, h, opts.scenario, opts.count,
                         elem_bytes)
