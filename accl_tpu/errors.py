"""Typed host-side errors: the driver's validation contract.

The reference driver fails a bad call with a retcode AFTER dispatch
(check_return_value, accl.cpp:1210-1234); a mis-parameterized call on the
device-resident sequence path would instead hang or corrupt a buffer with
no host-side symptom at all (the ACCL+ debugging pain, arxiv 2312.11742).
So every host-side precondition failure raises a TYPED error from this
module — callers can catch the precise failure class, tests can pin it,
and each class maps onto a static-analysis diagnostic code (the
`lint_code` attribute) so the same defect is reported identically whether
it is caught at call time or by the sequence linter
(accl_tpu/analysis/, docs/lint.md).

Subclassing keeps backward compatibility: code that caught the untyped
ValueError / RuntimeError / NotImplementedError these paths used to raise
still works.
"""

from __future__ import annotations


class ACCLValidationError(ValueError):
    """Base class for host-side call/descriptor validation failures.

    `lint_code` is the diagnostic code (docs/lint.md) the sequence
    linter emits for the same defect found statically.
    """

    lint_code: str | None = None


class InvalidRootError(ACCLValidationError):
    """Root / src / dst rank outside the addressed communicator
    (lint: ACCL402 root-out-of-range)."""

    lint_code = "ACCL402"


class ZeroLengthBufferError(ACCLValidationError):
    """A data-plane call with a non-positive element count — the compiled
    schedule would be shape-degenerate (lint: ACCL401)."""

    lint_code = "ACCL401"


class DtypeMismatchError(ACCLValidationError, NotImplementedError):
    """Operand/result dtypes disagree within one call (use compress_dtype
    for wire compression instead). Also NotImplementedError for backward
    compatibility with the facade's historical raise
    (lint: ACCL401 dtype/shape-mismatch)."""

    lint_code = "ACCL401"


def notify_sticky_retcode(function_name: str, retcode: int, *,
                          detail: int = 0, rank: int | None = None,
                          count: int | None = None):
    """The dump-on-error seam of the sticky-retcode contract: every
    path that materializes a nonzero sticky error word (request
    completion in request.py, the native EmuRank.wait) reports it here
    BEFORE raising. The telemetry flight recorder — when armed — emits
    an error marker span (the failing call's op name, count, rank, and
    sticky retcode) through the span stream and freezes its
    last-N-spans-per-track ring into a self-contained post-mortem
    trace (telemetry.recorder.on_sticky_retcode,
    docs/observability.md).

    Never raises and costs one armed() predicate when observability is
    off: error reporting must not mask or slow the error."""
    try:
        from .telemetry import recorder

        return recorder.on_sticky_retcode(function_name, int(retcode),
                                          detail=detail, rank=rank,
                                          count=count)
    except Exception:
        return None


class SequenceReuseError(RuntimeError):
    """A completed SequenceRecorder handle was reused — recording into or
    re-running an executed batch. RuntimeError subclass for backward
    compatibility with the recorder's historical raise."""


class LintError(ACCLValidationError):
    """A recorded descriptor batch failed static analysis with
    `lint="error"` (accl_tpu/analysis/). Carries the structured
    diagnostics so callers and tests can inspect codes individually."""

    def __init__(self, diagnostics):
        self.diagnostics = tuple(diagnostics)
        lines = [f"sequence rejected by lint ({len(self.diagnostics)} "
                 "diagnostic(s)):"]
        lines += [f"  {d}" for d in self.diagnostics]
        lines.append("  (suppress with lint='warn' or lint='off'; see "
                     "docs/lint.md)")
        super().__init__("\n".join(lines))

    @property
    def codes(self) -> tuple[str, ...]:
        return tuple(d.code for d in self.diagnostics)
