"""Static analysis for descriptor batches: the pre-dispatch lint gate.

ACCL's core inversion — the host records descriptors, the device runs
the whole batch — means a mis-recorded batch fails AFTER dispatch: a
hang, or a silently wrong buffer (the debugging pain ACCL+, arxiv
2312.11742, reports for FPGA-resident sequences). This package checks
recorded `SequenceDescriptor` batches and per-rank descriptor chains
BEFORE anything compiles or touches a device, emitting structured
diagnostics with stable codes (docs/lint.md has the full table):

  hazards.py    RAW/WAR/WAW aliasing + dtype flow over the canonical
                address renaming               (ACCL101-103, 401, 405)
  protocol.py   per-rank send/recv matching, deadlock cycles, and
                abstract interpretation of schedule bodies (ACCL201-204)
  modelcheck.py exhaustive-interleaving model checking: wildcard races
                and schedule-dependent deadlocks over ALL legal match
                orders, budgeted               (ACCL205-207)
  slots.py      overlap-slot collective_id liveness (ACCL301-302)
  validate.py   descriptor structure: roots, counts, dtypes,
                communicators                  (ACCL401-404)
  hopdag.py     the hop-DAG IR: schedules as data (send/recv/combine/
                encode/decode nodes with exact region intervals),
                executable and mutable — the shared substrate for the
                semantic certifier, the protocol passes, and future
                schedule synthesis
  semantics.py  contribution-set abstract interpretation proving each
                batch computes its DECLARED collective (ACCL501-504)
  interference.py cross-program non-interference: footprint summaries
                per program, O(N^2) pairwise certification with bounded
                product-modelcheck escalation  (ACCL601-604)
  linter.py     the SequenceLinter orchestrator + lint_sequence()

Wired in three places: the opt-out `lint=` stage in `ACCL.sequence()`
(enforced in TPUDevice.start_sequence, cached by composite signature;
`lint="deep"` opts into the interleaving tier), the corpus CLI
`tools/accl_lint.py` (`--deep`), and the CI lint job.
"""

from ..errors import LintError  # noqa: F401  (canonical home: errors.py)
from .diagnostics import CODES, Diagnostic, enforce, make  # noqa: F401
from .hazards import analyze_dataflow  # noqa: F401
from .linter import SequenceLinter, lint_sequence  # noqa: F401
from .modelcheck import (  # noqa: F401
    Budget,
    CheckResult,
    check_interleavings,
    diagnose_programs,
)
from .hopdag import HopDag  # noqa: F401
from .interference import (  # noqa: F401
    InterferenceCertifier,
    ProgramFootprint,
    TrafficSummary,
    certificate_id,
    certify_concurrent,
    footprint_from_rank_programs,
    footprint_from_steps,
)
from .protocol import (  # noqa: F401
    ANY_SRC,
    Event,
    MatchNote,
    batch_rank_programs,
    interpret_schedule,
    rank_programs_from_options,
    simulate,
    trace_schedule_hops,
    trace_schedule_jaxpr,
)
from .semantics import (  # noqa: F401
    UnsupportedSchedule,
    certify,
    certify_call,
    check_batch_semantics,
    collective_spec,
    lift_call,
)
from .slots import (  # noqa: F401
    SlotInstance,
    SlotTimeline,
    check_slots,
    ring_slot_timeline,
)
from .validate import validate_steps  # noqa: F401
