"""Exhaustive-interleaving model checking for per-rank event programs.

`protocol.simulate` explores exactly ONE interleaving of a batch's
per-rank programs — the canonical schedule (rank-index order, FIFO
buffer drain, first-posted TAG_ANY match). That is the right cheap
gate, but the real executors' match order is timing-dependent: a batch
that completes canonically can still deadlock or deliver different
data under another legal match order (the post-dispatch failure class
ACCL+, arxiv 2312.11742, reports — now reachable BEFORE dispatch, in
the spirit of schedule synthesis that proves schedules rather than
testing one run, arxiv 2008.08708).

This module certifies a batch over ALL match orders:

* `check_interleavings` — a match-set-based stateless explorer. The
  only nondeterminism in the event model is WHICH eligible send a recv
  consumes (buffered semantics) or WHICH sender head an any-source
  recv pairs with (rendezvous semantics); everything else commutes.
  The explorer exploits that with a dynamic partial-order reduction:
  statically pinned matches (a send and recv that can never pair with
  anything else) and barrier releases execute eagerly without
  branching — a singleton persistent set — and contended wildcard
  matches branch exhaustively over their match set. Reached states are
  hashed and memoized ((program counters, unconsumed posted sends)
  fully determine the future), which both collapses commuting
  interleavings like a sleep set and makes the search a DAG walk.
  `reduce=False` disables the reductions for a bounded brute-force
  enumeration of every individual action interleaving — the oracle the
  fuzz suite compares the reduced search against, and the fallback for
  tiny programs.

* `diagnose_programs` — runs the checker under BOTH rendezvous and
  buffered semantics and converts the verdict into stable diagnostics:
  ACCL205 wildcard-race (a recv whose alternative matchings in
  completing executions deliver different data), ACCL206
  schedule-dependent-deadlock (a reachable stuck state although the
  canonical run completes — with the witness interleaving rendered in
  the message), and ACCL207 modelcheck-truncated (the exploration
  budget ran out: the verdict is partial, never a silent pass).

Exploration is budgeted by explored-state count and wall clock
(`Budget`); both caps surface as ACCL207.
"""

from __future__ import annotations

import dataclasses
import sys
import time

from ..constants import TAG_ANY
from .diagnostics import Diagnostic, make
from .protocol import ANY_SRC, Event, _src_matches, _tags_match

__all__ = [
    "Budget",
    "CheckResult",
    "Race",
    "check_interleavings",
    "diagnose_programs",
    "canonical_completes",
    "statically_deterministic",
]


@dataclasses.dataclass(frozen=True)
class Budget:
    """Exploration caps. Exhausting either truncates the search and is
    REPORTED (ACCL207) — a partial exploration never passes silently."""

    max_states: int = 20_000
    max_seconds: float = 10.0


@dataclasses.dataclass(frozen=True)
class Race:
    """A recv that matches observably different sends across completing
    executions. `identities` are the distinct (sender, tag, count)
    classes seen; two sends of the same class are interchangeable at
    the batch level (same source rank, same wire signature), so a
    permutation among them is not reported."""

    rank: int
    pc: int
    identities: tuple[str, ...]


@dataclasses.dataclass
class CheckResult:
    semantics: str  # "buffered" | "rendezvous"
    canonical_complete: bool
    complete_reachable: bool  # some explored interleaving finishes
    stuck_trace: list[str] | None  # match steps reaching a stuck state
    stuck_state: str | None  # rendering of the stuck heads
    races: list[Race]
    truncated: bool
    states: int


class _BudgetExhausted(Exception):
    pass


def _fmt_ev(r: int, pc: int, ev: Event) -> str:
    if ev.kind == "coll":
        return f"r{r}:{ev.op}#{pc}"
    tag = "ANY" if ev.tag == TAG_ANY else str(ev.tag)
    peer = "ANY" if ev.peer == ANY_SRC else str(ev.peer)
    role = "->" if ev.kind == "send" else "<-"
    return f"r{r}:{ev.kind}#{pc}({role}r{peer}, tag {tag})"


def _send_identity(r: int, ev: Event) -> str:
    tag = "ANY" if ev.tag == TAG_ANY else str(ev.tag)
    return f"r{r}:send(tag {tag}, count {ev.count})"


@dataclasses.dataclass(frozen=True)
class _MatchStructure:
    """The static matching relation of one batch: which send occurrence
    can ever pair with which recv occurrence, and the PINNED subset — a
    send whose only compatible recv is R where R's only compatible send
    is that send. Matching a pinned pair is the only thing either side
    can ever do, commutes with every other transition, and can never be
    disabled — a singleton persistent set, executed eagerly without
    branching. Computed once per batch and shared across the checker's
    two semantic regimes."""

    n_sends: int
    n_recvs: int
    pinned_send: frozenset
    pinned_recv: frozenset
    pin_of_recv: dict

    @property
    def all_pinned(self) -> bool:
        return (len(self.pinned_send) == self.n_sends
                and len(self.pinned_recv) == self.n_recvs)


def _match_structure(programs: list[list[Event]]) -> _MatchStructure:
    """Build the static matching relation by bucketed indexing — recvs
    keyed by (rank, comm, source constraint, tag) — so candidate
    pairing is proportional to the number of COMPATIBLE pairs, not to
    sends x recvs (a 64-step ring batch has ~14k endpoint events whose
    all-pairs scan took tens of seconds; its namespaced hop tags make
    the buckets near-singleton)."""
    sends = [(r, i, ev) for r, prog in enumerate(programs)
             for i, ev in enumerate(prog) if ev.kind == "send"]
    recvs = [(r, i, ev) for r, prog in enumerate(programs)
             for i, ev in enumerate(prog) if ev.kind == "recv"]
    # (recv rank, comm, peer key) -> recv ids; peer key is the recv's
    # source constraint (exact rank or ANY_SRC)
    by_peer: dict[tuple, list] = {}
    by_peer_tag: dict[tuple, list] = {}
    for d, di, rev in recvs:
        by_peer.setdefault((d, rev.comm, rev.peer), []).append((d, di))
        by_peer_tag.setdefault((d, rev.comm, rev.peer, rev.tag),
                               []).append((d, di))
    cand_r: dict[tuple[int, int], list] = {}
    cand_s: dict[tuple[int, int], list] = {}
    for s, si, sev in sends:
        d = sev.peer
        cands: list = []
        for pk in (s, ANY_SRC):
            if sev.tag == TAG_ANY:  # a wildcard send matches every tag
                cands += by_peer.get((d, sev.comm, pk), [])
            else:  # exact or recv-side wildcard (disjoint buckets)
                cands += by_peer_tag.get((d, sev.comm, pk, sev.tag), [])
                cands += by_peer_tag.get((d, sev.comm, pk, TAG_ANY), [])
        for rid in cands:
            cand_s.setdefault((s, si), []).append(rid)
            cand_r.setdefault(rid, []).append((s, si))
    pinned_send = set()
    pinned_recv = set()
    pin_of_recv = {}
    for sid, rlist in cand_s.items():
        if len(rlist) == 1 and len(cand_r.get(rlist[0], ())) == 1:
            pinned_send.add(sid)
            pinned_recv.add(rlist[0])
            pin_of_recv[rlist[0]] = sid
    return _MatchStructure(len(sends), len(recvs),
                           frozenset(pinned_send), frozenset(pinned_recv),
                           pin_of_recv)


class _Checker:
    """One exploration of one (programs, semantics) pair."""

    def __init__(self, programs: list[list[Event]], semantics: str,
                 budget: Budget, reduce: bool,
                 structure: _MatchStructure | None = None):
        self.programs = [list(p) for p in programs]
        self.world = len(programs)
        self.buffered = semantics == "buffered"
        self.budget = budget
        self.reduce = reduce
        self.deadline = time.monotonic() + budget.max_seconds
        self.states = 0
        self.truncated = False
        # memo: state key -> (can_complete, saw_stuck)
        self.memo: dict = {}
        self.stuck_trace: list[str] | None = None
        self.stuck_state: str | None = None
        # (recv rank, recv pc) -> set of send identities on
        # completion-viable edges
        self.matches: dict[tuple[int, int], set[str]] = {}
        st = structure or _match_structure(programs)
        self.pinned_recv = st.pinned_recv
        self.pin_of_recv = st.pin_of_recv

    # -- static match structure -------------------------------------------

    def _compatible(self, s: int, sev: Event, d: int, rev: Event) -> bool:
        return (sev.peer == d and _src_matches(s, rev)
                and sev.comm == rev.comm and _tags_match(sev.tag, rev.tag))

    # -- shared state helpers ---------------------------------------------

    def _head(self, pcs, r: int) -> Event | None:
        return (self.programs[r][pcs[r]]
                if pcs[r] < len(self.programs[r]) else None)

    def _bad_peer(self, r: int, ev: Event) -> bool:
        if ev.kind == "recv" and ev.peer == ANY_SRC:
            return False
        return not 0 <= ev.peer < self.world

    def _barrier_ready(self, pcs) -> bool:
        """All `world` ranks parked on the same collective signature
        (mirrors simulate: a finished rank breaks the barrier)."""
        sigs = set()
        for r in range(self.world):
            ev = self._head(pcs, r)
            if ev is None or ev.kind != "coll":
                return False
            sigs.add((ev.op, ev.count, ev.comm))
        return len(sigs) == 1

    def _tick(self) -> None:
        self.states += 1
        if (self.states > self.budget.max_states
                or time.monotonic() > self.deadline):
            raise _BudgetExhausted

    # -- deterministic closure (the partial-order reduction) ----------------

    def _closure(self, pcs, posted):
        """Deterministic advance under the reduction: post head sends /
        skip bad-peer events (buffered — sends never block, posting is
        unobservable and monotone), fire statically pinned matches and
        barrier releases. Each is a singleton persistent set: always
        enabled once enabled, commutes with every other transition, and
        has no alternative — executing it eagerly cannot hide an
        outcome. With `reduce=False` the closure is the identity and
        every action interleaves individually (the brute-force
        oracle)."""
        if not self.reduce:
            return pcs, posted
        pcs = list(pcs)
        posted = set(posted)
        while True:
            moved = False
            for r in range(self.world):
                while (ev := self._head(pcs, r)) is not None:
                    if ev.kind == "send" and self.buffered:
                        if not self._bad_peer(r, ev):
                            posted.add((r, pcs[r]))
                        pcs[r] += 1
                        moved = True
                    elif ev.kind != "coll" and self._bad_peer(r, ev):
                        pcs[r] += 1
                        moved = True
                    else:
                        break
            if self.buffered:
                for r in range(self.world):
                    ev = self._head(pcs, r)
                    if (ev is None or ev.kind != "recv"
                            or (r, pcs[r]) not in self.pinned_recv):
                        continue
                    sid = self.pin_of_recv[(r, pcs[r])]
                    if sid in posted:
                        posted.discard(sid)
                        pcs[r] += 1
                        moved = True
            else:
                for r in range(self.world):
                    ev = self._head(pcs, r)
                    if ev is None or ev.kind != "send" \
                            or self._bad_peer(r, ev):
                        continue
                    d = ev.peer
                    rev = self._head(pcs, d)
                    if (d != r and rev is not None and rev.kind == "recv"
                            and rev.peer == r  # exact source: pinned pair
                            and rev.comm == ev.comm
                            and _tags_match(ev.tag, rev.tag)):
                        pcs[r] += 1
                        pcs[d] += 1
                        moved = True
            if self._barrier_ready(pcs):
                for r in range(self.world):
                    pcs[r] += 1
                moved = True
            if not moved:
                return tuple(pcs), frozenset(posted)

    # -- branching transitions ---------------------------------------------

    def _transitions(self, pcs, posted):
        """The branch set at a state. Under the reduction only contended
        matches remain (everything deterministic was closed); brute
        force enumerates every individual action: ("post", r),
        ("skip", r), ("barrier",), and ("match", recv rank, recv pc,
        send id)."""
        out = []
        for r in range(self.world):
            ev = self._head(pcs, r)
            if ev is None:
                continue
            if ev.kind == "send":
                if self.buffered:
                    if not self.reduce:
                        out.append(("skip", r) if self._bad_peer(r, ev)
                                   else ("post", r))
                    continue
                # rendezvous: head-to-head pair (keyed at the sender so
                # each pair appears once)
                if self._bad_peer(r, ev):
                    if not self.reduce:
                        out.append(("skip", r))
                    continue
                d = ev.peer
                rev = self._head(pcs, d)
                if (d != r and rev is not None and rev.kind == "recv"
                        and _src_matches(r, rev) and rev.comm == ev.comm
                        and _tags_match(ev.tag, rev.tag)):
                    out.append(("match", d, pcs[d], (r, pcs[r])))
            elif ev.kind == "recv":
                if self._bad_peer(r, ev):
                    if not self.reduce:
                        out.append(("skip", r))
                    continue
                if self.buffered:
                    for (s, si) in sorted(posted):
                        if self._compatible(s, self.programs[s][si], r, ev):
                            out.append(("match", r, pcs[r], (s, si)))
        if not self.reduce and self._barrier_ready(pcs):
            out.append(("barrier",))
        return out

    def _apply(self, pcs, posted, tr):
        pcs = list(pcs)
        if tr[0] == "post":
            posted = frozenset(posted | {(tr[1], pcs[tr[1]])})
            pcs[tr[1]] += 1
        elif tr[0] == "skip":
            pcs[tr[1]] += 1
        elif tr[0] == "barrier":
            for r in range(self.world):
                pcs[r] += 1
        else:  # ("match", recv rank, recv pc, send id)
            _, d, _, (s, _) = tr
            if self.buffered:
                posted = frozenset(posted - {tr[3]})
                pcs[d] += 1
            else:
                pcs[s] += 1
                pcs[d] += 1
        return tuple(pcs), posted

    # -- exploration --------------------------------------------------------

    def run(self) -> tuple[bool, bool]:
        """Explore from the initial state; returns (complete_reachable,
        stuck_reachable)."""

        def dfs(pcs, posted, trace) -> tuple[bool, bool]:
            pcs, posted = self._closure(pcs, posted)
            key = (pcs, posted)
            hit = self.memo.get(key)
            if hit is not None:
                return hit
            self._tick()
            # mark in-progress defensively; pcs are monotone so the
            # graph is a DAG and this is never read back
            self.memo[key] = (False, False)
            if all(pcs[r] >= len(self.programs[r])
                   for r in range(self.world)):
                if not posted:
                    res = (True, False)
                else:
                    # every pc ran out but buffered sends were never
                    # received: terminal, and a defect (simulate's
                    # leftover-posted ACCL201) — NOT a completion
                    if self.stuck_trace is None:
                        self.stuck_trace = list(trace)
                        self.stuck_state = ", ".join(
                            _send_identity(s, self.programs[s][si])
                            + " never received"
                            for s, si in sorted(posted))
                    res = (False, True)
                self.memo[key] = res
                return res
            todo = self._transitions(pcs, posted)
            if not todo:
                if self.stuck_trace is None:
                    self.stuck_trace = list(trace)
                    self.stuck_state = self._fmt_stuck(pcs)
                res = (False, True)
                self.memo[key] = res
                return res
            complete = stuck = False
            for tr in todo:
                is_match = tr[0] == "match"
                if is_match:
                    _, r, rpc, (s, si) = tr
                    trace.append(
                        f"{_fmt_ev(r, rpc, self.programs[r][rpc])} "
                        f"matched {_send_identity(s, self.programs[s][si])}")
                c, k = dfs(*self._apply(pcs, posted, tr), trace)
                if is_match:
                    trace.pop()
                    if c:
                        self.matches.setdefault((r, rpc), set()).add(
                            _send_identity(s, self.programs[s][si]))
                complete |= c
                stuck |= k
            res = (complete, stuck)
            self.memo[key] = res
            return res

        init = (tuple([0] * self.world), frozenset())
        # DFS depth is bounded by the total event count (every recursion
        # level consumes at least one event): raise the interpreter
        # recursion limit to cover it, scoped and restored. A long
        # program can legally exceed the default 1000 well inside the
        # state budget — escaping as a raw RecursionError would bypass
        # the loud-truncation contract.
        depth = sum(len(p) for p in self.programs)
        old_limit = sys.getrecursionlimit()
        need = 4 * depth + 1000
        try:
            if need > old_limit:
                sys.setrecursionlimit(need)
            return dfs(*init, [])
        except (_BudgetExhausted, RecursionError):
            # RecursionError: pathological depth beyond the raised
            # limit — report as truncation, never crash the linter
            self.truncated = True
            return (False, self.stuck_trace is not None)
        finally:
            sys.setrecursionlimit(old_limit)

    def _fmt_stuck(self, pcs) -> str:
        parts = []
        for r in range(self.world):
            ev = self._head(pcs, r)
            parts.append("r%d:done" % r if ev is None
                         else _fmt_ev(r, pcs[r], ev))
        return " | ".join(parts)


def canonical_completes(programs: list[list[Event]],
                        *, blocking_sends: bool) -> bool:
    """Does the canonical `simulate` schedule consume every event? THE
    gate for ACCL206: a schedule-dependent deadlock is only interesting
    when the one schedule the single-run linter tried looks fine
    (test_modelcheck pins checker/simulate agreement by fuzz). Keys on
    simulate's structural `outcome` signal, not its diagnostics —
    count-mismatched pairs still MATCH (and complete), and prose must
    never carry semantics."""
    from .protocol import simulate

    outcome: list[bool] = []
    simulate(programs, blocking_sends=blocking_sends, outcome=outcome)
    return outcome[0]


def statically_deterministic(programs: list[list[Event]]) -> bool:
    """True when every send and recv occurrence is statically pinned to
    a unique partner — the matching relation then admits exactly ONE
    assignment, every interleaving commutes to the same outcome, and
    exhaustive exploration can be skipped soundly. This is the deep
    tier's router (it subsumes `simulate`'s MatchNote signal: a
    multi-eligible recv is never uniquely pinned): a batch with any
    unpinned endpoint goes to the checker; a statically deterministic
    one is already certified by the canonical run. Hop-derived schedule
    programs (exact per-hop tags) land here, which is what keeps the
    deep tier affordable over the full schedule sweep."""
    return _match_structure(programs).all_pinned


def check_interleavings(programs: list[list[Event]], *,
                        semantics: str = "buffered",
                        budget: Budget | None = None,
                        reduce: bool = True,
                        _structure: _MatchStructure | None = None
                        ) -> CheckResult:
    """Model-check one batch of per-rank programs under one matching
    regime. `reduce=False` disables the persistent-set closure for the
    brute-force enumeration (fuzz oracle / tiny-program fallback)."""
    if semantics not in ("buffered", "rendezvous"):
        raise ValueError(f"semantics must be 'buffered'|'rendezvous', "
                         f"got {semantics!r}")
    budget = budget or Budget()
    chk = _Checker(programs, semantics, budget, reduce,
                   structure=_structure)
    complete, stuck = chk.run()
    races = [
        Race(r, pc, tuple(sorted(ids)))
        for (r, pc), ids in sorted(chk.matches.items())
        if len(ids) > 1
    ]
    return CheckResult(
        semantics=semantics,
        canonical_complete=canonical_completes(
            programs, blocking_sends=semantics == "rendezvous"),
        complete_reachable=complete,
        stuck_trace=chk.stuck_trace,
        stuck_state=chk.stuck_state,
        races=races,
        truncated=chk.truncated,
        states=chk.states,
    )


def diagnose_programs(programs: list[list[Event]], *,
                      semantics: tuple[str, ...] = ("rendezvous",
                                                    "buffered"),
                      budget: Budget | None = None,
                      step: int | None = None) -> list[Diagnostic]:
    """The deep-tier verdict for one batch: explore every match order
    under each regime and emit stable diagnostics.

    ACCL206 fires only when the canonical schedule completes under that
    regime — a canonically-stuck batch is already rejected by the
    single-run linter (ACCL201/202/203), and re-reporting it as
    schedule-dependent would be wrong: EVERY schedule loses. ACCL205
    likewise only considers completing executions; the data a doomed
    interleaving would have delivered is not a result."""
    budget = budget or Budget()
    diags: list[Diagnostic] = []
    seen: set[tuple[str, int, int]] = set()
    structure = _match_structure(programs)  # shared across regimes
    for sem in semantics:
        res = check_interleavings(programs, semantics=sem, budget=budget,
                                  _structure=structure)
        if res.truncated:
            diags.append(make(
                "ACCL207",
                f"{sem} exploration truncated after {res.states} states "
                f"(budget: {budget.max_states} states / "
                f"{budget.max_seconds:g}s): interleavings beyond the "
                "explored prefix are UNVERIFIED", step=step))
        if not res.canonical_complete:
            continue
        if res.stuck_trace is not None:
            key = ("ACCL206", -1, -1)
            if key not in seen:
                seen.add(key)
                steps = "\n    ".join(res.stuck_trace) or "(no matches)"
                diags.append(make(
                    "ACCL206",
                    "the canonical schedule completes, but under "
                    f"{sem} semantics the interleaving\n    {steps}\n"
                    f"  reaches the stuck state [{res.stuck_state}] — "
                    "no eligible match can ever fire", step=step))
        for race in res.races:
            key = ("ACCL205", race.rank, race.pc)
            if key in seen:
                continue
            seen.add(key)
            ev = programs[race.rank][race.pc]
            diags.append(make(
                "ACCL205",
                f"{_fmt_ev(race.rank, race.pc, ev)} matches "
                f"{' or '.join(race.identities)} depending on the "
                f"{sem} match order: the delivered data is "
                "schedule-dependent", step=step, rank=race.rank))
    return diags
