"""SequenceLinter: the static gate in front of ScheduleCompiler.

Orchestrates the analysis passes over a recorded descriptor batch —
structural validation (validate.py), dataflow hazards over the
canonical renaming (hazards.py), overlap-slot liveness (slots.py), and
optionally the deep per-rank protocol interpretation (protocol.py) —
and returns the combined diagnostic list, most severe first.

The shallow passes are pure Python over the descriptors (microseconds;
the bench smoke gate pins them under 5% of record+compile time). The
deep pass abstractly evaluates every step's schedule body under jax
tracing, so it costs about as much as a second trace: it is OFF in the
in-band `ACCL.sequence()` stage and ON in the corpus CLI
(tools/accl_lint.py) and the schedule-conformance tests, where its
job — proving the shipping schedules deadlock-free per rank — earns
the trace.
"""

from __future__ import annotations

from ..constants import Operation
from .diagnostics import Diagnostic, enforce
from .hazards import analyze_dataflow
from .slots import check_slots, ring_slot_timeline
from .validate import validate_steps

__all__ = ["SequenceLinter", "lint_sequence"]

_SEV_ORDER = {"error": 0, "warning": 1}


class SequenceLinter:
    """One linter per (world, lowering flags) configuration.

    `use_pallas_ring`/`pallas_ring_overlap` mirror the ScheduleCompiler
    flags of the communicator context the batch will compile under, so
    the slot model matches what the lowering would actually launch.
    """

    def __init__(
        self,
        world: int,
        *,
        use_pallas_ring: bool = False,
        pallas_ring_overlap: bool = True,
        deep: bool = False,
        axis_name: str = "ccl",
        arith_table: dict | None = None,
    ):
        self.world = world
        self.use_pallas_ring = use_pallas_ring
        self.pallas_ring_overlap = pallas_ring_overlap
        self.deep = deep
        self.axis_name = axis_name
        # the ACTIVE arithmetic configuration (compression-lane pairing,
        # ACCL406): None = the shipping default table
        self.arith_table = arith_table

    def ring_steps(self, steps) -> frozenset[int]:
        """Indices that lower to the slot-keyed pallas ring — the same
        predicate sequence.py uses to insert cross-step ordering."""
        if not self.use_pallas_ring:
            return frozenset()
        return frozenset(
            k for k, o in enumerate(steps)
            if o.scenario == Operation.allreduce)

    def lint(
        self,
        steps,
        plans=None,
        *,
        buffer_widths: dict[int, int] | None = None,
    ) -> list[Diagnostic]:
        """Run the configured passes over a batch of CallOptions.
        `plans` (one Plan per step, from plan.select_algorithm) enables
        the deep protocol pass; `buffer_widths` (address -> registered
        element width) enables the static underflow check."""
        steps = list(steps)
        diags = validate_steps(steps, self.world)
        if any(d.code in ("ACCL404", "ACCL403") for d in diags):
            # structurally not a sequence: downstream passes would
            # misread the batch
            return self._sorted(diags)
        diags += analyze_dataflow(
            steps, self.world,
            ring_steps=self.ring_steps(steps),
            buffer_widths=buffer_widths,
            arith_table=self.arith_table,
        )
        if self.use_pallas_ring:
            timeline = ring_slot_timeline(
                steps, self.world, overlap=self.pallas_ring_overlap)
            diags += check_slots(timeline)
        if self.deep and plans is not None and not diags:
            from .protocol import interpret_schedule

            for k, (opts, plan) in enumerate(zip(steps, plans)):
                for d in interpret_schedule(opts, plan, self.world,
                                            self.axis_name):
                    diags.append(Diagnostic(d.code, d.message, step=k,
                                            rank=d.rank))
        return self._sorted(diags)

    @staticmethod
    def _sorted(diags: list[Diagnostic]) -> list[Diagnostic]:
        return sorted(diags,
                      key=lambda d: (_SEV_ORDER[d.severity], d.code,
                                     d.step if d.step is not None else -1))


def lint_sequence(steps, world: int, *, mode: str = "error",
                  plans=None, buffer_widths=None, **kw) -> list[Diagnostic]:
    """One-shot convenience: lint a batch and apply `mode`
    (`"error"` raises LintError on error-severity findings, `"warn"`
    logs, `"off"` skips). Returns the diagnostics either way."""
    if mode == "off":
        return []
    diags = SequenceLinter(world, **kw).lint(
        steps, plans, buffer_widths=buffer_widths)
    enforce(diags, mode)
    return diags
