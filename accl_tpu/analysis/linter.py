"""SequenceLinter: the static gate in front of ScheduleCompiler.

Orchestrates the analysis passes over a recorded descriptor batch —
structural validation (validate.py), dataflow hazards over the
canonical renaming (hazards.py), overlap-slot liveness (slots.py), the
semantic certifier (semantics.py, when per-step Plans are available),
and optionally the deep per-rank protocol interpretation (protocol.py)
— and returns the combined diagnostic list, most severe first.

The shallow passes are pure Python over the descriptors (microseconds;
the bench smoke gate pins the whole default tier under 5% of
record+compile time). The semantic pass (ACCL501-504) is per-batch
LINEAR — one contribution-set abstract evaluation per step, verdicts
cached by static signature — so it rides the DEFAULT tier; only
pathologically segmented shapes defer to the CLI/CI sweep (see
semantics._within_inband_budget). The deep tier abstractly evaluates
every step's schedule body under jax tracing (about the cost of a
second trace) and then model-checks the batch's per-rank hop programs
over EVERY legal match order (modelcheck.py — ACCL205/206/207,
budgeted): it is OFF in the in-band default (`lint="error"`), opted
into per batch with `lint="deep"`, and ON in the corpus CLI
(tools/accl_lint.py) and the schedule-conformance tests, where its job
— proving the shipping schedules deadlock-free under all interleavings
— earns the cost.
"""

from __future__ import annotations

from ..constants import Operation
from .diagnostics import Diagnostic, enforce
from .hazards import analyze_dataflow
from .slots import check_slots, ring_slot_timeline
from .validate import validate_steps

__all__ = ["SequenceLinter", "lint_sequence"]

_SEV_ORDER = {"error": 0, "warning": 1}


class SequenceLinter:
    """One linter per (world, lowering flags) configuration.

    `use_pallas_ring`/`pallas_ring_overlap` mirror the ScheduleCompiler
    flags of the communicator context the batch will compile under, so
    the slot model matches what the lowering would actually launch.
    """

    def __init__(
        self,
        world: int,
        *,
        use_pallas_ring: bool = False,
        pallas_ring_overlap: bool = True,
        deep: bool = False,
        axis_name: str = "ccl",
        arith_table: dict | None = None,
        budget=None,
    ):
        self.world = world
        self.use_pallas_ring = use_pallas_ring
        self.pallas_ring_overlap = pallas_ring_overlap
        self.deep = deep
        self.axis_name = axis_name
        # the ACTIVE arithmetic configuration (compression-lane pairing,
        # ACCL406): None = the shipping default table
        self.arith_table = arith_table
        # exploration caps for the deep tier's interleaving checker
        # (modelcheck.Budget); None = the shipping default
        self.budget = budget

    def ring_steps(self, steps) -> frozenset[int]:
        """Indices that lower to the slot-keyed pallas ring — the same
        predicate sequence.py uses to insert cross-step ordering."""
        if not self.use_pallas_ring:
            return frozenset()
        return frozenset(
            k for k, o in enumerate(steps)
            if o.scenario == Operation.allreduce)

    def lint(
        self,
        steps,
        plans=None,
        *,
        buffer_widths: dict[int, int] | None = None,
        persistent_addrs: frozenset[int] | set[int] = frozenset(),
    ) -> list[Diagnostic]:
        """Run the configured passes over a batch of CallOptions.
        `plans` (one Plan per step, from plan.select_algorithm) enables
        the deep protocol pass; `buffer_widths` (address -> registered
        element width) enables the static underflow check;
        `persistent_addrs` declares device-resident state buffers whose
        partial-width refresh pattern waives ACCL101 (see
        hazards.analyze_dataflow)."""
        steps = list(steps)
        diags = validate_steps(steps, self.world)
        if any(d.code in ("ACCL404", "ACCL403") for d in diags):
            # structurally not a sequence: downstream passes would
            # misread the batch
            return self._sorted(diags)
        diags += analyze_dataflow(
            steps, self.world,
            ring_steps=self.ring_steps(steps),
            buffer_widths=buffer_widths,
            arith_table=self.arith_table,
            persistent_addrs=persistent_addrs,
        )
        if self.use_pallas_ring:
            timeline = ring_slot_timeline(
                steps, self.world, overlap=self.pallas_ring_overlap)
            diags += check_slots(timeline)
        if plans is not None and not any(
                d.severity == "error" for d in diags):
            # semantic certification (ACCL501-504): per-batch LINEAR —
            # one contribution-set abstract evaluation per step, cached
            # by static signature — so it rides the DEFAULT tier, not
            # just the deep one. Pathologically segmented shapes defer
            # to the CLI/CI conformance sweep (semantics budget).
            # Warning-severity findings (WAR/WAW advisories) do NOT
            # skip it: under lint="error" those batches still dispatch,
            # so they still need their answer certified.
            from .semantics import check_batch_semantics

            diags += check_batch_semantics(
                steps, plans, self.world, self.axis_name,
                arith_table=self.arith_table)
        if self.deep and plans is not None and not diags:
            from .protocol import (
                batch_programs_from_hops,
                check_hops,
                rank_programs_from_hops,
                simulate,
                trace_schedule_hops,
            )

            # per-step interpretation (interpret_schedule's passes,
            # inlined so each schedule body is abstractly traced ONCE —
            # the trace is the deep tier's dominant cost, and the batch
            # checker below reuses the same hops)
            hops_per_step = []
            for k, (opts, plan) in enumerate(zip(steps, plans)):
                hops = trace_schedule_hops(opts, plan, self.world,
                                           self.axis_name)
                hops_per_step.append(hops)
                step_diags = check_hops(hops, self.world)
                if not step_diags:  # malformed perms confuse the matcher
                    step_diags = simulate(
                        rank_programs_from_hops(hops, self.world),
                        blocking_sends=False)
                for d in step_diags:
                    diags.append(Diagnostic(d.code, d.message, step=k,
                                            rank=d.rank))
            if not diags:
                # exhaustive-interleaving tier: certify the BATCH's
                # per-rank hop programs over every legal match order
                # (per-step interpretation above saw one step and one
                # schedule at a time). The checker's static router skips
                # exploration when the matching is provably unique.
                programs = batch_programs_from_hops(hops_per_step,
                                                    self.world)
                diags += self.check_interleavings(programs)
        return self._sorted(diags)

    def check_interleavings(self, programs) -> list[Diagnostic]:
        """Model-check per-rank event programs over every legal match
        order (the deep tier's last pass; also the entry point
        tools/accl_lint.py uses for `rank_programs` fixtures). The
        static pin analysis routes: a batch where every endpoint has a
        provably unique partner (which subsumes the no-MatchNote case —
        a multi-eligible recv is never uniquely pinned) admits exactly
        one matching and skips exploration outright."""
        from .modelcheck import (
            Budget,
            diagnose_programs,
            statically_deterministic,
        )

        if statically_deterministic(programs):
            return []
        return diagnose_programs(programs,
                                 budget=self.budget or Budget())

    @staticmethod
    def _sorted(diags: list[Diagnostic]) -> list[Diagnostic]:
        return sorted(diags,
                      key=lambda d: (_SEV_ORDER[d.severity], d.code,
                                     d.step if d.step is not None else -1))


def lint_sequence(steps, world: int, *, mode: str = "error",
                  plans=None, buffer_widths=None, **kw) -> list[Diagnostic]:
    """One-shot convenience: lint a batch and apply `mode`
    (`"error"` raises LintError on error-severity findings, `"warn"`
    logs, `"off"` skips, `"deep"` adds the exhaustive-interleaving
    tier and enforces like `"error"`). Returns the diagnostics either
    way."""
    if mode == "off":
        return []
    if mode == "deep":
        kw.setdefault("deep", True)
    diags = SequenceLinter(world, **kw).lint(
        steps, plans, buffer_widths=buffer_widths)
    enforce(diags, mode)
    return diags
