"""Diagnostic codes and records for the sequence linter.

Every defect class the static analyzer can find has a STABLE code, so
corpus fixtures, CI gates, suppression lists, and docs all key on the
same identifiers (docs/lint.md holds the user-facing table):

  ACCL1xx  dataflow hazards over the canonical buffer renaming
  ACCL2xx  protocol defects (send/recv matching, deadlock)
  ACCL3xx  overlap-slot / collective_id resource defects
  ACCL4xx  descriptor validation (shape, dtype, root, communicator)
  ACCL5xx  semantic defects: the batch's final contribution sets differ
           from the declared collective (semantics.py)
  ACCL6xx  cross-program interference: two INDIVIDUALLY certified
           programs that are not safe to dispatch concurrently
           (interference.py)

Severity semantics: an `error` is a batch the analyzer can prove wrong
on SOME shipping executor (stale reads, deadlock, slot cross-talk,
malformed descriptors); a `warning` is a batch whose fused-program
semantics are well-defined but that races on an executor free to
overlap unordered steps (the device-resident FIFO posture) — almost
always a mis-recorded batch, never silently wrong under the current
fused lowering. `lint="error"` raises on errors and logs warnings;
`lint="warn"` logs both.
"""

from __future__ import annotations

import dataclasses

from ..errors import LintError

__all__ = ["CODES", "Diagnostic", "LintError", "make", "enforce"]

# code -> (kebab-case name, default severity, one-line description)
CODES: dict[str, tuple[str, str, str]] = {
    "ACCL101": ("raw-hazard", "error",
                "read extends past the region the producing step wrote "
                "(fresh prefix + stale tail)"),
    "ACCL102": ("war-hazard", "warning",
                "write to a buffer an earlier unordered step still reads"),
    "ACCL103": ("waw-hazard", "warning",
                "two unordered steps write the same buffer"),
    "ACCL201": ("unmatched-sendrecv", "error",
                "send or recv with no matching partner (or mismatched "
                "payload counts)"),
    "ACCL202": ("deadlock-cycle", "error",
                "circular wait among blocking sends/recvs/collectives"),
    "ACCL203": ("tag-mismatch", "error",
                "send/recv pair on one edge whose tags can never match"),
    "ACCL204": ("perm-conflict", "error",
                "malformed permute hop: duplicate or out-of-range "
                "source/destination"),
    "ACCL205": ("wildcard-race", "error",
                "a wildcard recv (TAG_ANY / any-source) matches different "
                "sends across legal match orders: the delivered data is "
                "schedule-dependent"),
    "ACCL206": ("schedule-dependent-deadlock", "error",
                "some legal match order reaches a stuck state although "
                "the canonical schedule completes"),
    "ACCL207": ("modelcheck-truncated", "warning",
                "exhaustive interleaving exploration hit its state or "
                "wall-clock budget: the deep verdict covers only the "
                "explored prefix"),
    "ACCL301": ("slot-collision", "error",
                "two live schedule instances share a collective_id slot "
                "with no ordering between them"),
    "ACCL302": ("slot-overcommit", "error",
                "overlap window larger than the kernel's independent "
                "slot resources"),
    "ACCL401": ("dtype-shape-mismatch", "error",
                "dtype or element-count inconsistency across the batch"),
    "ACCL402": ("root-out-of-range", "error",
                "root/src/dst rank outside the addressed communicator"),
    "ACCL403": ("comm-mismatch", "error",
                "steps address different communicators"),
    "ACCL404": ("not-sequenceable", "error",
                "descriptor kind cannot ride a fused call sequence"),
    "ACCL405": ("buffer-underflow", "error",
                "registered buffer narrower than the widths the batch "
                "needs"),
    "ACCL406": ("quantized-lane-mismatch", "error",
                "blockwise-quantized wire requested for a payload dtype "
                "with no quantized lane (or a wire dtype with no "
                "arithmetic-configuration row)"),
    "ACCL501": ("wrong-result", "error",
                "a rank's final contribution set differs from the "
                "declared collective (misrouted regions, foreign atoms, "
                "or the wrong reduction)"),
    "ACCL502": ("partial-contribution", "error",
                "some rank's input never reaches an output region the "
                "collective says must include it"),
    "ACCL503": ("double-count", "error",
                "a contribution folded into the same non-idempotent "
                "reduction twice"),
    "ACCL504": ("stale-read", "error",
                "a hop forwards a region before its producer wrote it "
                "(program-order violation in the hop DAG)"),
    "ACCL601": ("cross-program-overlap", "error",
                "two concurrent programs touch the same buffer region "
                "or stream endpoint with at least one writer: their "
                "interleaving is not equivalent to serial composition"),
    "ACCL602": ("cross-program-tag-collision", "error",
                "traffic of one program is matchable by another on a "
                "shared communicator (e.g. a wildcard recv in program A "
                "can steal a send posted by program B)"),
    "ACCL603": ("cross-program-slot-collision", "error",
                "two concurrent programs claim the same collective_id "
                "ring slot with no cross-program ordering"),
    "ACCL604": ("summary-unliftable", "error",
                "a program's interference footprint could not be "
                "extracted or composed: the pair is UNVERIFIED, which "
                "must never read as certified"),
}


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One linter finding, formatted `CODE name [step k] [rank r]: msg`."""

    code: str
    message: str
    step: int | None = None  # descriptor index within the batch
    rank: int | None = None  # communicator-relative rank, protocol passes

    @property
    def name(self) -> str:
        return CODES[self.code][0]

    @property
    def severity(self) -> str:
        return CODES[self.code][1]

    def __str__(self) -> str:
        where = ""
        if self.step is not None:
            where += f" [step {self.step}]"
        if self.rank is not None:
            where += f" [rank {self.rank}]"
        return f"{self.code} {self.name}{where}: {self.message}"


def make(code: str, message: str, step: int | None = None,
         rank: int | None = None) -> Diagnostic:
    if code not in CODES:
        raise KeyError(f"unknown diagnostic code {code!r}")
    return Diagnostic(code, message, step, rank)


def enforce(diagnostics, mode: str) -> None:
    """Apply a lint mode to a diagnostic list: `"error"` raises LintError
    on error-severity findings (warnings are logged), `"warn"` logs
    everything, `"off"` is a no-op. `"deep"` enforces like `"error"` —
    the mode names select which passes RUN (the deep tier adds the
    interleaving model checker); enforcement semantics differ only in
    error vs warn vs off. The full diagnostic list — warnings included —
    rides any raised LintError."""
    if mode not in ("error", "warn", "off", "deep"):
        raise ValueError(f"lint mode must be 'error'|'warn'|'off'|'deep', "
                         f"got {mode!r}")
    if mode == "off" or not diagnostics:
        return
    from ..utils.logging import Log

    errors = [d for d in diagnostics if d.severity == "error"]
    if mode in ("error", "deep") and errors:
        raise LintError(diagnostics)
    for d in diagnostics:
        Log.warning("lint: %s", d)
