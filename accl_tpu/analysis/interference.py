"""Cross-program interference certifier: compositional non-interference
proofs for CONCURRENT SequencePrograms.

Every other certifier in this package reasons about ONE descriptor
batch at a time; the multi-tenant sequencer (ROADMAP item 1) needs to
admit N tenants' pre-certified programs for concurrent dispatch, and a
whole-product model check over N programs is exponentially infeasible.
This module extends the prove-don't-test posture (SCCL, arxiv
2008.08708) across program boundaries the way ACCL+'s multi-process
collective engine demands (arxiv 2312.11742): prove statically that
ANY interleaving of a set of certified programs is equivalent to their
serial composition, so the scheduler admits tenants by checking
certificates — O(N^2) over small summaries — not by re-model-checking
the product.

Two tiers:

* Summary tier. At `SequenceRecorder.compile()` time each program gets
  a `ProgramFootprint`: exact read/write address prefixes through the
  canonical access model (`sequencer.sequence.step_accesses`), the
  persistent-buffer set, communicator ids, coarse per-communicator tag
  ranges (incl. wildcard flags), collective-id ring slots from the
  slot-liveness pass, and stream endpoints. Pairwise checks over
  footprints are EXACT for the resource classes:

    ACCL601  write/write or read/write region overlap (arena addresses
             are unique, every access is a prefix at offset 0, so a
             shared address with a writer IS an overlap) — shared
             stream endpoints report here too (a stream is a stateful
             FIFO with no cross-program ordering)
    ACCL603  collective-id ring-slot intersection (the slots are a
             global kernel resource; nothing orders two programs'
             launches)
    ACCL604  a footprint that could not be lifted or composed — loud,
             never a silent pass

* Escalation tier. Tag summaries are deliberately COARSE (ranges +
  wildcard flags), so a tag-range overlap on a shared communicator is
  only a MAY-interfere verdict: exactly those pairs escalate to a
  bounded cross-program product model check that reuses the
  ACCL205-207 explorer (modelcheck.py) over the per-rank concatenation
  of both programs, in BOTH orders. The exact cross-matching relation
  (a send of one program `_compatible` with a recv of the other,
  wildcards included) either refutes the summary overlap — the pair
  certifies clean — or confirms it as ACCL602 with the offending match
  pair rendered. Budget truncation surfaces as ACCL207, loud.

Tag namespaces: hop-derived programs (the fused jit(shard_map) path)
carry SYNTHETIC tags — ppermute matching is internal to one compiled
XLA program and no wire-level matching engine is shared between two
separately compiled programs, so synthetic traffic is program-private
(`synthetic_tags=True`; the multi-tenant scheduler's per-tenant tag
namespaces make the same promise operationally). Real descriptor-chain
tags (the native executor's shared matching engine) DO share the wire;
only pairs where both sides carry real tags can cross-match, and in a
composed product any synthetic tags are namespaced per program
(`_PROGRAM_TAG_STRIDE`) while TAG_ANY keeps piercing every namespace.

Verdicts are cached per pair, keyed by the two composite signatures
(order-normalized), so an admission-control loop re-checking a stable
tenant set pays dict lookups. The cache is LRU-BOUNDED
(``ACCL_INTERFERENCE_CACHE_CAP``, default 4096 pairs): under tenant
churn the signature universe is open-ended, and an admission-control
certifier lives as long as the scheduler does — an unbounded verdict
dict would be a slow leak. Evicting a verdict is always safe (the
next check_pair on that pair recomputes it identically; verdicts are
pure functions of the two footprints), it just costs the recheck.
`InterferenceCertifier.escalations` counts pairs that needed the
product model check — the summary-only fast path is provable by
asserting it stayed at zero.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from collections import OrderedDict
from typing import Callable, Iterable, Sequence

from ..constants import TAG_ANY
from .diagnostics import Diagnostic, make
from .modelcheck import Budget, check_interleavings
from .protocol import ANY_SRC, Event, _src_matches, _tags_match

__all__ = [
    "TrafficSummary",
    "ProgramFootprint",
    "InterferenceCertifier",
    "footprint_from_steps",
    "footprint_from_rank_programs",
    "product_programs",
    "certify_concurrent",
    "certificate_id",
]

# Tag offset separating one program's SYNTHETIC hop tags from another's
# in a composed product: hop tags are step * _STEP_TAG_STRIDE + hop
# (protocol.py), far below this, and real tags never get offset.
_PROGRAM_TAG_STRIDE = 1 << 24

# Default bound on the per-pair verdict cache: 4096 pairs covers a
# ~90-program stable working set (N*(N-1)/2) while keeping a churning
# multi-tenant admission loop O(1) in memory.
DEFAULT_VERDICT_CACHE_CAP = 4096


def _verdict_cache_cap() -> int:
    """The env-tunable cache bound (ACCL_INTERFERENCE_CACHE_CAP);
    clamped to >= 1 so the live pair can always be cached."""
    raw = os.environ.get("ACCL_INTERFERENCE_CACHE_CAP", "")
    try:
        cap = int(raw) if raw else DEFAULT_VERDICT_CACHE_CAP
    except ValueError:
        cap = DEFAULT_VERDICT_CACHE_CAP
    return max(cap, 1)


@dataclasses.dataclass(frozen=True)
class TrafficSummary:
    """Coarse per-communicator endpoint-traffic summary of one program:
    inclusive tag ranges over the exact-tag sends/recvs plus wildcard
    flags. Deliberately lossy — refining a range overlap into an exact
    cross-match verdict is the escalation tier's job."""

    comm: int
    send_tags: tuple[int, int] | None  # (lo, hi) over exact-tag sends
    recv_tags: tuple[int, int] | None
    send_any: bool  # a TAG_ANY send exists
    recv_any: bool  # a TAG_ANY recv exists
    any_src: bool  # an any-source recv exists
    n_sends: int
    n_recvs: int

    def sends_match_recvs(self, other: "TrafficSummary") -> bool:
        """Can SOME send of self match SOME recv of `other`? Coarse:
        range intersection or either-side wildcard."""
        if self.n_sends == 0 or other.n_recvs == 0:
            return False
        if self.send_any or other.recv_any:
            return True
        if self.send_tags is None or other.recv_tags is None:
            return False
        return (self.send_tags[0] <= other.recv_tags[1]
                and other.recv_tags[0] <= self.send_tags[1])


@dataclasses.dataclass(frozen=True)
class ProgramFootprint:
    """One program's interference summary (see module docstring).
    `reads`/`writes` are (arena address, prefix element count) pairs;
    `rank_events` is a lazy thunk producing the program's exact
    per-rank event programs — only the escalation tier forces it, so
    footprint extraction never pays for jax tracing."""

    label: str
    world: int
    signature: str  # composite-signature digest: the cache key half
    comms: frozenset[int]
    reads: tuple[tuple[int, int], ...]
    writes: tuple[tuple[int, int], ...]
    persistent: frozenset[int]
    ring_slots: frozenset[int]
    streams: frozenset[int]
    traffic: tuple[TrafficSummary, ...]
    colls: frozenset[tuple[str, int, int]]  # (op, count, comm)
    synthetic_tags: bool
    unliftable: str | None = None
    rank_events: Callable[[], list[list[Event]]] | None = \
        dataclasses.field(default=None, compare=False, repr=False)

    def traffic_on(self, comm: int) -> TrafficSummary | None:
        for t in self.traffic:
            if t.comm == comm:
                return t
        return None

    def events(self) -> list[list[Event]]:
        """Force the exact per-rank event programs (escalation only)."""
        if self.rank_events is None:
            raise RuntimeError(
                f"footprint {self.label!r} carries no per-rank event "
                "programs (extracted without plans)")
        return self.rank_events()


def _digest(payload: object) -> str:
    return hashlib.sha256(repr(payload).encode()).hexdigest()[:16]


def _merge_prefixes(
        acc: dict[int, int], pairs: Iterable[tuple[int, int]]) -> None:
    for addr, elems in pairs:
        acc[addr] = max(acc.get(addr, 0), elems)


def footprint_from_steps(
    steps: Sequence[object],
    world: int,
    *,
    persistent: frozenset[int] = frozenset(),
    use_pallas_ring: bool = False,
    pallas_ring_overlap: bool = True,
    plans: tuple[object, ...] | None = None,
    axis_name: str = "ccl",
    label: str = "",
    signature: str | None = None,
) -> ProgramFootprint:
    """Lift a recorded descriptor batch into its footprint — pure
    Python over the descriptors plus (under the pallas ring) the slot
    timeline mirror; never traces jax. Any extraction failure returns
    an `unliftable` footprint that rejects loudly (ACCL604) instead of
    raising — inability must never read as certified. `plans` (one per
    step) arms the lazy exact-event thunk the escalation tier uses.

    `signature` is the program's COMPOSITE signature (the canonically
    renamed batch digest, the compile-cache key). It cannot serve as
    the interference-cache key alone: the canonical renaming erases
    WHICH buffers the program binds, and two same-shape programs over
    different buffers must never alias an interference verdict — so the
    footprint's own `signature` extends it with a digest over the
    concrete resources (addresses, streams, slots, communicators)."""
    if signature is not None:
        base = signature
    else:
        try:
            base = _digest(
                (world, tuple(getattr(o, "signature")() for o in steps)))
        except Exception:
            # even the identity digest can fail on alien step objects;
            # such a footprint is unliftable below, and all unliftable
            # pairs reject identically (ACCL604), so a label-keyed
            # fallback cannot alias a VERDICT, only a rejection
            base = _digest((world, label, "unsigned"))
    try:
        reads: dict[int, int] = {}
        writes: dict[int, int] = {}
        comms: set[int] = set()
        streams: set[int] = set()
        from ..sequencer.sequence import step_accesses

        for opts in steps:
            r, w = step_accesses(opts, world)
            _merge_prefixes(reads, r)
            _merge_prefixes(writes, w)
            comms.add(int(getattr(opts, "comm_addr")))
            for sid in (getattr(opts, "op0_stream_id", 0),
                        getattr(opts, "res_stream_id", 0)):
                if sid:
                    streams.add(int(sid))
        ring_slots: frozenset[int] = frozenset()
        if use_pallas_ring:
            from .slots import ring_slot_timeline

            timeline = ring_slot_timeline(steps, world,
                                          overlap=pallas_ring_overlap)
            ring_slots = frozenset(i.slot for i in timeline.instances)
        thunk: Callable[[], list[list[Event]]] | None = None
        if plans is not None:
            steps_t = tuple(steps)
            plans_t = tuple(plans)
            cache: list[list[list[Event]]] = []

            def thunk() -> list[list[Event]]:
                if not cache:
                    from .protocol import batch_rank_programs

                    cache.append(batch_rank_programs(
                        list(steps_t), list(plans_t), world, axis_name))
                return cache[0]

        reads_t = tuple(sorted(reads.items()))
        writes_t = tuple(sorted(writes.items()))
        sig = _digest((base, world, reads_t, writes_t,
                       tuple(sorted(ring_slots)),
                       tuple(sorted(streams)), tuple(sorted(comms)),
                       tuple(sorted(persistent))))
        return ProgramFootprint(
            label=label or sig[:8], world=world, signature=sig,
            comms=frozenset(comms),
            reads=reads_t,
            writes=writes_t,
            persistent=frozenset(persistent),
            ring_slots=ring_slots,
            streams=frozenset(streams),
            # the fused path's wire matching is internal to one compiled
            # XLA program: no tags or collectives share a matching
            # engine with another program
            traffic=(), colls=frozenset(), synthetic_tags=True,
            rank_events=thunk,
        )
    except Exception as e:  # loud, never silent (ACCL604)
        return ProgramFootprint(
            label=label or base[:8], world=world,
            signature=_digest((base, "unliftable")),
            comms=frozenset(), reads=(), writes=(),
            persistent=frozenset(), ring_slots=frozenset(),
            streams=frozenset(), traffic=(), colls=frozenset(),
            synthetic_tags=True,
            unliftable=f"{type(e).__name__}: {e}")


def footprint_from_rank_programs(
    programs: Sequence[Sequence[Event]],
    world: int,
    *,
    label: str = "",
    signature: str | None = None,
) -> ProgramFootprint:
    """Lift per-rank event programs (the native executor's descriptor
    chains) into a footprint. These carry REAL tags on the shared
    matching engine — `synthetic_tags=False` — so the traffic checks
    apply; they carry no address information (the native chains bind
    per-rank buffers the event model does not see), so the memory tier
    is vacuous for them by construction."""
    progs = [list(p) for p in programs]
    sig = signature if signature is not None else _digest((world, progs))
    name = label or sig[:8]
    per_comm: dict[int, dict[str, object]] = {}
    colls: set[tuple[str, int, int]] = set()
    for prog in progs:
        for ev in prog:
            if ev.kind == "coll":
                colls.add((ev.op, ev.count, ev.comm))
                continue
            if ev.kind not in ("send", "recv"):
                continue
            t = per_comm.setdefault(ev.comm, {
                "s_lo": None, "s_hi": None, "r_lo": None, "r_hi": None,
                "s_any": False, "r_any": False, "any_src": False,
                "ns": 0, "nr": 0})
            if ev.kind == "send":
                t["ns"] = int(t["ns"]) + 1  # type: ignore[call-overload]
                if ev.tag == TAG_ANY:
                    t["s_any"] = True
                else:
                    lo, hi = t["s_lo"], t["s_hi"]
                    t["s_lo"] = ev.tag if lo is None \
                        else min(int(lo), ev.tag)  # type: ignore[arg-type]
                    t["s_hi"] = ev.tag if hi is None \
                        else max(int(hi), ev.tag)  # type: ignore[arg-type]
            else:
                t["nr"] = int(t["nr"]) + 1  # type: ignore[call-overload]
                if ev.peer == ANY_SRC:
                    t["any_src"] = True
                if ev.tag == TAG_ANY:
                    t["r_any"] = True
                else:
                    lo, hi = t["r_lo"], t["r_hi"]
                    t["r_lo"] = ev.tag if lo is None \
                        else min(int(lo), ev.tag)  # type: ignore[arg-type]
                    t["r_hi"] = ev.tag if hi is None \
                        else max(int(hi), ev.tag)  # type: ignore[arg-type]
    traffic = tuple(
        TrafficSummary(
            comm=comm,
            send_tags=(None if t["s_lo"] is None
                       else (int(t["s_lo"]), int(t["s_hi"]))),  # type: ignore[arg-type]
            recv_tags=(None if t["r_lo"] is None
                       else (int(t["r_lo"]), int(t["r_hi"]))),  # type: ignore[arg-type]
            send_any=bool(t["s_any"]), recv_any=bool(t["r_any"]),
            any_src=bool(t["any_src"]),
            n_sends=int(t["ns"]), n_recvs=int(t["nr"]))  # type: ignore[arg-type]
        for comm, t in sorted(per_comm.items()))
    return ProgramFootprint(
        label=name, world=world, signature=sig,
        comms=frozenset(per_comm) | {c for _, _, c in colls},
        reads=(), writes=(), persistent=frozenset(),
        ring_slots=frozenset(), streams=frozenset(),
        traffic=traffic, colls=frozenset(colls), synthetic_tags=False,
        rank_events=lambda: [list(p) for p in progs],
    )


def certificate_id(footprints: Sequence[ProgramFootprint]) -> str:
    """The certificate naming a pairwise-clean SET: a digest over the
    member signatures, order-independent — what the dispatch spans
    carry so the flight recorder can name the admitted tenant set."""
    return _digest(tuple(sorted(f.signature for f in footprints)))


def _fmt_end(prog: str, r: int, i: int, ev: Event) -> str:
    tag = "ANY" if ev.tag == TAG_ANY else str(ev.tag)
    peer = "ANY" if ev.peer == ANY_SRC else str(ev.peer)
    role = "->" if ev.kind == "send" else "<-"
    return (f"{prog} r{r}:{ev.kind}#{i}({role}r{peer}, tag {tag}, "
            f"comm {ev.comm:#x})")


def product_programs(
    a: list[list[Event]], b: list[list[Event]],
    *, a_synthetic: bool, b_synthetic: bool,
) -> list[list[Event]]:
    """The per-rank concatenation a_r + b_r the product model check
    explores, with SYNTHETIC tags namespaced per program (TAG_ANY stays
    wild: a wildcard pierces any namespace). Real tags are left alone —
    the shared wire is exactly what the product must model."""

    def shift(ev: Event, base: int) -> Event:
        if base == 0 or ev.kind == "coll" or ev.tag == TAG_ANY:
            return ev
        return dataclasses.replace(ev, tag=ev.tag + base)

    base_a = _PROGRAM_TAG_STRIDE if a_synthetic else 0
    base_b = 2 * _PROGRAM_TAG_STRIDE if b_synthetic else 0
    return [
        [shift(ev, base_a) for ev in ra] + [shift(ev, base_b) for ev in rb]
        for ra, rb in zip(a, b)
    ]


def _cross_matches(
    a: list[list[Event]], b: list[list[Event]],
    la: str, lb: str,
) -> list[str]:
    """The exact cross-program matching relation: every send occurrence
    of one program `_compatible` with a recv occurrence of the OTHER
    (same peer/comm, tags match incl. wildcards — protocol.py's own
    predicates, so the two layers cannot drift), plus cross-joinable
    collectives (equal (op, count, comm) signatures across programs).
    Returns rendered pairs; empty = the programs provably cannot
    exchange a single message, and any interleaving is equivalent to
    their serial composition."""
    pairs: list[str] = []

    def one_way(src: list[list[Event]], dst: list[list[Event]],
                ls: str, ld: str) -> None:
        for r, prog in enumerate(src):
            for i, sev in enumerate(prog):
                if sev.kind != "send":
                    continue
                d = sev.peer
                if not 0 <= d < len(dst):
                    continue
                for j, rev in enumerate(dst[d]):
                    if (rev.kind == "recv" and _src_matches(r, rev)
                            and rev.comm == sev.comm
                            and _tags_match(sev.tag, rev.tag)):
                        pairs.append(
                            f"{_fmt_end(ls, r, i, sev)} matchable by "
                            f"{_fmt_end(ld, d, j, rev)}")

    one_way(a, b, la, lb)
    one_way(b, a, lb, la)
    sigs_a = {(ev.op, ev.count, ev.comm)
              for prog in a for ev in prog if ev.kind == "coll"}
    sigs_b = {(ev.op, ev.count, ev.comm)
              for prog in b for ev in prog if ev.kind == "coll"}
    for op, count, comm in sorted(sigs_a & sigs_b):
        pairs.append(
            f"{la} and {lb} both join coll {op}(count {count}, comm "
            f"{comm:#x}): a barrier release can mix the two programs' "
            "arrivals")
    return pairs


class InterferenceCertifier:
    """Pairwise non-interference over footprints, with a per-pair
    verdict cache keyed by the two composite signatures
    (order-normalized: check(A, B) and check(B, A) share one entry).
    `escalations` counts cache-miss pairs that needed the product model
    check; `pairs_checked` counts cache misses total — a summary-only
    run is `escalations == 0`.

    The cache is LRU-bounded at `cache_cap` pairs (default from
    ``ACCL_INTERFERENCE_CACHE_CAP``, else 4096): an admission-control
    certifier outlives any one tenant set, and under churn the pair
    universe grows without limit. A hit refreshes the entry's recency;
    storing past the cap evicts the least-recently-used verdict
    (`cache_evictions` counts them). Eviction only ever costs a
    recompute — verdicts are pure functions of the two footprints, so
    a re-checked evicted pair gets the identical verdict back."""

    def __init__(self, budget: Budget | None = None,
                 cache_cap: int | None = None):
        self.budget = budget or Budget()
        self.cache_cap = (max(int(cache_cap), 1)
                          if cache_cap is not None
                          else _verdict_cache_cap())
        self._cache: OrderedDict[tuple[str, str],
                                 tuple[Diagnostic, ...]] = OrderedDict()
        self.escalations = 0
        self.pairs_checked = 0
        self.cache_evictions = 0

    # -- summary tier -------------------------------------------------

    def _memory_diags(self, a: ProgramFootprint,
                      b: ProgramFootprint) -> list[Diagnostic]:
        diags: list[Diagnostic] = []
        pair = f"[{a.label} x {b.label}]"
        reads_a, writes_a = dict(a.reads), dict(a.writes)
        reads_b, writes_b = dict(b.reads), dict(b.writes)
        seen: set[int] = set()
        for addr in sorted(writes_a.keys() | writes_b.keys()):
            wa, wb = addr in writes_a, addr in writes_b
            ra, rb = addr in reads_a, addr in reads_b
            if not ((wa and (wb or rb)) or (wb and (wa or ra))):
                continue
            if addr in seen:
                continue
            seen.add(addr)
            kind = "write/write" if wa and wb else "write/read"
            persist = (" (declared persistent — cross-program sharing "
                       "is still unordered)"
                       if addr in a.persistent | b.persistent else "")
            ea = max(writes_a.get(addr, 0), reads_a.get(addr, 0))
            eb = max(writes_b.get(addr, 0), reads_b.get(addr, 0))
            diags.append(make(
                "ACCL601",
                f"{pair} {kind} overlap on buffer {addr:#x}: "
                f"{a.label} touches [0, {ea}) and {b.label} touches "
                f"[0, {eb}) with no cross-program ordering{persist}"))
        for sid in sorted(a.streams & b.streams):
            diags.append(make(
                "ACCL601",
                f"{pair} both programs ride stream endpoint {sid}: a "
                "stream is a stateful FIFO, and concurrent dispatch "
                "interleaves the two programs' traffic through it"))
        return diags

    def _slot_diags(self, a: ProgramFootprint,
                    b: ProgramFootprint) -> list[Diagnostic]:
        shared = sorted(a.ring_slots & b.ring_slots)
        if not shared:
            return []
        return [make(
            "ACCL603",
            f"[{a.label} x {b.label}] both programs launch ring kernels "
            f"holding collective_id slot(s) {shared}: the slots are a "
            "global kernel resource and nothing orders the two "
            "programs' instances")]

    def _traffic_may_interfere(self, a: ProgramFootprint,
                               b: ProgramFootprint) -> bool:
        """Does the COARSE summary admit a cross-program message?
        Synthetic (hop-derived) traffic is program-private — only
        real-tag programs share the native matching engine."""
        if a.synthetic_tags or b.synthetic_tags:
            return False
        if a.colls & b.colls:
            return True
        for comm in sorted(a.comms & b.comms):
            ta, tb = a.traffic_on(comm), b.traffic_on(comm)
            if ta is None or tb is None:
                continue
            if ta.sends_match_recvs(tb) or tb.sends_match_recvs(ta):
                return True
        return False

    # -- escalation tier ----------------------------------------------

    def _escalate(self, a: ProgramFootprint,
                  b: ProgramFootprint) -> list[Diagnostic]:
        pair = f"[{a.label} x {b.label}]"
        if a.world != b.world:
            return [make(
                "ACCL604",
                f"{pair} traffic summaries overlap but the programs "
                f"span different worlds ({a.world} vs {b.world}): the "
                "product cannot be composed — UNVERIFIED")]
        try:
            ev_a, ev_b = a.events(), b.events()
        except Exception as e:
            return [make(
                "ACCL604",
                f"{pair} traffic summaries overlap and the pair needs "
                f"the product model check, but exact event programs "
                f"are unavailable ({e}) — UNVERIFIED")]
        cross = _cross_matches(ev_a, ev_b, a.label, b.label)
        if cross:
            shown = "\n    ".join(cross[:3])
            more = (f"\n    ... and {len(cross) - 3} more"
                    if len(cross) > 3 else "")
            return [make(
                "ACCL602",
                f"{pair} cross-program match on a shared communicator "
                f"— one program's traffic can steal the other's:\n    "
                f"{shown}{more}")]
        # no cross-compatible endpoint pair exists: certify the product
        # over every match order anyway (bounded, both concatenation
        # orders), so the refutation is a model-checked verdict, not
        # just a static argument. Truncation stays loud.
        diags: list[Diagnostic] = []
        for first, second, order in ((ev_a, ev_b, f"{a.label};{b.label}"),
                                     (ev_b, ev_a, f"{b.label};{a.label}")):
            prod = product_programs(
                first, second,
                a_synthetic=a.synthetic_tags if first is ev_a
                else b.synthetic_tags,
                b_synthetic=b.synthetic_tags if second is ev_b
                else a.synthetic_tags)
            for sem in ("rendezvous", "buffered"):
                res = check_interleavings(prod, semantics=sem,
                                          budget=self.budget)
                if res.truncated:
                    diags.append(make(
                        "ACCL207",
                        f"{pair} product exploration ({order}, {sem}) "
                        f"truncated after {res.states} states: "
                        "interleavings beyond the explored prefix are "
                        "UNVERIFIED"))
                if res.stuck_trace is not None:
                    steps = "\n    ".join(res.stuck_trace) \
                        or "(no matches)"
                    diags.append(make(
                        "ACCL602",
                        f"{pair} the {order} product reaches a stuck "
                        f"state under {sem} semantics although both "
                        f"programs certify alone:\n    {steps}\n  "
                        f"stuck at [{res.stuck_state}]"))
        return diags

    # -- the pairwise verdict -----------------------------------------

    def check_pair(self, a: ProgramFootprint,
                   b: ProgramFootprint) -> tuple[Diagnostic, ...]:
        """Certify one pair; cached by the order-normalized signature
        pair (messages render the labels the pair was FIRST checked
        under)."""
        lo, hi = sorted((a.signature, b.signature))
        key = (lo, hi)
        hit = self._cache.get(key)
        if hit is not None:
            self._cache.move_to_end(key)  # LRU refresh
            return hit
        self.pairs_checked += 1
        diags: list[Diagnostic]
        if a.unliftable is not None or b.unliftable is not None:
            bad = a if a.unliftable is not None else b
            diags = [make(
                "ACCL604",
                f"[{a.label} x {b.label}] footprint of {bad.label} "
                f"could not be lifted ({bad.unliftable}): the pair is "
                "UNVERIFIED")]
        else:
            diags = self._memory_diags(a, b)
            diags += self._slot_diags(a, b)
            if self._traffic_may_interfere(a, b):
                self.escalations += 1
                diags += self._escalate(a, b)
        verdict = tuple(diags)
        self._cache[key] = verdict
        while len(self._cache) > self.cache_cap:
            self._cache.popitem(last=False)
            self.cache_evictions += 1
        return verdict

    def certify(self, footprints: Sequence[ProgramFootprint]
                ) -> list[Diagnostic]:
        """The O(N^2) admission check: every unordered pair of the set,
        summaries first, escalating only on a summary overlap. A clean
        return means ANY concurrent interleaving of the set is
        equivalent to its serial composition."""
        out: list[Diagnostic] = []
        fps = list(footprints)
        for i in range(len(fps)):
            for j in range(i + 1, len(fps)):
                out.extend(self.check_pair(fps[i], fps[j]))
        return out


def certify_concurrent(
    footprints: Sequence[ProgramFootprint],
    *,
    budget: Budget | None = None,
    certifier: InterferenceCertifier | None = None,
) -> list[Diagnostic]:
    """One-shot module-level convenience over `InterferenceCertifier`
    (the facade's `ACCL.certify_concurrent` holds a long-lived
    certifier instead, so its per-pair cache spans admissions)."""
    c = certifier if certifier is not None \
        else InterferenceCertifier(budget)
    return c.certify(footprints)
