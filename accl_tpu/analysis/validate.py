"""Per-descriptor validation: the linter's ACCL4xx structural checks.

The facade's `_prepare` raises typed errors (accl_tpu/errors.py) for
calls built through the driver API; descriptors can ALSO enter the
system as raw word streams (corpus replay, the native executor's FIFO,
`CallOptions.from_words`) where no facade ever saw them. This pass
re-derives every host-side precondition from the descriptor alone so
both entry paths are gated identically — each check cites the typed
error class that guards the same invariant at call time.
"""

from __future__ import annotations

from ..constants import DataType, Operation
from ..sequencer.sequence import SEQUENCE_OPS
from .diagnostics import Diagnostic, make

# ops whose root_src_dst is a single communicator-relative root
_ROOTED = (Operation.bcast, Operation.scatter, Operation.gather,
           Operation.reduce)
# ops that move payload and therefore need a positive count and a dtype
_DATA = SEQUENCE_OPS + (Operation.send, Operation.recv)


def validate_steps(steps, world: int, *,
                   sequence: bool = True) -> list[Diagnostic]:
    """Structural checks over a batch of CallOptions. `sequence=True`
    additionally enforces the fused-batch contract (one communicator,
    sequenceable kinds, operand/result buffers present)."""
    diags: list[Diagnostic] = []
    steps = list(steps)
    if sequence and steps:
        comm = steps[0].comm_addr
        for k, opts in enumerate(steps):
            if opts.comm_addr != comm:
                diags.append(make(
                    "ACCL403",
                    f"step {k} addresses communicator "
                    f"{opts.comm_addr:#x} but the batch opened on "
                    f"{comm:#x}", step=k))
    for k, opts in enumerate(steps):
        scen = opts.scenario
        if sequence and scen not in SEQUENCE_OPS:
            diags.append(make(
                "ACCL404",
                f"{scen.name} cannot ride a call sequence (host-paired "
                "or payload-free descriptor)", step=k))
            continue
        if scen in _DATA:
            if opts.count <= 0:
                # host-side twin: errors.ZeroLengthBufferError
                diags.append(make(
                    "ACCL401",
                    f"{scen.name} with count {opts.count}: zero-length "
                    "payloads compile shape-degenerate schedules",
                    step=k))
            if opts.data_type == DataType.none:
                diags.append(make(
                    "ACCL401",
                    f"{scen.name} carries no payload dtype", step=k))
        if scen in _ROOTED and not 0 <= opts.root_src_dst < world:
            # host-side twin: errors.InvalidRootError
            diags.append(make(
                "ACCL402",
                f"{scen.name} root {opts.root_src_dst} outside "
                f"communicator of {world}", step=k))
        if scen in (Operation.send, Operation.recv):
            src = opts.root_src_dst & 0xFFFF
            dst = (opts.root_src_dst >> 16) & 0xFFFF
            if src >= world or dst >= world:
                diags.append(make(
                    "ACCL402",
                    f"{scen.name} src/dst ({src},{dst}) outside "
                    f"communicator of {world}", step=k))
        if sequence and scen in SEQUENCE_OPS:
            if opts.addr_0 == 0 or opts.addr_2 == 0:
                diags.append(make(
                    "ACCL401",
                    f"sequence step {scen.name} needs operand and "
                    "result buffers", step=k))
            if scen == Operation.combine and opts.addr_1 == 0:
                diags.append(make(
                    "ACCL401",
                    "combine step needs a second operand", step=k))
    return diags
