"""Semantic certifier: prove a descriptor batch computes its collective.

The linter and model checker prove SAFETY — no hazards, no protocol
mismatches, no races or deadlocks — but a schedule can pass all of that
and still leave rank 3 without rank 5's addend: a device-resident
sequencer then ships a wrong ANSWER, the failure class ACCL+ (arxiv
2312.11742) reports as silent numeric corruption debugged post-hoc.
This pass closes that gap with contribution-set abstract interpretation:

  1. `lift_call` abstractly evaluates the REAL schedule body's jaxpr
     (the same `protocol.trace_schedule_jaxpr` seam the protocol pass
     reads ppermute perms from — one model, nothing to drift) into a
     hop-DAG IR (`hopdag.HopDag`): every cross-rank move, reduction
     fold, and quantized encode/decode as data, with exact region
     intervals.
  2. `certify` interprets the DAG over the contribution-set domain:
     each element of each buffer region carries the multiset of source
     atoms it holds — atom (r, slot, j) is rank r's element j of
     operand `slot` — plus the reduction the atoms were folded under
     (SUM / MAX / pure data). Slices, concatenations and hops move
     contribution intervals around; combines merge them; the quantized
     lanes' named boundaries (codes carry their payload's provenance,
     scales are block metadata) keep the nonlinear encode math from
     dissolving provenance.
  3. The final per-rank contribution map is compared against the
     declared collective spec (`collective_spec`): allreduce means
     EVERY rank's element j holds {SUM over all ranks of atom j}, and
     so on for each family, quantized variants included.

Verdicts get stable codes:

  ACCL501  wrong-result: the final contribution set differs from the
           spec in a way that is neither purely missing nor purely
           duplicated (foreign atoms, wrong reduction, misrouted
           regions)
  ACCL502  partial-contribution: some rank's input never reaches an
           output region that the spec says must include it
  ACCL503  double-count: a contribution folded into the same
           non-idempotent reduction twice
  ACCL504  stale-read: a hop forwards a region before its producer
           wrote it (program-order violation in the DAG). This is the
           IR-level complement of the hazard pass's batch-level ACCL101
           — cross-checked against it by the corpus, never duplicated.

The pass is per-batch LINEAR (one abstract evaluation per step, no
interleaving exploration), so it rides the DEFAULT lint tier; verdicts
are cached by static signature alongside the compile cache they front.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import numpy as np

from ..constants import Operation, ReduceFunction
from .diagnostics import Diagnostic, make
from .hopdag import (
    CONST,
    DATA,
    SCALES,
    HopDag,
    Node,
    Piece,
    Value,
    concat_values,
    const_value,
    slice_value,
    splice_value,
    validate_order,
    value_length,
)

__all__ = [
    "UnsupportedSchedule",
    "lift_call",
    "collective_spec",
    "certify",
    "certify_call",
    "check_batch_semantics",
    "clear_cache",
]


class UnsupportedSchedule(Exception):
    """The lifter met a jaxpr construct outside the schedule
    vocabulary: the certifier can make NO claim about this body (it
    never guesses). Strict callers (the CLI conformance gate) fail
    loudly; the in-band tier skips the step."""


# ---------------------------------------------------------------------------
# Lifter: schedule jaxpr -> HopDag
# ---------------------------------------------------------------------------


def _literal_type():
    try:
        from jax.extend import core as jex_core

        return jex_core.Literal
    except (ImportError, AttributeError):  # pragma: no cover - old jax
        from jax import core as jcore

        return jcore.Literal


@dataclasses.dataclass
class _Sym:
    """One rank's abstract (payload-carrying) array during lifting:
    flat row-major piece list + logical shape."""

    shape: tuple[int, ...]
    pieces: Value
    dtype: Any

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1


def _is_sym(v: Any) -> bool:
    return isinstance(v, _Sym)


def _uniform_fill(a: np.ndarray) -> float | None:
    """The single fill value of a constant-uniform concrete array, or
    None when the array is not uniform."""
    flat = np.asarray(a).ravel()
    if flat.size == 0:
        return 0.0
    v = flat[0]
    if flat.size == 1 or bool(np.all(flat == v)):
        return float(v)
    return None


class _Lifter:
    def __init__(self, world: int):
        self.world = world
        self.nodes: list[Node] = []
        self.hops = 0
        self._literal = _literal_type()
        # Evaluation memos, keyed by object identity and kept alive for
        # the lift's duration (holding the keyed objects in the values
        # prevents id reuse). A scan body re-evaluates its jaxpr once
        # per trip, but its CONCRETE index math (rank offsets, masks) is
        # trip-invariant — memoizing per (eqn, operand identities) makes
        # later trips pay only for the abstract piece bookkeeping.
        self._lit_memo: dict[int, tuple[Any, list[Any]]] = {}
        self._const_memo: dict[int, tuple[Any, list[list[Any]]]] = {}
        self._eqn_memo: dict[tuple, tuple[list[Any], list[Any]]] = {}
        self._runs_memo: dict[int, tuple[Any, list[tuple[int, int, int]]]] = {}
        # one stable object per rank: downstream concrete memo keys are
        # identity-based, so axis_index must not mint fresh scalars
        self._axis_vals = [np.int32(r) for r in range(world)]

    # -- node construction -------------------------------------------------

    def emit(self, **kw: Any) -> int:
        nid = len(self.nodes)
        self.nodes.append(Node(id=nid, **kw))
        return nid

    def pieces_of(self, v: Any, size: int | None = None) -> Value:
        """A per-rank value as a piece list: syms directly, concrete
        uniform arrays as constant fill (zeros masks, pad values)."""
        if _is_sym(v):
            return v.pieces
        a = np.asarray(v)
        fill = _uniform_fill(a)
        if fill is None:
            raise UnsupportedSchedule(
                "non-uniform concrete data flows into the payload path")
        return const_value(size if size is not None else a.size, fill)

    # -- jaxpr evaluation --------------------------------------------------

    def eval_closed(self, closed: Any, args: list[list[Any]]) -> list[list[Any]]:
        memo = self._const_memo.get(id(closed))
        if memo is None:
            consts = [[np.asarray(c)] * self.world for c in closed.consts]
            self._const_memo[id(closed)] = (closed, consts)
        else:
            consts = memo[1]
        return self.eval_jaxpr(closed.jaxpr, consts, args)

    def eval_jaxpr(self, jaxpr: Any, consts: list[list[Any]],
                   args: list[list[Any]]) -> list[list[Any]]:
        env: dict[Any, list[Any]] = {}

        def read(x: Any) -> list[Any]:
            if isinstance(x, self._literal):
                memo = self._lit_memo.get(id(x))
                if memo is None:
                    memo = (x, [np.asarray(x.val)] * self.world)
                    self._lit_memo[id(x)] = memo
                return memo[1]
            return env[x]

        for var, val in zip(jaxpr.constvars, consts):
            env[var] = val
        for var, val in zip(jaxpr.invars, args):
            env[var] = val
        for eqn in jaxpr.eqns:
            invals = [read(x) for x in eqn.invars]
            outs = self.eval_eqn(eqn, invals)
            if len(outs) != len(eqn.outvars):
                raise UnsupportedSchedule(
                    f"{eqn.primitive.name}: arity mismatch in lifter")
            for var, val in zip(eqn.outvars, outs):
                env[var] = val
        return [read(v) for v in jaxpr.outvars]

    def eval_eqn(self, eqn: Any, invals: list[list[Any]]) -> list[list[Any]]:
        name = eqn.primitive.name
        if name == "ppermute":
            return [self._ppermute(eqn, invals[0])]
        if name == "axis_index":
            return [list(self._axis_vals)]
        if name in ("pjit", "closed_call", "core_call"):
            return self._call(eqn, invals)
        if name == "scan":
            return self._scan(eqn, invals)
        if name == "optimization_barrier":
            return list(invals)
        has_sym = any(_is_sym(v) for val in invals for v in val)
        if not has_sym:
            return self._concrete(eqn, invals)
        if name == "select_n":
            return [self._select(invals)]
        if name == "convert_element_type":
            return [self._convert(eqn, invals[0])]
        if name in ("add", "sub", "mul", "div", "max", "min"):
            return [self._binop(name, invals[0], invals[1])]
        if name == "dynamic_slice":
            return [self._dynamic_slice(eqn, invals)]
        if name == "dynamic_update_slice":
            return [self._dynamic_update_slice(invals)]
        if name == "slice":
            return [self._static_slice(eqn, invals[0])]
        if name == "concatenate":
            return [self._concat(eqn, invals)]
        if name in ("reshape", "squeeze"):
            return [self._reshape(eqn, invals[0])]
        if name == "broadcast_in_dim":
            return [self._reshape(eqn, invals[0])]
        if name == "pad":
            return [self._pad(eqn, invals)]
        raise UnsupportedSchedule(
            f"primitive {name!r} over abstract payload")

    # -- handlers ----------------------------------------------------------

    def _sym(self, shape: Sequence[int], pieces: Value, dtype: Any) -> _Sym:
        return _Sym(tuple(int(s) for s in shape), pieces, np.dtype(dtype))

    def _out_aval(self, eqn: Any, i: int = 0) -> Any:
        return eqn.outvars[i].aval

    def _ppermute(self, eqn: Any, val: list[Any]) -> list[Any]:
        perm = eqn.params["perm"]
        aval = self._out_aval(eqn)
        n = int(np.prod(aval.shape)) if aval.shape else 1
        hop = self.hops
        self.hops += 1
        if not any(_is_sym(v) for v in val):
            out: list[Any] = [np.zeros(aval.shape, np.asarray(val[0]).dtype)
                              for _ in range(self.world)]
            for s, d in perm:
                out[d] = np.asarray(val[s])
            return out
        dtype = next(v.dtype for v in val if _is_sym(v))
        recvs: dict[int, int] = {}
        for s, d in perm:
            self.emit(kind="send", rank=int(s), length=n,
                      value=self.pieces_of(val[s], n), hop=hop,
                      peer=int(d))
        for s, d in perm:
            recvs[int(d)] = self.emit(kind="recv", rank=int(d), length=n,
                                      hop=hop, peer=int(s))
        outs = []
        for r in range(self.world):
            if r in recvs:
                pieces: Value = (Piece(n, recvs[r]),)
            else:
                pieces = const_value(n, 0.0)
            outs.append(self._sym(aval.shape, pieces, dtype))
        return outs

    def _call(self, eqn: Any, invals: list[list[Any]]) -> list[list[Any]]:
        name = str(eqn.params.get("name", ""))
        if name.startswith("accl_sem_"):
            return self._marker(name, eqn, invals)
        closed = eqn.params["jaxpr"] if "jaxpr" in eqn.params \
            else eqn.params.get("call_jaxpr")
        if closed is None:
            raise UnsupportedSchedule(f"call primitive without jaxpr: {name}")
        if hasattr(closed, "consts"):
            return self.eval_closed(closed, invals)
        return self.eval_jaxpr(closed, [], invals)

    def _marker(self, name: str, eqn: Any,
                invals: list[list[Any]]) -> list[list[Any]]:
        """The compression lanes' named boundaries: apply each lane's
        SEMANTIC rule instead of descending into the blockwise math."""
        if name == "accl_sem_encode":
            x = invals[0]
            n = int(self._out_aval(eqn, 0).shape[-1])
            nb = int(self._out_aval(eqn, 1).shape[-1])
            codes, scales = [], []
            for r in range(self.world):
                nid = self.emit(kind="encode", rank=r, length=n,
                                scales_len=nb,
                                value=self.pieces_of(x[r], n),
                                dtype="int8")
                codes.append(self._sym((n,), (Piece(n, nid),), np.int8))
                scales.append(self._sym(
                    (nb,), (Piece(nb, nid, 0, SCALES),), np.float32))
            return [codes, scales]
        if name == "accl_sem_decode":
            q, s = invals[0], invals[1]
            aval = self._out_aval(eqn)
            n = int(aval.shape[-1])
            outs = []
            for r in range(self.world):
                nid = self.emit(kind="decode", rank=r, length=n,
                                value=self.pieces_of(q[r]),
                                value2=self.pieces_of(s[r]))
                outs.append(self._sym(aval.shape, (Piece(n, nid),),
                                      aval.dtype))
            return [outs]
        if name == "accl_sem_pack":
            # ONE-message quantized hop (ops.compression.pack_wire):
            # codes + bitcast scales concatenated into a single int8
            # wire payload. Abstract convention: the packed value's
            # pieces are (codes pieces, scales pieces) back to back in
            # ELEMENT space (n + nb), while the wire aval is the byte
            # form (n + 4*nb) — only the matching accl_sem_unpack ever
            # slices a packed value, and it slices by the same element
            # convention, so provenance flows exactly and the 3*nb
            # bitcast-padding tail reads as empty.
            q, s = invals[0], invals[1]
            aval = self._out_aval(eqn)
            outs = []
            for r in range(self.world):
                pieces = concat_values(self.pieces_of(q[r]),
                                       self.pieces_of(s[r]))
                outs.append(self._sym(aval.shape, pieces, np.int8))
            return [outs]
        if name == "accl_sem_unpack":
            p = invals[0]
            n = int(self._out_aval(eqn, 0).shape[-1])
            nb = int(self._out_aval(eqn, 1).shape[-1])
            codes, scales = [], []
            for r in range(self.world):
                pieces = self.pieces_of(p[r])
                codes.append(self._sym((n,), slice_value(pieces, 0, n),
                                       np.int8))
                scales.append(self._sym((nb,),
                                        slice_value(pieces, n, nb),
                                        np.float32))
            return [codes, scales]
        if name.startswith("accl_sem_dequant_combine_") \
                or name.startswith("accl_sem_dequant_requant_"):
            func = name.rsplit("_", 1)[-1]
            requant = "_requant_" in name
            q, s, local = invals[0], invals[1], invals[2]
            aval = self._out_aval(eqn, 0)
            n = int(aval.shape[-1])
            outs, scales_out = [], []
            for r in range(self.world):
                dec = self.emit(kind="decode", rank=r, length=n,
                                value=self.pieces_of(q[r]),
                                value2=self.pieces_of(s[r]))
                cmb = self.emit(kind="combine", rank=r, length=n,
                                func=func, value=(Piece(n, dec),),
                                value2=self.pieces_of(local[r], n))
                if requant:
                    nb = int(self._out_aval(eqn, 1).shape[-1])
                    enc = self.emit(kind="encode", rank=r, length=n,
                                    scales_len=nb,
                                    value=(Piece(n, cmb),), dtype="int8")
                    outs.append(self._sym((n,), (Piece(n, enc),), np.int8))
                    scales_out.append(self._sym(
                        (nb,), (Piece(nb, enc, 0, SCALES),), np.float32))
                else:
                    outs.append(self._sym(aval.shape, (Piece(n, cmb),),
                                          aval.dtype))
            return [outs, scales_out] if requant else [outs]
        raise UnsupportedSchedule(f"unknown semantic marker {name!r}")

    def _scan(self, eqn: Any, invals: list[list[Any]]) -> list[list[Any]]:
        p = eqn.params
        if p.get("_split_transpose"):
            raise UnsupportedSchedule("split-transpose scan")
        nc, ncar = int(p["num_consts"]), int(p["num_carry"])
        length = int(p["length"])
        closed = p["jaxpr"]
        consts = invals[:nc]
        carry = list(invals[nc:nc + ncar])
        xs = invals[nc + ncar:]
        order = range(length - 1, -1, -1) if p.get("reverse") \
            else range(length)
        ys_acc: list[list[list[Any]]] = []
        for i in order:
            xi = [self._index_leading(x, i) for x in xs]
            outs = self.eval_closed(closed, consts + carry + xi)
            carry = outs[:ncar]
            ys = outs[ncar:]
            if p.get("reverse"):
                ys_acc.insert(0, ys)
            else:
                ys_acc.append(ys)
        stacked = []
        n_ys = len(ys_acc[0]) if ys_acc else 0
        for j in range(n_ys):
            stacked.append(self._stack([ys[j] for ys in ys_acc]))
        return carry + stacked

    def _index_leading(self, x: list[Any], i: int) -> list[Any]:
        out = []
        for v in x:
            if _is_sym(v):
                if len(v.shape) < 1:
                    raise UnsupportedSchedule("scan over scalar payload")
                m = int(np.prod(v.shape[1:])) if len(v.shape) > 1 else 1
                sub = slice_value(v.pieces, i * m, m)
                out.append(self._sym(v.shape[1:] or (), sub, v.dtype))
            else:
                out.append(np.asarray(v)[i])
        return out

    def _stack(self, rows: list[list[Any]]) -> list[Any]:
        out = []
        for r in range(self.world):
            vals = [row[r] for row in rows]
            if any(_is_sym(v) for v in vals):
                pieces = concat_values(*[self.pieces_of(v) for v in vals])
                first = next(v for v in vals if _is_sym(v))
                out.append(self._sym((len(vals),) + first.shape, pieces,
                                     first.dtype))
            else:
                out.append(np.stack([np.asarray(v) for v in vals]))
        return out

    def _select(self, invals: list[list[Any]]) -> list[Any]:
        pred, cases = invals[0], invals[1:]
        outs = []
        for r in range(self.world):
            p = pred[r]
            if _is_sym(p):
                raise UnsupportedSchedule("data-dependent select predicate")
            pi = np.asarray(p).astype(np.int64).ravel()
            rcases = [c[r] for c in cases]
            if not any(_is_sym(c) for c in rcases):
                idx = np.asarray(p).astype(np.int64)
                stackable = [np.broadcast_to(np.asarray(c), idx.shape)
                             for c in rcases]
                outs.append(np.choose(idx, stackable))
                continue
            template = next(c for c in rcases if _is_sym(c))
            n = template.size
            if pi.size <= 1:
                choice = rcases[int(pi[0]) if pi.size else 0]
                pieces = self.pieces_of(choice, n)
            else:
                if pi.size != n:
                    raise UnsupportedSchedule("select mask/payload mismatch")
                memo = self._runs_memo.get(id(p))
                if memo is None:
                    bounds = list(np.flatnonzero(np.diff(pi)) + 1)
                    starts = [0, *bounds]
                    ends = [*bounds, n]
                    memo = (p, [(lo, hi, int(pi[lo]))
                                for lo, hi in zip(starts, ends)])
                    self._runs_memo[id(p)] = memo
                runs = []
                for lo, hi, which in memo[1]:
                    src = self.pieces_of(rcases[which], n)
                    runs.append(slice_value(src, lo, hi - lo))
                pieces = concat_values(*runs)
            outs.append(self._sym(template.shape, pieces, template.dtype))
        return outs

    def _convert(self, eqn: Any, val: list[Any]) -> list[Any]:
        new = np.dtype(eqn.params["new_dtype"])
        outs = []
        for r in range(self.world):
            v = val[r]
            if not _is_sym(v):
                outs.append(np.asarray(v).astype(new))
            elif v.dtype == new:
                outs.append(v)
            else:
                nid = self.emit(kind="cast", rank=r, length=v.size,
                                value=v.pieces, dtype=new.name)
                outs.append(self._sym(v.shape, (Piece(v.size, nid),), new))
        return outs

    def _binop(self, name: str, a: list[Any], b: list[Any]) -> list[Any]:
        np_ops: dict[str, Callable] = {
            "add": np.add, "sub": np.subtract, "mul": np.multiply,
            "div": np.divide, "max": np.maximum, "min": np.minimum}
        outs = []
        for r in range(self.world):
            x, y = a[r], b[r]
            if not _is_sym(x) and not _is_sym(y):
                outs.append(np_ops[name](np.asarray(x), np.asarray(y)))
                continue
            outs.append(self._abstract_binop(name, r, x, y))
        return outs

    def _abstract_binop(self, name: str, rank: int, x: Any, y: Any) -> _Sym:
        sym = x if _is_sym(x) else y
        other = y if _is_sym(x) else x
        if not _is_sym(other):
            fill = _uniform_fill(np.asarray(other))
            if fill is None:
                raise UnsupportedSchedule(
                    f"{name} of payload with non-uniform concrete data")
            neutral = {"add": 0.0, "sub": 0.0, "mul": 1.0, "div": 1.0}
            if name in neutral and fill == neutral[name]:
                if name in ("sub", "div") and _is_sym(y):
                    raise UnsupportedSchedule(f"payload on {name} rhs only")
                return sym
            if name == "mul" and fill == 0.0:
                return self._sym(sym.shape, const_value(sym.size, 0.0),
                                 sym.dtype)
            if name == "max":
                # max with a constant floor keeps provenance
                other = self._sym(sym.shape, const_value(sym.size, fill),
                                  sym.dtype)
            else:
                raise UnsupportedSchedule(
                    f"{name} of payload with constant {fill}")
        if name not in ("add", "max"):
            raise UnsupportedSchedule(f"{name} folds payload values")
        lhs = x if _is_sym(x) else other
        rhs = y if _is_sym(y) else other
        assert _is_sym(lhs) and _is_sym(rhs)
        if lhs.size != rhs.size:
            raise UnsupportedSchedule("combine of mismatched extents")
        func = "sum" if name == "add" else "max"
        nid = self.emit(kind="combine", rank=rank, length=lhs.size,
                        func=func, value=lhs.pieces, value2=rhs.pieces)
        return self._sym(lhs.shape, (Piece(lhs.size, nid),), lhs.dtype)

    def _int_of(self, v: Any) -> int:
        if _is_sym(v):
            raise UnsupportedSchedule("data-dependent index")
        return int(np.asarray(v).reshape(()))

    def _dynamic_slice(self, eqn: Any, invals: list[list[Any]]) -> list[Any]:
        sizes = eqn.params["slice_sizes"]
        outs = []
        for r in range(self.world):
            op = invals[0][r]
            starts = [self._int_of(s[r]) for s in invals[1:]]
            if not _is_sym(op):
                idx = tuple(slice(st, st + sz)
                            for st, sz in zip(starts, sizes))
                outs.append(np.asarray(op)[idx])
                continue
            if (len(op.shape) > 1
                    and (any(s for s in starts[1:])
                         or tuple(sizes[1:]) != op.shape[1:])):
                raise UnsupportedSchedule(
                    "non-contiguous dynamic_slice of payload")
            m = int(np.prod(op.shape[1:])) if len(op.shape) > 1 else 1
            n = int(sizes[0]) * m
            start = max(0, min(starts[0] * m, op.size - n))  # lax clamping
            outs.append(self._sym(tuple(sizes),
                                  slice_value(op.pieces, start, n),
                                  op.dtype))
        return outs

    def _dynamic_update_slice(self, invals: list[list[Any]]) -> list[Any]:
        outs = []
        for r in range(self.world):
            base, upd = invals[0][r], invals[1][r]
            starts = [self._int_of(s[r]) for s in invals[2:]]
            if not _is_sym(base) and not _is_sym(upd):
                a = np.array(np.asarray(base), copy=True)
                idx = tuple(slice(st, st + sz) for st, sz in
                            zip(starts, np.shape(upd)))
                a[idx] = upd
                outs.append(a)
                continue
            shape = base.shape if _is_sym(base) else np.shape(base)
            if len(shape) != 1:
                raise UnsupportedSchedule(
                    "dynamic_update_slice on nd payload")
            total = int(shape[0])
            u_len = upd.size if _is_sym(upd) else int(np.asarray(upd).size)
            start = max(0, min(starts[0], total - u_len))
            dtype = base.dtype if _is_sym(base) else upd.dtype
            pieces = splice_value(self.pieces_of(base, total),
                                  self.pieces_of(upd, u_len), start)
            outs.append(self._sym((total,), pieces, dtype))
        return outs

    def _static_slice(self, eqn: Any, val: list[Any]) -> list[Any]:
        p = eqn.params
        strides = p.get("strides")
        if strides is not None and any(int(s) != 1 for s in strides):
            raise UnsupportedSchedule("strided slice of payload")
        starts, limits = p["start_indices"], p["limit_indices"]
        outs = []
        for r in range(self.world):
            v = val[r]
            if not _is_sym(v):
                idx = tuple(slice(int(a), int(b))
                            for a, b in zip(starts, limits))
                outs.append(np.asarray(v)[idx])
                continue
            if (len(v.shape) > 1
                    and (any(int(a) for a in starts[1:])
                         or tuple(int(b) for b in limits[1:])
                         != v.shape[1:])):
                raise UnsupportedSchedule("non-contiguous slice of payload")
            m = int(np.prod(v.shape[1:])) if len(v.shape) > 1 else 1
            lo, hi = int(starts[0]), int(limits[0])
            shape = (hi - lo,) + v.shape[1:]
            outs.append(self._sym(shape,
                                  slice_value(v.pieces, lo * m,
                                              (hi - lo) * m),
                                  v.dtype))
        return outs

    def _concat(self, eqn: Any, invals: list[list[Any]]) -> list[Any]:
        dim = int(eqn.params["dimension"])
        outs = []
        for r in range(self.world):
            vals = [v[r] for v in invals]
            if not any(_is_sym(v) for v in vals):
                outs.append(np.concatenate(
                    [np.asarray(v) for v in vals], axis=dim))
                continue
            if dim != 0 or any(_is_sym(v) and len(v.shape) != 1
                               for v in vals):
                raise UnsupportedSchedule("nd concatenate of payload")
            pieces = concat_values(*[self.pieces_of(v) for v in vals])
            first = next(v for v in vals if _is_sym(v))
            outs.append(self._sym((value_length(pieces),), pieces,
                                  first.dtype))
        return outs

    def _reshape(self, eqn: Any, val: list[Any]) -> list[Any]:
        aval = self._out_aval(eqn)
        outs = []
        for r in range(self.world):
            v = val[r]
            if not _is_sym(v):
                outs.append(np.broadcast_to(
                    np.asarray(v), aval.shape).reshape(aval.shape))
                continue
            if int(np.prod(aval.shape)) != v.size:
                raise UnsupportedSchedule("broadcast enlarges payload")
            outs.append(self._sym(aval.shape, v.pieces, v.dtype))
        return outs

    def _pad(self, eqn: Any, invals: list[list[Any]]) -> list[Any]:
        config = eqn.params["padding_config"]
        outs = []
        for r in range(self.world):
            v, pv = invals[0][r], invals[1][r]
            if not _is_sym(v):
                outs.append(np.asarray(
                    np.pad(np.asarray(v),
                           [(int(lo), int(hi)) for lo, hi, _ in config],
                           constant_values=float(np.asarray(pv)))))
                continue
            if len(config) != 1:
                raise UnsupportedSchedule("nd pad of payload")
            lo, hi, interior = (int(x) for x in config[0])
            if interior or lo < 0 or hi < 0:
                raise UnsupportedSchedule("interior/negative pad of payload")
            fill = float(np.asarray(pv).reshape(()))
            pieces = concat_values(const_value(lo, fill), v.pieces,
                                   const_value(hi, fill))
            outs.append(self._sym((lo + v.size + hi,), pieces, v.dtype))
        return outs

    def _concrete(self, eqn: Any, invals: list[list[Any]]) -> list[list[Any]]:
        n_out = len(eqn.outvars)
        outs: list[list[Any]] = [[None] * self.world for _ in range(n_out)]
        for r in range(self.world):
            args = [val[r] for val in invals]
            key = (id(eqn), *(id(a) for a in args))
            memo = self._eqn_memo.get(key)
            if memo is None:
                try:
                    raw = eqn.primitive.bind(*args, **eqn.params)
                except Exception as e:
                    raise UnsupportedSchedule(
                        f"concrete eval of {eqn.primitive.name} failed: "
                        f"{e!r}") from e
                res = [np.asarray(x) for x in raw] \
                    if eqn.primitive.multiple_results else [np.asarray(raw)]
                # the keyed objects ride the value so their ids stay
                # live (no reuse) for the lift's lifetime
                memo = (args, res)
                self._eqn_memo[key] = memo
            for j in range(n_out):
                outs[j][r] = memo[1][j]
        return outs


def lift_call(options: Any, plan: Any, world: int,
              axis_name: str = "ccl",
              arith_table: dict | None = None) -> HopDag:
    """Lift ONE call's schedule body into the hop-DAG IR by abstract
    evaluation of its jaxpr (shared tracing seam:
    `protocol.trace_schedule_jaxpr` with the semantic boundaries
    active)."""
    from .protocol import trace_schedule_jaxpr

    closed, n_in, in_elems = trace_schedule_jaxpr(
        options, plan, world, axis_name, arith_table=arith_table,
        semantic_marks=True)
    lifter = _Lifter(world)
    args: list[list[Any]] = []
    for slot in range(n_in):
        per_rank = []
        for r in range(world):
            nid = lifter.emit(kind="arg", rank=r, length=in_elems,
                              arg=slot, dtype="float32")
            per_rank.append(lifter._sym((in_elems,),
                                        (Piece(in_elems, nid),),
                                        np.float32))
        args.append(per_rank)
    outs = lifter.eval_closed(closed, args)
    if len(outs) != 1:
        raise UnsupportedSchedule("schedule body with multiple outputs")
    result = outs[0]
    out_values = []
    out_elems = 0
    for r in range(world):
        v = result[r]
        pieces = lifter.pieces_of(v)
        out_values.append(pieces)
        out_elems = max(out_elems, value_length(pieces))
    return HopDag(world=world, n_in=n_in, in_elems=in_elems,
                  out_elems=out_elems, nodes=tuple(lifter.nodes),
                  outputs=tuple(out_values))


# ---------------------------------------------------------------------------
# Contribution-set interpretation
# ---------------------------------------------------------------------------

# A Term names one source of data: ("a", rank, slot, base) is the affine
# atom family "operand `slot` of rank `rank`, element base+j at local
# offset j"; ("s", node) is block-scale metadata of an encode node;
# ("stale", node) marks content read before node `node` produced it.
Term = tuple
Terms = dict[Term, int]
# A segment is (length, op, terms): `op` is the reduction the terms were
# folded under — None (pure data), "sum", "max", or "mixed".
Seg = tuple[int, Any, Terms]
IMap = list[Seg]


def _shift_terms(terms: Terms, off: int) -> Terms:
    if off == 0:
        return terms
    return {(t[0], t[1], t[2], t[3] + off) if t[0] == "a" else t: c
            for t, c in terms.items()}


def _imap_slice(imap: IMap, start: int, length: int) -> IMap:
    out: IMap = []
    pos = 0
    end = start + length
    for seg_len, op, terms in imap:
        lo, hi = max(start, pos), min(end, pos + seg_len)
        if lo < hi:
            out.append((hi - lo, op, _shift_terms(terms, lo - pos)))
        pos += seg_len
        if pos >= end:
            break
    got = sum(s[0] for s in out)
    if got < length:
        out.append((length - got, None, {}))
    return out


def _join_op(func: str, a: Any, b: Any) -> Any:
    for side in (a, b):
        if side not in (None, func):
            return "mixed"
    return func


def _merge_terms(a: Terms, b: Terms) -> Terms:
    out = dict(a)
    for t, c in b.items():
        out[t] = out.get(t, 0) + c
    return out


def _imap_join(func: str, a: IMap, b: IMap) -> IMap:
    out: IMap = []
    ai = bi = 0
    a_off = b_off = 0
    while ai < len(a) and bi < len(b):
        alen, aop, at = a[ai]
        blen, bop, bt = b[bi]
        take = min(alen - a_off, blen - b_off)
        out.append((take, _join_op(func, aop, bop),
                    _merge_terms(_shift_terms(at, a_off),
                                 _shift_terms(bt, b_off))))
        a_off += take
        b_off += take
        if a_off == alen:
            ai += 1
            a_off = 0
        if b_off == blen:
            bi += 1
            b_off = 0
    return _imap_norm(out)


def _imap_norm(imap: IMap) -> IMap:
    out: IMap = []
    for seg in imap:
        if seg[0] == 0:
            continue
        if out and out[-1][1] == seg[1] and out[-1][2] == _shift_terms(
                seg[2], -out[-1][0]):
            prev = out.pop()
            out.append((prev[0] + seg[0], prev[1], prev[2]))
        else:
            out.append(seg)
    return out


class _ContribEval:
    """Evaluate every node's contribution interval map in program
    order; reads of not-yet-produced nodes yield stale terms."""

    def __init__(self, dag: HopDag):
        self.dag = dag
        self.sends = dag.sends_by_channel()
        self.memo: dict[tuple[int, str], IMap] = {}

    def value_imap(self, value: Value, consumer: int) -> IMap:
        segs: IMap = []
        for p in value:
            if p.node == CONST:
                segs.append((p.length, None, {}))
            elif p.node >= consumer:
                segs.append((p.length, None, {("stale", p.node): 1}))
            else:
                segs.extend(_imap_slice(self.memo[(p.node, p.part)],
                                        p.offset, p.length))
        return _imap_norm(segs)

    def run(self) -> None:
        for n in self.dag.nodes:
            imap: IMap
            if n.kind == "arg":
                imap = [(n.length, None, {("a", n.rank, max(n.arg, 0), 0): 1})]
            elif n.kind in ("send", "cast"):
                imap = self.value_imap(n.value, n.id)
            elif n.kind == "recv":
                s = self.sends.get((n.hop, n.rank))
                if s is None:
                    imap = [(n.length, None, {("stale", n.id): 1})]
                elif s.id >= n.id:
                    imap = [(n.length, None, {("stale", s.id): 1})]
                else:
                    imap = _imap_slice(self.memo[(s.id, DATA)], 0, n.length)
            elif n.kind == "combine":
                imap = _imap_join(n.func or "sum",
                                  self.value_imap(n.value, n.id),
                                  self.value_imap(n.value2, n.id))
            elif n.kind == "encode":
                imap = self.value_imap(n.value, n.id)
                self.memo[(n.id, SCALES)] = [
                    (n.scales_len, None, {("s", n.id): 1})]
            elif n.kind == "decode":
                imap = _imap_slice(self.value_imap(n.value, n.id),
                                   0, n.length)
            else:
                raise UnsupportedSchedule(f"unknown node kind {n.kind!r}")
            self.memo[(n.id, DATA)] = imap

    def output_imap(self, rank: int) -> IMap:
        return self.value_imap(self.dag.outputs[rank],
                               len(self.dag.nodes))


# ---------------------------------------------------------------------------
# Collective specs
# ---------------------------------------------------------------------------


def _func_name(function: int) -> str:
    return "max" if ReduceFunction(function) == ReduceFunction.MAX \
        else "sum"


def collective_spec(options: Any, world: int) -> list[IMap | None] | None:
    """The declared meaning of one call as per-rank contribution maps:
    spec[r] is the interval map rank r's output MUST equal, or None for
    ranks whose output the collective leaves unspecified (non-root
    ranks of reduce/gather). Returns None when the scenario carries no
    payload contract (barrier/config/nop)."""
    op = options.scenario
    count = int(options.count)
    func = _func_name(options.function)

    def atom(r: int, base: int = 0, slot: int = 0) -> Terms:
        return {("a", r, slot, base): 1}

    def data(terms: Terms, length: int = count) -> Seg:
        return (length, None, terms)

    def red(terms: Terms, length: int = count) -> Seg:
        o = func if sum(terms.values()) > 1 else None
        return (length, o, terms)

    if op in (Operation.barrier, Operation.config, Operation.nop):
        return None
    if op == Operation.copy:
        return [[data(atom(r))] for r in range(world)]
    if op == Operation.combine:
        return [[red(_merge_terms(atom(r, 0, 0), atom(r, 0, 1)))]
                for r in range(world)]
    if op in (Operation.send, Operation.recv):
        src = options.root_src_dst & 0xFFFF
        dst = (options.root_src_dst >> 16) & 0xFFFF
        return [[data(atom(src if r == dst else r))] for r in range(world)]
    root = int(options.root_src_dst)
    if op == Operation.bcast:
        return [[data(atom(root))] for r in range(world)]
    if op == Operation.scatter:
        return [[data(atom(root, r * count))] for r in range(world)]
    if op == Operation.gather:
        rooted = [data(atom(c)) for c in range(world)]
        return [rooted if r == root else None for r in range(world)]
    if op == Operation.allgather:
        return [[data(atom(c)) for c in range(world)]
                for _ in range(world)]
    if op == Operation.reduce:
        full = _merge_all(atom(rr) for rr in range(world))
        return [[red(full)] if r == root else None for r in range(world)]
    if op == Operation.allreduce:
        # degraded live-subset mode (allreduce(mode="live_subset")): the
        # descriptor DECLARES the surviving-contributor set, and the
        # spec demands exactly those ranks' atoms — no more (a dead
        # rank's stale partial folded in is a foreign atom, ACCL501),
        # no fewer (a dropped survivor is ACCL502). Every rank's output
        # still carries the (survivor) sum: dead ranks relay the ring
        # but contribute masked zeros. Empty live_ranks = every rank
        # contributes, the ordinary collective.
        live = tuple(getattr(options, "live_ranks", ()) or ())
        contributors = live if live else tuple(range(world))
        full = _merge_all(atom(rr) for rr in contributors)
        return [[red(full)] for _ in range(world)]
    if op == Operation.reduce_scatter:
        return [[red(_merge_all(atom(rr, r * count)
                                for rr in range(world)))]
                for r in range(world)]
    if op == Operation.alltoall:
        pc = tuple(getattr(options, "peer_counts", ()) or ())
        if pc and any(c != count for c in pc):
            # alltoallv: rank r's slot for source c holds the first
            # peer_counts[r] elements of c's slot r — the capacity
            # prefix — and the overflow tail is DROPPED: the spec
            # declares it empty (zero-fill), so a schedule leaking
            # stale or misrouted data into the dropped region fails
            # certification instead of hiding behind the drop.
            def v_slot(r: int, c: int) -> IMap:
                v = int(pc[r])
                segs: IMap = [data(atom(c, r * count), v)]
                if v < count:
                    segs.append((count - v, None, {}))
                return segs

            return [[seg for c in range(world) for seg in v_slot(r, c)]
                    for r in range(world)]
        return [[data(atom(c, r * count)) for c in range(world)]
                for r in range(world)]
    return None


def _merge_all(terms_iter: Any) -> Terms:
    out: Terms = {}
    for t in terms_iter:
        out = _merge_terms(out, t)
    return out


# ---------------------------------------------------------------------------
# Certification
# ---------------------------------------------------------------------------

_MAX_DIAGS = 8


def _render_terms(terms: Terms, limit: int = 4) -> str:
    """Compact `{SUM-ready}` rendering: atom families grouped by
    (slot, base) over their rank sets."""
    fams: dict[tuple[int, int], list[tuple[int, int]]] = {}
    other: list[str] = []
    for t, c in sorted(terms.items(), key=repr):
        if t[0] == "a":
            fams.setdefault((t[2], t[3]), []).append((t[1], c))
        elif t[0] == "s":
            other.append(f"scales(node {t[1]})")
        else:
            other.append(f"stale(node {t[1]})")
    parts = []
    for (slot, base), ranks in sorted(fams.items()):
        rs = ",".join(f"r{r}" + (f"x{c}" if c != 1 else "")
                      for r, c in ranks)
        loc = f"@{base}+j" if base else "@j"
        sl = f" arg{slot}" if slot else ""
        parts.append("{" + rs + "}" + sl + loc)
    parts.extend(other)
    if not parts:
        return "(nothing: no source data reaches this region)"
    if len(parts) > limit:
        parts = parts[:limit] + [f"...+{len(parts) - limit} more"]
    return " + ".join(parts)


def _classify(got_op: Any, got: Terms, want_op: Any,
              want: Terms) -> tuple[str, str] | None:
    """Compare one aligned region's contribution set against the spec;
    returns (code, detail) or None when it matches."""
    idem = want_op == "max"
    g = {t: (1 if idem else c) for t, c in got.items()}
    w = {t: (1 if idem else c) for t, c in want.items()}
    stale = [t for t in g if t[0] == "stale"]
    if stale:
        return ("ACCL501",
                "region holds stale data (read before written)")
    op_ok = (sum(g.values()) <= 1 or got_op == want_op
             or (got_op is None and sum(g.values()) <= 1))
    if g == w and op_ok:
        return None
    foreign = {t: c for t, c in g.items() if t not in w}
    missing = {t: w[t] - g.get(t, 0) for t in w if g.get(t, 0) < w[t]}
    excess = {t: g[t] - w[t] for t in w if g.get(t, 0) > w[t]}
    if not foreign and not excess and missing:
        return ("ACCL502",
                f"missing contribution {_render_terms(missing)}")
    if not foreign and not missing and excess and not idem:
        return ("ACCL503",
                f"contribution {_render_terms(excess)} folded into the "
                f"same {want_op or 'sum'} twice")
    if g == w and not op_ok:
        return ("ACCL501",
                f"region reduced with {got_op or 'no fold'} where the "
                f"collective declares {want_op}")
    return ("ACCL501",
            f"expected {_render_terms(want)}, got {_render_terms(got)}")


def certify(dag: HopDag, spec: list[IMap | None] | None,
            scenario_name: str = "collective") -> list[Diagnostic]:
    """Prove the DAG's outputs carry exactly the contribution sets the
    collective spec declares. Emits ACCL501-504."""
    if spec is None:
        return []
    diags = validate_order(dag)
    ev = _ContribEval(dag)
    ev.run()
    have_stale = bool(diags)
    for r in range(dag.world):
        want = spec[r] if r < len(spec) else None
        if want is None:
            continue
        got = ev.output_imap(r)
        want_total = sum(s[0] for s in want)
        got_total = sum(s[0] for s in got)
        if got_total < want_total:
            got = got + [(want_total - got_total, None, {})]
        pos = 0
        gi = wi = 0
        g_off = w_off = 0
        while wi < len(want) and len(diags) < _MAX_DIAGS:
            wl, wop, wt = want[wi]
            if gi >= len(got):
                break
            gl, gop, gt = got[gi]
            take = min(wl - w_off, gl - g_off)
            verdict = _classify(gop, _shift_terms(gt, g_off),
                                wop, _shift_terms(wt, w_off))
            if verdict is not None:
                code, detail = verdict
                if not (code == "ACCL501" and "stale" in detail
                        and have_stale):
                    diags.append(make(
                        code,
                        f"{scenario_name}: rank {r} output elements "
                        f"[{pos}, {pos + take}): {detail}", rank=r))
            pos += take
            w_off += take
            g_off += take
            if w_off == wl:
                wi += 1
                w_off = 0
            if g_off == gl:
                gi += 1
                g_off = 0
    return diags[:_MAX_DIAGS]


# ---------------------------------------------------------------------------
# Cached entry points (the lint-tier surface)
# ---------------------------------------------------------------------------

# key -> (arith_table ref, verdict tuple); the table reference pins the
# id() component of the key against reuse after GC
_CERT_CACHE: dict[tuple, tuple[Any, tuple[Diagnostic, ...]]] = {}
_CERT_CACHE_CAP = 4096

# In-band budget: the abstract evaluation is linear in hop count, but a
# heavily segmented schedule (hundreds of eager segments x world ranks)
# can cost whole seconds to lift — too slow for the opt-out lint stage
# in front of every first-time compile. Batches past these bounds skip
# the in-band certification (the step still gets every other pass); the
# CLI conformance sweep (`accl_lint.py --semantic --schedules`) runs
# strict with no budget, so the same shape classes stay covered in CI.
_INBAND_MAX_SEGMENTS = 64
_INBAND_MAX_ELEMS = 1 << 19


def _within_inband_budget(options: Any, plan: Any, world: int) -> bool:
    # only the allreduce ring actually segments its own body
    # (schedules.segmented_apply); other plans' num_segments describe
    # the transport, not the traced program size
    if (options.scenario == Operation.allreduce
            and int(getattr(plan, "num_segments", 1)) > _INBAND_MAX_SEGMENTS):
        return False
    return int(options.count) * world <= _INBAND_MAX_ELEMS


def clear_cache() -> None:
    from ..ops import compression as _comp

    _CERT_CACHE.clear()
    _comp._SEM_JITS.clear()


def certify_call(options: Any, plan: Any, world: int,
                 axis_name: str = "ccl",
                 arith_table: dict | None = None) -> list[Diagnostic]:
    """Certify ONE call: lift its schedule body and check the final
    contribution sets against `collective_spec`. Verdicts are cached by
    the call's static signature (the same key class the compile cache
    uses), so re-linting a recorded shape costs a dict hit."""
    spec = collective_spec(options, world)
    if spec is None or world < 2:
        return []
    # custom tables key by identity; the table object rides the cache
    # value so its id can never be reused for a different table
    key = (options.signature(), plan, world, axis_name,
           0 if arith_table is None else id(arith_table))
    cached = _CERT_CACHE.get(key)
    if cached is not None:
        return list(cached[1])
    dag = lift_call(options, plan, world, axis_name,
                    arith_table=arith_table)
    diags = certify(dag, spec, options.scenario.name)
    if len(_CERT_CACHE) >= _CERT_CACHE_CAP:
        _CERT_CACHE.clear()
    _CERT_CACHE[key] = (arith_table, tuple(diags))
    return diags


def check_batch_semantics(steps: Sequence[Any], plans: Sequence[Any],
                          world: int, axis_name: str = "ccl",
                          arith_table: dict | None = None,
                          strict: bool = False) -> list[Diagnostic]:
    """The batch-level pass the linter's DEFAULT tier runs: certify
    each step's schedule against its declared collective. Per-batch
    linear — one abstract evaluation per step, no interleaving
    exploration. A step the lifter cannot analyze is SKIPPED unless
    `strict` (the CLI conformance gate), which re-raises
    UnsupportedSchedule: the certifier never converts inability into a
    wrong-result claim."""
    diags: list[Diagnostic] = []
    for k, (opts, plan) in enumerate(zip(steps, plans)):
        if not strict and not _within_inband_budget(opts, plan, world):
            continue
        try:
            step_diags = certify_call(opts, plan, world, axis_name,
                                      arith_table=arith_table)
        except UnsupportedSchedule:
            if strict:
                raise
            continue
        except Exception as e:  # analysis must never break dispatch
            if strict:
                raise UnsupportedSchedule(
                    f"step {k} ({opts.scenario.name}): lifter error "
                    f"{e!r}") from e
            continue
        for d in step_diags:
            diags.append(Diagnostic(d.code, d.message, step=k,
                                    rank=d.rank))
    return diags
