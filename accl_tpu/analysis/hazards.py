"""Dataflow hazard analysis over a recorded descriptor batch.

Tracks reads and writes through the SAME canonical address renaming the
composite signature uses (descriptor.SequenceDescriptor.signature /
sequencer.sequence.SequencePlan): buffer addresses become indices in
first-appearance order, and every access is a PREFIX region of its
buffer — step results always land at offset 0 with a static width
(step_out_elems), operands are read as `[..., :in_elems]` slices
(step_in_elems). Prefix geometry makes overlap exact, not approximate.

The ordering model is the device-resident contract: steps in a batch are
ordered ONLY by true data dependencies (a step consuming a buffer some
earlier step produced) plus the builder's explicit ring-ordering edges
(sequence.py chains pallas-ring steps via optimization_barrier). Any
aliasing between steps NOT so ordered is a hazard:

  ACCL101 raw-hazard  a read wider than what its producer wrote — the
                      consumer sees a fresh prefix spliced onto stale
                      pre-sequence bytes. Sequentially well-defined, but
                      virtually always a mis-recorded count, and the
                      class of silent corruption ACCL+ (arxiv 2312.11742)
                      reports as the hardest to debug post-dispatch.
  ACCL102 war-hazard  a later step overwrites a buffer an earlier
                      UNORDERED step reads: an executor free to overlap
                      steps (the descriptor-FIFO posture) can clobber
                      the operand mid-read.
  ACCL103 waw-hazard  two unordered steps write one buffer: final
                      contents depend on completion order.
  ACCL401             a step reads a buffer as a different dtype than
                      its in-sequence producer wrote (the fused program
                      casts silently — the eager path would have the
                      host mirror to compare against; dispatched, there
                      is no symptom at all).
  ACCL405             a registered buffer is narrower than the widest
                      access the batch makes to it (the static form of
                      TPUDevice.start_sequence's min_widths check).
  ACCL406             a step requests a compressed wire with no
                      arithmetic-configuration lane for its payload
                      dtype (e.g. blockwise int8 on an int32 operand) —
                      dispatched device-resident, the lane lookup would
                      fail after the host already returned.

The dtype-flow rules know the compression lanes: a compressed step's
in-sequence RESULT is always back in the payload dtype (cast lanes
decompress on arrival, the quantized lanes dequantize), so wire
compression never changes what a downstream step reads — ACCL401 keys
on the descriptor's data_type on both sides, and ACCL406 separately
proves the requested lane pairing exists.
"""

from __future__ import annotations

import dataclasses

from ..arithconfig import DEFAULT_ARITH_CONFIG
from ..constants import CompressionFlags, DataType, Operation
from ..sequencer.sequence import step_in_elems, step_out_elems
from .diagnostics import Diagnostic, make


@dataclasses.dataclass(frozen=True)
class _Access:
    step: int
    buf: int  # canonical buffer index
    elems: int  # prefix width
    dtype: DataType


def _accesses(steps, world):
    """Resolve each step's read/write prefix accesses under canonical
    renaming. Returns (reads, writes, rename): per-step access lists
    plus the address -> canonical-index map itself (callers translate
    address-keyed annotations like `persistent_addrs` through it)."""
    rename: dict[int, int] = {}

    def idx(addr: int) -> int:
        return rename.setdefault(addr, len(rename))

    reads: list[list[_Access]] = []
    writes: list[_Access | None] = []
    for k, opts in enumerate(steps):
        r: list[_Access] = []
        in_n = step_in_elems(opts, world)
        if opts.addr_0:
            r.append(_Access(k, idx(opts.addr_0), in_n, opts.data_type))
        if opts.scenario == Operation.combine and opts.addr_1:
            r.append(_Access(k, idx(opts.addr_1), in_n, opts.data_type))
        reads.append(r)
        if opts.addr_2:
            writes.append(_Access(k, idx(opts.addr_2),
                                  step_out_elems(opts, world),
                                  opts.data_type))
        else:
            writes.append(None)
    return reads, writes, rename


def _reachability(n: int, edges: set[tuple[int, int]]) -> list[set[int]]:
    """reach[i] = every step ordered after step i (transitive closure).
    Steps per batch are few (tens), so the quadratic closure is fine."""
    succ: list[set[int]] = [set() for _ in range(n)]
    for a, b in edges:
        succ[a].add(b)
    reach: list[set[int]] = [set() for _ in range(n)]
    # process in reverse step order: edges always point forward in the
    # batch (a dependency's producer precedes its consumer)
    for i in range(n - 1, -1, -1):
        for j in succ[i]:
            reach[i].add(j)
            reach[i] |= reach[j]
    return reach


def analyze_dataflow(
    steps,
    world: int,
    *,
    ring_steps: frozenset[int] | set[int] = frozenset(),
    buffer_widths: dict[int, int] | None = None,
    arith_table: dict | None = None,
    persistent_addrs: frozenset[int] | set[int] = frozenset(),
) -> list[Diagnostic]:
    """Run the RAW/WAR/WAW + dtype-flow hazard pass over a batch of
    CallOptions. `ring_steps` are indices the sequence builder chains
    with explicit ordering edges (pallas-ring steps); `buffer_widths`
    maps buffer ADDRESS -> registered element width for the static
    underflow check (omit when widths are unknown, e.g. corpus replay
    of a bare descriptor stream); `arith_table` is the ACTIVE arithmetic
    configuration the batch will lower under (an ACCL built with a
    custom table lints against ITS lanes, not the defaults — omit for
    bare-descriptor replay, where the default table is the lane set).

    `persistent_addrs` declares DEVICE-RESIDENT STATE buffers (by
    address): buffers whose tail bytes are carried from one dispatch of
    the program to the next by contract (a KV cache, an optimizer
    state). For those buffers a read wider than its in-sequence
    producer's write is the declared steady-state pattern — the stale
    tail is last dispatch's result, not a mis-recorded count — so
    ACCL101 is waived for them. Nothing else is: WAR/WAW ordering,
    dtype flow, and the static width check still apply in full, so the
    annotation cannot hide a clobber, only a deliberate partial-width
    refresh."""
    diags: list[Diagnostic] = []
    reads, writes, rename = _accesses(steps, world)
    persistent = {rename[a] for a in persistent_addrs if a in rename}
    n = len(list(steps))
    table = arith_table if arith_table is not None else DEFAULT_ARITH_CONFIG

    # pass 0: compression-lane pairing — a wire dtype only exists where
    # an arithmetic-configuration row maps (payload, wire) to lanes; the
    # quantized lanes in particular pair ONLY with fp32 payloads
    for k, opts in enumerate(steps):
        wire = opts.compress_dtype
        if (wire == DataType.none
                or not opts.compression_flags
                & CompressionFlags.ETH_COMPRESSED):
            continue
        if (opts.data_type, wire) not in table:
            kind = ("blockwise-quantized" if wire == DataType.int8
                    else "compressed")
            diags.append(make(
                "ACCL406",
                f"step {k} ({opts.scenario.name}) requests a {kind} "
                f"{wire.name} wire for a {opts.data_type.name} payload, "
                "but no arithmetic-configuration lane implements that "
                "pairing", step=k))

    # pass 1: true-dependency edges + RAW coverage / dtype-flow checks
    edges: set[tuple[int, int]] = set()
    last_write: dict[int, _Access] = {}  # canonical buf -> latest write
    widest_write: dict[int, _Access] = {}
    prev_ring: int | None = None
    for k in range(n):
        for acc in reads[k]:
            w = last_write.get(acc.buf)
            if w is None:
                continue  # reads pre-sequence contents: external input
            edges.add((w.step, k))
            if acc.elems > w.elems and acc.buf not in persistent:
                wider = widest_write.get(acc.buf)
                stale = ("bytes never written in this sequence"
                         if wider is None or wider.elems <= w.elems
                         else f"step {wider.step}'s older result")
                diags.append(make(
                    "ACCL101",
                    f"step {k} ({steps[k].scenario.name}) reads "
                    f"{acc.elems} elements of buffer #{acc.buf} but its "
                    f"producer step {w.step} "
                    f"({steps[w.step].scenario.name}) wrote only "
                    f"{w.elems}; the tail is {stale}",
                    step=k))
            if (acc.dtype != w.dtype
                    and DataType.none not in (acc.dtype, w.dtype)):
                diags.append(make(
                    "ACCL401",
                    f"step {k} reads buffer #{acc.buf} as "
                    f"{acc.dtype.name} but step {w.step} wrote it as "
                    f"{w.dtype.name}; the fused program would cast "
                    "silently",
                    step=k))
        w = writes[k]
        if w is not None:  # pass 2 re-derives WAW against the full order
            last_write[w.buf] = w
            ww = widest_write.get(w.buf)
            if ww is None or w.elems > ww.elems:
                widest_write[w.buf] = w
        if k in ring_steps:
            if prev_ring is not None:
                edges.add((prev_ring, k))  # builder's _ordered_after edge
            prev_ring = k

    reach = _reachability(n, edges)

    def ordered(a: int, b: int) -> bool:
        return b in reach[a]

    # pass 2: WAR / WAW between unordered aliased steps
    writers: dict[int, list[_Access]] = {}
    readers: dict[int, list[_Access]] = {}
    for k in range(n):
        w = writes[k]
        if w is not None:
            for r in readers.get(w.buf, ()):
                if r.step != k and not ordered(r.step, k):
                    diags.append(make(
                        "ACCL102",
                        f"step {k} ({steps[k].scenario.name}) overwrites "
                        f"buffer #{w.buf} while unordered step {r.step} "
                        f"({steps[r.step].scenario.name}) reads it; an "
                        "executor overlapping independent steps can "
                        "clobber the operand mid-read",
                        step=k))
            prev = writers.get(w.buf, ())
            if prev:
                lw = prev[-1]
                if not ordered(lw.step, k):
                    diags.append(make(
                        "ACCL103",
                        f"steps {lw.step} and {k} both write buffer "
                        f"#{w.buf} with no ordering between them; final "
                        "contents depend on completion order (and step "
                        f"{lw.step}'s result is never read)",
                        step=k))
            writers.setdefault(w.buf, []).append(w)
        for r in reads[k]:
            readers.setdefault(r.buf, []).append(r)

    # pass 3: static buffer-width underflow (when widths are known)
    if buffer_widths is not None:
        rename: dict[int, int] = {}
        addr_of: dict[int, int] = {}
        for opts in steps:
            for a in (opts.addr_0, opts.addr_1, opts.addr_2):
                if a and a not in rename:
                    addr_of[len(rename)] = a
                    rename[a] = len(rename)
        need: dict[int, int] = {}
        for k in range(n):
            accs = list(reads[k])
            w = writes[k]
            if w is not None:
                accs.append(w)
            for acc in accs:
                need[acc.buf] = max(need.get(acc.buf, 0), acc.elems)
        for buf, elems in sorted(need.items()):
            addr = addr_of[buf]
            have = buffer_widths.get(addr)
            if have is not None and have < elems:
                diags.append(make(
                    "ACCL405",
                    f"buffer {addr:#x} holds {have} elements but the "
                    f"batch accesses {elems}",
                ))
    return diags
