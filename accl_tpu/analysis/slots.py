"""Overlap-slot analysis: collective_id liveness over a descriptor batch.

The segmented pallas ring (ops/ring_allreduce.py) owns NUM_RING_SLOTS
independent semaphore/comm-buffer sets, keyed by collective_id. The
lowering double-buffers segments across those slots (segmented_apply
overlap_slots) and orders only slot REUSE: segment i depends on segment
i-k. Two kernel instances that share a collective_id while both live
would cross-talk on the shared semaphores — the exact silent-corruption
failure the slot keying exists to prevent, and invisible post-dispatch.

This pass rebuilds the slot timeline a batch will execute — every ring
instance each step launches, its slot assignment, and the ordering
edges the builder inserts (intra-step slot-reuse chains, plus
sequence.py's cross-step _ordered_after chaining of consecutive ring
steps) — and then checks the invariant from scratch:

  ACCL301 slot-collision   two instances share a slot with no ordering
                           path between them
  ACCL302 slot-overcommit  the overlap window claims more concurrent
                           instances than the kernel has slot resources
                           (or a slot id outside the kernel's range)

On the shipping lowering these cannot fire by construction; the pass is
the regression gate that keeps that true as the lowering evolves, and
the corpus exercises both codes through hand-built timelines.
"""

from __future__ import annotations

import dataclasses

from ..constants import Operation, dtype_nbytes
from ..sequencer.sequence import step_in_elems
from .diagnostics import Diagnostic, make

__all__ = [
    "SlotInstance",
    "SlotTimeline",
    "check_slots",
    "ring_slot_timeline",
]

# instances beyond this are a periodic continuation of the same slot
# pattern; analyzing one full period past the cap adds no information
MAX_INSTANCES = 256


@dataclasses.dataclass(frozen=True)
class SlotInstance:
    """One kernel launch: (step, segment) holding slot `slot`."""

    step: int
    segment: int
    slot: int


@dataclasses.dataclass
class SlotTimeline:
    """A batch's kernel launches in issue order plus the ordering edges
    (indices into `instances`) the program graph enforces."""

    num_slots: int
    instances: list[SlotInstance]
    deps: set[tuple[int, int]]
    truncated: bool = False


def ring_slot_timeline(
    steps,
    world: int,
    *,
    overlap: bool = True,
    num_slots: int | None = None,
    max_seg_bytes: int | None = None,
) -> SlotTimeline:
    """Mirror the lowering's slot assignment for a descriptor batch:
    allreduce steps chunk into PALLAS_RING_MAX_BYTES segments; overlap
    mode rotates segments through the kernel's slots with slot-reuse
    ordering (segmented_apply overlap_slots), serialize mode chains
    every segment through slot 0; consecutive ring steps are ordered
    end-to-start (sequence.py's prev_ring chaining)."""
    from ..ops.ring_allreduce import NUM_RING_SLOTS
    from ..sequencer.lowering import ScheduleCompiler

    if num_slots is None:
        num_slots = NUM_RING_SLOTS
    if max_seg_bytes is None:
        max_seg_bytes = ScheduleCompiler.PALLAS_RING_MAX_BYTES

    instances: list[SlotInstance] = []
    deps: set[tuple[int, int]] = set()
    truncated = False
    prev_step_range: tuple[int, int] | None = None  # instance idx span
    for k, opts in enumerate(steps):
        if opts.scenario != Operation.allreduce:
            continue
        elem_bytes = max(dtype_nbytes(opts.data_type), 1)
        seg_elems = max(max_seg_bytes // elem_bytes, 1)
        count = step_in_elems(opts, world)
        nseg = max(-(-count // seg_elems), 1)
        if len(instances) + nseg > MAX_INSTANCES:
            nseg = max(MAX_INSTANCES - len(instances), 1)
            truncated = True
        base = len(instances)
        for i in range(nseg):
            slot = (i % num_slots) if overlap and num_slots > 0 else 0
            instances.append(SlotInstance(k, i, slot))
            if overlap and num_slots > 0:
                if i >= num_slots:
                    deps.add((base + i - num_slots, base + i))
            elif i > 0:
                deps.add((base + i - 1, base + i))  # serialized chain
        if prev_step_range is not None:
            # _ordered_after(ins[0], prev_ring): the whole next ring
            # step starts after the previous ring step's output
            for a in range(*prev_step_range):
                for b in range(base, len(instances)):
                    deps.add((a, b))
        prev_step_range = (base, len(instances))
    return SlotTimeline(num_slots, instances, deps, truncated)


def check_slots(timeline: SlotTimeline) -> list[Diagnostic]:
    """Verify no two unordered instances share a collective_id slot and
    every slot id fits the kernel's resources."""
    diags: list[Diagnostic] = []
    n = len(timeline.instances)
    if timeline.num_slots < 1:
        diags.append(make("ACCL302",
                          f"kernel exposes {timeline.num_slots} slots"))
        return diags
    for i, inst in enumerate(timeline.instances):
        if not 0 <= inst.slot < timeline.num_slots:
            diags.append(make(
                "ACCL302",
                f"instance (step {inst.step}, segment {inst.segment}) "
                f"claims slot {inst.slot} of a {timeline.num_slots}-slot "
                "kernel", step=inst.step))
    if any(d.code == "ACCL302" for d in diags):
        return diags

    # transitive closure over ordering edges (instance count is capped)
    succ: list[set[int]] = [set() for _ in range(n)]
    for a, b in timeline.deps:
        if 0 <= a < n and 0 <= b < n:
            succ[a].add(b)
    reach: list[set[int]] = [set() for _ in range(n)]
    order = _topo_order(n, succ)
    if order is None:
        # an ordering cycle means the timeline itself is malformed;
        # report instead of looping
        diags.append(make("ACCL301",
                          "ordering edges form a cycle: timeline invalid"))
        return diags
    for i in reversed(order):
        for j in succ[i]:
            reach[i].add(j)
            reach[i] |= reach[j]

    by_slot: dict[int, list[int]] = {}
    for i, inst in enumerate(timeline.instances):
        by_slot.setdefault(inst.slot, []).append(i)
    for slot, idxs in sorted(by_slot.items()):
        for x in range(len(idxs)):
            for y in range(x + 1, len(idxs)):
                a, b = idxs[x], idxs[y]
                if b not in reach[a] and a not in reach[b]:
                    ia, ib = timeline.instances[a], timeline.instances[b]
                    diags.append(make(
                        "ACCL301",
                        f"(step {ia.step}, segment {ia.segment}) and "
                        f"(step {ib.step}, segment {ib.segment}) both "
                        f"hold collective_id slot {slot} with no "
                        "ordering between them: concurrent instances "
                        "would cross-talk on the slot's semaphores",
                        step=ib.step))
    return diags


def _topo_order(n: int, succ) -> list[int] | None:
    indeg = [0] * n
    for i in range(n):
        for j in succ[i]:
            indeg[j] += 1
    queue = [i for i in range(n) if indeg[i] == 0]
    order: list[int] = []
    while queue:
        i = queue.pop()
        order.append(i)
        for j in succ[i]:
            indeg[j] -= 1
            if indeg[j] == 0:
                queue.append(j)
    return order if len(order) == n else None
