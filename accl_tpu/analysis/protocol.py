"""Protocol analysis: abstract interpretation of per-rank communication.

Two entry points share one matching engine:

* `trace_schedule_hops` abstractly interprets a schedule body — the REAL
  lowering-built callable over `sequencer/schedules.py`, not a parallel
  model of it — under jax's abstract evaluation (`make_jaxpr` with an
  axis environment: no mesh, no devices, no XLA compile). Every
  cross-rank hop in the traced program surfaces as a `ppermute`
  equation whose `perm` pairs ARE the per-rank send/recv pattern, so
  the analysis can never drift from what the compiler actually emits.

* `rank_programs_from_options` models the per-rank eager path (the
  native executor's world: each rank issues its own descriptor chain):
  send/recv descriptors become blocking endpoint events, every other
  collective becomes a synchronizing group event.

`simulate` then runs the classic rendezvous matching game: each rank
executes its event list in order; a send blocks until its recv is
posted and vice versa; collectives block until every rank arrives at
the same one. This is the conservative model — eager-protocol sends can
buffer and complete early, so a batch clean under rendezvous semantics
is clean under both (the firmware's eager path is the optimization, not
the contract). Stuck states decompose into ACCL202 deadlock-cycle
(circular wait), ACCL203 tag-mismatch, ACCL403 comm-mismatch, and
ACCL201 unmatched-sendrecv (waiting on a rank that already finished, or
events left over at exit).
"""

from __future__ import annotations

import dataclasses

from ..constants import Operation, TAG_ANY
from .diagnostics import Diagnostic, make

__all__ = [
    "ANY_SRC",
    "Event",
    "MatchNote",
    "send",
    "recv",
    "coll",
    "simulate",
    "rank_programs_from_options",
    "trace_schedule_jaxpr",
    "trace_schedule_hops",
    "rank_programs_from_hops",
    "batch_programs_from_hops",
    "batch_rank_programs",
    "check_hops",
    "interpret_schedule",
]

# Wildcard source for recv events: matches a send from ANY rank (the
# native executor's recvs are source-exact, but descriptor chains built
# for single-controller or RDMA executors can be any-source; the model
# checker explores every eligible sender).
ANY_SRC = -2


@dataclasses.dataclass(frozen=True)
class Event:
    """One blocking step of a rank's program."""

    kind: str  # "send" | "recv" | "coll"
    peer: int = -1  # partner rank for send/recv
    tag: int = TAG_ANY
    count: int = 0
    comm: int = 0
    op: str = ""  # collective name for kind == "coll"


def send(peer: int, tag: int = TAG_ANY, count: int = 0,
         comm: int = 0) -> Event:
    return Event("send", peer, tag, count, comm)


def recv(peer: int, tag: int = TAG_ANY, count: int = 0,
         comm: int = 0) -> Event:
    return Event("recv", peer, tag, count, comm)


def coll(op: str, count: int = 0, comm: int = 0) -> Event:
    return Event("coll", -1, TAG_ANY, count, comm, op)


def _tags_match(a: int, b: int) -> bool:
    return a == b or TAG_ANY in (a, b)


def _src_matches(sender: int, ev: Event) -> bool:
    """A recv's source constraint: exact peer, or the ANY_SRC wildcard."""
    return ev.peer == ANY_SRC or sender == ev.peer


@dataclasses.dataclass(frozen=True)
class MatchNote:
    """One ambiguous match observed during the canonical `simulate` run:
    a recv for which MULTIPLE posted sends (or sender heads) were
    eligible. The canonical run commits to the first-posted candidate;
    the note records that the real executor had a choice — the cheap
    single-run precursor that routes a batch into the deep
    interleaving checker (modelcheck.py)."""

    rank: int  # receiving rank
    pc: int  # recv's index in its rank's program
    candidates: tuple[str, ...]  # human-readable eligible sends


def simulate(programs: list[list[Event]],
             *, blocking_sends: bool = True,
             notes: list[MatchNote] | None = None,
             outcome: list[bool] | None = None) -> list[Diagnostic]:
    """Run the blocking-match game over per-rank event lists and report
    every protocol defect found.

    `blocking_sends=True` is the rendezvous model (a send blocks until
    its recv is posted) — the conservative contract for per-rank
    descriptor chains. `blocking_sends=False` buffers sends (a send
    completes immediately, recvs drain the buffer in arrival order) —
    the semantics of hop-derived programs, where every ppermute hop's
    sends are posted collectively before any recv completes.

    This explores exactly ONE interleaving — the canonical schedule:
    ranks advance in index order, the posted buffer drains FIFO, and a
    TAG_ANY recv takes the FIRST-POSTED eligible send. `notes`, when a
    list is passed, collects a `MatchNote` per recv that had more than
    one eligible candidate: the signal that other interleavings exist
    and the batch needs the deep checker. `outcome`, when a list is
    passed, receives one bool: did the canonical run CONSUME everything
    (no stuck rank, no leftover posted send)? This is the structural
    completion signal the deep tier's ACCL206 gate keys on — never
    inferred from diagnostic text.

    Termination: each iteration of the outer loop advances at least one
    program counter or exits."""
    diags: list[Diagnostic] = []
    world = len(programs)
    pc = [0] * world
    posted: list[tuple[int, Event]] = []  # buffered (sender, send) FIFO
    noted: set[tuple[int, int]] = set()  # (rank, pc) already noted

    def head(r: int) -> Event | None:
        return programs[r][pc[r]] if pc[r] < len(programs[r]) else None

    def bad_peer(r: int, ev: Event) -> bool:
        if 0 <= ev.peer < world or (ev.kind == "recv"
                                    and ev.peer == ANY_SRC):
            return False
        diags.append(make(
            "ACCL402",
            f"{ev.kind} addresses rank {ev.peer} outside world {world}",
            rank=r))
        pc[r] += 1
        return True

    def note(r: int, cands: list[str]) -> None:
        if notes is not None and len(cands) > 1 and (r, pc[r]) not in noted:
            noted.add((r, pc[r]))
            notes.append(MatchNote(r, pc[r], tuple(cands)))

    while True:
        progressed = False
        if not blocking_sends:
            # sends complete immediately into the posted buffer
            for r in range(world):
                while (ev := head(r)) is not None and ev.kind == "send":
                    if not bad_peer(r, ev):
                        posted.append((r, ev))
                        pc[r] += 1
                    progressed = True
            # recvs drain the buffer in arrival order (first-posted
            # eligible send wins — the FIFO contract the native
            # executor's seqn-ordered links implement)
            for r in range(world):
                ev = head(r)
                if ev is None or ev.kind != "recv" or bad_peer(r, ev):
                    continue
                eligible = [
                    i for i, (s, sev) in enumerate(posted)
                    if (_src_matches(s, ev) and sev.peer == r
                        and sev.comm == ev.comm
                        and _tags_match(sev.tag, ev.tag))]
                note(r, [f"r{posted[i][0]}:send(tag {posted[i][1].tag})"
                         for i in eligible])
                if eligible:
                    i = eligible[0]
                    s, sev = posted[i]
                    if sev.count != ev.count:
                        diags.append(make(
                            "ACCL201",
                            f"rank {s} sends {sev.count} elements "
                            f"to rank {r}, which posted a recv for "
                            f"{ev.count}", rank=r))
                    posted.pop(i)
                    pc[r] += 1
                    progressed = True
        else:
            # point-to-point rendezvous: a send whose partner's CURRENT
            # event is the matching recv completes both. An ANY_SRC recv
            # head with several sender heads targeting it is ambiguous —
            # note it, then commit to the lowest-ranked sender (the
            # canonical order).
            for d in range(world):
                rv = head(d)
                if rv is None or rv.kind != "recv" or rv.peer != ANY_SRC:
                    continue
                cands = [
                    s for s in range(world)
                    if (sv := head(s)) is not None and sv.kind == "send"
                    and sv.peer == d and sv.comm == rv.comm
                    and _tags_match(sv.tag, rv.tag)]
                note(d, [f"r{s}:send(tag {head(s).tag})"  # type: ignore[union-attr]
                         for s in cands])
            for r in range(world):
                ev = head(r)
                if ev is None or ev.kind != "send" or bad_peer(r, ev):
                    continue
                pev = head(ev.peer)
                if (pev is not None and pev.kind == "recv"
                        and _src_matches(r, pev) and pev.comm == ev.comm
                        and _tags_match(ev.tag, pev.tag)):
                    if ev.count != pev.count:
                        diags.append(make(
                            "ACCL201",
                            f"rank {r} sends {ev.count} elements to rank "
                            f"{ev.peer}, which posted a recv for "
                            f"{pev.count}", rank=r))
                    pc[r] += 1
                    pc[ev.peer] += 1
                    progressed = True
        if progressed:
            continue
        # collective barrier: every unfinished rank parked on the same
        # group event releases together
        waiting = [(r, ev) for r in range(world)
                   if (ev := head(r)) is not None]
        if waiting and all(ev.kind == "coll" for _, ev in waiting):
            sigs = {(ev.op, ev.count, ev.comm) for _, ev in waiting}
            if len(sigs) == 1 and len(waiting) == world:
                for r, _ in waiting:
                    pc[r] += 1
                continue
        break

    if outcome is not None:
        outcome.append(not posted and all(
            pc[r] >= len(programs[r]) for r in range(world)))

    # stuck-state decomposition
    for s, sev in posted:
        diags.append(make(
            "ACCL201",
            f"rank {s}'s send to rank {sev.peer} (tag {sev.tag}) is "
            "never received", rank=s))
    stuck = [r for r in range(world) if head(r) is not None]
    if not stuck:
        return diags
    blames: set[int] = set()

    def cur(r: int) -> Event:
        ev = head(r)
        assert ev is not None  # r is in stuck
        return ev

    def waits_on(r: int) -> list[int]:
        ev = cur(r)
        if ev.kind == "coll" or (ev.kind == "recv" and ev.peer == ANY_SRC):
            return [p for p in range(world) if p != r and p in stuck]
        return [ev.peer] if 0 <= ev.peer < len(programs) else []

    # precise pairwise mismatches first: both ranks parked on each
    # other with incompatible tag/comm
    for r in stuck:
        ev = cur(r)
        if ev.kind != "send" or ev.peer not in stuck:
            continue
        pev = cur(ev.peer)
        if pev.kind == "recv" and _src_matches(r, pev):
            if ev.comm != pev.comm:
                diags.append(make(
                    "ACCL403",
                    f"rank {r} sends on communicator {ev.comm:#x} but "
                    f"rank {ev.peer}'s recv addresses {pev.comm:#x}",
                    rank=r))
                blames.update((r, ev.peer))
            elif not _tags_match(ev.tag, pev.tag):
                diags.append(make(
                    "ACCL203",
                    f"rank {r} sends tag {ev.tag} to rank {ev.peer}, "
                    f"whose recv expects tag {pev.tag}: the pair can "
                    "never match", rank=r))
                blames.update((r, ev.peer))

    # circular waits: DFS over the wait-for graph
    cycle = _find_cycle(stuck, waits_on)
    if cycle and not blames.intersection(cycle):
        names = " -> ".join(
            f"r{r}:{cur(r).kind}"
            + (f"(peer {cur(r).peer})" if cur(r).kind != "coll"
               else f"({cur(r).op})")
            for r in cycle)
        diags.append(make(
            "ACCL202",
            f"circular wait among ranks {cycle}: {names} -> r{cycle[0]}",
            rank=cycle[0]))
        blames.update(cycle)

    # everything else stuck: waiting on a rank that finished, or a
    # never-posted partner event
    for r in stuck:
        if r in blames:
            continue
        ev = cur(r)
        leftover = len(programs[r]) - pc[r]
        diags.append(make(
            "ACCL201",
            f"rank {r} blocks forever on {ev.kind}"
            + (f" to/from rank {ev.peer}" if ev.kind != "coll"
               else f" {ev.op}")
            + f" tag {ev.tag} ({leftover} event(s) unconsumed)",
            rank=r))
    return diags


def _find_cycle(stuck, waits_on) -> list[int] | None:
    state = {r: 0 for r in stuck}  # 0 unvisited, 1 on stack, 2 done
    parent: dict[int, int] = {}
    for start in stuck:
        if state[start]:
            continue
        stack = [start]
        while stack:
            r = stack[-1]
            if state[r] == 0:
                state[r] = 1
            advanced = False
            for p in waits_on(r):
                if p not in state:
                    continue  # waiting on a finished rank: not a cycle
                if state[p] == 1:
                    cyc = [p]
                    q = r
                    while q != p:
                        cyc.append(q)
                        q = parent[q]
                    cyc.reverse()
                    return cyc
                if state[p] == 0:
                    parent[p] = r
                    stack.append(p)
                    advanced = True
                    break
            if not advanced:
                state[r] = 2
                stack.pop()
    return None


# ---------------------------------------------------------------------------
# Per-rank descriptor chains (the native executor's world)
# ---------------------------------------------------------------------------


def rank_programs_from_options(per_rank) -> list[list[Event]]:
    """Model per-rank CallOptions chains as blocking event programs:
    send/recv descriptors become endpoint events (peer from the
    root_src_dst src|dst<<16 packing), data-plane collectives become
    group events, local ops (copy/combine/config/nop) are elided."""
    local = (Operation.copy, Operation.combine, Operation.config,
             Operation.nop)
    programs: list[list[Event]] = []
    for me, chain in enumerate(per_rank):
        events: list[Event] = []
        for opts in chain:
            scen = opts.scenario
            if scen in local:
                continue
            src = opts.root_src_dst & 0xFFFF
            dst = (opts.root_src_dst >> 16) & 0xFFFF
            if scen == Operation.send:
                events.append(send(dst, opts.tag, opts.count,
                                   opts.comm_addr))
            elif scen == Operation.recv:
                events.append(recv(src, opts.tag, opts.count,
                                   opts.comm_addr))
            else:
                events.append(coll(scen.name, opts.count, opts.comm_addr))
        programs.append(events)
    return programs


# ---------------------------------------------------------------------------
# Schedule interpretation (the fused SPMD path)
# ---------------------------------------------------------------------------


def trace_schedule_jaxpr(options, plan, world: int,
                         axis_name: str = "ccl", *,
                         arith_table: dict | None = None,
                         semantic_marks: bool = False):
    """Abstractly evaluate ONE call's schedule body — the REAL
    lowering-built callable — under jax's axis-env tracing and return
    `(closed_jaxpr, n_in, in_elems)`. THE tracing seam every jaxpr-level
    pass shares: the protocol pass reads ppermute perms from it and the
    semantic certifier lifts its hop DAG from it, so there is exactly
    one model of what the compiler emits. `semantic_marks=True`
    activates the compression lanes' named trace boundaries
    (ops.compression.semantic_boundaries) so the quantized transforms
    surface as single named equations instead of raw blockwise math."""
    import contextlib

    import jax
    import numpy as np

    from ..constants import DataType, to_numpy_dtype
    from ..ops.compression import semantic_boundaries
    from ..sequencer.lowering import analysis_body
    from ..sequencer.sequence import step_in_elems

    body, n_in = analysis_body(options, plan, world, axis_name,
                               arith_table=arith_table)
    if options.scenario == Operation.barrier:
        avals = [jax.ShapeDtypeStruct((1,), np.float32)]
    else:
        elems = step_in_elems(options, world)
        dtype = (to_numpy_dtype(options.data_type)
                 if options.data_type != DataType.none else np.float32)
        avals = [jax.ShapeDtypeStruct((elems,), dtype)] * n_in
    marks = semantic_boundaries() if semantic_marks \
        else contextlib.nullcontext()
    with marks:
        closed = jax.make_jaxpr(body, axis_env=[(axis_name, world)])(*avals)
    return closed, n_in, avals[0].shape[-1]


def trace_schedule_hops(options, plan, world: int,
                        axis_name: str = "ccl") -> list[tuple]:
    """Abstractly interpret ONE call's schedule body and return its
    cross-rank hops in program order: each hop is the ppermute perm
    tuple ((src, dst), ...). Pallas lowering is forced off — the lax
    schedule family expresses the same wire pattern through ppermute,
    which is the surface this pass reads. Hops inside a lax.map/scan
    body appear once (every iteration repeats the same pattern)."""
    closed, _, _ = trace_schedule_jaxpr(options, plan, world, axis_name)
    hops: list[tuple] = []
    _collect_ppermutes(closed.jaxpr, hops)
    return hops


def iter_ppermute_eqns(jaxpr):
    """Yield every ppermute equation of a (closed) jaxpr, depth-first
    through eqn-param sub-jaxprs (pjit bodies, shard_map, scan/cond
    branches), in trace order. THE walker for the 'every cross-rank hop
    is a ppermute' invariant — the protocol pass reads perms from it and
    bench.py's wire-byte audit sums operand bytes over it, so a jax
    version changing eqn param shapes gets fixed in exactly one place."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)  # unwrap ClosedJaxpr
    for eqn in inner.eqns:
        if eqn.primitive.name == "ppermute":
            yield eqn
            continue
        for val in eqn.params.values():
            for sub in _sub_jaxprs(val):
                yield from iter_ppermute_eqns(sub)


def _collect_ppermutes(jaxpr, hops: list) -> None:
    """Perm tuples of every ppermute hop, in trace order."""
    for eqn in iter_ppermute_eqns(jaxpr):
        hops.append(tuple(tuple(p) for p in eqn.params["perm"]))


def _sub_jaxprs(val):
    if hasattr(val, "jaxpr"):  # ClosedJaxpr
        yield val.jaxpr
    elif hasattr(val, "eqns"):  # raw Jaxpr
        yield val
    elif isinstance(val, (tuple, list)):
        for v in val:
            yield from _sub_jaxprs(v)


def check_hops(hops, world: int, step: int | None = None):
    """Validate hop well-formedness: every (src, dst) in range, no rank
    sending or receiving twice within one hop (ACCL204 — the jax
    runtime would reject the perm too, but post-dispatch)."""
    diags: list[Diagnostic] = []
    for h, perm in enumerate(hops):
        srcs: set[int] = set()
        dsts: set[int] = set()
        for s, d in perm:
            if not (0 <= s < world and 0 <= d < world):
                diags.append(make(
                    "ACCL204",
                    f"hop {h}: pair ({s}, {d}) outside world {world}",
                    step=step))
                continue
            if s in srcs:
                diags.append(make(
                    "ACCL204",
                    f"hop {h}: rank {s} sends twice in one permute",
                    step=step))
            if d in dsts:
                diags.append(make(
                    "ACCL204",
                    f"hop {h}: rank {d} receives twice in one permute",
                    step=step))
            srcs.add(s)
            dsts.add(d)
    return diags


def rank_programs_from_hops(hops, world: int,
                            tag_base: int = 0) -> list[list[Event]]:
    """Expand hop perms into per-rank blocking programs: hop h's pair
    (s, d) is a send at s and a recv at d, both on channel
    `tag_base + h` (the hop index as tag), so matching is exact per
    hop. `tag_base` namespaces hops when several calls' programs are
    concatenated into one batch — without it, step k's hop 0 and step
    k+1's hop 0 would alias one channel and fabricate match choices."""
    programs: list[list[Event]] = [[] for _ in range(world)]
    for h, perm in enumerate(hops):
        for s, d in perm:
            if 0 <= s < world and 0 <= d < world:
                programs[s].append(send(d, tag=tag_base + h))
                programs[d].append(recv(s, tag=tag_base + h))
    return programs


# Hop-tag stride between steps of one batch: no shipping schedule moves
# anywhere near 2**12 hops per call, and the namespaced tag stays far
# below TAG_ANY (0xFFFFFFFF).
_STEP_TAG_STRIDE = 1 << 12


def batch_programs_from_hops(hops_per_step, world: int) -> list[list[Event]]:
    """Concatenate per-step hop lists into whole-batch per-rank
    programs, tag-namespaced per step. This is the input the deep
    tier's interleaving checker explores — the cross-step view that
    per-step `interpret_schedule` cannot see. Takes ALREADY-TRACED hops
    so callers that interpreted each step (the linter's deep tier) pay
    for jax abstract tracing once, not twice."""
    programs: list[list[Event]] = [[] for _ in range(world)]
    for k, hops in enumerate(hops_per_step):
        for r, prog in enumerate(
                rank_programs_from_hops(hops, world,
                                        tag_base=k * _STEP_TAG_STRIDE)):
            programs[r].extend(prog)
    return programs


def batch_rank_programs(steps, plans, world: int,
                        axis_name: str = "ccl") -> list[list[Event]]:
    """Per-rank event programs for a WHOLE descriptor batch: each step's
    schedule body is abstractly interpreted (trace_schedule_hops) and
    its hops appended in step order via `batch_programs_from_hops`."""
    return batch_programs_from_hops(
        [trace_schedule_hops(opts, plan, world, axis_name)
         for opts, plan in zip(steps, plans)], world)


def interpret_schedule(options, plan, world: int,
                       axis_name: str = "ccl") -> list[Diagnostic]:
    """The deep protocol pass for one call: trace the schedule body,
    validate its hops, and run the per-rank matching game over them."""
    hops = trace_schedule_hops(options, plan, world, axis_name)
    diags = check_hops(hops, world)
    if not diags:  # malformed perms would confuse the matcher
        diags = simulate(rank_programs_from_hops(hops, world),
                         blocking_sends=False)
    return diags
