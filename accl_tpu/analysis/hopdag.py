"""Hop-DAG IR: the analyzable form of a collective schedule's data flow.

A `HopDag` is a rank-tagged, program-ordered list of nodes describing
every cross-rank move and every arithmetic fold of ONE call's schedule
body, plus the per-rank output composition. It is the shared substrate
the semantic certifier (semantics.py) interprets, the protocol passes
can consume (`rank_programs` lowers the hops to the same Event lists
`simulate`/modelcheck explore), and ROADMAP item 1's synthesis leg can
*generate* — a schedule as data, not a Python body.

Node kinds (each output is a flat run of `length` elements):

  arg      rank r's view of operand slot `arg` (the schedule input)
  send     rank r posts `value` on channel `hop` toward rank `peer`
  recv     rank r receives channel `hop` from rank `peer`; its content
           is the matching send's value (pairing is (hop, peer, rank))
  combine  elementwise reduction `func` of `value` with `value2`
  encode   blockwise quantization of `value`: the node has TWO outputs,
           `data` (int8 codes, `length` elements) and `scales`
           (`scales_len` fp32 per-block scales) — pieces select a part
  decode   dequantize codes `value` against scales `value2`
  cast     dtype conversion of `value` (the fp16/bf16 wire lanes);
           dtype == "" is a pure identity (used by mutations)

Values are piece lists: each `Piece` is a contiguous slice of some
node's output (or a constant fill with no data provenance), so region
intervals stay exact through slicing, concatenation and splicing —
the same prefix-exact posture the hazard pass uses.

The IR is *executable*: `execute` evaluates a DAG numerically (numpy,
with the real `ops.compression` reference for encode/decode), which is
what lets the fuzz harness compare certified-clean DAGs bitwise against
the eager oracle and prove mutated DAGs numerically wrong, not just
rejected. A node reading a region its producer has not yet written
(`validate_order` → ACCL504) reads `stale` zeros, mirroring what the
device would fetch from unwritten memory.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator

import numpy as np

from .diagnostics import Diagnostic, make

__all__ = [
    "CONST",
    "DATA",
    "SCALES",
    "Piece",
    "Value",
    "Node",
    "HopDag",
    "const_value",
    "value_length",
    "slice_value",
    "splice_value",
    "concat_values",
    "validate_order",
    "rank_programs",
    "execute",
    "to_json",
    "from_json",
    "mutate",
    "MUTATIONS",
]

DATA = "data"
SCALES = "scales"
CONST = -1  # Piece.node for constant fill (no producing node)


@dataclasses.dataclass(frozen=True)
class Piece:
    """A contiguous run of elements: a slice of node `node`'s output
    part (`offset` .. `offset+length`), or `length` elements of the
    constant `fill` when node == CONST."""

    length: int
    node: int = CONST
    offset: int = 0
    part: str = DATA
    fill: float = 0.0


Value = tuple[Piece, ...]


def const_value(length: int, fill: float = 0.0) -> Value:
    return (Piece(length, CONST, 0, DATA, fill),) if length else ()


def value_length(value: Value) -> int:
    return sum(p.length for p in value)


def slice_value(value: Value, start: int, length: int) -> Value:
    """The sub-value covering elements [start, start+length)."""
    if length == 0:
        return ()
    out: list[Piece] = []
    pos = 0
    end = start + length
    for p in value:
        lo = max(start, pos)
        hi = min(end, pos + p.length)
        if lo < hi:
            out.append(dataclasses.replace(
                p, length=hi - lo, offset=p.offset + (lo - pos)))
        pos += p.length
        if pos >= end:
            break
    got = sum(p.length for p in out)
    if got < length:  # slice past the end: stale/undefined tail
        out.append(Piece(length - got, CONST, 0, DATA, 0.0))
    return tuple(out)


def splice_value(base: Value, update: Value, start: int) -> Value:
    """`base` with `update` written at element offset `start`."""
    n = value_length(base)
    u = value_length(update)
    return (slice_value(base, 0, start) + update
            + slice_value(base, start + u, n - start - u))


def concat_values(*values: Value) -> Value:
    out: list[Piece] = []
    for v in values:
        out.extend(v)
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class Node:
    """One IR node; `id` is its position in HopDag.nodes (program
    order — the order the device would execute the hops in)."""

    id: int
    kind: str  # arg | send | recv | combine | encode | decode | cast
    rank: int
    length: int  # elements of the node's data output
    value: Value = ()  # primary input (send payload, combine lhs, ...)
    value2: Value = ()  # combine rhs / decode scales
    func: str = ""  # combine: "sum" | "max"
    hop: int = -1  # send/recv channel id
    peer: int = -1  # send: destination rank; recv: source rank
    arg: int = -1  # arg nodes: operand slot
    dtype: str = ""  # cast target / arg & encode element dtype
    scales_len: int = 0  # encode: number of per-block scales

    def refs(self) -> Iterator[Piece]:
        for p in self.value:
            if p.node != CONST:
                yield p
        for p in self.value2:
            if p.node != CONST:
                yield p


@dataclasses.dataclass
class HopDag:
    """One call's schedule as data: nodes in program order plus the
    per-rank output composition."""

    world: int
    n_in: int
    in_elems: int
    out_elems: int
    nodes: tuple[Node, ...]
    outputs: tuple[Value, ...]  # one Value per rank

    def sends_by_channel(self) -> dict[tuple[int, int], Node]:
        """(hop, dst_rank) -> send node. A rank receives at most one
        payload per channel (check_hops' ACCL204 guards the perm side)."""
        idx: dict[tuple[int, int], Node] = {}
        for n in self.nodes:
            if n.kind == "send":
                idx.setdefault((n.hop, n.peer), n)
        return idx


# ---------------------------------------------------------------------------
# Order validation (ACCL504)
# ---------------------------------------------------------------------------


def validate_order(dag: HopDag) -> list[Diagnostic]:
    """Prove every node's inputs are produced before the node runs: a
    send/combine reading a node with a LARGER program index forwards a
    region before its producer wrote it (the device would ship stale
    memory). This is the IR-level form of the stale-read class — the
    hazard pass's ACCL101 covers the BATCH level (a step reading past
    what an earlier step wrote); ACCL504 covers hop order within one
    schedule, which descriptors alone cannot express."""
    diags: list[Diagnostic] = []
    sends = {}
    for n in dag.nodes:
        if n.kind == "send":
            sends[(n.hop, n.peer)] = n
    for n in dag.nodes:
        for p in n.refs():
            if p.node >= n.id:
                src = dag.nodes[p.node]
                diags.append(make(
                    "ACCL504",
                    f"{n.kind} node {n.id} (rank {n.rank}"
                    + (f", hop {n.hop}" if n.hop >= 0 else "")
                    + f") reads {p.length} elements of {src.kind} node "
                    f"{src.id} before it is produced: the device would "
                    "forward stale memory", rank=n.rank))
        if n.kind == "recv":
            s = sends.get((n.hop, n.rank))
            if s is not None and s.id >= n.id:
                diags.append(make(
                    "ACCL504",
                    f"recv node {n.id} (rank {n.rank}, hop {n.hop}) "
                    f"consumes send node {s.id} posted later in program "
                    "order", rank=n.rank))
    return diags


# ---------------------------------------------------------------------------
# Protocol view: lower the hops to per-rank Event programs
# ---------------------------------------------------------------------------


def rank_programs(dag: HopDag) -> list[list[Any]]:
    """Per-rank blocking Event programs over the DAG's hops (tag = hop
    channel), the input `protocol.simulate` and the interleaving model
    checker consume — so hand-written or mutated DAGs run through the
    SAME matching/deadlock machinery lifted schedules do."""
    from .protocol import recv as _recv
    from .protocol import send as _send

    programs: list[list[Any]] = [[] for _ in range(dag.world)]
    for n in dag.nodes:
        if n.kind == "send":
            programs[n.rank].append(_send(n.peer, tag=n.hop))
        elif n.kind == "recv":
            programs[n.rank].append(_recv(n.peer, tag=n.hop))
    return programs


# ---------------------------------------------------------------------------
# Numeric execution (the fuzz harness's device)
# ---------------------------------------------------------------------------


def execute(dag: HopDag, operands: list[list[np.ndarray]]) -> list[np.ndarray]:
    """Evaluate the DAG numerically: `operands[rank][slot]` are the
    per-rank input buffers; returns one output array per rank.

    Arithmetic goes through the SAME reference ops the schedule bodies
    lower to (`ops.compression` for encode/decode, fp32 adds/maxes for
    combine), so a DAG lifted from a schedule reproduces the compiled
    program's results bitwise on CPU. Reads of not-yet-produced nodes
    (the ACCL504 class) evaluate as zeros — stale memory."""
    from ..ops import compression as _comp

    done: dict[tuple[int, str], np.ndarray] = {}
    sends = dag.sends_by_channel()

    def materialize(value: Value, dtype: Any = np.float32) -> np.ndarray:
        parts: list[np.ndarray] = []
        for p in value:
            if p.node == CONST:
                parts.append(np.full(p.length, p.fill, dtype=dtype))
                continue
            src = done.get((p.node, p.part))
            if src is None:  # stale read: producer hasn't run
                parts.append(np.zeros(p.length, dtype=dtype))
            else:
                parts.append(src[p.offset:p.offset + p.length])
        if not parts:
            return np.zeros(0, dtype=dtype)
        widest = max(parts, key=lambda a: a.dtype.itemsize)
        return np.concatenate([a.astype(widest.dtype) for a in parts])

    for n in dag.nodes:
        if n.kind == "arg":
            out = np.asarray(operands[n.rank][max(n.arg, 0)])[: n.length]
        elif n.kind == "send":
            out = materialize(n.value)
        elif n.kind == "recv":
            s = sends.get((n.hop, n.rank))
            if s is None or (s.id, DATA) not in done:
                out = np.zeros(n.length, dtype=np.float32)
            else:
                out = done[(s.id, DATA)][: n.length]
        elif n.kind == "combine":
            a = materialize(n.value)
            b = materialize(n.value2, dtype=a.dtype)
            out = np.maximum(a, b) if n.func == "max" else a + b
        elif n.kind == "encode":
            x = materialize(n.value)
            q, s = _comp.quantize_blockwise(np.asarray(x, np.float32))
            done[(n.id, SCALES)] = np.asarray(s)
            out = np.asarray(q)
        elif n.kind == "decode":
            q = materialize(n.value, dtype=np.int8)
            s = materialize(n.value2, dtype=np.float32)
            out = np.asarray(_comp.dequantize_blockwise(
                np.asarray(q, np.int8), np.asarray(s, np.float32),
                n.length))
        elif n.kind == "cast":
            x = materialize(n.value)
            out = x.astype(np.dtype(n.dtype)) if n.dtype else x
        else:  # pragma: no cover - guarded by from_json/lift
            raise ValueError(f"unknown node kind {n.kind!r}")
        done[(n.id, DATA)] = np.asarray(out)

    return [materialize(dag.outputs[r]) for r in range(dag.world)]


# ---------------------------------------------------------------------------
# JSON (corpus fixtures)
# ---------------------------------------------------------------------------


def _piece_json(p: Piece) -> list:
    out: list = [p.length, p.node, p.offset]
    if p.part != DATA or p.fill:
        out.append(p.part)
    if p.fill:
        out.append(p.fill)
    return out


def _piece_from(v: list) -> Piece:
    part = v[3] if len(v) > 3 else DATA
    fill = float(v[4]) if len(v) > 4 else 0.0
    return Piece(int(v[0]), int(v[1]), int(v[2]), part, fill)


def to_json(dag: HopDag) -> dict:
    nodes = []
    for n in dag.nodes:
        d: dict[str, Any] = {"kind": n.kind, "rank": n.rank,
                             "length": n.length}
        if n.value:
            d["value"] = [_piece_json(p) for p in n.value]
        if n.value2:
            d["value2"] = [_piece_json(p) for p in n.value2]
        for field in ("func", "dtype"):
            if getattr(n, field):
                d[field] = getattr(n, field)
        for field in ("hop", "peer", "arg"):
            if getattr(n, field) >= 0:
                d[field] = getattr(n, field)
        if n.scales_len:
            d["scales_len"] = n.scales_len
        nodes.append(d)
    return {
        "world": dag.world, "n_in": dag.n_in,
        "in_elems": dag.in_elems, "out_elems": dag.out_elems,
        "nodes": nodes,
        "outputs": [[_piece_json(p) for p in v] for v in dag.outputs],
    }


def from_json(d: dict) -> HopDag:
    nodes = []
    for i, nd in enumerate(d["nodes"]):
        nodes.append(Node(
            id=i, kind=nd["kind"], rank=int(nd["rank"]),
            length=int(nd["length"]),
            value=tuple(_piece_from(p) for p in nd.get("value", [])),
            value2=tuple(_piece_from(p) for p in nd.get("value2", [])),
            func=nd.get("func", ""), hop=int(nd.get("hop", -1)),
            peer=int(nd.get("peer", -1)), arg=int(nd.get("arg", -1)),
            dtype=nd.get("dtype", ""),
            scales_len=int(nd.get("scales_len", 0))))
    return HopDag(
        world=int(d["world"]), n_in=int(d.get("n_in", 1)),
        in_elems=int(d["in_elems"]), out_elems=int(d["out_elems"]),
        nodes=tuple(nodes),
        outputs=tuple(tuple(_piece_from(p) for p in v)
                      for v in d["outputs"]))


# ---------------------------------------------------------------------------
# Mutations (the fuzz harness's fault injector)
# ---------------------------------------------------------------------------


def _remap_value(value: Value, remap: dict[int, int]) -> Value:
    return tuple(p if p.node == CONST
                 else dataclasses.replace(p, node=remap[p.node])
                 for p in value)


def _rebuild(dag: HopDag, nodes: list[Node],
             remap: dict[int, int]) -> HopDag:
    """Renumber `nodes` (listed in their NEW program order, carrying
    their old ids) under old-id -> new-id `remap`."""
    new_nodes = tuple(
        dataclasses.replace(n, id=i,
                            value=_remap_value(n.value, remap),
                            value2=_remap_value(n.value2, remap))
        for i, n in enumerate(nodes))
    outputs = tuple(_remap_value(v, remap) for v in dag.outputs)
    return HopDag(dag.world, dag.n_in, dag.in_elems, dag.out_elems,
                  new_nodes, outputs)


def _combines(dag: HopDag, func: str | None = None) -> list[Node]:
    return [n for n in dag.nodes if n.kind == "combine"
            and (func is None or n.func == func)]


def mutate_drop_combine(dag: HopDag, rng: Any) -> HopDag | None:
    """Drop one reduction fold: the combine becomes an identity pass of
    its first operand, so the second operand's contribution never
    reaches the output (the ACCL502 class)."""
    cands = _combines(dag)
    if not cands:
        return None
    c = cands[rng.randrange(len(cands))]
    nodes = list(dag.nodes)
    nodes[c.id] = dataclasses.replace(c, kind="cast", value2=(), func="",
                                      dtype="")
    ident = {n.id: n.id for n in dag.nodes}
    return _rebuild(dag, nodes, ident)


def mutate_duplicate_combine(dag: HopDag, rng: Any) -> HopDag | None:
    """Fold one combine's second operand in twice (the ACCL503 class:
    a contribution double-counted into a non-idempotent reduction)."""
    cands = _combines(dag, "sum")
    if not cands:
        return None
    c = cands[rng.randrange(len(cands))]
    dup = Node(id=-1, kind="combine", rank=c.rank, length=c.length,
               value=(Piece(c.length, c.id),), value2=c.value2,
               func=c.func)
    order = list(dag.nodes[: c.id + 1]) + [dup] + list(dag.nodes[c.id + 1:])
    remap = {}
    for i, n in enumerate(order):
        if n.id >= 0:
            remap[n.id] = i
    # consumers of c now read the duplicated fold
    dup_new = remap[c.id] + 1

    def redirect(value: Value, skip_dup: bool = False) -> Value:
        return tuple(
            p if p.node == CONST else dataclasses.replace(
                p, node=(dup_new if p.node == c.id and not skip_dup
                         else remap[p.node]))
            for p in value)

    new_nodes = []
    for i, n in enumerate(order):
        if n is dup:
            new_nodes.append(dataclasses.replace(
                dup, id=i, value=(Piece(c.length, remap[c.id]),),
                value2=_remap_value(c.value2, remap)))
        else:
            skip = n.id <= c.id  # nodes at/before c keep their wiring
            new_nodes.append(dataclasses.replace(
                n, id=i, value=redirect(n.value, skip_dup=skip),
                value2=redirect(n.value2, skip_dup=skip)))
    outputs = tuple(redirect(v) for v in dag.outputs)
    return HopDag(dag.world, dag.n_in, dag.in_elems, dag.out_elems,
                  tuple(new_nodes), outputs)


def mutate_reorder_combine(dag: HopDag, rng: Any) -> HopDag | None:
    """Hoist a combine above the recv it folds: the fold now reads the
    arrival before the wire delivers it (the ACCL504 class)."""
    cands = [c for c in _combines(dag)
             if any(dag.nodes[p.node].kind == "recv" for p in c.refs())]
    if not cands:
        return None
    c = cands[rng.randrange(len(cands))]
    first_recv = min(p.node for p in c.refs()
                     if dag.nodes[p.node].kind == "recv")
    order = list(dag.nodes)
    order.remove(c)
    order.insert(first_recv, c)
    remap = {n.id: i for i, n in enumerate(order)}
    return _rebuild(dag, order, remap)


def mutate_swap_send_values(dag: HopDag, rng: Any) -> HopDag | None:
    """Swap the payloads of two sends in one hop: every endpoint still
    matches (the protocol passes stay clean) but two destinations get
    each other's region (the ACCL501 class)."""
    by_hop: dict[int, list[Node]] = {}
    for n in dag.nodes:
        if n.kind == "send":
            by_hop.setdefault(n.hop, []).append(n)
    hops = [ns for ns in by_hop.values()
            if len(ns) >= 2 and ns[0].length == ns[1].length
            and ns[0].value != ns[1].value]
    if not hops:
        return None
    ns = hops[rng.randrange(len(hops))]
    a, b = ns[0], ns[1]
    nodes = list(dag.nodes)
    nodes[a.id] = dataclasses.replace(a, value=b.value)
    nodes[b.id] = dataclasses.replace(b, value=a.value)
    ident = {n.id: n.id for n in dag.nodes}
    return _rebuild(dag, nodes, ident)


MUTATIONS: dict[str, Callable[[HopDag, Any], HopDag | None]] = {
    "drop_combine": mutate_drop_combine,  # expect ACCL502
    "duplicate_combine": mutate_duplicate_combine,  # expect ACCL503
    "reorder_combine": mutate_reorder_combine,  # expect ACCL504
    "swap_send_values": mutate_swap_send_values,  # expect ACCL501
}


def mutate(dag: HopDag, kind: str, rng: Any) -> HopDag | None:
    return MUTATIONS[kind](dag, rng)
