"""Core enumerations and constants of the accl-tpu framework.

Behavioral parity with the reference host driver's constant set
(reference: driver/xrt/include/accl/constants.hpp:179-411) with TPU-native
extensions (bfloat16 as a first-class dtype, transport kinds for ICI/DCN
instead of TCP/UDP/RDMA protocol-offload engines).
"""

from __future__ import annotations

import enum

import numpy as np

# ---------------------------------------------------------------------------
# Call scenarios (reference: constants.hpp:190-216 `enum class operation`)
# ---------------------------------------------------------------------------


class Operation(enum.IntEnum):
    """The scenario field of a call descriptor."""

    config = 0
    copy = 1
    combine = 2
    send = 3
    recv = 4
    bcast = 5
    scatter = 6
    gather = 7
    reduce = 8
    allgather = 9
    allreduce = 10
    reduce_scatter = 11
    barrier = 12
    alltoall = 13
    nop = 255


class CfgFunc(enum.IntEnum):
    """Housekeeping sub-functions of Operation.config.

    Reference: constants.hpp:178-186 `enum class cfgFunc`.
    """

    reset_periph = 0
    enable_pkt = 1
    set_timeout = 2
    set_max_eager_msg_size = 3
    set_max_rendezvous_msg_size = 4


class ReduceFunction(enum.IntEnum):
    """Reference: constants.hpp:218-226 `enum class reduceFunction`."""

    SUM = 0
    MAX = 1


class OperationStatus(enum.IntEnum):
    """Status of an in-flight request (constants.hpp:228-236)."""

    QUEUED = 0
    EXECUTING = 1
    COMPLETED = 2


# ---------------------------------------------------------------------------
# Data types (reference: constants.hpp:252-273). bfloat16 is a TPU-native
# addition: it slots into the compression lanes exactly like float16.
# ---------------------------------------------------------------------------


class DataType(enum.IntEnum):
    none = 0
    int8 = 1
    float16 = 2
    float32 = 3
    float64 = 4
    int32 = 5
    int64 = 6
    bfloat16 = 7  # TPU-native extension


DATATYPE_BITS: dict[DataType, int] = {
    DataType.none: 0,
    DataType.int8: 8,
    DataType.float16: 16,
    DataType.float32: 32,
    DataType.float64: 64,
    DataType.int32: 32,
    DataType.int64: 64,
    DataType.bfloat16: 16,
}


def dtype_nbytes(dt: DataType) -> int:
    return DATATYPE_BITS[dt] // 8


def to_numpy_dtype(dt: DataType) -> np.dtype:
    import ml_dtypes

    table = {
        DataType.int8: np.dtype(np.int8),
        DataType.float16: np.dtype(np.float16),
        DataType.float32: np.dtype(np.float32),
        DataType.float64: np.dtype(np.float64),
        DataType.int32: np.dtype(np.int32),
        DataType.int64: np.dtype(np.int64),
        DataType.bfloat16: np.dtype(ml_dtypes.bfloat16),
    }
    return table[dt]


def from_numpy_dtype(dt) -> DataType:
    import ml_dtypes

    dt = np.dtype(dt)
    if dt == np.dtype(ml_dtypes.bfloat16):
        return DataType.bfloat16
    table = {
        np.dtype(np.int8): DataType.int8,
        np.dtype(np.float16): DataType.float16,
        np.dtype(np.float32): DataType.float32,
        np.dtype(np.float64): DataType.float64,
        np.dtype(np.int32): DataType.int32,
        np.dtype(np.int64): DataType.int64,
    }
    return table[dt]


# ---------------------------------------------------------------------------
# Flag words carried in the call descriptor
# ---------------------------------------------------------------------------


class StreamFlags(enum.IntFlag):
    """Streamed-operand flags (constants.hpp:275-283)."""

    NO_STREAM = 0
    OP0_STREAM = 1
    RES_STREAM = 2


class HostFlags(enum.IntFlag):
    """Host-resident-operand flags (constants.hpp:295-305).

    On TPU "host" buffers map to pinned host memory staged over PCIe rather
    than HBM; the flag propagation rules through collectives are identical.
    """

    NO_HOST = 0
    OP0_HOST = 1
    OP1_HOST = 2
    RES_HOST = 4


class CompressionFlags(enum.IntFlag):
    """Compression flags (constants.hpp:317-327).

    ETH_COMPRESSED requests wire (inter-chip) compression: payloads are cast
    to the compressed dtype of the active arithmetic configuration before
    crossing ICI/DCN and cast back on arrival.
    """

    NO_COMPRESSION = 0
    OP0_COMPRESSED = 1
    OP1_COMPRESSED = 2
    RES_COMPRESSED = 4
    ETH_COMPRESSED = 8


class Transport(enum.IntEnum):
    """Analog of networkProtocol (constants.hpp:329-339).

    The reference selects a TCP/UDP/RDMA protocol-offload engine at build
    time; we select how collective steps move bytes between ranks:
      ICI  - XLA collectives / Pallas remote DMA across an intra-slice mesh
      DCN  - inter-slice transfers through jax distributed + host network
      EMU  - the native CPU emulator's socket transport (test/model analog)
    """

    ICI = 0
    DCN = 1
    EMU = 2


# ---------------------------------------------------------------------------
# Error codes (reference: constants.hpp:341-376). The sticky-bit contract is
# preserved: any engine can OR bits into the call's return code and the host
# driver raises with every set bit decoded.
# ---------------------------------------------------------------------------


class ErrorCode(enum.IntFlag):
    COLLECTIVE_OP_SUCCESS = 0
    DMA_MISMATCH_ERROR = 1 << 0
    DMA_INTERNAL_ERROR = 1 << 1
    DMA_DECODE_ERROR = 1 << 2
    DMA_SLAVE_ERROR = 1 << 3
    DMA_NOT_OKAY_ERROR = 1 << 4
    DMA_NOT_END_OF_PACKET_ERROR = 1 << 5
    DMA_NOT_EXPECTED_BTT_ERROR = 1 << 6
    DMA_TIMEOUT_ERROR = 1 << 7
    CONFIG_SWITCH_ERROR = 1 << 8
    DEQUEUE_BUFFER_TIMEOUT_ERROR = 1 << 9
    DEQUEUE_BUFFER_SPARE_BUFFER_STATUS_ERROR = 1 << 10
    RECEIVE_TIMEOUT_ERROR = 1 << 11
    DEQUEUE_BUFFER_SPARE_BUFFER_DMATAG_MISMATCH = 1 << 12
    DEQUEUE_BUFFER_SPARE_BUFFER_INDEX_ERROR = 1 << 13
    COLLECTIVE_NOT_IMPLEMENTED = 1 << 14
    RECEIVE_OFFCHIP_SPARE_BUFF_ID_NOT_VALID = 1 << 15
    EAGER_THRESHOLD_INVALID = 1 << 16
    RENDEZVOUS_THRESHOLD_INVALID = 1 << 17
    DMA_SIZE_ERROR = 1 << 18
    ARITH_ERROR = 1 << 19
    PACK_TIMEOUT_STS_ERROR = 1 << 20
    PACK_SEQ_NUMBER_ERROR = 1 << 21
    COMPRESSION_ERROR = 1 << 22
    KRNL_TIMEOUT_STS_ERROR = 1 << 23
    KRNL_STS_COUNT_ERROR = 1 << 24
    SEGMENTER_EXPECTED_BTT_ERROR = 1 << 25
    DMA_TAG_MISMATCH_ERROR = 1 << 26


ERROR_CODE_BITS = 27  # bits 0..26 inclusive


def error_code_to_string(code: int) -> str:
    """Decode a sticky error word into a human-readable string."""
    if code == 0:
        return "COLLECTIVE_OP_SUCCESS"
    names = [e.name for e in ErrorCode if e.value and (code & e.value)]
    return " | ".join(names) if names else f"UNKNOWN_ERROR(0x{code:x})"


class ACCLError(RuntimeError):
    """Raised by the host driver when a call returns a nonzero retcode.

    Mirrors ACCL::check_return_value (reference: driver/xrt/src/accl.cpp:1210-1234).
    """

    def __init__(self, function_name: str, retcode: int):
        self.retcode = retcode
        super().__init__(
            f"CCLO call {function_name} failed: {error_code_to_string(retcode)} "
            f"(retcode=0x{retcode:x})"
        )


# ---------------------------------------------------------------------------
# Defaults (reference: driver/xrt/include/accl.hpp:102-104 and
# kernels/cclo/fw .../ccl_offload_control.h:51-54)
# ---------------------------------------------------------------------------

TAG_ANY = 0xFFFFFFFF

DEFAULT_NUM_EAGER_RX_BUFS = 16
DEFAULT_EAGER_RX_BUF_SIZE = 1024  # bytes
DEFAULT_MAX_EAGER_SIZE = 1024  # bytes; above this (uncompressed, non-stream)
#   a transfer takes the rendezvous path
DEFAULT_MAX_RENDEZVOUS_SIZE = 32 * 1024  # bytes

# Max bytes a single data-movement command may carry before being chunked
# (reference DMA_MAX_BTT, ccl_offload_control.h:54). On TPU this bounds the
# per-step block a schedule moves between HBM buffers / across ICI.
DMA_MAX_BTT = 8 * 1024 * 1024 - 64

# Max bytes per wire segment (reference MAX_PACKETSIZE, ccl_offload_control.h:51)
MAX_SEG_SIZE = 4096

# ---------------------------------------------------------------------------
# Hop-shape constants shared by the native executor and the timing model.
# These are the SINGLE SOURCE for the logp crossover rules and the streamed
# ring's jumbo-segment size: native/src/runtime.cpp (logp_max_bytes,
# logp_ag_max_bytes, the egr_send jumbo seg_bytes) hard-codes the same
# values, and tests/test_timing.py pins the two sources together so the
# timing model cannot silently drift from the executor it models.
# ---------------------------------------------------------------------------

# allreduce: recursive halving-doubling wins while the payload is under
# ~this many bytes per ring hop saved (measured tie points,
# accl_log/rt_stats_shape_*.csv)
LOGP_ALLREDUCE_HOP_BYTES = 32 * 1024
# allgather: recursive doubling threshold per hop saved, against the TOTAL
# gathered payload
LOGP_ALLGATHER_HOP_BYTES = 128 * 1024
# jumbo-segment size for streamed whole-chunk ring/tree hop messages
# (runtime.cpp egr_send seg_bytes at its ring-collective call sites)
STREAM_SEG_BYTES = 1 << 20


def log2_floor(world: int) -> int:
    """floor(log2(world)) by bit scan — the exact arithmetic of the
    native executor's log2_floor (runtime.cpp), so the crossover rules
    below can never diverge from it by a rounding convention."""
    r = 0
    while (1 << (r + 1)) <= world:
        r += 1
    return r


def logp_allreduce_max_bytes(world: int) -> int:
    """Mirror of runtime.cpp logp_max_bytes: the payload ceiling (bytes)
    under which a power-of-two world runs the recursive halving-doubling
    allreduce instead of the ring. SINGLE SOURCE for the crossover shape:
    timing._logp_allreduce and the native rule both read this arithmetic
    (ring 2(P-1) hops vs halving-doubling 2*log2(P))."""
    hops_saved = 2 * (world - 1) - 2 * log2_floor(world)
    return hops_saved * LOGP_ALLREDUCE_HOP_BYTES


def logp_allgather_max_bytes(world: int) -> int:
    """Mirror of runtime.cpp logp_ag_max_bytes: recursive-doubling
    threshold against the TOTAL gathered payload (ring P-1 hops vs
    doubling log2(P))."""
    hops_saved = (world - 1) - log2_floor(world)
    return hops_saved * LOGP_ALLGATHER_HOP_BYTES

# ---------------------------------------------------------------------------
# Blockwise int8 wire quantization (the EQuARX-style compression lanes,
# arxiv 2506.17615): payloads cross each hop as int8 blocks with one fp32
# scale per block. The block size divides STREAM_SEG_BYTES for every
# payload dtype the lanes accept, so a jumbo wire segment never splits a
# block between two messages (1 MiB of fp32 = 1024 blocks exactly).
# ---------------------------------------------------------------------------

QUANT_BLOCK_ELEMS = 256  # elements per scale block
QUANT_SCALE_BYTES = 4  # one fp32 scale per block
# symmetric round-to-nearest-even onto [-QUANT_QMAX, QUANT_QMAX]: the
# full-range -128 code is unused so the grid is symmetric and MAX
# reductions cannot bias toward the negative rail
QUANT_QMAX = 127
# the block scale is DEFINED as amax * fp32(1/QUANT_QMAX): an explicit
# reciprocal multiply encodes bitwise-identically across executors,
# where a divide-by-literal may or may not be strength-reduced
QUANT_INV_QMAX = float(np.float32(1.0) / np.float32(QUANT_QMAX))
# effective wire width per element (timing.wire_elem_bytes bills this):
# 1 B of payload + the amortized per-block scale = 1.015625 B for fp32

EXCHMEM_SIZE = 8192  # bytes of emulated exchange memory per rank


class TuningParams:
    """Runtime algorithm-tuning registers.

    Mirrors the CCLO_ADDR tuning registers and their default values written
    by ACCL::configure_tuning_parameters (reference: driver/xrt/src/accl.cpp:1198-1208).
    """

    def __init__(
        self,
        gather_flat_tree_max_fanin: int = 2,
        gather_flat_tree_max_count: int = 32 * 1024,
        bcast_flat_tree_max_ranks: int = 3,
        reduce_flat_tree_max_ranks: int = 4,
        reduce_flat_tree_max_count: int = 32 * 1024,
        allreduce_composition_max_count: int = 0,
        synth_allreduce_max_count: int = 0,
        synth_allgather_max_count: int = 0,
        synth_reduce_scatter_max_count: int = 0,
        hier_allreduce_min_count: int = 0,
        alltoall_compress_min_count: int = 0,
        overlap_min_count: int = 0,
        synth_latency_max_count: int = 0,
    ):
        self.gather_flat_tree_max_fanin = gather_flat_tree_max_fanin
        self.gather_flat_tree_max_count = gather_flat_tree_max_count
        self.bcast_flat_tree_max_ranks = bcast_flat_tree_max_ranks
        self.reduce_flat_tree_max_ranks = reduce_flat_tree_max_ranks
        self.reduce_flat_tree_max_count = reduce_flat_tree_max_count
        # Allreduce payloads in (max_eager, this] bytes run the reference's
        # rendezvous reduce+bcast composition (.c:1878-1887); 0 — the
        # default, backed by the emulator measurement in
        # accl_log/emu_bench.csv where the ring beat the composition ~4x
        # at 1 MB / 8 ranks — selects the streamed ring at every size.
        # Runtime-tunable like the reference's algorithm registers
        # (accl.cpp:1198-1208); the timing model arbitrates per
        # (size, world) via tuning_crossovers.
        self.allreduce_composition_max_count = allreduce_composition_max_count
        # Synthesized-schedule crossovers (sequencer/synthesis.py):
        # payloads up to this many bytes run the search-produced
        # hop-DAG from the committed library when one exists for the
        # (op, world) cell. 0 — the default — keeps the hand-written
        # zoo; ACCL.autotune sets these from the calibrated timing
        # model's predicted crossovers, the same measured-selection
        # posture as the other registers.
        self.synth_allreduce_max_count = synth_allreduce_max_count
        self.synth_allgather_max_count = synth_allgather_max_count
        self.synth_reduce_scatter_max_count = synth_reduce_scatter_max_count
        # Latency-window synthesized-schedule crossover: exact fp32
        # allreduce payloads up to this many bytes run the committed
        # LATENCY-GRID library entry (synthesis.SIZE_GRID_LAT, the
        # 1-64 KiB decode regime where the alpha term is the product)
        # when one covers the cell — checked BEFORE the bandwidth-
        # biased std synth window, so a minimum-step schedule that
        # only wins the small-payload floor can be shipped without
        # widening the std register past its calibration. 0 — the
        # default — keeps selection bit-for-bit unchanged;
        # ACCL.autotune sets it from timing.tuning_crossovers'
        # synth_latency_max_bytes, the same measured-selection posture
        # as every other register.
        self.synth_latency_max_count = synth_latency_max_count
        # Hierarchical-allreduce crossover (sequencer/hierarchical.py):
        # on a device that declares a two-tier topology, allreduce
        # payloads of AT LEAST this many bytes run the striped two-tier
        # composition (Algorithm.HIER_RS_AR_AG) — a MIN register,
        # because the composition wins the bandwidth-bound regime
        # (large payloads, where moving 1/L of the bytes on the slow
        # tier dominates) and loses the latency floor to its extra
        # message count. 0 — the default — keeps the flat selection
        # everywhere; ACCL.autotune sets it from the calibrated
        # per-tier crossover (timing.tuning_crossovers with tier_links
        # + topology), the same measured-selection posture as the synth
        # registers.
        self.hier_allreduce_min_count = hier_allreduce_min_count
        # Quantized-alltoall crossover (sequencer/schedules.py alltoall
        # family + the EQuARX int8 wire lanes): on a device with the
        # blockwise-quantized wire, uncompressed fp32 alltoall(v)
        # payloads of AT LEAST this many bytes (the descriptor's
        # count * elem_bytes, the same bytes_count every register
        # compares) ship int8 codes + per-block scales on every hop —
        # a MIN register, because the compressed wire wins the
        # bandwidth regime (~3.94x fewer wire bytes) and buys nothing
        # on the latency floor, where the exact fp32 wire is kept. 0 —
        # the default — keeps selection bit-for-bit unchanged;
        # ACCL.autotune sets it from the calibrated timing model's
        # predicted crossover (timing.tuning_crossovers'
        # alltoall_compress_min_bytes), the same measured-selection
        # posture as the hier register.
        self.alltoall_compress_min_count = alltoall_compress_min_count
        # Compute-communication overlap crossover (sequencer/plan.py +
        # timing.predict_overlapped): STREAMED eager fp32 allreduce
        # payloads of AT LEAST this many bytes — the consumer-spliced
        # gradient-sync seam, where adjacent compute exists to overlap
        # with — run as Plan.stripes independent stripe chains whose
        # depth is the cost model's argmin (timing.best_overlap_stripes
        # under the calibrated shaped link and the measured ComputeFit
        # compute term). A MIN register like the hier one: overlap wins
        # the regime where wire time is visible next to compute, and
        # buys nothing on the latency floor. 0 — the default — keeps
        # selection bit-for-bit the serial dispatch->compute form;
        # ACCL.autotune sets it from timing.tuning_crossovers'
        # overlap_min_bytes, the same measured-selection posture as
        # every other register.
        self.overlap_min_count = overlap_min_count

    @classmethod
    def default(cls, max_rndzv_msg_size: int = DEFAULT_MAX_RENDEZVOUS_SIZE):
        reduce_flat_ranks = 4
        return cls(
            reduce_flat_tree_max_ranks=reduce_flat_ranks,
            reduce_flat_tree_max_count=min(
                max_rndzv_msg_size // reduce_flat_ranks, 32 * 1024
            ),
        )

    @classmethod
    def from_crossovers(cls, cross: dict,
                        max_count_cap: int = 1 << 22) -> "TuningParams":
        """Register values from the timing model's switch-over points
        (sequencer.timing.tuning_crossovers / the committed
        accl_log/timing_model.json): the measured-performance form of the
        reference's hand-picked defaults (accl.cpp:1198-1208). Byte
        thresholds are clamped to [1, max_count_cap] — an infinite
        crossover (flat never loses on this link) caps rather than
        overflowing the 32-bit register."""
        def as_reg(v):
            if v != v or v == float("inf"):  # NaN/inf -> cap
                return max_count_cap
            return max(1, min(int(v), max_count_cap))

        # the allreduce composition crossover may legitimately be 0
        # ("ring always wins"), which as_reg would clamp to 1; NaN/inf
        # cap like every other threshold
        comp = cross.get("allreduce_composition_max_bytes", 0)
        if comp != comp or comp == float("inf"):
            comp = max_count_cap
        comp = 0 if comp <= 0 else min(int(comp), max_count_cap)
        return cls(
            gather_flat_tree_max_count=as_reg(
                cross["gather_flat_tree_max_count_bytes"]),
            bcast_flat_tree_max_ranks=max(
                1, int(cross["bcast_flat_tree_max_ranks"])),
            reduce_flat_tree_max_ranks=max(
                1, int(cross["reduce_flat_tree_max_ranks"])),
            reduce_flat_tree_max_count=as_reg(
                cross["reduce_flat_tree_max_count_bytes"]),
            allreduce_composition_max_count=comp,
            # 0 is meaningful for the synth registers ("never wins on
            # this link" / no library entry): clamp only the top end
            synth_allreduce_max_count=min(
                int(cross.get("synth_allreduce_max_bytes", 0)),
                max_count_cap),
            synth_allgather_max_count=min(
                int(cross.get("synth_allgather_max_bytes", 0)),
                max_count_cap),
            synth_reduce_scatter_max_count=min(
                int(cross.get("synth_reduce_scatter_max_bytes", 0)),
                max_count_cap),
            # same MAX-register posture as the synth trio: 0 = no
            # latency-grid entry or never wins on this link
            synth_latency_max_count=min(
                int(cross.get("synth_latency_max_bytes", 0)),
                max_count_cap),
            # 0 is meaningful here too: no per-tier calibration / no
            # topology / hierarchical never wins on these links. This
            # is a MIN threshold, so the overflow-safe clamp is OFF —
            # min(v, cap) would WIDEN the window into the region the
            # calibration said flat wins.
            hier_allreduce_min_count=(
                int(cross.get("hier_allreduce_min_bytes", 0))
                if int(cross.get("hier_allreduce_min_bytes", 0))
                <= max_count_cap else 0),
            # same MIN-register posture: 0 = never wins / no quantized
            # lane on this link, and an over-cap window start clamps to
            # OFF (min(v, cap) would widen the window into the regime
            # the calibration said the exact wire wins)
            alltoall_compress_min_count=(
                int(cross.get("alltoall_compress_min_bytes", 0))
                if int(cross.get("alltoall_compress_min_bytes", 0))
                <= max_count_cap else 0),
            # same MIN-register posture again: 0 = no compute
            # calibration / overlap never predicts a win, and an
            # over-cap window start clamps to OFF (min(v, cap) would
            # widen the window into the regime the calibration said
            # the serial form wins)
            overlap_min_count=(
                int(cross.get("overlap_min_bytes", 0))
                if int(cross.get("overlap_min_bytes", 0))
                <= max_count_cap else 0),
        )
