"""Buffer hierarchy: host-mirrored device buffers.

Reference semantics: driver/xrt/include/accl/buffer.hpp:32-204 — a
BaseBuffer pairs a host pointer with a device allocation, with explicit
sync_to_device/sync_from_device, slicing, a device address for call
descriptors, and backend-specific subclasses (XRTBuffer/SimBuffer/
CoyoteBuffer/DummyBuffer).

TPU mapping: the device allocation is a jax.Array laid out as a stacked
(world, n) array sharded over the collective mesh axis, so device r's
shard is rank r's buffer — the HBM analog of per-FPGA DDR buffers. Host
mirrors are numpy. Addresses are allocated from a per-context virtual
arena so descriptors, exchange-memory dumps and the native emulator agree
on buffer identity.
"""

from __future__ import annotations

import itertools

import jax
import numpy as np

from .constants import DataType, from_numpy_dtype

_addr_arena = itertools.count(0x1000_0000, 0x100_0000)


class BaseBuffer:
    """Common buffer interface (reference buffer.hpp:32-95)."""

    def __init__(self, shape, dtype, address=None):
        self.shape = tuple(shape)
        self.np_dtype = np.dtype(dtype)
        self.address = next(_addr_arena) if address is None else address

    @property
    def count(self) -> int:
        """Elements per rank (the descriptor's count field)."""
        return int(np.prod(self.shape[1:])) if len(self.shape) > 1 else self.shape[0]

    @property
    def data_type(self) -> DataType:
        return from_numpy_dtype(self.np_dtype)

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape)) * self.np_dtype.itemsize

    def sync_to_device(self):
        raise NotImplementedError

    def sync_from_device(self):
        raise NotImplementedError


class TPUBuffer(BaseBuffer):
    """A (world, n) stacked rank buffer sharded over the mesh axis.

    The host mirror (`host`) is numpy; `device` is the sharded jax.Array.
    sync_to_device/sync_from_device move whole images, like the reference's
    explicit DMA syncs (buffer.hpp:60-72) — collectives can then run
    `from_fpga/to_fpga`-style without host round-trips.
    """

    def __init__(self, host: np.ndarray, sharding, host_only: bool = False):
        super().__init__(host.shape, host.dtype)
        self.host = host
        self.sharding = sharding
        self.host_only = host_only
        self.device: jax.Array | None = None
        if not host_only:
            self.sync_to_device()

    def sync_to_device(self):
        self.device = jax.device_put(self.host, self.sharding)
        return self

    def sync_from_device(self):
        if self.device is not None:
            self.host = np.asarray(jax.device_get(self.device))
        return self

    def write(self, data: np.ndarray):
        data = np.asarray(data, self.np_dtype).reshape(self.shape)
        self.host = data
        return self

    def rank_view(self, rank: int) -> np.ndarray:
        """Host view of one rank's buffer."""
        return self.host[rank]


class EmuBuffer(BaseBuffer):
    """A per-rank host buffer registered with the native emulator runtime
    (reference SimBuffer, driver/xrt/include/accl/simbuffer.hpp): memory
    lives in this process, the runtime addresses it by `address`."""

    def __init__(self, host: np.ndarray, address=None):
        super().__init__((1,) + tuple(host.shape), host.dtype, address)
        self.host = host

    def sync_to_device(self):
        return self

    def sync_from_device(self):
        return self


class DummyBuffer(BaseBuffer):
    """Placeholder for unused operands (reference dummybuffer.hpp; used by
    prepare_call for absent operands, accl.cpp:1243-1268)."""

    def __init__(self):
        super().__init__((0,), np.float32, address=0)
        self.host = np.zeros((0,), np.float32)
        self.device = None

    def sync_to_device(self):
        return self

    def sync_from_device(self):
        return self
