"""Flagship demo: a TP x SP x DP transformer LM on the framework.

The model is deliberately the vadd_put pattern (reference
kernels/plugins/vadd_put/vadd_put.cpp:25-87 — device compute pushing
straight into a collective with no host round-trip) at training scale:
one shard_map program contains the forward, the ring-attention sequence
parallelism, the tensor-parallel partial-sum reductions, the backward,
and the data-parallel gradient sync — every cross-device byte moves
through the framework's own schedule bodies (sequencer/schedules.py),
and the host only dispatches the step.

Sharding layout over mesh axes (dp, sp, tp):
  - batch over dp, sequence over sp (ring attention handles cross-shard
    attention), attention heads + mlp hidden over tp;
  - parameters: qkv/o and mlp weights sharded over tp, embeddings
    replicated;
  - gradients: allreduced over dp and sp with the framework's ring
    schedule (eager segmented ring, the ACCL hot path).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..constants import ReduceFunction
from ..sequencer import schedules
from ..parallel.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 256
    dtype: str = "float32"
    # grouped-query attention: kv heads < query heads shrink the KV cache
    # (the decode-path memory lever) and the ring-attention wire bytes;
    # None = multi-head (kv_heads == n_heads)
    n_kv_heads: int | None = None
    # rotary position embeddings; positions are GLOBAL under sequence
    # parallelism (each sp shard offsets by its rank)
    rope: bool = True
    rope_theta: float = 10000.0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        kv = self.n_kv_heads or self.n_heads
        assert self.n_heads % kv == 0, (self.n_heads, kv)
        return kv


def init_params(cfg: TransformerConfig, key) -> dict:
    """Global (unsharded) parameter pytree; shard with shard_params."""
    keys = jax.random.split(key, 2 + cfg.n_layers)
    dt = jnp.dtype(cfg.dtype)
    scale = 0.02

    def dense(k, shape):
        return (jax.random.normal(k, shape) * scale).astype(dt)

    params = {
        "embed": dense(keys[0], (cfg.vocab, cfg.d_model)),
        "unembed": dense(keys[1], (cfg.d_model, cfg.vocab)),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[2 + i], 6)
        params["layers"].append(
            {
                "wq": dense(k[0], (cfg.d_model, cfg.n_heads, cfg.head_dim)),
                "wkv": dense(k[4], (cfg.d_model, 2, cfg.kv_heads,
                                    cfg.head_dim)),
                "wo": dense(k[1], (cfg.n_heads, cfg.head_dim, cfg.d_model)),
                "w_up": dense(k[2], (cfg.d_model, cfg.d_ff)),
                "w_down": dense(k[3], (cfg.d_ff, cfg.d_model)),
                "ln1": jnp.ones((cfg.d_model,), dt),
                "ln2": jnp.ones((cfg.d_model,), dt),
            }
        )
    return params


def param_specs(cfg: TransformerConfig) -> dict:
    """PartitionSpecs: tp shards heads/ff, everything else replicated."""
    layer = {
        "wq": P(None, "tp", None),
        "wkv": P(None, None, "tp", None),
        "wo": P("tp", None, None),
        "w_up": P(None, "tp"),
        "w_down": P("tp", None),
        "ln1": P(),
        "ln2": P(),
    }
    return {
        "embed": P(),
        "unembed": P(),
        "layers": [layer] * cfg.n_layers,
    }


def stack_layer_params(params) -> dict:
    """Convert the per-layer parameter list into stacked (n_layers, ...)
    leaves so the layer dim can shard over a `pp` mesh axis (stage i =
    layers [i*L/P, (i+1)*L/P))."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params["layers"])
    return {"embed": params["embed"], "unembed": params["unembed"],
            "layers": stacked}


def unstack_layer_params(params, n_layers: int) -> dict:
    """Inverse of stack_layer_params: stacked (n_layers, ...) leaves back
    to the per-layer list form (checkpoint interop across mesh shapes)."""
    layers = [jax.tree.map(lambda x: x[i], params["layers"])
              for i in range(n_layers)]
    return {"embed": params["embed"], "unembed": params["unembed"],
            "layers": layers}


def pp_param_specs(cfg: TransformerConfig) -> dict:
    """PartitionSpecs for the stacked form: layer dim over pp, head/ff
    dims over tp as in param_specs, embeddings replicated."""
    layer = param_specs(cfg)["layers"][0]
    return {
        "embed": P(),
        "unembed": P(),
        "layers": {k: P("pp", *s) for k, s in layer.items()},
    }


def _pp_world(mesh: Mesh) -> int:
    return dict(mesh.shape).get("pp", 1)


def _spec_has_axis(spec, axis: str) -> bool:
    """True if a PartitionSpec shards any dimension over `axis`."""
    for part in spec:
        if part is None:
            continue
        parts = part if isinstance(part, tuple) else (part,)
        if axis in parts:
            return True
    return False


def _rmsnorm(x, g):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * g


def _rope(x, pos, theta: float):
    """Rotate (B, T, H, D) by absolute positions `pos` (T,) — rotary
    embeddings in fp32, half-split form. Positions must be GLOBAL: under
    sequence parallelism the caller offsets by its sp shard."""
    D = x.shape[-1]
    assert D % 2 == 0, "rope needs an even head_dim"
    half = D // 2
    inv_freq = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, None] * inv_freq[None, :]  # (T, half)
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1).astype(x.dtype)


def _qkv(h, lyr, cfg: TransformerConfig, pos):
    """Project q / k / v with grouped-query layout and rotate q,k by the
    global positions `pos`. k/v stay at kv_heads (GQA): ring_attention
    attends grouped natively, so each sp ring hop carries the Hkv slice —
    a kv_heads/n_heads wire-byte saving per hop. Head dims are tp-LOCAL
    here, and H_local / Hkv_local == n_heads / kv_heads on every shard
    (tp must divide kv_heads)."""
    q = jnp.einsum("btd,dhk->bthk", h, lyr["wq"])
    kv = jnp.einsum("btd,dchk->btchk", h, lyr["wkv"])
    k, v = kv[:, :, 0], kv[:, :, 1]
    if cfg.rope:
        q = _rope(q, pos, cfg.rope_theta)
        k = _rope(k, pos, cfg.rope_theta)
    return q, k, v


def _tp_allreduce(x, wire):
    """Tensor-parallel partial-sum reduction through the framework's ring
    reduce-scatter + allgather schedule (the ACCL eager allreduce)."""
    shape = x.shape
    flat = x.reshape(-1)
    out = schedules.allreduce_ring_schedule(
        flat,
        func=ReduceFunction.SUM,
        axis="tp",
        world=lax.axis_size("tp"),
        wire=wire,
        seg_count=flat.shape[0],
    )
    return out.reshape(shape)


def _grad_allreduce(g, axis, wire):
    world = lax.axis_size(axis)
    if world == 1:
        return g
    shape = g.shape
    out = schedules.allreduce_ring_schedule(
        g.reshape(-1),
        func=ReduceFunction.SUM,
        axis=axis,
        world=world,
        wire=wire,
        seg_count=g.size,
    )
    return out.reshape(shape) / world  # mean over replicas


def _mlp_half(x, lyr, wire):
    """ln2 + gelu MLP + tp partial-sum residual — shared by the training
    block and the decode block so the two cannot silently diverge."""
    h = _rmsnorm(x, lyr["ln2"])
    up = jax.nn.gelu(jnp.einsum("btd,df->btf", h, lyr["w_up"]))
    down_partial = jnp.einsum("btf,fd->btd", up, lyr["w_down"])
    return x + _tp_allreduce(down_partial, wire)


def _block(x, lyr, cfg: TransformerConfig, wire):
    """One transformer block (ring attention over sp, tp partial-sum
    reductions through the framework ring). RoPE positions are global:
    each sp shard offsets by its rank."""
    h = _rmsnorm(x, lyr["ln1"])
    T = h.shape[1]
    pos = lax.axis_index("sp") * T + jnp.arange(T)
    q, k, v = _qkv(h, lyr, cfg, pos)
    attn = ring_attention(q, k, v, axis_name="sp", causal=True)
    o_partial = jnp.einsum("bthk,hkd->btd", attn, lyr["wo"])
    # heads are sharded over tp: partial sums reduce on-device-ring
    x = x + _tp_allreduce(o_partial, wire)
    return _mlp_half(x, lyr, wire)


def _block_fn(cfg: TransformerConfig, wire, remat: bool):
    """The per-layer body, optionally rematerialized: jax.checkpoint drops
    the block's activations (attention scores, MLP hidden) in the forward
    pass and recomputes them — including the ring/tp collectives — during
    the backward, trading FLOPs for HBM (the long-context lever on TPU)."""
    fn = lambda x, lyr: _block(x, lyr, cfg, wire)  # noqa: E731
    return jax.checkpoint(fn) if remat else fn


def _forward_local(params, tokens, cfg: TransformerConfig, wire,
                   remat: bool = False):
    """Per-device forward: tokens (B_local, T_local) -> logits. Runs inside
    shard_map; heads are the tp-local slice, sequence the sp-local shard."""
    blk = _block_fn(cfg, wire, remat)
    x = params["embed"][tokens]  # (B, T, Dm)
    for lyr in params["layers"]:
        x = blk(x, lyr)
    x = _rmsnorm(x, jnp.ones((cfg.d_model,), x.dtype))
    return jnp.einsum("btd,dv->btv", x, params["unembed"])


def _forward_local_pp(params, tokens, cfg: TransformerConfig, wire,
                      n_microbatches: int, remat: bool = False):
    """Pipelined per-device forward: params["layers"] leaves arrive as the
    pp-local (L_local, ...) stage slice; microbatches flow through the
    GPipe schedule (parallel/pipeline.py) with each stage scanning its
    local layers, and the last stage's activations come back replicated
    for the (pp-replicated) unembed projection."""
    from ..parallel.pipeline import gpipe_schedule

    x = params["embed"][tokens]  # (B, T, Dm)
    B = x.shape[0]
    M = n_microbatches
    assert B % M == 0, (B, M)
    mb = x.reshape((M, B // M) + x.shape[1:])

    blk = _block_fn(cfg, wire, remat)

    def stage(h):
        def one_layer(carry, lyr):
            return blk(carry, lyr), None

        h, _ = lax.scan(one_layer, h, params["layers"])
        return h

    out = gpipe_schedule(mb, stage, axis="pp", world=lax.axis_size("pp"),
                         wire=wire)
    x = out.reshape(x.shape)
    x = _rmsnorm(x, jnp.ones((cfg.d_model,), x.dtype))
    return jnp.einsum("btd,dv->btv", x, params["unembed"])


def make_forward(cfg: TransformerConfig, mesh: Mesh,
                 n_microbatches: int | None = None):
    """Jitted SPMD forward: tokens (B, T) -> logits, batch over dp,
    sequence over sp, heads over tp; with a `pp` mesh axis the layer
    stack pipelines over it (params in the stacked form, see
    stack_layer_params)."""
    wire = schedules.Wire(None)
    pp = _pp_world(mesh)

    if pp > 1:
        M = n_microbatches or pp
        pspecs = pp_param_specs(cfg)

        def body(params, tokens):
            return _forward_local_pp(params, tokens, cfg, wire, M)
    else:
        pspecs = param_specs(cfg)

        def body(params, tokens):
            return _forward_local(params, tokens, cfg, wire)

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(pspecs, P("dp", "sp")),
            out_specs=P("dp", "sp"),
            check_vma=False,
        )
    )


# KV-cache layout: (batch over dp, seq, heads over tp, head_dim) — ONE
# constant shared by allocation and the decode step's shard_map specs
_KV_SPEC = P("dp", None, "tp", None)


def init_kv_cache(cfg: TransformerConfig, mesh: Mesh, batch: int,
                  max_len: int):
    """Per-layer KV cache for incremental decode, sharded batch over dp
    and heads over tp (the sequence dim is NOT sharded: decode emits one
    token at a time, so sp must be 1 on the decode mesh)."""
    dt = jnp.dtype(cfg.dtype)
    sh = NamedSharding(mesh, _KV_SPEC)
    # kv_heads (not n_heads): under GQA the cache is the grouped slice —
    # the inference memory saving that motivates grouped-query attention
    shape = (batch, max_len, cfg.kv_heads, cfg.head_dim)
    return [
        {"k": jax.device_put(jnp.zeros(shape, dt), sh),
         "v": jax.device_put(jnp.zeros(shape, dt), sh)}
        for _ in range(cfg.n_layers)
    ]


def _decode_block(x, lyr, cfg, ck, cv, pos, wire):
    """One block for a single new token position: append this position's
    (rotated, grouped) k/v to the cache and attend over cache[:pos+1]
    (masked full-length dot — static shapes, so one compiled program
    serves every step). The cache holds kv_heads; query heads index their
    group's slice at attention time."""
    h = _rmsnorm(x, lyr["ln1"])
    q = jnp.einsum("btd,dhk->bthk", h, lyr["wq"])
    kv = jnp.einsum("btd,dchk->btchk", h, lyr["wkv"])
    k_new, v_new = kv[:, :, 0], kv[:, :, 1]
    if cfg.rope:
        p1 = pos[None]  # (1,) absolute position of this token
        q = _rope(q, p1, cfg.rope_theta)
        k_new = _rope(k_new, p1, cfg.rope_theta)
    ck = lax.dynamic_update_slice_in_dim(ck, k_new, pos, axis=1)
    cv = lax.dynamic_update_slice_in_dim(cv, v_new, pos, axis=1)
    groups = cfg.n_heads // cfg.kv_heads
    # (B, 1, Hkv, G, hd) x (B, T, Hkv, hd) -> (B, Hkv, G, T); mask j > pos
    qg = q.reshape(q.shape[0], 1, -1, groups, q.shape[-1])
    scores = jnp.einsum("bqhgk,bthk->bhgt", qg, ck) / np.sqrt(q.shape[-1])
    mask = jnp.arange(ck.shape[1])[None, None, None, :] > pos
    scores = jnp.where(mask, -jnp.inf, scores.astype(jnp.float32))
    attn = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
    ctx = jnp.einsum("bhgt,bthk->bhgk", attn, cv)  # (B, Hkv, G, hd)
    ctx = ctx.reshape(ctx.shape[0], 1, -1, ctx.shape[-1])  # (B, 1, H, hd)
    o_partial = jnp.einsum("bthk,hkd->btd", ctx, lyr["wo"])
    x = x + _tp_allreduce(o_partial, wire)
    return _mlp_half(x, lyr, wire), ck, cv


def make_decode_step(cfg: TransformerConfig, mesh: Mesh):
    """One compiled incremental-decode step (the inference half of the
    model family): (params, cache, tokens (B, 1), pos) ->
    (logits (B, 1, V), cache). Batch over dp, heads + ffn over tp —
    the same tp partial-sum reductions as training, through the
    framework's ring schedule. sp/pp must be 1 on the decode mesh
    (decode is one position; pipeline decode would bubble every step).
    The cache threads through functionally — donate it at the call site
    for in-place updates."""
    for ax in ("sp", "pp"):
        if dict(mesh.shape).get(ax, 1) != 1:
            raise ValueError(f"decode mesh must have {ax}=1")
    wire = schedules.Wire(None)
    pspecs = param_specs(cfg)
    cache_spec = [{"k": _KV_SPEC, "v": _KV_SPEC}] * cfg.n_layers

    def body(params, cache, tokens, pos):
        x = params["embed"][tokens[:, :1]]
        p = pos[0]  # replicated scalar arrives as a (1,) shard
        new_cache = []
        for lyr, c in zip(params["layers"], cache):
            x, ck, cv = _decode_block(x, lyr, cfg, c["k"], c["v"], p, wire)
            new_cache.append({"k": ck, "v": cv})
        x = _rmsnorm(x, jnp.ones((cfg.d_model,), x.dtype))
        logits = jnp.einsum("btd,dv->btv", x, params["unembed"])
        return logits, new_cache

    step = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pspecs, cache_spec, P("dp", None), P()),
        out_specs=(P("dp", None), cache_spec),
        check_vma=False,
    )
    return jax.jit(step, donate_argnums=(1,))


def make_train_step(cfg: TransformerConfig, mesh: Mesh, lr: float = 1e-3,
                    n_microbatches: int | None = None, remat: bool = False):
    """One compiled SGD step: forward + backward + grad sync + update, all
    inside a single shard_map program (host-only-dispatches). With a `pp`
    mesh axis the layers pipeline over it (GPipe microbatches) and params
    take the stacked form from stack_layer_params/pp_param_specs.
    remat=True rematerializes each block in the backward pass
    (jax.checkpoint), cutting peak activation memory from O(layers) to
    O(1) blocks at ~1/3 extra FLOPs — the standard long-context tradeoff."""
    wire = schedules.Wire(None)
    pp = _pp_world(mesh)
    M = (n_microbatches or pp) if pp > 1 else 1
    pspecs = pp_param_specs(cfg) if pp > 1 else param_specs(cfg)

    def loss_fn(params, tokens, targets):
        if pp > 1:
            logits = _forward_local_pp(params, tokens, cfg, wire, M,
                                       remat=remat)
        else:
            logits = _forward_local(params, tokens, cfg, wire, remat=remat)
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, -1)
        nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
        return nll.mean()

    def body(params, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)

        tp_world = lax.axis_size("tp")

        def sync(g, spec):
            # every param (tp-sharded or replicated) saw only its dp batch
            # shard and sp sequence shard: mean-reduce over both axes.
            g = _grad_allreduce(g, "dp", wire)
            g = _grad_allreduce(g, "sp", wire)
            if tp_world > 1:
                # The ring-allreduce transpose is itself an allreduce, so a
                # replicated cotangent entering a tp branch comes back
                # amplified by tp: tp-sharded weight grads are tp x the true
                # value (rescale), while tp-replicated params see only their
                # rank's head/ff-slice contribution (mean-allreduce over tp
                # restores the full gradient — sum of slices / tp x tp).
                if _spec_has_axis(spec, "tp"):
                    g = g / tp_world
                else:
                    g = _grad_allreduce(g, "tp", wire)
            return g

        grads = jax.tree.map(sync, grads, pspecs)
        if pp > 1:
            # the pipeline injects microbatches only on pp rank 0, so the
            # embed cotangent lands entirely on rank 0 (zeros elsewhere):
            # SUM-allreduce over pp replicates the full gradient. unembed
            # applies after the replicated pipeline output, so its grad is
            # already identical on every pp rank; stage (pp-sharded)
            # leaves are stage-local by construction.
            e = grads["embed"]
            esum = schedules.allreduce_ring_schedule(
                e.reshape(-1), func=ReduceFunction.SUM, axis="pp",
                world=lax.axis_size("pp"), wire=wire, seg_count=e.size,
            )
            grads = {**grads, "embed": esum.reshape(e.shape)}
        new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)
        for ax in ("dp", "sp"):
            loss = schedules.allreduce_ring_schedule(
                loss[None], func=ReduceFunction.SUM, axis=ax,
                world=lax.axis_size(ax), wire=wire, seg_count=1,
            )[0] / lax.axis_size(ax)
        return new_params, loss

    step = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pspecs, P("dp", "sp"), P("dp", "sp")),
        out_specs=(pspecs, P()),
        check_vma=False,
    )
    return jax.jit(step)


def shard_params(params, cfg, mesh):
    """Place a global parameter pytree according to param_specs; on a mesh
    with a pp axis the layer list is first stacked (stack_layer_params)
    and the layer dim sharded over pp."""
    if _pp_world(mesh) > 1:
        if cfg.n_layers % _pp_world(mesh):
            raise ValueError(
                f"n_layers {cfg.n_layers} must divide over pp "
                f"{_pp_world(mesh)}")
        params = stack_layer_params(params)
        specs = pp_param_specs(cfg)
    else:
        specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def demo_batch(cfg, mesh, batch=4, seq=64, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab, (batch, seq)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1)
    sh = NamedSharding(mesh, P("dp", "sp"))
    return jax.device_put(tokens, sh), jax.device_put(targets, sh)
