"""Flagship demo: a TP x SP x DP transformer LM on the framework.

The model is deliberately the vadd_put pattern (reference
kernels/plugins/vadd_put/vadd_put.cpp:25-87 — device compute pushing
straight into a collective with no host round-trip) at training scale:
one shard_map program contains the forward, the ring-attention sequence
parallelism, the tensor-parallel partial-sum reductions, the backward,
and the data-parallel gradient sync — every cross-device byte moves
through the framework's own schedule bodies (sequencer/schedules.py),
and the host only dispatches the step.

Sharding layout over mesh axes (dp, sp, tp):
  - batch over dp, sequence over sp (ring attention handles cross-shard
    attention), attention heads + mlp hidden over tp;
  - parameters: qkv/o and mlp weights sharded over tp, embeddings
    replicated;
  - gradients: allreduced over dp and sp with the framework's ring
    schedule (eager segmented ring, the ACCL hot path).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..constants import ReduceFunction
from ..sequencer import schedules
from ..parallel.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 256
    dtype: str = "float32"
    # grouped-query attention: kv heads < query heads shrink the KV cache
    # (the decode-path memory lever) and the ring-attention wire bytes;
    # None = multi-head (kv_heads == n_heads)
    n_kv_heads: int | None = None
    # rotary position embeddings; positions are GLOBAL under sequence
    # parallelism (each sp shard offsets by its rank)
    rope: bool = True
    rope_theta: float = 10000.0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        kv = self.n_kv_heads or self.n_heads
        assert self.n_heads % kv == 0, (self.n_heads, kv)
        return kv


def init_params(cfg: TransformerConfig, key) -> dict:
    """Global (unsharded) parameter pytree; shard with shard_params."""
    keys = jax.random.split(key, 2 + cfg.n_layers)
    dt = jnp.dtype(cfg.dtype)
    scale = 0.02

    def dense(k, shape):
        return (jax.random.normal(k, shape) * scale).astype(dt)

    params = {
        "embed": dense(keys[0], (cfg.vocab, cfg.d_model)),
        "unembed": dense(keys[1], (cfg.d_model, cfg.vocab)),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[2 + i], 6)
        params["layers"].append(
            {
                "wq": dense(k[0], (cfg.d_model, cfg.n_heads, cfg.head_dim)),
                "wkv": dense(k[4], (cfg.d_model, 2, cfg.kv_heads,
                                    cfg.head_dim)),
                "wo": dense(k[1], (cfg.n_heads, cfg.head_dim, cfg.d_model)),
                "w_up": dense(k[2], (cfg.d_model, cfg.d_ff)),
                "w_down": dense(k[3], (cfg.d_ff, cfg.d_model)),
                "ln1": jnp.ones((cfg.d_model,), dt),
                "ln2": jnp.ones((cfg.d_model,), dt),
            }
        )
    return params


def param_specs(cfg: TransformerConfig) -> dict:
    """PartitionSpecs: tp shards heads/ff, everything else replicated."""
    layer = {
        "wq": P(None, "tp", None),
        "wkv": P(None, None, "tp", None),
        "wo": P("tp", None, None),
        "w_up": P(None, "tp"),
        "w_down": P("tp", None),
        "ln1": P(),
        "ln2": P(),
    }
    return {
        "embed": P(),
        "unembed": P(),
        "layers": [layer] * cfg.n_layers,
    }


def stack_layer_params(params) -> dict:
    """Convert the per-layer parameter list into stacked (n_layers, ...)
    leaves so the layer dim can shard over a `pp` mesh axis (stage i =
    layers [i*L/P, (i+1)*L/P))."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params["layers"])
    return {"embed": params["embed"], "unembed": params["unembed"],
            "layers": stacked}


def unstack_layer_params(params, n_layers: int) -> dict:
    """Inverse of stack_layer_params: stacked (n_layers, ...) leaves back
    to the per-layer list form (checkpoint interop across mesh shapes)."""
    layers = [jax.tree.map(lambda x: x[i], params["layers"])
              for i in range(n_layers)]
    return {"embed": params["embed"], "unembed": params["unembed"],
            "layers": layers}


def pp_param_specs(cfg: TransformerConfig) -> dict:
    """PartitionSpecs for the stacked form: layer dim over pp, head/ff
    dims over tp as in param_specs, embeddings replicated."""
    layer = param_specs(cfg)["layers"][0]
    return {
        "embed": P(),
        "unembed": P(),
        "layers": {k: P("pp", *s) for k, s in layer.items()},
    }


def _pp_world(mesh: Mesh) -> int:
    return dict(mesh.shape).get("pp", 1)


def _spec_has_axis(spec, axis: str) -> bool:
    """True if a PartitionSpec shards any dimension over `axis`."""
    for part in spec:
        if part is None:
            continue
        parts = part if isinstance(part, tuple) else (part,)
        if axis in parts:
            return True
    return False


def _rmsnorm(x, g):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * g


def _rope(x, pos, theta: float):
    """Rotate (B, T, H, D) by absolute positions `pos` (T,) — rotary
    embeddings in fp32, half-split form. Positions must be GLOBAL: under
    sequence parallelism the caller offsets by its sp shard."""
    D = x.shape[-1]
    assert D % 2 == 0, "rope needs an even head_dim"
    half = D // 2
    inv_freq = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, None] * inv_freq[None, :]  # (T, half)
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1).astype(x.dtype)


def _qkv(h, lyr, cfg: TransformerConfig, pos):
    """Project q / k / v with grouped-query layout and rotate q,k by the
    global positions `pos`. k/v stay at kv_heads (GQA): ring_attention
    attends grouped natively, so each sp ring hop carries the Hkv slice —
    a kv_heads/n_heads wire-byte saving per hop. Head dims are tp-LOCAL
    here, and H_local / Hkv_local == n_heads / kv_heads on every shard
    (tp must divide kv_heads)."""
    q = jnp.einsum("btd,dhk->bthk", h, lyr["wq"])
    kv = jnp.einsum("btd,dchk->btchk", h, lyr["wkv"])
    k, v = kv[:, :, 0], kv[:, :, 1]
    if cfg.rope:
        q = _rope(q, pos, cfg.rope_theta)
        k = _rope(k, pos, cfg.rope_theta)
    return q, k, v


def _tp_allreduce(x, wire, axis: str | None = "tp"):
    """Tensor-parallel partial-sum reduction through the framework's ring
    reduce-scatter + allgather schedule (the ACCL eager allreduce).
    axis=None is the single-shard degenerate (no tp axis in the mesh —
    the facade train step's data-parallel body): identity."""
    if axis is None:
        return x
    shape = x.shape
    flat = x.reshape(-1)
    out = schedules.allreduce_ring_schedule(
        flat,
        func=ReduceFunction.SUM,
        axis=axis,
        world=lax.axis_size(axis),
        wire=wire,
        seg_count=flat.shape[0],
    )
    return out.reshape(shape)


def _grad_allreduce(g, axis, wire):
    world = lax.axis_size(axis)
    if world == 1:
        return g
    shape = g.shape
    out = schedules.allreduce_ring_schedule(
        g.reshape(-1),
        func=ReduceFunction.SUM,
        axis=axis,
        world=world,
        wire=wire,
        seg_count=g.size,
    )
    return out.reshape(shape) / world  # mean over replicas


def _local_attention(q, k, v):
    """Plain causal attention over a fully-local sequence — the
    sp-axis-free degenerate of ring attention, grouped-query aware
    (the facade train step's body runs it: its mesh has only the
    collective axis, so the sequence is never sharded)."""
    B, T, H, Dh = q.shape
    kv_heads = k.shape[2]
    groups = H // kv_heads
    qg = q.reshape(B, T, kv_heads, groups, Dh)
    s = jnp.einsum("bthgk,bshk->bhgts", qg, k).astype(jnp.float32)
    s = s / np.sqrt(Dh)
    mask = jnp.tril(jnp.ones((T, T), bool))
    s = jnp.where(mask[None, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bhgts,bshk->bthgk", p.astype(v.dtype), v)
    return ctx.reshape(B, T, H, Dh)


# per-layer leaf order of the flat gradient/parameter vector, REVERSE
# backward-materialization order within a block: the backward produces
# the MLP's grads before the attention's, so the flat layout (unembed,
# layers N-1..0 each in this order, embed) puts the earliest-available
# gradients first — stripe 0 of an overlapped sync is ready while the
# rest of the backward still computes
_LAYER_BWD_ORDER = ("w_down", "w_up", "ln2", "wo", "wkv", "wq", "ln1")


def _backward_ordered_leaves(tree: dict) -> list:
    """The parameter/gradient leaves of the (pp=1) transformer pytree in
    backward-materialization order (see _LAYER_BWD_ORDER)."""
    leaves = [tree["unembed"]]
    for lyr in reversed(tree["layers"]):
        leaves.extend(lyr[k] for k in _LAYER_BWD_ORDER)
    leaves.append(tree["embed"])
    return leaves


def _striped_grad_sync(grads: dict, pspecs: dict, wire,
                       stripes: int, serial: bool):
    """Bucketed gradient sync, the stripe-overlapped form: per-leaf tp
    treatment first (the rescale-vs-allreduce logic is per spec), then
    ONE flat dp+sp mean-allreduce over the concatenated gradient
    vector split into `stripes` independent stripe chains. Leaves
    concatenate in backward-materialization order, and each stripe's
    ring chains depend only on its own leaves (XLA's slice-of-concat
    simplification restores the fine-grained dependence), so stripe
    i's allreduce runs while stripe i+1's gradients materialize in the
    backward. serial=True is the dispatch->compute twin: stripe 0 is
    order-barriered on the WHOLE gradient vector and each later stripe
    on its predecessor's output — bitwise-identical (barriers change
    scheduling, never values), measured as the A/B baseline."""
    tp_world = lax.axis_size("tp")

    def tp_fix(g, spec):
        if tp_world > 1:
            if _spec_has_axis(spec, "tp"):
                return g / tp_world
            return _grad_allreduce(g, "tp", wire)
        return g

    grads = jax.tree.map(tp_fix, grads, pspecs)
    leaves = _backward_ordered_leaves(grads)
    shapes = [g.shape for g in leaves]
    flat = jnp.concatenate([g.reshape(-1) for g in leaves])
    n = flat.shape[-1]
    per = -(-n // max(stripes, 1))
    outs = []
    prev = None
    for s in range(max(stripes, 1)):
        lo = s * per
        if lo >= n:
            break
        seg = flat[lo:min(lo + per, n)]
        if serial:
            seg = schedules._ordered_after(
                seg, flat if prev is None else prev)
        for ax in ("dp", "sp"):
            world = lax.axis_size(ax)
            if world == 1:
                continue
            seg = schedules.allreduce_ring_schedule(
                seg, func=ReduceFunction.SUM, axis=ax, world=world,
                wire=wire, seg_count=seg.shape[-1],
            ) / world
        outs.append(seg)
        prev = outs[-1]
    flat = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
    parts = []
    off = 0
    for sh in shapes:
        size = int(np.prod(sh)) if sh else 1
        parts.append(flat[off:off + size].reshape(sh))
        off += size
    out = {"unembed": parts[0], "embed": parts[-1], "layers": []}
    idx = 1
    rev_layers = []
    for _ in grads["layers"]:
        lyr = {}
        for k in _LAYER_BWD_ORDER:
            lyr[k] = parts[idx]
            idx += 1
        rev_layers.append(lyr)
    out["layers"] = list(reversed(rev_layers))
    return out


def _mlp_half(x, lyr, wire, tp_axis: str | None = "tp"):
    """ln2 + gelu MLP + tp partial-sum residual — shared by the training
    block and the decode block so the two cannot silently diverge."""
    h = _rmsnorm(x, lyr["ln2"])
    up = jax.nn.gelu(jnp.einsum("btd,df->btf", h, lyr["w_up"]))
    down_partial = jnp.einsum("btf,fd->btd", up, lyr["w_down"])
    return x + _tp_allreduce(down_partial, wire, tp_axis)


def _block(x, lyr, cfg: TransformerConfig, wire,
           tp_axis: str | None = "tp", sp_axis: str | None = "sp"):
    """One transformer block (ring attention over sp, tp partial-sum
    reductions through the framework ring). RoPE positions are global:
    each sp shard offsets by its rank. tp_axis/sp_axis None run the
    axis-free degenerates (local causal attention, identity partial
    sum) — the SAME block serving the facade train step's
    data-parallel body, so the two model forms cannot diverge."""
    h = _rmsnorm(x, lyr["ln1"])
    T = h.shape[1]
    if sp_axis is None:
        pos = jnp.arange(T)
    else:
        pos = lax.axis_index(sp_axis) * T + jnp.arange(T)
    q, k, v = _qkv(h, lyr, cfg, pos)
    if sp_axis is None:
        attn = _local_attention(q, k, v)
    else:
        attn = ring_attention(q, k, v, axis_name=sp_axis, causal=True)
    o_partial = jnp.einsum("bthk,hkd->btd", attn, lyr["wo"])
    # heads are sharded over tp: partial sums reduce on-device-ring
    x = x + _tp_allreduce(o_partial, wire, tp_axis)
    return _mlp_half(x, lyr, wire, tp_axis)


def _block_fn(cfg: TransformerConfig, wire, remat: bool):
    """The per-layer body, optionally rematerialized: jax.checkpoint drops
    the block's activations (attention scores, MLP hidden) in the forward
    pass and recomputes them — including the ring/tp collectives — during
    the backward, trading FLOPs for HBM (the long-context lever on TPU)."""
    fn = lambda x, lyr: _block(x, lyr, cfg, wire)  # noqa: E731
    return jax.checkpoint(fn) if remat else fn


def _forward_local(params, tokens, cfg: TransformerConfig, wire,
                   remat: bool = False):
    """Per-device forward: tokens (B_local, T_local) -> logits. Runs inside
    shard_map; heads are the tp-local slice, sequence the sp-local shard."""
    blk = _block_fn(cfg, wire, remat)
    x = params["embed"][tokens]  # (B, T, Dm)
    for lyr in params["layers"]:
        x = blk(x, lyr)
    x = _rmsnorm(x, jnp.ones((cfg.d_model,), x.dtype))
    return jnp.einsum("btd,dv->btv", x, params["unembed"])


def _forward_local_pp(params, tokens, cfg: TransformerConfig, wire,
                      n_microbatches: int, remat: bool = False):
    """Pipelined per-device forward: params["layers"] leaves arrive as the
    pp-local (L_local, ...) stage slice; microbatches flow through the
    GPipe schedule (parallel/pipeline.py) with each stage scanning its
    local layers, and the last stage's activations come back replicated
    for the (pp-replicated) unembed projection."""
    from ..parallel.pipeline import gpipe_schedule

    x = params["embed"][tokens]  # (B, T, Dm)
    B = x.shape[0]
    M = n_microbatches
    assert B % M == 0, (B, M)
    mb = x.reshape((M, B // M) + x.shape[1:])

    blk = _block_fn(cfg, wire, remat)

    def stage(h):
        def one_layer(carry, lyr):
            return blk(carry, lyr), None

        h, _ = lax.scan(one_layer, h, params["layers"])
        return h

    out = gpipe_schedule(mb, stage, axis="pp", world=lax.axis_size("pp"),
                         wire=wire)
    x = out.reshape(x.shape)
    x = _rmsnorm(x, jnp.ones((cfg.d_model,), x.dtype))
    return jnp.einsum("btd,dv->btv", x, params["unembed"])


def make_forward(cfg: TransformerConfig, mesh: Mesh,
                 n_microbatches: int | None = None):
    """Jitted SPMD forward: tokens (B, T) -> logits, batch over dp,
    sequence over sp, heads over tp; with a `pp` mesh axis the layer
    stack pipelines over it (params in the stacked form, see
    stack_layer_params)."""
    wire = schedules.Wire(None)
    pp = _pp_world(mesh)

    if pp > 1:
        M = n_microbatches or pp
        pspecs = pp_param_specs(cfg)

        def body(params, tokens):
            return _forward_local_pp(params, tokens, cfg, wire, M)
    else:
        pspecs = param_specs(cfg)

        def body(params, tokens):
            return _forward_local(params, tokens, cfg, wire)

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(pspecs, P("dp", "sp")),
            out_specs=P("dp", "sp"),
            check_vma=False,
        )
    )


# KV-cache layout: (batch over dp, seq, heads over tp, head_dim) — ONE
# constant shared by allocation and the decode step's shard_map specs
_KV_SPEC = P("dp", None, "tp", None)


def init_kv_cache(cfg: TransformerConfig, mesh: Mesh, batch: int,
                  max_len: int):
    """Per-layer KV cache for incremental decode, sharded batch over dp
    and heads over tp (the sequence dim is NOT sharded: decode emits one
    token at a time, so sp must be 1 on the decode mesh)."""
    dt = jnp.dtype(cfg.dtype)
    sh = NamedSharding(mesh, _KV_SPEC)
    # kv_heads (not n_heads): under GQA the cache is the grouped slice —
    # the inference memory saving that motivates grouped-query attention
    shape = (batch, max_len, cfg.kv_heads, cfg.head_dim)
    return [
        {"k": jax.device_put(jnp.zeros(shape, dt), sh),
         "v": jax.device_put(jnp.zeros(shape, dt), sh)}
        for _ in range(cfg.n_layers)
    ]


def _decode_block(x, lyr, cfg, ck, cv, pos, wire):
    """One block for a single new token position: append this position's
    (rotated, grouped) k/v to the cache and attend over cache[:pos+1]
    (masked full-length dot — static shapes, so one compiled program
    serves every step). The cache holds kv_heads; query heads index their
    group's slice at attention time."""
    h = _rmsnorm(x, lyr["ln1"])
    q = jnp.einsum("btd,dhk->bthk", h, lyr["wq"])
    kv = jnp.einsum("btd,dchk->btchk", h, lyr["wkv"])
    k_new, v_new = kv[:, :, 0], kv[:, :, 1]
    if cfg.rope:
        p1 = pos[None]  # (1,) absolute position of this token
        q = _rope(q, p1, cfg.rope_theta)
        k_new = _rope(k_new, p1, cfg.rope_theta)
    ck = lax.dynamic_update_slice_in_dim(ck, k_new, pos, axis=1)
    cv = lax.dynamic_update_slice_in_dim(cv, v_new, pos, axis=1)
    groups = cfg.n_heads // cfg.kv_heads
    # (B, 1, Hkv, G, hd) x (B, T, Hkv, hd) -> (B, Hkv, G, T); mask j > pos
    qg = q.reshape(q.shape[0], 1, -1, groups, q.shape[-1])
    scores = jnp.einsum("bqhgk,bthk->bhgt", qg, ck) / np.sqrt(q.shape[-1])
    mask = jnp.arange(ck.shape[1])[None, None, None, :] > pos
    scores = jnp.where(mask, -jnp.inf, scores.astype(jnp.float32))
    attn = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
    ctx = jnp.einsum("bhgt,bthk->bhgk", attn, cv)  # (B, Hkv, G, hd)
    ctx = ctx.reshape(ctx.shape[0], 1, -1, ctx.shape[-1])  # (B, 1, H, hd)
    o_partial = jnp.einsum("bthk,hkd->btd", ctx, lyr["wo"])
    x = x + _tp_allreduce(o_partial, wire)
    return _mlp_half(x, lyr, wire), ck, cv


def make_decode_step(cfg: TransformerConfig, mesh: Mesh):
    """One compiled incremental-decode step (the inference half of the
    model family): (params, cache, tokens (B, 1), pos) ->
    (logits (B, 1, V), cache). Batch over dp, heads + ffn over tp —
    the same tp partial-sum reductions as training, through the
    framework's ring schedule. sp/pp must be 1 on the decode mesh
    (decode is one position; pipeline decode would bubble every step).
    The cache threads through functionally — donate it at the call site
    for in-place updates."""
    for ax in ("sp", "pp"):
        if dict(mesh.shape).get(ax, 1) != 1:
            raise ValueError(f"decode mesh must have {ax}=1")
    wire = schedules.Wire(None)
    pspecs = param_specs(cfg)
    cache_spec = [{"k": _KV_SPEC, "v": _KV_SPEC}] * cfg.n_layers

    def body(params, cache, tokens, pos):
        x = params["embed"][tokens[:, :1]]
        p = pos[0]  # replicated scalar arrives as a (1,) shard
        new_cache = []
        for lyr, c in zip(params["layers"], cache):
            x, ck, cv = _decode_block(x, lyr, cfg, c["k"], c["v"], p, wire)
            new_cache.append({"k": ck, "v": cv})
        x = _rmsnorm(x, jnp.ones((cfg.d_model,), x.dtype))
        logits = jnp.einsum("btd,dv->btv", x, params["unembed"])
        return logits, new_cache

    step = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pspecs, cache_spec, P("dp", None), P()),
        out_specs=(P("dp", None), cache_spec),
        check_vma=False,
    )
    return jax.jit(step, donate_argnums=(1,))


def make_train_step(cfg: TransformerConfig, mesh: Mesh, lr: float = 1e-3,
                    n_microbatches: int | None = None, remat: bool = False,
                    grad_sync: str = "leaf",
                    grad_stripes: int | None = None):
    """One compiled SGD step: forward + backward + grad sync + update, all
    inside a single shard_map program (host-only-dispatches). With a `pp`
    mesh axis the layers pipeline over it (GPipe microbatches) and params
    take the stacked form from stack_layer_params/pp_param_specs.
    remat=True rematerializes each block in the backward pass
    (jax.checkpoint), cutting peak activation memory from O(layers) to
    O(1) blocks at ~1/3 extra FLOPs — the standard long-context tradeoff.

    grad_sync picks the dp/sp gradient-sync shape: "leaf" (default, the
    original per-leaf allreduces), "striped" (bucketed: one flat
    backward-ordered gradient vector allreduced as `grad_stripes`
    independent stripe chains the backward can overlap — see
    _striped_grad_sync), or "striped_serial" (the same stripes
    barrier-serialized after the full backward, the bitwise-identical
    dispatch->compute twin). grad_stripes=None derives the stripe
    count from the cost model's argmin under the shipped calibration
    (timing.best_overlap_stripes with the shaped link and the measured
    compute term — no calibration falls back to 1, never a made-up
    depth)."""
    if grad_sync not in ("leaf", "striped", "striped_serial"):
        raise ValueError(f"unknown grad_sync {grad_sync!r}")
    wire = schedules.Wire(None)
    pp = _pp_world(mesh)
    M = (n_microbatches or pp) if pp > 1 else 1
    pspecs = pp_param_specs(cfg) if pp > 1 else param_specs(cfg)
    if grad_sync != "leaf" and pp > 1:
        raise NotImplementedError(
            "striped grad sync covers the pp=1 layer-list form")
    if grad_sync != "leaf" and grad_stripes is None:
        from ..sequencer.timing import best_overlap_stripes
        from ..telemetry import feedback as _fb

        tl = _fb.default_tier_links()
        link = tl.outer if tl is not None else _fb.default_link()
        fit = _fb.default_compute_fit()
        grad_stripes = 1
        if link is not None and fit is not None:
            nbytes = train_param_count(cfg) * 4
            sync_world = max(dict(mesh.shape).get("dp", 1),
                             dict(mesh.shape).get("sp", 1))
            grad_stripes = best_overlap_stripes(
                link, nbytes // 4, 4, max(sync_world, 2),
                compute_s=fit.seconds(nbytes), rx_buf_bytes=1024)

    def loss_fn(params, tokens, targets):
        if pp > 1:
            logits = _forward_local_pp(params, tokens, cfg, wire, M,
                                       remat=remat)
        else:
            logits = _forward_local(params, tokens, cfg, wire, remat=remat)
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, -1)
        nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
        return nll.mean()

    def body(params, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)

        tp_world = lax.axis_size("tp")

        def sync(g, spec):
            # every param (tp-sharded or replicated) saw only its dp batch
            # shard and sp sequence shard: mean-reduce over both axes.
            g = _grad_allreduce(g, "dp", wire)
            g = _grad_allreduce(g, "sp", wire)
            if tp_world > 1:
                # The ring-allreduce transpose is itself an allreduce, so a
                # replicated cotangent entering a tp branch comes back
                # amplified by tp: tp-sharded weight grads are tp x the true
                # value (rescale), while tp-replicated params see only their
                # rank's head/ff-slice contribution (mean-allreduce over tp
                # restores the full gradient — sum of slices / tp x tp).
                if _spec_has_axis(spec, "tp"):
                    g = g / tp_world
                else:
                    g = _grad_allreduce(g, "tp", wire)
            return g

        if grad_sync == "leaf":
            grads = jax.tree.map(sync, grads, pspecs)
        else:
            grads = _striped_grad_sync(
                grads, pspecs, wire, stripes=int(grad_stripes or 1),
                serial=(grad_sync == "striped_serial"))
        if pp > 1:
            # the pipeline injects microbatches only on pp rank 0, so the
            # embed cotangent lands entirely on rank 0 (zeros elsewhere):
            # SUM-allreduce over pp replicates the full gradient. unembed
            # applies after the replicated pipeline output, so its grad is
            # already identical on every pp rank; stage (pp-sharded)
            # leaves are stage-local by construction.
            e = grads["embed"]
            esum = schedules.allreduce_ring_schedule(
                e.reshape(-1), func=ReduceFunction.SUM, axis="pp",
                world=lax.axis_size("pp"), wire=wire, seg_count=e.size,
            )
            grads = {**grads, "embed": esum.reshape(e.shape)}
        new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)
        for ax in ("dp", "sp"):
            loss = schedules.allreduce_ring_schedule(
                loss[None], func=ReduceFunction.SUM, axis=ax,
                world=lax.axis_size(ax), wire=wire, seg_count=1,
            )[0] / lax.axis_size(ax)
        return new_params, loss

    step = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pspecs, P("dp", "sp"), P("dp", "sp")),
        out_specs=(pspecs, P()),
        check_vma=False,
    )
    return jax.jit(step)


def shard_params(params, cfg, mesh):
    """Place a global parameter pytree according to param_specs; on a mesh
    with a pp axis the layer list is first stacked (stack_layer_params)
    and the layer dim sharded over pp."""
    if _pp_world(mesh) > 1:
        if cfg.n_layers % _pp_world(mesh):
            raise ValueError(
                f"n_layers {cfg.n_layers} must divide over pp "
                f"{_pp_world(mesh)}")
        params = stack_layer_params(params)
        specs = pp_param_specs(cfg)
    else:
        specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


# ---------------------------------------------------------------------------
# Device-resident train step: forward + backward + stripe-overlapped
# gradient allreduce + SGD update as ONE recorded descriptor batch
# (ROADMAP item 4's training-scale form of the stream-consumer seam)
# ---------------------------------------------------------------------------

# kernel-stream id the train step's fwd+bwd consumer registers under
# (one well-known default keeps bench, fuzz and tests on the endpoint)
TRAIN_GRAD_STREAM = 21


def _train_leaf_shapes(cfg: TransformerConfig) -> list:
    """Leaf shapes of the flat train-step parameter vector, in the
    backward-materialization order _backward_ordered_leaves uses
    (unembed, layers N-1..0 each per _LAYER_BWD_ORDER, embed)."""
    d, ff = cfg.d_model, cfg.d_ff
    layer = {
        "w_down": (ff, d), "w_up": (d, ff), "ln2": (d,),
        "wo": (cfg.n_heads, cfg.head_dim, d),
        "wkv": (d, 2, cfg.kv_heads, cfg.head_dim),
        "wq": (d, cfg.n_heads, cfg.head_dim), "ln1": (d,),
    }
    shapes: list = [(d, cfg.vocab)]  # unembed
    for _ in range(cfg.n_layers):
        shapes.extend(layer[k] for k in _LAYER_BWD_ORDER)
    shapes.append((cfg.vocab, d))  # embed
    return shapes


def train_param_count(cfg: TransformerConfig) -> int:
    """Element count of the flat train-step parameter vector — the
    `count` of every descriptor in the fused train-step batch (and the
    gradient bytes the overlap register compares, x4)."""
    return sum(int(np.prod(s)) for s in _train_leaf_shapes(cfg))


def flatten_train_params(params: dict):
    """Parameter/gradient pytree -> flat vector in backward order (the
    layout every train-step buffer uses; see _backward_ordered_leaves
    for why the order matters to the overlap)."""
    return jnp.concatenate(
        [g.reshape(-1) for g in _backward_ordered_leaves(params)])


def unflatten_train_params(flat, cfg: TransformerConfig) -> dict:
    """Inverse of flatten_train_params (traced-value friendly)."""
    shapes = _train_leaf_shapes(cfg)
    parts = []
    off = 0
    for sh in shapes:
        size = int(np.prod(sh))
        parts.append(flat[off:off + size].reshape(sh))
        off += size
    rev_layers = []
    idx = 1
    for _ in range(cfg.n_layers):
        lyr = {}
        for k in _LAYER_BWD_ORDER:
            lyr[k] = parts[idx]
            idx += 1
        rev_layers.append(lyr)
    return {"unembed": parts[0], "embed": parts[-1],
            "layers": list(reversed(rev_layers))}


def local_train_loss(params: dict, tokens, targets,
                     cfg: TransformerConfig):
    """Mean next-token NLL of the axis-free transformer forward — the
    SAME blocks as the sharded model (_block with tp_axis=sp_axis=None:
    local causal attention, identity partial sums), so the facade train
    step runs the real model, not a stand-in."""
    x = params["embed"][tokens]
    for lyr in params["layers"]:
        x = _block(x, lyr, cfg, schedules.Wire(None),
                   tp_axis=None, sp_axis=None)
    x = _rmsnorm(x, jnp.ones((cfg.d_model,), x.dtype))
    logits = jnp.einsum("btd,dv->btv", x, params["unembed"])
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    return nll.mean()


def make_grad_consumer(cfg: TransformerConfig, tokens, targets,
                       axis_name: str = "ccl", scale: float = 1.0):
    """The forward+backward as a RES_STREAM consumer: the copy step's
    result (this rank's flat parameter vector) runs the full local
    fwd+bwd over the rank's (batch-shard) tokens — selected by
    axis_index, so ONE traced callable serves every rank — and lands
    the flat gradient (backward order) in the result buffer. The
    tokens/targets close over the endpoint as program constants, like
    the MoE expert consumer's weights.

    `scale` folds into the differentiated loss (the backward's seed
    cotangent), so the consumer emits scale * grad directly. The train
    step passes -lr/world here: the dp mean and the SGD learning rate
    ride the backward, the allreduce SUMs per-rank update
    contributions, and the final combine is a pure add of two
    materialized values — no multiply ever feeds that add, so XLA
    cannot FMA-contract it differently in the fused program than in
    the eager twin (which is what keeps fused bitwise-identical to
    eager; a post-allreduce scale consumer provably broke it by an
    ULP)."""
    tok = jnp.asarray(tokens)
    tgt = jnp.asarray(targets)
    s = np.float32(scale)

    def consumer(params_flat):
        params = unflatten_train_params(
            params_flat.astype(jnp.float32), cfg)
        me = lax.axis_index(axis_name)
        t = lax.dynamic_index_in_dim(tok, me, axis=0, keepdims=False)
        g = lax.dynamic_index_in_dim(tgt, me, axis=0, keepdims=False)
        grads = jax.grad(
            lambda p: s * local_train_loss(p, t, g, cfg))(params)
        return flatten_train_params(grads).astype(params_flat.dtype)

    return consumer


def create_train_step_buffers(accl, cfg: TransformerConfig):
    """(params, grads, update, new_params) flat rank buffers for the
    fused train step, each (world, train_param_count) fp32."""
    n = train_param_count(cfg)
    return tuple(accl.create_buffer(n, np.float32) for _ in range(4))


def _register_train_consumers(accl, cfg: TransformerConfig, tokens,
                              targets, lr: float):
    # dp mean + SGD learning rate fold into the backward's seed
    # cotangent (see make_grad_consumer's scale note): each rank emits
    # its UPDATE contribution u_r = grad(-lr/world * loss_r), the
    # allreduce sums them, and the combine is a pure add
    accl.register_stream_consumer(
        TRAIN_GRAD_STREAM,
        make_grad_consumer(cfg, tokens, targets, accl.axis_name,
                           scale=-lr / accl.world))


def record_train_step(accl, cfg: TransformerConfig, tokens, targets, *,
                      lr: float = 1e-3, lint: str = "error",
                      buffers=None):
    """Record the data-parallel transformer train step as ONE
    descriptor batch over `accl`'s axis:

      1. copy(params -> grads) with the fwd+bwd spliced as its
         RES_STREAM consumer (the model compute IS in the program; the
         -lr/world update scale rides the backward seed);
      2. allreduce(grads -> update, SUM) — inside the
         OVERLAP_MIN_COUNT window this step's plan stripes into
         independent chains, and because the flat gradient is a
         backward-ordered concat whose slices simplify to the
         individual leaves, stripe i's ring chains depend only on
         stripe i's gradients: the wire runs while the rest of the
         backward materializes, in ONE jit(shard_map) program;
      3. combine(SUM, params, update -> new_params): the SGD step.

    Returns (recorder, buffers); `recorder.compile()` freezes it into
    the steady-state SequenceProgram (`make_train_step_program`), and
    the same three descriptors issued eagerly are the serial
    dispatch->compute twin (`run_train_step_eager`) — bitwise-identical
    at fp32, the measured A/B of bench --overlap-gate."""
    if buffers is None:
        buffers = create_train_step_buffers(accl, cfg)
    pbuf, gbuf, ubuf, obuf = buffers
    n = train_param_count(cfg)
    _register_train_consumers(accl, cfg, tokens, targets, lr)
    seq = accl.sequence(lint=lint)
    seq.copy(pbuf, gbuf, n, res_stream=TRAIN_GRAD_STREAM)
    seq.allreduce(gbuf, ubuf, n, ReduceFunction.SUM)
    seq.combine(n, ReduceFunction.SUM, pbuf, ubuf, obuf)
    return seq, buffers


def make_train_step_program(accl, cfg: TransformerConfig, tokens,
                            targets, *, lr: float = 1e-3,
                            lint: str = "error", buffers=None):
    """The steady-state fused train step: record once, compile once,
    dispatch ONE program per iteration (the SequenceProgram seam the
    MoE layer step rides). Returns (program, buffers); the caller's
    loop is `write pbuf -> program.run() -> read obuf`."""
    seq, buffers = record_train_step(accl, cfg, tokens, targets, lr=lr,
                                    lint=lint, buffers=buffers)
    return seq.compile(), buffers


def run_train_step_eager(accl, cfg: TransformerConfig, buffers):
    """The serial dispatch->compute twin: the SAME three descriptors
    the fused batch records, issued eagerly — the compute program
    completes before the allreduce program dispatches, and the stripe
    chains (same register-selected plan) run serialized when the
    compiler's overlap_serialize twin flag is set. Three dispatches,
    intermediates kept on-device (the baseline pays the dispatch
    seams, not artificial host round trips). Bitwise-identical to the
    fused overlapped program at fp32 (fuzz-pinned)."""
    pbuf, gbuf, ubuf, obuf = buffers
    n = train_param_count(cfg)
    accl.copy_to_stream(pbuf, n, res_stream=TRAIN_GRAD_STREAM,
                        dstbuf=gbuf, from_device=True, to_device=True)
    accl.allreduce(gbuf, ubuf, n, ReduceFunction.SUM, from_device=True,
                   to_device=True)
    accl.combine(n, ReduceFunction.SUM, pbuf, ubuf, obuf,
                 from_device=True, to_device=True)
    return accl._last_request


# ---------------------------------------------------------------------------
# Device-resident decode step: N layers of KV-cached single-token
# attention + MLP, each closed by a TP partial-sum allreduce, fused
# into ONE recorded descriptor batch (the record-once/dispatch-many
# seam serving interactive traffic — ROADMAP item 4's inference half)
# ---------------------------------------------------------------------------

# kernel-stream id base for the decode step's consumers: attention for
# layer l registers at base + 2l, its MLP at base + 2l + 1, and the
# final logits head at base + 2*n_layers (distinct from
# MOE_EXPERT_STREAM=11 and TRAIN_GRAD_STREAM=21)
DECODE_STREAM_BASE = 40


def decode_attn_stream(layer: int) -> int:
    return DECODE_STREAM_BASE + 2 * layer


def decode_mlp_stream(layer: int) -> int:
    return DECODE_STREAM_BASE + 2 * layer + 1


def decode_logits_stream(cfg: TransformerConfig) -> int:
    return DECODE_STREAM_BASE + 2 * cfg.n_layers


@dataclasses.dataclass(frozen=True)
class DecodeDims:
    """Flat-buffer geometry of the fused decode step. The facade world
    is the TENSOR-PARALLEL world: each rank's state buffer carries its
    kv-head slice of the cache, and the two allreduces per layer are
    the tp partial-sum reductions of the sharded model."""

    batch: int
    max_len: int
    d_model: int
    vocab: int
    heads_local: int
    kv_heads_local: int
    ff_local: int
    # [x (B*D) | pos (B) | k-cache | v-cache], per rank
    n_state: int
    # [x (B*D) | pos (B)] on the way in, logits (B*V) on the way out —
    # one width serves both, so the x/pos prefix survives in the tail
    n_out: int


def decode_dims(cfg: TransformerConfig, world: int, batch: int,
                max_len: int) -> DecodeDims:
    for name, dim in (("n_heads", cfg.n_heads),
                      ("kv_heads", cfg.kv_heads), ("d_ff", cfg.d_ff)):
        if dim % world:
            raise ValueError(
                f"decode facade world {world} must divide {name}={dim}")
    if jnp.dtype(cfg.dtype) != jnp.float32:
        raise ValueError("the fused decode step rides fp32 rank buffers")
    kvl = cfg.kv_heads // world
    b_d = batch * cfg.d_model
    return DecodeDims(
        batch=batch, max_len=max_len, d_model=cfg.d_model,
        vocab=cfg.vocab,
        heads_local=cfg.n_heads // world, kv_heads_local=kvl,
        ff_local=cfg.d_ff // world,
        n_state=b_d + batch + 2 * batch * max_len * kvl * cfg.head_dim,
        n_out=max(batch * cfg.vocab, b_d + batch),
    )


def _rope_slots(x, pos, theta: float):
    """Per-slot rotary: (B, 1, H, D) rotated by per-slot absolute
    positions `pos` (B,) — the batched-decode form of _rope (same fp32
    half-split math), one position per batch row instead of one shared
    (T,) vector, so concurrent requests at different depths share one
    compiled step."""
    D = x.shape[-1]
    assert D % 2 == 0, "rope needs an even head_dim"
    half = D // 2
    inv_freq = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, None] * inv_freq[None, :]  # (B, half)
    cos = jnp.cos(ang)[:, None, None, :]
    sin = jnp.sin(ang)[:, None, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1).astype(x.dtype)


def make_decode_attn_consumer(cfg: TransformerConfig, lyr: dict,
                              dims: DecodeDims, world: int,
                              axis_name: str = "ccl"):
    """Layer attention as a RES_STREAM consumer over the rank's flat
    state [x, pos, kv-cache]: rmsnorm + the rank's q/kv head slice
    (selected by axis_index, so ONE traced callable serves every rank),
    per-slot RoPE, per-slot cache append at pos, masked full-length
    grouped attention, and the rank's wo partial product — landing
    [o_partial, pos, new kv-cache] in the result buffer. The FULL layer
    weights close over the endpoint as program constants, like the
    train step's fwd+bwd consumer."""
    B, T, D = dims.batch, dims.max_len, dims.d_model
    hd = cfg.head_dim
    hl, kvl = dims.heads_local, dims.kv_heads_local
    groups = cfg.n_heads // cfg.kv_heads
    wq = jnp.asarray(lyr["wq"])
    wkv = jnp.asarray(lyr["wkv"])
    wo = jnp.asarray(lyr["wo"])
    ln1 = jnp.asarray(lyr["ln1"])

    def consumer(state):
        me = lax.axis_index(axis_name)
        x = state[:B * D].reshape(B, 1, D)
        pos = state[B * D:B * D + B].astype(jnp.int32)
        kv = state[B * D + B:].reshape(2, B, T, kvl, hd)
        ck, cv = kv[0], kv[1]
        wq_r = lax.dynamic_slice_in_dim(wq, me * hl, hl, axis=1)
        wkv_r = lax.dynamic_slice_in_dim(wkv, me * kvl, kvl, axis=2)
        wo_r = lax.dynamic_slice_in_dim(wo, me * hl, hl, axis=0)
        h = _rmsnorm(x, ln1)
        q = jnp.einsum("btd,dhk->bthk", h, wq_r)
        kvp = jnp.einsum("btd,dchk->btchk", h, wkv_r)
        k_new, v_new = kvp[:, :, 0], kvp[:, :, 1]
        if cfg.rope:
            q = _rope_slots(q, pos, cfg.rope_theta)
            k_new = _rope_slots(k_new, pos, cfg.rope_theta)
        upd = lambda c, n, p: lax.dynamic_update_slice_in_dim(  # noqa: E731
            c, n, p, axis=0)
        ck = jax.vmap(upd)(ck, k_new, pos)
        cv = jax.vmap(upd)(cv, v_new, pos)
        qg = q.reshape(B, 1, kvl, groups, hd)
        scores = jnp.einsum("bqhgk,bthk->bhgt", qg, ck) / np.sqrt(hd)
        mask = (jnp.arange(T)[None, None, None, :]
                > pos[:, None, None, None])
        scores = jnp.where(mask, -jnp.inf, scores.astype(jnp.float32))
        attn = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
        ctx = jnp.einsum("bhgt,bthk->bhgk", attn, cv)
        o_partial = jnp.einsum("bthk,hkd->btd",
                               ctx.reshape(B, 1, hl, hd), wo_r)
        return jnp.concatenate([
            o_partial.reshape(-1).astype(state.dtype),
            pos.astype(state.dtype),
            jnp.stack([ck, cv]).reshape(-1).astype(state.dtype),
        ])

    return consumer


def make_decode_mlp_consumer(cfg: TransformerConfig, lyr: dict,
                             dims: DecodeDims, world: int,
                             axis_name: str = "ccl"):
    """Layer MLP as a RES_STREAM consumer over the flat post-attention
    residual x2 (B*D): ln2 + the rank's gelu MLP ff slice — the same
    math as _mlp_half's local half, emitting the down-projection
    partial sum the next allreduce closes."""
    B, D = dims.batch, dims.d_model
    ffl = dims.ff_local
    w_up = jnp.asarray(lyr["w_up"])
    w_down = jnp.asarray(lyr["w_down"])
    ln2 = jnp.asarray(lyr["ln2"])

    def consumer(x2_flat):
        me = lax.axis_index(axis_name)
        x = x2_flat.reshape(B, 1, D)
        h = _rmsnorm(x, ln2)
        w_up_r = lax.dynamic_slice_in_dim(w_up, me * ffl, ffl, axis=1)
        w_down_r = lax.dynamic_slice_in_dim(w_down, me * ffl, ffl, axis=0)
        up = jax.nn.gelu(jnp.einsum("btd,df->btf", h, w_up_r))
        down_partial = jnp.einsum("btf,fd->btd", up, w_down_r)
        return down_partial.reshape(-1).astype(x2_flat.dtype)

    return consumer


def make_decode_logits_consumer(cfg: TransformerConfig, params: dict,
                                dims: DecodeDims):
    """Final rmsnorm + unembed projection over the last layer's
    residual prefix, zero-padded to the n_out row width (the replicated
    head: every rank computes identical logits, the host reads row 0)."""
    B, D, V = dims.batch, dims.d_model, dims.vocab
    n_out = dims.n_out
    unembed = jnp.asarray(params["unembed"])

    def consumer(xp):
        x = xp[:B * D].reshape(B, 1, D)
        x = _rmsnorm(x, jnp.ones((D,), x.dtype))
        logits = jnp.einsum("btd,dv->btv", x, unembed)
        flat = logits.reshape(-1).astype(xp.dtype)
        pad = n_out - B * V
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        return flat

    return consumer


@dataclasses.dataclass
class DecodeBuffers:
    """The fused decode step's rank buffers (each (world, n) fp32).
    `state[l]` persists layer l's kv cache across dispatches in its
    tail — only its [x, pos] prefix is re-staged per step — so the
    cache never crosses the host boundary in the steady state."""

    dims: DecodeDims
    xp: object  # [x, pos] in / logits landing width (n_out)
    logits: object  # final logits (n_out)
    state: list  # per-layer [x, pos, kv] (n_state)
    attn_sum: object  # allreduced attention output (B*D)
    x2: object  # post-attention residual (B*D)
    mlp_partial: object  # MLP consumer output (B*D)
    mlp_sum: object  # allreduced MLP output (B*D)

    @property
    def persistent(self) -> tuple:
        """The buffers whose tails are device-resident dispatch-to-
        dispatch state: the per-layer [x, pos, kv] states (the kv cache
        rides behind the refreshed [x, pos] prefix) and xp (pos rides
        behind each layer's B*D-wide residual write). Declared on the
        recorded sequence so the hazard pass can hold every OTHER
        buffer to the full ACCL101 contract."""
        return (self.xp, *self.state)


def create_decode_buffers(accl, cfg: TransformerConfig, batch: int,
                          max_len: int) -> DecodeBuffers:
    dims = decode_dims(cfg, accl.world, batch, max_len)
    b_d = batch * cfg.d_model
    return DecodeBuffers(
        dims=dims,
        xp=accl.create_buffer(dims.n_out, np.float32),
        logits=accl.create_buffer(dims.n_out, np.float32),
        state=[accl.create_buffer(dims.n_state, np.float32)
               for _ in range(cfg.n_layers)],
        attn_sum=accl.create_buffer(b_d, np.float32),
        x2=accl.create_buffer(b_d, np.float32),
        mlp_partial=accl.create_buffer(b_d, np.float32),
        mlp_sum=accl.create_buffer(b_d, np.float32),
    )


def register_decode_consumers(accl, cfg: TransformerConfig, params: dict,
                              dims: DecodeDims):
    for l, lyr in enumerate(params["layers"]):
        accl.register_stream_consumer(
            decode_attn_stream(l),
            make_decode_attn_consumer(cfg, lyr, dims, accl.world,
                                      accl.axis_name))
        accl.register_stream_consumer(
            decode_mlp_stream(l),
            make_decode_mlp_consumer(cfg, lyr, dims, accl.world,
                                     accl.axis_name))
    accl.register_stream_consumer(
        decode_logits_stream(cfg),
        make_decode_logits_consumer(cfg, params, dims))


def _decode_layer_steps(seq_or_accl, cfg, buffers: DecodeBuffers,
                        layer: int, *, eager: bool):
    """The 7 descriptors of one decode layer — ONE list shared by the
    recorded and eager forms so the two cannot diverge:

      1. copy(xp -> state[l], B*D+B): stage [x, pos] into the state
         prefix (the kv tail survives — partial-width prefix write);
      2. copy(state[l] -> state[l], n_state) through the ATTN consumer:
         [x, pos, kv] -> [o_partial, pos, new kv] IN PLACE — the
         appended cache persists where it lives, no shuttle buffer
         (and no WAR hazard for a reordering executor to trip on);
      3. allreduce(state[l] -> attn_sum, B*D, SUM): the tp partial-sum
         reduction over the o projections (reads the state prefix);
      4. combine(SUM, xp, attn_sum -> x2, B*D): the residual add;
      5. copy(x2 -> mlp_partial, B*D) through the MLP consumer;
      6. allreduce(mlp_partial -> mlp_sum, B*D, SUM);
      7. combine(SUM, x2, mlp_sum -> xp, B*D): layer output back into
         xp's PREFIX — pos rides untouched in the tail for layer l+1.
    """
    d = buffers.dims
    b_d = d.batch * d.d_model
    kw = (dict(from_device=True, to_device=True) if eager else {})
    s = seq_or_accl
    if eager:
        s.copy(buffers.xp, buffers.state[layer], b_d + d.batch,
               from_device=(layer > 0), to_device=True)
        s.copy_to_stream(buffers.state[layer], d.n_state,
                         res_stream=decode_attn_stream(layer),
                         dstbuf=buffers.state[layer], **kw)
    else:
        s.copy(buffers.xp, buffers.state[layer], b_d + d.batch)
        s.copy(buffers.state[layer], buffers.state[layer], d.n_state,
               res_stream=decode_attn_stream(layer))
    s.allreduce(buffers.state[layer], buffers.attn_sum, b_d,
                ReduceFunction.SUM, **kw)
    s.combine(b_d, ReduceFunction.SUM, buffers.xp, buffers.attn_sum,
              buffers.x2, **kw)
    if eager:
        s.copy_to_stream(buffers.x2, b_d,
                         res_stream=decode_mlp_stream(layer),
                         dstbuf=buffers.mlp_partial, **kw)
    else:
        s.copy(buffers.x2, buffers.mlp_partial, b_d,
               res_stream=decode_mlp_stream(layer))
    s.allreduce(buffers.mlp_partial, buffers.mlp_sum, b_d,
                ReduceFunction.SUM, **kw)
    s.combine(b_d, ReduceFunction.SUM, buffers.x2, buffers.mlp_sum,
              buffers.xp, **kw)


def record_decode_step(accl, cfg: TransformerConfig, params: dict, *,
                       batch: int, max_len: int, lint: str = "error",
                       buffers: DecodeBuffers | None = None):
    """Record the KV-cached single-token decode step as ONE descriptor
    batch over `accl`'s (tensor-parallel) axis: n_layers x (attention
    consumer + tp allreduce + MLP consumer + tp allreduce) + the logits
    head, 7*n_layers + 1 descriptors in one dispatch. Returns
    (recorder, buffers); `recorder.compile()` freezes the steady-state
    SequenceProgram, and the same descriptors issued eagerly
    (`run_decode_step_eager`) are the dispatch-per-layer twin —
    bitwise-identical at fp32 (the sequence-vs-eager contract,
    fuzz-pinned)."""
    if buffers is None:
        buffers = create_decode_buffers(accl, cfg, batch, max_len)
    d = buffers.dims
    register_decode_consumers(accl, cfg, params, d)
    seq = accl.sequence(lint=lint, persistent=buffers.persistent)
    for layer in range(cfg.n_layers):
        _decode_layer_steps(seq, cfg, buffers, layer, eager=False)
    seq.copy(buffers.xp, buffers.logits, d.n_out,
             res_stream=decode_logits_stream(cfg))
    return seq, buffers


def make_decode_step_program(accl, cfg: TransformerConfig, params: dict,
                             *, batch: int, max_len: int,
                             lint: str = "error",
                             buffers: DecodeBuffers | None = None):
    """The steady-state fused decode step: record once, compile once,
    dispatch ONE program per token (the SequenceProgram seam the train
    step rides, serving-side). The caller's loop is `write_decode_inputs
    -> program.run() -> read_decode_logits`."""
    seq, buffers = record_decode_step(accl, cfg, params, batch=batch,
                                      max_len=max_len, lint=lint,
                                      buffers=buffers)
    return seq.compile(), buffers


def run_decode_step_eager(accl, cfg: TransformerConfig,
                          buffers: DecodeBuffers):
    """The dispatch-per-layer twin: the SAME 7*n_layers + 1 descriptors
    the fused batch records, issued eagerly — every layer pays its
    dispatch seams while intermediates stay on-device (the same honest
    baseline shape as run_train_step_eager). Bitwise-identical to the
    fused program at fp32 (fuzz-pinned)."""
    for layer in range(len(buffers.state)):
        _decode_layer_steps(accl, cfg, buffers, layer, eager=True)
    d = buffers.dims
    accl.copy_to_stream(buffers.xp, d.n_out,
                        res_stream=decode_logits_stream(cfg),
                        dstbuf=buffers.logits, from_device=True)
    return accl._last_request


def write_decode_inputs(buffers: DecodeBuffers, params: dict, tokens,
                        pos):
    """Stage one step's inputs: embed `tokens` (B,) at per-slot
    positions `pos` (B,) into every rank row of the xp buffer — the
    decode loop's host half (identical rows: the embedding is
    replicated, exactly like the sharded model's)."""
    d = buffers.dims
    x0 = np.asarray(params["embed"])[np.asarray(tokens, np.int64)]
    row = np.zeros(d.n_out, np.float32)
    row[:d.batch * d.d_model] = x0.reshape(-1)
    row[d.batch * d.d_model:d.batch * d.d_model + d.batch] = (
        np.asarray(pos, np.float32))
    buffers.xp.host[:] = row[None]


def read_decode_logits(buffers: DecodeBuffers, *,
                       sync: bool = False) -> np.ndarray:
    """The step's logits (B, V) from rank row 0 (replicated head).
    Pass sync=True after `program.run(to_device=True)` — the
    steady-state dispatch form that keeps the kv caches device-resident
    and syncs ONLY the logits buffer back (the eager twin's final
    copy_to_stream already lands host-side)."""
    d = buffers.dims
    if sync:
        buffers.logits.sync_from_device()
    return np.asarray(
        buffers.logits.host[0][:d.batch * d.vocab],
        np.float32).reshape(d.batch, d.vocab)


def demo_batch(cfg, mesh, batch=4, seq=64, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab, (batch, seq)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1)
    sh = NamedSharding(mesh, P("dp", "sp"))
    return jax.device_put(tokens, sh), jax.device_put(targets, sh)
