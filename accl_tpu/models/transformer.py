"""Flagship demo: a TP x SP x DP transformer LM on the framework.

The model is deliberately the vadd_put pattern (reference
kernels/plugins/vadd_put/vadd_put.cpp:25-87 — device compute pushing
straight into a collective with no host round-trip) at training scale:
one shard_map program contains the forward, the ring-attention sequence
parallelism, the tensor-parallel partial-sum reductions, the backward,
and the data-parallel gradient sync — every cross-device byte moves
through the framework's own schedule bodies (sequencer/schedules.py),
and the host only dispatches the step.

Sharding layout over mesh axes (dp, sp, tp):
  - batch over dp, sequence over sp (ring attention handles cross-shard
    attention), attention heads + mlp hidden over tp;
  - parameters: qkv/o and mlp weights sharded over tp, embeddings
    replicated;
  - gradients: allreduced over dp and sp with the framework's ring
    schedule (eager segmented ring, the ACCL hot path).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..constants import ReduceFunction
from ..sequencer import schedules
from ..parallel.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 256
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(cfg: TransformerConfig, key) -> dict:
    """Global (unsharded) parameter pytree; shard with shard_params."""
    keys = jax.random.split(key, 2 + cfg.n_layers)
    dt = jnp.dtype(cfg.dtype)
    scale = 0.02

    def dense(k, shape):
        return (jax.random.normal(k, shape) * scale).astype(dt)

    params = {
        "embed": dense(keys[0], (cfg.vocab, cfg.d_model)),
        "unembed": dense(keys[1], (cfg.d_model, cfg.vocab)),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[2 + i], 6)
        params["layers"].append(
            {
                "wqkv": dense(k[0], (cfg.d_model, 3, cfg.n_heads, cfg.head_dim)),
                "wo": dense(k[1], (cfg.n_heads, cfg.head_dim, cfg.d_model)),
                "w_up": dense(k[2], (cfg.d_model, cfg.d_ff)),
                "w_down": dense(k[3], (cfg.d_ff, cfg.d_model)),
                "ln1": jnp.ones((cfg.d_model,), dt),
                "ln2": jnp.ones((cfg.d_model,), dt),
            }
        )
    return params


def param_specs(cfg: TransformerConfig) -> dict:
    """PartitionSpecs: tp shards heads/ff, everything else replicated."""
    layer = {
        "wqkv": P(None, None, "tp", None),
        "wo": P("tp", None, None),
        "w_up": P(None, "tp"),
        "w_down": P("tp", None),
        "ln1": P(),
        "ln2": P(),
    }
    return {
        "embed": P(),
        "unembed": P(),
        "layers": [layer] * cfg.n_layers,
    }


def _spec_has_axis(spec, axis: str) -> bool:
    """True if a PartitionSpec shards any dimension over `axis`."""
    for part in spec:
        if part is None:
            continue
        parts = part if isinstance(part, tuple) else (part,)
        if axis in parts:
            return True
    return False


def _rmsnorm(x, g):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * g


def _tp_allreduce(x, wire):
    """Tensor-parallel partial-sum reduction through the framework's ring
    reduce-scatter + allgather schedule (the ACCL eager allreduce)."""
    shape = x.shape
    flat = x.reshape(-1)
    out = schedules.allreduce_ring_schedule(
        flat,
        func=ReduceFunction.SUM,
        axis="tp",
        world=lax.axis_size("tp"),
        wire=wire,
        seg_count=flat.shape[0],
    )
    return out.reshape(shape)


def _grad_allreduce(g, axis, wire):
    world = lax.axis_size(axis)
    if world == 1:
        return g
    shape = g.shape
    out = schedules.allreduce_ring_schedule(
        g.reshape(-1),
        func=ReduceFunction.SUM,
        axis=axis,
        world=world,
        wire=wire,
        seg_count=g.size,
    )
    return out.reshape(shape) / world  # mean over replicas


def _forward_local(params, tokens, cfg: TransformerConfig, wire):
    """Per-device forward: tokens (B_local, T_local) -> logits. Runs inside
    shard_map; heads are the tp-local slice, sequence the sp-local shard."""
    x = params["embed"][tokens]  # (B, T, Dm)
    for lyr in params["layers"]:
        h = _rmsnorm(x, lyr["ln1"])
        qkv = jnp.einsum("btd,dchk->btchk", h, lyr["wqkv"])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = ring_attention(q, k, v, axis_name="sp", causal=True)
        o_partial = jnp.einsum("bthk,hkd->btd", attn, lyr["wo"])
        # heads are sharded over tp: partial sums reduce on-device-ring
        o = _tp_allreduce(o_partial, wire)
        x = x + o
        h = _rmsnorm(x, lyr["ln2"])
        up = jnp.einsum("btd,df->btf", h, lyr["w_up"])
        up = jax.nn.gelu(up)
        down_partial = jnp.einsum("btf,fd->btd", up, lyr["w_down"])
        x = x + _tp_allreduce(down_partial, wire)
    x = _rmsnorm(x, jnp.ones((cfg.d_model,), x.dtype))
    return jnp.einsum("btd,dv->btv", x, params["unembed"])


def _loss_local(params, tokens, targets, cfg, wire):
    logits = _forward_local(params, tokens, cfg, wire).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, -1)
    nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    return nll.mean()


def make_forward(cfg: TransformerConfig, mesh: Mesh):
    """Jitted SPMD forward: tokens (B, T) -> logits, batch over dp,
    sequence over sp, heads over tp."""
    wire = schedules.Wire(None)

    def body(params, tokens):
        return _forward_local(params, tokens, cfg, wire)

    pspecs = param_specs(cfg)
    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(pspecs, P("dp", "sp")),
            out_specs=P("dp", "sp"),
            check_vma=False,
        )
    )


def make_train_step(cfg: TransformerConfig, mesh: Mesh, lr: float = 1e-3):
    """One compiled SGD step: forward + backward + grad sync + update, all
    inside a single shard_map program (host-only-dispatches)."""
    wire = schedules.Wire(None)
    pspecs = param_specs(cfg)

    def body(params, tokens, targets):
        loss, grads = jax.value_and_grad(_loss_local)(
            params, tokens, targets, cfg, wire
        )

        tp_world = lax.axis_size("tp")

        def sync(g, spec):
            # every param (tp-sharded or replicated) saw only its dp batch
            # shard and sp sequence shard: mean-reduce over both axes.
            g = _grad_allreduce(g, "dp", wire)
            g = _grad_allreduce(g, "sp", wire)
            if tp_world > 1:
                # The ring-allreduce transpose is itself an allreduce, so a
                # replicated cotangent entering a tp branch comes back
                # amplified by tp: tp-sharded weight grads are tp x the true
                # value (rescale), while tp-replicated params see only their
                # rank's head/ff-slice contribution (mean-allreduce over tp
                # restores the full gradient — sum of slices / tp x tp).
                if _spec_has_axis(spec, "tp"):
                    g = g / tp_world
                else:
                    g = _grad_allreduce(g, "tp", wire)
            return g

        grads = jax.tree.map(sync, grads, pspecs)
        new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)
        for ax in ("dp", "sp"):
            loss = schedules.allreduce_ring_schedule(
                loss[None], func=ReduceFunction.SUM, axis=ax,
                world=lax.axis_size(ax), wire=wire, seg_count=1,
            )[0] / lax.axis_size(ax)
        return new_params, loss

    step = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pspecs, P("dp", "sp"), P("dp", "sp")),
        out_specs=(pspecs, P()),
        check_vma=False,
    )
    return jax.jit(step)


def shard_params(params, cfg, mesh):
    """Place a global parameter pytree according to param_specs."""
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def demo_batch(cfg, mesh, batch=4, seq=64, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab, (batch, seq)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1)
    sh = NamedSharding(mesh, P("dp", "sp"))
    return jax.device_put(tokens, sh), jax.device_put(targets, sh)
