"""Expert-parallel Mixture-of-Experts on the framework's alltoall.

Second model family beside the dense TP x SP x DP transformer
(transformer.py): the FFN is replaced by a top-1-routed MoE whose experts
shard over an `ep` mesh axis, with BOTH the token dispatch and the
return combine moving through the framework's own pairwise-rotation
alltoall schedule (sequencer/schedules.py:alltoall_schedule — the ACCL
alltoall, ccl_offload_control.c:2123-2218). This is the vadd_put pattern
again at a different scale point: device compute feeding straight into a
collective inside one compiled program, no host in the loop.

Routing is capacity-based top-k (fixed shapes, XLA-friendly): each token
routes to its top_k experts (k=1 keeps the raw router probability as the
gate; k>1 normalizes gates over the chosen k), each expert accepts at
most C = ceil(T * k / E * capacity_factor) pseudo-tokens per rank, and
overflow passes through on the residual stream (standard dropped-token
semantics).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..sequencer import schedules


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int = 64
    d_ff: int = 128
    n_experts: int = 4       # total experts == ep axis size x experts_per_rank
    experts_per_rank: int = 1
    capacity_factor: float = 1.25
    top_k: int = 1           # experts per token (k=1: raw-prob gate;
                             # k>1: gates normalized over the chosen k)
    vocab: int = 64
    seq: int = 32
    dtype: str = "float32"


def init_moe_params(cfg: MoEConfig, key) -> dict:
    """Global parameter pytree: router replicated, experts stacked on the
    leading axis (sharded over ep)."""
    kr, ke1, ke2, kemb, kun = jax.random.split(key, 5)
    dt = jnp.dtype(cfg.dtype)
    E = cfg.n_experts
    s = 0.02
    return {
        "embed": (jax.random.normal(kemb, (cfg.vocab, cfg.d_model)) * s).astype(dt),
        "router": (jax.random.normal(kr, (cfg.d_model, E)) * s).astype(dt),
        "w_up": (jax.random.normal(ke1, (E, cfg.d_model, cfg.d_ff)) * s).astype(dt),
        "w_down": (jax.random.normal(ke2, (E, cfg.d_ff, cfg.d_model)) * s).astype(dt),
        "unembed": (jax.random.normal(kun, (cfg.d_model, cfg.vocab)) * s).astype(dt),
    }


def moe_param_specs(cfg: MoEConfig) -> dict:
    return {
        "embed": P(),
        "router": P(),
        "w_up": P("ep"),
        "w_down": P("ep"),
        "unembed": P(),
    }


def place_moe_params(params, cfg: MoEConfig, mesh: Mesh):
    """Place a global MoE parameter pytree according to moe_param_specs."""
    specs = moe_param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, P))


def _capacity(cfg: MoEConfig, tokens: int) -> int:
    return max(1, int(np.ceil(tokens / cfg.n_experts * cfg.capacity_factor)))


def _route(x, params, cfg: MoEConfig, C: int):
    """Top-k routing + capacity assignment for ONE rank's (T, D) tokens
    — the shared half of the dispatch math (the shard_map body and the
    facade-sequence path below both call it, so the two executions can
    never diverge). Returns (dispatch (E, C, D), safe_e, safe_c, keep,
    gate)."""
    T, D = x.shape
    E = cfg.n_experts
    k = cfg.top_k

    # top-k routing (router weights are replicated): each token becomes k
    # pseudo-tokens, token-major, so capacity positions fill in token order
    logits = x @ params["router"]                      # (T, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = lax.top_k(probs, k)                   # (T, k)
    gates = topv if k == 1 else topv / topv.sum(-1, keepdims=True)
    assign = topi.reshape(-1)                          # (T*k,)
    gate = gates.reshape(-1)
    x_rep = jnp.repeat(x, k, axis=0)                   # (T*k, D)

    # capacity assignment: position of each pseudo-token within its expert
    onehot = jax.nn.one_hot(assign, E, dtype=jnp.int32)          # (T*k, E)
    pos = (jnp.cumsum(onehot, axis=0) - 1) * onehot              # (T*k, E)
    pos_in_e = pos.sum(axis=-1)                                  # (T*k,)
    keep = pos_in_e < C

    # dispatch buffer (E, C, D): slot [e, c] = the c-th token routed to e
    safe_e = jnp.where(keep, assign, 0)
    safe_c = jnp.where(keep, pos_in_e, 0)
    dispatch = jnp.zeros((E, C, D), x.dtype)
    dispatch = dispatch.at[safe_e, safe_c].add(
        jnp.where(keep[:, None], x_rep, 0.0)
    )
    return dispatch, safe_e, safe_c, keep, gate


def _combine_tokens(back, safe_e, safe_c, keep, gate, T: int, k: int,
                    D: int, dtype):
    """The gather-and-gate half of the combine: each pseudo-token reads
    its expert output slot, weights it by its gate, and the k expert
    contributions per token sum. Shared by both execution paths."""
    token_out = back[safe_e, safe_c]                   # (T*k, D)
    contrib = jnp.where(keep[:, None],
                        token_out * gate[:, None].astype(dtype), 0.0)
    return contrib.reshape(T, k, D).sum(axis=1)


def moe_ffn_local(x, params, cfg: MoEConfig, *, ep_axis: str, wire):
    """Per-rank MoE FFN body (runs inside shard_map): routes the local
    (T, D) tokens to experts across the ep axis through the framework
    alltoall, applies the rank's local experts, and alltoalls results
    back. Returns (T, D) expert outputs weighted by router probability
    (zeros for capacity-dropped tokens)."""
    T, D = x.shape
    ep_world = lax.axis_size(ep_axis)
    n_local = cfg.experts_per_rank
    E = ep_world * n_local
    assert E == cfg.n_experts, (E, cfg.n_experts)
    k = cfg.top_k
    C = _capacity(cfg, T * k)

    dispatch, safe_e, safe_c, keep, gate = _route(x, params, cfg, C)

    # dispatch alltoall: destination rank r gets experts [r*n_local, ...)
    flat = dispatch.reshape(-1)                        # (ep_world * n_local*C*D)
    routed = schedules.alltoall_schedule(
        flat, axis=ep_axis, world=ep_world, wire=wire
    )
    # (ep_world, n_local, C, D): source-rank-major blocks for MY experts
    recv = routed.reshape(ep_world, n_local, C, D)

    # local expert FFN: under in_specs P(ep) the expert stacks enter
    # shard_map already sliced to this rank's (n_local, ...) block, so
    # they are used directly — re-slicing by axis_index here would be a
    # clamped no-op that silently misroutes if the param spec changed
    w_up = params["w_up"]
    w_down = params["w_down"]
    assert w_up.shape[0] == n_local, (w_up.shape, n_local)
    h = jnp.einsum("slcd,ldf->slcf", recv, w_up)
    h = jax.nn.gelu(h)
    out = jnp.einsum("slcf,lfd->slcd", h, w_down)

    # return alltoall: send block s back to source rank s
    back = schedules.alltoall_schedule(
        out.reshape(-1), axis=ep_axis, world=ep_world, wire=wire
    ).reshape(E, C, D)

    # combine: gather each pseudo-token's slot, weight by its gate, and
    # sum each token's k expert contributions
    return _combine_tokens(back, safe_e, safe_c, keep, gate, T, k, D,
                           x.dtype)


def make_moe_forward(cfg: MoEConfig, mesh: Mesh):
    """Jitted SPMD forward: tokens (B, T) -> logits; batch over dp,
    experts over ep. One compiled program per call signature."""
    wire = schedules.Wire(None)
    pspecs = moe_param_specs(cfg)

    def body(params, tokens):
        x = params["embed"][tokens]                    # (Blocal, T, D)

        def per_seq(xi):
            return xi + moe_ffn_local(xi, params, cfg, ep_axis="ep",
                                      wire=wire)

        x = jax.vmap(per_seq)(x)
        x = x * lax.rsqrt(jnp.mean(jnp.square(x), -1, keepdims=True) + 1e-6)
        return jnp.einsum("btd,dv->btv", x, params["unembed"])

    # tokens shard over BOTH axes (true expert parallelism: every rank
    # routes a distinct batch shard); routing is per-sequence, so the
    # sharded program equals the single-device oracle exactly
    return jax.jit(
        jax.shard_map(
            body, mesh=mesh,
            in_specs=(pspecs, P(("dp", "ep"))),
            out_specs=P(("dp", "ep")),
            check_vma=False,
        )
    )


def make_moe_train_step(cfg: MoEConfig, mesh: Mesh, lr: float = 1e-2):
    """SGD step with dp-mean + ep-aware gradient sync: expert-sharded
    grads stay local to their ep shard; replicated params (embed, router,
    unembed) mean-allreduce over BOTH axes through the framework ring."""
    from ..constants import ReduceFunction

    wire = schedules.Wire(None)
    pspecs = moe_param_specs(cfg)

    def loss_fn(params, tokens, targets):
        x = params["embed"][tokens]

        def per_seq(xi):
            return xi + moe_ffn_local(xi, params, cfg, ep_axis="ep",
                                      wire=wire)

        x = jax.vmap(per_seq)(x)
        x = x * lax.rsqrt(jnp.mean(jnp.square(x), -1, keepdims=True) + 1e-6)
        logits = jnp.einsum("btd,dv->btv", x, params["unembed"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
        return nll.mean()

    def _allreduce_mean(g, axis):
        world = lax.axis_size(axis)
        if world == 1:
            return g
        out = schedules.allreduce_ring_schedule(
            g.reshape(-1), func=ReduceFunction.SUM, axis=axis, world=world,
            wire=wire, seg_count=g.size,
        )
        return out.reshape(g.shape) / world

    def body(params, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        ep_world = lax.axis_size("ep")

        def sync(g, spec):
            g = _allreduce_mean(g, "dp")
            if "ep" in tuple(spec):
                # the alltoall transpose already accumulated every ep
                # shard's cotangent on the owning rank (one term per
                # shard-local loss), so after the dp mean the expert grad
                # is ep_world x the global-mean gradient: rescale
                return g / ep_world
            # replicated params: each rank's grad covers only its own
            # token shard — mean over ep completes the batch mean
            return _allreduce_mean(g, "ep")

        grads = jax.tree.map(sync, grads, pspecs)
        new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)
        for ax in ("dp", "ep"):
            loss = schedules.allreduce_ring_schedule(
                loss[None], func=ReduceFunction.SUM, axis=ax,
                world=lax.axis_size(ax), wire=wire, seg_count=1,
            )[0] / lax.axis_size(ax)
        return new_params, loss

    return jax.jit(
        jax.shard_map(
            body, mesh=mesh,
            in_specs=(pspecs, P(("dp", "ep")), P(("dp", "ep"))),
            out_specs=(pspecs, P()),
            check_vma=False,
        )
    )


# ---------------------------------------------------------------------------
# Device-resident MoE layer step: the dispatch -> expert -> combine round
# trip as ONE recorded descriptor batch (ROADMAP item 4's fused form)
# ---------------------------------------------------------------------------

# kernel-stream id the expert-FFN consumer registers under (any id in
# 1..246 works; one well-known default keeps the bench, the dryrun and
# the tests on the same endpoint)
MOE_EXPERT_STREAM = 11


def moe_expert_consumer(cfg: MoEConfig, capacity: int, w_up, w_down,
                        axis_name: str = "ccl"):
    """The expert-FFN stage as a RES_STREAM consumer: the dispatch
    alltoall's routed arrival — (ep_world, n_local, C, D) source-major
    blocks, flat — runs this rank's local experts BEFORE landing in the
    result buffer, so compute fuses into the same compiled program as
    the collective (the stream_put posture at MoE scale). The stacked
    expert weights close over the endpoint as program constants and the
    rank's block is selected by axis_index, so ONE traced callable
    serves every rank; re-registering with new weights is a new
    endpoint identity and compiles a new program (the stream cache keys
    on it)."""
    ep_world = cfg.n_experts // cfg.experts_per_rank
    n_local, C, D = cfg.experts_per_rank, capacity, cfg.d_model
    wu = jnp.asarray(w_up)
    wd = jnp.asarray(w_down)

    def consumer(flat):
        # materialize the routed arrival before the expert matmuls: a
        # fused producer (the quantized wire's dequantize chain feeding
        # straight into dot_general) degrades XLA:CPU's gemm to a slow
        # fused loop — the barrier keeps the einsums on the fast path
        # without changing a bit of the math
        flat = lax.optimization_barrier(flat)
        recv = flat.reshape(ep_world, n_local, C, D)
        me = lax.axis_index(axis_name)
        wu_l = lax.dynamic_slice_in_dim(wu, me * n_local, n_local, axis=0)
        wd_l = lax.dynamic_slice_in_dim(wd, me * n_local, n_local, axis=0)
        h = jax.nn.gelu(jnp.einsum("slcd,ldf->slcf", recv, wu_l))
        out = jnp.einsum("slcf,lfd->slcd", h, wd_l)
        return out.reshape(-1).astype(flat.dtype)

    return consumer


def make_expert_program(accl, cfg: MoEConfig, capacity: int, w_up, w_down):
    """The UNFUSED expert stage: the same per-rank expert-FFN body as
    the stream consumer, compiled as its OWN jit(shard_map) program over
    the routed buffer — the middle dispatch of the eager baseline (a
    descriptor-per-stage caller pays this seam; the fused sequence is
    exactly what removes it)."""
    from jax.sharding import PartitionSpec

    consumer = moe_expert_consumer(cfg, capacity, w_up, w_down,
                                   accl.axis_name)

    def body(xrow):
        y = consumer(xrow.reshape(xrow.shape[-1]))
        return y.reshape(1, y.shape[-1])

    spec = PartitionSpec(accl.axis_name)
    return jax.jit(jax.shard_map(body, mesh=accl.mesh, in_specs=(spec,),
                                 out_specs=spec, check_vma=False))


def _ensure_expert_consumer(accl, cfg: MoEConfig, capacity: int, w_up,
                            w_down, stream_id: int) -> None:
    """Register the expert-FFN consumer ONCE per (shape, weights): the
    stream endpoint's IDENTITY keys the compiled-program caches
    (SequencePlan.cache_key holds strong refs), so registering a fresh
    closure per call would re-trace and re-compile the fused program —
    and retain the stale one — every iteration. The memo (held on the
    accl, weights kept alive so object ids cannot be reused) makes
    repeat calls with the same weights reuse the SAME endpoint, hence
    the same compiled program."""
    memo = getattr(accl, "_moe_consumer_memo", None)
    if memo is None:
        memo = {}
        accl._moe_consumer_memo = memo
    # keyed by STREAM ID alone: the memo must mirror what the endpoint
    # currently holds — keying by (stream, cfg, ...) would hit a stale
    # entry after a DIFFERENT config overwrote the shared stream and
    # silently run the wrong expert shapes/weights
    binding = (cfg, capacity, accl.axis_name, w_up, w_down)
    prev = memo.get(stream_id)
    if (prev is not None and prev[0] == binding[0]
            and prev[1] == binding[1] and prev[2] == binding[2]
            and prev[3] is w_up and prev[4] is w_down):
        return
    memo[stream_id] = binding
    accl.register_stream_consumer(
        stream_id,
        moe_expert_consumer(cfg, capacity, w_up, w_down, accl.axis_name))


def run_moe_layer(accl, disp, mid, out, count: int, *,
                  stream_id: int = MOE_EXPERT_STREAM, fused: bool = True,
                  expert_fn=None, compress_dtype=None, peer_counts=(),
                  from_device: bool = False, to_device: bool = False,
                  lint: str = "error"):
    """One MoE layer step over registered facade buffers: the dispatch
    alltoall (expert FFN spliced as its RES_STREAM consumer) followed by
    the combine alltoall returning expert outputs to their source ranks.

    fused=True records BOTH steps through ``accl.sequence()`` — one
    ``jit(shard_map)`` program per layer step, one dispatch,
    signature-cached, the mid buffer threaded on-device between the
    stages. fused=False issues the SAME two descriptors eagerly (two
    dispatches; both paths compose the same schedule bodies, so their
    results are bitwise-identical at fp32 — pinned by test_moe).
    fused=False with `expert_fn` (make_expert_program) instead runs the
    fully EAGER descriptor-per-stage form — dispatch alltoall, the
    standalone expert program, combine alltoall: three dispatches, the
    pre-fusion baseline the bench's moe_dispatch gate measures against
    (intermediates stay on-device via from/to_device, so the baseline
    pays the dispatch seams, not artificial host round trips).

    `compress_dtype=DataType.int8` rides the blockwise-quantized wire on
    both legs explicitly; leaving it None defers to the device's
    ALLTOALL_COMPRESS_MIN_COUNT register (the autotuned crossover).
    `peer_counts` routes both legs through the capacity-bounded
    alltoallv (per-peer valid prefixes, overflow dropped on the wire)."""
    def leg(tgt, a, b, **kw):
        if peer_counts:
            tgt.alltoallv(a, b, count, peer_counts,
                          compress_dtype=compress_dtype, **kw)
        else:
            tgt.alltoall(a, b, count, compress_dtype=compress_dtype, **kw)

    if fused:
        seq = accl.sequence(lint=lint)
        leg(seq, disp, mid, res_stream=stream_id)
        leg(seq, mid, out)
        return seq.run(from_device=from_device, to_device=to_device)
    if expert_fn is not None:
        # descriptor-per-stage: expert outputs land back in mid
        # on-device, then the combine leg returns them to their sources
        # (intermediates ride from/to_device — the baseline pays the
        # dispatch-per-stage seams, not artificial host round trips)
        leg(accl, disp, mid, from_device=from_device, to_device=True)
        mid.device = expert_fn(mid.device)
        leg(accl, mid, out, from_device=True, to_device=to_device)
        return accl._last_request
    leg(accl, disp, mid, res_stream=stream_id, from_device=from_device,
        to_device=True)
    leg(accl, mid, out, from_device=True, to_device=to_device)
    return accl._last_request


def make_moe_layer_program(accl, disp, mid, out, count: int, *,
                           stream_id: int = MOE_EXPERT_STREAM,
                           compress_dtype=None, peer_counts=(),
                           lint: str = "error"):
    """The steady-state form of the fused layer step: record the
    dispatch -> expert -> combine batch ONCE and freeze it into a
    re-dispatchable SequenceProgram (resolve + lint + compile happen
    here; every `program.run()` afterwards is one dispatch). This is
    what a training/serving loop holds per MoE layer — ONE compiled
    program per layer step, dispatched per iteration."""
    seq = accl.sequence(lint=lint)
    if peer_counts:
        seq.alltoallv(disp, mid, count, peer_counts,
                      compress_dtype=compress_dtype, res_stream=stream_id)
        seq.alltoallv(mid, out, count, peer_counts,
                      compress_dtype=compress_dtype)
    else:
        seq.alltoall(disp, mid, count, compress_dtype=compress_dtype,
                     res_stream=stream_id)
        seq.alltoall(mid, out, count, compress_dtype=compress_dtype)
    return seq.compile()


def create_moe_layer_buffers(accl, cfg: MoEConfig, capacity: int):
    """(disp, mid, out) stacked rank buffers for `run_moe_layer`, each
    (world, E * C * D) fp32."""
    n = cfg.n_experts * capacity * cfg.d_model
    return tuple(accl.create_buffer(n, np.float32) for _ in range(3))


def moe_ffn_via_sequence(accl, x, params, cfg: MoEConfig, *,
                         buffers=None, capacity: int | None = None,
                         fused: bool = True, compress_dtype=None,
                         wire_capacity: int | None = None,
                         stream_id: int = MOE_EXPERT_STREAM):
    """The facade form of `moe_ffn_local`: per-rank routing host-side,
    then the dispatch -> expert -> combine round trip as recorded
    descriptors over `accl`'s axis (`x` is the stacked (world, T, D)
    token activations; returns the stacked FFN contributions). The
    routing and combine math is `_route`/`_combine_tokens` — the SAME
    helpers the shard_map body uses — and the alltoall legs lower the
    same schedule bodies, so at fp32 this path reproduces
    `moe_ffn_local` exactly.

    `wire_capacity` (experts_per_rank == 1 only) applies the capacity
    bound ON THE WIRE via alltoallv: the dispatch buffer keeps its full
    per-expert slots, but each peer accepts only the first
    wire_capacity token rows — tokens beyond it are dropped by the
    schedule itself (zero contribution after the gate), and every hop
    ships wire_capacity/C of the dense bytes."""
    world = accl.world
    T, D = int(x.shape[-2]), int(x.shape[-1])
    k = cfg.top_k
    C = capacity if capacity is not None else _capacity(cfg, T * k)
    E = cfg.n_experts
    count = (E // world) * C * D  # per-peer chunk elements
    peer_counts: tuple[int, ...] = ()
    if wire_capacity is not None and wire_capacity < C:
        if cfg.experts_per_rank != 1:
            raise ValueError(
                "wire_capacity needs experts_per_rank == 1 (a flat slot "
                "prefix is a token prefix only for one expert per rank)")
        peer_counts = (wire_capacity * D,) * world

    _ensure_expert_consumer(accl, cfg, C, params["w_up"],
                            params["w_down"], stream_id)
    if buffers is None:
        buffers = create_moe_layer_buffers(accl, cfg, C)
    disp, mid, out = buffers

    route = jax.jit(jax.vmap(lambda xi: _route(xi, params, cfg, C)))
    dispatch, safe_e, safe_c, keep, gate = route(jnp.asarray(x))
    disp.write(np.asarray(dispatch.reshape(world, -1), np.float32))
    run_moe_layer(accl, disp, mid, out, count, stream_id=stream_id,
                  fused=fused, compress_dtype=compress_dtype,
                  peer_counts=peer_counts)
    back = jnp.asarray(out.host).reshape(world, E, C, D)
    comb = jax.jit(jax.vmap(
        lambda b, se, sc, kp, g: _combine_tokens(b, se, sc, kp, g, T, k, D,
                                                 b.dtype)))
    return np.asarray(comb(back, safe_e, safe_c, keep, gate))


def moe_reference_forward(params, tokens, cfg: MoEConfig):
    """Single-device oracle: identical routing/capacity math, no mesh."""
    x = params["embed"][tokens]

    def per_seq(xi):
        T, D = xi.shape
        k = cfg.top_k
        E, C = cfg.n_experts, _capacity(cfg, T * k)
        logits = xi @ params["router"]
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
        topv, topi = jax.lax.top_k(probs, k)
        gates = topv if k == 1 else topv / topv.sum(-1, keepdims=True)
        assign = topi.reshape(-1)
        gate = gates.reshape(-1)
        x_rep = jnp.repeat(xi, k, axis=0)
        onehot = jax.nn.one_hot(assign, E, dtype=jnp.int32)
        pos_in_e = ((jnp.cumsum(onehot, 0) - 1) * onehot).sum(-1)
        keep = pos_in_e < C
        safe_e = jnp.where(keep, assign, 0)
        safe_c = jnp.where(keep, pos_in_e, 0)
        disp = jnp.zeros((E, C, D), xi.dtype).at[safe_e, safe_c].add(
            jnp.where(keep[:, None], x_rep, 0.0))
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", disp, params["w_up"]))
        out = jnp.einsum("ecf,efd->ecd", h, params["w_down"])
        tok = out[safe_e, safe_c]
        contrib = jnp.where(keep[:, None],
                            tok * gate[:, None].astype(xi.dtype), 0.0)
        return xi + contrib.reshape(T, k, D).sum(axis=1)

    x = jax.vmap(per_seq)(x)
    x = x * jax.lax.rsqrt(jnp.mean(jnp.square(x), -1, keepdims=True) + 1e-6)
    return jnp.einsum("btd,dv->btv", x, params["unembed"])
