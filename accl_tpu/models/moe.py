"""Expert-parallel Mixture-of-Experts on the framework's alltoall.

Second model family beside the dense TP x SP x DP transformer
(transformer.py): the FFN is replaced by a top-1-routed MoE whose experts
shard over an `ep` mesh axis, with BOTH the token dispatch and the
return combine moving through the framework's own pairwise-rotation
alltoall schedule (sequencer/schedules.py:alltoall_schedule — the ACCL
alltoall, ccl_offload_control.c:2123-2218). This is the vadd_put pattern
again at a different scale point: device compute feeding straight into a
collective inside one compiled program, no host in the loop.

Routing is capacity-based top-k (fixed shapes, XLA-friendly): each token
routes to its top_k experts (k=1 keeps the raw router probability as the
gate; k>1 normalizes gates over the chosen k), each expert accepts at
most C = ceil(T * k / E * capacity_factor) pseudo-tokens per rank, and
overflow passes through on the residual stream (standard dropped-token
semantics).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..sequencer import schedules


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int = 64
    d_ff: int = 128
    n_experts: int = 4       # total experts == ep axis size x experts_per_rank
    experts_per_rank: int = 1
    capacity_factor: float = 1.25
    top_k: int = 1           # experts per token (k=1: raw-prob gate;
                             # k>1: gates normalized over the chosen k)
    vocab: int = 64
    seq: int = 32
    dtype: str = "float32"


def init_moe_params(cfg: MoEConfig, key) -> dict:
    """Global parameter pytree: router replicated, experts stacked on the
    leading axis (sharded over ep)."""
    kr, ke1, ke2, kemb, kun = jax.random.split(key, 5)
    dt = jnp.dtype(cfg.dtype)
    E = cfg.n_experts
    s = 0.02
    return {
        "embed": (jax.random.normal(kemb, (cfg.vocab, cfg.d_model)) * s).astype(dt),
        "router": (jax.random.normal(kr, (cfg.d_model, E)) * s).astype(dt),
        "w_up": (jax.random.normal(ke1, (E, cfg.d_model, cfg.d_ff)) * s).astype(dt),
        "w_down": (jax.random.normal(ke2, (E, cfg.d_ff, cfg.d_model)) * s).astype(dt),
        "unembed": (jax.random.normal(kun, (cfg.d_model, cfg.vocab)) * s).astype(dt),
    }


def moe_param_specs(cfg: MoEConfig) -> dict:
    return {
        "embed": P(),
        "router": P(),
        "w_up": P("ep"),
        "w_down": P("ep"),
        "unembed": P(),
    }


def place_moe_params(params, cfg: MoEConfig, mesh: Mesh):
    """Place a global MoE parameter pytree according to moe_param_specs."""
    specs = moe_param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, P))


def _capacity(cfg: MoEConfig, tokens: int) -> int:
    return max(1, int(np.ceil(tokens / cfg.n_experts * cfg.capacity_factor)))


def moe_ffn_local(x, params, cfg: MoEConfig, *, ep_axis: str, wire):
    """Per-rank MoE FFN body (runs inside shard_map): routes the local
    (T, D) tokens to experts across the ep axis through the framework
    alltoall, applies the rank's local experts, and alltoalls results
    back. Returns (T, D) expert outputs weighted by router probability
    (zeros for capacity-dropped tokens)."""
    T, D = x.shape
    ep_world = lax.axis_size(ep_axis)
    n_local = cfg.experts_per_rank
    E = ep_world * n_local
    assert E == cfg.n_experts, (E, cfg.n_experts)
    k = cfg.top_k
    C = _capacity(cfg, T * k)

    # top-k routing (router weights are replicated): each token becomes k
    # pseudo-tokens, token-major, so capacity positions fill in token order
    logits = x @ params["router"]                      # (T, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = lax.top_k(probs, k)                   # (T, k)
    gates = topv if k == 1 else topv / topv.sum(-1, keepdims=True)
    assign = topi.reshape(-1)                          # (T*k,)
    gate = gates.reshape(-1)
    x_rep = jnp.repeat(x, k, axis=0)                   # (T*k, D)

    # capacity assignment: position of each pseudo-token within its expert
    onehot = jax.nn.one_hot(assign, E, dtype=jnp.int32)          # (T*k, E)
    pos = (jnp.cumsum(onehot, axis=0) - 1) * onehot              # (T*k, E)
    pos_in_e = pos.sum(axis=-1)                                  # (T*k,)
    keep = pos_in_e < C

    # dispatch buffer (E, C, D): slot [e, c] = the c-th token routed to e
    safe_e = jnp.where(keep, assign, 0)
    safe_c = jnp.where(keep, pos_in_e, 0)
    dispatch = jnp.zeros((E, C, D), x.dtype)
    dispatch = dispatch.at[safe_e, safe_c].add(
        jnp.where(keep[:, None], x_rep, 0.0)
    )

    # dispatch alltoall: destination rank r gets experts [r*n_local, ...)
    flat = dispatch.reshape(-1)                        # (ep_world * n_local*C*D)
    routed = schedules.alltoall_schedule(
        flat, axis=ep_axis, world=ep_world, wire=wire
    )
    # (ep_world, n_local, C, D): source-rank-major blocks for MY experts
    recv = routed.reshape(ep_world, n_local, C, D)

    # local expert FFN: under in_specs P(ep) the expert stacks enter
    # shard_map already sliced to this rank's (n_local, ...) block, so
    # they are used directly — re-slicing by axis_index here would be a
    # clamped no-op that silently misroutes if the param spec changed
    w_up = params["w_up"]
    w_down = params["w_down"]
    assert w_up.shape[0] == n_local, (w_up.shape, n_local)
    h = jnp.einsum("slcd,ldf->slcf", recv, w_up)
    h = jax.nn.gelu(h)
    out = jnp.einsum("slcf,lfd->slcd", h, w_down)

    # return alltoall: send block s back to source rank s
    back = schedules.alltoall_schedule(
        out.reshape(-1), axis=ep_axis, world=ep_world, wire=wire
    ).reshape(E, C, D)

    # combine: gather each pseudo-token's slot, weight by its gate, and
    # sum each token's k expert contributions
    token_out = back[safe_e, safe_c]                   # (T*k, D)
    contrib = jnp.where(keep[:, None],
                        token_out * gate[:, None].astype(x.dtype), 0.0)
    return contrib.reshape(T, k, D).sum(axis=1)


def make_moe_forward(cfg: MoEConfig, mesh: Mesh):
    """Jitted SPMD forward: tokens (B, T) -> logits; batch over dp,
    experts over ep. One compiled program per call signature."""
    wire = schedules.Wire(None)
    pspecs = moe_param_specs(cfg)

    def body(params, tokens):
        x = params["embed"][tokens]                    # (Blocal, T, D)

        def per_seq(xi):
            return xi + moe_ffn_local(xi, params, cfg, ep_axis="ep",
                                      wire=wire)

        x = jax.vmap(per_seq)(x)
        x = x * lax.rsqrt(jnp.mean(jnp.square(x), -1, keepdims=True) + 1e-6)
        return jnp.einsum("btd,dv->btv", x, params["unembed"])

    # tokens shard over BOTH axes (true expert parallelism: every rank
    # routes a distinct batch shard); routing is per-sequence, so the
    # sharded program equals the single-device oracle exactly
    return jax.jit(
        jax.shard_map(
            body, mesh=mesh,
            in_specs=(pspecs, P(("dp", "ep"))),
            out_specs=P(("dp", "ep")),
            check_vma=False,
        )
    )


def make_moe_train_step(cfg: MoEConfig, mesh: Mesh, lr: float = 1e-2):
    """SGD step with dp-mean + ep-aware gradient sync: expert-sharded
    grads stay local to their ep shard; replicated params (embed, router,
    unembed) mean-allreduce over BOTH axes through the framework ring."""
    from ..constants import ReduceFunction

    wire = schedules.Wire(None)
    pspecs = moe_param_specs(cfg)

    def loss_fn(params, tokens, targets):
        x = params["embed"][tokens]

        def per_seq(xi):
            return xi + moe_ffn_local(xi, params, cfg, ep_axis="ep",
                                      wire=wire)

        x = jax.vmap(per_seq)(x)
        x = x * lax.rsqrt(jnp.mean(jnp.square(x), -1, keepdims=True) + 1e-6)
        logits = jnp.einsum("btd,dv->btv", x, params["unembed"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
        return nll.mean()

    def _allreduce_mean(g, axis):
        world = lax.axis_size(axis)
        if world == 1:
            return g
        out = schedules.allreduce_ring_schedule(
            g.reshape(-1), func=ReduceFunction.SUM, axis=axis, world=world,
            wire=wire, seg_count=g.size,
        )
        return out.reshape(g.shape) / world

    def body(params, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        ep_world = lax.axis_size("ep")

        def sync(g, spec):
            g = _allreduce_mean(g, "dp")
            if "ep" in tuple(spec):
                # the alltoall transpose already accumulated every ep
                # shard's cotangent on the owning rank (one term per
                # shard-local loss), so after the dp mean the expert grad
                # is ep_world x the global-mean gradient: rescale
                return g / ep_world
            # replicated params: each rank's grad covers only its own
            # token shard — mean over ep completes the batch mean
            return _allreduce_mean(g, "ep")

        grads = jax.tree.map(sync, grads, pspecs)
        new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)
        for ax in ("dp", "ep"):
            loss = schedules.allreduce_ring_schedule(
                loss[None], func=ReduceFunction.SUM, axis=ax,
                world=lax.axis_size(ax), wire=wire, seg_count=1,
            )[0] / lax.axis_size(ax)
        return new_params, loss

    return jax.jit(
        jax.shard_map(
            body, mesh=mesh,
            in_specs=(pspecs, P(("dp", "ep")), P(("dp", "ep"))),
            out_specs=(pspecs, P()),
            check_vma=False,
        )
    )


def moe_reference_forward(params, tokens, cfg: MoEConfig):
    """Single-device oracle: identical routing/capacity math, no mesh."""
    x = params["embed"][tokens]

    def per_seq(xi):
        T, D = xi.shape
        k = cfg.top_k
        E, C = cfg.n_experts, _capacity(cfg, T * k)
        logits = xi @ params["router"]
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
        topv, topi = jax.lax.top_k(probs, k)
        gates = topv if k == 1 else topv / topv.sum(-1, keepdims=True)
        assign = topi.reshape(-1)
        gate = gates.reshape(-1)
        x_rep = jnp.repeat(xi, k, axis=0)
        onehot = jax.nn.one_hot(assign, E, dtype=jnp.int32)
        pos_in_e = ((jnp.cumsum(onehot, 0) - 1) * onehot).sum(-1)
        keep = pos_in_e < C
        safe_e = jnp.where(keep, assign, 0)
        safe_c = jnp.where(keep, pos_in_e, 0)
        disp = jnp.zeros((E, C, D), xi.dtype).at[safe_e, safe_c].add(
            jnp.where(keep[:, None], x_rep, 0.0))
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", disp, params["w_up"]))
        out = jnp.einsum("ecf,efd->ecd", h, params["w_down"])
        tok = out[safe_e, safe_c]
        contrib = jnp.where(keep[:, None],
                            tok * gate[:, None].astype(xi.dtype), 0.0)
        return xi + contrib.reshape(T, k, D).sum(axis=1)

    x = jax.vmap(per_seq)(x)
    x = x * jax.lax.rsqrt(jnp.mean(jnp.square(x), -1, keepdims=True) + 1e-6)
    return jnp.einsum("btd,dv->btv", x, params["unembed"])
