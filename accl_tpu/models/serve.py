"""Continuous-batching decode serving over the fused decode step.

The latency floor of interactive inference is the per-token decode
step: one token's compute is tiny, so at production request rates the
dispatch seams — N layers x (kernel launch + TP-allreduce launch) —
dominate the step, not the math. transformer.record_decode_step fuses
the whole step (attention consumer + tp allreduce + MLP consumer + tp
allreduce per layer, plus the logits head) into ONE SequenceProgram
dispatch; this module multiplexes concurrent requests over that single
program:

  - the batch axis is STATIC (the program is compiled once for B
    slots); requests join and leave at STEP BOUNDARIES only, so the
    steady state never recompiles — the continuous-batching model of
    Orca/vLLM, at the descriptor-batch layer;
  - per-slot state is one integer (the slot's position): the KV cache
    itself lives device-resident in the program's state buffers, and a
    freshly admitted request simply starts writing rows at pos 0 — the
    causal mask (t > pos) makes the previous occupant's stale tail
    unreachable, so slot reuse needs NO cache reset or extra dispatch;
  - prompt prefill teacher-forces one prompt token per step riding the
    SAME decode program (no separate prefill graph): a joining request
    streams its prompt through its slot while neighbours keep
    decoding — join never stalls the batch;
  - every step is measured into the telemetry registry
    (accl_serve_step_seconds p50/p95/p99/p99.9, accl_serve_tokens_total),
    the same always-on surface the rest of the data plane reports to.

Batched decode is bitwise-equal to sequential per-request decode
through the same program (tests/test_decode.py pins it): every per-slot
computation in the step is row-independent — einsums contract only
model dims, softmax/rmsnorm normalize per (slot, position), and cache
appends write only the slot's own rows — so occupancy cannot leak
between requests.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import numpy as np

from ..telemetry import metrics
from . import transformer as trf


@dataclasses.dataclass
class DecodeRequest:
    """One inference request: `prompt` streams in one token per step
    (teacher-forced prefill), then up to `max_new_tokens` tokens decode
    greedily. `generated` fills as the request runs; `done` flips when
    it leaves its slot."""

    rid: int
    prompt: list[int]
    max_new_tokens: int
    generated: list[int] = dataclasses.field(default_factory=list)
    done: bool = False


@dataclasses.dataclass
class _Slot:
    req: DecodeRequest
    pos: int = 0  # next position to feed (== tokens consumed so far)


class DecodeServer:
    """Multiplex concurrent decode requests over one fused decode-step
    program (mode="fused", the production path) or its dispatch-per-
    layer eager twin (mode="eager", the baseline the serve gate measures
    the fusion win against). One instance owns its ACCL facade's decode
    buffers; all requests share them, one slot each."""

    def __init__(self, accl, cfg, params, *, batch: int, max_len: int,
                 mode: str = "fused", lint: str = "error",
                 registry=None, time_fn=time.perf_counter,
                 scheduler=None, tenant: str = "serve"):
        if mode not in ("fused", "eager"):
            raise ValueError(f"mode must be 'fused'|'eager', got {mode!r}")
        self.cfg = cfg
        self.batch = batch
        self.max_len = max_len
        self.mode = mode
        self._accl = accl
        self._params = {
            "embed": np.asarray(params["embed"]),
            "unembed": np.asarray(params["unembed"]),
            "layers": [{k: np.asarray(v) for k, v in lyr.items()}
                       for lyr in params["layers"]],
        }
        self._time = time_fn
        self._buffers = trf.create_decode_buffers(accl, cfg, batch, max_len)
        if mode == "fused":
            self._program, _ = trf.make_decode_step_program(
                accl, cfg, self._params, batch=batch, max_len=max_len,
                lint=lint, buffers=self._buffers)
        else:
            self._program = None
            trf.register_decode_consumers(accl, cfg, self._params,
                                          self._buffers.dims)
        # the multi-tenant seam (ROADMAP item 4's deferred "admission
        # = item 1"): with a scheduler attached, request admission
        # consults its backpressure (typed SchedulerSaturatedError
        # when the ring is saturated) and every fused step dispatches
        # through scheduler.dispatch_now — the same program, the same
        # run(to_device=True), so batched==sequential bitwise parity
        # is untouched; what the scheduler adds is tenant metering,
        # SLO residuals and the concurrency/certificate discipline
        # next to any co-running tenants.
        self._scheduler = scheduler
        self._tenant = tenant
        self._step_cost_s: float | None = None
        if scheduler is not None:
            if tenant not in scheduler.tenants:
                scheduler.register_tenant(tenant, priority=0)
            if self._program is not None:
                self._step_cost_s = scheduler.predict_cost_s(
                    self._program)
        self._slots: list[_Slot | None] = [None] * batch
        self._queue: deque[DecodeRequest] = deque()
        self._next_rid = 0
        self.n_steps = 0
        reg = registry if registry is not None else metrics.get_registry()
        self._m_step = reg.histogram("accl_serve_step_seconds",
                                     mode=mode, batch=batch)
        self._m_tokens = reg.counter("accl_serve_tokens_total", mode=mode)
        self._m_active = reg.gauge("accl_serve_active_requests", mode=mode)

    # -- request intake ----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int) -> DecodeRequest:
        """Queue a request; it joins the batch at the next step
        boundary with a free slot. The prompt must be non-empty and
        prompt+generation must fit the compiled max_len window."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(not 0 <= t < self.cfg.vocab for t in prompt):
            raise ValueError("prompt token outside vocab")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len {self.max_len}")
        if self._scheduler is not None:
            # admission through the scheduler seam: the request's
            # predicted cost is (steps it will occupy) x (one fused
            # step's price); a saturated scheduler rejects HERE with
            # the typed error, before the request ever holds a slot
            step_cost = (self._step_cost_s
                         if self._step_cost_s is not None else 1e-5)
            n_steps = len(prompt) + int(max_new_tokens)
            self._scheduler.admit_request(self._tenant,
                                          cost_s=step_cost * n_steps)
        req = DecodeRequest(rid=self._next_rid, prompt=prompt,
                            max_new_tokens=int(max_new_tokens))
        self._next_rid += 1
        self._queue.append(req)
        return req

    @property
    def active(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    @property
    def n_active_slots(self) -> int:
        return sum(s is not None for s in self._slots)

    # -- the step loop -----------------------------------------------------

    def _admit(self) -> None:
        """Join at the step boundary: fill free slots from the queue.
        No cache reset — the joining request's pos starts at 0, and the
        mask hides everything past the rows it will itself write."""
        for i in range(self.batch):
            if self._slots[i] is None and self._queue:
                self._slots[i] = _Slot(self._queue.popleft())

    def step(self) -> int:
        """One fused decode step for every occupied slot: admit at the
        boundary, stage [token, pos] rows, ONE dispatch, harvest
        argmax tokens, retire finished requests. Returns the number of
        generated (non-prefill) tokens this step."""
        self._admit()
        tokens = np.zeros((self.batch,), np.int64)
        pos = np.zeros((self.batch,), np.int64)
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue  # idle rows feed (token 0, pos 0): harmless —
                # they touch only their own slot's cache row 0
            r = slot.req
            if slot.pos < len(r.prompt):
                tokens[i] = r.prompt[slot.pos]
            else:
                tokens[i] = r.generated[-1]
            pos[i] = slot.pos
        trf.write_decode_inputs(self._buffers, self._params, tokens, pos)
        t0 = self._time()
        if self._program is not None:
            # steady state: one dispatch; kv caches stay device-resident
            if self._scheduler is not None:
                self._scheduler.dispatch_now(self._tenant,
                                             self._program,
                                             to_device=True)
            else:
                self._program.run(to_device=True)
            logits = trf.read_decode_logits(self._buffers, sync=True)
        else:
            trf.run_decode_step_eager(self._accl, self.cfg, self._buffers)
            logits = trf.read_decode_logits(self._buffers)
        dt = self._time() - t0
        n_generated = 0
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            r = slot.req
            nxt = int(np.argmax(logits[i]))
            slot.pos += 1
            if slot.pos >= len(r.prompt):
                # fed the last prompt token (or a generated one): the
                # argmax is a real generated token
                r.generated.append(nxt)
                n_generated += 1
            if (len(r.generated) >= r.max_new_tokens
                    or slot.pos >= self.max_len):
                r.done = True
                self._slots[i] = None  # leave at the boundary
        self.n_steps += 1
        self._m_step.observe(dt)
        if n_generated:
            self._m_tokens.inc(n_generated)
        self._m_active.set(self.n_active_slots + len(self._queue))
        return n_generated

    def run(self, max_steps: int | None = None) -> int:
        """Drive steps until every request drained (or max_steps).
        Returns total generated tokens."""
        total = 0
        while self.active:
            if max_steps is not None and self.n_steps >= max_steps:
                break
            total += self.step()
        return total


def generate(server: DecodeServer, prompts, max_new_tokens: int):
    """Convenience batch API: submit every prompt, drain, return the
    generated token lists in submission order."""
    reqs = [server.submit(p, max_new_tokens) for p in prompts]
    server.run()
    return [r.generated for r in reqs]
