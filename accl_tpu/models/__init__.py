"""Demo model family: workloads that exercise the framework end to end.

The reference ships example kernels (vadd_put: compute fused with a
collective, kernels/plugins/vadd_put/vadd_put.cpp:25-87) rather than
models. Here the same role at TPU scale: a transformer LM whose tensor-
parallel reductions, sequence-parallel attention and data-parallel
gradient sync all run through the framework's own schedule bodies inside
one compiled training step.
"""

from ..utils import compat as _compat

_compat.install()  # jax version shims, before the jax-heavy modules load

from .transformer import (  # noqa: F401,E402
    TransformerConfig,
    init_kv_cache,
    init_params,
    make_decode_step,
    make_decode_step_program,
    make_forward,
    make_train_step,
    record_decode_step,
    run_decode_step_eager,
)
from .moe import (  # noqa: F401
    MoEConfig,
    init_moe_params,
    make_moe_forward,
    make_moe_train_step,
)
from .serve import (  # noqa: F401
    DecodeRequest,
    DecodeServer,
    generate,
)
