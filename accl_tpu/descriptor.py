"""Call descriptors: the host <-> sequencer contract.

A collective call is described by a fixed 15-word descriptor, exactly the
shape the reference streams from the hostctrl kernel into the CCLO's
CMD_CALL FIFO (reference: driver/hls/accl_hls.h:134-198 start_call;
firmware unpack at ccl_offload_control.c:2317-2360). The same descriptor is
consumed by the native emulator runtime and, on the TPU path, used as the
cache key + static parameter set for the compiled XLA schedule.
"""

from __future__ import annotations

import dataclasses

from .constants import (
    CompressionFlags,
    DataType,
    HostFlags,
    Operation,
    ReduceFunction,
    StreamFlags,
    TAG_ANY,
)

DESCRIPTOR_WORDS = 15


def normalize_live_ranks(live_ranks, world: int) -> tuple[int, ...]:
    """The ONE validation of a degraded live-subset survivor set
    (shared by the facade seam and plan selection, so the two can never
    drift): sorted, duplicate-free, every member inside the world.
    Returns the normalized tuple; callers decide what a full set means
    (the facade folds it to the ordinary collective)."""
    lr = tuple(sorted(int(r) for r in live_ranks))
    if len(set(lr)) != len(lr):
        raise ValueError(f"duplicate ranks in live_ranks {live_ranks}")
    if any(not 0 <= r < world for r in lr):
        raise ValueError(f"live_ranks {lr} outside world of {world}")
    return lr


@dataclasses.dataclass
class CallOptions:
    """Host-side form of a call descriptor (reference CCLO::Options,
    driver/xrt/include/accl/cclo.hpp:41-83)."""

    scenario: Operation = Operation.nop
    count: int = 0
    comm_addr: int = 0
    root_src_dst: int = 0
    function: int = 0  # ReduceFunction for reductions, CfgFunc for config
    tag: int = TAG_ANY
    arithcfg_addr: int = 0
    compression_flags: CompressionFlags = CompressionFlags.NO_COMPRESSION
    stream_flags: StreamFlags = StreamFlags.NO_STREAM
    host_flags: HostFlags = HostFlags.NO_HOST
    # Kernel-stream ids (strm routing, dma_mover.cpp:497): dedicated
    # descriptor bytes (word 8 bytes 2-3), NOT the tag field, so a
    # streamed collective can still tag-match independently.
    op0_stream_id: int = 0
    res_stream_id: int = 0
    addr_0: int = 0  # operand 0 (send buffer)
    addr_1: int = 0  # operand 1 (second reduction operand)
    addr_2: int = 0  # result buffer
    # TPU-path extras (not serialized into the 15-word form): static dtypes
    # so compiled schedules can be cached per signature. compress_dtype is
    # the wire dtype requested by the caller (prepare_call's compressed
    # operand resolution, reference accl.cpp:1236-1356).
    data_type: DataType = DataType.none
    compress_dtype: DataType = DataType.none
    # alltoallv: static per-peer valid counts (one per rank, each in
    # (0, count]) — peer p accepts only the first peer_counts[p]
    # elements of each source's slot p, the rest is capacity-overflow
    # drop expressed in the schedule. Empty = the dense alltoall. A
    # TPU-path extra like the dtypes (the 15-word form cannot carry a
    # variable-length vector), so it MUST ride signature(): two calls
    # differing only in capacities compile different programs.
    peer_counts: tuple[int, ...] = ()
    # Degraded live-subset allreduce (accl_tpu/resilience/): the
    # DECLARED surviving-contributor set of an
    # `allreduce(mode="live_subset")`. Non-members' operands are masked
    # to exact zeros at the source inside the schedule — the alltoallv
    # drop-to-zeros posture generalized — so the semantic certifier can
    # prove exactly which ranks' data is in the answer
    # (semantics.collective_spec declares the survivor sum, ACCL501
    # fires on any ghost contribution). Empty = every rank contributes
    # (the ordinary collective). A TPU-path extra like peer_counts, and
    # like it MUST ride signature(): two calls differing only in the
    # survivor set compile different programs.
    live_ranks: tuple[int, ...] = ()

    def to_words(self) -> list[int]:
        """Serialize into the 15-word call stream layout (accl_hls.h:134-198):
        scenario, count, comm, root_src_dst, function, tag, arithcfg,
        compression, stream|host<<8, then three 64-bit addresses as lo/hi
        word pairs."""
        words = [
            int(self.scenario),
            self.count,
            self.comm_addr,
            self.root_src_dst,
            int(self.function),
            self.tag,
            self.arithcfg_addr,
            int(self.compression_flags),
            int(self.stream_flags) | (int(self.host_flags) << 8)
            | ((self.op0_stream_id & 0xFF) << 16)
            | ((self.res_stream_id & 0xFF) << 24),
        ]
        for addr in (self.addr_0, self.addr_1, self.addr_2):
            words.append(addr & 0xFFFFFFFF)
            words.append((addr >> 32) & 0xFFFFFFFF)
        assert len(words) == DESCRIPTOR_WORDS
        return words

    @classmethod
    def from_words(cls, words: list[int]) -> "CallOptions":
        if len(words) != DESCRIPTOR_WORDS:
            raise ValueError(f"descriptor must be {DESCRIPTOR_WORDS} words")
        return cls(
            scenario=Operation(words[0]),
            count=words[1],
            comm_addr=words[2],
            root_src_dst=words[3],
            function=words[4],
            tag=words[5],
            arithcfg_addr=words[6],
            compression_flags=CompressionFlags(words[7]),
            stream_flags=StreamFlags(words[8] & 0xFF),
            host_flags=HostFlags((words[8] >> 8) & 0xFF),
            op0_stream_id=(words[8] >> 16) & 0xFF,
            res_stream_id=(words[8] >> 24) & 0xFF,
            addr_0=words[9] | (words[10] << 32),
            addr_1=words[11] | (words[12] << 32),
            addr_2=words[13] | (words[14] << 32),
        )

    @property
    def reduce_function(self) -> ReduceFunction:
        return ReduceFunction(self.function)

    def signature(self) -> tuple:
        """Static compilation signature for the XLA schedule cache: every
        field that changes the compiled program (count class, dtype, flags)
        but not the runtime-variable buffer addresses."""
        return (
            self.scenario,
            self.count,
            self.comm_addr,
            self.root_src_dst,
            self.function,
            self.data_type,
            self.compress_dtype,
            int(self.compression_flags),
            int(self.stream_flags),
            int(self.host_flags),
            self.op0_stream_id,
            self.res_stream_id,
            tuple(self.peer_counts),
            tuple(self.live_ranks),
        )


@dataclasses.dataclass
class SequenceDescriptor:
    """A recorded batch of call descriptors executed as ONE device program
    (the device-resident call-sequence contract: the host issues a single
    batch instead of one descriptor per collective, and the sequencer
    lowers the whole chain — reference posture: the CCLO call FIFO can
    hold many descriptors; here the batch additionally compiles into one
    fused XLA program so nothing re-crosses the host between stages)."""

    steps: tuple[CallOptions, ...]

    def __post_init__(self):
        self.steps = tuple(self.steps)
        if not self.steps:
            raise ValueError("empty call sequence")
        comm = self.steps[0].comm_addr
        if any(s.comm_addr != comm for s in self.steps):
            raise ValueError(
                "all steps of a sequence must address one communicator")

    @property
    def comm_addr(self) -> int:
        return self.steps[0].comm_addr

    def to_words(self) -> list[int]:
        """Serialize as a batched call stream: a count header word followed
        by each step's 15-word descriptor back to back — the shape a
        descriptor-FIFO executor would consume."""
        words = [len(self.steps)]
        for s in self.steps:
            words.extend(s.to_words())
        return words

    @classmethod
    def from_words(cls, words: list[int]) -> "SequenceDescriptor":
        n = words[0]
        if len(words) != 1 + n * DESCRIPTOR_WORDS:
            raise ValueError("malformed sequence descriptor stream")
        return cls(tuple(
            CallOptions.from_words(
                words[1 + i * DESCRIPTOR_WORDS:1 + (i + 1) * DESCRIPTOR_WORDS]
            )
            for i in range(n)
        ))

    def signature(self) -> tuple:
        """Composite static signature: the per-step signatures plus the
        DATAFLOW between steps — which operands alias which results —
        with buffer addresses canonically renamed (first appearance
        order), so two batches over different buffers with the same
        shapes and wiring share one compiled program."""
        rename: dict[int, int] = {}

        def idx(addr: int) -> int | None:
            if addr == 0:
                return None
            return rename.setdefault(addr, len(rename))

        flow = tuple(
            (idx(s.addr_0), idx(s.addr_1), idx(s.addr_2)) for s in self.steps
        )
        return ("sequence",
                tuple(s.signature() for s in self.steps), flow)
