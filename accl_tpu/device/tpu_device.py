"""TPUDevice: descriptor execution as compiled mesh programs.

The hardware backend (reference XRTDevice, driver/xrt/src/xrtdevice.cpp):
where XRTDevice latches descriptor words into the hostctrl kernel and an
on-FPGA firmware loop interprets them, TPUDevice resolves the descriptor's
buffer addresses against its buffer registry, asks the sequencer for a
plan, and launches the cached compiled schedule — one device program per
collective, with XLA's async dispatch standing in for the hardware call
FIFO. Single-controller SPMD replaces per-rank MPI processes: one call
executes the collective for every rank in the communicator.
"""

from __future__ import annotations

import numpy as np
import jax

from ..constants import (
    DEFAULT_EAGER_RX_BUF_SIZE,
    DEFAULT_MAX_EAGER_SIZE,
    DEFAULT_MAX_RENDEZVOUS_SIZE,
    CfgFunc,
    ErrorCode,
    Operation,
    TAG_ANY,
    TuningParams,
    dtype_nbytes,
)
from ..descriptor import CallOptions
from ..request import BaseRequest, TPURequest
from ..sequencer.lowering import ScheduleCompiler
from ..sequencer.plan import select_algorithm
from .base import CCLOAddr, CCLODevice


class TPUDevice(CCLODevice):
    def __init__(self, mesh, axis_name: str = "ccl"):
        super().__init__()
        self.mesh = mesh
        self.axis_name = axis_name
        self.compiler = ScheduleCompiler(mesh, axis_name)
        self.buffers: dict[int, object] = {}  # address -> TPUBuffer
        self.timeout = 1_000_000
        self.max_eager_size = DEFAULT_MAX_EAGER_SIZE
        self.max_rendezvous_size = DEFAULT_MAX_RENDEZVOUS_SIZE
        self.eager_rx_buf_size = DEFAULT_EAGER_RX_BUF_SIZE
        self.pkt_enabled = False
        # Pending sends awaiting their recv partner (single-controller
        # pairing of the MPI-style send/recv API).
        self._pending_sends: dict[tuple, CallOptions] = {}
        # Kernel-stream endpoints (strm != 0 routing, SURVEY.md §3.4).
        from ..ops.streams import StreamRegistry

        self.streams = StreamRegistry()
        self._stream_cache: dict = {}

    # -- registry ---------------------------------------------------------

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis_name]

    def register_buffer(self, buf) -> None:
        self.buffers[buf.address] = buf

    def unregister_buffer(self, buf) -> None:
        self.buffers.pop(buf.address, None)

    def _buf(self, addr: int):
        if addr == 0:
            return None
        try:
            return self.buffers[addr]
        except KeyError:
            raise KeyError(f"no buffer registered at address {addr:#x}") from None

    # -- tuning registers (exchange-memory backed) ------------------------

    def tuning(self) -> TuningParams:
        rd = self.read
        defaults = TuningParams.default(self.max_rendezvous_size)
        return TuningParams(
            gather_flat_tree_max_fanin=rd(CCLOAddr.GATHER_FLAT_TREE_MAX_FANIN)
            or defaults.gather_flat_tree_max_fanin,
            gather_flat_tree_max_count=rd(CCLOAddr.GATHER_FLAT_TREE_MAX_COUNT)
            or defaults.gather_flat_tree_max_count,
            bcast_flat_tree_max_ranks=rd(CCLOAddr.BCAST_FLAT_TREE_MAX_RANKS)
            or defaults.bcast_flat_tree_max_ranks,
            reduce_flat_tree_max_ranks=rd(CCLOAddr.REDUCE_FLAT_TREE_MAX_RANKS)
            or defaults.reduce_flat_tree_max_ranks,
            reduce_flat_tree_max_count=rd(CCLOAddr.REDUCE_FLAT_TREE_MAX_COUNT)
            or defaults.reduce_flat_tree_max_count,
        )

    # -- execution --------------------------------------------------------

    def start(self, options: CallOptions) -> BaseRequest:
        if options.scenario == Operation.config:
            return self._config(options)
        if options.scenario == Operation.nop:
            req = BaseRequest("nop")
            req.running()
            req.complete(0)
            return req
        if options.scenario == Operation.send:
            return self._enqueue_send(options)
        if options.scenario == Operation.recv:
            return self._match_recv(options)
        return self._launch(options)

    def _launch(self, options: CallOptions) -> BaseRequest:
        plan = select_algorithm(
            options.scenario,
            options.count,
            dtype_nbytes(options.data_type),
            self.world,
            options.compression_flags,
            options.stream_flags,
            max_eager_size=self.max_eager_size,
            eager_rx_buf_size=self.eager_rx_buf_size,
            tuning=self.tuning(),
        )
        fn = self.compiler.lower(options, plan)

        op0 = self._buf(options.addr_0)
        op1 = self._buf(options.addr_1)
        res = self._buf(options.addr_2)
        args = []
        n = options.count
        scen = options.scenario
        in_n = n * self.world if scen in (
            Operation.scatter,
            Operation.reduce_scatter,
            Operation.alltoall,
        ) else n
        if scen == Operation.barrier:
            from jax.sharding import NamedSharding, PartitionSpec

            token_sharding = NamedSharding(self.mesh, PartitionSpec(self.axis_name))
            args.append(
                jax.device_put(np.ones((self.world, 1), np.float32), token_sharding)
            )
        else:
            args.append(_slice_to(op0.device, in_n))
            if scen == Operation.combine:
                args.append(_slice_to(op1.device, in_n))

        out = fn(*args)

        def place(req):
            if res is not None and scen != Operation.barrier:
                if res.device is None:  # host-only result: materialize first
                    res.sync_to_device()
                res.device = _place_into(res.device, out)

        req = TPURequest(options.scenario.name, [out], on_complete=place)
        req.plan = plan
        return req

    # -- send/recv pairing ------------------------------------------------

    def _enqueue_send(self, options: CallOptions) -> BaseRequest:
        """Single-controller pairing: a send parks its descriptor until the
        matching recv arrives, the role the eager rx-ring notification
        queue plays per-rank in the reference (rxbuf_seek.cpp:20-79)."""
        src = options.root_src_dst & 0xFFFF
        dst = (options.root_src_dst >> 16) & 0xFFFF
        self._pending_sends[(src, dst, options.tag)] = options
        req = BaseRequest("send")
        req.running()
        req.complete(0)
        return req

    def _match_recv(self, options: CallOptions) -> BaseRequest:
        src = options.root_src_dst & 0xFFFF
        dst = (options.root_src_dst >> 16) & 0xFFFF
        match = None
        for (s, d, tag) in self._pending_sends:
            if s == src and d == dst and (
                tag == options.tag or TAG_ANY in (tag, options.tag)
            ):
                match = (s, d, tag)
                break
        if match is None:
            req = BaseRequest("recv")
            req.running()
            req.complete(int(ErrorCode.RECEIVE_TIMEOUT_ERROR))
            return req
        send_opts = self._pending_sends.pop(match)
        pair = CallOptions(
            scenario=Operation.send,
            count=options.count,
            root_src_dst=src | (dst << 16),
            tag=match[2],
            compression_flags=options.compression_flags,
            stream_flags=options.stream_flags,
            data_type=options.data_type,
            addr_0=send_opts.addr_0,
            addr_2=options.addr_2,
        )
        return self._launch(pair)

    # -- kernel streams (stream_put flow, vadd_put analog) -----------------

    def stream_put(self, options: CallOptions) -> BaseRequest:
        """Producer -> collective fused in one program: the operand comes
        from the stream producer registered under options.tag (the strm
        field rides the tag, like the reference's strm=tag routing,
        dma_mover.cpp:497) and the payload lands in the destination's
        result buffer after its consumer kernel."""
        from ..ops.streams import splice_consumer, splice_producer
        from ..sequencer import schedules

        sid = options.tag
        src = options.root_src_dst & 0xFFFF
        dst = (options.root_src_dst >> 16) & 0xFFFF
        res = self._buf(options.addr_2)
        prod = self.streams.producer(sid)
        cons = self.streams.consumer(sid)
        key = (sid, options.count, options.root_src_dst, options.data_type,
               id(prod), id(cons))
        prog = self._stream_cache.get(key)
        if prog is None:
            import functools

            from jax.sharding import PartitionSpec

            body = functools.partial(
                schedules.sendrecv_schedule,
                src=src,
                dst=dst,
                axis=self.axis_name,
                world=self.world,
                wire=schedules.Wire(None),
            )
            body = splice_producer(body, prod, options.count)
            body = splice_consumer(body, cons)

            def wrapped(x):
                out = body(x.reshape(x.shape[-1]))
                return out.reshape(1, out.shape[-1])

            spec = PartitionSpec(self.axis_name)
            prog = jax.jit(
                jax.shard_map(
                    wrapped, mesh=self.mesh, in_specs=(spec,),
                    out_specs=spec, check_vma=False,
                )
            )
            self._stream_cache[key] = prog
        placeholder = res.device[..., : options.count]
        out = prog(placeholder)

        def place(req):
            res.device = _place_into(res.device, out)

        return TPURequest("stream_put", [out], on_complete=place)

    # -- config calls (ACCL_CONFIG switch, .c:2416-2452) -------------------

    def _config(self, options: CallOptions) -> BaseRequest:
        req = BaseRequest(f"config/{CfgFunc(options.function).name}")
        req.running()
        fn = CfgFunc(options.function)
        if fn == CfgFunc.reset_periph:
            self._pending_sends.clear()
            self.compiler._cache.clear()
        elif fn == CfgFunc.enable_pkt:
            self.pkt_enabled = True
        elif fn == CfgFunc.set_timeout:
            self.timeout = options.count
        elif fn == CfgFunc.set_max_eager_msg_size:
            # value arrives in the count field (.c:2432-2439)
            if options.count > self.eager_rx_buf_size:
                req.complete(int(ErrorCode.EAGER_THRESHOLD_INVALID))
                return req
            self.max_eager_size = options.count
        elif fn == CfgFunc.set_max_rendezvous_msg_size:
            self.max_rendezvous_size = options.count
        req.complete(0)
        return req


def _slice_to(arr, n: int):
    return arr if arr.shape[-1] == n else arr[..., :n]


def _place_into(dst, out):
    """Write a program result into a (possibly wider) result buffer."""
    if dst.shape == out.shape:
        return out
    return jax.jit(
        lambda d, o: jax.lax.dynamic_update_slice_in_dim(
            d, o.astype(d.dtype), 0, axis=-1
        )
    )(dst, out)
