"""TPUDevice: descriptor execution as compiled mesh programs.

The hardware backend (reference XRTDevice, driver/xrt/src/xrtdevice.cpp):
where XRTDevice latches descriptor words into the hostctrl kernel and an
on-FPGA firmware loop interprets them, TPUDevice resolves the descriptor's
buffer addresses against its buffer registry, asks the sequencer for a
plan, and launches the cached compiled schedule — one device program per
collective, with XLA's async dispatch standing in for the hardware call
FIFO. Single-controller SPMD replaces per-rank MPI processes: one call
executes the collective for every rank in the communicator.
"""

from __future__ import annotations

import threading
import time
from typing import Any

import numpy as np
import jax

from ..constants import (
    DEFAULT_EAGER_RX_BUF_SIZE,
    DEFAULT_MAX_EAGER_SIZE,
    DEFAULT_MAX_RENDEZVOUS_SIZE,
    CfgFunc,
    DataType,
    ErrorCode,
    Operation,
    TAG_ANY,
    TuningParams,
    dtype_nbytes,
)
from ..descriptor import CallOptions
from ..request import BaseRequest, ParkedRecvRequest, TPURequest
from ..sequencer.lowering import ScheduleCompiler
from ..sequencer.plan import select_algorithm
from ..telemetry import get_tracer
from .base import CCLOAddr, CCLODevice


class TPUDevice(CCLODevice):
    # the blockwise int8 wire (compressor lanes 4/5) is implemented in
    # the XLA schedule tier only; backends without the quantized ring
    # kernels leave this unset so the facade rejects the request up
    # front instead of letting a lane-less executor degrade it silently
    supports_quantized_wire = True
    # the capacity-masked alltoallv rotation
    # (schedules.alltoallv_schedule) is likewise XLA-schedule-tier only:
    # the native emulator's alltoall knows nothing about per-peer valid
    # counts, so the facade rejects uneven vectors on lane-less backends
    supports_alltoallv = True
    # the ALLTOALL_COMPRESS_MIN_COUNT register auto-applies the int8
    # wire to eligible fp32 alltoall(v) calls on this device (backends
    # whose alltoall is not the flat exchange the crossover was
    # calibrated for — DCNDevice's two-tier composition — opt out)
    auto_alltoall_wire = True
    # the degraded live-subset allreduce (source-masked ring,
    # schedules.allreduce_ring_schedule live_ranks=) is an XLA-tier
    # schedule like alltoallv: the native emulator's ring knows nothing
    # about a declared survivor set (its degraded path is membership
    # change — a recovery sub-communicator over the survivors)
    supports_live_subset = True

    def __init__(self, mesh, axis_name: str = "ccl",
                 hier_topology: tuple[int, int] | None = None):
        super().__init__()
        self.mesh = mesh
        self.axis_name = axis_name
        self.compiler = ScheduleCompiler(mesh, axis_name)
        # Two-tier (inner_world, outer_world) shape for the hierarchical
        # compositions: DCNDevice sets it from its (ici, dcn) mesh; a
        # flat mesh may declare a VIRTUAL factoring (the bench's
        # 8-ranks-as-4x2 emulated world). None = flat — and even with a
        # topology, hierarchical plans stay unreachable until the
        # HIER_ALLREDUCE_MIN_COUNT register is tuned on.
        self.hier_topology = hier_topology
        # Per-tier wire dtypes for hierarchical plans, set by
        # ACCL.autotune from plan.select_tier_wires (int8 on DCN / fp32
        # on ICI under the shipped calibration); default exact on both
        # tiers. Arbitrated for the canonical fp32 payload, so
        # _resolve_step applies them to fp32 calls only.
        self.hier_wires: tuple[DataType, DataType] = (DataType.none,
                                                      DataType.none)
        self.buffers: dict[int, Any] = {}  # address -> TPUBuffer
        self.timeout = 1_000_000
        self.max_eager_size = DEFAULT_MAX_EAGER_SIZE
        self.max_rendezvous_size = DEFAULT_MAX_RENDEZVOUS_SIZE
        self.eager_rx_buf_size = DEFAULT_EAGER_RX_BUF_SIZE
        self.pkt_enabled = False
        # Pending sends awaiting their recv partner (single-controller
        # pairing of the MPI-style send/recv API) and recvs parked until
        # their send arrives (the firmware retry-queue contract,
        # ccl_offload_control.c:2460-2479 — a recv with no matching
        # message is requeued, not failed, until the timeout).
        # Each signature keys a FIFO of (arrival_seq, options): every
        # notification parks, none is dropped, and TAG_ANY matching picks
        # the globally oldest across signatures — arrival order, like the
        # reference's in-order notification queue scan (rxbuf_seek.cpp:20-79).
        # Total parked sends are capped at the reference's 512-notification
        # park limit (rxbuf_seek.cpp:47-50); beyond that the send errors.
        self._pending_sends: dict[tuple, list[tuple[int, CallOptions]]] = {}
        self._park_seq = 0
        self._parked_send_count = 0
        self.MAX_PARKED_SENDS = 512
        # BOTH pending maps are guarded by _recv_mu: mutated by driver
        # threads (match-or-enqueue on send, match-or-park on recv) and
        # by waiter threads firing timeouts (unpark)
        self._recv_mu = threading.Lock()
        # XLA's CPU cross-module collectives rendezvous per device SET,
        # not per executable: two collective programs launched
        # concurrently over the same mesh interleave their participants
        # in one rendezvous and deadlock. The emulated CCLO has a single
        # sequencer anyway, so executable launches serialize here —
        # concurrent dispatches interleave at PROGRAM granularity, the
        # exact model certify_concurrent proves order-equivalence for.
        self._launch_mu = threading.Lock()
        self._pending_recvs: dict[tuple, list[ParkedRecvRequest]] = {}
        # Kernel-stream endpoints (strm != 0 routing, SURVEY.md §3.4).
        from ..ops.streams import StreamRegistry

        self.streams = StreamRegistry()
        self._stream_cache: dict = {}
        # composite-signature -> lint diagnostics (sequence lint stage)
        self._lint_cache: dict = {}
        # comm_addr -> resolved communicator context (the firmware caches
        # the addressed communicator per call, ccl_offload_control.c:2317-2372)
        self._comm_cache: dict[int, "_CommCtx"] = {}
        self._comm_extents: dict[int, int] = {}  # comm_addr -> table end
        self._group_cache: dict[tuple, "_CommCtx"] = {}  # members -> ctx

    # -- registry ---------------------------------------------------------

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis_name]

    def register_buffer(self, buf) -> None:
        self.buffers[buf.address] = buf

    def unregister_buffer(self, buf) -> None:
        self.buffers.pop(buf.address, None)

    def _buf(self, addr: int):
        if addr == 0:
            return None
        try:
            return self.buffers[addr]
        except KeyError:
            raise KeyError(f"no buffer registered at address {addr:#x}") from None

    # -- tuning registers (exchange-memory backed) ------------------------

    def tuning(self) -> TuningParams:
        rd = self.read
        defaults = TuningParams.default(self.max_rendezvous_size)
        return TuningParams(
            gather_flat_tree_max_fanin=rd(CCLOAddr.GATHER_FLAT_TREE_MAX_FANIN)
            or defaults.gather_flat_tree_max_fanin,
            gather_flat_tree_max_count=rd(CCLOAddr.GATHER_FLAT_TREE_MAX_COUNT)
            or defaults.gather_flat_tree_max_count,
            bcast_flat_tree_max_ranks=rd(CCLOAddr.BCAST_FLAT_TREE_MAX_RANKS)
            or defaults.bcast_flat_tree_max_ranks,
            reduce_flat_tree_max_ranks=rd(CCLOAddr.REDUCE_FLAT_TREE_MAX_RANKS)
            or defaults.reduce_flat_tree_max_ranks,
            reduce_flat_tree_max_count=rd(CCLOAddr.REDUCE_FLAT_TREE_MAX_COUNT)
            or defaults.reduce_flat_tree_max_count,
            # 0 is this register's meaningful default (ring everywhere),
            # so no `or defaults` fallback
            allreduce_composition_max_count=rd(
                CCLOAddr.ALLREDUCE_COMPOSITION_MAX_COUNT),
            # likewise 0 = synthesized schedules off
            synth_allreduce_max_count=rd(
                CCLOAddr.SYNTH_ALLREDUCE_MAX_COUNT),
            synth_allgather_max_count=rd(
                CCLOAddr.SYNTH_ALLGATHER_MAX_COUNT),
            synth_reduce_scatter_max_count=rd(
                CCLOAddr.SYNTH_REDUCE_SCATTER_MAX_COUNT),
            # and 0 = hierarchical composition off
            hier_allreduce_min_count=rd(
                CCLOAddr.HIER_ALLREDUCE_MIN_COUNT),
            # and 0 = quantized alltoall wire off
            alltoall_compress_min_count=rd(
                CCLOAddr.ALLTOALL_COMPRESS_MIN_COUNT),
            # and 0 = stripe-overlapped allreduce off (serial form)
            overlap_min_count=rd(CCLOAddr.OVERLAP_MIN_COUNT),
            # and 0 = latency-window synthesized schedules off
            synth_latency_max_count=rd(
                CCLOAddr.SYNTH_LATENCY_MAX_COUNT),
        )

    # -- communicator resolution (comm_addr -> rank group) -----------------

    def _comm_ctx(self, comm_addr: int) -> "_CommCtx":
        """Resolve a descriptor's comm_addr into an execution context by
        reading the rank table back from exchange memory — the same
        caching the firmware does per call (ccl_offload_control.c:2317-2372).
        comm_addr 0 or a full-world identity table is the default axis."""
        ctx = self._comm_cache.get(comm_addr)
        if ctx is not None:
            return ctx
        rows = None
        table_words = 0
        if comm_addr != 0:
            from ..communicator import Communicator

            size = self.read(comm_addr)
            if not 0 < size <= self.world:
                raise ValueError(
                    f"invalid communicator at {comm_addr:#x}: size={size}")
            nwords = 2 + size * Communicator.WORDS_PER_RANK
            table_words = nwords
            words = [self.read(comm_addr + 4 * i) for i in range(nwords)]
            comm = Communicator.from_exchmem_words(words, comm_addr)
            members = tuple(r.device_index for r in comm.ranks)
            if any(not 0 <= d < self.world for d in members):
                raise ValueError(
                    f"communicator at {comm_addr:#x} references device "
                    f"indices {members} outside world {self.world}")
            if len(set(members)) != len(members):
                raise ValueError(
                    f"communicator at {comm_addr:#x} has duplicate "
                    f"members {members}")
            if members != tuple(range(self.world)):
                rows = members
        if rows is None:
            ctx = _CommCtx(self.world, self.mesh, self.compiler, None)
        else:
            # identical member sets at different table addresses share one
            # context, so re-splits reuse the compiled schedules
            ctx = self._group_cache.get(rows)
            if ctx is None:
                ctx = self._make_group_ctx(rows)
                self._group_cache[rows] = ctx
        self._comm_cache[comm_addr] = ctx
        if table_words:
            self._comm_extents[comm_addr] = comm_addr + 4 * table_words
        return ctx

    def _make_group_ctx(self, rows: tuple) -> "_CommCtx":
        """Build the execution context for a sub-communicator (overridden
        by backends with a different mesh topology)."""
        from jax.sharding import Mesh

        devices = self.mesh.devices.reshape(-1)
        sub_mesh = Mesh(np.array([devices[r] for r in rows]),
                        (self.axis_name,))
        compiler = ScheduleCompiler(
            sub_mesh, self.axis_name,
            arith_table=self.compiler.arith_table,
            use_pallas_ring=self.compiler.use_pallas_ring,
            pallas_ring_overlap=self.compiler.pallas_ring_overlap,
            overlap_serialize=self.compiler.overlap_serialize,
        )
        return _CommCtx(len(rows), sub_mesh, compiler, rows)

    def write(self, addr: int, value: int) -> None:
        # a write into a cached communicator table invalidates that cache
        # entry (the firmware re-reads exchange memory per call; the cache
        # must not outlive the table it mirrors)
        for start, end in list(self._comm_extents.items()):
            if start <= addr < end:
                self._comm_cache.pop(start, None)
                self._comm_extents.pop(start, None)
        super().write(addr, value)

    def validate_split(self, rows: tuple) -> None:
        """Reject an unsupported rank group BEFORE the facade allocates
        exchange memory for it (backends with topology constraints
        override; the base single-controller mesh accepts any subset)."""

    def _rows_to_submesh(self, arr, ctx: "_CommCtx", n: int):
        """View the member rows of a full-world stacked buffer as a
        (group, n) array on the sub-mesh. Each row already lives on its
        member device, so this is shard re-labelling, not data movement.
        Non-addressable devices (remote hosts on a multi-process backend)
        contribute their own shards from their own processes."""
        from jax.sharding import NamedSharding, PartitionSpec

        by_dev = {s.device: s.data for s in arr.addressable_shards}
        shards = [by_dev[d][..., :n]
                  for d in ctx.mesh.devices.reshape(-1) if d in by_dev]
        sharding = NamedSharding(ctx.mesh, PartitionSpec(self.axis_name))
        return jax.make_array_from_single_device_arrays(
            (ctx.world, n), sharding, shards)

    def _scatter_rows(self, full, ctx: "_CommCtx", out):
        """Write a sub-communicator result back into the member rows of a
        full-world buffer, leaving non-member rows (and remote hosts'
        rows, which their own processes assemble) untouched."""
        by_dev = {s.device: s.data for s in full.addressable_shards}
        out_by_dev = {s.device: s.data for s in out.addressable_shards}
        shards = []
        for d in self.mesh.devices.reshape(-1):
            if d not in by_dev:
                continue  # remote device on a multi-process backend
            cur = by_dev[d]
            if d in out_by_dev:
                new = out_by_dev[d].astype(cur.dtype)
                if new.shape[-1] != cur.shape[-1]:
                    new = cur.at[..., : new.shape[-1]].set(new)
                shards.append(new)
            else:
                shards.append(cur)
        return jax.make_array_from_single_device_arrays(
            full.shape, full.sharding, shards)

    # -- execution --------------------------------------------------------

    def start(self, options: CallOptions) -> BaseRequest:
        if options.scenario == Operation.config:
            return self._config(options)
        if options.scenario == Operation.nop:
            req = BaseRequest("nop")
            req.running()
            req.complete(0)
            return req
        if options.scenario == Operation.send:
            return self._enqueue_send(options)
        if options.scenario == Operation.recv:
            return self._match_recv(options)
        return self._launch(options)

    def _apply_alltoall_wire(self, options: CallOptions,
                             tuning: TuningParams) -> CallOptions:
        """The ALLTOALL_COMPRESS_MIN_COUNT register, applied where the
        hier wires are: per-descriptor, in front of plan selection, for
        BOTH the eager path and the call-sequence path. An uncompressed
        unstreamed-or-streamed fp32 alltoall(v) whose payload clears the
        register ships the blockwise int8 wire (compress_dtype=int8 +
        ETH_COMPRESSED, exactly the descriptor the facade's explicit
        `compress_dtype=` seam would have produced — same plan, same
        compiled program, same cache key). Register 0 — the default —
        returns the descriptor untouched, so selection stays bit-for-bit
        the fp32 wire. Applied to fp32 calls only (the dtype the
        crossover was calibrated for) on devices that ship the quantized
        lanes."""
        reg = tuning.alltoall_compress_min_count
        if (reg <= 0
                or options.scenario != Operation.alltoall
                or options.data_type != DataType.float32
                or options.compress_dtype != DataType.none
                or int(options.compression_flags) != 0
                or not getattr(self, "auto_alltoall_wire", False)
                or not getattr(self, "supports_quantized_wire", False)):
            return options
        # what actually crosses each hop: the dense slot for alltoall,
        # max(peer_counts) elements for the capacity-bounded alltoallv —
        # the same payload the FLAT_ALLTOALLV cost shape charges, so a
        # heavily-capped exchange is not quantized in the regime the
        # calibration says the exact wire wins
        hop_elems = (max(options.peer_counts) if options.peer_counts
                     else options.count)
        if hop_elems * dtype_nbytes(options.data_type) < reg:
            return options
        if (DataType.float32, DataType.int8) not in self.compiler.arith_table:
            return options
        import dataclasses

        from ..constants import CompressionFlags

        return dataclasses.replace(
            options, compress_dtype=DataType.int8,
            compression_flags=CompressionFlags.ETH_COMPRESSED)

    def _resolve_step(self, options: CallOptions, ctx: "_CommCtx",
                      tuning: TuningParams | None = None):
        """Per-descriptor plan selection + stream-endpoint resolution —
        ONE source for both the eager path and the call-sequence path, so
        the fused program can never silently diverge from what eager
        execution would run. Returns (plan, producer, consumer)."""
        # the two-tier topology applies only to the full-world
        # communicator: a sub-communicator is its own (usually flat)
        # world and selects flat schedules
        topo = self.hier_topology if (
            self.hier_topology is not None and ctx.rows is None) else None
        plan = select_algorithm(
            options.scenario,
            options.count,
            dtype_nbytes(options.data_type),
            ctx.world,
            options.compression_flags,
            options.stream_flags,
            max_eager_size=self.max_eager_size,
            eager_rx_buf_size=self.eager_rx_buf_size,
            tuning=tuning if tuning is not None else self.tuning(),
            # the wire rides the Plan so timing.predict on recorded
            # plans charges compressed widths (+ scale side-channel)
            compress_dtype=options.compress_dtype,
            topology=topo,
            # arbitrated for fp32 (the canonical payload); other dtypes
            # stay exact on both tiers — their arith rows may not exist
            tier_wires=(self.hier_wires
                        if options.data_type == DataType.float32
                        else (DataType.none, DataType.none)),
            # alltoallv: the static per-peer capacity vector rides the
            # descriptor into the Plan (frozen, cache-keyed)
            peer_counts=options.peer_counts,
            # degraded live-subset allreduce: the declared survivor set
            # rides the descriptor into the Plan the same way
            live_ranks=options.live_ranks,
        )
        # stream ids ride dedicated descriptor bytes (word 8), so the tag
        # stays available for matching
        from ..constants import StreamFlags

        producer = consumer = None
        if options.stream_flags & StreamFlags.OP0_STREAM:
            producer = self.streams.producer(options.op0_stream_id)
        if options.stream_flags & StreamFlags.RES_STREAM:
            consumer = self.streams.consumer(options.res_stream_id,
                                             strict=True)
        return plan, producer, consumer

    def _launch(self, options: CallOptions) -> BaseRequest:
        ctx = self._comm_ctx(options.comm_addr)
        # send/recv arrive here already PAIRED (start() routes the raw
        # halves through the parking maps; _pair merged their endpoint ids)
        tuning = self.tuning()
        options = self._apply_alltoall_wire(options, tuning)
        plan, producer, consumer = self._resolve_step(options, ctx, tuning)
        if options.stream_flags:
            fn = ctx.compiler.lower_streamed(options, plan, producer, consumer)
        else:
            fn = ctx.compiler.lower(options, plan)

        op0 = self._buf(options.addr_0)
        op1 = self._buf(options.addr_1)
        res = self._buf(options.addr_2)
        args = []
        scen = options.scenario
        # single source for the wide-operand width rule, shared with the
        # call-sequence dataflow resolution
        from ..sequencer.sequence import step_in_elems

        in_n = step_in_elems(options, ctx.world)
        if scen == Operation.barrier:
            from jax.sharding import NamedSharding, PartitionSpec

            token_sharding = NamedSharding(ctx.mesh, PartitionSpec(self.axis_name))
            args.append(
                jax.device_put(np.ones((ctx.world, 1), np.float32), token_sharding)
            )
        elif ctx.rows is None:
            args.append(_slice_to(op0.device, in_n))
            if scen == Operation.combine:
                args.append(_slice_to(op1.device, in_n))
        else:
            args.append(self._rows_to_submesh(op0.device, ctx, in_n))
            if scen == Operation.combine:
                args.append(self._rows_to_submesh(op1.device, ctx, in_n))

        with self._launch_mu:  # one collective executable in flight
            out = fn(*args)
            jax.block_until_ready(out)

        def place(req):
            if res is not None and scen != Operation.barrier:
                if res.device is None:  # host-only result: materialize first
                    res.sync_to_device()
                if ctx.rows is None:
                    res.device = _place_into(res.device, out)
                else:
                    res.device = self._scatter_rows(res.device, ctx, out)

        req = TPURequest(options.scenario.name, [out], on_complete=place)
        req.plan = plan
        if get_tracer().active:
            # the facade span drains this: every traced call carries its
            # timing.predict estimate next to the measured duration
            req.predicted_s = self._predict_call(options, plan, ctx.world)
        return req

    def _predict_call(self, options: CallOptions, plan,
                      world: int) -> float | None:
        """timing.predict estimate for one resolved call under the
        shipped default link (telemetry.feedback.default_link, the same
        calibration autotune consults); None when no timing model is
        committed or the plan has no cost shape. Uses the aggregate
        cost shape — the regime the shipped emulator fit calibrates."""
        from ..sequencer.timing import predict
        from ..telemetry.feedback import default_link

        link = default_link()
        if link is None or plan is None:
            return None
        try:
            return predict(link, options.scenario, plan, options.count,
                           dtype_nbytes(options.data_type), world,
                           rx_buf_bytes=self.eager_rx_buf_size,
                           aggregate=True)
        except (ValueError, KeyError, ZeroDivisionError):
            return None

    def predict_sequence_cost(self, prepared) -> float | None:
        """Predicted steady-state seconds for ONE dispatch of a
        prepared batch under the shipped default link — the admission
        price the multi-tenant scheduler budgets a tenant's program at
        BEFORE dispatching it (timing.predict_prepared over the frozen
        steps + plans, aggregate cost shape). None when no calibration
        is committed or the batch has no priceable step (the scheduler
        then falls back to its bytes proxy rather than admitting for
        free)."""
        from ..sequencer.timing import predict_prepared
        from ..telemetry.feedback import default_link

        link = default_link()
        if link is None:
            return None
        try:
            return predict_prepared(
                link, prepared.desc.steps, prepared.plans,
                prepared.ctx.world,
                rx_buf_bytes=self.eager_rx_buf_size, aggregate=True)
        except (ValueError, KeyError, ZeroDivisionError):
            return None

    # -- call sequences (device-resident descriptor batches) ---------------

    def start_sequence(self, options_list, lint: str = "error",
                       persistent=frozenset()) -> BaseRequest:
        """Execute a recorded batch of call descriptors as ONE compiled
        device program (sequencer.sequence.SequencePlan): a single
        dispatch for the whole chain, intermediate results threaded
        on-device between stages instead of re-crossing the host. Plans
        are selected per step with the live tuning registers, exactly as
        the eager path would.

        `lint` gates the batch through the static analyzer
        (accl_tpu/analysis/) BEFORE anything compiles: "error" rejects
        hazardous batches with a typed LintError, "warn" logs the
        diagnostics and proceeds, "off" skips the stage, "deep" adds
        the exhaustive-interleaving model checker (ACCL205/206,
        budgeted) on top of "error" enforcement. Results are cached
        under the same composite signature the compiled program is —
        keyed per tier, so a re-recorded batch re-lints nothing and
        the default tier never pays for the deep one.

        `persistent` (buffer addresses) declares device-resident state
        the batch refreshes partial-width by design — the hazard pass
        waives ACCL101 for those buffers only (docs/lint.md)."""
        return self.dispatch_sequence(
            self.prepare_sequence(options_list, lint,
                                  persistent=persistent))

    def prepare_sequence(self, options_list, lint: str = "error",
                         persistent=frozenset()) -> "_PreparedSequence":
        """The resolve half of `start_sequence`: wire-register rewrite,
        per-step plan selection, lint gate, dataflow resolution and
        compile — everything whose result is a pure function of the
        descriptor batch and the live registers — captured in a
        re-dispatchable handle. `dispatch_sequence(prepared)` then runs
        the compiled program over the bound buffers' CURRENT contents:
        steady-state cost is one dispatch, none of the per-call
        re-resolution (the facade's SequenceRecorder.compile() /
        SequenceProgram ride this seam). The handle pins the registers
        it was resolved under — re-prepare after retuning."""
        from ..descriptor import SequenceDescriptor
        from ..sequencer.sequence import SequencePlan

        desc = SequenceDescriptor(tuple(options_list))
        ctx = self._comm_ctx(desc.comm_addr)
        tuning = self.tuning()  # read the registers once for the batch
        # the alltoall wire register rewrites descriptors BEFORE the
        # batch signature / lint / compile pipeline sees them, so the
        # fused program is keyed, traced and certified on what actually
        # runs (register 0 leaves every descriptor untouched)
        steps = tuple(self._apply_alltoall_wire(o, tuning)
                      for o in desc.steps)
        if steps != desc.steps:
            desc = SequenceDescriptor(steps)
        tracer = get_tracer()
        # the composite signature tags every phase/step span, so one
        # batch's record -> lint -> compile -> dispatch pipeline can be
        # followed across tracks in the exported trace, and it keys the
        # per-pair interference-verdict cache. A content digest, not
        # hash(): enum hashes are PYTHONHASHSEED-salted, and the
        # signature must match across runs so archived traces correlate.
        # Computed unconditionally — a program prepared with tracing OFF
        # must still dispatch with its signature (a tracer enabled later,
        # and certify_concurrent, both need it).
        import hashlib

        sig = hashlib.sha256(
            repr(desc.signature()).encode()).hexdigest()[:16]
        with tracer.span("record", cat="phase", track="device") as sp:
            sp.set(signature=sig, n_steps=len(desc.steps))
            plans = []
            endpoints = []
            for opts in desc.steps:
                plan, producer, consumer = self._resolve_step(opts, ctx,
                                                              tuning)
                plans.append(plan)
                endpoints.append((producer, consumer))

        if lint != "off":
            with tracer.span("lint", cat="phase", track="device") as sp:
                sp.set(signature=sig, tier=lint)
                self._lint_batch(desc, tuple(plans), ctx, lint,
                                 persistent=frozenset(persistent))

        with tracer.span("compile", cat="phase", track="device") as sp:
            sp.set(signature=sig)
            seq = SequencePlan(desc, plans, ctx.world, endpoints)
            bufs = {addr: self._buf(addr) for addr in seq.buffer_addrs}
            for addr, need in seq.min_widths().items():
                have = bufs[addr].shape[-1]
                if have < need:
                    raise ValueError(
                        f"sequence needs {need} elements in buffer "
                        f"{addr:#x}, which holds {have}")
            fn = ctx.compiler.compile_sequence(seq)
        # the interference summary rides every prepared program — pure
        # Python over the descriptors (the exact-event thunk defers any
        # tracing to an escalated pair), so extraction is O(steps)
        from ..analysis.interference import footprint_from_steps

        footprint = footprint_from_steps(
            desc.steps, ctx.world,
            persistent=frozenset(persistent),
            use_pallas_ring=ctx.compiler.use_pallas_ring,
            pallas_ring_overlap=ctx.compiler.pallas_ring_overlap,
            plans=tuple(plans), axis_name=self.axis_name,
            signature=sig)
        return _PreparedSequence(desc=desc, plans=tuple(plans), seq=seq,
                                 fn=fn, bufs=bufs, ctx=ctx, sig=sig,
                                 footprint=footprint)

    def dispatch_sequence(self, prepared: "_PreparedSequence") -> BaseRequest:
        """The dispatch half of `start_sequence`: run a prepared batch's
        compiled program over its bound buffers' current device contents
        and place the results. Safe to call repeatedly on one handle —
        each call is an independent request."""
        from ..request import SequenceRequest

        desc, seq, ctx = prepared.desc, prepared.seq, prepared.ctx
        plans, fn, bufs = prepared.plans, prepared.fn, prepared.bufs
        sig = prepared.sig
        tracer = get_tracer()
        with tracer.span("dispatch", cat="phase", track="device") as sp:
            sp.set(signature=sig)
            if prepared.cert is not None:
                # a certify_concurrent-stamped tenant: the flight
                # recorder can name which admitted set this dispatch
                # belonged to when it wedges
                sp.set(interference_cert=prepared.cert)
            args = []
            for addr in seq.buffer_addrs:
                buf = bufs[addr]
                if buf.device is None:  # host-only buffer not yet staged
                    buf.sync_to_device()
                arr = buf.device
                if ctx.rows is None:
                    args.append(arr)
                else:
                    args.append(self._rows_to_submesh(arr, ctx,
                                                      arr.shape[-1]))
            # serialize the launch (see _launch_mu): async dispatch must
            # not let a second tenant's collectives enter the rendezvous
            # before this program's have all arrived, so block inside
            with self._launch_mu:
                outs = fn(*args)
                jax.block_until_ready(outs)

        out_bufs = [bufs[a] for a in seq.out_addrs]

        def place(req):
            for buf, out in zip(out_bufs, outs):
                if buf.device is None:  # host-only result: materialize
                    buf.sync_to_device()
                if ctx.rows is None:
                    buf.device = _place_into(buf.device, out)
                else:
                    buf.device = self._scatter_rows(buf.device, ctx, out)

        req = SequenceRequest(list(outs), list(plans), on_complete=place)
        # the signature names the program on the request whether or not
        # a tracer is live — telemetry attached later (or a debugger
        # poking a wedged request) must still see which program owns it
        req.signature = sig
        if prepared.cert is not None:
            req.interference_cert = prepared.cert
        if tracer.active:
            # per-step marker spans: the fused program executes the steps
            # inside ONE dispatch, so each step carries its timing.predict
            # estimate (and the batch signature) rather than a host-
            # measured duration — instants, not intervals, honestly.
            # Predictions are a pure function of the frozen (steps,
            # plans), so they are computed once per handle, not per
            # dispatch (the re-resolution cost prepare/dispatch splits
            # out must not sneak back in through telemetry).
            if prepared.preds is None:
                prepared.preds = [self._predict_call(o, p, ctx.world)
                                  for o, p in zip(desc.steps, plans)]
            preds = prepared.preds
            known = [p for p in preds if p is not None]
            req.predicted_s = sum(known) if known else None
            now = time.perf_counter_ns()
            for i, (o, p, pred) in enumerate(zip(desc.steps, plans, preds)):
                step_args = {
                    "op": o.scenario.name,
                    "count": o.count,
                    "step": i,
                    "world": ctx.world,
                    "algorithm": p.algorithm.name,
                    "protocol": p.protocol.name,
                    "signature": sig,
                }
                if pred is not None:
                    step_args["predicted_s"] = pred
                tracer.emit(f"step{i}:{o.scenario.name}", "step", "device",
                            ts_ns=now, dur_ns=0, args=step_args)
        return req

    def _lint_batch(self, desc, plans, ctx, mode: str,
                    persistent: frozenset = frozenset()) -> None:
        """The opt-out static gate in front of compile_sequence: lint
        diagnostics are cached by the batch's composite signature (the
        same canonical renaming the compile cache keys on), so steady
        state pays a dict lookup. Buffer widths come from the registry
        where registered, enabling the static underflow check."""
        from ..analysis.diagnostics import enforce
        from ..analysis.linter import SequenceLinter

        widths = {}
        canon: list[int] = []  # widths in canonical (renamed) order, so
        # the cache can never alias two batches whose buffers differ
        rename: dict[int, int] = {}  # addr -> canonical index, for the
        # persistent-annotation part of the key (addresses are arena-
        # unique, so the raw set would defeat cross-buffer cache hits)
        for opts in desc.steps:
            for addr in (opts.addr_0, opts.addr_1, opts.addr_2):
                if addr and addr not in rename:
                    rename[addr] = len(rename)
                buf = self.buffers.get(addr)
                if addr and buf is not None and addr not in widths:
                    widths[addr] = buf.shape[-1]
                    canon.append(widths[addr])
        deep = mode == "deep"
        canon_persist = tuple(sorted(
            rename[a] for a in persistent if a in rename))
        key = (desc.signature(), plans, ctx.world, tuple(canon),
               ctx.compiler.use_pallas_ring,
               ctx.compiler.pallas_ring_overlap, canon_persist, deep)
        diags = self._lint_cache.get(key)
        if diags is None:
            linter = SequenceLinter(
                ctx.world,
                use_pallas_ring=ctx.compiler.use_pallas_ring,
                pallas_ring_overlap=ctx.compiler.pallas_ring_overlap,
                deep=deep,
                axis_name=self.axis_name,
                # lint against the lanes this device will LOWER with: a
                # custom arith_config's extra rows must not be rejected,
                # and its removed rows must not slip through
                arith_table=ctx.compiler.arith_table,
            )
            diags = tuple(linter.lint(desc.steps, plans,
                                      buffer_widths=widths,
                                      persistent_addrs=persistent))
            self._lint_cache[key] = diags
        enforce(diags, mode)

    # -- send/recv pairing ------------------------------------------------

    def _enqueue_send(self, options: CallOptions) -> BaseRequest:
        """Single-controller pairing: a send parks its descriptor until the
        matching recv arrives, the role the eager rx-ring notification
        queue plays per-rank in the reference (rxbuf_seek.cpp:20-79)."""
        src = options.root_src_dst & 0xFFFF
        dst = (options.root_src_dst >> 16) & 0xFFFF
        # match-or-enqueue is ATOMIC under _recv_mu (which guards BOTH
        # pending maps): otherwise a concurrent recv could scan the send
        # map before this insert while this scan misses its parking —
        # both sides parked, lost wakeup. The claimed recv resolves
        # outside the lock (launch may compile).
        parked = None
        with self._recv_mu:
            while parked is None:
                # oldest-parked-first across ALL matching signatures: a
                # TAG_ANY send must pair with the earliest-arrived recv
                # even when several tag keys match (arrival-order scan,
                # rxbuf_seek.cpp:20-79); per-queue heads are each queue's
                # minimum, so comparing heads finds the global minimum
                best_key = None
                for key, queue in self._pending_recvs.items():
                    ca, s, d, tag = key
                    if ca == options.comm_addr and s == src and d == dst and (
                        tag == options.tag or TAG_ANY in (tag, options.tag)
                    ) and (
                        best_key is None
                        or queue[0]._park_seq
                        < self._pending_recvs[best_key][0]._park_seq
                    ):
                        best_key = key
                if best_key is None:
                    break
                queue = self._pending_recvs[best_key]
                candidate = queue.pop(0)
                if not queue:
                    self._pending_recvs.pop(best_key, None)
                if candidate.claim():  # skip already-timed-out
                    parked = candidate
            if parked is None:
                if self._parked_send_count >= self.MAX_PARKED_SENDS:
                    # park backlog full: fail loudly instead of growing
                    # without bound (reference caps parked notifications
                    # at 512, rxbuf_seek.cpp:47-50)
                    req = BaseRequest("send")
                    req.running()
                    req.complete(int(
                        ErrorCode.DEQUEUE_BUFFER_SPARE_BUFFER_STATUS_ERROR))
                    return req
                self._park_seq += 1
                self._parked_send_count += 1
                self._pending_sends.setdefault(
                    (options.comm_addr, src, dst, options.tag), []
                ).append((self._park_seq, options))
        if parked is not None:
            parked.resolve(self._launch(self._pair(parked.options, options)))
        req = BaseRequest("send")
        req.running()
        req.complete(0)
        return req

    def _pair(self, recv_opts: CallOptions, send_opts: CallOptions) -> CallOptions:
        src = recv_opts.root_src_dst & 0xFFFF
        dst = (recv_opts.root_src_dst >> 16) & 0xFFFF
        # stream endpoints merge from the side that owns them: the send
        # contributes OP0 (its operand may come from a producer kernel,
        # reference accl.hpp:190 stream-send overload), the recv RES (its
        # result may feed a consumer kernel, accl.hpp:278)
        from ..constants import StreamFlags

        flags = StreamFlags.NO_STREAM
        op0_id = res_id = 0
        if send_opts.stream_flags & StreamFlags.OP0_STREAM:
            flags |= StreamFlags.OP0_STREAM
            op0_id = send_opts.op0_stream_id
        if recv_opts.stream_flags & StreamFlags.RES_STREAM:
            flags |= StreamFlags.RES_STREAM
            res_id = recv_opts.res_stream_id
        return CallOptions(
            scenario=Operation.send,
            count=recv_opts.count,
            comm_addr=recv_opts.comm_addr,
            root_src_dst=src | (dst << 16),
            tag=send_opts.tag,
            compression_flags=recv_opts.compression_flags,
            stream_flags=flags,
            op0_stream_id=op0_id,
            res_stream_id=res_id,
            data_type=recv_opts.data_type,
            addr_0=send_opts.addr_0,
            addr_2=recv_opts.addr_2,
        )

    def _match_recv(self, options: CallOptions) -> BaseRequest:
        src = options.root_src_dst & 0xFFFF
        dst = (options.root_src_dst >> 16) & 0xFFFF
        # match-or-park is ATOMIC under _recv_mu, mirroring _enqueue_send:
        # scanning the send map and parking must not interleave with a
        # concurrent send's scan-and-insert (lost wakeup / mutation during
        # iteration)
        with self._recv_mu:
            # oldest-send-first across ALL matching signatures (see
            # _enqueue_send): a TAG_ANY recv drains sends in arrival
            # order even when they parked under different tag keys
            match = None
            for key, queue in self._pending_sends.items():
                ca, s, d, tag = key
                if ca == options.comm_addr and s == src and d == dst and (
                    tag == options.tag or TAG_ANY in (tag, options.tag)
                ) and (
                    match is None
                    or queue[0][0] < self._pending_sends[match][0][0]
                ):
                    match = key
            if match is None:
                # park until the send arrives or the configured timeout
                # lapses (reference: unmatched recvs ride the retry queue
                # until HOUSEKEEP_TIMEOUT, ccl_offload_control.c:2460-2479)
                req = ParkedRecvRequest(options, self.timeout / 1e6)
                self._park_seq += 1
                req._park_seq = self._park_seq
                key = (options.comm_addr, src, dst, options.tag)
                self._pending_recvs.setdefault(key, []).append(req)

                def unpark(_key=key, _req=req):
                    with self._recv_mu:
                        queue = self._pending_recvs.get(_key)
                        if queue is not None:
                            try:
                                queue.remove(_req)  # by identity of self
                            except ValueError:
                                pass
                            if not queue:
                                self._pending_recvs.pop(_key, None)

                req._unpark = unpark
                return req
            queue = self._pending_sends[match]
            _seq, send_opts = queue.pop(0)
            self._parked_send_count -= 1
            if not queue:
                self._pending_sends.pop(match, None)
        return self._launch(self._pair(options, send_opts))

    # -- kernel streams (stream_put flow, vadd_put analog) -----------------

    def stream_put(self, options: CallOptions) -> BaseRequest:
        """Producer -> collective fused in one program: the operand comes
        from the stream producer registered under the descriptor's
        op0_stream_id byte (the reference's strm routing, dma_mover.cpp:497)
        and the payload lands in the destination's result buffer after its
        consumer kernel."""
        from ..ops.streams import splice_consumer, splice_producer
        from ..sequencer import schedules

        sid = options.op0_stream_id
        src = options.root_src_dst & 0xFFFF
        dst = (options.root_src_dst >> 16) & 0xFFFF
        res = self._buf(options.addr_2)
        prod = self.streams.producer(sid)
        cons = self.streams.consumer(sid)
        key = (sid, options.count, options.root_src_dst, options.data_type,
               id(prod), id(cons))
        prog = self._stream_cache.get(key)
        if prog is None:
            import functools

            from jax.sharding import PartitionSpec

            body = functools.partial(
                schedules.sendrecv_schedule,
                src=src,
                dst=dst,
                axis=self.axis_name,
                world=self.world,
                wire=schedules.Wire(None),
            )
            body = splice_producer(body, prod, options.count)
            body = splice_consumer(body, cons)

            def wrapped(x):
                out = body(x.reshape(x.shape[-1]))
                return out.reshape(1, out.shape[-1])

            spec = PartitionSpec(self.axis_name)
            prog = jax.jit(
                jax.shard_map(
                    wrapped, mesh=self.mesh, in_specs=(spec,),
                    out_specs=spec, check_vma=False,
                )
            )
            self._stream_cache[key] = prog
        placeholder = res.device[..., : options.count]
        out = prog(placeholder)

        def place(req):
            res.device = _place_into(res.device, out)

        return TPURequest("stream_put", [out], on_complete=place)

    def dump_eager_rx_buffers(self) -> str:
        """The XLA executor's analog of the rx-ring dump
        (accl.cpp:964-1012): this backend has no spare-buffer ring — XLA
        owns the data plane — so the parked recv/send queues (its
        rx-notification parking, rxbuf_seek.cpp role) are the observable
        eager state."""
        with self._recv_mu:
            lines = [
                f"eager rx (XLA executor): buf_size {self.eager_rx_buf_size}"
                f", parked sends {self._parked_send_count}"
                f"/{self.MAX_PARKED_SENDS}"
            ]
            for (ca, s, d, tag), q in sorted(self._pending_recvs.items()):
                for parked in q:
                    lines.append(
                        f"parked recv: comm {ca:#x} src {s} dst {d} "
                        f"tag {tag} seq {parked._park_seq}")
            for (ca, s, d, tag), q in sorted(self._pending_sends.items()):
                for seq, opts in q:
                    lines.append(
                        f"parked send: comm {ca:#x} src {s} dst {d} "
                        f"tag {tag} seq {seq} count {opts.count}")
        return "\n".join(lines)

    def wire_stats(self) -> dict:
        """The stats2 counter surface mirrored onto the XLA tier
        (EmuRank.wire_stats's schema, every field zero): XLA owns this
        backend's data plane — there is no native wire, so there are no
        native wire faults to count — but consumers (telemetry wire-
        health export, the resilience manager's lossy-vs-dark
        classifier) read one stable dict shape across device kinds."""
        from .emu_device import STATS2_FIELDS

        return {name: 0 for name in STATS2_FIELDS}

    # -- config calls (ACCL_CONFIG switch, .c:2416-2452) -------------------

    def _config(self, options: CallOptions) -> BaseRequest:
        req = BaseRequest(f"config/{CfgFunc(options.function).name}")
        req.running()
        fn = CfgFunc(options.function)
        if fn == CfgFunc.reset_periph:
            with self._recv_mu:
                self._pending_sends.clear()
                self._parked_send_count = 0
                queues = [q for q in self._pending_recvs.values()]
                self._pending_recvs.clear()
            for queue in queues:
                for parked in queue:
                    if parked.claim():
                        parked._timeout_fire()
            self.compiler._cache.clear()
            self._lint_cache.clear()
            self._comm_cache.clear()
            self._comm_extents.clear()
            self._group_cache.clear()
        elif fn == CfgFunc.enable_pkt:
            self.pkt_enabled = True
        elif fn == CfgFunc.set_timeout:
            self.timeout = options.count
        elif fn == CfgFunc.set_max_eager_msg_size:
            # value arrives in the count field (.c:2432-2439)
            if options.count > self.eager_rx_buf_size:
                req.complete(int(ErrorCode.EAGER_THRESHOLD_INVALID))
                return req
            self.max_eager_size = options.count
        elif fn == CfgFunc.set_max_rendezvous_msg_size:
            self.max_rendezvous_size = options.count
        req.complete(0)
        return req


class _PreparedSequence:
    """A resolved + compiled descriptor batch, ready to dispatch any
    number of times (TPUDevice.prepare_sequence / dispatch_sequence):
    the descriptor batch post wire-register rewrite, its per-step
    plans, the fused SequencePlan, the compiled program, and the bound
    buffer objects (re-read per dispatch, so their current device
    contents flow in)."""

    __slots__ = ("desc", "plans", "seq", "fn", "bufs", "ctx", "sig",
                 "preds", "footprint", "cert")

    def __init__(self, desc, plans, seq, fn, bufs, ctx, sig,
                 footprint=None):
        self.desc = desc
        self.plans = plans
        self.seq = seq
        self.fn = fn
        self.bufs = bufs
        self.ctx = ctx
        self.sig = sig
        # per-step timing.predict estimates, computed lazily on the
        # first traced dispatch and reused (pure function of the frozen
        # steps + plans)
        self.preds = None
        # the cross-program interference summary (analysis/interference
        # ProgramFootprint) and, once ACCL.certify_concurrent admits
        # this program into a pairwise-clean set, the certificate id
        # naming that set — threaded through dispatch spans/requests
        self.footprint = footprint
        self.cert = None


class _CommCtx:
    """Resolved communicator: group size, the mesh it executes on, its
    schedule compiler, and the member rows of full-world buffers (None for
    the default full-axis communicator)."""

    __slots__ = ("world", "mesh", "compiler", "rows", "_member_here")

    def __init__(self, world, mesh, compiler, rows):
        self.world = world
        self.mesh = mesh
        self.compiler = compiler
        self.rows = rows
        self._member_here = None  # lazy per-process membership cache


def _slice_to(arr, n: int):
    return arr if arr.shape[-1] == n else arr[..., :n]


def _place_into(dst, out):
    """Write a program result into a (possibly wider) result buffer."""
    if dst.shape == out.shape:
        return out
    return jax.jit(
        lambda d, o: jax.lax.dynamic_update_slice_in_dim(
            d, o.astype(d.dtype), 0, axis=-1
        )
    )(dst, out)
