"""EmuDevice: ctypes binding to the native multi-rank emulator runtime.

The SimDevice analog (reference driver/xrt/src/simdevice.cpp talking ZMQ
to test/model/emulator): each EmuRank owns one native runtime instance —
a rank with its own sequencer thread, TCP links, eager rx ring and
rendezvous queues (native/src/runtime.cpp). Unlike the single-controller
TPUDevice, this backend is genuinely per-rank: N EmuRanks (threads or
processes) execute collectives against each other over sockets, which is
how the reference's emulator-based CI runs the gtest suite with no
hardware in the loop (SURVEY.md §4).
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import socket
import subprocess
import threading
from typing import Literal, overload

import numpy as np

from ..constants import (
    ACCLError,
    DEFAULT_EAGER_RX_BUF_SIZE,
    DEFAULT_MAX_EAGER_SIZE,
    DEFAULT_NUM_EAGER_RX_BUFS,
    Operation,
    TAG_ANY,
    from_numpy_dtype,
)
from ..descriptor import CallOptions

_NATIVE_DIR = pathlib.Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libacclrt.so"
_lib = None
_lib_lock = threading.Lock()


# Field names of the versioned accl_rt_get_stats2 counter surface, in
# native index order (native/include/acclrt.h accl_rt_stat2). The first
# five are the classic sequencer counters; the rest are the reliability
# sublayer's wire-health counters (CRC/dup drops, selective-retransmit
# ack/nack traffic, seeded fault-injection tallies, cumulative ns of
# CRC+ack bookkeeping). The native return value may exceed
# len(STATS2_FIELDS) on a newer library — unknown trailing counters are
# ignored, never misnamed.
STATS2_FIELDS = (
    "passes", "parks", "park_ns", "seek_hit", "seek_miss",
    "tx_frames", "rx_frames", "crc_drops", "dup_drops",
    "retx_sent", "retx_miss", "nack_sent", "nack_rx",
    "ack_sent", "ack_rx", "rndzv_drops",
    "inj_loss", "inj_corrupt", "inj_dup", "inj_reorder", "rely_ns",
    # vectored-wire transmit shape: syscalls issued for frame transmit
    # and frames shipped inside a multi-frame writev/sendmmsg batch
    # (tx_syscalls / tx_frames is the per-frame syscall ratio)
    "tx_syscalls", "tx_batched",
)

# (The repair-activity subset the resilience escalation policy reads —
# lossy-link vs dead-rank classification — is single-sourced as
# telemetry.export.WIRE_FAULT_KEYS, next to the exporter that renders
# these counters.)


class NativeSpan(ctypes.Structure):
    """ctypes mirror of accl_rt_span_t (native/include/acclrt.h): one
    record of the device-resident trace ring per completed call."""

    _fields_ = [
        ("opcode", ctypes.c_uint32),
        ("retcode", ctypes.c_uint32),
        ("detail", ctypes.c_uint32),
        ("count", ctypes.c_uint32),
        ("bytes", ctypes.c_uint64),
        ("start_ns", ctypes.c_uint64),
        ("end_ns", ctypes.c_uint64),
        ("d_passes", ctypes.c_uint64),
        ("d_parks", ctypes.c_uint64),
        ("d_seek_hit", ctypes.c_uint64),
        ("d_seek_miss", ctypes.c_uint64),
    ]


def load_native():
    """Load (building if needed) the native runtime library.

    ACCL_NATIVE_LIB overrides the library path — the sanitizer CI lane
    points it at the ASan/UBSan build (native/libacclrt.san.so, `make
    -C native sanitize`) so the same test suite exercises the
    instrumented data plane without touching the default artifact."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        override = os.environ.get("ACCL_NATIVE_LIB")
        if override:
            lib_path = pathlib.Path(override).resolve()
        else:
            # always invoke make: a fresh build is a no-op, and a stale
            # .so silently shadowing source edits is worse than the
            # fork cost
            subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                           capture_output=True)
            lib_path = _LIB_PATH
        lib = ctypes.CDLL(str(lib_path))
        lib.accl_rt_create.restype = ctypes.c_void_p
        lib.accl_rt_create.argtypes = [
            ctypes.c_uint32, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint16),
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_uint64,
        ]
        lib.accl_rt_create_ex.restype = ctypes.c_void_p
        lib.accl_rt_create_ex.argtypes = lib.accl_rt_create.argtypes + [
            ctypes.c_uint32,
        ]
        lib.accl_rt_destroy.argtypes = [ctypes.c_void_p]
        lib.accl_rt_start.restype = ctypes.c_int64
        lib.accl_rt_start.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.accl_rt_test.restype = ctypes.c_int
        lib.accl_rt_test.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.accl_rt_wait.restype = ctypes.c_int
        lib.accl_rt_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                     ctypes.c_uint64]
        lib.accl_rt_retcode.restype = ctypes.c_uint32
        lib.accl_rt_retcode.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.accl_rt_duration_ns.restype = ctypes.c_uint64
        lib.accl_rt_duration_ns.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.accl_rt_release.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.accl_rt_read.restype = ctypes.c_uint32
        lib.accl_rt_read.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.accl_rt_write.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                      ctypes.c_uint32]
        lib.accl_rt_get_stats.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(ctypes.c_uint64)]
        lib.accl_rt_get_stats2.restype = ctypes.c_size_t
        lib.accl_rt_get_stats2.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t,
        ]
        lib.accl_rt_dump_rxbufs.restype = ctypes.c_size_t
        lib.accl_rt_dump_rxbufs.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                            ctypes.c_size_t]
        lib.accl_rt_trace_read.restype = ctypes.c_size_t
        lib.accl_rt_trace_read.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(NativeSpan), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.accl_rt_kill.argtypes = [ctypes.c_void_p]
        lib.accl_rt_flush_rx.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


@overload
def free_ports(n: int, hold: Literal[True]) -> tuple[list[int], list[socket.socket]]: ...
@overload
def free_ports(n: int, hold: Literal[False] = False) -> list[int]: ...
def free_ports(n, hold=False):
    """Reserve n free localhost ports (emulator launch helper, the role of
    test/model/emulator/run.py's port allocation).

    hold=True returns (ports, sockets) with the reserving sockets still
    bound: the "local" POE never binds these ports itself (they are pure
    registry keys into the native g_local_ports map), so without a live
    reservation the OS may hand the same numbers to a second
    concurrently-alive world and the native registry refuses the
    collision at bring-up — the caller keeps the sockets open for the
    world's lifetime."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    if hold:
        return ports, socks
    for s in socks:
        s.close()
    return ports


class EmuRank:
    """One rank of the native emulator (per-rank driver endpoint)."""

    def __init__(
        self,
        world: int,
        rank: int,
        ports: list[int],
        n_rx_bufs: int = DEFAULT_NUM_EAGER_RX_BUFS,
        rx_buf_bytes: int = DEFAULT_EAGER_RX_BUF_SIZE,
        max_eager: int = DEFAULT_MAX_EAGER_SIZE,
        # The driver default ceiling (32 KB, accl.hpp:104) is what apps
        # immediately raise at bring-up; the emulator defaults to a roomy
        # ceiling so rendezvous tests exercise real sizes. The limit stays
        # enforced (DMA_SIZE_ERROR past it).
        max_rndzv: int = 64 * 1024 * 1024,
        # "tcp" = session full mesh (EasyNet-class POE); "udp" = sessionless
        # datagram transport (VNX POE analog, eager-only)
        transport: str = "tcp",
    ):
        lib = load_native()
        self.world = world
        self.rank = rank
        self.transport = transport
        arr = (ctypes.c_uint16 * world)(*ports)
        # "local" is the intra-process POE (direct-call delivery, no
        # sockets): the intra-node fast-path transport beside the TCP
        # session mesh and the datagram POE
        tr = {"tcp": 0, "udp": 1, "local": 2}[transport]
        self._rt = lib.accl_rt_create_ex(
            world, rank, arr, n_rx_bufs, rx_buf_bytes, max_eager, max_rndzv,
            tr,
        )
        if not self._rt:
            raise RuntimeError(f"native runtime bring-up failed (rank {rank})")
        self._lib = lib
        self._keepalive: dict[int, tuple] = {}
        self._durations: dict[int, int] = {}
        # per-handle descriptor, so a failed wait can name the call in
        # the flight-recorder post-mortem (popped with the keepalive)
        self._call_opts: dict[int, CallOptions] = {}

    def close(self):
        if self._rt:
            self._lib.accl_rt_destroy(self._rt)
            self._rt = None

    def kill(self):
        """Permanently wedge this rank (accl_rt_kill — the programmatic
        ACCL_RT_FAULT_KILL_RANK): in-flight and future calls complete
        with a sticky RECEIVE_TIMEOUT retcode (a final trace-ring span
        when tracing is armed) and the rank's wire goes dark in both
        directions. The fault-injection primitive of the self-healing
        soak (bench --fault-gate, tests/test_resilience.py)."""
        if self._rt:
            self._lib.accl_rt_kill(self._rt)

    def flush_rx(self, settle_s: float = 0.05):
        """Reconfiguration fence (accl_rt_flush_rx): drop stale landed
        frames of the old membership's aborted collectives and advance
        the per-peer seqn past them. Call QUIESCENT (no live calls on
        this rank, survivor threads joined) between excluding a dead
        rank and the first call on the recovery communicator — the
        seqn-ordered streamed matching would otherwise deliver old-
        world frames into the new world's first recv as data.

        The fence runs TWICE around a `settle_s` pause: quiescence
        means no peer is *sending* (their calls terminated before this
        rank's threads joined — sends happen synchronously inside
        calls), but a final frame may still be crossing the receive
        path (the rx thread mid-read of a socket buffer). Such a
        straggler lands with a seqn at-or-past the first flush's
        advance and would read as new-world data; the settle window
        lets it land and the second flush drops it. A frame delayed
        longer than `settle_s` after every sender terminated would
        need a transport that buffers outside both endpoints — not a
        property of the in-process/loopback POEs."""
        if self._rt:
            import time

            self._lib.accl_rt_flush_rx(self._rt)
            if settle_s > 0:
                time.sleep(settle_s)
                self._lib.accl_rt_flush_rx(self._rt)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- MMIO --------------------------------------------------------------

    def read(self, addr: int) -> int:
        return self._lib.accl_rt_read(self._rt, addr)

    def write(self, addr: int, value: int):
        self._lib.accl_rt_write(self._rt, addr, value)

    def sequencer_stats(self) -> dict:
        """Cumulative sequencer counters of this rank's runtime —
        execute passes, event-counter parks, nanoseconds parked, rx-seek
        hits/misses. The live form of the ACCL_RT_STATS destroy-time
        dump: diff two snapshots to profile one phase of a run
        (tools/rt_stats_sweep.py automates the per-config version)."""
        buf = (ctypes.c_uint64 * 5)()
        self._lib.accl_rt_get_stats(self._rt, buf)
        return {"passes": buf[0], "parks": buf[1], "park_ns": buf[2],
                "seek_hit": buf[3], "seek_miss": buf[4]}

    def wire_stats(self) -> dict:
        """Full versioned counter surface (accl_rt_get_stats2): the
        sequencer counters PLUS the reliability sublayer's wire-health
        counters — frames tx/rx, CRC-corrupt and duplicate drops,
        selective-retransmit ack/nack traffic, the seeded fault model's
        injection tallies, and the cumulative CRC+ack bookkeeping ns.
        Diff two snapshots to judge one phase of a run; the resilience
        manager consumes exactly that delta to tell a lossy link from a
        dark one (docs/resilience.md escalation policy)."""
        cap = len(STATS2_FIELDS)
        buf = (ctypes.c_uint64 * cap)()
        n = min(int(self._lib.accl_rt_get_stats2(self._rt, buf, cap)), cap)
        # schema-stable: every known field present (zero when the
        # library predates it), unknown trailing counters ignored
        return {name: int(buf[i]) if i < n else 0
                for i, name in enumerate(STATS2_FIELDS)}

    def trace_read(self, chunk: int = 4096) -> tuple[list[dict], int]:
        """Drain this rank's device-resident trace ring (ACCL_RT_TRACE=1;
        accl_rt_trace_read): returns (spans, dropped) where each span is
        a dict in the telemetry subsystem's native-span shape — opcode,
        count, payload bytes, start/end ns since runtime creation, the
        sticky retcode, the deferred-mismatch fault detail behind a
        RECEIVE_TIMEOUT, and the per-call sequencer-counter deltas.
        Loops until the ring is empty (a raised ACCL_RT_TRACE_CAP must
        not silently truncate at one chunk). `dropped` is the cumulative
        count of spans the ring overflowed (oldest first). Empty when
        tracing is disabled."""
        spans: list[dict] = []
        dropped = ctypes.c_uint64(0)
        while True:
            buf = (NativeSpan * chunk)()
            n = self._lib.accl_rt_trace_read(self._rt, buf, chunk,
                                             ctypes.byref(dropped))
            spans.extend(
                {
                    "opcode": s.opcode,
                    "retcode": s.retcode,
                    "detail": s.detail,
                    "count": s.count,
                    "bytes": s.bytes,
                    "start_ns": s.start_ns,
                    "end_ns": s.end_ns,
                    "d_passes": s.d_passes,
                    "d_parks": s.d_parks,
                    "d_seek_hit": s.d_seek_hit,
                    "d_seek_miss": s.d_seek_miss,
                    "rank": self.rank,
                }
                for s in buf[:n]
            )
            if n < chunk:
                return spans, int(dropped.value)

    def dump_eager_rx_buffers(self) -> str:
        """Slot-by-slot rx-ring snapshot from the native runtime
        (accl_rt_dump_rxbufs; reference accl.cpp:964-1012)."""
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            need = self._lib.accl_rt_dump_rxbufs(self._rt, buf, cap)
            if need < cap:  # re-loop if the ring grew between calls
                return buf.value.decode()
            cap = need + 4096

    # -- calls -------------------------------------------------------------

    @staticmethod
    def _ptr(arr):
        if arr is None:
            return None
        assert arr.flags["C_CONTIGUOUS"]
        return arr.ctypes.data_as(ctypes.c_void_p)

    def start(self, opts: CallOptions, op0=None, op1=None, res=None) -> int:
        words = (ctypes.c_uint32 * 15)(*[w & 0xFFFFFFFF for w in opts.to_words()])
        dt = int(opts.data_type)
        h = self._lib.accl_rt_start(
            self._rt, words, dt, self._ptr(op0), self._ptr(op1), self._ptr(res)
        )
        # operands must outlive the call (reference: buffers owned by caller
        # until request completion, acclrequest.hpp)
        self._keepalive[h] = (op0, op1, res)
        self._call_opts[h] = opts
        return h

    def wait(self, handle: int, timeout_ms: int = 0) -> None:
        ok = self._lib.accl_rt_wait(self._rt, handle, timeout_ms)
        if not ok:
            raise TimeoutError(f"rank {self.rank}: call {handle} timed out")
        rc = self._lib.accl_rt_retcode(self._rt, handle)
        # cache duration, then release the native completion record
        self._durations[handle] = self._lib.accl_rt_duration_ns(self._rt, handle)
        self._lib.accl_rt_release(self._rt, handle)
        self._keepalive.pop(handle, None)
        opts = self._call_opts.pop(handle, None)
        if rc:
            # dump-on-error: report the failing call (its descriptor's
            # op name + count, this rank, the sticky retcode) to the
            # armed flight recorder BEFORE the typed raise, so the
            # post-mortem names the span that died. The device trace
            # ring is deliberately NOT drained here — consuming it
            # would steal the wedged span from an explicit
            # trace_read()/drain_world a caller runs after the failure.
            from ..errors import notify_sticky_retcode

            notify_sticky_retcode(
                opts.scenario.name if opts is not None
                else f"emu rank {self.rank}", rc, rank=self.rank,
                count=opts.count if opts is not None else None)
            raise ACCLError(f"emu rank {self.rank}", rc)

    def test(self, handle: int) -> bool:
        return bool(self._lib.accl_rt_test(self._rt, handle))

    def duration_ns(self, handle: int) -> int:
        if handle in self._durations:
            return self._durations[handle]
        return self._lib.accl_rt_duration_ns(self._rt, handle)

    def call(self, opts: CallOptions, op0=None, op1=None, res=None) -> int:
        h = self.start(opts, op0, op1, res)
        self.wait(h)
        return h

    # -- communicators (multi-communicator support) -----------------------

    def write_communicator(self, comm) -> None:
        """Write a Communicator's rank table into this rank's exchange
        memory at comm.exchmem_addr; pass that address as comm_addr to any
        collective (the firmware reads the table back per call,
        ccl_offload_control.c:2317-2372). Membership is derived from each
        entry's device_index (= global transport rank)."""
        for i, w in enumerate(comm.exchmem_words()):
            self.write(comm.exchmem_addr + 4 * i, w)

    # -- convenience collective wrappers (per-rank ACCL-style API) --------

    def _opts(self, scenario, count, dtype, root=0, func=0, tag=TAG_ANY,
              comm_addr=0):
        return CallOptions(
            scenario=scenario, count=count, root_src_dst=root,
            function=int(func), tag=tag, comm_addr=comm_addr,
            data_type=from_numpy_dtype(dtype),
        )

    def send(self, buf, count, dst, tag=TAG_ANY, comm_addr=0):
        return self.call(self._opts(Operation.send, count, buf.dtype, dst,
                                    tag=tag, comm_addr=comm_addr), op0=buf)

    def recv(self, buf, count, src, tag=TAG_ANY, comm_addr=0):
        return self.call(self._opts(Operation.recv, count, buf.dtype, src,
                                    tag=tag, comm_addr=comm_addr), res=buf)

    def copy(self, src, dst, count):
        return self.call(self._opts(Operation.copy, count, src.dtype), op0=src, res=dst)

    def combine(self, count, func, op0, op1, res):
        return self.call(self._opts(Operation.combine, count, op0.dtype, func=func),
                         op0=op0, op1=op1, res=res)

    def bcast(self, buf, count, root, comm_addr=0):
        return self.call(self._opts(Operation.bcast, count, buf.dtype, root,
                                    comm_addr=comm_addr), op0=buf)

    def scatter(self, sendbuf, recvbuf, count, root, comm_addr=0):
        return self.call(self._opts(Operation.scatter, count, recvbuf.dtype,
                                    root, comm_addr=comm_addr),
                         op0=sendbuf, res=recvbuf)

    def gather(self, sendbuf, recvbuf, count, root, comm_addr=0):
        return self.call(self._opts(Operation.gather, count, sendbuf.dtype,
                                    root, comm_addr=comm_addr),
                         op0=sendbuf, res=recvbuf)

    def allgather(self, sendbuf, recvbuf, count, comm_addr=0):
        return self.call(self._opts(Operation.allgather, count, sendbuf.dtype,
                                    comm_addr=comm_addr),
                         op0=sendbuf, res=recvbuf)

    def reduce(self, sendbuf, recvbuf, count, root, func, comm_addr=0):
        return self.call(self._opts(Operation.reduce, count, sendbuf.dtype,
                                    root, func, comm_addr=comm_addr),
                         op0=sendbuf, res=recvbuf)

    def allreduce(self, sendbuf, recvbuf, count, func, comm_addr=0):
        return self.call(self._opts(Operation.allreduce, count, sendbuf.dtype,
                                    func=func, comm_addr=comm_addr),
                         op0=sendbuf, res=recvbuf)

    def reduce_scatter(self, sendbuf, recvbuf, count, func, comm_addr=0):
        return self.call(self._opts(Operation.reduce_scatter, count,
                                    sendbuf.dtype, func=func,
                                    comm_addr=comm_addr),
                         op0=sendbuf, res=recvbuf)

    def alltoall(self, sendbuf, recvbuf, count, comm_addr=0):
        return self.call(self._opts(Operation.alltoall, count, sendbuf.dtype,
                                    comm_addr=comm_addr),
                         op0=sendbuf, res=recvbuf)

    def barrier(self, comm_addr=0):
        return self.call(self._opts(Operation.barrier, 0, np.float32,
                                    comm_addr=comm_addr))


class EmuWorld:
    """Bring up N emulator ranks in one process (the in-process analog of
    run.py launching N emulator processes; rank bring-up is concurrent
    because link establishment blocks on peers)."""

    # worlds whose bring-up failed (a socket-transport port lost to a
    # colliding process, a refused link) are retried with FRESH ports —
    # bounded, so an environment-level flake costs a retry instead of a
    # failed run
    BRINGUP_ATTEMPTS = 3

    def __init__(self, world: int, **kw):
        self.ranks: list[EmuRank | None] = [None] * world
        self._port_holds: list = []
        last: Exception | None = None
        for _attempt in range(self.BRINGUP_ATTEMPTS):
            if kw.get("transport") == "local":
                # local mode uses port numbers only as registry keys —
                # hold the reserving sockets for the world's lifetime so
                # a second live world can never be assigned the same keys
                # (the port-registry collision that used to flake
                # concurrent local worlds)
                ports, self._port_holds = free_ports(world, hold=True)
            else:
                ports, self._port_holds = free_ports(world), []
            self.ports = list(ports)
            self.ranks = [None] * world
            errs: list[Exception] = []

            def mk(r):
                try:
                    self.ranks[r] = EmuRank(world, r, ports, **kw)
                except Exception as e:  # pragma: no cover
                    errs.append(e)

            threads = [threading.Thread(target=mk, args=(r,))
                       for r in range(world)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if not errs:
                return
            last = errs[0]
            self.close()  # tear down the half-up world before retrying
        assert last is not None  # loop body ran and every attempt failed
        raise last

    def close(self):
        for r in self.ranks:
            if r is not None:
                r.close()
        # release the local-mode port reservations only after every rank
        # has unregistered from the native registry
        for s in self._port_holds:
            s.close()
        self._port_holds = []

    def run(self, fn):
        """Execute fn(rank_obj, rank_idx) on every rank concurrently and
        return the list of results (MPI-program analog of the gtest
        fixture, test/host/xrt/include/fixture.hpp)."""
        results = [None] * len(self.ranks)
        errs = []

        def body(i):
            try:
                results[i] = fn(self.ranks[i], i)
            except Exception as e:
                errs.append(e)

        threads = [
            threading.Thread(target=body, args=(i,))
            for i in range(len(self.ranks))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        return results
