"""DCNDevice: the multi-host backend — third interchangeable CCLODevice.

Reference: the CoyoteDevice slot. The reference driver offers three
backends behind one CCLO interface (driver/xrt/include/accl/cclo.hpp:85-89);
CoyoteDevice's constructor brings up the RDMA queue pairs to every peer
before any collective runs (driver/xrt/src/coyotedevice.cpp:38-220). The
TPU analog of that bring-up is `jax.distributed.initialize`: one process
per host joins a coordinator, after which `jax.devices()` is the global
device list and compiled programs span hosts, with XLA routing intra-host
traffic over ICI and cross-host traffic over DCN.

Topology: a two-tier mesh (outer axis = processes/hosts over DCN, inner
axis = local devices over ICI), global rank = process * local + device
(process-major, so each process's buffer rows are contiguous). Collectives
with a bandwidth-optimal two-tier decomposition (allreduce,
reduce_scatter, allgather, bcast, alltoall — sequencer/hierarchical.py)
lower to it so the slow tier carries 1/inner_world of the traffic (or,
for alltoall, one aggregated transfer per host pair); everything else
lowers flat over the combined (outer, inner) axis, which JAX treats as one
named ring in process-major order.

CPU test form (the reference's emulator posture): N processes x M virtual
CPU devices on one host — same program structure, no TPU in the loop.
"""

from __future__ import annotations

import functools
from typing import cast

import jax
import numpy as np
from jax.sharding import Mesh

from ..constants import Operation, ReduceFunction
from ..sequencer.hierarchical import (
    RankMap,
    hierarchical_allgather_schedule,
    hierarchical_allreduce_schedule,
    hierarchical_alltoall_schedule,
    hierarchical_barrier_schedule,
    hierarchical_bcast_schedule,
    hierarchical_gather_schedule,
    hierarchical_reduce_schedule,
    hierarchical_reduce_scatter_schedule,
    hierarchical_scatter_schedule,
)
from ..sequencer.lowering import ScheduleCompiler
from ..buffers import TPUBuffer
from .tpu_device import TPUDevice


class DCNCompiler(ScheduleCompiler):
    """Two-tier lowering over (outer, inner): hierarchical compositions
    for the ops that have one whenever both tiers are wider than 1,
    flat combined-axis schedules otherwise. Outputs are adapted from the
    compositions' inner-major chunk order to the device's process-major
    rank numbering with local (on-device) transposes."""

    HIER_OPS = frozenset(
        {Operation.allreduce, Operation.reduce_scatter,
         Operation.allgather, Operation.bcast, Operation.alltoall,
         Operation.scatter, Operation.gather, Operation.reduce,
         Operation.barrier}
    )

    def __init__(self, mesh, outer_axis: str, inner_axis: str,
                 arith_table=None):
        # jax collectives accept an axis-name tuple (the two-tier flat
        # ring); the compiler annotation keeps the common single-axis
        # str form, so the tuple goes through a cast at this one seam
        super().__init__(mesh, cast(str, (outer_axis, inner_axis)),
                         arith_table=arith_table, use_pallas_ring=False)
        self.outer_axis = outer_axis
        self.inner_axis = inner_axis

    @property
    def world(self) -> int:
        return self.mesh.shape[self.outer_axis] * self.mesh.shape[self.inner_axis]

    def _build(self, options, plan, arithcfg):
        from ..sequencer.plan import Algorithm

        P = self.mesh.shape[self.outer_axis]
        L = self.mesh.shape[self.inner_axis]
        op = options.scenario
        if plan.algorithm == Algorithm.HIER_RS_AR_AG:
            # the register-gated striped composition: plan-driven, lowered
            # by the base compiler's HIER branch over the combined tuple
            # axis (global perms; the plan's RankMap is outer-major =
            # this device's process-major numbering)
            return super()._build(options, plan, arithcfg)
        if P == 1 or L == 1 or op not in self.HIER_OPS:
            # flat over the combined axis: every schedule body takes the
            # (outer, inner) tuple as its axis name; the combined index is
            # process-major, matching the device's rank numbering
            return super()._build(options, plan, arithcfg)

        func = ReduceFunction(options.function) if op in (
            Operation.allreduce, Operation.reduce_scatter,
            Operation.reduce) else None
        wire = self._wire(options, arithcfg, func, False)
        common = dict(inner_axis=self.inner_axis, outer_axis=self.outer_axis,
                      inner_world=L, outer_world=P, wire=wire)
        # the device's rank numbering is outer-major (process-major); all
        # root/chunk conversions go through the ONE mapping helper
        rm = RankMap(L, P, "outer_major")
        root = options.root_src_dst
        root_outer, root_inner = rm.outer_pos(root), rm.inner_pos(root)

        if op == Operation.allreduce:
            body = functools.partial(
                hierarchical_allreduce_schedule, func=func, **common)
        elif op == Operation.scatter:
            body = functools.partial(
                hierarchical_scatter_schedule,
                root_outer=root_outer, root_inner=root_inner, **common)
        elif op == Operation.gather:
            body = functools.partial(
                hierarchical_gather_schedule,
                root_outer=root_outer, root_inner=root_inner, **common)
        elif op == Operation.reduce:
            body = functools.partial(
                hierarchical_reduce_schedule, func=func,
                root_outer=root_outer, root_inner=root_inner, **common)
        elif op == Operation.barrier:
            body = functools.partial(hierarchical_barrier_schedule, **common)
        elif op == Operation.alltoall:
            # already process-major on both ends — no reorder needed
            body = functools.partial(hierarchical_alltoall_schedule, **common)
        elif op == Operation.bcast:
            body = functools.partial(
                hierarchical_bcast_schedule,
                root_outer=root_outer, root_inner=root_inner, **common)
        elif op == Operation.allgather:
            # composition output is inner-major; relabel locally to the
            # device's process-major chunk order
            def body(x, *, _c=common, _rm=rm):
                raw = hierarchical_allgather_schedule(x, **_c)
                c = raw.shape[-1] // _rm.world
                return _rm.reorder_chunks(raw, c, "inner_major",
                                          "outer_major")
        else:  # reduce_scatter
            # pre-permute the input's process-major chunks to the
            # composition's inner-major layout so each device ends with
            # its own (process-major) chunk
            def body(x, *, _c=common, _f=func, _rm=rm):
                c = x.shape[-1] // _rm.world
                xim = _rm.reorder_chunks(x, c, "outer_major", "inner_major")
                return hierarchical_reduce_scatter_schedule(
                    xim, func=_f, **_c)

        from jax.sharding import PartitionSpec

        spec = PartitionSpec(self.axis_name)

        def wrapped(x):
            out = body(x.reshape(x.shape[-1]))
            return out.reshape(1, out.shape[-1])

        return jax.jit(
            jax.shard_map(wrapped, mesh=self.mesh, in_specs=(spec,),
                          out_specs=spec, check_vma=False)
        )


def _distributed_active() -> bool:
    """True if jax.distributed is already initialized — checked WITHOUT
    touching the backend (jax.process_count would initialise XLA and make
    a later distributed.initialize impossible)."""
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:
        return False


class DCNBuffer(TPUBuffer):
    """Multi-process stacked buffer: the device array is global, the host
    mirror is authoritative only for rows on this process's devices
    (remote rows are not addressable — the reference analog is each host
    syncing only its own FPGA's DDR)."""

    def sync_to_device(self):
        # assemble from process-local rows: each process contributes the
        # shards its devices own, so host mirrors may legitimately differ
        # across processes on remote rows (jax.device_put's global
        # equality check would wrongly reject that)
        imap = self.sharding.addressable_devices_indices_map(self.shape)
        shards = [jax.device_put(np.ascontiguousarray(self.host[idx]), d)
                  for d, idx in imap.items()]
        self.device = jax.make_array_from_single_device_arrays(
            self.shape, self.sharding, shards)
        return self

    def sync_from_device(self):
        if self.device is not None:
            for s in self.device.addressable_shards:
                self.host[s.index] = np.asarray(s.data)
        return self


class DCNDevice(TPUDevice):
    """Multi-process/multi-host device backend over a (dcn, ici) mesh."""

    # sub-communicators are supported for OUTER-ALIGNED groups: members
    # must be the full inner (ici) groups of a subset of hosts, because a
    # cross-host program involves exactly the processes owning its
    # devices. A within-one-host group therefore selects the flat
    # ICI-only path while the world communicator selects the hierarchical
    # compositions — communicator-driven flat-vs-hierarchical selection.
    supports_split = True
    buffer_class = DCNBuffer
    # the two-tier alltoall composition (hierarchical_alltoall_schedule)
    # has no capacity-masked variant yet: reject uneven alltoallv
    # vectors up front rather than silently running the dense exchange
    supports_alltoallv = False
    # and keep the ALLTOALL_COMPRESS_MIN_COUNT auto-rewrite off: its
    # crossover is calibrated for the FLAT exchange; on the two-tier
    # composition each tier would re-encode (doubling the per-block
    # error) on a link mix the flat model does not describe. Explicit
    # compress_dtype= stays available, as before.
    auto_alltoall_wire = False

    def __init__(
        self,
        num_processes: int = 1,
        process_id: int = 0,
        coordinator_address: str | None = None,
        local_device_count: int | None = None,
        outer_axis: str = "dcn",
        inner_axis: str = "ici",
        platform: str | None = None,
        mesh: Mesh | None = None,
    ):
        if mesh is None:
            # bring-up (CoyoteDevice ctor analog): force the platform
            # before any backend touch, then join the coordinator
            if platform is not None:
                try:
                    jax.config.update("jax_platforms", platform)
                    if local_device_count:
                        jax.config.update("jax_num_cpu_devices",
                                          local_device_count)
                except Exception:
                    pass  # backend already initialized
            if num_processes > 1 and not _distributed_active():
                if coordinator_address is None:
                    raise ValueError(
                        "multi-process DCNDevice needs a coordinator_address")
                # must run before ANY backend-initialising jax call
                # (jax.devices / device_put / process_count)
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                )
            devs = sorted(jax.devices(),
                          key=lambda d: (d.process_index, d.id))
            n_proc = max(jax.process_count(), 1)
            if len(devs) % n_proc:
                raise ValueError(
                    f"{len(devs)} devices not uniform over {n_proc} processes")
            local = len(devs) // n_proc
            mesh = Mesh(np.array(devs).reshape(n_proc, local),
                        (outer_axis, inner_axis))
        else:
            outer_axis, inner_axis = mesh.axis_names
        # tuple axis name: same seam as DCNCompiler — jax accepts it,
        # the TPUDevice annotation keeps the single-axis common form
        super().__init__(mesh, axis_name=cast(str, (outer_axis, inner_axis)))
        self.outer_axis = outer_axis
        self.inner_axis = inner_axis
        self.compiler = DCNCompiler(mesh, outer_axis, inner_axis)
        # declare the two-tier shape so the register-gated striped
        # composition is selectable (plan.select_algorithm topology=)
        self.hier_topology = (mesh.shape[inner_axis],
                              mesh.shape[outer_axis])

    @property
    def world(self) -> int:
        return (self.mesh.shape[self.outer_axis]
                * self.mesh.shape[self.inner_axis])

    @property
    def process_index(self) -> int:
        return jax.process_index()

    def local_rows(self) -> list[int]:
        """Global rank rows whose buffers live on this process."""
        flat = self.mesh.devices.reshape(-1)
        me = jax.process_index()
        return [i for i, d in enumerate(flat) if d.process_index == me]

    def validate_split(self, rows: tuple) -> None:
        """Members must be outer-aligned (whole inner groups of a host
        subset): a compiled program involves exactly the processes owning
        its devices, and partial hosts would strand devices. Checked at
        split() time so a bad group never allocates exchange memory."""
        L = self.mesh.shape[self.inner_axis]
        if len(rows) % L or any(
            rows[i * L + j] != rows[i * L] + j or rows[i * L] % L
            for i in range(len(rows) // L)
            for j in range(L)
        ):
            raise NotImplementedError(
                f"DCN sub-communicators must be whole-host groups "
                f"(members aligned to inner groups of {L}); got {rows}")

    def _make_group_ctx(self, rows: tuple):
        """Sub-communicator context as a two-tier sub-mesh."""
        from .tpu_device import _CommCtx

        self.validate_split(rows)
        L = self.mesh.shape[self.inner_axis]
        devices = self.mesh.devices.reshape(-1)
        sub_mesh = Mesh(
            np.array([devices[r] for r in rows]).reshape(len(rows) // L, L),
            (self.outer_axis, self.inner_axis))
        compiler = DCNCompiler(sub_mesh, self.outer_axis, self.inner_axis,
                               arith_table=self.compiler.arith_table)
        return _CommCtx(len(rows), sub_mesh, compiler, rows)

    def _member_process(self, ctx) -> bool:
        """Does this process own any device of the communicator?
        Membership is immutable per context, so it is computed once and
        cached on the ctx (start() is the dispatch hot path)."""
        if ctx.rows is None:
            return True
        member = getattr(ctx, "_member_here", None)
        if member is None:
            me = jax.process_index()
            flat = self.mesh.devices.reshape(-1)
            member = any(flat[r].process_index == me for r in ctx.rows)
            ctx._member_here = member
        return member

    def start(self, options):
        if options.scenario != Operation.config:
            ctx = self._comm_ctx(options.comm_addr)
            if not self._member_process(ctx):
                # MPI semantics: a collective on a communicator this host
                # is not part of is a no-op here (the member hosts run it)
                from ..request import BaseRequest

                req = BaseRequest(options.scenario.name)
                req.running()
                req.complete(0)
                return req
        return super().start(options)

