"""Device backends: where call descriptors are executed.

Reference structure: driver/xrt/include/accl/cclo.hpp:85-89 enumerates
three interchangeable backends (XRTDevice for hardware, SimDevice for the
emulator, CoyoteDevice for the Coyote shell). Here:

  TPUDevice  - compiled-schedule execution over a jax mesh (the hardware
               backend; ICI transport)
  EmuDevice  - the native C++ multi-rank emulator over sockets (the
               SimDevice analog; see native/)
"""

from .base import CCLODevice, CCLOAddr  # noqa: F401
from .tpu_device import TPUDevice  # noqa: F401
