"""Abstract CCLO device + exchange-memory model.

Reference: driver/xrt/include/accl/cclo.hpp:41-203 — a device executes
call descriptors (call/start), exposes MMIO read/write into exchange
memory, and reports retcode/duration per request. The exchange-memory
register map mirrors constants.hpp:139-154 so dumps and config writes are
recognizable to anyone who knows the reference.
"""

from __future__ import annotations

from ..constants import EXCHMEM_SIZE
from ..descriptor import CallOptions
from ..request import BaseRequest


class CCLOAddr:
    """Exchange-memory register offsets (reference CCLO_ADDR namespace,
    constants.hpp:139-154)."""

    RETCODE = 0x1FFC
    IDCODE = 0x1FF8
    CFGRDY = 0x1FF4
    PERFCNT = 0x1FF0
    SPARE3 = 0x1FE8
    SPARE2 = 0x1FE0
    # repurposed spare: allreduce payloads <= this many bytes (and above
    # max_eager) run the rendezvous reduce+bcast composition
    # (.c:1878-1887); 0 = streamed ring at every size (the measured
    # default, accl_log/emu_bench.csv)
    ALLREDUCE_COMPOSITION_MAX_COUNT = 0x1FD8
    REDUCE_FLAT_TREE_MAX_COUNT = 0x1FD4
    REDUCE_FLAT_TREE_MAX_RANKS = 0x1FD0
    BCAST_FLAT_TREE_MAX_RANKS = 0x1FCC
    GATHER_FLAT_TREE_MAX_COUNT = 0x1FC8
    GATHER_FLAT_TREE_MAX_FANIN = 0x1FC4
    # Synthesized-schedule crossover registers (sequencer/synthesis.py):
    # payloads up to this many bytes run the committed search-produced
    # hop-DAG for the collective; 0 (the default) keeps the hand-written
    # zoo. Set by ACCL.autotune from the calibrated timing model.
    SYNTH_ALLREDUCE_MAX_COUNT = 0x1FC0
    SYNTH_ALLGATHER_MAX_COUNT = 0x1FBC
    SYNTH_REDUCE_SCATTER_MAX_COUNT = 0x1FB8
    # Hierarchical-allreduce crossover (sequencer/hierarchical.py):
    # allreduce payloads of AT LEAST this many bytes run the striped
    # two-tier composition on a device with a declared (inner, outer)
    # topology — a MIN threshold: the composition wins the
    # bandwidth-bound regime, not the latency floor. 0 (the default)
    # keeps the flat selection. Set by ACCL.autotune from the
    # calibrated per-tier crossover.
    HIER_ALLREDUCE_MIN_COUNT = 0x1FB4
    # Quantized-alltoall crossover (sequencer/schedules.py + the int8
    # wire lanes): uncompressed fp32 alltoall(v) payloads of AT LEAST
    # this many bytes ride the blockwise-quantized wire on a device
    # that ships it — a MIN threshold like the hier register (the
    # compressed wire wins the bandwidth regime, never the latency
    # floor). 0 (the default) keeps selection bit-for-bit unchanged.
    # Set by ACCL.autotune from the calibrated crossover.
    ALLTOALL_COMPRESS_MIN_COUNT = 0x1FB0
    # Compute-communication overlap crossover (sequencer/plan.py +
    # timing.predict_overlapped): streamed eager fp32 allreduce
    # payloads of AT LEAST this many bytes run as cost-model-chosen
    # independent stripe chains (Plan.stripes) so the wire overlaps
    # the compute spliced next to it — a MIN threshold like the hier
    # and alltoall-compress registers (overlap wins where wire time is
    # visible next to compute, never the latency floor). 0 (the
    # default) keeps selection bit-for-bit the serial form. Set by
    # ACCL.autotune from the calibrated crossover.
    OVERLAP_MIN_COUNT = 0x1FAC
    # Latency-window synthesized-schedule crossover (sequencer/
    # synthesis.py, SIZE_GRID_LAT): exact fp32 allreduce payloads up to
    # this many bytes run the committed latency-grid hop-DAG (minimum-
    # step exchange/doubling members scored on the 1-64 KiB grid where
    # the alpha term dominates) when one covers the cell — a MAX
    # threshold like the synth registers, but scoped to the small-
    # payload decode regime and checked BEFORE the bandwidth-biased
    # std window. 0 (the default) keeps selection bit-for-bit
    # unchanged. Set by ACCL.autotune from the calibrated crossover.
    SYNTH_LATENCY_MAX_COUNT = 0x1FA8
    EGR_RX_BUF_SIZE = 0x4
    NUM_EGR_RX_BUFS = 0x0
    # Start of the dynamically-laid-out region (communicators, arith
    # configs), after the rx-ring descriptor table.
    DYNAMIC_BASE = 0x200
    # End of the dynamic region: the lowest-addressed register above
    # (keep in sync when adding registers).
    DYNAMIC_END = 0x1FA8


# The hardware id this framework reports, with capability bits analogous
# to the reference HWID decode (accl.cpp:1050-1064).
ACCL_TPU_IDCODE = 0xACC1_7B00


class CCLODevice:
    """Backend interface: execute descriptors, expose exchange memory."""

    def __init__(self):
        # Word-addressed exchange-memory model, 8 KB like the BRAM
        # (ccl_offload_control.h:85-98).
        self._exchmem: dict[int, int] = {CCLOAddr.IDCODE: ACCL_TPU_IDCODE}

    # -- MMIO -------------------------------------------------------------

    def read(self, addr: int) -> int:
        self._check_addr(addr)
        return self._exchmem.get(addr, 0)

    def write(self, addr: int, value: int) -> None:
        self._check_addr(addr)
        self._exchmem[addr] = value & 0xFFFFFFFF

    def _check_addr(self, addr: int):
        if not 0 <= addr < EXCHMEM_SIZE:
            raise ValueError(f"exchange-memory address {addr:#x} out of range")

    def dump_exchange_memory(self) -> str:
        """Reference ACCL::dump_exchange_memory (accl.cpp:964-1048)."""
        lines = ["exchange memory:"]
        for addr in sorted(self._exchmem):
            lines.append(f"  [{addr:#06x}] = {self._exchmem[addr]:#010x}")
        return "\n".join(lines)

    def dump_eager_rx_buffers(self) -> str:
        """Reference ACCL::dump_eager_rx_buffers (accl.cpp:964-1012);
        backends with eager rx state override."""
        return "eager rx ring: none on this backend"

    # -- calls ------------------------------------------------------------

    def call(self, options: CallOptions) -> BaseRequest:
        """Synchronous call: start + wait + store retcode."""
        req = self.start(options)
        req.wait()
        self.write(CCLOAddr.RETCODE, req.retcode)
        self.write(CCLOAddr.PERFCNT, req.duration_ns & 0xFFFFFFFF)
        return req

    def start(self, options: CallOptions) -> BaseRequest:
        raise NotImplementedError

    def deinit(self):
        pass
