"""The multi-tenant scheduler: certified concurrent streams, QoS, and
admission control over SequenceProgram dispatches (ROADMAP item 1).

ACCL's inversion makes the host a thin RPC client over device-resident
collective programs; production traffic means MANY independent hosts —
the ACCL+ multi-process collective-engine posture (arxiv 2312.11742).
This module is the subsystem that multiplexes N logical tenants over
one facade with provable isolation instead of hope:

* **Admission.** A program enters the queues only after (1) it is
  PRICED — `timing.predict_prepared` under the shipped calibration
  (the device's `predict_sequence_cost` seam), falling back to an
  honest bytes proxy so nothing is ever admitted for free — and
  (2) it is CERTIFIED against every program currently queued or in
  flight via the facade's long-lived `InterferenceCertifier` (the same
  per-pair verdict cache `ACCL.certify_concurrent` uses, LRU-bounded).
  A pair the certifier cannot prove clean (ACCL6xx) is NEVER rejected
  silently: the entry is admitted in SERIAL-FALLBACK mode and simply
  refuses to overlap its conflicts — correctness by scheduling, loudly
  accounted (`serialized` per tenant).

* **QoS.** Strict priority classes; start-time weighted fair queueing
  over predicted cost within a class (qos.py has the virtual-time
  math); preemption points at program boundaries — selection re-runs
  before every dispatch, which is exactly the granularity the
  certificates prove order-equivalent. Saturation is a typed
  `SchedulerSaturatedError` at submit time (backpressure), never
  unbounded queue growth.

* **Certificates at dispatch.** Every dispatch is stamped with the
  `certificate_id` of the set it was admitted to overlap with (the
  in-flight group at its pick, itself included — a solo dispatch
  carries the singleton certificate). The id rides the dispatch span
  and request (`interference_cert`), so the flight recorder can name
  the admitted set any interleaving belonged to, and the bench gate
  can prove ZERO uncertified concurrent dispatches happened.

* **Accountability.** Per-tenant series through the metrics registry
  (`accl_tenant_dispatch_seconds{tenant=...}` p50/p95/p99/p99.9, queue
  wait, dispatched predicted cost — the fair-share measurement), SLO
  residuals against model-derived budgets (the resilience/deadline.py
  formula: predicted * (1 + band-widened tolerance) + floor, or the
  tenant's explicit budget), and a noisy-neighbor attribution that
  names which co-running tenant's dispatched cost overlapped each SLO
  miss. Tenant labels ride the registry's cardinality guard, so even
  an abusive tenant-id stream cannot blow up the exposition.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

from ..constants import dtype_nbytes
from ..resilience.deadline import (
    DEFAULT_DEADLINE_FLOOR_S,
    DEFAULT_UNARMED_REFERENCE,
)
from ..telemetry import metrics
from ..telemetry.metrics import (
    DEFAULT_SENTINEL_BAND_FACTOR,
    DEFAULT_SENTINEL_BAND_FLOOR,
)
from .errors import SchedulerSaturatedError
from .qos import FairQueue, QueueEntry
from .tenant import Tenant, TenantRegistry

# fallback pricing when no calibration is committed: a per-step
# dispatch floor plus a ~1 GB/s bytes proxy — deterministic, monotone
# in payload, and never zero (free admission would let one tenant
# starve the fair queue invisibly)
_FALLBACK_STEP_S = 1e-5
_FALLBACK_S_PER_BYTE = 1e-9

_DEFAULT_CAPACITY_S = 30.0
_DEFAULT_HISTORY = 4096


class MultiTenantScheduler:
    """Admission control + QoS + accountability over one ACCL facade
    (module docstring). Thread-safe: submits and `drain(workers=N)`
    dispatch loops may run concurrently; the certifier, queues and
    in-flight set are guarded by one lock, and programs only ever
    overlap when their pairwise verdicts are clean."""

    def __init__(self, accl, *, capacity_s: float = _DEFAULT_CAPACITY_S,
                 registry=None,
                 slo_reference: float = DEFAULT_UNARMED_REFERENCE,
                 band_factor: float = DEFAULT_SENTINEL_BAND_FACTOR,
                 band_floor: float = DEFAULT_SENTINEL_BAND_FLOOR,
                 slo_floor_s: float = DEFAULT_DEADLINE_FLOOR_S,
                 history: int = _DEFAULT_HISTORY,
                 time_fn=time.perf_counter):
        from ..analysis.interference import InterferenceCertifier

        self._accl = accl
        # share the facade's long-lived certifier: verdicts cached by
        # certify_concurrent serve admission here and vice versa
        if getattr(accl, "_interference", None) is None:
            accl._interference = InterferenceCertifier()
        self._certifier = accl._interference
        self.tenants = TenantRegistry()
        self.capacity_s = float(capacity_s)
        self._slo_reference = float(slo_reference)
        self._band_factor = float(band_factor)
        self._band_floor = float(band_floor)
        self._slo_floor_s = float(slo_floor_s)
        self._time = time_fn
        self._reg = registry if registry is not None \
            else metrics.get_registry()
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._classes: dict[int, FairQueue] = {}
        self._inflight: dict[int, QueueEntry] = {}
        self._next_seq = 0
        self._cost_cache: dict[str, float] = {}
        self._history: deque = deque(maxlen=max(int(history), 16))
        self.stats = {
            "dispatches": 0,
            "concurrent_dispatches": 0,  # picked with >= 1 in flight
            "certified_concurrent": 0,   # ... under a clean group cert
            "uncertified_concurrent": 0,  # must stay 0 (the gate pins it)
            "serialized_admissions": 0,
            "rejected_saturated": 0,
            "max_inflight": 0,
        }

    # -- tenants -----------------------------------------------------------

    def register_tenant(self, name: str, *, priority: int = 1,
                        weight: float = 1.0,
                        slo_budget_s: float | None = None,
                        comm: Any = None) -> Tenant:
        """Admit a tenant (typed DuplicateTenantError on reuse). Its
        per-tenant metric series appear on first dispatch; `comm` may
        carry a per-tenant communicator (`accl.split`) so the tenant's
        traffic is namespaced at the communicator level too."""
        return self.tenants.register(name, priority=priority,
                                     weight=weight,
                                     slo_budget_s=slo_budget_s, comm=comm)

    # -- pricing -----------------------------------------------------------

    def predict_cost_s(self, program) -> float:
        """The admission price of one dispatch: the calibrated timing
        model where committed (device predict_sequence_cost ->
        timing.predict_prepared), the bytes proxy otherwise. Cached per
        program signature."""
        sig = getattr(program, "signature", None)
        if sig is not None:
            hit = self._cost_cache.get(sig)
            if hit is not None:
                return hit
        cost = None
        prepared = getattr(program, "_prepared", None)
        cclo = getattr(self._accl, "cclo", None)
        if prepared is not None and cclo is not None \
                and hasattr(cclo, "predict_sequence_cost"):
            cost = cclo.predict_sequence_cost(prepared)
        if cost is None and prepared is not None:
            cost = 0.0
            for o in prepared.desc.steps:
                cost += (_FALLBACK_STEP_S
                         + o.count * dtype_nbytes(o.data_type)
                         * _FALLBACK_S_PER_BYTE)
        if cost is None or cost <= 0:
            cost = _FALLBACK_STEP_S
        if sig is not None:
            self._cost_cache[sig] = cost
        return cost

    def slo_deadline_s(self, tenant: Tenant, cost_s: float) -> float:
        """The tenant's per-dispatch budget: its explicit slo_budget_s,
        else the model-derived deadline (resilience/deadline.py):
        predicted * (1 + max(ref*band_factor, ref+band_floor)) +
        floor_s, with the deliberately loose unarmed reference until
        `arm_slo_reference` pins a measured one."""
        if tenant.slo_budget_s is not None:
            return tenant.slo_budget_s
        tol = max(self._slo_reference * self._band_factor,
                  self._slo_reference + self._band_floor)
        return cost_s * (1.0 + tol) + self._slo_floor_s

    def arm_slo_reference(self, median_rel_err: float) -> None:
        """Tighten the derived SLO band from a measured residual
        reference (the drift sentinel's armed median)."""
        self._slo_reference = float(median_rel_err)

    # -- admission ---------------------------------------------------------

    def queued_cost_s(self) -> float:
        with self._mu:
            return self._queued_cost_locked()

    def _queued_cost_locked(self) -> float:
        q = sum(fq.queued_cost() for fq in self._classes.values())
        return q + sum(e.cost_s for e in self._inflight.values())

    def admit_request(self, tenant_name: str,
                      cost_s: float = _FALLBACK_STEP_S) -> None:
        """The serve-layer admission check (DecodeServer.submit rides
        it): raises the typed SchedulerSaturatedError when accepting
        `cost_s` more predicted work would exceed capacity. No queue
        mutation — the caller owns its request queue."""
        t = self.tenants.get(tenant_name)
        with self._mu:
            queued = self._queued_cost_locked()
            if queued + cost_s > self.capacity_s:
                self.stats["rejected_saturated"] += 1
                self._reg.counter("accl_tenant_rejected_total",
                                  tenant=t.name).inc()
                raise SchedulerSaturatedError(t.name, cost_s, queued,
                                              self.capacity_s)

    def submit(self, tenant_name: str, program, *, repeats: int = 1,
               cost_s: float | None = None, **run_kwargs) -> int:
        """Queue `repeats` dispatches of a compiled program for a
        tenant. Admission = backpressure check (typed saturation
        error) + pairwise certification against everything currently
        admitted; an uncertifiable pair queues in serial-fallback mode
        (accounted, never silently dropped). Returns the number of
        queued dispatches. `cost_s` overrides the predicted price
        (tests pin the WFQ math with it)."""
        t = self.tenants.get(tenant_name)
        fp = getattr(program, "footprint", None)
        cost = float(cost_s) if cost_s is not None \
            else self.predict_cost_s(program)
        with self._cv:
            queued = self._queued_cost_locked()
            if queued + cost * repeats > self.capacity_s:
                self.stats["rejected_saturated"] += 1
                self._reg.counter("accl_tenant_rejected_total",
                                  tenant=t.name).inc()
                raise SchedulerSaturatedError(t.name, cost * repeats,
                                              queued, self.capacity_s)
            conflicts = set()
            if fp is not None:
                t.record_footprint(fp)
                for other in self._admitted_footprints_locked():
                    if other.signature == fp.signature:
                        continue
                    if self._certifier.check_pair(fp, other):
                        conflicts.add(other.signature)
            serial = fp is None or bool(conflicts)
            if serial:
                self.stats["serialized_admissions"] += repeats
                t.serialized += repeats
                self._reg.counter("accl_tenant_serialized_total",
                                  tenant=t.name).inc(repeats)
            fq = self._classes.setdefault(t.priority, FairQueue())
            now = self._time()
            for _ in range(repeats):
                e = QueueEntry(tenant=t.name, priority=t.priority,
                               program=program, footprint=fp,
                               cost_s=cost, seq=self._next_seq,
                               run_kwargs=dict(run_kwargs),
                               conflicts=frozenset(conflicts),
                               submitted_t=now)
                self._next_seq += 1
                fq.push(t, e)
            t.submitted += repeats
            self._reg.gauge("accl_scheduler_queue_depth").set(
                sum(len(fq) for fq in self._classes.values()))
            self._cv.notify_all()
        return repeats

    def _admitted_footprints_locked(self):
        seen: dict[str, Any] = {}
        for e in self._inflight.values():
            if e.footprint is not None:
                seen.setdefault(e.footprint.signature, e.footprint)
        for fq in self._classes.values():
            for e in fq.entries():
                if e.footprint is not None:
                    seen.setdefault(e.footprint.signature, e.footprint)
        return list(seen.values())

    # -- the concurrency discipline ---------------------------------------

    def _eligible_locked(self, e: QueueEntry) -> bool:
        """May `e` start NOW, next to the current in-flight set? A
        footprint-less entry runs exclusively; a same-program overlap
        is always a conflict (a program interferes with itself by
        construction); otherwise every in-flight pair must hold a
        clean verdict."""
        if not self._inflight:
            return True
        if e.footprint is None:
            return False
        for f in self._inflight.values():
            if f.footprint is None:
                return False
            if f.footprint.signature == e.footprint.signature:
                return False
            if (f.footprint.signature in e.conflicts
                    or e.footprint.signature in f.conflicts):
                return False
            if self._certifier.check_pair(e.footprint, f.footprint):
                return False
        return True

    def _take_next_locked(self) -> QueueEntry | None:
        for prio in sorted(self._classes):
            e = self._classes[prio].pop_best(self._eligible_locked)
            if e is not None:
                return e
            if len(self._classes[prio]):
                # strict priority: a blocked higher class does NOT
                # yield the link to a lower one — its conflicts drain
                # first (priority inversion would let a bulk tenant
                # starve the interactive class through a conflict)
                return None
        return None

    def _admit_inflight_locked(self, e: QueueEntry) -> str | None:
        """Move a picked entry into the in-flight set and stamp the
        group certificate: the id naming everything this dispatch was
        admitted to overlap with (itself included). Returns the cert
        id (None only for footprint-less programs)."""
        from ..analysis.interference import certificate_id

        self._inflight[e.seq] = e
        self.stats["max_inflight"] = max(self.stats["max_inflight"],
                                         len(self._inflight))
        group = [f for f in self._inflight.values()
                 if f.footprint is not None]
        if e.footprint is None:
            return None
        fps = {f.footprint.signature: f.footprint for f in group}
        cert = certificate_id(list(fps.values()))
        if len(self._inflight) > 1:
            self.stats["concurrent_dispatches"] += 1
            clean = all(
                not self._certifier.check_pair(a, b)
                for i, a in enumerate(list(fps.values()))
                for b in list(fps.values())[i + 1:])
            if clean and len(fps) == len(self._inflight):
                self.stats["certified_concurrent"] += 1
            else:
                # belt-and-braces: _eligible_locked makes this
                # unreachable, but the gate pins the counter at 0 so a
                # future scheduling bug fails loudly, not silently
                self.stats["uncertified_concurrent"] += 1
                self._reg.counter(
                    "accl_scheduler_uncertified_concurrent_total").inc()
        prepared = getattr(e.program, "_prepared", None)
        if prepared is not None:
            prepared.cert = cert
        return cert

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, e: QueueEntry) -> None:
        t0 = self._time()
        try:
            e.program.run(**e.run_kwargs)
        finally:
            t1 = self._time()
            self._account(e, t0, t1)

    def _account(self, e: QueueEntry, t0: float, t1: float) -> None:
        dt = t1 - t0
        tenant = self.tenants.get(e.tenant)
        deadline = self.slo_deadline_s(tenant, e.cost_s)
        missed = dt > deadline
        with self._cv:
            self._inflight.pop(e.seq, None)
            self.stats["dispatches"] += 1
            tenant.dispatched += 1
            tenant.dispatched_cost_s += e.cost_s
            tenant.measured_s += dt
            if missed:
                tenant.slo_misses += 1
            self._history.append((e.tenant, t0, t1, e.cost_s, missed))
            self._reg.gauge("accl_scheduler_queue_depth").set(
                sum(len(fq) for fq in self._classes.values()))
            self._cv.notify_all()
        lbl = dict(tenant=e.tenant, priority=e.priority)
        self._reg.histogram("accl_tenant_dispatch_seconds",
                            **lbl).observe(dt)
        self._reg.histogram("accl_tenant_queue_wait_seconds",
                            tenant=e.tenant).observe(
                                max(t0 - e.submitted_t, 0.0))
        self._reg.counter("accl_tenant_dispatches_total",
                          tenant=e.tenant).inc()
        self._reg.counter("accl_tenant_cost_seconds_total",
                          tenant=e.tenant).inc(e.cost_s)
        # positive residual = headroom inside the budget; negative =
        # the miss the noisy-neighbor report attributes
        self._reg.histogram("accl_tenant_slo_residual_seconds",
                            tenant=e.tenant).observe(deadline - dt)
        if missed:
            self._reg.counter("accl_tenant_slo_miss_total",
                              tenant=e.tenant).inc()

    def step(self) -> bool:
        """Dispatch at most one queued program — THE preemption point:
        each call re-runs class/WFQ selection, so a newly arrived
        higher-priority program wins the very next boundary. Returns
        False when nothing was eligible."""
        with self._cv:
            e = self._take_next_locked()
            if e is None:
                return False
            self._admit_inflight_locked(e)
        self._dispatch(e)
        return True

    def drain(self, workers: int = 1) -> int:
        """Dispatch until the queues are empty. `workers > 1` runs that
        many dispatch loops concurrently — certified-clean programs
        overlap (each under its group certificate), serial-fallback
        entries wait for their conflicts to leave the in-flight set.
        Returns the number of dispatches performed."""
        n = [0]
        n_mu = threading.Lock()

        def loop() -> None:
            while True:
                with self._cv:
                    e = self._take_next_locked()
                    while e is None:
                        if not any(len(fq)
                                   for fq in self._classes.values()):
                            return
                        # queued work exists but conflicts with the
                        # in-flight set: wait for a completion
                        self._cv.wait(timeout=0.05)
                        e = self._take_next_locked()
                    self._admit_inflight_locked(e)
                self._dispatch(e)
                with n_mu:
                    n[0] += 1

        k = max(int(workers), 1)
        if k == 1:
            loop()
            return n[0]
        threads = [threading.Thread(target=loop, name=f"accl-sched-{i}")
                   for i in range(k)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        return n[0]

    def dispatch_now(self, tenant_name: str, program,
                     **run_kwargs) -> float:
        """Immediate metered dispatch for a latency-critical caller
        (the DecodeServer step loop): bypasses the queues but fully
        participates in the concurrency discipline — waits until the
        program is eligible next to the in-flight set, joins it under
        the group certificate, and is accounted like any queued
        dispatch. Returns the measured seconds."""
        t = self.tenants.get(tenant_name)
        fp = getattr(program, "footprint", None)
        cost = self.predict_cost_s(program)
        e = QueueEntry(tenant=t.name, priority=t.priority,
                       program=program, footprint=fp, cost_s=cost,
                       seq=-1, run_kwargs=dict(run_kwargs),
                       submitted_t=self._time())
        with self._cv:
            e.seq = self._next_seq
            self._next_seq += 1
            t.submitted += 1
            while not self._eligible_locked(e):
                self._cv.wait(timeout=0.05)
            self._admit_inflight_locked(e)
        t0 = self._time()
        try:
            program.run(**run_kwargs)
        finally:
            t1 = self._time()
            self._account(e, t0, t1)
        return t1 - t0

    # -- accountability ----------------------------------------------------

    def noisy_neighbor_report(self, *, lookback_s: float = 0.25
                              ) -> list[dict[str, Any]]:
        """For every tenant with SLO misses: which OTHER tenant's
        dispatched predicted cost overlapped the missed windows most —
        the named noisy neighbor. Windows extend `lookback_s` before
        each miss (queue pressure precedes the miss). Merged with the
        drift sentinel's straggler attribution when it has data, so a
        rank-level straggler and a tenant-level neighbor are one
        report."""
        with self._mu:
            hist = list(self._history)
        misses = [(tn, t0, t1) for tn, t0, t1, _, m in hist if m]
        out: list[dict[str, Any]] = []
        by_tenant: dict[str, list[tuple[float, float]]] = {}
        for tn, t0, t1 in misses:
            by_tenant.setdefault(tn, []).append((t0 - lookback_s, t1))
        for tn in sorted(by_tenant):
            windows = by_tenant[tn]
            blame: dict[str, float] = {}
            for other, o0, o1, cost, _ in hist:
                if other == tn:
                    continue
                for w0, w1 in windows:
                    if o0 < w1 and o1 > w0:  # wall-clock overlap
                        blame[other] = blame.get(other, 0.0) + cost
                        break
            row: dict[str, Any] = {
                "tenant": tn,
                "slo_misses": len(windows),
                "neighbor_cost_s": dict(sorted(blame.items())),
            }
            if blame:
                suspect = max(blame, key=lambda k: blame[k])
                row["noisy_neighbor"] = suspect
                row["neighbor_share"] = (blame[suspect]
                                         / sum(blame.values()))
            out.append(row)
        stragglers = metrics.get_sentinel().straggler_report()
        if stragglers:
            for row in out:
                row["stragglers"] = stragglers
        return out

    def report(self) -> dict[str, Any]:
        """The JSON block the bench gate and the artifact carry:
        scheduler stats, per-tenant accounting, namespace
        disjointness, and the noisy-neighbor attribution."""
        with self._mu:
            stats = dict(self.stats)
            queued = sum(len(fq) for fq in self._classes.values())
        return {
            "capacity_s": self.capacity_s,
            "queued": queued,
            "stats": stats,
            "tenants": {t.name: t.account()
                        for t in self.tenants.tenants()},
            "namespaces": self.tenants.disjointness_report(),
            "noisy_neighbors": self.noisy_neighbor_report(),
        }
