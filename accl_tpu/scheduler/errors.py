"""Typed scheduler errors: the admission contract's failure surface.

The errors.py posture carried into the control plane: every admission
failure is a TYPED raise callers can catch precisely and tests can pin
— saturation is never a silent drop (the queue either takes the work
or refuses it loudly with the numbers that prove why), and tenant
bookkeeping mistakes fail at the registry seam, before anything is
priced or certified.
"""

from __future__ import annotations


class SchedulerError(RuntimeError):
    """Base class for multi-tenant scheduler failures."""


class SchedulerSaturatedError(SchedulerError):
    """Backpressure: admitting the work would push the queued predicted
    cost past the scheduler's capacity. Carries the accounting so the
    caller can decide to retry, shed, or re-weight — the typed
    admission-rejection the QoS contract promises instead of unbounded
    queue growth."""

    def __init__(self, tenant: str, requested_s: float, queued_s: float,
                 capacity_s: float):
        self.tenant = tenant
        self.requested_s = float(requested_s)
        self.queued_s = float(queued_s)
        self.capacity_s = float(capacity_s)
        super().__init__(
            f"scheduler saturated: tenant {tenant!r} asked for "
            f"{self.requested_s * 1e3:.2f} ms of predicted work with "
            f"{self.queued_s * 1e3:.2f} ms already queued against a "
            f"{self.capacity_s * 1e3:.2f} ms capacity")


class UnknownTenantError(SchedulerError, KeyError):
    """A submit/lookup named a tenant the registry never admitted."""

    def __init__(self, name: str):
        self.tenant = name
        # KeyError renders its arg with repr(); keep the message usable
        RuntimeError.__init__(self, f"unknown tenant {name!r} "
                                    "(register_tenant first)")

    def __str__(self) -> str:  # KeyError would quote the whole message
        return self.args[0] if self.args else ""


class DuplicateTenantError(SchedulerError, ValueError):
    """A tenant name was registered twice — tenant namespaces are
    disjoint by construction, starting with the name itself."""

    def __init__(self, name: str):
        self.tenant = name
        super().__init__(f"tenant {name!r} already registered")
