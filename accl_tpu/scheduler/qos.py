"""QoS machinery: weighted fair queueing over predicted cost, strict
priority classes, preemption at program boundaries.

The quantum the scheduler arbitrates is one SequenceProgram dispatch —
exactly the granularity the interference certifier proves
order-equivalent (any interleaving of a certified set == its serial
composition), so reordering dispatches for fairness can never change a
result. Within a priority class the queue is start-time weighted fair
queueing (SFQ) over PREDICTED seconds (timing.predict_prepared — the
calibrated cost the admission control already priced the entry at):

    S(e) = max(V, F_prev(tenant))     # start tag at enqueue
    F(e) = S(e) + cost_s / weight     # finish tag; F_prev := F(e)

dispatch picks the eligible head with the smallest finish tag and
advances the class's virtual time V to the dispatched entry's start
tag. Long-run dispatched cost per backlogged tenant then tracks its
weight share — the bench gate measures exactly that ratio. Across
classes priority is STRICT: class 0 drains before class 1 sees the
link; preemption happens at program boundaries because selection
re-runs before every dispatch (an arriving class-0 entry wins the next
boundary; nothing ever interrupts a dispatched program mid-flight —
there is no certified notion of "half a program").

Eligibility is a caller-supplied predicate: the scheduler passes the
concurrency discipline (an entry conflicting with an in-flight program
is skipped this round, i.e. serial-fallback entries wait for their
conflicts to drain while clean entries overtake them).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Callable, Iterable

from .tenant import Tenant


@dataclasses.dataclass
class QueueEntry:
    """One queued program dispatch."""

    tenant: str
    priority: int
    program: Any  # SequenceProgram (or any .run(**kwargs) handle)
    footprint: Any  # the program's ProgramFootprint
    cost_s: float  # predicted seconds (the WFQ currency)
    seq: int  # global FIFO tiebreak
    run_kwargs: dict = dataclasses.field(default_factory=dict)
    start_tag: float = 0.0
    finish_tag: float = 0.0
    # signatures this entry may NOT overlap with (non-clean pairwise
    # verdicts at admission time -> serial fallback)
    conflicts: frozenset = frozenset()
    submitted_t: float = 0.0


class FairQueue:
    """One priority class's SFQ state: per-tenant FIFOs + virtual time.
    Not thread-safe — the scheduler serializes access under its lock."""

    def __init__(self) -> None:
        self.virtual_time = 0.0
        self._fifos: dict[str, deque[QueueEntry]] = {}

    def push(self, tenant: Tenant, entry: QueueEntry) -> None:
        entry.start_tag = max(self.virtual_time, tenant.finish_tag)
        entry.finish_tag = (entry.start_tag
                            + entry.cost_s / tenant.weight)
        tenant.finish_tag = entry.finish_tag
        self._fifos.setdefault(entry.tenant, deque()).append(entry)

    def pop_best(self, eligible: Callable[[QueueEntry], bool]
                 ) -> QueueEntry | None:
        """Remove and return the eligible head with the smallest
        (finish tag, seq); None when no head is eligible. Heads only:
        within a tenant the FIFO order is part of the program's
        semantics (its dispatches may carry state between runs)."""
        best: QueueEntry | None = None
        for fifo in self._fifos.values():
            if not fifo:
                continue
            head = fifo[0]
            if not eligible(head):
                continue
            if (best is None
                    or (head.finish_tag, head.seq)
                    < (best.finish_tag, best.seq)):
                best = head
        if best is None:
            return None
        self._fifos[best.tenant].popleft()
        self.virtual_time = max(self.virtual_time, best.start_tag)
        return best

    def __len__(self) -> int:
        return sum(len(f) for f in self._fifos.values())

    def queued_cost(self) -> float:
        return sum(e.cost_s for f in self._fifos.values() for e in f)

    def entries(self) -> Iterable[QueueEntry]:
        for fifo in self._fifos.values():
            yield from fifo
