"""Tenant registry: who is admitted, at what priority and weight, and
over which resource namespaces.

A tenant is a logical traffic source — one host process of the ACCL+
multi-process collective-engine posture (arxiv 2312.11742) — named,
classed (strict priority), weighted (fair-queue share within its
class), and optionally budgeted (an explicit SLO deadline per
dispatch; without one the scheduler derives the budget from the timing
model the way resilience/deadline.py derives per-call deadlines).

The registry also keeps the OPERATIONAL half of the isolation story:
every program a tenant submits contributes its interference-footprint
resources (buffer addresses, stream endpoints, ring slots,
communicators) to the tenant's namespace record, so
`disjointness_report()` can show per tenant what it binds and name any
cross-tenant sharing — the same facts the certifier proves over, but
surfaced as bookkeeping a human can read. Synthetic-tag namespaces
need no bookkeeping: a compiled program's hop tags are program-private
by construction (analysis/interference.py module docstring), which is
exactly the per-tenant tag-namespace promise.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable

from .errors import DuplicateTenantError, UnknownTenantError


@dataclasses.dataclass
class Tenant:
    """One admitted traffic source and its live accounting."""

    name: str
    priority: int = 1  # 0 is the highest class; strict across classes
    weight: float = 1.0  # fair-queue share within the class
    slo_budget_s: float | None = None  # explicit per-dispatch deadline
    comm: Any = None  # per-tenant communicator handle (optional)
    # WFQ state: finish tag of this tenant's last enqueued entry
    finish_tag: float = 0.0
    # accounting (the bench gate and the noisy-neighbor report read
    # these; the metrics registry carries the same numbers as series)
    submitted: int = 0
    dispatched: int = 0
    serialized: int = 0  # dispatches admitted in serial fallback mode
    dispatched_cost_s: float = 0.0
    measured_s: float = 0.0
    slo_misses: int = 0
    # namespace record: resource class -> bound ids, merged from every
    # submitted program's footprint
    namespaces: dict[str, set] = dataclasses.field(
        default_factory=lambda: {"addrs": set(), "streams": set(),
                                 "ring_slots": set(), "comms": set()})

    def record_footprint(self, fp) -> None:
        ns = self.namespaces
        ns["addrs"].update(a for a, _ in fp.reads)
        ns["addrs"].update(a for a, _ in fp.writes)
        ns["streams"].update(fp.streams)
        ns["ring_slots"].update(fp.ring_slots)
        ns["comms"].update(fp.comms)

    def account(self) -> dict[str, Any]:
        return {
            "priority": self.priority,
            "weight": self.weight,
            "submitted": self.submitted,
            "dispatched": self.dispatched,
            "serialized": self.serialized,
            "dispatched_cost_s": self.dispatched_cost_s,
            "measured_s": self.measured_s,
            "slo_misses": self.slo_misses,
        }


class TenantRegistry:
    """Name -> Tenant, with the validation at the seam: duplicate names
    and nonsensical QoS parameters fail HERE, before anything queues."""

    def __init__(self) -> None:
        self._tenants: dict[str, Tenant] = {}

    def register(self, name: str, *, priority: int = 1,
                 weight: float = 1.0, slo_budget_s: float | None = None,
                 comm: Any = None) -> Tenant:
        if not name or not isinstance(name, str):
            raise ValueError(f"tenant name must be a non-empty string, "
                             f"got {name!r}")
        if name in self._tenants:
            raise DuplicateTenantError(name)
        if int(priority) < 0:
            raise ValueError(f"priority must be >= 0 (0 is the highest "
                             f"class), got {priority}")
        if not float(weight) > 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        if slo_budget_s is not None and not float(slo_budget_s) > 0:
            raise ValueError(f"slo_budget_s must be > 0, "
                             f"got {slo_budget_s}")
        t = Tenant(name=name, priority=int(priority),
                   weight=float(weight),
                   slo_budget_s=(None if slo_budget_s is None
                                 else float(slo_budget_s)),
                   comm=comm)
        self._tenants[name] = t
        return t

    def get(self, name: str) -> Tenant:
        t = self._tenants.get(name)
        if t is None:
            raise UnknownTenantError(name)
        return t

    def __contains__(self, name: str) -> bool:
        return name in self._tenants

    def __len__(self) -> int:
        return len(self._tenants)

    def names(self) -> list[str]:
        return sorted(self._tenants)

    def tenants(self) -> Iterable[Tenant]:
        return [self._tenants[n] for n in sorted(self._tenants)]

    def disjointness_report(self) -> dict[str, Any]:
        """Per-tenant namespace sizes plus every cross-tenant resource
        intersection: empty `shared` IS the disjoint-by-construction
        claim, stated over what tenants actually bound (the certifier
        proves the same facts pairwise at admission; this is the
        human-readable ledger)."""
        names = self.names()
        per_tenant = {
            n: {k: len(v) for k, v in self._tenants[n].namespaces.items()}
            for n in names}
        shared: list[dict[str, Any]] = []
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                na, nb = (self._tenants[a].namespaces,
                          self._tenants[b].namespaces)
                for res in ("addrs", "streams", "ring_slots"):
                    inter = na[res] & nb[res]
                    if inter:
                        shared.append({
                            "tenants": [a, b], "resource": res,
                            "n_shared": len(inter),
                            "sample": sorted(inter)[:4]})
        return {"tenants": per_tenant, "shared": shared}
