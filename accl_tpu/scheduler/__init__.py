"""Multi-tenant scheduler: certified concurrent streams, QoS, and
admission control over SequenceProgram dispatches.

The subsystem that turns the interference certifier's pairwise proofs
(analysis/interference.py, ACCL601-604) and the calibrated timing
model (sequencer/timing.py) into an actual scheduler: tenants register
with a priority class and a fair-queue weight, programs are priced and
certified at admission, uncertifiable pairs serialize instead of
silently failing, every dispatch carries the certificate id of the set
it overlapped with, and per-tenant p99s / SLO residuals /
noisy-neighbor attribution ride the always-on metrics registry.

    sched = accl.scheduler(capacity_s=10.0)
    sched.register_tenant("interactive", priority=0, weight=4.0)
    sched.register_tenant("bulk", priority=1, weight=1.0)
    sched.submit("interactive", small_program, repeats=100)
    sched.submit("bulk", big_program, repeats=8)
    sched.drain(workers=2)
    sched.report()  # fairness, certificates, SLO residuals, neighbors

docs/scheduler.md has the admission/QoS/backpressure semantics, the
certificate lifecycle and the fairness math.
"""

from .errors import (
    DuplicateTenantError,
    SchedulerError,
    SchedulerSaturatedError,
    UnknownTenantError,
)
from .qos import FairQueue, QueueEntry
from .scheduler import MultiTenantScheduler
from .tenant import Tenant, TenantRegistry

__all__ = [
    "MultiTenantScheduler",
    "Tenant",
    "TenantRegistry",
    "FairQueue",
    "QueueEntry",
    "SchedulerError",
    "SchedulerSaturatedError",
    "UnknownTenantError",
    "DuplicateTenantError",
]
