"""The ACCL driver facade: the user-facing API of the framework.

Reference: driver/xrt/include/accl.hpp:45-1131 / src/accl.cpp — the
facade owns initialization (buffer rings, communicator, arithmetic
configs, tuning registers), exposes every collective in sync and async
forms with host/device sync control and optional wire compression, and
routes calls to an interchangeable device backend.

TPU shape of the API: one controller drives a communicator whose ranks
are devices on a mesh axis. Buffers are stacked (world, n) arrays
sharded across the axis. `from_device`/`to_device` mirror the
reference's from_fpga/to_fpga: they skip the host<->HBM syncs so chained
collectives stay on-device.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .arithconfig import DEFAULT_ARITH_CONFIG, validate_arith_config
from .buffers import BaseBuffer, DummyBuffer, TPUBuffer
from .communicator import Communicator, Rank
from .constants import (
    DEFAULT_EAGER_RX_BUF_SIZE,
    DEFAULT_MAX_EAGER_SIZE,
    DEFAULT_MAX_RENDEZVOUS_SIZE,
    DEFAULT_NUM_EAGER_RX_BUFS,
    CfgFunc,
    CompressionFlags,
    DataType,
    HostFlags,
    Operation,
    ReduceFunction,
    StreamFlags,
    TAG_ANY,
    TuningParams,
    dtype_nbytes,
    to_numpy_dtype,
)
from .descriptor import CallOptions, normalize_live_ranks
from .device.base import CCLOAddr
from .errors import (
    DtypeMismatchError,
    InvalidRootError,
    SequenceReuseError,
    ZeroLengthBufferError,
)
from .device.tpu_device import TPUDevice
from .request import BaseRequest
from .telemetry import get_tracer
from .utils.logging import Log

if TYPE_CHECKING:
    from .resilience.manager import ResilienceManager


class ACCL:
    """Driver facade over a device backend (reference ACCL class)."""

    def __init__(
        self,
        mesh: Mesh | None = None,
        axis_name: str = "ccl",
        device=None,
        n_egr_rx_bufs: int = DEFAULT_NUM_EAGER_RX_BUFS,
        egr_rx_buf_size: int = DEFAULT_EAGER_RX_BUF_SIZE,
        max_eager_size: int = DEFAULT_MAX_EAGER_SIZE,
        max_rendezvous_size: int = DEFAULT_MAX_RENDEZVOUS_SIZE,
        arith_config: dict | None = None,
    ):
        if device is None:
            if mesh is None:
                raise ValueError("provide a mesh or an explicit device backend")
            device = TPUDevice(mesh, axis_name)
        self.cclo = device
        self.mesh = getattr(device, "mesh", mesh)
        self.axis_name = getattr(device, "axis_name", axis_name)
        self.arith_config = validate_arith_config(arith_config or DEFAULT_ARITH_CONFIG)
        self._config = dict(
            n_egr_rx_bufs=n_egr_rx_bufs,
            egr_rx_buf_size=egr_rx_buf_size,
            max_eager_size=max_eager_size,
            max_rendezvous_size=max_rendezvous_size,
        )
        self.communicators: list[Communicator] = []
        self._initialized = False
        self._last_request: BaseRequest | None = None
        # armed resilience manager (accl_tpu/resilience/): when set,
        # every synchronous data-plane call is checked against its
        # model-derived deadline post-completion (one perf_counter pair
        # + a cached policy lookup; None = zero overhead)
        self._resilience: ResilienceManager | None = None
        # lazily-built cross-program interference certifier (see
        # certify_concurrent): long-lived so its per-pair verdict cache
        # spans admissions of a stable tenant set
        self._interference = None
        # placeholder rank buffers backing the buffer-less stream forms
        # (reference send/recv/copy overloads that take only a dataType,
        # accl.hpp:190,278,349): one per (count, dtype), reused
        self._stream_scratch: dict = {}
        self.initialize()

    # ------------------------------------------------------------------ #
    # bring-up (reference ACCL::initialize, accl.cpp:1066-1114)
    # ------------------------------------------------------------------ #

    def initialize(self):
        if self._initialized:
            raise RuntimeError("ACCL already initialized (CFGRDY set)")
        cfg = self._config
        dev = self.cclo
        # rx-ring + threshold config words (setup_eager_rx_buffers analog,
        # accl.cpp:1131-1172: descriptor table first, count written last).
        dev.write(CCLOAddr.EGR_RX_BUF_SIZE, cfg["egr_rx_buf_size"])
        dev.write(CCLOAddr.NUM_EGR_RX_BUFS, cfg["n_egr_rx_bufs"])
        dev.eager_rx_buf_size = cfg["egr_rx_buf_size"]
        # default communicator over the whole axis; re-initialization
        # invalidates all prior communicator handles (their exchange-memory
        # addresses are reallocated), so the list starts fresh
        self.communicators.clear()
        self._split_cache: dict[tuple[int, ...], Communicator] = {}
        world = dev.world
        ranks = [Rank(device_index=i, session_id=i) for i in range(world)]
        self.communicators.append(Communicator(ranks, 0, CCLOAddr.DYNAMIC_BASE))
        self._write_communicator(self.communicators[0])
        # arithmetic configs -> exchange memory (configure_arithmetic,
        # accl.cpp:1116-1125)
        addr = CCLOAddr.DYNAMIC_BASE + 4 * (2 + world * Communicator.WORDS_PER_RANK)
        for key, ac in self.arith_config.items():
            ac.set_exchmem(addr)
            for i, w in enumerate(ac.exchmem_words()):
                dev.write(addr + 4 * i, w)
            addr += 4 * ac.WORDS_PER_ROW
        # dynamic exchange-memory allocator tail: later communicators
        # (split) are laid out from here
        self._exchmem_alloc: int = addr
        # tuning registers (configure_tuning_parameters, accl.cpp:1198-1208)
        self.configure_tuning_parameters(
            TuningParams.default(cfg["max_rendezvous_size"]))
        # thresholds via config calls (accl.cpp:1096-1109)
        self._config_call(CfgFunc.set_max_eager_msg_size, cfg["max_eager_size"])
        self._config_call(CfgFunc.set_max_rendezvous_msg_size, cfg["max_rendezvous_size"])
        self._config_call(CfgFunc.enable_pkt, 0)
        dev.write(CCLOAddr.CFGRDY, 1)
        self._initialized = True

    def _config_call(self, fn: CfgFunc, value: int):
        req = self.cclo.call(
            CallOptions(scenario=Operation.config, function=int(fn), count=value)
        )
        req.check()

    def deinit(self):
        self._config_call(CfgFunc.reset_periph, 0)
        self.cclo.write(CCLOAddr.CFGRDY, 0)
        self._initialized = False

    def _write_communicator(self, comm: Communicator):
        for i, w in enumerate(comm.exchmem_words()):
            self.cclo.write(comm.exchmem_addr + 4 * i, w)

    # ------------------------------------------------------------------ #
    # buffers
    # ------------------------------------------------------------------ #

    @property
    def world(self) -> int:
        return self.cclo.world

    def _sharding(self):
        return NamedSharding(self.mesh, PartitionSpec(self.axis_name))

    def create_buffer(
        self, count: int, dtype=np.float32, data: np.ndarray | None = None,
        host_only: bool = False,
    ) -> TPUBuffer:
        """Allocate a stacked (world, count) rank buffer in HBM (the
        reference's create_buffer factories, accl.hpp:760-987).
        host_only buffers live in host memory and are staged to HBM around
        each call (the reference's host-only XRTBuffer / OP*_HOST flags)."""
        if isinstance(dtype, DataType):
            dtype = to_numpy_dtype(dtype)
        if data is None:
            data = np.zeros((self.world, count), dtype)
        else:
            # always copy: the buffer owns its memory (reference buffer
            # semantics), and backends may update the host mirror in place
            data = np.array(data, dtype).reshape(self.world, count)
        buf_cls = getattr(self.cclo, "buffer_class", TPUBuffer)
        buf = buf_cls(data, self._sharding(), host_only=host_only)
        self.cclo.register_buffer(buf)
        return buf

    def free_buffer(self, buf: BaseBuffer):
        self.cclo.unregister_buffer(buf)

    # ------------------------------------------------------------------ #
    # prepare_call: dtype/compression resolution (accl.cpp:1236-1356)
    # ------------------------------------------------------------------ #

    def _prepare(
        self,
        scenario: Operation,
        op0: BaseBuffer | None,
        op1: BaseBuffer | None,
        res: BaseBuffer | None,
        count: int,
        root_src_dst: int = 0,
        function: int = 0,
        tag: int = TAG_ANY,
        compress_dtype: DataType | None = None,
        comm: Communicator | None = None,
    ) -> CallOptions:
        if comm is None:
            comm = self.communicators[0]
        elif comm not in self.communicators:
            raise ValueError("communicator does not belong to this ACCL")
        # roots and src/dst ranks are communicator-relative; an out-of-range
        # rank would compile a schedule in which nobody is root
        if scenario in (Operation.bcast, Operation.scatter, Operation.gather,
                        Operation.reduce):
            if not 0 <= root_src_dst < comm.size:
                raise InvalidRootError(
                    f"root {root_src_dst} outside communicator of {comm.size}")
        elif scenario in (Operation.send, Operation.recv):
            src, dst = root_src_dst & 0xFFFF, (root_src_dst >> 16) & 0xFFFF
            if src >= comm.size or dst >= comm.size:
                raise InvalidRootError(
                    f"src/dst ({src},{dst}) outside communicator of {comm.size}")
        # a zero-length payload would compile a shape-degenerate schedule
        # and, dispatched device-resident, fail with no host-side symptom
        if count <= 0 and scenario not in (Operation.barrier,
                                           Operation.config, Operation.nop):
            raise ZeroLengthBufferError(
                f"{scenario.name} with count {count}: data-plane calls "
                "need a positive element count")
        dtype = None
        for b in (op0, op1, res):
            if b is not None and not isinstance(b, DummyBuffer):
                if dtype is None:
                    dtype = b.data_type
                elif b.data_type != dtype:
                    raise DtypeMismatchError(
                        "mixed-dtype operands: use compress_dtype for wire "
                        "compression instead"
                    )
        comp = CompressionFlags.NO_COMPRESSION
        host = HostFlags.NO_HOST
        for b, flag in ((op0, HostFlags.OP0_HOST), (op1, HostFlags.OP1_HOST),
                        (res, HostFlags.RES_HOST)):
            if b is not None and getattr(b, "host_only", False):
                host |= flag
        arithcfg_addr = 0
        if dtype is not None:
            pair = (dtype, compress_dtype or dtype)
            if pair not in self.arith_config:
                raise ValueError(f"no arithmetic configuration for {pair}")
            if compress_dtype is not None and compress_dtype != dtype:
                from .ops.compression import is_quantized

                # quantized lanes exist only where the backend ships the
                # blockwise ring kernels (the XLA schedule tier); a
                # lane-less executor would degrade the request to a cast
                # — 2 B/elem on a wire billed at ~1 B — so fail host-side
                if is_quantized(self.arith_config[pair]) and not getattr(
                        self.cclo, "supports_quantized_wire", False):
                    raise NotImplementedError(
                        f"{type(self.cclo).__name__} has no blockwise-"
                        f"quantized wire lanes ({pair[0].name} -> "
                        f"{pair[1].name}); quantized compression is "
                        "XLA-schedule-tier only")
                comp |= CompressionFlags.ETH_COMPRESSED
            arithcfg_addr = self.arith_config[pair].addr()
        return CallOptions(
            scenario=scenario,
            count=count,
            comm_addr=comm.exchmem_addr,
            root_src_dst=root_src_dst,
            function=function,
            tag=tag,
            arithcfg_addr=arithcfg_addr,
            compression_flags=comp,
            stream_flags=StreamFlags.NO_STREAM,
            host_flags=host,
            addr_0=0 if op0 is None else op0.address,
            addr_1=0 if op1 is None else op1.address,
            addr_2=0 if res is None else res.address,
            data_type=dtype or DataType.none,
            compress_dtype=compress_dtype or DataType.none,
        )

    def _stage_in(self, sync_in: list[BaseBuffer], from_device: bool):
        """Pre-launch host->HBM staging: host-only operands always stage;
        device buffers only when the caller didn't claim from_device
        residence."""
        for b in sync_in:
            if not from_device or getattr(b, "host_only", False):
                b.sync_to_device()

    def _complete(self, req, sync_out: list[BaseBuffer], to_device: bool,
                  run_async: bool):
        """Post-launch completion contract shared by single calls and
        recorded sequences: async defers sync-out to wait() (host-only
        results still need their copy-back even under to_device), sync
        waits/checks and pulls results."""
        self._last_request = req
        if run_async:
            if to_device:
                req._accl_sync_out = [
                    b for b in sync_out if getattr(b, "host_only", False)
                ]
            else:
                req._accl_sync_out = sync_out
            return req
        req.wait()
        req.check()
        for b in sync_out:
            if not to_device or getattr(b, "host_only", False):
                b.sync_from_device()
        return req

    def _execute(
        self,
        opts: CallOptions,
        sync_in: list[BaseBuffer],
        sync_out: list[BaseBuffer],
        from_device: bool,
        to_device: bool,
        run_async: bool,
    ):
        # armed deadlines (resilience seam): time the synchronous call
        # end to end so the manager can check it against its
        # model-derived deadline after completion. async calls complete
        # in wait() where no end-to-end wall time exists host-side.
        mgr = self._resilience
        t0 = (time.perf_counter()
              if mgr is not None and not run_async else None)
        # tracer.span is the shared no-op when telemetry is off (one
        # predicate; the bench smoke path gates the disabled cost <1%)
        with get_tracer().span(opts.scenario.name, cat="call",
                               track="facade") as sp:
            self._stage_in(sync_in, from_device)
            Log.debug("call %s count=%d flags=c%x/s%x", opts.scenario.name,
                      opts.count, int(opts.compression_flags),
                      int(opts.stream_flags))
            req = self.cclo.start(opts)
            ret = self._complete(req, sync_out, to_device, run_async)
            if mgr is not None and t0 is not None:
                mgr.observe_call(opts.scenario, opts.count,
                                 dtype_nbytes(opts.data_type)
                                 if opts.data_type != DataType.none else 4,
                                 time.perf_counter() - t0)
            if get_tracer().active:  # attach what the device resolved
                sp.set(op=opts.scenario.name, count=opts.count,
                       retcode=req.retcode)
                if run_async:
                    sp.set(dispatch_only=True)
                plan = getattr(req, "plan", None)
                if plan is not None:
                    sp.set(algorithm=plan.algorithm.name,
                           protocol=plan.protocol.name)
                pred = getattr(req, "predicted_s", None)
                if pred is not None:
                    sp.set(predicted_s=pred)
            return ret

    def wait(self, req: BaseRequest):
        """Complete an async request (sync-out deferred at start time)."""
        try:
            req.wait()
            req.check()
            for b in getattr(req, "_accl_sync_out", []):
                b.sync_from_device()
        finally:
            # release the private placeholder a run_async stream form rode
            # (fresh _scratch) even when check() raises on a failed op:
            # it was registered like any user buffer and would otherwise
            # leak one (world, count) array per failed async call
            sc = getattr(req, "_accl_scratch", None)
            if sc is not None:
                self.free_buffer(sc)
                req._accl_scratch = None
        return req

    def get_duration_ns(self, req: BaseRequest | None = None) -> int:
        req = req or self._last_request
        return 0 if req is None else req.get_duration_ns()

    # ------------------------------------------------------------------ #
    # primitives & collectives (reference accl.cpp:122-944)
    # ------------------------------------------------------------------ #

    def nop(self):
        return self.cclo.call(CallOptions(scenario=Operation.nop))

    def copy(self, srcbuf, dstbuf, count, *, from_device=False, to_device=False,
             run_async=False):
        opts = self._prepare(Operation.copy, srcbuf, None, dstbuf, count)
        return self._execute(opts, [srcbuf], [dstbuf], from_device, to_device,
                             run_async)

    def _scratch(self, count, dtype, fresh=False):
        """Internal placeholder buffer for a buffer-less stream endpoint
        (the dataType-only overloads of the reference driver). The cache is
        keyed by (count, dtype), so two in-flight calls of the same shape
        would DMA through the same placeholder — callers with run_async
        pass fresh=True to get a private buffer instead of the cached one."""
        if isinstance(dtype, DataType):
            dtype = to_numpy_dtype(dtype)
        if fresh:
            return self.create_buffer(count, dtype)
        key = (int(count), str(np.dtype(dtype)))
        buf = self._stream_scratch.get(key)
        if buf is None:
            buf = self.create_buffer(count, dtype)
            self._stream_scratch[key] = buf
        return buf

    def copy_from_stream(self, dstbuf, count, *, op0_stream, to_device=False,
                         run_async=False):
        """Operand arrives from a registered producer stream, result lands
        in dstbuf (reference copy_from_stream, accl.hpp:317)."""
        opts = self._prepare(Operation.copy, dstbuf, None, dstbuf, count)
        self._stream_opts(opts, op0_stream, None)
        return self._execute(opts, [dstbuf], [dstbuf], True, to_device,
                             run_async)

    def copy_to_stream(self, srcbuf, count, *, res_stream, dstbuf=None,
                       from_device=False, to_device=False,
                       run_async=False):
        """srcbuf routes through a registered consumer stream (reference
        copy_to_stream, accl.hpp:334). The consumer's return value
        materializes into dstbuf when given (the observable form; the
        reference's PL-kernel sink has no host-visible landing spot),
        else into an internal placeholder. `to_device=True` skips the
        device->host result sync even with a dstbuf — the chained
        on-device form (the eager train-step twin keeps its gradient
        intermediate resident between stages)."""
        fresh = dstbuf is None and run_async
        dst = dstbuf if dstbuf is not None else self._scratch(
            count, srcbuf.np_dtype, fresh=run_async)
        opts = self._prepare(Operation.copy, srcbuf, None, dst, count)
        self._stream_opts(opts, None, res_stream)
        # to_device=True (skip the device->host result sync) for the
        # unobserved internal placeholder, or on caller request
        req = self._execute(opts, [srcbuf], [dst], from_device,
                            to_device or dstbuf is None, run_async)
        if fresh:
            req._accl_scratch = dst
        return req

    def copy_from_to_stream(self, data_type, count, *, op0_stream, res_stream,
                            dstbuf=None, run_async=False):
        """Producer stream -> consumer stream, no host buffers (reference
        copy_from_to_stream, accl.hpp:349); dstbuf optionally captures the
        consumer output."""
        scratch = self._scratch(count, data_type, fresh=run_async)
        dst = dstbuf if dstbuf is not None else scratch
        opts = self._prepare(Operation.copy, scratch, None, dst, count)
        self._stream_opts(opts, op0_stream, res_stream)
        req = self._execute(opts, [scratch], [dst], True,
                            dstbuf is None, run_async)
        if run_async:
            req._accl_scratch = scratch
        return req

    def combine(self, count, function, op0, op1, res, *, from_device=False,
                to_device=False, run_async=False):
        opts = self._prepare(Operation.combine, op0, op1, res, count,
                             function=int(function))
        return self._execute(opts, [op0, op1], [res], from_device, to_device,
                             run_async)

    def send(self, srcbuf, count, src, dst, tag=TAG_ANY, *, from_device=False,
             run_async=False, compress_dtype=None, comm=None,
             op0_stream=None):
        """srcbuf may be a DataType when op0_stream is set (the reference's
        stream-send overload, accl.hpp:190: the payload comes from the
        producer kernel, not a buffer)."""
        fresh = False
        if isinstance(srcbuf, DataType):
            if op0_stream is None:
                raise ValueError("dataType-only send requires op0_stream")
            srcbuf = self._scratch(count, srcbuf, fresh=run_async)
            from_device = True
            fresh = run_async
        opts = self._prepare(Operation.send, srcbuf, None, None, count,
                             root_src_dst=src | (dst << 16), tag=tag,
                             compress_dtype=compress_dtype, comm=comm)
        self._stream_opts(opts, op0_stream, None)
        req = self._execute(opts, [srcbuf], [], from_device, True, run_async)
        if fresh:
            req._accl_scratch = srcbuf
        return req

    def recv(self, dstbuf, count, src, dst, tag=TAG_ANY, *, to_device=False,
             run_async=False, compress_dtype=None, comm=None,
             res_stream=None):
        """dstbuf may be a DataType when res_stream is set (the reference's
        stream-recv overload, accl.hpp:278: the payload feeds the consumer
        kernel; pass a real buffer to also capture the consumer output)."""
        fresh = False
        if isinstance(dstbuf, DataType):
            if res_stream is None:
                raise ValueError("dataType-only recv requires res_stream")
            dstbuf = self._scratch(count, dstbuf, fresh=run_async)
            to_device = True  # nothing observes the placeholder: skip sync
            fresh = run_async
        opts = self._prepare(Operation.recv, None, None, dstbuf, count,
                             root_src_dst=src | (dst << 16), tag=tag,
                             compress_dtype=compress_dtype, comm=comm)
        self._stream_opts(opts, None, res_stream)
        req = self._execute(opts, [], [dstbuf], True, to_device, run_async)
        if fresh:
            req._accl_scratch = dstbuf
        return req

    def _stream_opts(self, opts, op0_stream, res_stream):
        """Arm OP0_STREAM/RES_STREAM on a prepared descriptor (reference:
        streams route through any collective, ccl_offload_control.c:628-636).
        Stream ids ride dedicated descriptor bytes (word 8), leaving the
        tag free for matching."""
        if op0_stream is None and res_stream is None:
            return opts
        if not hasattr(self.cclo, "streams"):
            raise NotImplementedError(
                f"{type(self.cclo).__name__} does not support streamed "
                "collectives")
        from .ops.streams import check_stream_id

        flags = StreamFlags.NO_STREAM
        if op0_stream is not None:
            flags |= StreamFlags.OP0_STREAM
            opts.op0_stream_id = check_stream_id(op0_stream)
        if res_stream is not None:
            flags |= StreamFlags.RES_STREAM
            opts.res_stream_id = check_stream_id(res_stream)
        opts.stream_flags = flags
        return opts

    def bcast(self, buf, count, root, *, from_device=False, to_device=False,
              run_async=False, compress_dtype=None, comm=None,
              op0_stream=None, res_stream=None):
        opts = self._prepare(Operation.bcast, buf, None, buf, count,
                             root_src_dst=root, compress_dtype=compress_dtype,
                             comm=comm)
        self._stream_opts(opts, op0_stream, res_stream)
        return self._execute(opts, [buf], [buf], from_device, to_device,
                             run_async)

    def scatter(self, sendbuf, recvbuf, count, root, *, from_device=False,
                to_device=False, run_async=False, compress_dtype=None,
                comm=None, op0_stream=None, res_stream=None):
        opts = self._prepare(Operation.scatter, sendbuf, None, recvbuf, count,
                             root_src_dst=root, compress_dtype=compress_dtype,
                             comm=comm)
        self._stream_opts(opts, op0_stream, res_stream)
        return self._execute(opts, [sendbuf], [recvbuf], from_device,
                             to_device, run_async)

    def gather(self, sendbuf, recvbuf, count, root, *, from_device=False,
               to_device=False, run_async=False, compress_dtype=None,
               comm=None, op0_stream=None, res_stream=None):
        opts = self._prepare(Operation.gather, sendbuf, None, recvbuf, count,
                             root_src_dst=root, compress_dtype=compress_dtype,
                             comm=comm)
        self._stream_opts(opts, op0_stream, res_stream)
        return self._execute(opts, [sendbuf], [recvbuf], from_device,
                             to_device, run_async)

    def allgather(self, sendbuf, recvbuf, count, *, from_device=False,
                  to_device=False, run_async=False, compress_dtype=None,
                  comm=None, op0_stream=None, res_stream=None):
        opts = self._prepare(Operation.allgather, sendbuf, None, recvbuf,
                             count, compress_dtype=compress_dtype, comm=comm)
        self._stream_opts(opts, op0_stream, res_stream)
        return self._execute(opts, [sendbuf], [recvbuf], from_device,
                             to_device, run_async)

    def reduce(self, sendbuf, recvbuf, count, root, function, *,
               from_device=False, to_device=False, run_async=False,
               compress_dtype=None, comm=None, op0_stream=None,
               res_stream=None):
        opts = self._prepare(Operation.reduce, sendbuf, None, recvbuf, count,
                             root_src_dst=root, function=int(function),
                             compress_dtype=compress_dtype, comm=comm)
        self._stream_opts(opts, op0_stream, res_stream)
        return self._execute(opts, [sendbuf], [recvbuf], from_device,
                             to_device, run_async)

    def allreduce(self, sendbuf, recvbuf, count, function, *,
                  from_device=False, to_device=False, run_async=False,
                  compress_dtype=None, comm=None,
                  op0_stream=None, res_stream=None,
                  mode="all", live_ranks=None):
        """`mode="live_subset"` is the CERTIFIED degraded form
        (docs/resilience.md): `live_ranks` declares the
        surviving-contributor set, every other rank's operand is masked
        to exact zeros at the source inside the schedule, and the
        semantic certifier proves the answer sums exactly the declared
        survivors (the alltoallv drop-to-zeros posture generalized to
        the reduction — a dead rank's stale buffer can never leak a
        ghost contribution). SUM only, exact wire only. A full
        survivor set normalizes to the ordinary allreduce bit-for-bit
        (one compiled program, like the all-full alltoallv vector)."""
        opts = self._prepare(Operation.allreduce, sendbuf, None, recvbuf,
                             count, function=int(function),
                             compress_dtype=compress_dtype, comm=comm)
        opts.live_ranks = self._live_subset(mode, live_ranks, function,
                                            compress_dtype, comm)
        self._stream_opts(opts, op0_stream, res_stream)
        return self._execute(opts, [sendbuf], [recvbuf], from_device,
                             to_device, run_async)

    def _live_subset(self, mode, live_ranks, function, compress_dtype,
                     comm) -> tuple:
        """Validate the degraded-mode arguments at the host seam (the
        _prepare posture: a bad survivor set fails before anything
        compiles or dispatches). Returns the normalized live_ranks
        tuple for the descriptor — () for the ordinary collective."""
        if mode not in ("all", "live_subset"):
            raise ValueError(
                f"allreduce mode must be 'all'|'live_subset', got {mode!r}")
        if mode == "all":
            if live_ranks is not None:
                raise ValueError(
                    "live_ranks requires mode='live_subset'")
            return ()
        if not live_ranks:
            raise ValueError(
                "mode='live_subset' needs a non-empty live_ranks set")
        comm_size = (comm or self.communicators[0]).size
        lr = normalize_live_ranks(live_ranks, comm_size)
        if ReduceFunction(function) != ReduceFunction.SUM:
            raise ValueError(
                "live-subset allreduce is SUM-only: the zero mask is "
                "the fold identity for SUM, nothing else is certified")
        if compress_dtype is not None:
            raise NotImplementedError(
                "live-subset allreduce is exact-wire only")
        if lr == tuple(range(comm_size)):
            # every rank lives: the ordinary allreduce, shared program
            return ()
        if not getattr(self.cclo, "supports_live_subset", False):
            raise NotImplementedError(
                f"{type(self.cclo).__name__} has no masked live-subset "
                "ring; degraded allreduce is XLA-schedule-tier only")
        return lr

    def reduce_scatter(self, sendbuf, recvbuf, count, function, *,
                       from_device=False, to_device=False, run_async=False,
                       compress_dtype=None, comm=None, op0_stream=None,
                       res_stream=None):
        opts = self._prepare(Operation.reduce_scatter, sendbuf, None, recvbuf,
                             count, function=int(function),
                             compress_dtype=compress_dtype, comm=comm)
        self._stream_opts(opts, op0_stream, res_stream)
        return self._execute(opts, [sendbuf], [recvbuf], from_device,
                             to_device, run_async)

    def alltoall(self, sendbuf, recvbuf, count, *, from_device=False,
                 to_device=False, run_async=False, compress_dtype=None,
                 comm=None, op0_stream=None, res_stream=None):
        opts = self._prepare(Operation.alltoall, sendbuf, None, recvbuf,
                             count, compress_dtype=compress_dtype, comm=comm)
        self._stream_opts(opts, op0_stream, res_stream)
        return self._execute(opts, [sendbuf], [recvbuf], from_device,
                             to_device, run_async)

    def alltoallv(self, sendbuf, recvbuf, count, send_counts, *,
                  from_device=False, to_device=False, run_async=False,
                  compress_dtype=None, comm=None, op0_stream=None,
                  res_stream=None):
        """Capacity-bounded all-to-all: the buffer keeps alltoall's
        uniform world-slot layout (`count` elements per peer slot), but
        peer p receives only the first `send_counts[p]` elements of each
        source's slot p — the per-peer capacity (the MoE dispatch's
        expert capacity) — and the overflow tail is dropped to zeros ON
        THE WIRE (schedules.alltoallv_schedule; each hop moves
        max(send_counts) elements, so an under-capacity exchange ships
        fewer bytes than the dense one). An all-`count` vector is the
        dense alltoall, bit-for-bit. XLA-schedule-tier only: executors
        without the capacity-masked rotation reject up front."""
        opts = self._prepare_alltoallv(sendbuf, recvbuf, count, send_counts,
                                       compress_dtype=compress_dtype,
                                       comm=comm)
        self._stream_opts(opts, op0_stream, res_stream)
        return self._execute(opts, [sendbuf], [recvbuf], from_device,
                             to_device, run_async)

    def _prepare_alltoallv(self, sendbuf, recvbuf, count, send_counts, *,
                           compress_dtype=None, comm=None) -> CallOptions:
        """The alltoallv descriptor: a dense-alltoall descriptor plus the
        static per-peer capacity vector (validated here, the host seam,
        so a bad vector fails before anything compiles or dispatches)."""
        comm_size = (comm or self.communicators[0]).size
        pc = tuple(int(c) for c in send_counts)
        if len(pc) != comm_size:
            raise ValueError(
                f"alltoallv needs one send count per rank: got {len(pc)} "
                f"for communicator of {comm_size}")
        if any(c <= 0 for c in pc):
            raise ZeroLengthBufferError(
                f"alltoallv send counts {pc} include a non-positive "
                "capacity; every peer needs a positive valid prefix")
        if any(c > count for c in pc):
            raise ValueError(
                f"alltoallv send counts {pc} exceed the {count}-element "
                "peer slot")
        if all(c == count for c in pc):
            # an all-full vector IS the dense alltoall: normalize at the
            # descriptor seam too (not just in select_algorithm), so the
            # signature — and with it the compiled program — is SHARED
            # with the plain alltoall at the same shape
            pc = ()
        if pc and not getattr(self.cclo, "supports_alltoallv", False):
            raise NotImplementedError(
                f"{type(self.cclo).__name__} has no capacity-masked "
                "alltoallv rotation; alltoallv is XLA-schedule-tier only")
        opts = self._prepare(Operation.alltoall, sendbuf, None, recvbuf,
                             count, compress_dtype=compress_dtype, comm=comm)
        opts.peer_counts = pc
        return opts

    # ------------------------------------------------------------------ #
    # call sequences: record a batch, dispatch ONE fused program
    # ------------------------------------------------------------------ #

    def sequence(self, comm: Communicator | None = None,
                 lint: str = "error",
                 persistent=()) -> "SequenceRecorder":
        """Start recording a call sequence: collective/copy/combine calls
        on the returned recorder queue descriptors host-side (nothing
        executes), then `run()` lowers the WHOLE batch into one compiled
        device program — a single dispatch, intermediates threaded
        on-device between stages, stream endpoints spliced at the seams.
        Usable as a context manager (the batch runs on clean exit)::

            with accl.sequence() as seq:
                seq.reduce_scatter(a, b, n, ReduceFunction.SUM)
                seq.allgather(b, c, n)
            # one dispatch happened; results are in b and c

        Results are bitwise-identical to issuing the same calls eagerly
        back to back (the cross-executor fuzz pins this).

        `lint` runs the batch through the static analyzer
        (accl_tpu/analysis/, docs/lint.md) before it compiles:
        "error" (default) raises errors.LintError on hazardous batches,
        "warn" logs the diagnostics and proceeds, "off" opts out, and
        "deep" adds the exhaustive-interleaving tier (wildcard races
        and schedule-dependent deadlocks over every legal match order,
        ACCL205/206 — budgeted, enforced like "error").

        `persistent` declares DEVICE-RESIDENT STATE buffers: buffers
        whose tails carry results from one dispatch of the compiled
        program to the next (a KV cache, an optimizer state), refreshed
        partial-width inside the batch by design. The hazard pass
        waives ACCL101 (read wider than the in-sequence producer wrote)
        for exactly those buffers — every other diagnostic, including
        WAR/WAW ordering and the static width check, still applies."""
        if lint not in ("error", "warn", "off", "deep"):
            raise ValueError(
                f"lint must be 'error'|'warn'|'off'|'deep', got {lint!r}")
        if not hasattr(self.cclo, "start_sequence"):
            raise NotImplementedError(
                f"{type(self.cclo).__name__} does not support call "
                "sequences")
        return SequenceRecorder(self, comm, lint=lint,
                                persistent=persistent)

    def certify_concurrent(self, programs, mode: str = "error"):
        """Prove a set of compiled SequencePrograms safe to dispatch
        CONCURRENTLY: pairwise non-interference over their footprint
        summaries (O(N^2) dict-sized checks), escalating a pair to the
        bounded cross-program product model check only when its
        summaries overlap (analysis/interference.py, ACCL601-604).

        A clean verdict means any interleaving of the set is equivalent
        to its serial composition — the admission criterion the
        multi-tenant sequencer (ROADMAP item 1) checks certificates
        against. On success every program is stamped with the set's
        certificate id (`SequenceProgram.certificate`), which then
        rides its dispatch spans so the flight recorder can name the
        admitted set a wedged dispatch belonged to.

        `programs` may mix SequenceProgram handles and raw
        ProgramFootprint summaries (a remote tenant's shipped
        footprint). `mode` follows the lint gate: "error" raises
        LintError on findings, "warn" logs them, "off" skips
        enforcement; all modes return the diagnostic list. Verdicts are
        cached per pair on this ACCL, keyed by the two composite
        signatures."""
        from .analysis.diagnostics import enforce
        from .analysis.interference import (InterferenceCertifier,
                                            ProgramFootprint,
                                            certificate_id)

        if self._interference is None:
            self._interference = InterferenceCertifier()
        footprints = []
        handles = []
        for p in programs:
            if isinstance(p, ProgramFootprint):
                footprints.append(p)
                continue
            fp = getattr(p, "footprint", None)
            if fp is None:
                raise ValueError(
                    f"{type(p).__name__} carries no interference "
                    "footprint (pass SequenceProgram handles or "
                    "ProgramFootprint summaries)")
            footprints.append(fp)
            handles.append(p)
        diags = self._interference.certify(footprints)
        if not diags:
            cert = certificate_id(footprints)
            for h in handles:
                h._prepared.cert = cert
        enforce(diags, mode)
        return diags

    def scheduler(self, **kwargs) -> "MultiTenantScheduler":
        """Build a multi-tenant scheduler over this facade
        (scheduler/MultiTenantScheduler, docs/scheduler.md): admission
        control with live interference certificates (the scheduler
        shares THIS facade's long-lived certifier, so verdicts cached
        by certify_concurrent serve admission and vice versa), strict
        priority classes with weighted fair queueing over predicted
        cost, typed backpressure, and per-tenant accountability
        through the metrics registry. Kwargs forward to the
        MultiTenantScheduler constructor (capacity_s, registry, ...)."""
        from .scheduler import MultiTenantScheduler

        return MultiTenantScheduler(self, **kwargs)

    def split(self, rank_indices: list[int]) -> Communicator:
        """Create a sub-communicator over a subset of ranks (reference
        multi-communicator support: the firmware caches the addressed
        communicator per call from the descriptor's comm_addr,
        ccl_offload_control.c:2317-2372). The new communicator's rank
        table is written to exchange memory and its handle can be passed
        as `comm=` to any collective — no new ACCL, no new device, no new
        compile caches. Buffers stay full-world stacked arrays; a
        sub-communicator collective touches only its member rows."""
        if not getattr(self.cclo, "supports_split", True):
            raise NotImplementedError(
                f"{type(self.cclo).__name__} does not support "
                "sub-communicators yet")
        if len(set(rank_indices)) != len(rank_indices):
            raise ValueError("duplicate ranks in split")
        if not all(0 <= r < self.world for r in rank_indices):
            raise ValueError(f"split ranks outside world of {self.world}")
        # repeated splits of the same member list reuse the existing table
        # (the allocator only grows; the device-side group cache already
        # dedups the execution context, so a fresh table would only burn
        # exchange memory)
        cached = self._split_cache.get(tuple(rank_indices))
        if cached is not None and cached in self.communicators:
            return cached
        import dataclasses

        parent = self.communicators[0].ranks
        # backend topology constraints fail HERE, before any exchange
        # memory is allocated for the group
        validate = getattr(self.cclo, "validate_split", None)
        if validate is not None:
            validate(tuple(parent[r].device_index for r in rank_indices))
        ranks = [
            dataclasses.replace(parent[r], inbound_seq=0, outbound_seq=0)
            for r in rank_indices
        ]
        nwords = 2 + len(ranks) * Communicator.WORDS_PER_RANK
        if self._exchmem_alloc + 4 * nwords > CCLOAddr.DYNAMIC_END:
            raise MemoryError("exchange memory exhausted by communicators")
        comm = Communicator(ranks, 0, self._exchmem_alloc)
        self._exchmem_alloc += 4 * nwords
        self.communicators.append(comm)
        self._write_communicator(comm)
        self._split_cache[tuple(rank_indices)] = comm
        return comm

    def register_stream_producer(self, stream_id: int, fn):
        """Attach a device-side producer to a kernel stream (the PL
        kernel's data_to_cclo port, accl_hls.h ACCLData)."""
        self.cclo.streams.register_producer(stream_id, fn)

    def register_stream_consumer(self, stream_id: int, fn):
        self.cclo.streams.register_consumer(stream_id, fn)

    def stream_put(self, count, stream_id, src, dst, recvbuf, *,
                   dtype=DataType.float32, run_async=False):
        """Device-autonomous send: the payload is produced on-device by
        the registered stream producer and lands in recvbuf at dst after
        dst's consumer kernel — no host data path (reference stream_put
        flow, SURVEY.md §3.4 / vadd_put.cpp:55-72)."""
        opts = CallOptions(
            scenario=Operation.send,
            count=count,
            root_src_dst=src | (dst << 16),
            op0_stream_id=stream_id,
            stream_flags=StreamFlags.OP0_STREAM,
            data_type=dtype,
            addr_2=recvbuf.address,
        )
        req = self.cclo.stream_put(opts)
        self._last_request = req
        if run_async:
            req._accl_sync_out = [recvbuf]
            return req
        req.wait()
        req.check()
        recvbuf.sync_from_device()
        return req

    def barrier(self, comm=None):
        opts = self._prepare(Operation.barrier, None, None, None, 0, comm=comm)
        req = self.cclo.start(opts)
        req.wait()
        req.check()
        return req

    # ------------------------------------------------------------------ #
    # housekeeping / observability
    # ------------------------------------------------------------------ #

    def set_timeout(self, value: int):
        self._config_call(CfgFunc.set_timeout, value)

    def set_max_eager_size(self, value: int):
        self._config_call(CfgFunc.set_max_eager_msg_size, value)

    def set_max_rendezvous_size(self, value: int):
        self._config_call(CfgFunc.set_max_rendezvous_msg_size, value)

    def dump_exchange_memory(self) -> str:
        return self.cclo.dump_exchange_memory()

    def dump_communicator(self, index: int = 0) -> str:
        return self.communicators[index].dump()

    def dump_eager_rx_buffers(self) -> str:
        """Snapshot of the eager rx machinery (reference
        dump_eager_rx_buffers, accl.cpp:964-1012): the native executor
        reports its rx ring slot-by-slot; the XLA executor reports its
        parked recv/send queues (the rx-notification parking that plays
        the ring's role there)."""
        return self.cclo.dump_eager_rx_buffers()

    def configure_tuning_parameters(self, tuning: TuningParams):
        """Write the algorithm-tuning registers (the reference's six
        plus the three synthesized-schedule crossovers) to exchange memory
        (reference configure_tuning_parameters, accl.cpp:1198-1208); both
        executors read them per call."""
        dev = self.cclo
        dev.write(CCLOAddr.GATHER_FLAT_TREE_MAX_FANIN,
                  tuning.gather_flat_tree_max_fanin)
        dev.write(CCLOAddr.GATHER_FLAT_TREE_MAX_COUNT,
                  tuning.gather_flat_tree_max_count)
        dev.write(CCLOAddr.BCAST_FLAT_TREE_MAX_RANKS,
                  tuning.bcast_flat_tree_max_ranks)
        dev.write(CCLOAddr.REDUCE_FLAT_TREE_MAX_RANKS,
                  tuning.reduce_flat_tree_max_ranks)
        dev.write(CCLOAddr.REDUCE_FLAT_TREE_MAX_COUNT,
                  tuning.reduce_flat_tree_max_count)
        dev.write(CCLOAddr.ALLREDUCE_COMPOSITION_MAX_COUNT,
                  tuning.allreduce_composition_max_count)
        dev.write(CCLOAddr.SYNTH_ALLREDUCE_MAX_COUNT,
                  tuning.synth_allreduce_max_count)
        dev.write(CCLOAddr.SYNTH_ALLGATHER_MAX_COUNT,
                  tuning.synth_allgather_max_count)
        dev.write(CCLOAddr.SYNTH_REDUCE_SCATTER_MAX_COUNT,
                  tuning.synth_reduce_scatter_max_count)
        dev.write(CCLOAddr.HIER_ALLREDUCE_MIN_COUNT,
                  tuning.hier_allreduce_min_count)
        dev.write(CCLOAddr.ALLTOALL_COMPRESS_MIN_COUNT,
                  tuning.alltoall_compress_min_count)
        dev.write(CCLOAddr.OVERLAP_MIN_COUNT, tuning.overlap_min_count)
        dev.write(CCLOAddr.SYNTH_LATENCY_MAX_COUNT,
                  tuning.synth_latency_max_count)

    def autotune(self, link=None, timing_model_path=None,
                 tier: str = "emulator",
                 wire_dtype: DataType = DataType.none,
                 tier_links=None, compute_fit=None) -> TuningParams:
        """Derive the switch-point tuning registers — the reference's
        four, the synth windows, and (on a device that declares a
        two-tier topology) HIER_ALLREDUCE_MIN_COUNT — from the
        calibrated timing model and apply them (gather fan-in keeps its
        structural default): the measured-performance closure of the
        reference's hand-picked defaults. When the hierarchical window
        opens, the device's per-tier wire dtypes (`hier_wires`) are
        also set from `plan.select_tier_wires` under the same per-tier
        calibration (the int8-on-DCN / fp32-on-ICI arbitration), so
        subsequent fp32 allreduces in the window ship the arbitrated
        wires. `link` is a
        sequencer.timing.LinkParams; absent, it is loaded from
        `timing_model_path` (default accl_log/timing_model.json, written
        by tools/timing_model.py). tier="tpu" uses the on-chip
        calibration tier instead of the emulator link fit (dispatch alpha
        + HBM-bounded beta — a projection until ICI is measured on a
        multi-chip slice). `wire_dtype` tunes for a workload running
        that compression lane on its collectives (e.g. DataType.int8 for
        the blockwise-quantized wire): crossover arithmetic happens in
        wire bytes, so byte-threshold registers stretch by the
        compression ratio — the registers MOVE when quantized lanes are
        enabled. Returns the applied TuningParams."""
        from .sequencer.timing import (
            LinkParams,
            emulator_link,
            tuning_crossovers,
        )

        if tier not in ("emulator", "tpu"):
            raise ValueError(f"unknown autotune tier {tier!r}")
        if link is not None and tier != "emulator":
            raise ValueError("pass either link= or tier=, not both")
        if link is None:
            import json
            import pathlib

            path = pathlib.Path(
                timing_model_path
                or pathlib.Path(__file__).parent.parent
                / "accl_log" / "timing_model.json")
            model = json.loads(path.read_text())
            if tier == "tpu":
                t = model.get("tpu_tier")
                if not t or not t.get("hbm_stream_gbps"):
                    raise ValueError(
                        "timing model has no usable tpu_tier; re-run "
                        "tools/timing_model.py with an on-chip profile")
                link = LinkParams(alpha=t["dispatch_alpha_us"] * 1e-6,
                                  beta=t["hbm_stream_gbps"] * 1e9)
            else:
                link = emulator_link(model)
        # Per-tier crossover: with a per-tier calibration (passed in, or
        # the shipped link_tiers fit) AND a device that declares a
        # two-tier topology, the hierarchical-allreduce register moves
        # to the predicted hier-beats-flat window; otherwise it stays 0
        # (off) and selection is unchanged.
        topology = getattr(self.cclo, "hier_topology", None)
        if tier_links is None:
            from .telemetry.feedback import default_tier_links

            tier_links = default_tier_links(timing_model_path)
        # the overlap register needs a measured compute term next to
        # the link fit (timing.ComputeFit); absent one the crossover
        # stays 0 and streamed-allreduce selection is untouched
        if compute_fit is None:
            from .telemetry.feedback import default_compute_fit

            compute_fit = default_compute_fit(timing_model_path)
        cross = tuning_crossovers(link, world=self.world,
                                  wire_dtype=wire_dtype,
                                  tier_links=tier_links,
                                  topology=topology,
                                  compute_fit=compute_fit)
        tuning = TuningParams.from_crossovers(cross)
        self.configure_tuning_parameters(tuning)
        # per-tier wire arbitration rides the same tune: with the
        # window open, arbitrate each tier's wire at a clearly
        # bandwidth-bound payload (>= 1 MiB, never below the window
        # floor — the floor itself can sit in the latency regime where
        # no compression clears the min-gain bar) for the canonical
        # fp32 payload; _resolve_step applies these only to fp32
        # calls, the dtype they were arbitrated for
        if (tuning.hier_allreduce_min_count > 0 and topology is not None
                and tier_links is not None
                and hasattr(self.cclo, "hier_wires")):
            from .sequencer.plan import select_tier_wires

            cnt = max(tuning.hier_allreduce_min_count, 1 << 20) // 4
            self.cclo.hier_wires = select_tier_wires(
                cnt, DataType.float32, topology, tier_links,
                arith_table=self.arith_config,
                quantized_ok=getattr(self.cclo,
                                     "supports_quantized_wire", False))
        return tuning

    def arm_resilience(self, manager: ResilienceManager | None) -> None:
        """Arm per-call deadlines on this facade
        (resilience.ResilienceManager with a DeadlinePolicy): every
        synchronous data-plane call is checked against its
        model-derived deadline after completion — a miss produces the
        structured DeadlineMissed verdict (flight-recorder post-mortem
        attached) on the manager, it never fails the completed call.
        Disarm with ``arm_resilience(None)``; disarmed cost is one
        attribute check per call (the no-fault control run is pinned
        bit-for-bit identical with the seam armed)."""
        self._resilience = manager

    def soft_reset(self):
        """reset_periph config call (reference soft_reset, accl.cpp:57-69):
        drains parked/pending call state and compiled-schedule caches but
        leaves the device configured (unlike deinit, which also clears
        CFGRDY)."""
        self._config_call(CfgFunc.reset_periph, 0)
        # the compiled-schedule caches are gone: an armed resilience
        # manager must re-exempt every shape's next (recompiling)
        # dispatch, or the compile time reads as a deadline miss
        if self._resilience is not None:
            self._resilience.reset_warmup()

    def get_comm_group(self, comm: Communicator | None = None) -> list[Rank]:
        """Round-trip the communicator's rank table from exchange memory
        (reference get_comm_group, accl.hpp readback path): returns what
        the DEVICE holds, not the facade's cached object, so drift between
        the two is observable."""
        comm = comm or self.communicators[0]
        n_words = 2 + Communicator.WORDS_PER_RANK * comm.size
        words = [self.cclo.read(comm.exchmem_addr + 4 * i)
                 for i in range(n_words)]
        return Communicator.from_exchmem_words(
            words, exchmem_addr=comm.exchmem_addr).ranks


class SequenceRecorder:
    """Records a batch of collective/copy/combine descriptors host-side
    (the thin-client half of the device-resident call-sequence contract):
    each method queues the SAME descriptor its eager ACCL counterpart
    would dispatch, and `run()` hands the whole batch to the device for
    one fused compile+dispatch (TPUDevice.start_sequence). Collective
    methods return the recorder, so chains compose fluently; send/recv
    and barrier cannot ride a sequence (host-paired / payload-free)."""

    def __init__(self, accl: ACCL, comm: Communicator | None = None,
                 lint: str = "error", persistent=()):
        self._accl = accl
        self._comm = comm
        self._lint = lint
        # declared device-resident state buffers (ACCL101 waiver) — kept
        # as addresses: that's the layer the hazard pass renames from
        self._persistent = frozenset(b.address for b in persistent)
        self.calls: list[CallOptions] = []
        self._reads: list[BaseBuffer] = []  # per-step operand buffers
        self._writes: list[BaseBuffer] = []  # per-step result buffers
        self._ran = False

    def __len__(self) -> int:
        return len(self.calls)

    def __enter__(self) -> "SequenceRecorder":
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and self.calls and not self._ran:
            self.run()
        return False

    def _record(self, opts: CallOptions, reads, writes) -> "SequenceRecorder":
        if self._ran:
            raise SequenceReuseError(
                "sequence already executed; record a new one")
        self.calls.append(opts)
        self._reads.append(list(reads))
        self._writes.append(list(writes))
        return self

    def _prep(self, scenario, op0, op1, res, count, **kw):
        return self._accl._prepare(scenario, op0, op1, res, count,
                                   comm=self._comm, **kw)

    # -- recorded forms of the facade's data-plane calls -------------------

    def copy(self, srcbuf, dstbuf, count, *, op0_stream=None,
             res_stream=None):
        """Recorded copy; `res_stream` routes the result through a
        registered consumer before it lands in dstbuf (the recorded
        form of copy_to_stream — the seam the fused train step splices
        its forward+backward compute through)."""
        opts = self._prep(Operation.copy, srcbuf, None, dstbuf, count)
        self._accl._stream_opts(opts, op0_stream, res_stream)
        return self._record(opts, [srcbuf], [dstbuf])

    def combine(self, count, function, op0, op1, res):
        opts = self._prep(Operation.combine, op0, op1, res, count,
                          function=int(function))
        return self._record(opts, [op0, op1], [res])

    def bcast(self, buf, count, root, *, compress_dtype=None,
              op0_stream=None, res_stream=None):
        opts = self._prep(Operation.bcast, buf, None, buf, count,
                          root_src_dst=root, compress_dtype=compress_dtype)
        self._accl._stream_opts(opts, op0_stream, res_stream)
        return self._record(opts, [buf], [buf])

    def scatter(self, sendbuf, recvbuf, count, root, *, compress_dtype=None,
                op0_stream=None, res_stream=None):
        opts = self._prep(Operation.scatter, sendbuf, None, recvbuf, count,
                          root_src_dst=root, compress_dtype=compress_dtype)
        self._accl._stream_opts(opts, op0_stream, res_stream)
        return self._record(opts, [sendbuf], [recvbuf])

    def gather(self, sendbuf, recvbuf, count, root, *, compress_dtype=None,
               op0_stream=None, res_stream=None):
        opts = self._prep(Operation.gather, sendbuf, None, recvbuf, count,
                          root_src_dst=root, compress_dtype=compress_dtype)
        self._accl._stream_opts(opts, op0_stream, res_stream)
        return self._record(opts, [sendbuf], [recvbuf])

    def allgather(self, sendbuf, recvbuf, count, *, compress_dtype=None,
                  op0_stream=None, res_stream=None):
        opts = self._prep(Operation.allgather, sendbuf, None, recvbuf, count,
                          compress_dtype=compress_dtype)
        self._accl._stream_opts(opts, op0_stream, res_stream)
        return self._record(opts, [sendbuf], [recvbuf])

    def reduce(self, sendbuf, recvbuf, count, root, function, *,
               compress_dtype=None, op0_stream=None, res_stream=None):
        opts = self._prep(Operation.reduce, sendbuf, None, recvbuf, count,
                          root_src_dst=root, function=int(function),
                          compress_dtype=compress_dtype)
        self._accl._stream_opts(opts, op0_stream, res_stream)
        return self._record(opts, [sendbuf], [recvbuf])

    def allreduce(self, sendbuf, recvbuf, count, function, *,
                  compress_dtype=None, op0_stream=None, res_stream=None,
                  mode="all", live_ranks=None):
        opts = self._prep(Operation.allreduce, sendbuf, None, recvbuf, count,
                          function=int(function),
                          compress_dtype=compress_dtype)
        opts.live_ranks = self._accl._live_subset(
            mode, live_ranks, int(function), compress_dtype, self._comm)
        self._accl._stream_opts(opts, op0_stream, res_stream)
        return self._record(opts, [sendbuf], [recvbuf])

    def reduce_scatter(self, sendbuf, recvbuf, count, function, *,
                       compress_dtype=None, op0_stream=None,
                       res_stream=None):
        opts = self._prep(Operation.reduce_scatter, sendbuf, None, recvbuf,
                          count, function=int(function),
                          compress_dtype=compress_dtype)
        self._accl._stream_opts(opts, op0_stream, res_stream)
        return self._record(opts, [sendbuf], [recvbuf])

    def alltoall(self, sendbuf, recvbuf, count, *, compress_dtype=None,
                 op0_stream=None, res_stream=None):
        opts = self._prep(Operation.alltoall, sendbuf, None, recvbuf, count,
                          compress_dtype=compress_dtype)
        self._accl._stream_opts(opts, op0_stream, res_stream)
        return self._record(opts, [sendbuf], [recvbuf])

    def alltoallv(self, sendbuf, recvbuf, count, send_counts, *,
                  compress_dtype=None, op0_stream=None, res_stream=None):
        opts = self._accl._prepare_alltoallv(
            sendbuf, recvbuf, count, send_counts,
            compress_dtype=compress_dtype, comm=self._comm)
        self._accl._stream_opts(opts, op0_stream, res_stream)
        return self._record(opts, [sendbuf], [recvbuf])

    # -- execution ---------------------------------------------------------

    def _sync_sets(self):
        """(sync_in, sync_out): external inputs = buffers read before any
        in-sequence write (intermediates chain on-device); outputs =
        every written buffer, first-write order — the same sets eager
        back-to-back calls would sync."""
        written: set[int] = set()
        sync_in: list[BaseBuffer] = []
        sync_out: list[BaseBuffer] = []
        for reads, writes in zip(self._reads, self._writes):
            for b in reads:
                if id(b) not in written and all(b is not x for x in sync_in):
                    sync_in.append(b)
            for b in writes:
                written.add(id(b))
                if all(b is not x for x in sync_out):
                    sync_out.append(b)
        return sync_in, sync_out

    def compile(self) -> "SequenceProgram":
        """Freeze the recorded batch into a re-dispatchable
        SequenceProgram: the descriptor resolution, lint gate, dataflow
        analysis and compile all happen ONCE here, and every
        `program.run()` afterwards is stage-in + one dispatch +
        completion — none of the per-call re-resolution a fresh
        recorder pays. The recorder is consumed (same one-shot contract
        as run()). This is the steady-state form of the device-resident
        call sequence: one compiled program per recorded step shape,
        dispatched per iteration (the MoE layer step rides it)."""
        if self._ran:
            raise SequenceReuseError(
                "sequence already executed; record a new one")
        if not self.calls:
            raise ValueError("empty sequence: record at least one call")
        if not hasattr(self._accl.cclo, "prepare_sequence"):
            raise NotImplementedError(
                f"{type(self._accl.cclo).__name__} does not support "
                "prepared call sequences")
        self._ran = True
        return SequenceProgram(self._accl, self)

    def run(self, *, from_device=False, to_device=False, run_async=False):
        """Dispatch the recorded batch as ONE compiled device program.
        from_device/to_device skip the host<->HBM syncs around the WHOLE
        sequence (per-call syncs between stages never happen: that seam
        is what the fusion removes); run_async returns the request, to be
        completed with accl.wait()."""
        if self._ran:
            raise SequenceReuseError(
                "sequence already executed; record a new one")
        if not self.calls:
            raise ValueError("empty sequence: record at least one call")
        self._ran = True
        accl = self._accl
        sync_in, sync_out = self._sync_sets()
        with get_tracer().span("sequence", cat="sequence",
                               track="facade") as sp:
            accl._stage_in(sync_in, from_device)
            Log.debug("sequence of %d: %s", len(self.calls),
                      "+".join(o.scenario.name for o in self.calls))
            req = accl.cclo.start_sequence(self.calls, lint=self._lint,
                                           persistent=self._persistent)
            ret = accl._complete(req, sync_out, to_device, run_async)
            if get_tracer().active:
                sp.set(n_steps=len(self.calls),
                       ops="+".join(o.scenario.name for o in self.calls))
                if run_async:
                    sp.set(dispatch_only=True)
                sig = getattr(req, "signature", None)
                if sig is not None:
                    sp.set(signature=sig)
                pred = getattr(req, "predicted_s", None)
                if pred is not None:
                    sp.set(predicted_s=pred)
            return ret


class SequenceProgram:
    """A recorded call sequence frozen into its steady-state form:
    resolve + lint + compile happened once (at SequenceRecorder.compile),
    and every `run()` is stage-in + ONE device dispatch + completion —
    the per-iteration cost profile of a device-resident descriptor
    batch (no re-recording, no re-planning, no signature hashing).

    The program binds the buffers the recorder referenced: each run
    reads their CURRENT device contents and places results back, so the
    caller's loop is `write inputs -> program.run() -> read outputs`.
    The plans were resolved under the tuning registers live at compile
    time — retune, then re-record, to pick up new registers."""

    def __init__(self, accl: ACCL, recorder: SequenceRecorder):
        self._accl = accl
        self._sync_in, self._sync_out = recorder._sync_sets()
        self.n_steps = len(recorder.calls)
        self._ops = "+".join(o.scenario.name for o in recorder.calls)
        self._prepared = accl.cclo.prepare_sequence(
            recorder.calls, lint=recorder._lint,
            persistent=recorder._persistent)

    @property
    def plans(self):
        """The per-step Plans the batch resolved to (frozen)."""
        return self._prepared.plans

    @property
    def signature(self):
        """Composite-signature digest of the recorded batch: the
        compile/lint cache key and the interference-verdict cache key
        half — available whether or not a tracer was live at compile."""
        return self._prepared.sig

    @property
    def footprint(self):
        """The program's interference summary (ProgramFootprint), the
        input to ACCL.certify_concurrent."""
        return getattr(self._prepared, "footprint", None)

    @property
    def certificate(self):
        """Certificate id of the pairwise-clean concurrent set this
        program was last admitted into (None until certify_concurrent
        passes it)."""
        return getattr(self._prepared, "cert", None)

    def run(self, *, from_device=False, to_device=False, run_async=False):
        """Dispatch the compiled batch over the bound buffers' current
        contents; same sync semantics as SequenceRecorder.run()."""
        accl = self._accl
        with get_tracer().span("sequence", cat="sequence",
                               track="facade") as sp:
            accl._stage_in(self._sync_in, from_device)
            req = accl.cclo.dispatch_sequence(self._prepared)
            ret = accl._complete(req, self._sync_out, to_device, run_async)
            if get_tracer().active:
                sp.set(n_steps=self.n_steps, ops=self._ops, prepared=True)
                if run_async:
                    sp.set(dispatch_only=True)
                sig = getattr(req, "signature", None)
                if sig is not None:
                    sp.set(signature=sig)
                cert = getattr(req, "interference_cert", None)
                if cert is not None:
                    sp.set(interference_cert=cert)
            return ret
