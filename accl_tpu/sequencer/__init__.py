"""The collective sequencer: algorithm selection + schedule compilation.

This package is the TPU re-expression of the CCLO firmware
(reference: kernels/cclo/fw/sw_apps/ccl_offload_control/src/ccl_offload_control.c).
Where the reference runs a microcoded control loop on a soft CPU emitting
move instructions at runtime, we split the same logic into:

  - plan.py       algorithm selection (eager/rendezvous protocol switch,
                  ring vs flat-tree vs binary-tree, segmentation math,
                  tuning registers) — pure logic shared with the native
                  C++ runtime;
  - schedules.py  SPMD implementations of each algorithm as traced JAX
                  programs over a mesh axis (the "move programs" of the
                  TPU path — one compiled program executes the entire
                  collective on-device, preserving ACCL's host-only-
                  supervises property);
  - lowering.py   descriptor -> compiled program, with a schedule cache
                  keyed by the descriptor's static signature;
  - sequence.py   recorded descriptor BATCHES -> one fused program (the
                  device-resident call-sequence layer: one dispatch for a
                  whole collective chain, cached under a composite
                  signature);
  - synthesis.py  SCCL-style schedule search over the hop-DAG IR: the
                  committed synthesized/ library of certified winner
                  DAGs, selected by plan.py behind measured crossover
                  registers and lowered by lowering.py like any other
                  algorithm (docs/synthesis.md).
"""

from .plan import (  # noqa: F401
    Algorithm,
    Plan,
    Protocol,
    select_algorithm,
    select_wire,
)
from .sequence import SequencePlan  # noqa: F401
