"""Hierarchical (two-tier) collectives: ICI inside a slice, DCN across.

The reference's three POEs are flat — every rank one hop away on
Ethernet. TPU pods are not: intra-slice ICI is an order of magnitude
faster than the inter-slice data-center network, so cross-tier
collectives must be composed so the slow tier carries 1/P_inner of the
traffic. The compositions here are the standard bandwidth-optimal
decompositions, built from the same ring schedule bodies the flat path
uses (sequencer/schedules.py):

  allreduce      = reduce_scatter(inner) -> allreduce(outer on 1/Pi
                   shard) -> allgather(inner)
  reduce_scatter = reduce_scatter(inner) -> reduce_scatter(outer)
  allgather      = allgather(outer) -> allgather(inner)
  bcast          = bcast(inner on root host) -> shard bcast(outer)
                   -> allgather(inner)
  scatter        = regroup -> scatter(inner on root host) -> scatter(outer)
  gather         = gather(outer per row) -> gather(inner) -> de-normalize
  reduce         = reduce_scatter(inner) -> reduce(outer) -> gather(inner)
  barrier        = barrier(inner) -> barrier(outer)

Each runs inside one shard_map over BOTH axes — a single compiled
program, the host-only-dispatches property preserved across tiers. On a
real multi-slice mesh the outer axis maps to DCN; on the CPU test mesh
both axes are virtual, which exercises the identical program structure
(the driver's dryrun posture).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..constants import ReduceFunction
from . import schedules


def _pad_to(x, m):
    rem = (-x.shape[-1]) % m
    return jnp.pad(x, (0, rem)) if rem else x


# ---------------------------------------------------------------------------
# The global-rank convention, in ONE place
# ---------------------------------------------------------------------------


class RankMap:
    """THE two-tier global-rank mapping helper.

    Two conventions coexist in a two-tier world and every composition
    must declare which one it speaks:

      outer-major  g = outer_pos * inner_world + inner_pos
                   (the DCN backend's process-major numbering: each
                   host's ranks are contiguous; alltoall/scatter/gather
                   and the striped allreduce use it)
      inner-major  g = inner_pos * outer_world + outer_pos
                   (the raw allgather composition's chunk order: an
                   inner allgather of outer allgathers interleaves
                   hosts)

    Everything that converts between (inner_pos, outer_pos) and global
    ranks — device root resolution, chunk reordering between the
    conventions, per-tier ring permutes — goes through this class, so
    the convention can never be re-derived inconsistently at two sites
    (the pre-PR8 state: allgather was inner-major at hierarchical.py:91
    while alltoall/scatter/gather were outer-major).

    `inner_pos`/`outer_pos`/`global_rank` accept python ints AND traced
    scalars (the arithmetic is // and %, which jax traces)."""

    __slots__ = ("inner_world", "outer_world", "order")

    def __init__(self, inner_world: int, outer_world: int,
                 order: str = "outer_major"):
        if order not in ("outer_major", "inner_major"):
            raise ValueError(f"unknown rank order {order!r}")
        self.inner_world = int(inner_world)
        self.outer_world = int(outer_world)
        self.order = order

    @property
    def world(self) -> int:
        return self.inner_world * self.outer_world

    def global_rank(self, inner_pos, outer_pos):
        if self.order == "outer_major":
            return outer_pos * self.inner_world + inner_pos
        return inner_pos * self.outer_world + outer_pos

    def inner_pos(self, g):
        if self.order == "outer_major":
            return g % self.inner_world
        return g // self.outer_world

    def outer_pos(self, g):
        if self.order == "outer_major":
            return g // self.inner_world
        return g % self.outer_world

    def inner_perm(self, distance: int = 1) -> list[tuple[int, int]]:
        """GLOBAL ppermute pairs for one inner-ring hop: every host's
        inner ring advances by `distance` in lockstep (all pairs stay
        within their host — on hardware these are the ICI moves)."""
        L = self.inner_world
        return [
            (self.global_rank(i, o), self.global_rank((i + distance) % L, o))
            for o in range(self.outer_world)
            for i in range(L)
        ]

    def outer_perm(self, distance: int = 1) -> list[tuple[int, int]]:
        """GLOBAL ppermute pairs for one outer-ring hop: every inner
        row's outer ring advances in lockstep (all pairs cross hosts —
        the DCN moves)."""
        P = self.outer_world
        return [
            (self.global_rank(i, o), self.global_rank(i, (o + distance) % P))
            for o in range(P)
            for i in range(self.inner_world)
        ]

    def reorder_chunks(self, x, chunk: int, frm: str, to: str):
        """Relabel a (world * chunk,) buffer whose chunk g holds data
        for/from global rank g under convention `frm` into convention
        `to` — a local transpose, no data movement across ranks."""
        if frm == to:
            return x
        L, P = self.inner_world, self.outer_world
        if frm == "inner_major":  # rows (i, o) -> (o, i)
            return x.reshape(L, P, chunk).transpose(1, 0, 2).reshape(-1)
        return x.reshape(P, L, chunk).transpose(1, 0, 2).reshape(-1)


class TierWire:
    """Per-tier datapath configuration: ONE wire per tier, so
    `select_wire` can arbitrate each link separately — int8 codes riding
    the slow DCN tier while fp32 stays exact on ICI (the plan fields
    inner_wire_dtype / outer_wire_dtype resolve to these two Wires)."""

    __slots__ = ("inner", "outer")

    def __init__(self, inner: schedules.Wire | None = None,
                 outer: schedules.Wire | None = None):
        self.inner = inner if inner is not None else schedules.Wire(None)
        self.outer = outer if outer is not None else schedules.Wire(None)


def hierarchical_allreduce_striped_schedule(
    x, *, func: ReduceFunction, axis, rankmap: RankMap,
    wire: TierWire | None = None, stripes: int = 1,
):
    """Striped, software-pipelined two-tier allreduce over GLOBAL ranks:
    RS(inner) -> AR(outer on the 1/L shard) -> AG(inner), payload split
    into `stripes` independent stripes.

    Unlike the per-axis composition above, every hop here is a permute
    over the COMBINED axis with globally-numbered pairs from the
    RankMap (inner hops stay within a host, outer hops cross hosts), so
    the same body runs on a real (dcn, ici) mesh, on the DCN device's
    tuple axis, and on a flat single-axis mesh with a VIRTUAL topology
    (the 8-dev CPU mesh as 4 pods x 2) — and the static analyzers read
    it through the ordinary single-axis trace seam with no special
    casing.

    Striping is the pipelining lever: the stripes' phase chains are
    data-independent, so while stripe i's shard crosses the slow outer
    tier, stripe i+1 runs its inner reduce-scatter on the fast tier —
    XLA overlaps the independent permutes exactly like the reference's
    segmenter overlaps rx slots. The stripe count is chosen by the cost
    model (timing.best_stripes), not hardcoded: plan.stripes rides the
    frozen Plan, so S is part of the XLA cache key.

    Built from the SAME ring bodies the flat path lowers
    (schedules.reduce_scatter/allreduce/allgather_ring_schedule via the
    `ring=` embedding), so fused sequences stay bitwise-identical to
    eager dispatch — nothing is re-modeled."""
    if wire is None:
        wire = TierWire()
    L, P = rankmap.inner_world, rankmap.outer_world
    n = x.shape[-1]
    me = lax.axis_index(axis)
    inner_ring = (rankmap.inner_pos(me), rankmap.inner_perm())
    outer_ring = (rankmap.outer_pos(me), rankmap.outer_perm())

    S = max(int(stripes), 1)
    per = -(-n // S)  # ceil: stripe width before the L-padding
    outs = []
    for s in range(S):
        seg = x[s * per: min((s + 1) * per, n)]
        if seg.shape[-1] == 0:
            continue
        padded = _pad_to(seg, L)
        # fast tier: reduce-scatter so each inner position holds the
        # host-partial of its 1/L chunk
        shard = schedules.reduce_scatter_ring_schedule(
            padded, func=func, axis=axis, world=L, wire=wire.inner,
            ring=inner_ring)
        # slow tier: allreduce the 1/L shard across hosts — the only
        # bytes that ever cross DCN
        shard = schedules.allreduce_ring_schedule(
            shard, func=func, axis=axis, world=P, wire=wire.outer,
            seg_count=shard.shape[-1], ring=outer_ring)
        # fast tier: rebuild the full stripe from the L shards
        full = schedules.allgather_ring_schedule(
            shard, axis=axis, world=L, wire=wire.inner, ring=inner_ring)
        outs.append(full[: seg.shape[-1]])
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs)


def hierarchical_allreduce_schedule(
    x, *, func: ReduceFunction, inner_axis: str, outer_axis: str,
    inner_world: int, outer_world: int, wire,
):
    """RS(inner) -> AR(outer) -> AG(inner): the outer (slow) tier moves
    1/inner_world of the payload per device."""
    n = x.shape[-1]
    padded = _pad_to(x, inner_world)
    # reduce-scatter over the fast tier: each inner rank holds the partial
    # sum of its 1/Pi chunk across the inner group
    shard = schedules.reduce_scatter_ring_schedule(
        padded, func=func, axis=inner_axis, world=inner_world, wire=wire
    )
    # allreduce the shard across the slow tier
    shard = schedules.allreduce_ring_schedule(
        shard, func=func, axis=outer_axis, world=outer_world, wire=wire,
        seg_count=shard.shape[-1],
    )
    # allgather over the fast tier to rebuild the full buffer
    full = schedules.allgather_ring_schedule(
        shard, axis=inner_axis, world=inner_world, wire=wire
    )
    return full[:n]


def hierarchical_reduce_scatter_schedule(
    x, *, func, inner_axis, outer_axis, inner_world, outer_world, wire,
):
    """Input world*count per rank (world = inner*outer); output: the
    rank's own chunk under the module's inner-major convention
    (g = inner_pos * outer_world + outer_pos)."""
    world = inner_world * outer_world
    count = x.shape[-1] // world
    # group the global chunks by outer rank: first reduce-scatter across
    # the inner axis over blocks of outer_world*count, then across outer
    inner_rs = schedules.reduce_scatter_ring_schedule(
        x, func=func, axis=inner_axis, world=inner_world, wire=wire
    )  # (outer_world * count,) per device: partial chunks for my inner pos
    out = schedules.reduce_scatter_ring_schedule(
        inner_rs, func=func, axis=outer_axis, world=outer_world, wire=wire
    )
    return out


def hierarchical_allgather_schedule(
    x, *, inner_axis, outer_axis, inner_world, outer_world, wire,
):
    """AG(outer) then AG(inner): output ordered (inner, outer, count) —
    i.e. global rank id = inner_pos * outer_world + outer_pos."""
    outer = schedules.allgather_ring_schedule(
        x, axis=outer_axis, world=outer_world, wire=wire
    )
    return schedules.allgather_ring_schedule(
        outer, axis=inner_axis, world=inner_world, wire=wire
    )


def hierarchical_alltoall_schedule(
    x, *, inner_axis, outer_axis, inner_world, outer_world, wire,
):
    """Two-tier alltoall under OUTER-MAJOR global ranks (g = outer_pos *
    inner_world + inner_pos, the DCN backend's process-major numbering):
    stage 1 redistributes over the fast tier so each device holds every
    local source's chunks for its own inner position; stage 2 crosses the
    slow tier once per remote host with an aggregated inner_world*c block
    instead of inner_world separate messages. Bytes moved are inherent to
    alltoall; the win is (P-1) aggregated DCN transfers instead of
    (P-1)*L small ones. Input chunks are destination-ordered outer-major;
    output chunks are source-ordered outer-major (the flat alltoall
    contract)."""
    L, P = inner_world, outer_world
    c = x.shape[-1] // (L * P)
    # group by inner destination: block l' carries my chunks for every
    # host's device l' -> inner alltoall lands them on local device l'
    s1 = x.reshape(P, L, c).transpose(1, 0, 2).reshape(-1)
    r1 = schedules.alltoall_schedule(s1, axis=inner_axis, world=L, wire=wire)
    # r1 = (l_src, p_dst, c); regroup by destination host and cross DCN
    s2 = r1.reshape(L, P, c).transpose(1, 0, 2).reshape(-1)
    r2 = schedules.alltoall_schedule(s2, axis=outer_axis, world=P, wire=wire)
    # r2 = (p_src, l_src, c) == source-ordered outer-major
    return r2


def hierarchical_bcast_schedule(
    x, *, root_inner: int, root_outer: int, inner_axis, outer_axis,
    inner_world, outer_world, wire,
):
    """Scatter-bcast-allgather: the root's host fans the payload out on
    ICI, each inner position carries ONE 1/L shard across the slow tier,
    and an inner allgather rebuilds the buffer — so the payload crosses
    DCN once in aggregate ((P-1) * n/L per inner row) instead of once per
    inner row (the naive outer-bcast-everywhere costs L * that)."""
    n = x.shape[-1]
    padded = _pad_to(x, inner_world)
    c = padded.shape[-1] // inner_world
    # root's host distributes internally (other hosts relay garbage here;
    # their shards are replaced by the outer hop next)
    y = schedules.bcast_flat_schedule(
        padded, root=root_inner, axis=inner_axis, world=inner_world, wire=wire
    )
    me = lax.axis_index(inner_axis)
    shard = lax.dynamic_slice_in_dim(y, me * c, c, axis=-1)
    shard = schedules.bcast_flat_schedule(
        shard, root=root_outer, axis=outer_axis, world=outer_world, wire=wire
    )
    full = schedules.allgather_ring_schedule(
        shard, axis=inner_axis, world=inner_world, wire=wire
    )
    return full[:n]


def hierarchical_scatter_schedule(
    x, *, root_inner: int, root_outer: int, inner_axis, outer_axis,
    inner_world, outer_world, wire,
):
    """Input: world*c per rank (real on the root device), PROCESS-MAJOR
    chunks (chunk for global rank g = p*L + l at offset g*c). The root
    regroups locally to (l, p, c), inner-scatters so its host's device l
    holds every host's chunk for inner position l (ICI), then each inner
    row outer-scatters its (P, c) block — every DCN byte is payload some
    host needs ((P-1)*c per row, optimal)."""
    L, P = inner_world, outer_world
    c = x.shape[-1] // (L * P)
    xt = x.reshape(P, L, c).transpose(1, 0, 2).reshape(-1)
    blk = schedules.scatter_schedule(
        xt, root=root_inner, axis=inner_axis, world=L, wire=wire
    )  # (P*c): chunks for MY inner position, one per host
    return schedules.scatter_schedule(
        blk, root=root_outer, axis=outer_axis, world=P, wire=wire
    )


def hierarchical_gather_schedule(
    x, *, root_inner: int, root_outer: int, inner_axis, outer_axis,
    inner_world, outer_world, wire,
):
    """Mirror of hierarchical_scatter: each inner row ring-gathers across
    the slow tier to the root host ((P-1)*c DCN per row), the root host
    gathers its rows on ICI, and the root device de-normalizes to
    process-major chunk order. Only the root's output is defined (the
    flat gather contract)."""
    L, P = inner_world, outer_world
    c = x.shape[-1]
    og = schedules.gather_ring_schedule(
        x, root=root_outer, axis=outer_axis, world=P, wire=wire
    )  # (P*c) valid on the root host's row
    ig = schedules.gather_ring_schedule(
        og, root=root_inner, axis=inner_axis, world=L, wire=wire
    )  # (L*P*c) on the root device, layout (l, p, c)
    return ig.reshape(L, P, c).transpose(1, 0, 2).reshape(-1)


def hierarchical_reduce_schedule(
    x, *, func, root_inner: int, root_outer: int, inner_axis, outer_axis,
    inner_world, outer_world, wire,
):
    """RS(inner) -> reduce(outer) -> gather(inner to root): the slow tier
    carries one 1/L shard per inner row (n/L per device, n aggregate)
    instead of whole payloads. Only the root's output is defined."""
    n = x.shape[-1]
    padded = _pad_to(x, inner_world)
    shard = schedules.reduce_scatter_ring_schedule(
        padded, func=func, axis=inner_axis, world=inner_world, wire=wire
    )
    shard = schedules.reduce_ring_schedule(
        shard, root=root_outer, func=func, axis=outer_axis,
        world=outer_world, wire=wire,
    )
    full = schedules.gather_ring_schedule(
        shard, root=root_inner, axis=inner_axis, world=inner_world, wire=wire
    )  # chunks ordered by inner position == original contiguous layout
    return full[:n]


def hierarchical_barrier_schedule(
    token, *, inner_axis, outer_axis, inner_world, outer_world, wire,
):
    """Inner barrier then outer barrier: a device passes the outer tier
    only after every device on its host arrived, so outer completion on
    any row implies global arrival — and the slow tier carries P tokens
    per row instead of a P*L-rank flat fan-in."""
    t = schedules.barrier_schedule(
        token, axis=inner_axis, world=inner_world, wire=wire
    )
    return schedules.barrier_schedule(
        t, axis=outer_axis, world=outer_world, wire=wire
    )
