"""Hierarchical (two-tier) collectives: ICI inside a slice, DCN across.

The reference's three POEs are flat — every rank one hop away on
Ethernet. TPU pods are not: intra-slice ICI is an order of magnitude
faster than the inter-slice data-center network, so cross-tier
collectives must be composed so the slow tier carries 1/P_inner of the
traffic. The compositions here are the standard bandwidth-optimal
decompositions, built from the same ring schedule bodies the flat path
uses (sequencer/schedules.py):

  allreduce      = reduce_scatter(inner) -> allreduce(outer on 1/Pi
                   shard) -> allgather(inner)
  reduce_scatter = reduce_scatter(inner) -> reduce_scatter(outer)
  allgather      = allgather(outer) -> allgather(inner)
  bcast          = bcast(outer from root's column) -> bcast(inner)

Each runs inside one shard_map over BOTH axes — a single compiled
program, the host-only-dispatches property preserved across tiers. On a
real multi-slice mesh the outer axis maps to DCN; on the CPU test mesh
both axes are virtual, which exercises the identical program structure
(the driver's dryrun posture).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..constants import ReduceFunction
from . import schedules


def _pad_to(x, m):
    rem = (-x.shape[-1]) % m
    return jnp.pad(x, (0, rem)) if rem else x


def hierarchical_allreduce_schedule(
    x, *, func: ReduceFunction, inner_axis: str, outer_axis: str,
    inner_world: int, outer_world: int, wire,
):
    """RS(inner) -> AR(outer) -> AG(inner): the outer (slow) tier moves
    1/inner_world of the payload per device."""
    n = x.shape[-1]
    padded = _pad_to(x, inner_world)
    # reduce-scatter over the fast tier: each inner rank holds the partial
    # sum of its 1/Pi chunk across the inner group
    shard = schedules.reduce_scatter_ring_schedule(
        padded, func=func, axis=inner_axis, world=inner_world, wire=wire
    )
    # allreduce the shard across the slow tier
    shard = schedules.allreduce_ring_schedule(
        shard, func=func, axis=outer_axis, world=outer_world, wire=wire,
        seg_count=shard.shape[-1],
    )
    # allgather over the fast tier to rebuild the full buffer
    full = schedules.allgather_ring_schedule(
        shard, axis=inner_axis, world=inner_world, wire=wire
    )
    return full[:n]


def hierarchical_reduce_scatter_schedule(
    x, *, func, inner_axis, outer_axis, inner_world, outer_world, wire,
):
    """Input world*count per rank (world = inner*outer); output: the
    rank's own chunk under the module's inner-major convention
    (g = inner_pos * outer_world + outer_pos)."""
    world = inner_world * outer_world
    count = x.shape[-1] // world
    # group the global chunks by outer rank: first reduce-scatter across
    # the inner axis over blocks of outer_world*count, then across outer
    inner_rs = schedules.reduce_scatter_ring_schedule(
        x, func=func, axis=inner_axis, world=inner_world, wire=wire
    )  # (outer_world * count,) per device: partial chunks for my inner pos
    out = schedules.reduce_scatter_ring_schedule(
        inner_rs, func=func, axis=outer_axis, world=outer_world, wire=wire
    )
    return out


def hierarchical_allgather_schedule(
    x, *, inner_axis, outer_axis, inner_world, outer_world, wire,
):
    """AG(outer) then AG(inner): output ordered (inner, outer, count) —
    i.e. global rank id = inner_pos * outer_world + outer_pos."""
    outer = schedules.allgather_ring_schedule(
        x, axis=outer_axis, world=outer_world, wire=wire
    )
    return schedules.allgather_ring_schedule(
        outer, axis=inner_axis, world=inner_world, wire=wire
    )


def hierarchical_alltoall_schedule(
    x, *, inner_axis, outer_axis, inner_world, outer_world, wire,
):
    """Two-tier alltoall under OUTER-MAJOR global ranks (g = outer_pos *
    inner_world + inner_pos, the DCN backend's process-major numbering):
    stage 1 redistributes over the fast tier so each device holds every
    local source's chunks for its own inner position; stage 2 crosses the
    slow tier once per remote host with an aggregated inner_world*c block
    instead of inner_world separate messages. Bytes moved are inherent to
    alltoall; the win is (P-1) aggregated DCN transfers instead of
    (P-1)*L small ones. Input chunks are destination-ordered outer-major;
    output chunks are source-ordered outer-major (the flat alltoall
    contract)."""
    L, P = inner_world, outer_world
    c = x.shape[-1] // (L * P)
    # group by inner destination: block l' carries my chunks for every
    # host's device l' -> inner alltoall lands them on local device l'
    s1 = x.reshape(P, L, c).transpose(1, 0, 2).reshape(-1)
    r1 = schedules.alltoall_schedule(s1, axis=inner_axis, world=L, wire=wire)
    # r1 = (l_src, p_dst, c); regroup by destination host and cross DCN
    s2 = r1.reshape(L, P, c).transpose(1, 0, 2).reshape(-1)
    r2 = schedules.alltoall_schedule(s2, axis=outer_axis, world=P, wire=wire)
    # r2 = (p_src, l_src, c) == source-ordered outer-major
    return r2


def hierarchical_bcast_schedule(
    x, *, root_inner: int, root_outer: int, inner_axis, outer_axis,
    inner_world, outer_world, wire,
):
    """Root's slice broadcasts across the slow tier once, then every slice
    fans out internally on ICI."""
    # outer hop happens only usefully on the root's inner row; other rows
    # relay garbage among themselves in the same SPMD program, and the
    # inner bcast from root_inner then overwrites every row with real data.
    y = schedules.bcast_flat_schedule(
        x, root=root_outer, axis=outer_axis, world=outer_world, wire=wire
    )
    return schedules.bcast_flat_schedule(
        y, root=root_inner, axis=inner_axis, world=inner_world, wire=wire
    )
