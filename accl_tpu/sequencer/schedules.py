"""SPMD collective schedules: the move programs of the TPU path.

Each function here is the body of a shard_map over one mesh axis and
implements one algorithm family from plan.Algorithm, composed from the
framework's own primitives (neighbor permutes over ICI + reduce/compression
lanes) rather than XLA's prebuilt collectives — the whole schedule compiles
into a single device program, preserving the reference's host-only-issues-
the-call inversion (SURVEY.md §1).

Conventions:
  - every rank's operand is its full local buffer (ACCL buffer semantics,
    not a shard of a global array);
  - `perm`-based sends are lax.ppermute: a rank not addressed by any pair
    receives zeros, which schedules mask with `where`;
  - ring neighbor order follows the communicator (next = rank+1, as in
    ccl_offload_control.c:1311-1312);
  - wire compression (ETH_COMPRESSED) casts payloads to the arithconfig's
    compressed dtype around every cross-rank hop, mirroring the
    compression-lane plumbing of the reference data plane.

Algorithm provenance (reference ccl_offload_control.c):
  ring gather .c:1206-1293, ring allgather .c:1402-1499, ring reduce relay
  with fused recv-reduce-send .c:1730-1743 + .c:755-789, ring
  reduce-scatter .c:1782-1850, segmented ring allreduce .c:1888-2071,
  binary-tree bcast .c:814-867, flat bcast .c:868-919, flat/binomial
  gather/reduce trees .c:1142-1204/.c:1531-1727, alltoall .c:2140-2211,
  barrier .c:2078-2120.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..constants import QUANT_BLOCK_ELEMS, ReduceFunction
from ..ops.compression import (
    compress,
    decompress,
    dequant_combine,
    dequant_combine_requant,
    dequantize_blockwise,
    is_quantized,
    pack_wire,
    quantize_blockwise,
    unpack_wire,
)
from ..ops.reduce_ops import combine_op, reduce_lane


def _ring_perm(world: int, distance: int = 1):
    return [(i, (i + distance) % world) for i in range(world)]


def _ring_ctx(axis, world, ring):
    """Resolve the ring embedding a ring schedule runs on.

    By default a ring schedule IS the axis: position = axis_index, hops
    = the distance-1 rotation over the axis extent. `ring=(pos, perm)`
    embeds the same schedule onto a SUB-ring of a wider axis (the
    two-tier compositions in hierarchical.py): `pos` is this rank's
    traced position on its ring [0, world) and `perm` the GLOBAL
    ppermute pairs one ring hop expresses (e.g. every host's inner ring
    advancing in lockstep). The chunk arithmetic below depends only on
    (pos, world), so one body serves the flat axis and every tier
    embedding — which is what keeps the hierarchical compositions
    bitwise-identical to the flat families they are built from."""
    if ring is None:
        return lax.axis_index(axis), _ring_perm(world)
    return ring


def _fast_log2(x: int) -> int:
    return x.bit_length() - 1


class Wire:
    """Per-call datapath configuration: the wire transform (compression
    lanes around each cross-rank hop when ETH_COMPRESSED is active) and the
    arithmetic lane reductions run through — the schedule-level analog of
    the AXIS switch steering payloads through the hp_compression and
    reduce_ops plugin lanes.

    Cast lanes (fp16/bf16) wrap each hop as compress -> ppermute ->
    decompress. The blockwise-quantized lanes (int8 + per-block fp32
    scales) instead carry an ENCODED payload — a (codes, scales) pair —
    through `encode`/`hop`/`decode`, so the scale side-channel crosses
    the same ppermute the codes do and the ring families can relay or
    fuse the encoded form without bouncing through fp32 at every hop."""

    def __init__(self, cfg=None, arith_lane=None):
        self.cfg = cfg  # ArithConfig when wire compression is active
        self.arith_lane = arith_lane
        self.quantized = cfg is not None and is_quantized(cfg)

    def send(self, x):
        if self.quantized:
            raise NotImplementedError(
                "quantized wire hops carry (payload, scales): use "
                "encode/hop/decode")
        return x if self.cfg is None else compress(x, self.cfg)

    def recv(self, x, out_dtype):
        if self.quantized:
            raise NotImplementedError(
                "quantized wire hops carry (payload, scales): use "
                "encode/hop/decode")
        return x if self.cfg is None else decompress(x, self.cfg, out_dtype)

    def ppermute(self, x, axis, perm):
        """One cross-rank hop: compress -> permute -> decompress. On the
        quantized wire this is encode -> pack -> permute ONE message ->
        unpack -> decode: the per-block scales travel bitcast to raw
        bytes INSIDE the codes payload (ops.compression.pack_wire), so a
        single-hop exchange pays one message latency like the fp32 wire
        instead of a codes + scales ppermute pair — same wire bytes
        (n + 4*ceil(n/256)), half the messages, which is what lets the
        quantized pairwise families keep their fusion win. Ranks not
        addressed by perm receive an all-zero packed payload, which
        unpacks to zero codes AND zero scales and decodes to exact zeros
        — the same masking contract the cast lanes have. (The ring
        families' RELAYED hops keep the explicit encode/hop/decode pair:
        their scales side-channel stays decoded-form-free across many
        hops, and their fused dequant-reduce-requant kernels consume the
        pair directly.)"""
        if self.quantized:
            n = x.shape[-1]
            q, s = self.encode(x)
            arr = lax.ppermute(pack_wire(q, s), axis, perm)
            return self.decode(unpack_wire(arr, n), n, x.dtype)
        y = lax.ppermute(self.send(x), axis, perm)
        return self.recv(y, x.dtype)

    def combine(self, func, a, b):
        """Elementwise reduction through the configured arith lane."""
        if self.arith_lane is not None:
            return reduce_lane(self.arith_lane, a, b)
        return combine_op(func, a, b)

    # -- quantized-wire datapath (compressor lanes 4/5) --------------------

    def encode(self, x):
        """fp32 payload -> (int8 codes, per-block fp32 scales)."""
        return quantize_blockwise(x)

    def hop(self, enc, axis, perm):
        """Permute an encoded payload: codes and the scale side-channel
        cross the same hop, so bytes-on-wire per hop is exactly
        len(codes) + 4 * n_blocks."""
        q, s = enc
        return lax.ppermute(q, axis, perm), lax.ppermute(s, axis, perm)

    def decode(self, enc, n, out_dtype):
        q, s = enc
        return dequantize_blockwise(q, s, n, out_dtype)

    def combine_decoded(self, func, enc, local):
        """Fused dequantize -> reduce (terminal ring hop): fp32
        accumulation of an encoded arrival against the local operand."""
        q, s = enc
        op = "sum" if func == ReduceFunction.SUM else "max"
        return dequant_combine(q, s, local, op)

    def combine_requant(self, func, enc, local):
        """Fused dequantize -> reduce -> requantize (interior ring step):
        accumulate in fp32, re-encode so only (codes, scales) travel to
        the next hop."""
        q, s = enc
        op = "sum" if func == ReduceFunction.SUM else "max"
        return dequant_combine_requant(q, s, local, op)


# ---------------------------------------------------------------------------
# Primitives (firmware primitives layer, ccl_offload_control.c:531-789)
# ---------------------------------------------------------------------------


def copy_schedule(x, *, axis, world, wire):
    return x


def combine_schedule(x, y, *, func: ReduceFunction, axis, world, wire):
    return wire.combine(func, x, y)


def sendrecv_schedule(x, *, src: int, dst: int, axis, world, wire):
    """Point-to-point: dst's output is src's buffer, everyone else keeps
    their input (send .c:573-649 / recv .c:653-710)."""
    if src == dst:
        return x
    recv = wire.ppermute(x, axis, [(src, dst)])
    me = lax.axis_index(axis)
    return jnp.where(me == dst, recv, x)


def fused_recv_reduce(acc, recv, is_receiver, func, wire):
    """The fused recv-reduce primitive (.c:716-749): combine an incoming
    partial into the local accumulator on receiving ranks only, through the
    configured arith lane."""
    return jnp.where(is_receiver, wire.combine(func, acc, recv), acc)


# ---------------------------------------------------------------------------
# Broadcast family
# ---------------------------------------------------------------------------


def bcast_flat_schedule(x, *, root: int, axis, world, wire):
    """Flat fan-out: root sends the full buffer to each rank with one move
    per destination (eager .c:921-988 / rendezvous flat .c:868-919) — the
    per-destination hops all leave root's egress links, so the sequential
    permutes mirror the physical serialization of the flat tree."""
    me = lax.axis_index(axis)
    out = x
    for j in range(world):
        if j == root:
            continue
        recv = wire.ppermute(x, axis, [(root, j)])
        out = jnp.where(me == j, recv, out)
    return out


def bcast_bin_tree_schedule(x, *, root: int, axis, world, wire):
    """Distance-doubling binary tree (.c:814-867): the sender set doubles
    each round; round distances run d = 2^floor(log2(P-1)) .. 1."""
    me = lax.axis_index(axis)
    l = (me - root) % world  # normalized rank, root at 0
    have = (me == root)
    d = 1 << _fast_log2(world - 1)
    while d > 0:
        perm = []
        receivers = []
        for ln in range(0, world, 2 * d):  # senders: l % 2d == 0 with l+d < P
            if ln + d < world:
                perm.append(((ln + root) % world, (ln + d + root) % world))
                receivers.append(ln + d)
        recv = wire.ppermute(x, axis, perm)
        is_recv = jnp.isin(l, jnp.asarray(receivers))
        x = jnp.where(is_recv & ~have, recv, x)
        have = have | is_recv
        d >>= 1
    return x


# ---------------------------------------------------------------------------
# Scatter / gather family
# ---------------------------------------------------------------------------


def scatter_schedule(x, *, root: int, axis, world, wire):
    """Root holds world*count elements; rank j receives chunk j. Flat
    per-destination sends in round-robin (.c:992-1123)."""
    count = x.shape[-1] // world
    me = lax.axis_index(axis)
    out = lax.dynamic_slice_in_dim(x, root * count, count, axis=-1)
    for j in range(world):
        if j == root:
            continue
        chunk = lax.dynamic_slice_in_dim(x, j * count, count, axis=-1)
        recv = wire.ppermute(chunk, axis, [(root, j)])
        out = jnp.where(me == j, recv, out)
    return out


def gather_ring_schedule(x, *, root: int, axis, world, wire):
    """Eager daisy-chain gather (.c:1206-1293): every rank relays its
    upstream neighbours' chunks around the ring; root collects P-1 chunks
    in arrival order (origin of the step-s arrival is rank root-1-s)."""
    count = x.shape[-1]
    me = lax.axis_index(axis)
    out = jnp.zeros((world * count,), x.dtype)
    out = lax.dynamic_update_slice_in_dim(out, x, root * count, axis=-1)
    relay = x
    for s in range(world - 1):
        recv = wire.ppermute(relay, axis, _ring_perm(world))
        origin = (root - 1 - s) % world
        placed = lax.dynamic_update_slice_in_dim(out, recv, origin * count, axis=-1)
        out = jnp.where(me == root, placed, out)
        relay = recv
    return out


def gather_flat_schedule(x, *, root: int, axis, world, wire, fanin: int):
    """Rendezvous gather. With unbounded fan-in every rank writes straight
    to root (.c:1142-1204); with the tuning cap (fan-in 2 above the count
    threshold, accl.cpp:1200-1201) it becomes a binomial combining tree."""
    count = x.shape[-1]
    me = lax.axis_index(axis)
    l = (me - root) % world
    out = jnp.zeros((world * count,), x.dtype)
    out = lax.dynamic_update_slice_in_dim(out, x, me * count, axis=-1)
    if fanin >= world - 1:
        for j in range(world):
            if j == root:
                continue
            recv = wire.ppermute(x, axis, [(j, root)])
            placed = lax.dynamic_update_slice_in_dim(out, recv, j * count, axis=-1)
            out = jnp.where(me == root, placed, out)
        return out
    # Binomial tree: at distance d, normalized ranks with l % 2d == d send
    # their accumulated subtree [l, min(l+d, P)) to parent l-d.
    positions = jnp.arange(world * count) // count  # owner chunk of each slot
    norm_pos = (positions - root) % world
    d = 1
    while d < world:
        perm = []
        senders = []
        for ln in range(d, world, 2 * d):
            perm.append(((ln + root) % world, (ln - d + root) % world))
            senders.append(ln)
        recv = wire.ppermute(out, axis, perm)
        sender_norm = l + d  # the child that sent to me this round
        subtree = (norm_pos >= sender_norm) & (norm_pos < jnp.minimum(sender_norm + d, world))
        is_parent = jnp.isin(l, jnp.asarray([ln - d for ln in senders]))
        out = jnp.where(is_parent & subtree, recv, out)
        d *= 2
    return out


def allgather_ring_schedule(x, *, axis, world, wire, ring=None):
    """Ring allgather (eager .c:1402-1499, rendezvous .c:1314-1401): P-1
    relay steps; the step-s arrival originates from rank me-1-s."""
    if wire.quantized:
        return _allgather_ring_quant(x, axis=axis, world=world, wire=wire,
                                     ring=ring)
    count = x.shape[-1]
    me, perm = _ring_ctx(axis, world, ring)
    out = jnp.zeros((world * count,), x.dtype)
    out = lax.dynamic_update_slice_in_dim(out, x, me * count, axis=-1)
    relay = x
    for s in range(world - 1):
        recv = wire.ppermute(relay, axis, perm)
        origin = (me - 1 - s) % world
        out = lax.dynamic_update_slice_in_dim(out, recv, origin * count, axis=-1)
        relay = recv
    return out


def _allgather_ring_quant(x, *, axis, world, wire, ring=None):
    """Quantized ring allgather: each rank encodes its chunk ONCE and the
    (codes, scales) pair relays around the ring unchanged — one
    quantization error per chunk total (not per hop), and every rank
    decodes identical bytes for chunk c, so a downstream allreduce stays
    rank-consistent. The local chunk is placed through the same
    encode/decode round trip the remote copies take, which is what makes
    the quantized allreduce's result identical on every rank."""
    count = x.shape[-1]
    me, perm = _ring_ctx(axis, world, ring)
    out = jnp.zeros((world * count,), x.dtype)
    enc = wire.encode(x)
    out = lax.dynamic_update_slice_in_dim(
        out, wire.decode(enc, count, x.dtype), me * count, axis=-1)
    for s in range(world - 1):
        enc = wire.hop(enc, axis, perm)
        origin = (me - 1 - s) % world
        out = lax.dynamic_update_slice_in_dim(
            out, wire.decode(enc, count, x.dtype), origin * count, axis=-1)
    return out


# ---------------------------------------------------------------------------
# Reduction family
# ---------------------------------------------------------------------------


def reduce_ring_schedule(x, *, root: int, func, axis, world, wire):
    """Eager ring reduce (.c:1730-1743): partials relay around the ring,
    each hop a fused recv-reduce-send (.c:755-789), terminating at root."""
    me = lax.axis_index(axis)
    acc = x
    for s in range(world - 1):
        sender = (root + 1 + s) % world
        receiver = (sender + 1) % world
        recv = wire.ppermute(acc, axis, [(sender, receiver)])
        acc = fused_recv_reduce(acc, recv, me == receiver, func, wire)
    return acc


def reduce_flat_schedule(x, *, root: int, func, axis, world, wire):
    """Rendezvous flat-tree reduce (.c:1531-1602): children write straight
    into root's scratch, root folds arrivals into the accumulator."""
    me = lax.axis_index(axis)
    acc = x
    for j in range(world):
        if j == root:
            continue
        recv = wire.ppermute(x, axis, [(j, root)])
        acc = fused_recv_reduce(acc, recv, me == root, func, wire)
    return acc


def reduce_bin_tree_schedule(x, *, root: int, func, axis, world, wire):
    """Rendezvous binomial-tree reduce (.c:1603-1727): at distance d the
    normalized ranks with l % 2d == d send partials to l-d; log2(P) rounds."""
    me = lax.axis_index(axis)
    l = (me - root) % world
    acc = x
    d = 1
    while d < world:
        perm = []
        parents = []
        for ln in range(d, world, 2 * d):
            perm.append(((ln + root) % world, (ln - d + root) % world))
            parents.append(ln - d)
        recv = wire.ppermute(acc, axis, perm)
        is_parent = jnp.isin(l, jnp.asarray(parents))
        acc = fused_recv_reduce(acc, recv, is_parent, func, wire)
        d *= 2
    return acc


def reduce_scatter_ring_schedule(x, *, func, axis, world, wire, ring=None):
    """Ring reduce-scatter (.c:1782-1850): P-1 steps; at step s each rank
    combines the arriving partial with its local copy of chunk me-1-s and
    forwards; rank r ends holding reduced chunk r."""
    if wire.quantized:
        return _reduce_scatter_ring_quant(
            x, func=func, axis=axis, world=world, wire=wire, ring=ring)
    count = x.shape[-1] // world
    me, perm = _ring_ctx(axis, world, ring)
    # Step-0 send is our local copy of chunk me-1; the step-s arrival is the
    # running partial of chunk me-2-s, combined with our local copy and
    # forwarded. After P-1 hops rank r holds fully-reduced chunk r.
    v = lax.dynamic_slice_in_dim(x, ((me - 1) % world) * count, count, axis=-1)
    for s in range(world - 1):
        recv = wire.ppermute(v, axis, perm)
        idx = (me - 2 - s) % world
        local = lax.dynamic_slice_in_dim(x, idx * count, count, axis=-1)
        v = wire.combine(func, recv, local)
    return v


def _reduce_scatter_ring_quant(x, *, func, axis, world, wire, ring=None):
    """Quantized ring reduce-scatter: the fused quantize-reduce ring.
    The traveling partial stays ENCODED between hops — only (int8 codes +
    per-block scales) cross each ppermute — while every combine runs the
    fused dequantize -> reduce(fp32) -> requantize step, so accumulation
    never drops below fp32. The terminal hop skips the requantize and
    lands the fp32 partial directly (one quantization pass per hop on the
    partial's path, P-1 total)."""
    count = x.shape[-1] // world
    me, perm = _ring_ctx(axis, world, ring)
    v = lax.dynamic_slice_in_dim(x, ((me - 1) % world) * count, count, axis=-1)
    enc = wire.encode(v)
    out = v  # world == 1 degenerates to the local chunk (plan NONE upstream)
    for s in range(world - 1):
        enc = wire.hop(enc, axis, perm)
        local = lax.dynamic_slice_in_dim(
            x, ((me - 2 - s) % world) * count, count, axis=-1)
        if s < world - 2:
            enc = wire.combine_requant(func, enc, local)
        else:
            out = wire.combine_decoded(func, enc, local)
    return out


def allreduce_ring_schedule(x, *, func, axis, world, wire, seg_count: int,
                            ring=None, serialize: bool = False,
                            live_ranks=None):
    """Segmented ring allreduce (.c:1888-2071): per segment, a ring
    reduce-scatter over world-size chunks followed by a ring allgather.
    Segments bound scratch footprint and pipeline across the loop.

    serialize=True threads an order-only dependency between the
    segment chains (segment i+1's chain starts only after segment i's
    output exists) — the serial dispatch->compute twin of a
    stripe-overlapped plan, bitwise-identical to the unserialized form
    (barriers change scheduling freedom, never values), kept reachable
    for A/B measurement exactly like the pallas ring's
    ACCL_PALLAS_RING_SERIALIZE baseline.

    live_ranks (the degraded live-subset mode, Plan.live_ranks): a
    declared surviving-contributor set. Every NON-member's operand is
    masked to exact zeros HERE, at the source, before any wire hop —
    the alltoallv capacity-drop posture generalized to the reduction —
    so the ring's folds provably accumulate exactly the survivors'
    data and the semantic certifier can match the output against the
    declared survivor sum (a dead rank's buffer can never leak a ghost
    contribution into the answer). Every rank, dead or alive, still
    relays its ring position: the wire pattern is the ordinary ring,
    only the contribution set shrinks. SUM-class folds only (a zero
    mask is the fold identity for SUM; the facade enforces this)."""
    count = x.shape[-1]
    if live_ranks is not None:
        me = lax.axis_index(axis)
        is_live = jnp.isin(me, jnp.asarray(tuple(live_ranks), jnp.int32))
        x = jnp.where(is_live, x, jnp.zeros_like(x))

    def one_segment(seg):
        padded = _pad_to_multiple(seg, world)
        chunk = padded.shape[-1] // world
        red = reduce_scatter_ring_schedule(
            padded, func=func, axis=axis, world=world, wire=wire, ring=ring
        )
        gathered = allgather_ring_schedule(red, axis=axis, world=world,
                                           wire=wire, ring=ring)
        return gathered[: seg.shape[-1]]

    return segmented_apply(one_segment, x, seg_count, serialize=serialize)


def segmented_apply(one_segment, x, seg_count, unroll_limit: int = 8,
                    serialize: bool = False, overlap_slots: int = 0):
    """Apply a per-segment schedule over a flat buffer in seg_count-element
    pieces (the eager segmentation substrate, .c:626-647). Independent
    segments are unrolled up to unroll_limit so XLA can software-pipeline
    their permutes (>2 outstanding moves); beyond that, lax.map bounds
    compile time. serialize=True threads a data dependency between
    segments for bodies that share stateful device resources (e.g. pallas
    kernels with a fixed collective_id).

    overlap_slots=k pipelines bodies whose device resources come in k
    independent slots (the reference's double-buffered rx ring): segment
    i runs in slot i%k and is called as one_segment(seg, slot). Only
    slot REUSE is ordered — segment i depends on segment i-k, so up to k
    segments double-buffer in flight while same-slot instances can never
    cross-talk (the de-serialized form of serialize=True for the
    slot-keyed pallas ring)."""
    count = x.shape[-1]
    if count <= seg_count:
        return one_segment(x, 0) if overlap_slots else one_segment(x)
    num_bulk = count // seg_count
    tail = count - num_bulk * seg_count
    bulk = x[: num_bulk * seg_count].reshape(num_bulk, seg_count)
    if overlap_slots:
        outs = []
        for i in range(num_bulk):
            seg_in = bulk[i]
            if i >= overlap_slots:
                # order-only dependency on the previous occupant of this
                # slot: its resources must be drained before reuse
                seg_in = _ordered_after(seg_in, outs[i - overlap_slots])
            outs.append(one_segment(seg_in, i % overlap_slots))
        if tail:
            tail_in = x[num_bulk * seg_count :]
            if num_bulk >= overlap_slots:
                tail_in = _ordered_after(
                    tail_in, outs[num_bulk - overlap_slots])
            outs.append(one_segment(tail_in, num_bulk % overlap_slots))
        return jnp.concatenate(outs)
    if serialize or num_bulk <= unroll_limit:
        outs = []
        carry = None
        for i in range(num_bulk):
            seg_in = bulk[i]
            if serialize and carry is not None:
                seg_in = _ordered_after(seg_in, carry)
            out_i = one_segment(seg_in)
            if serialize:
                carry = out_i
            outs.append(out_i)
        bulk_out = jnp.concatenate(outs)
    else:
        bulk_out = lax.map(one_segment, bulk).reshape(num_bulk * seg_count)
    if tail:
        tail_in = x[num_bulk * seg_count :]
        if serialize and carry is not None:
            # order on the LAST segment's output itself — a slice of the
            # concatenation would simplify to a slice of the FIRST
            # operand, quietly dropping the dependency on segments 2..N
            tail_in = _ordered_after(tail_in, carry)
        tail_out = one_segment(tail_in)
        return jnp.concatenate([bulk_out, tail_out])
    return bulk_out


def _ordered_after(seg_in, prev_out):
    """Order-only dependency: seg_in becomes unusable until prev_out has
    been computed, without changing its value. optimization_barrier (not
    `+ prev*0`) because the algebraic simplifier folds mul-by-zero away
    for integer dtypes, which would silently drop the serialization the
    slot-keyed kernel semaphores rely on. The barrier takes the WHOLE
    prev_out: narrowing it first (e.g. prev_out[:1]) would let the
    simplifier reduce a slice of a concatenation to a slice of its
    FIRST operand — and a segmented ring step's output IS a concat — so
    the dependency on segments 2..N would silently vanish (the same
    hazard the serialize tail path below documents)."""
    seg_in, _ = lax.optimization_barrier((seg_in, prev_out))
    return seg_in


def _pad_to_multiple(x, m):
    n = x.shape[-1]
    rem = (-n) % m
    if rem:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, rem)])
    return x


# ---------------------------------------------------------------------------
# All-to-all and barrier
# ---------------------------------------------------------------------------


def alltoall_schedule(x, *, axis, world, wire):
    """Pairwise rotation exchange (.c:2140-2211): at step k every rank
    sends chunk me+k to rank me+k and files the arrival from rank me-k
    into slot me-k; P-1 steps cover all peers.

    On the blockwise-quantized wire every peer chunk crosses its one
    hop as (int8 codes, per-block fp32 scales) — `Wire.ppermute`
    encodes at the source and dequantizes only at the destination slot,
    so each chunk pays exactly ONE quantization pass and the wire moves
    ~1/3.94 of the fp32 bytes. The LOCAL chunk never crosses a wire and
    stays exact: unlike the quantized allreduce ring there is no
    rank-consistency constraint here (every output slot has exactly one
    source), so round-tripping the local chunk would buy nothing but
    error."""
    count = x.shape[-1] // world
    me = lax.axis_index(axis)
    if wire.quantized and count % QUANT_BLOCK_ELEMS == 0:
        return _alltoall_quant_aligned(x, axis=axis, world=world, wire=wire)
    own = lax.dynamic_slice_in_dim(x, me * count, count, axis=-1)
    out = jnp.zeros_like(x)
    out = lax.dynamic_update_slice_in_dim(out, own, me * count, axis=-1)
    for k in range(1, world):
        peer_chunk = lax.dynamic_slice_in_dim(
            x, ((me + k) % world) * count, count, axis=-1
        )
        recv = wire.ppermute(peer_chunk, axis, _ring_perm(world, k))
        out = lax.dynamic_update_slice_in_dim(
            out, recv, ((me - k) % world) * count, axis=-1
        )
    return out


def _alltoall_quant_aligned(x, *, axis, world, wire):
    """The block-aligned quantized exchange: when the peer chunk is a
    whole number of quantization blocks, the WHOLE send buffer encodes
    ONCE (blocks never span chunk boundaries, so the per-chunk codes
    and scales are exact slices of the one encode — bitwise what
    per-chunk encoding would produce), every hop ships its packed
    slice as ONE message, arrivals assemble into a codes + scales
    staging pair, and the WHOLE received buffer dequantizes ONCE at
    the end. P-1 encodes and P-1 decodes become 1 + 1; per hop only a
    slice/pack/permute/unpack/file remains — the quantized exchange
    keeps the fp32 schedule's message count and sheds the per-hop
    transform chains that were costing it the fusion win. The local
    slot never crosses a wire and is spliced in EXACT (fp32) after the
    decode."""
    count = x.shape[-1] // world
    nb = count // QUANT_BLOCK_ELEMS
    me = lax.axis_index(axis)
    q_all, s_all = wire.encode(x)
    q_recv = jnp.zeros_like(q_all)
    s_recv = jnp.zeros_like(s_all)
    for k in range(1, world):
        dst = (me + k) % world
        src = (me - k) % world
        qc = lax.dynamic_slice_in_dim(q_all, dst * count, count, axis=-1)
        sc = lax.dynamic_slice_in_dim(s_all, dst * nb, nb, axis=-1)
        arr = lax.ppermute(pack_wire(qc, sc), axis, _ring_perm(world, k))
        q2, s2 = unpack_wire(arr, count)
        q_recv = lax.dynamic_update_slice_in_dim(
            q_recv, q2, src * count, axis=-1)
        s_recv = lax.dynamic_update_slice_in_dim(
            s_recv, s2, src * nb, axis=-1)
    out = wire.decode((q_recv, s_recv), world * count, x.dtype)
    own = lax.dynamic_slice_in_dim(x, me * count, count, axis=-1)
    return lax.dynamic_update_slice_in_dim(out, own, me * count, axis=-1)


def alltoallv_schedule(x, *, peer_counts, axis, world, wire):
    """Capacity-bounded pairwise exchange — the alltoallv of the MoE
    dispatch path. The buffer keeps the dense alltoall's uniform
    world-slot layout (slot = count elements, count = x.size // world),
    but peer p accepts only the first peer_counts[p] elements of each
    source's slot p — the per-peer CAPACITY, e.g. the expert capacity
    of the experts hosted on rank p — and everything past the valid
    prefix is DROPPED to zeros on the wire (standard dropped-token
    semantics, expressed inside the schedule so hazards, protocol,
    modelcheck and the semantic certifier can prove the routed
    contribution map; a receiver can never observe stale tail data).

    Every hop moves vmax = max(peer_counts) elements (one SPMD program
    serves all ranks, so hop shapes must be uniform; sub-vmax validity
    is masked at the SOURCE, which is what guarantees the dropped tail
    arrives as exact zeros), cutting wire bytes by count/vmax against
    the dense exchange. The quantized wire composes: the masked vmax
    chunk is encoded once at the source and dequantized only at the
    destination slot, exactly like the dense family. The local slot
    (the capacity prefix a rank keeps for its own experts) crosses no
    wire and stays exact."""
    count = x.shape[-1] // world
    counts = tuple(int(c) for c in peer_counts)
    if len(counts) != world:
        raise ValueError(
            f"alltoallv needs one peer count per rank: got {len(counts)} "
            f"for world {world}")
    if any(c <= 0 or c > count for c in counts):
        raise ValueError(
            f"peer counts {counts} outside (0, {count}] slot capacity")
    vmax = max(counts)
    cvec = jnp.asarray(counts, jnp.int32)
    valid = jnp.arange(vmax)
    me = lax.axis_index(axis)
    out = jnp.zeros_like(x)

    def capacity_prefix(dst):
        """Slot `dst` of the local buffer, truncated to dst's capacity:
        vmax elements with the overflow tail zeroed at the source."""
        chunk = lax.dynamic_slice_in_dim(x, dst * count, vmax, axis=-1)
        return jnp.where(valid < cvec[dst], chunk, 0)

    own = capacity_prefix(me)
    out = lax.dynamic_update_slice_in_dim(out, own, me * count, axis=-1)
    for k in range(1, world):
        chunk = capacity_prefix((me + k) % world)
        recv = wire.ppermute(chunk, axis, _ring_perm(world, k))
        out = lax.dynamic_update_slice_in_dim(
            out, recv, ((me - k) % world) * count, axis=-1
        )
    return out


def barrier_schedule(token, *, axis, world, wire):
    """Notification-only gather-to-0 + fan-out (.c:2078-2120): zero-payload
    messages carried here as a 1-element token reduced then rebroadcast."""
    gathered = reduce_flat_schedule(
        token, root=0, func=ReduceFunction.SUM, axis=axis, world=world, wire=wire
    )
    return bcast_flat_schedule(gathered, root=0, axis=axis, world=world, wire=wire)
