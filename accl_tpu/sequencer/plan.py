"""Algorithm selection: which schedule runs a given call.

Ports the firmware's per-collective selection logic
(reference: ccl_offload_control.c — bcast .c:796-988, scatter .c:992-1123,
gather .c:1128-1294, allgather .c:1297-1503, reduce .c:1507-1744,
reduce_scatter .c:1748-1852, allreduce .c:1855-2075, alltoall .c:2123-2218,
barrier .c:2078-2120) as a pure function so the Python lowering, the native
C++ runtime, and the tests all agree on exactly one set of rules.
"""

from __future__ import annotations

import dataclasses
import enum
import functools

from ..constants import (
    CompressionFlags,
    DataType,
    Operation,
    StreamFlags,
    TuningParams,
)


class Protocol(enum.IntEnum):
    EAGER = 0  # segmented through preallocated RX ring slots
    RENDEZVOUS = 1  # bulk zero-copy transfer after an address handshake


class Algorithm(enum.IntEnum):
    """Schedule families (SURVEY.md §2.7 table)."""

    NONE = 0  # local-only ops: copy/combine, world==1 corner cases
    EAGER_SENDRECV = 1  # segmented pipeline through rx slots (.c:611-648)
    RNDZV_SENDRECV = 2  # zero-copy one-sided write (.c:587-610)
    EAGER_FLAT = 3  # root fan-out, segmented (eager bcast/scatter)
    EAGER_RING = 4  # daisy-chain (eager gather/allgather/reduce/rs)
    EAGER_RING_RS_AG = 5  # ring reduce-scatter + ring allgather (eager allreduce)
    RNDZV_FLAT_TREE = 6  # out-of-order flat tree (small world/message)
    RNDZV_BIN_TREE = 7  # distance-doubling binary tree (bcast/reduce)
    RNDZV_RING = 8  # rendezvous ring (allgather)
    RNDZV_REDUCE_BCAST = 9  # allreduce = reduce + bcast (.c:1878-1887)
    RNDZV_REDUCE_SCATTER = 10  # reduce_scatter = reduce + scatter (.c:1768-1781)
    FLAT_ALLTOALL = 11  # pairwise exchange (.c:2140-2211)
    BARRIER_GATHER_SCATTER = 12  # zero-count notification tree (.c:2078-2120)
    # A search-produced hop-DAG from the committed synthesized library
    # (sequencer/synthesis.py): Plan.synth_key names the entry; the
    # compiler lowers the certified DAG instead of a Python body.
    SYNTHESIZED = 13
    # Striped two-tier allreduce (sequencer/hierarchical.py, HiCCL's
    # multiply/factor composition): RS(inner) -> AR(outer shard) ->
    # AG(inner) over Plan.stripes software-pipelined stripes, with
    # per-tier wire dtypes. Reachable only through the
    # HIER_ALLREDUCE_MIN_COUNT register window on a device that
    # declares a two-tier topology.
    HIER_RS_AR_AG = 14
    # Capacity-bounded pairwise exchange (schedules.alltoallv_schedule):
    # the dense alltoall's rotation with per-peer valid counts
    # (Plan.peer_counts) — every hop moves max(peer_counts) elements and
    # the overflow tail is dropped to zeros at the source, the MoE
    # dispatch's dropped-token semantics expressed in the schedule.
    FLAT_ALLTOALLV = 15


@dataclasses.dataclass(frozen=True)
class Plan:
    """The resolved execution plan for one call.

    seg_count is in elements: the eager segment size (rx-buffer capacity in
    elements, world-aligned where the algorithm strides by world size,
    .c:1898-1901). tree_fanin/tree_distance parameterize the flat/binary
    trees. All fields are static so a Plan is part of the XLA cache key.
    """

    protocol: Protocol
    algorithm: Algorithm
    seg_count: int  # elements per eager segment (== count when unsegmented)
    num_segments: int
    tree_fanin: int = 0  # flat-tree fan-in cap (gather tuning)
    use_bin_tree: bool = False
    # Composed algorithms (rendezvous allreduce/reduce_scatter) re-run the
    # per-stage selection with the same tuning registers, the way the
    # firmware re-enters reduce()/broadcast()/scatter() (.c:1878-1887,
    # .c:1768-1781). The stage plans are resolved here so lowering and the
    # native runtime never re-derive selection rules.
    stages: tuple["Plan", ...] = ()
    # The dtype payloads travel in on cross-rank hops (DataType.none =
    # uncompressed). Compression is a PLAN dimension, not just a
    # descriptor flag: the timing model charges wire bytes from this
    # field (cast lanes at the cast width, int8 at 1 B + amortized
    # per-block scale), so predict()/autotune() crossovers move when a
    # wire is active and select_wire() can arbitrate it by predicted
    # time (HiCCL's compression-as-measured-decision posture).
    wire_dtype: DataType = DataType.none
    # SYNTHESIZED plans: the library entry key (sequencer/synthesis.py)
    # the compiler lowers. Part of the frozen Plan, so it rides the XLA
    # cache key like every other selection decision.
    synth_key: str = ""
    # HIER_RS_AR_AG plans: the two-tier shape and the per-tier wire
    # decision. inner/outer_world pin the topology the schedule was
    # selected for; stripes is the cost-model-chosen pipeline depth —
    # shared with stripe-overlapped EAGER_RING_RS_AG plans (the
    # OVERLAP_MIN_COUNT register), where it counts the independent
    # stripe chains a fused program overlaps against adjacent compute
    # (timing.best_overlap_stripes' argmin; 1 = the serial form);
    # inner/outer_wire_dtype are the per-tier compression lanes
    # (select_tier_wires arbitrates each link separately — int8 on DCN
    # while fp32 stays on ICI). All frozen, so every one of these
    # decisions rides the Plan/XLA cache key.
    inner_world: int = 0
    outer_world: int = 0
    stripes: int = 1
    inner_wire_dtype: DataType = DataType.none
    outer_wire_dtype: DataType = DataType.none
    # FLAT_ALLTOALLV plans: the static per-peer valid counts the
    # schedule truncates each slot to (the descriptor's peer_counts).
    # Frozen, so two alltoallv calls with different capacity vectors
    # can never share a compiled program or a timing estimate.
    peer_counts: tuple[int, ...] = ()
    # Degraded live-subset allreduce (the descriptor's live_ranks): the
    # declared surviving-contributor set. Non-empty only on
    # EAGER_RING_RS_AG plans selected for `allreduce(mode=
    # "live_subset")` — the schedule masks every non-member's operand
    # to exact zeros at the source before the ordinary ring runs, so
    # the answer provably sums exactly the survivors. Frozen and
    # cache-keyed like peer_counts: two survivor sets can never share
    # a compiled program.
    live_ranks: tuple[int, ...] = ()


def is_rendezvous(
    bytes_count: int,
    compression: CompressionFlags,
    stream: StreamFlags,
    max_eager_size: int,
) -> bool:
    """The protocol switch every collective applies first
    (e.g. .c:808, .c:1524, .c:1879): large, uncompressed, non-streamed
    messages go rendezvous; everything else is eager."""
    return (
        bytes_count > max_eager_size
        and compression == CompressionFlags.NO_COMPRESSION
        and stream == StreamFlags.NO_STREAM
    )


def eager_seg_count(
    count: int,
    dtype_nbytes: int,
    eager_rx_buf_size: int,
    stream: StreamFlags,
    world_align: int = 1,
) -> int:
    """Eager segment size in elements (.c:925-936, .c:1891-1901): the rx
    buffer capacity, optionally rounded down to a multiple of world size for
    algorithms that stride chunks by rank; streamed operands are never
    segmented because streams can't be re-read."""
    if stream & StreamFlags.OP0_STREAM:
        return count
    seg = max(eager_rx_buf_size // dtype_nbytes, 1)
    if world_align > 1:
        seg -= seg % world_align
        seg = max(seg, world_align)
    return min(seg, count) if count > 0 else seg


def _segments(count: int, seg: int) -> int:
    return max((count + seg - 1) // seg, 1)


def select_algorithm(
    scenario: Operation,
    count: int,
    dtype_nbytes: int,
    world_size: int,
    compression: CompressionFlags = CompressionFlags.NO_COMPRESSION,
    stream: StreamFlags = StreamFlags.NO_STREAM,
    *,
    max_eager_size: int,
    eager_rx_buf_size: int,
    tuning: TuningParams,
    compress_dtype: DataType = DataType.none,
    topology: tuple[int, int] | None = None,
    tier_wires: tuple[DataType, DataType] = (DataType.none, DataType.none),
    tier_links=None,
    peer_counts: tuple[int, ...] = (),
    overlap_link=None,
    overlap_compute=None,
    tiered_synth_ok: bool = True,
    live_ranks: tuple[int, ...] = (),
) -> Plan:
    """Resolve scenario + message + communicator into a Plan.

    Selection rules are the firmware's, collective by collective; each
    branch cites the reference decision point. `compress_dtype` names
    the wire dtype of an ETH_COMPRESSED call (the descriptor's
    compress_dtype): it rides the Plan so the timing model charges wire
    widths, not payload widths.

    `topology=(inner_world, outer_world)` declares the caller's
    two-tier shape (a DCN device's (ici, dcn) extents, or a virtual
    factoring of a flat mesh). With it, allreduce payloads inside the
    HIER_ALLREDUCE_MIN_COUNT register window run the striped two-tier
    composition (Algorithm.HIER_RS_AR_AG); the register defaults 0
    (off) and is set by ACCL.autotune from the calibrated per-tier
    crossover, so absent a tune the behavior is bit-for-bit the flat
    selection. `tier_wires=(inner, outer)` are the per-tier wire dtypes
    (select_tier_wires arbitrates them); `tier_links` is a
    timing.TierLinks used to pick the stripe count (default: the
    shipped per-tier calibration, telemetry.feedback.default_tier_links
    — no calibration means 1 stripe, never a made-up pipeline depth).

    `overlap_link` (timing.LinkParams) and `overlap_compute`
    (timing.ComputeFit) parameterize the OVERLAP_MIN_COUNT register's
    stripe choice for exact eager allreduces (the consumer-spliced
    gradient-sync seam): inside the window the call runs as
    Plan.stripes independent stripe chains whose depth is
    timing.best_overlap_stripes' argmin under the calibrated shaped
    link and the measured compute term. Defaults load the shipped
    calibration (the tier-outer link and compute_fit) from
    telemetry.feedback; with no calibration the plan stays the serial
    form — never a made-up pipeline depth. Register 0 (the default)
    keeps selection bit-for-bit unchanged.
    """
    bytes_count = count * dtype_nbytes
    rndzv = is_rendezvous(bytes_count, compression, stream, max_eager_size)
    proto = Protocol.RENDEZVOUS if rndzv else Protocol.EAGER
    wire = (compress_dtype
            if compression & CompressionFlags.ETH_COMPRESSED
            and compress_dtype != DataType.none
            else DataType.none)

    def eager_plan(algorithm: Algorithm, world_align: int = 1) -> Plan:
        seg = eager_seg_count(
            count, dtype_nbytes, eager_rx_buf_size, stream, world_align
        )
        return Plan(Protocol.EAGER, algorithm, seg, _segments(count, seg),
                    wire_dtype=wire)

    def rndzv_plan(algorithm: Algorithm, **kw) -> Plan:
        return Plan(Protocol.RENDEZVOUS, algorithm, count, 1,
                    wire_dtype=wire, **kw)

    # Local-only operations and single-rank corner cases (.c:1520-1522,
    # .c:1765-1767, .c:1875-1877: world==1 reductions degrade to copy).
    if scenario in (Operation.copy, Operation.combine, Operation.config, Operation.nop):
        return Plan(proto, Algorithm.NONE, count, 1)
    if world_size == 1 and scenario != Operation.barrier:
        return Plan(proto, Algorithm.NONE, count, 1)

    # Degraded live-subset allreduce (accl_tpu/resilience/): a declared
    # surviving-contributor set pins the plan to the source-masked eager
    # ring — the one schedule family the certifier proves against the
    # survivor spec — BEFORE any performance window (hier / synthesized
    # / overlap / the rendezvous composition): degraded mode is the
    # certified-correctness path, and those windows were all calibrated
    # for the full-contributor collective. A full survivor set IS the
    # ordinary allreduce and falls through (the facade normalizes it to
    # () so the compiled program is shared, like the all-full alltoallv
    # vector).
    if scenario == Operation.allreduce and live_ranks:
        from ..descriptor import normalize_live_ranks

        lr = normalize_live_ranks(live_ranks, world_size)
        if lr != tuple(range(world_size)):
            if compression != CompressionFlags.NO_COMPRESSION:
                raise ValueError(
                    "live-subset allreduce is exact-wire only: the "
                    "certified degraded mode does not compose with "
                    "compression lanes")
            base = eager_plan(Algorithm.EAGER_RING_RS_AG,
                              world_align=world_size)
            return dataclasses.replace(base, live_ranks=lr)

    # Striped two-tier allreduce (sequencer/hierarchical.py): reachable
    # ONLY inside the HIER_ALLREDUCE_MIN_COUNT register window on a
    # caller that declared a two-tier topology — the same
    # measured-selection posture as the synth registers (register 0
    # keeps selection bit-for-bit unchanged). Checked BEFORE the flat
    # synthesized library: the flat synth windows were calibrated on a
    # uniform link, and on a declared two-tier world their flat
    # hop-DAGs would drag full payloads across the slow tier — a
    # caller who declared the topology and tuned the hier register has
    # asserted the per-tier calibration governs here. INSIDE the
    # window, though, the hand-written composition no longer pre-empts
    # unconditionally: a committed TIERED library entry for this exact
    # factoring (synthesis.select_entry(tiers=...), scored per-tier
    # against the striped composition itself) arbitrates BY PREDICTED
    # TIME under the same per-tier calibration — the composition is
    # one point in the factored search space, and the schedule that
    # predicts faster wins the cell. No tiered entry (or no per-tier
    # calibration) keeps the old behavior bit-for-bit;
    # tiered_synth_ok=False pins the composition (the bench/lint
    # twin-measurement escape, like select_wire's quantized_ok). Only
    # exact uncompressed unstreamed calls are eligible; per-tier
    # compression rides tier_wires/the plan's tier dtypes instead of
    # the descriptor's global compression flag.
    if scenario == Operation.allreduce and topology is not None:
        inner_w, outer_w = topology
        if (tuning.hier_allreduce_min_count > 0
                and inner_w > 1 and outer_w > 1
                and inner_w * outer_w == world_size
                and bytes_count >= tuning.hier_allreduce_min_count
                and stream == StreamFlags.NO_STREAM
                and compression == CompressionFlags.NO_COMPRESSION):
            from .timing import best_stripes, predict_tiered

            iw, ow = tier_wires
            links = tier_links
            if links is None:
                from ..telemetry.feedback import default_tier_links

                links = default_tier_links()
            stripes = 1
            if links is not None:
                stripes = best_stripes(
                    links, count, dtype_nbytes, inner_w, outer_w,
                    inner_wire=iw, outer_wire=ow)
            hier_plan = Plan(Protocol.EAGER, Algorithm.HIER_RS_AR_AG,
                             count, 1, inner_world=inner_w,
                             outer_world=outer_w, stripes=stripes,
                             inner_wire_dtype=iw, outer_wire_dtype=ow)
            if tiered_synth_ok and links is not None:
                from . import synthesis
                from .timing import predict_synth_tiered

                key = synthesis.select_entry(
                    scenario, world_size, bytes_count,
                    tiers=(inner_w, outer_w))
                if key is not None:
                    synth_plan = Plan(Protocol.EAGER,
                                      Algorithm.SYNTHESIZED, count, 1,
                                      synth_key=key,
                                      inner_world=inner_w,
                                      outer_world=outer_w)
                    t_synth = predict_synth_tiered(
                        links, synth_plan, count, dtype_nbytes)
                    t_hier = predict_tiered(links, hier_plan, count,
                                            dtype_nbytes)
                    if t_synth < t_hier:
                        return synth_plan
            return hier_plan

    # Latency-window synthesized schedules (synthesis.SIZE_GRID_LAT):
    # exact uncompressed unstreamed allreduce payloads inside the
    # SYNTH_LATENCY_MAX_COUNT window run the committed latency-grid
    # hop-DAG — the minimum-step members scored on the 1-64 KiB decode
    # grid where the alpha term dominates. Checked BEFORE the std
    # synth window: the lat register is derived contiguous-from-bottom
    # on the fine grid, so inside it the lat entry is the calibrated
    # winner even where the coarser std window also claims the cell.
    # Register 0 (the default) skips this branch entirely — selection
    # is bit-for-bit the established behavior.
    if (scenario == Operation.allreduce
            and tuning.synth_latency_max_count
            and 0 < bytes_count <= tuning.synth_latency_max_count
            and stream == StreamFlags.NO_STREAM
            and compression == CompressionFlags.NO_COMPRESSION):
        from . import synthesis

        key = synthesis.select_entry(scenario, world_size, bytes_count,
                                     grid="lat")
        if key is not None:
            return Plan(Protocol.EAGER, Algorithm.SYNTHESIZED,
                        count, 1, wire_dtype=wire, synth_key=key)

    # Synthesized schedules (sequencer/synthesis.py): payloads inside a
    # synth crossover register run the search-produced hop-DAG for this
    # (op, world) when the committed library carries a certified entry
    # whose predicted winning window covers the payload. Registers
    # default 0 (off) and are set by ACCL.autotune from the calibrated
    # timing model — selection from measured crossovers, the same
    # posture as every other register. Only exact uncompressed
    # unstreamed calls are eligible: the library's int8-wire entries
    # (exchange family re-encodes the running partial every hop) are
    # NOT rank-consistent — different ranks fold differently-quantized
    # copies and finish apart by up to the per-block bound — so they
    # must never silently replace the hand-written quantized ring,
    # whose rank-consistent round-trip is a documented contract
    # (docs/api.md). int8 entries stay first-class for explicit use
    # (synthesis.select_entry(wire="int8"), tools/accl_synth).
    synth_reg = {
        Operation.allreduce: tuning.synth_allreduce_max_count,
        Operation.allgather: tuning.synth_allgather_max_count,
        Operation.reduce_scatter: tuning.synth_reduce_scatter_max_count,
    }.get(scenario, 0)
    if (synth_reg and 0 < bytes_count <= synth_reg
            and stream == StreamFlags.NO_STREAM
            and compression == CompressionFlags.NO_COMPRESSION):
        from . import synthesis

        key = synthesis.select_entry(scenario, world_size, bytes_count)
        if key is not None:
            return Plan(Protocol.EAGER, Algorithm.SYNTHESIZED,
                        count, 1, wire_dtype=wire, synth_key=key)

    if scenario in (Operation.send, Operation.recv):
        # send .c:573-649 / recv .c:653-710: rendezvous one-sided write vs
        # eager segmented pipeline.
        if rndzv:
            return rndzv_plan(Algorithm.RNDZV_SENDRECV)
        return eager_plan(Algorithm.EAGER_SENDRECV)

    if scenario == Operation.bcast:
        if rndzv:
            # .c:814-867: binary tree once the world outgrows the flat-tree
            # tuning register; else out-of-order flat fan-out (.c:868-919).
            if world_size > tuning.bcast_flat_tree_max_ranks:
                return rndzv_plan(Algorithm.RNDZV_BIN_TREE, use_bin_tree=True)
            return rndzv_plan(Algorithm.RNDZV_FLAT_TREE, tree_fanin=world_size - 1)
        return eager_plan(Algorithm.EAGER_FLAT)  # .c:921-988 root fan-out

    if scenario == Operation.scatter:
        if rndzv:
            return rndzv_plan(Algorithm.RNDZV_FLAT_TREE, tree_fanin=world_size - 1)
        return eager_plan(Algorithm.EAGER_FLAT)  # .c:992-1123 round-robin

    if scenario == Operation.gather:
        if rndzv:
            # .c:1142-1204: flat tree, fan-in capped above the tuning count
            # threshold (gather fan-in 2 above 32 KB, accl.cpp:1200-1201).
            if bytes_count > tuning.gather_flat_tree_max_count:
                fanin = max(tuning.gather_flat_tree_max_fanin, 1)
            else:
                fanin = world_size - 1
            return rndzv_plan(Algorithm.RNDZV_FLAT_TREE, tree_fanin=fanin)
        return eager_plan(Algorithm.EAGER_RING)  # .c:1206-1293 daisy chain

    if scenario == Operation.allgather:
        if rndzv:
            return rndzv_plan(Algorithm.RNDZV_RING)  # .c:1314-1401
        return eager_plan(Algorithm.EAGER_RING)  # .c:1402-1499

    if scenario == Operation.reduce:
        if rndzv:
            # .c:1531: flat tree when world or message is small, else
            # distance-doubling binary tree (.c:1603-1727).
            if (
                world_size <= tuning.reduce_flat_tree_max_ranks
                or bytes_count <= tuning.reduce_flat_tree_max_count
            ):
                return rndzv_plan(Algorithm.RNDZV_FLAT_TREE, tree_fanin=world_size - 1)
            return rndzv_plan(Algorithm.RNDZV_BIN_TREE, use_bin_tree=True)
        return eager_plan(Algorithm.EAGER_RING)  # .c:1730-1743 ring relay

    if scenario == Operation.reduce_scatter:
        if rndzv:
            # .c:1768-1781: reduce(count*world, root=0) then scatter(count).
            sub = functools.partial(
                select_algorithm,
                dtype_nbytes=dtype_nbytes,
                world_size=world_size,
                compression=compression,
                stream=stream,
                max_eager_size=max_eager_size,
                eager_rx_buf_size=eager_rx_buf_size,
                tuning=tuning,
                compress_dtype=compress_dtype,
            )
            return rndzv_plan(
                Algorithm.RNDZV_REDUCE_SCATTER,
                stages=(
                    sub(Operation.reduce, count * world_size),
                    sub(Operation.scatter, count),
                ),
            )
        return eager_plan(Algorithm.EAGER_RING, world_align=world_size)  # .c:1782-1850

    if scenario == Operation.allreduce:
        # Segmented ring reduce-scatter + ring allgather with world-aligned
        # segments as the DEFAULT at every size (.c:1888-2071): the ring
        # moves the bandwidth-optimal 2*bytes*(P-1)/P per link with chunks
        # pipelined down both phases, while the reference's rendezvous
        # reduce+bcast composition (.c:1878-1887) serializes full payloads
        # through tree combine nodes — measured 4x slower than bcast alone
        # at 1 MB / 8 ranks on the native emulator (accl_log/emu_bench.csv).
        # The composition stays reachable through a tuning register (the
        # reference's runtime-tunable-selection posture, accl.cpp:1198-1208)
        # so the timing model can arbitrate per (size, world) on links
        # where trees win; register 0 keeps the measured ring default.
        if rndzv and bytes_count <= tuning.allreduce_composition_max_count:
            sub = functools.partial(
                select_algorithm,
                dtype_nbytes=dtype_nbytes,
                world_size=world_size,
                compression=compression,
                stream=stream,
                max_eager_size=max_eager_size,
                eager_rx_buf_size=eager_rx_buf_size,
                tuning=tuning,
                compress_dtype=compress_dtype,
            )
            return rndzv_plan(
                Algorithm.RNDZV_REDUCE_BCAST,
                stages=(
                    sub(Operation.reduce, count),
                    sub(Operation.bcast, count),
                ),
            )
        plan = eager_plan(Algorithm.EAGER_RING_RS_AG,
                          world_align=world_size)
        # Stripe-overlapped gradient allreduce (the OVERLAP_MIN_COUNT
        # register): an exact eager allreduce inside the window runs as
        # Plan.stripes independent stripe chains, so a fused program
        # can overlap stripe i's wire with stripe i+1's compute (the
        # consumer-spliced gradient-sync seam). XLA-schedule-tier in
        # effect: only autotuned XLA/DCN devices ever move the
        # register off 0, and the native runtime's selection never
        # reads it — the same scoping as the hier register. The stripe
        # count is timing.best_overlap_stripes' argmin under the
        # calibrated shaped link and the measured compute term — no
        # calibration means the serial plan, never a made-up depth.
        if (tuning.overlap_min_count > 0
                and compression == CompressionFlags.NO_COMPRESSION
                and bytes_count >= tuning.overlap_min_count):
            link, fit = overlap_link, overlap_compute
            if link is None or fit is None:
                from ..telemetry import feedback as _fb

                if fit is None:
                    fit = _fb.default_compute_fit()
                if link is None:
                    tl = _fb.default_tier_links()
                    link = tl.outer if tl is not None \
                        else _fb.default_link()
            if link is not None and fit is not None:
                from .timing import best_overlap_stripes

                stripes = best_overlap_stripes(
                    link, count, dtype_nbytes, world_size,
                    compute_s=fit.seconds(bytes_count),
                    rx_buf_bytes=eager_rx_buf_size)
                if stripes > 1:
                    seg = -(-count // stripes)
                    seg += (-seg) % world_size
                    # world-aligning the stripe segment can merge the
                    # tail stripes (count=100, world=8, S=8 -> seg=16
                    # -> 7 chains): the frozen stripe count must be
                    # the chain count the lowering actually runs, or
                    # the cost model and the serialized twin's barrier
                    # accounting drift off the real program
                    n_seg = _segments(count, seg)
                    if n_seg > 1:
                        return dataclasses.replace(
                            plan, seg_count=seg, num_segments=n_seg,
                            stripes=n_seg)
        return plan

    if scenario == Operation.alltoall:
        # alltoallv: a per-peer capacity vector turns the dense rotation
        # into the capacity-bounded exchange. An all-full vector IS the
        # dense alltoall and normalizes to it (one compiled program, no
        # vmax machinery), so `alltoallv(counts=(count,)*world)` is
        # bit-for-bit `alltoall`.
        if peer_counts and any(c != count for c in peer_counts):
            if len(peer_counts) != world_size:
                raise ValueError(
                    f"alltoallv needs {world_size} peer counts, got "
                    f"{len(peer_counts)}")
            if any(c <= 0 or c > count for c in peer_counts):
                raise ValueError(
                    f"alltoallv peer counts {peer_counts} outside "
                    f"(0, {count}]")
            pc = tuple(int(c) for c in peer_counts)
            if rndzv:
                return rndzv_plan(Algorithm.FLAT_ALLTOALLV, peer_counts=pc)
            return dataclasses.replace(
                eager_plan(Algorithm.FLAT_ALLTOALLV), peer_counts=pc)
        return rndzv_plan(Algorithm.FLAT_ALLTOALL) if rndzv else eager_plan(
            Algorithm.FLAT_ALLTOALL
        )  # .c:2140-2211

    if scenario == Operation.barrier:
        # .c:2078-2120: count==0 notification gather-to-0 then scatter.
        return Plan(Protocol.RENDEZVOUS, Algorithm.BARRIER_GATHER_SCATTER, 0, 1)

    raise ValueError(f"no algorithm for scenario {scenario!r}")


def select_wire(
    scenario: Operation,
    count: int,
    data_type: DataType,
    world_size: int,
    link,
    *,
    max_eager_size: int,
    eager_rx_buf_size: int,
    rx_buf_bytes: int,
    tuning: TuningParams,
    arith_table: dict | None = None,
    min_gain: float = 0.05,
    aggregate: bool = False,
    quantized_ok: bool = True,
) -> DataType:
    """Pick the wire dtype for a call by PREDICTED TIME — compression as
    a plan dimension, not a flag (HiCCL's point that compression and
    algorithm choice must be measured performance decisions).

    Candidates are the arithmetic-configuration rows whose uncompressed
    dtype matches the payload (fp32 -> {fp16, bf16, int8-blockwise} on
    the default table) plus the uncompressed baseline. Each candidate is
    re-planned (compressed calls route eager) and costed through the
    calibrated timing model with WIRE-byte accounting; a compressed wire
    is chosen only when it beats the baseline by more than `min_gain`
    relative — on latency-dominated small payloads, where wire bytes
    barely move the prediction, the call keeps its exact fp32 wire
    rather than paying quantization error for nothing.

    `link` is a timing.LinkParams. Returns the chosen compress_dtype
    (DataType.none = stay uncompressed); callers hand it to the facade's
    `compress_dtype=` seam unchanged. `quantized_ok=False` drops the
    blockwise lanes from the candidate set — pass
    `getattr(device, "supports_quantized_wire", False)` when selecting
    for a backend that may lack the quantized ring kernels, so the
    runner-up cast lane wins instead of the facade rejecting the pick.
    """
    from ..arithconfig import DEFAULT_ARITH_CONFIG
    from ..constants import dtype_nbytes
    from ..ops.compression import is_quantized
    from .timing import predict

    table = arith_table or DEFAULT_ARITH_CONFIG
    elem_bytes = dtype_nbytes(data_type)
    kw: dict = dict(max_eager_size=max_eager_size,
                    eager_rx_buf_size=eager_rx_buf_size, tuning=tuning)

    def cost(wire: DataType) -> float:
        comp = (CompressionFlags.ETH_COMPRESSED if wire != DataType.none
                else CompressionFlags.NO_COMPRESSION)
        plan = select_algorithm(scenario, count, elem_bytes, world_size,
                                comp, compress_dtype=wire, **kw)
        return predict(link, scenario, plan, count, elem_bytes, world_size,
                       rx_buf_bytes=rx_buf_bytes, aggregate=aggregate)

    t_none = cost(DataType.none)
    best, t_best = DataType.none, t_none
    for (unc, cmp_), row in table.items():
        if unc != data_type or cmp_ == unc:
            continue
        if not quantized_ok and is_quantized(row):
            continue
        t = cost(cmp_)
        if t < t_best and (t_none - t) > min_gain * t_none:
            best, t_best = cmp_, t
    return best


def select_tier_wires(
    count: int,
    data_type: DataType,
    topology: tuple[int, int],
    tier_links,
    *,
    arith_table: dict | None = None,
    min_gain: float = 0.05,
    quantized_ok: bool = True,
) -> tuple[DataType, DataType]:
    """Per-tier wire arbitration for the striped hierarchical allreduce:
    `select_wire`'s predicted-time decision, made ONCE PER LINK.

    The hierarchical cost decomposes by tier (timing.hier_phase_costs
    charges phases 1/3 to the inner link and phase 2 to the outer), so
    each tier's wire is chosen independently: the candidate set is the
    arithmetic-configuration rows for the payload dtype, each costed
    through predict_tiered with that tier's wire active and the other
    uncompressed, and a compressed wire wins only when it beats the
    tier's uncompressed baseline by `min_gain` of the TOTAL call time.
    The typical calibrated outcome is exactly HiCCL's: int8 codes on
    the slow DCN tier (where wire bytes dominate), fp32 kept exact on
    ICI (where the latency term dominates and quantization error buys
    nothing). Returns (inner_wire, outer_wire) — DataType.none = stay
    uncompressed — which callers hand to select_algorithm's
    `tier_wires=`."""
    from ..arithconfig import DEFAULT_ARITH_CONFIG
    from ..constants import dtype_nbytes
    from ..ops.compression import is_quantized
    from .timing import best_stripes, predict_tiered

    table = arith_table or DEFAULT_ARITH_CONFIG
    elem_bytes = dtype_nbytes(data_type)
    inner_w, outer_w = topology

    def cost(iw: DataType, ow: DataType) -> float:
        stripes = best_stripes(tier_links, count, elem_bytes, inner_w,
                               outer_w, inner_wire=iw, outer_wire=ow)
        plan = Plan(Protocol.EAGER, Algorithm.HIER_RS_AR_AG, count, 1,
                    inner_world=inner_w, outer_world=outer_w,
                    stripes=stripes, inner_wire_dtype=iw,
                    outer_wire_dtype=ow)
        return predict_tiered(tier_links, plan, count, elem_bytes)

    picks = []
    for tier in ("inner", "outer"):
        def with_tier(w: DataType) -> float:
            return cost(w, DataType.none) if tier == "inner" \
                else cost(DataType.none, w)

        t_none = with_tier(DataType.none)
        best, t_best = DataType.none, t_none
        for (unc, cmp_), row in table.items():
            if unc != data_type or cmp_ == unc:
                continue
            if not quantized_ok and is_quantized(row):
                continue
            t = with_tier(cmp_)
            if t < t_best and (t_none - t) > min_gain * t_none:
                best, t_best = cmp_, t
        picks.append(best)
    return picks[0], picks[1]
