"""Device-resident call sequences: one compiled program per descriptor batch.

Every facade call used to dispatch its own jitted program, so a
reduce-scatter -> allgather -> compute chain paid a host round-trip and an
HBM materialization at every seam. A SequencePlan lowers a RECORDED batch
of call descriptors (SequenceDescriptor) through the same schedule bodies
the per-call path uses into ONE jax.jit(shard_map(...)) device program:
one dispatch for the whole chain, XLA free to fuse across collective
seams, stream producers/consumers spliced between stages — the composed
form of ACCL's host-only-issues-the-call inversion (HiCCL's fused-schedule
observation applied to the descriptor batch).

Dataflow: buffers referenced by the batch become program inputs (one per
unique address, full buffer width); an environment threads each step's
result to later operands by address, mirroring what chained eager calls
with from_device/to_device would observe — so a recorded sequence is
bitwise-identical to the same calls issued eagerly (the cross-executor
fuzz pins this).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from ..constants import DataType, Operation
from ..descriptor import SequenceDescriptor

# ops that read `count * world` elements per rank (stacked chunk inputs,
# tpu_device._launch's in_n rule)
_WIDE_IN = (Operation.scatter, Operation.reduce_scatter, Operation.alltoall)
# ops whose per-rank result is `count * world` elements
_WIDE_OUT = (Operation.gather, Operation.allgather, Operation.alltoall)

# the descriptor kinds a sequence can carry: data-plane steps with static
# operand/result addresses. send/recv pair through the host-side parking
# maps and barrier carries no payload — none of them belongs in a fused
# data-flow program.
SEQUENCE_OPS = (
    Operation.copy,
    Operation.combine,
    Operation.bcast,
    Operation.scatter,
    Operation.gather,
    Operation.allgather,
    Operation.reduce,
    Operation.allreduce,
    Operation.reduce_scatter,
    Operation.alltoall,
)


def step_in_elems(options, world: int) -> int:
    return options.count * world if options.scenario in _WIDE_IN \
        else options.count


def step_out_elems(options, world: int) -> int:
    return options.count * world if options.scenario in _WIDE_OUT \
        else options.count


def step_accesses(
    options: Any, world: int
) -> tuple[list[tuple[int, int]], list[tuple[int, int]]]:
    """(reads, writes) of one step as (address, prefix elems) pairs —
    the exact access model the hazard pass reasons over (every
    sequence-able op touches a PREFIX region at offset 0: the wide
    in/out rule above is the only width variation), shared with the
    cross-program footprint extractor (analysis/interference.py) so the
    two layers can never disagree on what a step touches."""
    reads: list[tuple[int, int]] = []
    if options.addr_0:
        reads.append((options.addr_0, step_in_elems(options, world)))
    if options.addr_1:
        reads.append((options.addr_1, options.count))
    writes: list[tuple[int, int]] = []
    if options.addr_2:
        writes.append((options.addr_2, step_out_elems(options, world)))
    return reads, writes


@dataclasses.dataclass(frozen=True)
class _Step:
    """One lowered stage: its descriptor/plan plus the resolved dataflow
    (buffer-table indices and static element counts)."""

    options: Any  # CallOptions
    plan: Any  # Plan
    in_idx: tuple[int, ...]
    res_idx: int
    in_elems: int
    out_elems: int
    producer: Callable | None
    consumer: Callable | None


class SequencePlan:
    """The lowered form of a recorded descriptor batch.

    Construction resolves the batch's dataflow (which addresses feed
    which steps) against per-step Plans; `build()` composes the per-step
    schedule bodies into one traced callable over the buffer table, and
    `cache_key()` is the composite signature the ScheduleCompiler caches
    the compiled program under, alongside its per-call entries.
    """

    def __init__(
        self,
        descriptor: SequenceDescriptor,
        plans: list,
        world: int,
        endpoints: list[tuple[Callable | None, Callable | None]] | None = None,
    ):
        if len(plans) != len(descriptor.steps):
            raise ValueError("one Plan per descriptor step required")
        if endpoints is None:
            endpoints = [(None, None)] * len(descriptor.steps)
        self.descriptor = descriptor
        self.world = world
        addr_order: dict[int, int] = {}

        def idx(addr: int) -> int:
            return addr_order.setdefault(addr, len(addr_order))

        steps: list[_Step] = []
        written: list[int] = []
        for opts, plan, (prod, cons) in zip(descriptor.steps, plans,
                                            endpoints):
            if opts.scenario not in SEQUENCE_OPS:
                raise ValueError(
                    f"{opts.scenario.name} cannot ride a call sequence "
                    "(host-paired or payload-free descriptor)")
            if opts.addr_0 == 0 or opts.addr_2 == 0:
                raise ValueError(
                    f"sequence step {opts.scenario.name} needs operand and "
                    "result buffers")
            in_idx = [idx(opts.addr_0)]
            if opts.scenario == Operation.combine:
                if opts.addr_1 == 0:
                    raise ValueError("combine step needs a second operand")
                in_idx.append(idx(opts.addr_1))
            res_idx = idx(opts.addr_2)
            if res_idx not in written:
                written.append(res_idx)
            steps.append(_Step(
                options=opts,
                plan=plan,
                in_idx=tuple(in_idx),
                res_idx=res_idx,
                in_elems=step_in_elems(opts, world),
                out_elems=step_out_elems(opts, world),
                producer=prod,
                consumer=cons,
            ))
        self.steps = tuple(steps)
        # buffer table: unique addresses in first-appearance order (the
        # same canonical order descriptor.signature() renames by)
        self.buffer_addrs = tuple(addr_order)
        # program outputs: every written buffer, in first-write order
        self.out_idx = tuple(written)
        self.out_addrs = tuple(self.buffer_addrs[i] for i in written)

    @property
    def n_in(self) -> int:
        return len(self.buffer_addrs)

    def lint(self, *, use_pallas_ring: bool = False,
             pallas_ring_overlap: bool = True, deep: bool = False,
             buffer_widths: dict[int, int] | None = None,
             axis_name: str = "ccl", arith_table: dict | None = None,
             budget=None):
        """Run the static analyzer (accl_tpu/analysis/) over this plan's
        descriptor batch and return the diagnostic list — the same gate
        TPUDevice.start_sequence applies before compile_sequence, here
        callable on a standalone plan (corpus replay, tests). The flags
        mirror the ScheduleCompiler configuration the batch would lower
        under, so the slot model matches the real launch. `deep=True`
        (the `lint="deep"` tier) adds the per-step schedule
        interpretation AND the exhaustive-interleaving model checker
        (ACCL205-207); `budget` caps its exploration
        (analysis.modelcheck.Budget)."""
        from ..analysis.linter import SequenceLinter

        linter = SequenceLinter(
            self.world,
            use_pallas_ring=use_pallas_ring,
            pallas_ring_overlap=pallas_ring_overlap,
            deep=deep,
            axis_name=axis_name,
            arith_table=arith_table,
            budget=budget,
        )
        return linter.lint(self.descriptor.steps,
                           [st.plan for st in self.steps],
                           buffer_widths=buffer_widths)

    def min_widths(self) -> dict[int, int]:
        """Per-address minimum buffer width (elements) the batch needs —
        execution-time validation against the registered buffers."""
        need: dict[int, int] = {}
        for st in self.steps:
            for i in st.in_idx:
                a = self.buffer_addrs[i]
                need[a] = max(need.get(a, 0), st.in_elems)
            a = self.buffer_addrs[st.res_idx]
            need[a] = max(need.get(a, 0), st.out_elems)
        return need

    def cache_key(self, axis_name: str, use_pallas_ring: bool,
                  pallas_ring_overlap: bool,
                  overlap_serialize: bool = False) -> tuple:
        # endpoint callables ride the key by identity, with strong refs
        # held (same id-reuse hazard as lower_streamed)
        eps = tuple((st.producer, st.consumer) for st in self.steps)
        return (
            self.descriptor.signature(),
            tuple(st.plan for st in self.steps),
            eps,
            axis_name,
            use_pallas_ring,
            pallas_ring_overlap,
            overlap_serialize,
        )

    # -- construction ------------------------------------------------------

    def build(self, compiler) -> tuple[Callable, int]:
        """Compose the per-step schedule bodies into one traced callable:
        (flat per-rank buffer views...) -> (written buffer views...).
        Returns (body, n_in) for the compiler's shard_map finalization."""
        from jax import lax

        from ..ops.streams import splice_consumer, splice_producer
        from .lowering import _arithcfg_for

        lowered = []
        for st in self.steps:
            arithcfg = None
            if st.options.data_type != DataType.none:
                arithcfg = _arithcfg_for(compiler.arith_table, st.options)
            body, n_in = compiler._body(st.options, st.plan, arithcfg)
            if st.producer is not None:
                if n_in != 1:
                    raise ValueError(
                        "OP0_STREAM unsupported for "
                        f"{st.options.scenario.name}")
                body = splice_producer(body, st.producer, st.in_elems)
            if st.consumer is not None:
                body = splice_consumer(body, st.consumer)
            # steps that may lower to the pallas ring share its slot-keyed
            # collective_ids: two such steps with no dataflow between them
            # must still be ORDERED, or concurrent kernel instances would
            # cross-talk on the shared semaphores (conservative: an
            # allreduce that actually took the lax branch is ordered too,
            # which costs nothing but a scheduling edge)
            uses_ring = (st.options.scenario == Operation.allreduce
                         and compiler.use_pallas_ring)
            lowered.append((body, uses_ring))

        steps = self.steps
        out_idx = self.out_idx

        def fused(*bufs):
            from .schedules import _ordered_after

            env = list(bufs)
            prev_ring = None
            for st, (body, uses_ring) in zip(steps, lowered):
                ins = [env[i][..., : st.in_elems] for i in st.in_idx]
                if uses_ring and prev_ring is not None:
                    ins[0] = _ordered_after(ins[0], prev_ring)
                out = body(*ins)
                if uses_ring:
                    prev_ring = out
                cur = env[st.res_idx]
                if out.shape[-1] == cur.shape[-1]:
                    # full-width result replaces the value outright (the
                    # eager path's res.device = out)
                    env[st.res_idx] = out
                else:
                    # partial-width result prefixes the buffer, keeping
                    # the tail (the eager _place_into shape)
                    env[st.res_idx] = lax.dynamic_update_slice_in_dim(
                        cur, out.astype(cur.dtype), 0, axis=-1)
            return tuple(env[i] for i in out_idx)

        return fused, self.n_in
