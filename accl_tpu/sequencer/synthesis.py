"""Schedule synthesis: search the hop-DAG space, certify winners, ship
them as first-class algorithms.

The prove side already exists: `analysis.semantics` certifies that a
hop-DAG computes its declared collective (ACCL501-504) and
`analysis.modelcheck` certifies its hop programs race/deadlock-free over
every legal match order (ACCL205-207). This module is the inversion of
those checkers into a GENERATOR (ROADMAP item 1; SCCL's k-step hop
formulation, arxiv 2008.08708): given (operation, world size, payload,
link parameters), enumerate candidate schedules as hop-DAGs, prune by
latency/bandwidth dominance, certify every survivor with the existing
stack — an uncertified candidate is discarded loudly, never shipped —
score the rest with `timing`-style alpha-beta prediction, and cache the
winners as JSON hop-DAGs in the committed `synthesized/` library, where
`plan.select_algorithm` can pick them behind measured crossover
registers and `lowering.ScheduleCompiler` compiles them like any other
algorithm.

Search space
------------
Candidates are ROTATIONALLY SYMMETRIC k-step schedules over the
fully-connected per-step topology one `lax.ppermute` expresses: a
candidate is a sequence of rotation distances (d_1 .. d_k), each step a
full-ring permutation `rank -> rank + d_i`. Rank symmetry is the
symmetry pruning rule: the whole orbit of rank-relabelings collapses to
one candidate, and the compiled program is one rank-relative chain (no
per-rank branching). Families:

  exchange   allreduce: every rank sends its running PARTIAL to
             rank+d_i and folds the arrival from rank-d_i; valid iff
             the 2^k subset sums of the distances are pairwise distinct
             mod world (each input contributes exactly once — the
             double-count/partial classes are pruned here, and the
             certifier re-proves it). k = log2(world) steps: the
             latency-optimal end of the frontier (recursive doubling is
             the (1, 2, 4, ...) member).
  doubling   allgather: every rank relays ALL chunks held so far;
             same validity condition; k steps moving (P-1) chunks.
  halving    reduce_scatter: the time-reversal dual of `doubling` —
             responsibility sets halve each step, partials fold at the
             receiver.
  rs_ag      allreduce as halving reduce_scatter + doubling allgather
             over the same distance set: 2k steps, 2(P-1)/P payload
             bytes — the bandwidth-optimal point at log latency
             (recursive halving-doubling is a member).

Each family also admits an int8 blockwise-quantized wire variant
(`wire="int8"` currently generated for `exchange`): hops carry
(codes, scales) through encode/decode nodes backed by the real
`ops.compression` reference, so certification and numeric execution see
exactly what the compiled program runs.

These families cover the latency-bandwidth frontier the hand-written
zoo lacks (the zoo's eager ring is the bandwidth end; nothing
hand-written occupies the log-step region on the XLA tier). Non-power-
of-two worlds admit no valid candidate in these families and simply
yield an empty library — the search never ships a schedule it cannot
prove.

Factored topologies (pod scale)
-------------------------------
TPU pod slices are not uniform rings: a world factors as
inner x outer (L devices per slice on the fast tier, P slices across
the slow tier), and a hop moves along exactly ONE axis of that 2-D
torus. The tiered families (`t_<inner>_<outer>`, allreduce) search
this factored space with TIER-ANNOTATED hops: every hop carries which
tier it crosses (`hop_layout`), is charged against that tier's
`timing.TierLinks` entry (`tiered_phase_costs` /
`predict_spec_tiered` — the `timing.hier_phase_costs` accounting,
generalized to arbitrary hop sequences), and compiles to that tier's
ring permutation (the `ring=(pos, perm)` embedding `hierarchical.
RankMap` provides; outer-major global ranks, g = outer*L + inner).
Members compose one inner reduce-scatter, one outer shard-allreduce,
and one inner allgather — HiCCL's multiply/factor shape — from
per-tier family choices:

  inner  `lg`    log-step halving/doubling over the inner distance
                 tuple (power-of-two L)
         `ring`  the bandwidth-optimal one-chunk-per-hop ring
                 (any L; distance d with gcd(d, L) = 1)
  outer  `exchange` / `rs_ag`  the flat families over the 1/L shard
         `ring`  ring RS + ring AG over the shard

The hand-written striped `HIER_RS_AR_AG` composition is exactly the
`t_ring_ring` member at one stripe — a POINT in this space the search
rediscovers (it scores identical to the composition's serial form) and
then beats with the log-step members wherever per-message latency
matters. Tiered entries arbitrate against the striped composition by
predicted time inside the HIER_ALLREDUCE_MIN_COUNT window
(plan.select_algorithm), never through a separate register.

Scaling the enumeration (w16-w256)
----------------------------------
Distance tuples are enumerated by a branch-and-bound DFS
(`_valid_distance_tuples`): a prefix is pruned the moment its subset
sums collide, so the first valid tuple at w256 costs ~k*world set ops
instead of the lexicographic-combinations scan's millions. Candidates
are scored with the alpha-beta model BEFORE any certification is paid
(`search` scores, beam-prunes to the `beam` best predicted advantages,
then certifies only the survivors): the score is the model's EXACT
serial cost of the emitted DAG — phases never overlap, each hop is
charged to precisely the link it crosses — so pruning by it is
admissible: certification only rejects candidates, never improves
their score, and the kept set always contains the model's best
certifiable candidate. Every survivor still pays the FULL existing
stack (semantics ACCL501-504 + modelcheck ACCL205-207); an uncertified
winner is a loud discard, never shipped.

Everything here is deterministic: no RNG, candidates enumerated in
lexicographic order, so the same inputs always produce the same winner
DAG (pinned by tests/test_synthesis.py).
"""

from __future__ import annotations

import dataclasses
import json
import math
import pathlib
from typing import Any, Callable, Iterator

from ..constants import (
    QUANT_BLOCK_ELEMS,
    QUANT_SCALE_BYTES,
    STREAM_SEG_BYTES,
    Operation,
    ReduceFunction,
)
from ..analysis.diagnostics import Diagnostic
from ..analysis.hopdag import (
    CONST,
    DATA,
    SCALES,
    HopDag,
    Node,
    Piece,
    Value,
    concat_values,
    from_json,
    slice_value,
    to_json,
)

__all__ = [
    "SynthSpec",
    "SynthesisError",
    "instantiate",
    "certify_spec",
    "enumerate_candidates",
    "enumerate_tiered_candidates",
    "search",
    "cost_shape",
    "predict_spec",
    "tiered_phase_costs",
    "predict_spec_tiered",
    "hop_layout",
    "lower_dag",
    "lower_plan",
    "library",
    "library_dir",
    "select_entry",
    "clear_library_cache",
    "hand_written_best",
    "hand_written_tiered_best",
    "SIZE_GRID",
    "SIZE_GRID_LAT",
    "grid_for",
]

# the ops a synthesized schedule can implement today
SYNTH_OPS = (Operation.allreduce, Operation.allgather,
             Operation.reduce_scatter)

# predicted-score grid: payload bytes per (world, size) cell
SIZE_GRID = tuple(1 << k for k in range(10, 25, 2))  # 1 KB .. 16 MB

# the latency grid: every power of two across the 1-64 KiB decode
# regime, where the alpha term — not bytes — is the product. Entries
# searched on this grid carry grid="lat" and a "_lat" key suffix; they
# live behind SYNTH_LATENCY_MAX_COUNT, never the std synth registers,
# so a minimum-step schedule that only wins the small-payload floor
# cannot widen the bandwidth-calibrated windows.
SIZE_GRID_LAT = tuple(1 << k for k in range(10, 17))  # 1 KB .. 64 KB


def grid_for(spec: "SynthSpec") -> tuple[int, ...]:
    """The scoring grid a spec's window is defined over — the ONE
    resolution rule shared by search/--export, verify_library, and
    timing.tuning_crossovers."""
    return SIZE_GRID_LAT if spec.grid == "lat" else SIZE_GRID


class SynthesisError(Exception):
    """A candidate the generator/lowering cannot handle (never converted
    into a silent pass: callers fail loudly or discard the candidate)."""


class _NotRankSymmetric(SynthesisError):
    """The DAG is well-formed but its per-rank programs are not a strict
    rotation of rank 0's — the one condition under which `lower_dag` may
    fall back to the generic masked lowering. Structural malformation
    (cross-rank dataflow, out-of-range ranks) stays a plain
    SynthesisError: the generic lowering would compile it to a WRONG
    program, so it must never be caught as a fallback signal."""


@dataclasses.dataclass(frozen=True)
class SynthSpec:
    """One synthesized schedule family member: enough to regenerate its
    hop-DAG deterministically at any payload size. `key` names the
    library entry (and rides Plan.synth_key into the XLA cache key).

    `tiers=(inner_world, outer_world)` marks a FACTORED-topology member
    (family `t_<inner>_<outer>`): `distances` are then the inner-axis
    tuple and `outer_distances` the outer-axis one, and every hop is
    tier-annotated (`hop_layout`) — charged to its `TierLinks` entry
    and compiled to its RankMap ring permutation. `tiers=()` is the
    flat single-ring space."""

    key: str
    op: str  # "allreduce" | "allgather" | "reduce_scatter"
    world: int
    family: str  # exchange | doubling | halving | rs_ag | t_<ik>_<ok>
    distances: tuple[int, ...]
    wire: str = ""  # "" = payload dtype on the wire, "int8" = quantized
    tiers: tuple[int, ...] = ()  # (inner_world, outer_world) | () flat
    outer_distances: tuple[int, ...] = ()
    grid: str = "std"  # "std" = SIZE_GRID window, "lat" = SIZE_GRID_LAT

    @property
    def scenario(self) -> Operation:
        return Operation[self.op]

    def to_json(self) -> dict:
        d: dict[str, Any] = {
            "key": self.key, "op": self.op, "world": self.world,
            "family": self.family, "distances": list(self.distances),
        }
        if self.wire:
            d["wire"] = self.wire
        if self.tiers:
            d["tiers"] = list(self.tiers)
            d["outer_distances"] = list(self.outer_distances)
        if self.grid != "std":
            d["grid"] = self.grid
        return d

    @classmethod
    def from_json(cls, d: dict) -> "SynthSpec":
        return cls(key=str(d["key"]), op=str(d["op"]),
                   world=int(d["world"]), family=str(d["family"]),
                   distances=tuple(int(x) for x in d["distances"]),
                   wire=str(d.get("wire", "")),
                   tiers=tuple(int(x) for x in d.get("tiers", ())),
                   outer_distances=tuple(
                       int(x) for x in d.get("outer_distances", ())),
                   grid=str(d.get("grid", "std")))


def _spec_key(op: str, world: int, family: str,
              distances: tuple[int, ...], wire: str) -> str:
    d = "_".join(str(x) for x in distances)
    w = f"_{wire}" if wire else ""
    return f"{op}_w{world}_{family}_d{d}{w}"


def _tiered_key(world: int, tiers: tuple[int, int], family: str,
                di: tuple[int, ...], do: tuple[int, ...]) -> str:
    L, P = tiers
    return (f"allreduce_w{world}_t{L}x{P}_{family[2:]}"
            f"_d{'_'.join(map(str, di))}_o{'_'.join(map(str, do))}")


def _tier_kinds(family: str) -> tuple[str, str]:
    """('lg'|'ring', 'exchange'|'rs_ag'|'ring') of a tiered family."""
    if not family.startswith("t_"):
        raise SynthesisError(f"not a tiered family: {family!r}")
    ik, ok = family[2:].split("_", 1)
    if ik not in ("lg", "ring") or ok not in ("exchange", "rs_ag",
                                              "ring"):
        raise SynthesisError(f"unknown tiered family {family!r}")
    return ik, ok


# ---------------------------------------------------------------------------
# Validity: the exact-cover condition shared by every family
# ---------------------------------------------------------------------------


def _subset_sums_distinct(world: int, distances: tuple[int, ...]) -> bool:
    """True iff the 2^k subset sums of `distances` are pairwise distinct
    mod `world` (and therefore, with 2^k == world, cover Z_world exactly
    once). This is the generator-side pruning of the wrong-result
    classes: a collision is a double-count (ACCL503) and a shortfall a
    missing contribution (ACCL502) — the certifier re-proves the same
    property on the emitted DAG, so the pruning can never silently
    diverge from the proof."""
    sums = {0}
    for d in distances:
        shifted = {(s + d) % world for s in sums}
        if sums & shifted:
            return False
        sums |= shifted
    return len(sums) == world


def coverage_sets(world: int,
                  distances: tuple[int, ...]) -> list[set[int]]:
    """S_0 .. S_k with S_i the relative offsets reachable after step i
    (S_0 = {0}, S_i = S_{i-1} u (S_{i-1} + d_i))."""
    sets = [{0}]
    for d in distances:
        cur = sets[-1]
        sets.append(cur | {(s + d) % world for s in cur})
    return sets


def _valid_distance_tuples(world: int, k: int) -> Iterator[tuple[int, ...]]:
    """Strictly-increasing k-tuples whose 2^k subset sums are pairwise
    distinct mod `world`, in lexicographic order — enumerated by
    branch-and-bound DFS: a prefix dies the moment its sums collide, so
    the first valid tuple at w256 costs ~k*world set extensions instead
    of the millions of complete tuples a combinations scan would build
    and re-check (the scaling lever for w16-w256 enumeration)."""

    def rec(start: int, sums: frozenset, prefix: tuple[int, ...],
            ) -> Iterator[tuple[int, ...]]:
        if len(prefix) == k:
            yield prefix
            return
        for d in range(start, world):
            shifted = {(s + d) % world for s in sums}
            if sums & shifted:
                continue  # collision: every extension collides too
            yield from rec(d + 1, frozenset(sums | shifted),
                           prefix + (d,))

    yield from rec(1, frozenset({0}), ())


def _first_valid_tuple(world: int) -> tuple[int, ...] | None:
    """The lexicographically first valid k=log2(world) tuple (the
    dominance representative: valid tuples within a family share the
    per-step byte profile, so they are cost-identical)."""
    if world < 2 or world & (world - 1):
        return None
    k = world.bit_length() - 1
    return next(_valid_distance_tuples(world, k), None)


# ---------------------------------------------------------------------------
# DAG generation (rank-symmetric by construction)
# ---------------------------------------------------------------------------


class _Builder:
    """Emit nodes in a strict per-step, rank-major order so position
    p*world + r is rank r's p-th node — the layout `lower_dag`'s
    rotational-symmetry extraction relies on."""

    def __init__(self, world: int):
        self.world = world
        self.nodes: list[Node] = []

    def emit_round(self, make: Callable[[int, int], Node]) -> list[int]:
        """One rank-major round: `make(rank, id)` for every rank;
        returns the new node ids (index by rank)."""
        ids = []
        for r in range(self.world):
            nid = len(self.nodes)
            self.nodes.append(make(r, nid))
            ids.append(nid)
        return ids


class _FlatAxis:
    """The single-ring geometry: positions ARE global ranks, a hop at
    distance d is the full-ring rotation g -> g + d."""

    tier = ""

    def __init__(self, world: int):
        self.world = world
        self.nranks = world

    def pos(self, g: int) -> int:
        return g

    def peer(self, g: int, d: int) -> int:
        return (g + d) % self.world


class _InnerAxis:
    """The fast tier of an outer-major (g = outer*L + inner) factored
    world: a hop rotates every slice's inner ring in lockstep — the
    global pairs are exactly `hierarchical.RankMap.inner_perm(d)`."""

    tier = "inner"

    def __init__(self, L: int, P: int):
        self.world = L
        self.nranks = L * P
        self._L = L

    def pos(self, g: int) -> int:
        return g % self._L

    def peer(self, g: int, d: int) -> int:
        return g - g % self._L + (g % self._L + d) % self._L


class _OuterAxis:
    """The slow tier: a hop rotates every inner row's outer ring in
    lockstep — the global pairs of `RankMap.outer_perm(d)`."""

    tier = "outer"

    def __init__(self, L: int, P: int):
        self.world = P
        self.nranks = L * P
        self._L = L

    def pos(self, g: int) -> int:
        return g // self._L

    def peer(self, g: int, d: int) -> int:
        return ((g // self._L + d) % self.world) * self._L + g % self._L


def _scales_len(n: int) -> int:
    return max(1, math.ceil(n / QUANT_BLOCK_ELEMS))


def _exchange_core(b: _Builder, axis, distances: tuple[int, ...],
                   count: int, func: str, acc: list[Value],
                   hop_base: int, wire: str) -> tuple[list[Value], int]:
    """allreduce exchange along one axis: every rank sends its running
    partial `acc[g]` distance d down the axis and folds the arrival
    from distance -d. Returns (final partials, next free hop). The flat
    family and the tiered outer-`exchange` phase share this emitter —
    only the axis geometry differs."""
    w = axis.world
    hop = hop_base
    for d in distances:
        if wire == "int8":
            enc = b.emit_round(lambda g, i: Node(
                id=i, kind="encode", rank=g, length=count,
                value=acc[g],
                scales_len=_scales_len(count), dtype="int8"))
            b.emit_round(lambda g, i: Node(
                id=i, kind="send", rank=g, length=count,
                value=(Piece(count, enc[g]),), hop=hop,
                peer=axis.peer(g, d)))
            b.emit_round(lambda g, i: Node(
                id=i, kind="send", rank=g, length=_scales_len(count),
                value=(Piece(_scales_len(count), enc[g], 0, SCALES),),
                hop=hop + 1, peer=axis.peer(g, d)))
            rq = b.emit_round(lambda g, i: Node(
                id=i, kind="recv", rank=g, length=count, hop=hop,
                peer=axis.peer(g, -d)))
            rs = b.emit_round(lambda g, i: Node(
                id=i, kind="recv", rank=g, length=_scales_len(count),
                hop=hop + 1, peer=axis.peer(g, -d)))
            dec = b.emit_round(lambda g, i: Node(
                id=i, kind="decode", rank=g, length=count,
                value=(Piece(count, rq[g]),),
                value2=(Piece(_scales_len(count), rs[g]),)))
            ids = b.emit_round(lambda g, i: Node(
                id=i, kind="combine", rank=g, length=count,
                value=acc[g],
                value2=(Piece(count, dec[g]),), func=func))
            acc = [(Piece(count, ids[g]),) for g in range(axis.nranks)]
            hop += 2
        else:
            b.emit_round(lambda g, i: Node(
                id=i, kind="send", rank=g, length=count,
                value=acc[g], hop=hop, peer=axis.peer(g, d)))
            rv = b.emit_round(lambda g, i: Node(
                id=i, kind="recv", rank=g, length=count, hop=hop,
                peer=axis.peer(g, -d)))
            ids = b.emit_round(lambda g, i: Node(
                id=i, kind="combine", rank=g, length=count,
                value=acc[g],
                value2=(Piece(count, rv[g]),), func=func))
            acc = [(Piece(count, ids[g]),) for g in range(axis.nranks)]
            hop += 1
    return acc, hop


def _exchange_dag(spec: SynthSpec, count: int, func: str) -> HopDag:
    """allreduce: acc[r] folds the arrival from r - d_i each step."""
    w = spec.world
    b = _Builder(w)
    args = b.emit_round(lambda r, i: Node(
        id=i, kind="arg", rank=r, length=count, arg=0, dtype="float32"))
    acc: list[Value] = [(Piece(count, args[r]),) for r in range(w)]
    acc, _hop = _exchange_core(b, _FlatAxis(w), spec.distances, count,
                               func, acc, 0, spec.wire)
    outputs: tuple[Value, ...] = tuple(acc[r] for r in range(w))
    return HopDag(world=w, n_in=1, in_elems=count, out_elems=count,
                  nodes=tuple(b.nodes), outputs=outputs)


def _doubling_core(b: _Builder, axis, distances: tuple[int, ...],
                   count: int, held: list[dict[int, Value]],
                   hop_base: int) -> tuple[list[dict[int, Value]], int]:
    """allgather doubling along one axis: each rank relays EVERY chunk
    held so far; `held[g]` maps origin axis POSITION -> that origin's
    chunk Value on rank g. Returns (full held maps, next free hop)."""
    w = axis.world
    sets = coverage_sets(w, distances)
    for step, d in enumerate(distances):
        rel = sorted(sets[step])  # canonical message layout
        msg_len = len(rel) * count

        def payload(g: int) -> Value:
            out: tuple[Piece, ...] = ()
            for s in rel:
                out = out + held[g][(axis.pos(g) - s) % w]
            return out

        b.emit_round(lambda g, i: Node(
            id=i, kind="send", rank=g, length=msg_len,
            value=payload(g), hop=hop_base + step, peer=axis.peer(g, d)))
        rv = b.emit_round(lambda g, i: Node(
            id=i, kind="recv", rank=g, length=msg_len,
            hop=hop_base + step, peer=axis.peer(g, -d)))
        for g in range(axis.nranks):
            for j, s in enumerate(rel):
                origin = (axis.pos(g) - d - s) % w
                held[g][origin] = (
                    Piece(count, rv[g], j * count),)
    return held, hop_base + len(distances)


def _doubling_dag(spec: SynthSpec, count: int) -> HopDag:
    """allgather: each rank relays every chunk held so far; held sets
    are `coverage_sets` in relative offsets (held chunk = rank - s)."""
    w = spec.world
    b = _Builder(w)
    args = b.emit_round(lambda r, i: Node(
        id=i, kind="arg", rank=r, length=count, arg=0, dtype="float32"))
    # held[r][origin] = Value holding origin's chunk on rank r
    held: list[dict[int, Value]] = [
        {r: (Piece(count, args[r]),)} for r in range(w)]
    held, _hop = _doubling_core(b, _FlatAxis(w), spec.distances, count,
                                held, 0)
    outputs = []
    for r in range(w):
        v: tuple[Piece, ...] = ()
        for origin in range(w):
            v = v + held[r][origin]
        outputs.append(v)
    return HopDag(world=w, n_in=1, in_elems=count,
                  out_elems=w * count, nodes=tuple(b.nodes),
                  outputs=tuple(outputs))


def _halving_core(b: _Builder, axis, distances: tuple[int, ...],
                  count: int, func: str,
                  part: list[dict[int, Value]],
                  hop_base: int) -> tuple[list[dict[int, Value]], int]:
    """reduce_scatter halving along one axis: position p hands off
    partials for chunks p + d + A_i to position p + d each step;
    responsibility sets A_i halve (A_i = S_{k-i} of the reversed
    distance sequence). `part[g]` maps ABSOLUTE axis chunk -> partial
    Value; on return only position g's kept chunks remain. Returns
    (part, next free hop)."""
    w = axis.world
    k = len(distances)
    # A_i chain: A_k = {0}; A_{i-1} = A_i u (A_i + d_i)
    A: list[set[int]] = [set() for _ in range(k + 1)]
    A[k] = {0}
    for i in range(k, 0, -1):
        d = distances[i - 1]
        A[i - 1] = A[i] | {(a + d) % w for a in A[i]}
    for i in range(1, k + 1):
        d = distances[i - 1]
        send_rel = sorted((a + d) % w for a in A[i])
        msg_len = len(send_rel) * count

        def payload(g: int) -> Value:
            out: tuple[Piece, ...] = ()
            for a in send_rel:
                out = out + part[g][(axis.pos(g) + a) % w]
            return out

        b.emit_round(lambda g, i_: Node(
            id=i_, kind="send", rank=g, length=msg_len,
            value=payload(g), hop=hop_base + i - 1,
            peer=axis.peer(g, d)))
        rv = b.emit_round(lambda g, i_: Node(
            id=i_, kind="recv", rank=g, length=msg_len,
            hop=hop_base + i - 1, peer=axis.peer(g, -d)))
        # arrival from pos-d carries chunks (pos-d) + send_rel, i.e.
        # pos + a for a = send_rel - d (mod w) — all kept chunks; fold
        # each slice into the kept partial, rank-major per arrival slot
        # so symmetry holds
        arr_rel = [(a - d) % w for a in send_rel]
        for j, a in enumerate(arr_rel):
            ids = b.emit_round(lambda g, i_: Node(
                id=i_, kind="combine", rank=g, length=count,
                value=part[g][(axis.pos(g) + a) % w],
                value2=(Piece(count, rv[g], j * count),), func=func))
            for g in range(axis.nranks):
                part[g][(axis.pos(g) + a) % w] = (Piece(count, ids[g]),)
        # drop handed-off chunks (no longer this position's duty)
        for g in range(axis.nranks):
            part[g] = {c: v for c, v in part[g].items()
                       if (c - axis.pos(g)) % w in A[i]}
    return part, hop_base + k


def _ring_rs_core(b: _Builder, axis, d: int, count: int, func: str,
                  part: list[dict[int, Value]],
                  hop_base: int) -> tuple[list[dict[int, Value]], int]:
    """Bandwidth-optimal ring reduce-scatter along one axis — the
    hand-written ring's structure as a searchable point: w-1 steps each
    moving exactly ONE chunk partial distance d down the axis. At step
    s position p sends its partial of chunk p - s*d and folds the
    arrival into chunk p - (s+1)*d; after w-1 steps position p owns
    chunk p fully reduced (gcd(d, w) = 1 walks the whole ring)."""
    w = axis.world
    hop = hop_base
    for s in range(1, w):
        b.emit_round(lambda g, i: Node(
            id=i, kind="send", rank=g, length=count,
            value=part[g][(axis.pos(g) - s * d) % w], hop=hop,
            peer=axis.peer(g, d)))
        rv = b.emit_round(lambda g, i: Node(
            id=i, kind="recv", rank=g, length=count, hop=hop,
            peer=axis.peer(g, -d)))
        ids = b.emit_round(lambda g, i: Node(
            id=i, kind="combine", rank=g, length=count,
            value=part[g][(axis.pos(g) - (s + 1) * d) % w],
            value2=(Piece(count, rv[g]),), func=func))
        for g in range(axis.nranks):
            part[g][(axis.pos(g) - (s + 1) * d) % w] = (
                Piece(count, ids[g]),)
        hop += 1
    return part, hop


def _ring_ag_core(b: _Builder, axis, d: int, count: int,
                  held: list[dict[int, Value]],
                  hop_base: int) -> tuple[list[dict[int, Value]], int]:
    """Ring allgather along one axis: w-1 steps each relaying the chunk
    received the previous step (at step 1 the own chunk), so every
    position holds every origin after the walk."""
    w = axis.world
    hop = hop_base
    for s in range(1, w):
        b.emit_round(lambda g, i: Node(
            id=i, kind="send", rank=g, length=count,
            value=held[g][(axis.pos(g) - (s - 1) * d) % w], hop=hop,
            peer=axis.peer(g, d)))
        rv = b.emit_round(lambda g, i: Node(
            id=i, kind="recv", rank=g, length=count, hop=hop,
            peer=axis.peer(g, -d)))
        for g in range(axis.nranks):
            held[g][(axis.pos(g) - s * d) % w] = (Piece(count, rv[g]),)
        hop += 1
    return held, hop


def _halving_dag(spec: SynthSpec, count: int, func: str,
                 b: _Builder | None = None,
                 part_in: list[dict[int, Value]] | None = None,
                 hop_base: int = 0) -> tuple[
                     "_Builder", list[dict[int, Value]]]:
    """reduce_scatter wrapper over `_halving_core` on the flat axis;
    returns the builder and per-rank {abs_chunk: partial Value} so
    `rs_ag` can continue the same DAG."""
    w = spec.world
    if b is None:
        b = _Builder(w)
        args = b.emit_round(lambda r, i: Node(
            id=i, kind="arg", rank=r, length=w * count, arg=0,
            dtype="float32"))
        part_in = [
            {c: (Piece(count, args[r], c * count),) for c in range(w)}
            for r in range(w)]
    assert b is not None and part_in is not None
    part, _hop = _halving_core(b, _FlatAxis(w), spec.distances, count,
                               func, part_in, hop_base)
    return b, part


def _reduce_scatter_dag(spec: SynthSpec, count: int, func: str) -> HopDag:
    b, part = _halving_dag(spec, count, func)
    w = spec.world
    outputs = tuple(part[r][r] for r in range(w))
    return HopDag(world=w, n_in=1, in_elems=w * count, out_elems=count,
                  nodes=tuple(b.nodes), outputs=outputs)


def _rs_ag_dag(spec: SynthSpec, count: int, func: str) -> HopDag:
    """allreduce = halving reduce_scatter + doubling allgather over the
    same distance set (payload padded to a world multiple upstream by
    the chunking rule in `instantiate`)."""
    w = spec.world
    if count % w:
        raise SynthesisError(
            f"rs_ag payload must chunk by world ({count} % {w})")
    chunk = count // w
    k = len(spec.distances)
    b, part = _halving_dag(spec, chunk, func, hop_base=0)
    # allgather phase: start from the reduced chunk, doubling relays
    held: list[dict[int, Value]] = [
        {r: part[r][r]} for r in range(w)]
    held, _hop = _doubling_core(b, _FlatAxis(w), spec.distances, chunk,
                                held, k)
    outputs = []
    for r in range(w):
        v: tuple[Piece, ...] = ()
        for origin in range(w):
            v = v + held[r][origin]
        outputs.append(v)
    return HopDag(world=w, n_in=1, in_elems=count, out_elems=count,
                  nodes=tuple(b.nodes), outputs=tuple(outputs))


def _tiered_dag(spec: SynthSpec, count: int, func: str) -> HopDag:
    """Factored-topology allreduce over outer-major global ranks
    (g = outer*L + inner): inner reduce-scatter -> outer allreduce of
    the 1/L shard (the ONLY bytes that ever cross the slow tier) ->
    inner allgather, each phase built from the per-tier family the spec
    names. Every hop moves along exactly one axis of the (L, P) torus —
    the tier annotation `hop_layout` records and the per-tier cost
    accounting charges."""
    L, P = spec.tiers
    w = L * P
    if count % (L * P):
        raise SynthesisError(
            f"{spec.key}: tiered payload must chunk by inner*outer "
            f"({count} % {L * P})")
    cpk = count // L  # one inner chunk == the outer shard
    ik, ok = _tier_kinds(spec.family)
    inner = _InnerAxis(L, P)
    outer = _OuterAxis(L, P)
    b = _Builder(w)
    args = b.emit_round(lambda g, i: Node(
        id=i, kind="arg", rank=g, length=count, arg=0, dtype="float32"))
    part: list[dict[int, Value]] = [
        {c: (Piece(cpk, args[g], c * cpk),) for c in range(L)}
        for g in range(w)]
    hop = 0
    if ik == "ring":
        part, hop = _ring_rs_core(b, inner, spec.distances[0], cpk,
                                  func, part, hop)
    else:
        part, hop = _halving_core(b, inner, spec.distances, cpk, func,
                                  part, hop)
    shard: list[Value] = [part[g][inner.pos(g)] for g in range(w)]
    if ok == "exchange":
        shard, hop = _exchange_core(b, outer, spec.outer_distances,
                                    cpk, func, shard, hop, "")
    else:
        ocpk = cpk // P
        opart: list[dict[int, Value]] = [
            {c: slice_value(shard[g], c * ocpk, ocpk) for c in range(P)}
            for g in range(w)]
        if ok == "ring":
            od = spec.outer_distances[0]
            opart, hop = _ring_rs_core(b, outer, od, ocpk, func,
                                       opart, hop)
            held_o: list[dict[int, Value]] = [
                {outer.pos(g): opart[g][outer.pos(g)]} for g in range(w)]
            held_o, hop = _ring_ag_core(b, outer, od, ocpk, held_o, hop)
        else:  # rs_ag
            opart, hop = _halving_core(b, outer, spec.outer_distances,
                                       ocpk, func, opart, hop)
            held_o = [
                {outer.pos(g): opart[g][outer.pos(g)]} for g in range(w)]
            held_o, hop = _doubling_core(b, outer, spec.outer_distances,
                                         ocpk, held_o, hop)
        shard = [concat_values(*(held_o[g][c] for c in range(P)))
                 for g in range(w)]
    held: list[dict[int, Value]] = [
        {inner.pos(g): shard[g]} for g in range(w)]
    if ik == "ring":
        held, hop = _ring_ag_core(b, inner, spec.distances[0], cpk,
                                  held, hop)
    else:
        held, hop = _doubling_core(b, inner, spec.distances, cpk,
                                   held, hop)
    outputs = tuple(concat_values(*(held[g][c] for c in range(L)))
                    for g in range(w))
    return HopDag(world=w, n_in=1, in_elems=count, out_elems=count,
                  nodes=tuple(b.nodes), outputs=outputs)


def _check_axis_family(spec: SynthSpec, kind: str, axis_world: int,
                       distances: tuple[int, ...], what: str) -> None:
    """Per-tier validity: the log-step families need the exact-cover
    subset-sum condition over THEIR axis; a ring needs one distance
    coprime to the axis extent (the walk must visit every position)."""
    if kind in ("lg", "exchange", "rs_ag"):
        if not _subset_sums_distinct(axis_world, distances):
            raise SynthesisError(
                f"{spec.key}: {what} distances {distances} do not "
                f"cover Z_{axis_world} exactly once — not a valid "
                "schedule")
    else:  # ring
        if len(distances) != 1 or math.gcd(distances[0],
                                           axis_world) != 1:
            raise SynthesisError(
                f"{spec.key}: {what} ring distance {distances} must be "
                f"a single generator of Z_{axis_world}")


def instantiate(spec: SynthSpec, count: int,
                func: str = "sum") -> HopDag:
    """Deterministically regenerate `spec`'s hop-DAG for a concrete
    per-rank element count. The same generator builds the committed
    canonical instance, the fuzz instances and the lowered program's
    source DAG — there is exactly one structure to certify."""
    if count <= 0:
        raise SynthesisError(f"count must be positive, got {count}")
    if spec.tiers:
        L, P = spec.tiers
        if L * P != spec.world or L < 2 or P < 2:
            raise SynthesisError(
                f"{spec.key}: tiers {spec.tiers} do not factor world "
                f"{spec.world}")
        ik, ok = _tier_kinds(spec.family)
        _check_axis_family(spec, ik, L, spec.distances, "inner")
        _check_axis_family(spec, ok, P, spec.outer_distances, "outer")
        return _tiered_dag(spec, count, func)
    if not _subset_sums_distinct(spec.world, spec.distances):
        raise SynthesisError(
            f"{spec.key}: distances {spec.distances} do not cover "
            f"Z_{spec.world} exactly once — not a valid schedule")
    if spec.family == "exchange":
        return _exchange_dag(spec, count, func)
    if spec.family == "doubling":
        return _doubling_dag(spec, count)
    if spec.family == "halving":
        return _reduce_scatter_dag(spec, count, func)
    if spec.family == "rs_ag":
        return _rs_ag_dag(spec, count, func)
    raise SynthesisError(f"unknown family {spec.family!r}")


# canonical counts for the committed/certified instances: big enough to
# exercise multi-chunk layouts, small enough to keep fixtures readable
CANONICAL_COUNT = {"exchange": 64, "doubling": 16, "halving": 16,
                   "rs_ag": 64}


def canonical_count(spec: SynthSpec) -> int:
    if spec.tiers:
        # must chunk by inner*outer (the 2-D torus chunking rule)
        L, P = spec.tiers
        return 8 * L * P
    base = CANONICAL_COUNT[spec.family]
    if spec.family == "rs_ag":
        return max(base, spec.world)  # must chunk by world
    return base


# ---------------------------------------------------------------------------
# Certification: the existing prove stack, candidate by candidate
# ---------------------------------------------------------------------------


def _call_options(spec: SynthSpec, count: int,
                  func: ReduceFunction = ReduceFunction.SUM) -> Any:
    from ..constants import DataType
    from ..descriptor import CallOptions

    return CallOptions(scenario=spec.scenario, count=count,
                       function=int(func), data_type=DataType.float32)


def certify_dag(dag: HopDag, spec: SynthSpec, count: int,
                func: ReduceFunction = ReduceFunction.SUM,
                ) -> list[Diagnostic]:
    """Run one candidate instance through the full prove stack:
    semantic certification (ACCL501-504) against the declared
    collective, the canonical protocol simulation, and the exhaustive-
    interleaving model checker (ACCL205-207). Returns every diagnostic;
    an empty list is the only shippable verdict."""
    from ..analysis import semantics
    from ..analysis.hopdag import rank_programs, validate_order
    from ..analysis.linter import SequenceLinter
    from ..analysis.protocol import simulate

    opts = _call_options(spec, count, func)
    spec_map = semantics.collective_spec(opts, dag.world)
    diags = list(validate_order(dag))
    diags += semantics.certify(dag, spec_map, spec.op)
    programs = rank_programs(dag)
    diags += simulate(programs, blocking_sends=False)
    if not diags:
        diags += SequenceLinter(dag.world).check_interleavings(programs)
    return diags


def certify_spec(spec: SynthSpec,
                 counts: tuple[int, ...] = (),
                 ) -> tuple[bool, list[Diagnostic]]:
    """Certify a spec at its canonical count (and any extra counts).
    False means DISCARD: the caller must not ship the candidate."""
    all_diags: list[Diagnostic] = []
    for count in (canonical_count(spec),) + tuple(counts):
        try:
            dag = instantiate(spec, count)
        except SynthesisError:
            return False, all_diags
        all_diags += certify_dag(dag, spec, count)
        if spec.op == "allreduce" and spec.wire != "int8":
            # MAX folds certify too (idempotent reduction class)
            dag_max = instantiate(spec, count, func="max")
            all_diags += certify_dag(dag_max, spec, count,
                                     ReduceFunction.MAX)
    return not all_diags, all_diags


# ---------------------------------------------------------------------------
# Scoring: alpha-beta prediction of a spec, same posture as timing.py
# ---------------------------------------------------------------------------


def _wire_bytes_per_elem(spec: SynthSpec, elem_bytes: int) -> float:
    if spec.wire == "int8":
        return 1.0 + QUANT_SCALE_BYTES / QUANT_BLOCK_ELEMS
    return float(elem_bytes)


def hop_layout(spec: SynthSpec) -> list[tuple[str, int]]:
    """(tier, axis_distance) per hop channel of a tiered spec, in hop
    order — THE tier annotation of the factored search space: each hop
    is charged against its `TierLinks` entry (`tiered_phase_costs`) and
    compiles to its tier's ring permutation (`lower_plan` cross-checks
    the emitted DAG's send pairs against `RankMap.inner_perm` /
    `outer_perm` at exactly these distances)."""
    if not spec.tiers:
        raise SynthesisError(f"{spec.key} is not a tiered spec")
    L, P = spec.tiers
    ik, ok = _tier_kinds(spec.family)
    inner_hops = ([("inner", spec.distances[0])] * (L - 1)
                  if ik == "ring"
                  else [("inner", d) for d in spec.distances])
    if ok == "exchange":
        outer_hops = [("outer", d) for d in spec.outer_distances]
    elif ok == "rs_ag":
        outer_hops = [("outer", d) for d in spec.outer_distances] * 2
    else:  # ring RS + ring AG
        outer_hops = [("outer", spec.outer_distances[0])] * (2 * (P - 1))
    # the inner allgather mirrors the inner reduce-scatter's hop count
    return inner_hops + outer_hops + inner_hops


def _tiered_step_elems(spec: SynthSpec,
                       count: int) -> list[tuple[str, int]]:
    """(tier, elements-sent-per-rank) per hop of a tiered spec, in hop
    order (count padded up to the inner*outer chunking the DAG
    requires — the same rule `lower_plan` applies)."""
    L, P = spec.tiers
    padded = count + (-count) % (L * P)
    cpk = padded // L
    ik, ok = _tier_kinds(spec.family)
    k_i = len(spec.distances)
    if ik == "ring":
        inner_rs = [cpk] * (L - 1)
        inner_ag = [cpk] * (L - 1)
    else:
        inner_rs = [cpk * (1 << (k_i - i)) // 2 for i in range(k_i)]
        inner_ag = [cpk * (1 << i) for i in range(k_i)]
    if ok == "exchange":
        outer = [cpk] * len(spec.outer_distances)
    else:
        ocpk = cpk // P
        if ok == "ring":
            outer = [ocpk] * (2 * (P - 1))
        else:
            k_o = len(spec.outer_distances)
            outer = ([ocpk * (1 << (k_o - i)) // 2 for i in range(k_o)]
                     + [ocpk * (1 << i) for i in range(k_o)])
    return ([("inner", e) for e in inner_rs]
            + [("outer", e) for e in outer]
            + [("inner", e) for e in inner_ag])


def _step_elems(spec: SynthSpec, count: int) -> list[int]:
    """Per-step elements each rank sends (every rank sends the same —
    rank symmetry). `count` follows the descriptor convention of the
    op: allgather = chunk elems, reduce_scatter = output chunk elems,
    allreduce = payload elems. Tiered specs flatten their per-tier hop
    profile (the single-link fallback `cost_shape` documents)."""
    if spec.tiers:
        return [e for _t, e in _tiered_step_elems(spec, count)]
    w = spec.world
    k = len(spec.distances)
    if spec.family == "exchange":
        return [count] * k
    if spec.family == "doubling":
        return [count * (1 << i) for i in range(k)]
    if spec.family == "halving":
        return [count * (1 << (k - i)) // 2 for i in range(k)]
    if spec.family == "rs_ag":
        chunk = max(count // w, 1)
        rs = [chunk * (1 << (k - i)) // 2 for i in range(k)]
        ag = [chunk * (1 << i) for i in range(k)]
        return rs + ag
    raise SynthesisError(f"unknown family {spec.family!r}")


def cost_shape(spec: SynthSpec, count: int, elem_bytes: int,
               *, aggregate: bool = False) -> tuple[float, float]:
    """(messages, bytes) for one call of the synthesized schedule —
    critical path by default (every step is one full-ring permutation:
    all ranks move concurrently, so the critical path is the per-rank
    chain), aggregate = summed over ranks (the serialized-host shape
    timing.coefficients_aggregate documents). Bytes are WIRE bytes;
    jumbo-segment streaming charges one message per STREAM_SEG_BYTES
    like the hand-written eager shapes."""
    wb = _wire_bytes_per_elem(spec, elem_bytes)
    msgs = 0.0
    nbytes = 0.0
    for elems in _step_elems(spec, count):
        step_bytes = elems * wb
        msgs += max(1, math.ceil(step_bytes / STREAM_SEG_BYTES))
        nbytes += step_bytes
    if aggregate:
        return msgs * spec.world, nbytes * spec.world
    return msgs, nbytes


def predict_spec(link: Any, spec: SynthSpec, count: int,
                 elem_bytes: int, *, aggregate: bool = False) -> float:
    """Expected seconds under LinkParams `link` (timing.predict's synth
    counterpart; timing.coefficients routes SYNTHESIZED plans here).
    For a tiered spec this is the single-link FALLBACK (both tiers
    charged to one link); the calibrated per-tier prediction is
    `predict_spec_tiered`."""
    m, b = cost_shape(spec, count, elem_bytes, aggregate=aggregate)
    return float(link.seconds(m, b))


def tiered_phase_costs(spec: SynthSpec, count: int, elem_bytes: int,
                       *, aggregate: bool = False,
                       ) -> list[tuple[str, float, float]]:
    """(tier, messages, bytes) of a tiered spec's hops, summed per tier
    — the `timing.hier_phase_costs` accounting generalized to arbitrary
    tier-annotated hop sequences: every hop's wire bytes are charged to
    exactly the link it crosses. aggregate=True sums over all ranks
    (the serialized-host regime); default is the per-link critical
    path (every hop is a full-torus permutation — all ranks move
    concurrently)."""
    wb = _wire_bytes_per_elem(spec, elem_bytes)
    per: dict[str, list[float]] = {"inner": [0.0, 0.0],
                                   "outer": [0.0, 0.0]}
    for tier, elems in _tiered_step_elems(spec, count):
        step_bytes = elems * wb
        per[tier][0] += max(1, math.ceil(step_bytes / STREAM_SEG_BYTES))
        per[tier][1] += step_bytes
    scale = spec.world if aggregate else 1
    return [("inner", per["inner"][0] * scale, per["inner"][1] * scale),
            ("outer", per["outer"][0] * scale, per["outer"][1] * scale)]


def predict_spec_tiered(links: Any, spec: SynthSpec, count: int,
                        elem_bytes: int, *,
                        aggregate: bool = False) -> float:
    """Expected seconds for a tiered spec under a `timing.TierLinks`
    calibration: the phases serialize (the emitted DAG never overlaps
    tiers), so the prediction is the exact per-tier alpha-beta sum —
    which is also why it is an ADMISSIBLE pruning bound for the search:
    it is the model's exact cost of the candidate, not a relaxation,
    and certification can only reject candidates, never improve this
    score."""
    return float(sum(
        links.of(tier).seconds(m, b)
        for tier, m, b in tiered_phase_costs(spec, count, elem_bytes,
                                             aggregate=aggregate)))


def hand_written_best(link: Any, op: Operation, count: int,
                      elem_bytes: int, world: int, *,
                      rx_buf_bytes: int = 4096,
                      aggregate: bool = False,
                      wire: str = "") -> float:
    """The best PREDICTED hand-written time for this cell: the default
    selection plus every tuning-reachable alternative (the rendezvous
    compositions/trees the registers can force), so 'beats every
    hand-written algorithm' is checked against the whole zoo, not just
    the default pick. `wire="int8"` scores against the hand-written
    quantized ring (the baseline an int8 synthesized entry must
    beat)."""
    from ..constants import (
        DEFAULT_EAGER_RX_BUF_SIZE,
        DEFAULT_MAX_EAGER_SIZE,
        DEFAULT_MAX_RENDEZVOUS_SIZE,
        CompressionFlags,
        DataType,
        TuningParams,
    )
    from .plan import select_algorithm
    from .timing import predict

    comp = (CompressionFlags.ETH_COMPRESSED if wire
            else CompressionFlags.NO_COMPRESSION)
    cdt = DataType.int8 if wire == "int8" else DataType.none
    tunings = (
        TuningParams.default(DEFAULT_MAX_RENDEZVOUS_SIZE),
        # force the composition / tree branches so they compete
        TuningParams(allreduce_composition_max_count=1 << 62),
        TuningParams(bcast_flat_tree_max_ranks=2,
                     reduce_flat_tree_max_ranks=2,
                     reduce_flat_tree_max_count=64),
    )
    best = math.inf
    for tuning in tunings:
        plan = select_algorithm(
            op, count, elem_bytes, world, comp,
            max_eager_size=DEFAULT_MAX_EAGER_SIZE,
            eager_rx_buf_size=DEFAULT_EAGER_RX_BUF_SIZE,
            tuning=tuning, compress_dtype=cdt)
        t = predict(link, op, plan, count, elem_bytes, world,
                    rx_buf_bytes=rx_buf_bytes, aggregate=aggregate)
        best = min(best, t)
    return best


def hand_written_tiered_best(tier_links: Any, count: int,
                             elem_bytes: int,
                             tiers: tuple[int, int], *,
                             rx_buf_bytes: int = 4096,
                             aggregate: bool = False) -> float:
    """The best PREDICTED two-tier-aware hand-written time for this
    cell: the striped hierarchical composition at the cost model's own
    stripe count (timing.best_stripes' argmin — the strongest
    hand-written two-tier opponent, pipelining included) and the flat
    zoo charged to the OUTER link (every flat ring step crosses the
    slow tier — the same accounting the hier crossover scan uses). A
    tiered synthesized entry ships only when it beats BOTH."""
    from .plan import Algorithm, Plan, Protocol
    from .timing import best_stripes, predict_tiered

    L, P = tiers
    s = best_stripes(tier_links, count, elem_bytes, L, P,
                     aggregate=aggregate)
    hplan = Plan(Protocol.EAGER, Algorithm.HIER_RS_AR_AG, count, 1,
                 inner_world=L, outer_world=P, stripes=s)
    t_hier = predict_tiered(tier_links, hplan, count, elem_bytes,
                            aggregate=aggregate)
    t_flat = hand_written_best(tier_links.outer, Operation.allreduce,
                               count, elem_bytes, L * P,
                               rx_buf_bytes=rx_buf_bytes,
                               aggregate=aggregate)
    return min(t_hier, t_flat)


# ---------------------------------------------------------------------------
# Search: enumerate -> prune -> certify -> score
# ---------------------------------------------------------------------------


def enumerate_candidates(op: Operation, world: int,
                         include_wire: bool = True,
                         ) -> Iterator[SynthSpec]:
    """All valid FLAT candidates for (op, world) in deterministic
    lexicographic order. Distances are strictly increasing (two equal
    distances always collide in the subset-sum check) and k is pinned
    to log2(world) by the exact-cover condition; candidates with the
    same per-step byte profile are cost-equivalent, so dominance
    pruning keeps only the lexicographically first of each family —
    found by the branch-and-bound DFS (`_valid_distance_tuples`), which
    is what keeps enumeration O(k*world) at w64-w256 instead of the
    combinations scan's millions of dead tuples."""
    if world < 2 or world & (world - 1):
        return  # the symmetric families need 2^k == world
    op_name = op.name
    families = {"allreduce": ("exchange", "rs_ag"),
                "allgather": ("doubling",),
                "reduce_scatter": ("halving",)}[op_name]
    distances = _first_valid_tuple(world)
    if distances is None:
        return
    for family in families:
        yield SynthSpec(
            key=_spec_key(op_name, world, family, distances, ""),
            op=op_name, world=world, family=family,
            distances=distances)
        if include_wire and family == "exchange":
            yield SynthSpec(
                key=_spec_key(op_name, world, family, distances,
                              "int8"),
                op=op_name, world=world, family=family,
                distances=distances, wire="int8")


def enumerate_tiered_candidates(world: int, tiers: tuple[int, int],
                                ) -> Iterator[SynthSpec]:
    """All tiered allreduce candidates for one (inner, outer) factoring
    of `world`, deterministic order: the per-tier family product
    {lg, ring} x {exchange, rs_ag, ring}, each at its dominance-
    representative distance tuple. The log-step kinds need a
    power-of-two axis; the ring kinds serve ANY axis extent (d = 1),
    which is what keeps non-power-of-two pod slices searchable.
    Degenerate duplicates are skipped (at an axis extent of 2 the ring
    and the log-step member emit the same hops; ring == rs_ag on the
    outer shard at P = 2)."""
    L, P = tiers
    if L < 2 or P < 2 or L * P != world:
        return
    inner_kinds: list[tuple[str, tuple[int, ...]]] = []
    i_tuple = _first_valid_tuple(L)
    if i_tuple is not None:
        inner_kinds.append(("lg", i_tuple))
    if L > 2 or i_tuple is None:
        inner_kinds.append(("ring", (1,)))
    outer_kinds: list[tuple[str, tuple[int, ...]]] = []
    o_tuple = _first_valid_tuple(P)
    if o_tuple is not None:
        outer_kinds.append(("exchange", o_tuple))
        outer_kinds.append(("rs_ag", o_tuple))
    if P > 2 or o_tuple is None:
        outer_kinds.append(("ring", (1,)))
    for ik, di in inner_kinds:
        for ok, do in outer_kinds:
            family = f"t_{ik}_{ok}"
            yield SynthSpec(
                key=_tiered_key(world, (L, P), family, di, do),
                op="allreduce", world=world, family=family,
                distances=di, tiers=(L, P), outer_distances=do)


@dataclasses.dataclass
class SearchResult:
    """One library-ready winner: its spec, certified canonical DAG, and
    the predicted winning byte window under the scoring link."""

    spec: SynthSpec
    dag: HopDag
    win_bytes: tuple[int, int]
    predicted: dict[int, tuple[float, float]]  # bytes -> (synth, hand)


def _narrow_contiguous(wins: list[int], size_grid: tuple[int, ...],
                       key: str, say: Callable[[str], None],
                       ) -> tuple[int, int]:
    """Longest contiguous grid run of a win set: select_entry treats
    every payload inside [lo, hi] as a predicted win, so a win set with
    a losing cell in the middle must not overclaim the whole span."""
    runs: list[list[int]] = [[wins[0]]]
    for prev, nbytes in zip(wins, wins[1:]):
        if size_grid.index(nbytes) - size_grid.index(prev) == 1:
            runs[-1].append(nbytes)
        else:
            runs.append([nbytes])
    run = max(runs, key=len)
    if len(run) < len(wins):
        say(f"narrow {key}: win cells non-contiguous across "
            f"the grid; keeping [{run[0]}, {run[-1]}]")
    return run[0], run[-1]


def score_window(link: Any, spec: SynthSpec, *,
                 elem_bytes: int = 4,
                 size_grid: tuple[int, ...] | None = None,
                 aggregate: bool = False,
                 log: Callable[[str], None] | None = None,
                 ) -> tuple[tuple[int, int] | None,
                            dict[int, tuple[float, float]]]:
    """Score one FLAT spec per size-grid cell against the best
    hand-written prediction (strict inequality wins) and narrow the win
    set to its longest CONTIGUOUS grid run. The ONE window rule shared
    by search/--export and verify_library — a scoring change lands here
    or nowhere. `size_grid` defaults to the spec's OWN grid
    (`grid_for`: SIZE_GRID_LAT for grid="lat" entries), so a lat
    entry's window re-scores on the cells it was searched over.
    Returns (window or None, per-cell predictions)."""
    say = log or (lambda m: None)
    if size_grid is None:
        size_grid = grid_for(spec)
    wins: list[int] = []
    predicted: dict[int, tuple[float, float]] = {}
    op = Operation[spec.op]
    for nbytes in size_grid:
        count = max(nbytes // elem_bytes, 1)
        t_synth = predict_spec(link, spec, count, elem_bytes,
                               aggregate=aggregate)
        # an int8 candidate competes against the hand-written
        # QUANTIZED ring — never against the exact fp32 zoo (a
        # lossy schedule must not displace an exact one)
        t_hand = hand_written_best(link, op, count, elem_bytes,
                                   spec.world, aggregate=aggregate,
                                   wire=spec.wire)
        predicted[nbytes] = (t_synth, t_hand)
        if t_synth < t_hand:
            wins.append(nbytes)
    if not wins:
        return None, predicted
    return _narrow_contiguous(wins, size_grid, spec.key, say), predicted


def score_window_tiered(tier_links: Any, spec: SynthSpec, *,
                        elem_bytes: int = 4,
                        size_grid: tuple[int, ...] = SIZE_GRID,
                        aggregate: bool = False,
                        log: Callable[[str], None] | None = None,
                        ) -> tuple[tuple[int, int] | None,
                                   dict[int, tuple[float, float]]]:
    """The tiered-entry window rule: per size-grid cell, the spec's
    per-tier prediction (every hop charged to ITS link) must strictly
    beat `hand_written_tiered_best` — the striped hierarchical
    composition at the model's own stripe count AND the flat zoo on the
    outer link. Shared by search/--export and verify_library's tiered
    leg exactly like `score_window` is for flat entries.

    A win needs a (tiny) relative MARGIN, not one ULP: the composition
    re-discovered (the ring x ring member) predicts EXACTLY the striped
    composition's serial form, differing only in summation order — a
    tie is a keep-out, never a shippable entry, and a summation-order
    artifact must not flip windows between hosts."""
    say = log or (lambda m: None)
    wins: list[int] = []
    predicted: dict[int, tuple[float, float]] = {}
    L, P = spec.tiers
    for nbytes in size_grid:
        count = max(nbytes // elem_bytes, 1)
        t_synth = predict_spec_tiered(tier_links, spec, count,
                                      elem_bytes, aggregate=aggregate)
        t_hand = hand_written_tiered_best(tier_links, count, elem_bytes,
                                          (L, P), aggregate=aggregate)
        predicted[nbytes] = (t_synth, t_hand)
        if t_synth < t_hand * (1.0 - 1e-9):
            wins.append(nbytes)
    if not wins:
        return None, predicted
    return _narrow_contiguous(wins, size_grid, spec.key, say), predicted


def search(op: Operation, world: int, link: Any, *,
           elem_bytes: int = 4,
           size_grid: tuple[int, ...] | None = None,
           aggregate: bool = False,
           log: Callable[[str], None] | None = None,
           beam: int | None = None,
           tiers: tuple[int, int] | None = None,
           tier_links: Any = None,
           grid: str = "std",
           ) -> list[SearchResult]:
    """The full synthesize -> score -> prune -> certify loop for one
    (op, world) — flat by default, or the factored space for one
    (inner, outer) factoring when `tiers` is given (then `tier_links`
    supplies the per-tier scoring calibration).

    Candidates are SCORED FIRST with the alpha-beta model (per-tier
    charged for tiered candidates) — the model's exact serial cost of
    the emitted DAG, so pruning on it is admissible (see module
    docstring) — and only the survivors pay certification: losers are
    reported as keep-outs without ever instantiating a DAG, and
    `beam` keeps only the beam best predicted advantages (ranked by
    best hand/synth ratio over the window; ties break to key order so
    the prune is deterministic). Every survivor is then CERTIFIED with
    the existing stack; a candidate with any diagnostic is discarded
    LOUDLY (reported through `log`) and can never reach the library.
    Winners are returned in enumeration order with their contiguous
    winning windows."""
    say = log or (lambda m: None)
    if grid not in ("std", "lat"):
        raise SynthesisError(f"unknown scoring grid {grid!r}")
    if grid == "lat" and tiers is not None:
        raise SynthesisError(
            "the latency grid scores FLAT candidates only: tiered "
            "windows are per-tier predictions selected through the "
            "hier register, not the latency window")
    if size_grid is None:
        size_grid = SIZE_GRID_LAT if grid == "lat" else SIZE_GRID
    if tiers is not None and op != Operation.allreduce:
        raise SynthesisError(
            f"the tiered families implement allreduce only; a tiered "
            f"{op.name} search has no candidates to return (and must "
            "not silently hand back allreduce schedules)")
    if tiers is not None and tier_links is None:
        raise SynthesisError(
            "tiered search needs tier_links (per-tier scoring "
            "calibration): pass timing.TierLinks or run "
            "bench.py --hier-gate to ship one")
    scored: list[tuple[SynthSpec, tuple[int, int],
                       dict[int, tuple[float, float]], float]] = []
    cands = (enumerate_tiered_candidates(world, tiers)
             if tiers is not None else enumerate_candidates(op, world))
    if grid == "lat":
        # the same candidate space re-scored on the latency grid: keys
        # get a "_lat" suffix so a member can ship BOTH a bandwidth
        # window and a latency window without colliding in the library
        cands = (dataclasses.replace(s, key=s.key + "_lat", grid="lat")
                 for s in cands)
    for spec in cands:
        if spec.tiers:
            window, predicted = score_window_tiered(
                tier_links, spec, elem_bytes=elem_bytes,
                size_grid=size_grid, aggregate=aggregate, log=say)
        else:
            window, predicted = score_window(
                link, spec, elem_bytes=elem_bytes, size_grid=size_grid,
                aggregate=aggregate, log=say)
        if window is None:
            say(f"keep-out {spec.key}: never beats the hand-written "
                "baselines on this link (pruned before certification)")
            continue
        advantage = max(
            hand / synth
            for nb, (synth, hand) in predicted.items()
            if window[0] <= nb <= window[1] and synth > 0)
        scored.append((spec, window, predicted, advantage))
    if beam is not None and len(scored) > beam:
        ranked = sorted(scored, key=lambda s: (-s[3], s[0].key))
        kept = {id(s) for s in ranked[:beam]}
        for spec, _w, _p, adv in ranked[beam:]:
            say(f"PRUNE {spec.key}: outside the beam of {beam} "
                f"(predicted advantage {adv:.2f}x) — never certified")
        scored = [s for s in scored if id(s) in kept]
    results: list[SearchResult] = []
    for spec, window, predicted, _adv in scored:
        ok, diags = certify_spec(spec)
        if not ok:
            say(f"DISCARD {spec.key}: candidate failed certification: "
                + "; ".join(str(d) for d in diags[:4]))
            continue
        dag = instantiate(spec, canonical_count(spec))
        results.append(SearchResult(
            spec=spec, dag=dag, win_bytes=window, predicted=predicted))
        n_cells = (size_grid.index(window[1])
                   - size_grid.index(window[0]) + 1)
        say(f"WINNER {spec.key}: beats hand-written on "
            f"[{window[0]}, {window[1]}] bytes "
            f"({n_cells}/{len(size_grid)} cells)")
    return results


# ---------------------------------------------------------------------------
# Library: the committed synthesized/ directory
# ---------------------------------------------------------------------------


def library_dir() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parent / "synthesized"


@dataclasses.dataclass(frozen=True)
class LibraryEntry:
    spec: SynthSpec
    win_bytes: tuple[int, int]
    canonical_count: int
    path: pathlib.Path

    def load_dag(self) -> HopDag:
        return from_json(json.loads(self.path.read_text())["dag"])


_LIBRARY: dict[str, LibraryEntry] | None = None


def clear_library_cache() -> None:
    global _LIBRARY
    _LIBRARY = None


def library() -> dict[str, LibraryEntry]:
    """key -> entry for every committed synthesized schedule. Cached;
    `clear_library_cache()` rescans (tests, regeneration)."""
    global _LIBRARY
    if _LIBRARY is None:
        entries: dict[str, LibraryEntry] = {}
        d = library_dir()
        if d.is_dir():
            for p in sorted(d.glob("*.json")):
                try:
                    doc = json.loads(p.read_text())
                    spec = SynthSpec.from_json(doc)
                    lo, hi = doc.get("win_bytes", [0, 0])
                    entries[spec.key] = LibraryEntry(
                        spec=spec, win_bytes=(int(lo), int(hi)),
                        canonical_count=int(doc.get(
                            "canonical_count", canonical_count(spec))),
                        path=p)
                except (OSError, ValueError, KeyError) as e:
                    raise SynthesisError(
                        f"unreadable synthesized library entry {p}: "
                        f"{e!r}") from e
        _LIBRARY = entries
    return _LIBRARY


def select_entry(op: Operation, world: int, payload_bytes: int,
                 wire: str = "",
                 tiers: tuple[int, ...] = (),
                 grid: str = "std") -> str | None:
    """The library entry `plan.select_algorithm` should use for this
    cell, or None. `tiers=()` (the default) matches only FLAT entries —
    the synth registers' uniform-link windows; `tiers=(inner, outer)`
    matches only the tiered entries of that exact factoring (the
    HIER_ALLREDUCE_MIN_COUNT window's predicted-time arbitration).
    `grid="std"` (the default) matches only SIZE_GRID entries;
    `grid="lat"` matches only the latency-grid entries behind
    SYNTH_LATENCY_MAX_COUNT — the two windows never cross-select.
    Among matching entries the one whose predicted winning window
    contains the payload wins; ties break to the narrower window (the
    more specialized schedule), then key order — all deterministic."""
    best: LibraryEntry | None = None
    for entry in library().values():
        s = entry.spec
        if (s.op != op.name or s.world != world or s.wire != wire
                or s.tiers != tuple(tiers) or s.grid != grid):
            continue
        lo, hi = entry.win_bytes
        if not (lo <= payload_bytes <= hi):
            continue
        if best is None:
            best = entry
            continue
        bw = best.win_bytes[1] - best.win_bytes[0]
        ew = hi - lo
        if ew < bw or (ew == bw and entry.spec.key < best.spec.key):
            best = entry
    return best.spec.key if best else None


def entry_for_key(key: str) -> LibraryEntry:
    entry = library().get(key)
    if entry is None:
        raise SynthesisError(
            f"no synthesized library entry {key!r} "
            f"(library at {library_dir()})")
    return entry


def export_entry(result: SearchResult,
                 out_dir: pathlib.Path | None = None) -> pathlib.Path:
    """Write one winner to the library (the committed JSON form)."""
    out = out_dir or library_dir()
    out.mkdir(parents=True, exist_ok=True)
    doc = result.spec.to_json()
    doc["schema"] = 1
    doc["canonical_count"] = canonical_count(result.spec)
    doc["win_bytes"] = list(result.win_bytes)
    doc["cert"] = {"semantic": "clean", "modelcheck": "clean"}
    doc["dag"] = to_json(result.dag)
    path = out / f"{result.spec.key}.json"
    path.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n")
    return path


def shipped_link() -> Any:
    """LinkParams from the committed calibrated timing model — the same
    link `ACCL.autotune`, `bench.py --check`, and `tools/accl_synth`
    resolve (timing.emulator_link, the one resolution rule)."""
    from .timing import emulator_link

    model_path = (pathlib.Path(__file__).resolve().parent.parent.parent
                  / "accl_log" / "timing_model.json")
    try:
        model = json.loads(model_path.read_text())
        return emulator_link(model)
    except (OSError, ValueError) as e:
        raise SynthesisError(
            f"cannot load the shipped timing model {model_path} "
            f"(needed to re-validate library win_bytes): {e!r}") from e


def shipped_tier_links() -> Any:
    """TierLinks from the committed calibrated timing model's
    `link_tiers` section (written by bench.py --hier-gate) — the
    scoring calibration tiered library entries are verified under.
    Raises loudly when absent: a library with tiered entries and no
    shipped per-tier calibration cannot be re-validated."""
    from ..telemetry.feedback import default_tier_links

    tiers = default_tier_links()
    if tiers is None:
        raise SynthesisError(
            "the shipped timing model carries no link_tiers (needed to "
            "re-validate tiered library windows) — run "
            "bench.py --hier-gate to calibrate the two-tier world")
    return tiers


def verify_library(log: Callable[[str], None] | None = None,
                   link: Any = None, tier_links: Any = None) -> bool:
    """Re-certify every committed entry from scratch: the spec must
    regenerate the committed DAG byte-for-byte (generator drift check),
    the DAG must pass semantics + deep modelcheck clean, and the
    committed win_bytes window must equal a fresh `score_window` under
    `link` (default: the shipped calibrated model) — a timing-model or
    cost-model change that leaves stale selection windows fails here
    instead of silently steering `select_entry`. TIERED entries
    re-score under `tier_links` (default: the shipped `link_tiers`
    calibration, never the flat link — their windows are per-tier
    predictions against the striped composition). The CI step that
    keeps a stale library or a checker change from silently shipping
    an uncertified schedule."""
    say = log or print
    ok = True
    entries = library()
    if not entries:
        say("synthesized library is EMPTY")
        return False
    if link is None:
        link = shipped_link()
    for key, entry in sorted(entries.items()):
        committed = entry.load_dag()
        regen = instantiate(entry.spec, entry.canonical_count)
        if to_json(regen) != to_json(committed):
            say(f" FAIL {key}: committed DAG != regenerated DAG "
                "(generator drift — re-export the library)")
            ok = False
            continue
        diags = certify_dag(committed, entry.spec,
                            entry.canonical_count)
        if diags:
            say(f" FAIL {key}: committed DAG no longer certifies: "
                + "; ".join(str(d) for d in diags[:4]))
            ok = False
            continue
        if entry.spec.tiers:
            if tier_links is None:
                tier_links = shipped_tier_links()
            window, _ = score_window_tiered(tier_links, entry.spec)
        else:
            window, _ = score_window(link, entry.spec)
        if window != entry.win_bytes:
            say(f" FAIL {key}: committed win_bytes "
                f"{list(entry.win_bytes)} != fresh scoring "
                f"{list(window) if window else None} under the scoring "
                "link (stale selection window — re-export the library)")
            ok = False
            continue
        tier_note = (f", tiers {entry.spec.tiers[0]}x"
                     f"{entry.spec.tiers[1]}" if entry.spec.tiers else "")
        say(f"  ok  {key}: regenerates + certifies clean, win window "
            f"current ({len(committed.nodes)} nodes, "
            f"world {entry.spec.world}{tier_note})")
    return ok


# ---------------------------------------------------------------------------
# Lowering: certified hop-DAG -> schedule body (the compiler seam)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _SymView:
    """The rank-relative view `lower_dag` extracts: rank 0's node slice
    with, per position, the rotation distance of its hop (if any)."""

    positions: tuple[Node, ...]  # rank-0 nodes in per-rank order
    send_pos_of_hop: dict[int, int]
    send_distance: dict[int, int]
    output: Value  # rank-0 output, refs rewritten to positions


def _extract_symmetric(dag: HopDag) -> _SymView:
    """Validate rotational symmetry and extract the rank-relative
    program. Every rank must hold the same per-rank node sequence with
    peers rotated by a constant per hop and piece references mapping to
    the same positions — the structural form of the search's symmetry
    pruning. Raises SynthesisError otherwise (the lowering never guesses
    at an asymmetric DAG)."""
    w = dag.world
    per_rank: list[list[Node]] = [[] for _ in range(w)]
    for n in dag.nodes:
        if not 0 <= n.rank < w:
            raise SynthesisError(f"node {n.id} rank {n.rank} out of range")
        per_rank[n.rank].append(n)
    n_pos = len(per_rank[0])
    if any(len(p) != n_pos for p in per_rank):
        raise _NotRankSymmetric("per-rank node counts differ")
    pos_of: list[dict[int, int]] = [
        {n.id: p for p, n in enumerate(per_rank[r])} for r in range(w)]

    def rel_value(value: Value, r: int) -> tuple:
        out = []
        for pc in value:
            if pc.node == CONST:
                out.append(("const", pc.length, pc.fill))
            else:
                ref = pos_of[r].get(pc.node)
                if ref is None:
                    raise SynthesisError(
                        "cross-rank piece reference (data must flow "
                        "through send/recv hops)")
                out.append((ref, pc.offset, pc.length, pc.part))
        return tuple(out)

    send_distance: dict[int, int] = {}
    send_pos_of_hop: dict[int, int] = {}
    for p in range(n_pos):
        base = per_rank[0][p]
        for r in range(w):
            n = per_rank[r][p]
            same = (n.kind == base.kind and n.length == base.length
                    and n.func == base.func and n.dtype == base.dtype
                    and n.hop == base.hop and n.arg == base.arg
                    and n.scales_len == base.scales_len
                    and rel_value(n.value, r) == rel_value(base.value, 0)
                    and rel_value(n.value2, r) == rel_value(base.value2,
                                                           0))
            if not same:
                raise _NotRankSymmetric(
                    f"DAG is not rank-symmetric at position {p} "
                    f"(rank {r} differs from rank 0)")
            if n.kind == "send":
                d = (n.peer - n.rank) % w
                prev = send_distance.setdefault(n.hop, d)
                if prev != d:
                    raise _NotRankSymmetric(
                        f"hop {n.hop} mixes rotation distances")
            if n.kind == "recv":
                d = (n.rank - n.peer) % w
                prev = send_distance.setdefault(n.hop, d)
                if prev != d:
                    raise _NotRankSymmetric(
                        f"hop {n.hop} recv distance mismatch")
        if base.kind == "send":
            if base.hop in send_pos_of_hop:
                raise SynthesisError(
                    f"hop {base.hop} has multiple sends per rank")
            send_pos_of_hop[base.hop] = p
    out0 = dag.outputs[0]
    for r in range(w):
        if rel_value(dag.outputs[r], r) != rel_value(out0, 0):
            raise _NotRankSymmetric("DAG outputs are not rank-symmetric")
    return _SymView(positions=tuple(per_rank[0]),
                    send_pos_of_hop=send_pos_of_hop,
                    send_distance=send_distance,
                    output=out0)


def _check_same_rank_dataflow(dag: HopDag) -> None:
    """Structural precondition BOTH lowerings require: ranks in range,
    every piece reference resolves to a node of the SAME rank (cross-rank
    data flows only through send/recv hops — the generic lowering's
    `env` is per-rank-correct only under this contract), at most one
    send per (hop, rank), and every recv hop has a send. Raises a plain
    SynthesisError: a violation means NO lowering can compile this DAG
    correctly, so it must never be demoted to a fallback."""
    rank_of: dict[int, int] = {}
    for n in dag.nodes:
        if not 0 <= n.rank < dag.world:
            raise SynthesisError(f"node {n.id} rank {n.rank} out of range")
        rank_of[n.id] = n.rank

    def check_refs(value: Value, rank: int, what: str) -> None:
        for pc in value:
            if pc.node == CONST:
                continue
            src = rank_of.get(pc.node)
            if src is None:
                raise SynthesisError(
                    f"{what} references unknown node {pc.node}")
            if src != rank:
                raise SynthesisError(
                    f"{what} is a cross-rank piece reference (data must "
                    f"flow through send/recv hops)")

    send_ranks: dict[int, set[int]] = {}
    for n in dag.nodes:
        check_refs(n.value, n.rank, f"node {n.id}")
        check_refs(n.value2, n.rank, f"node {n.id}")
        if n.kind == "send":
            ranks = send_ranks.setdefault(n.hop, set())
            if n.rank in ranks:
                raise SynthesisError(
                    f"hop {n.hop} has multiple sends from rank {n.rank}")
            ranks.add(n.rank)
    for n in dag.nodes:
        if n.kind == "recv" and n.hop not in send_ranks:
            raise SynthesisError(
                f"recv node {n.id} has no matching send on hop {n.hop}")
    for r, out in enumerate(dag.outputs):
        check_refs(out, r, f"rank {r} output")


def lower_dag(dag: HopDag, axis_name: str) -> Callable[[Any], Any]:
    """Compile a certified hop-DAG into a schedule body (flat per-rank
    buffer -> flat per-rank result) over the mesh axis, built from the
    SAME wire primitives schedules.py uses: lax.ppermute for every hop,
    ops.compression's blockwise quantize/dequantize for encode/decode
    nodes, and the reduce lane's elementwise folds for combines. The
    body is what ScheduleCompiler shard_maps + jits — a synthesized
    schedule is a first-class algorithm, not an interpreter.

    Two lowerings share this entry: DAGs whose per-rank programs are a
    strict rotation of rank 0's (the exchange family: every offset
    static) compile to ONE rank-relative chain; DAGs whose chunk
    indexing is rank-absolute (the chunked doubling/halving families)
    take the generic masked lowering, where every rank's chain is
    evaluated and each hop payload / final output is selected by
    `axis_index` — the schedules.py `jnp.where(me == ...)` idiom,
    generalized."""
    _check_same_rank_dataflow(dag)
    try:
        view = _extract_symmetric(dag)
    except _NotRankSymmetric:
        return _lower_generic(dag, axis_name)
    w = dag.world

    def body(x: Any) -> Any:
        import jax.numpy as jnp
        from jax import lax

        from ..ops.compression import (
            dequantize_blockwise,
            quantize_blockwise,
        )
        from ..ops.reduce_ops import combine_op

        env: dict[tuple[int, str], Any] = {}

        def materialize(value: Value, pos_map: dict[int, int]) -> Any:
            parts = []
            for pc in value:
                if pc.node == CONST:
                    parts.append(jnp.full((pc.length,), pc.fill,
                                          dtype=x.dtype))
                else:
                    src = env[(pos_map[pc.node], pc.part)]
                    parts.append(src[pc.offset:pc.offset + pc.length])
            if not parts:
                return jnp.zeros((0,), dtype=x.dtype)
            if len(parts) == 1:
                return parts[0]
            return jnp.concatenate(parts)

        # position map for rank-0 ids (materialize resolves refs by
        # node id -> position)
        pos_map = {n.id: p for p, n in enumerate(view.positions)}

        for p, n in enumerate(view.positions):
            if n.kind == "arg":
                out = x[: n.length]
            elif n.kind == "send":
                out = materialize(n.value, pos_map)
            elif n.kind == "recv":
                d = view.send_distance[n.hop]
                payload = env[(view.send_pos_of_hop[n.hop], DATA)]
                perm = [(i, (i + d) % w) for i in range(w)]
                out = lax.ppermute(payload, axis_name, perm)
            elif n.kind == "combine":
                func = (ReduceFunction.MAX if n.func == "max"
                        else ReduceFunction.SUM)
                out = combine_op(func, materialize(n.value, pos_map),
                                 materialize(n.value2, pos_map))
            elif n.kind == "encode":
                q, s = quantize_blockwise(materialize(n.value, pos_map))
                env[(p, SCALES)] = s
                out = q
            elif n.kind == "decode":
                q = materialize(n.value, pos_map)
                s = materialize(n.value2, pos_map)
                out = dequantize_blockwise(q, s, n.length, x.dtype)
            elif n.kind == "cast":
                v = materialize(n.value, pos_map)
                out = v.astype(jnp.dtype(n.dtype)) if n.dtype else v
            else:
                raise SynthesisError(f"cannot lower node kind {n.kind!r}")
            env[(p, DATA)] = out
        return materialize(view.output, pos_map)

    return body


def _lower_generic(dag: HopDag, axis_name: str) -> Callable[[Any], Any]:
    """Masked SPMD lowering for any same-rank-dataflow hop-DAG: every
    rank's node chain is evaluated (correct on its own rank, defined
    everywhere), hop payloads select the local rank's send by
    `axis_index`, and the output selects the local rank's composition —
    exactly the masking contract the hand-written schedules use for
    rank-dependent moves. Cross-rank data still flows ONLY through the
    ppermute hops."""
    w = dag.world
    sends_by_hop: dict[int, list[Node]] = {}
    for n in dag.nodes:
        if n.kind == "send":
            sends_by_hop.setdefault(n.hop, []).append(n)

    def body(x: Any) -> Any:
        import jax.numpy as jnp
        from jax import lax

        from ..ops.compression import (
            dequantize_blockwise,
            quantize_blockwise,
        )
        from ..ops.reduce_ops import combine_op

        me = lax.axis_index(axis_name)
        env: dict[tuple[int, str], Any] = {}

        def materialize(value: Value) -> Any:
            parts = []
            for pc in value:
                if pc.node == CONST:
                    parts.append(jnp.full((pc.length,), pc.fill,
                                          dtype=x.dtype))
                else:
                    src = env[(pc.node, pc.part)]
                    parts.append(src[pc.offset:pc.offset + pc.length])
            if not parts:
                return jnp.zeros((0,), dtype=x.dtype)
            if len(parts) == 1:
                return parts[0]
            return jnp.concatenate(parts)

        permuted: dict[int, Any] = {}
        for n in dag.nodes:
            if n.kind == "arg":
                out = x[: n.length]
            elif n.kind == "send":
                out = materialize(n.value)
            elif n.kind == "recv":
                if n.hop not in permuted:
                    sends = sends_by_hop.get(n.hop, [])
                    if not sends:
                        raise SynthesisError(
                            f"recv node {n.id} has no matching send on "
                            f"hop {n.hop}")
                    payload = env[(sends[0].id, DATA)]
                    for s in sends[1:]:
                        payload = jnp.where(me == s.rank,
                                            env[(s.id, DATA)], payload)
                    perm = [(s.rank, s.peer) for s in sends]
                    permuted[n.hop] = lax.ppermute(payload, axis_name,
                                                   perm)
                out = permuted[n.hop][: n.length]
            elif n.kind == "combine":
                func = (ReduceFunction.MAX if n.func == "max"
                        else ReduceFunction.SUM)
                out = combine_op(func, materialize(n.value),
                                 materialize(n.value2))
            elif n.kind == "encode":
                q, s = quantize_blockwise(materialize(n.value))
                env[(n.id, SCALES)] = s
                out = q
            elif n.kind == "decode":
                out = dequantize_blockwise(materialize(n.value),
                                           materialize(n.value2),
                                           n.length, x.dtype)
            elif n.kind == "cast":
                v = materialize(n.value)
                out = v.astype(jnp.dtype(n.dtype)) if n.dtype else v
            else:
                raise SynthesisError(f"cannot lower node kind {n.kind!r}")
            env[(n.id, DATA)] = out
        result = materialize(dag.outputs[0])
        for r in range(1, w):
            result = jnp.where(me == r, materialize(dag.outputs[r]),
                               result)
        return result

    return body


def _check_tier_layout(dag: HopDag, spec: SynthSpec) -> None:
    """Cross-check the spec's tier annotation against the emitted DAG:
    every hop's (rank -> peer) send pairs must be EXACTLY the RankMap
    ring permutation of its annotated (tier, distance) — the
    `ring=(pos, perm)` embedding the compiled ppermute uses and the
    per-tier cost accounting charges. A mismatch means the annotation
    would charge (or compile) the hop on the wrong tier: FATAL, never
    a fallback — a mis-annotated hop would silently bill DCN traffic
    to ICI."""
    from .hierarchical import RankMap

    L, P = spec.tiers
    rm = RankMap(L, P, "outer_major")
    layout = hop_layout(spec)
    pairs: dict[int, set[tuple[int, int]]] = {}
    for n in dag.nodes:
        if n.kind == "send":
            pairs.setdefault(n.hop, set()).add((n.rank, n.peer))
    if sorted(pairs) != list(range(len(layout))):
        raise SynthesisError(
            f"{spec.key}: DAG hops {sorted(pairs)} do not match the "
            f"tier annotation's {len(layout)} channels")
    for h, (tier, d) in enumerate(layout):
        want = set(rm.inner_perm(d) if tier == "inner"
                   else rm.outer_perm(d))
        if pairs[h] != want:
            raise SynthesisError(
                f"{spec.key}: hop {h} send pairs are not the {tier} "
                f"ring permutation at distance {d} — the tier "
                "annotation disagrees with the emitted DAG")


def lower_plan(plan: Any, options: Any, world: int,
               axis_name: str) -> tuple[Callable[[Any], Any], int]:
    """The ScheduleCompiler._body seam for Algorithm.SYNTHESIZED plans:
    resolve the plan's library entry, regenerate the DAG at the call's
    count, and lower it. Raises loudly when the key is missing or the
    entry's world disagrees — a synthesized plan must never silently
    fall back to a different schedule.

    Tiered entries validate their hop annotation against the RankMap
    ring permutations first (`_check_tier_layout`) and then compile
    through the generic same-rank-dataflow lowering, whose per-hop
    `ppermute` perm is built from the DAG's sends — i.e. exactly the
    validated `inner_perm`/`outer_perm` global pairs of the PR 8
    `ring=(pos, perm)` embedding: inner hops stay within a slice,
    outer hops cross, one compiled program either way."""
    entry = entry_for_key(plan.synth_key)
    spec = entry.spec
    if spec.world != world:
        raise SynthesisError(
            f"synthesized entry {spec.key} is for world {spec.world}, "
            f"called with world {world}")
    if spec.scenario != options.scenario:
        raise SynthesisError(
            f"synthesized entry {spec.key} implements {spec.op}, "
            f"called as {options.scenario.name}")
    func = ("max" if ReduceFunction(options.function)
            == ReduceFunction.MAX else "sum")
    count = int(options.count)
    chunk_by = 0
    if spec.tiers:
        chunk_by = spec.tiers[0] * spec.tiers[1]
    elif spec.family == "rs_ag":
        chunk_by = world
    if chunk_by and count % chunk_by:
        # chunked families pad to a chunking multiple and trim, the
        # same rule allreduce_ring_schedule applies per segment
        padded = count + (-count) % chunk_by
        dag = instantiate(spec, padded, func)
        if spec.tiers:
            _check_tier_layout(dag, spec)
        inner = lower_dag(dag, axis_name)

        def body(x: Any) -> Any:
            import jax.numpy as jnp

            y = jnp.pad(x, (0, padded - count))
            return inner(y)[:count]

        return body, 1
    dag = instantiate(spec, count, func)
    if spec.tiers:
        _check_tier_layout(dag, spec)
    return lower_dag(dag, axis_name), 1
