"""Lowering: call descriptor + plan -> compiled device program.

This is the TPU analog of the firmware's dispatch (ccl_offload_control.c:2374-2456)
combined with the move-instruction emission (.c:413-527): instead of
streaming move words into a hardware DMP at runtime, the whole collective
schedule is traced once per static descriptor signature, compiled by XLA
into a single device program over the mesh, and cached — subsequent calls
with the same signature are a dispatch-only cost, preserving ACCL's
"host only issues the call" property.

Operands enter as stacked per-rank buffers: a global array of shape
(world, n) sharded on the collective axis, so device r's shard is rank r's
local buffer (ACCL buffer semantics, not slices of one logical tensor).
"""

from __future__ import annotations

import functools
from typing import Callable

from ..utils import compat as _compat

_compat.install()  # jax version shims, before any jax.shard_map use

import jax  # noqa: E402
from jax.sharding import Mesh, PartitionSpec

from ..arithconfig import DEFAULT_ARITH_CONFIG, ArithConfig
from ..constants import (
    CompressionFlags,
    DataType,
    Operation,
    ReduceFunction,
    to_numpy_dtype,
)
from ..descriptor import CallOptions
from ..ops.compression import wire_dtype
from . import schedules
from .plan import Algorithm, Plan


class ScheduleCompiler:
    """Compiles and caches collective programs for one mesh axis.

    The cache key is the descriptor's static signature + the plan, mirroring
    how the reference caches nothing but re-executes firmware per call — on
    TPU, tracing per call would forfeit all performance, so compilation is
    amortized exactly like XLA intends.
    """

    def __init__(
        self,
        mesh: Mesh,
        axis_name: str = "ccl",
        arith_table: dict | None = None,
        use_pallas_ring: bool | None = None,
        pallas_ring_overlap: bool | None = None,
        overlap_serialize: bool | None = None,
    ):
        self.mesh = mesh
        self.axis_name = axis_name
        self.arith_table = arith_table or DEFAULT_ARITH_CONFIG
        if use_pallas_ring is None:
            # Auto: the fused ICI kernel on real TPU, lax schedules on the
            # CPU emulation mesh (where interpret-mode kernels are slower).
            from ..ops.pallas_kernels import _on_tpu

            use_pallas_ring = _on_tpu()
        self.use_pallas_ring = use_pallas_ring
        if pallas_ring_overlap is None:
            # segment-slot double-buffering for the large-payload pallas
            # ring (see _body's allreduce branch); the env knob keeps the
            # serialized baseline reachable for A/B measurement
            import os

            pallas_ring_overlap = (
                os.environ.get("ACCL_PALLAS_RING_SERIALIZE") != "1")
        self.pallas_ring_overlap = pallas_ring_overlap
        if overlap_serialize is None:
            # the serial dispatch->compute twin of a stripe-overlapped
            # allreduce plan (Plan.stripes > 1): order-only barriers
            # serialize the stripe chains, bitwise-identical to the
            # overlapped form — the A/B baseline bench --overlap-gate
            # measures against (same knob pattern as the pallas ring's
            # serialized baseline above)
            import os

            overlap_serialize = (
                os.environ.get("ACCL_OVERLAP_SERIALIZE") == "1")
        self.overlap_serialize = overlap_serialize
        self._cache: dict = {}

    # Per-device payload ceiling for the VMEM-resident fused ring kernel;
    # larger transfers fall back to the segmented lax schedule.
    PALLAS_RING_MAX_BYTES = 4 * 1024 * 1024

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis_name]

    def _wire(
        self,
        options: CallOptions,
        arithcfg: ArithConfig | None,
        func: ReduceFunction | None,
        compressed_domain: bool,
    ) -> schedules.Wire:
        """Resolve the datapath config: which compression lanes wrap each
        hop and which arith lane reductions use (prepare_call's dtype logic,
        reference accl.cpp:1236-1356). Blockwise-quantized rows (compressor
        lane 4) produce a Wire whose hops carry (int8 codes, per-block
        scales) and whose ring families fuse dequantize->reduce->requantize
        per step; because the call-sequence path composes the SAME _body
        lowerings, recorded sequences fuse quantized steps bitwise-
        identically to eager dispatch (pinned by the quantized sequence
        fuzz)."""
        arith_lane = None
        if arithcfg is not None and func is not None:
            arith_lane = arithcfg.arith_lanes[int(func)]
        eth = (
            arithcfg is not None
            and options.compression_flags & CompressionFlags.ETH_COMPRESSED
            and wire_dtype(arithcfg) is not None
        )
        # In compressed-domain execution the operand is cast once up front,
        # so per-hop lanes are disabled (payload already at wire width).
        cfg = arithcfg if (eth and not compressed_domain) else None
        return schedules.Wire(cfg, arith_lane)

    def compile(
        self,
        options: CallOptions,
        plan: Plan,
        arithcfg: ArithConfig | None = None,
    ) -> Callable:
        key = (options.signature(), plan, self.axis_name,
               self.use_pallas_ring, self.pallas_ring_overlap,
               self.overlap_serialize)
        fn = self._cache.get(key)
        if fn is None:
            from ..utils.logging import Log

            Log.info("compiling %s: %s/%s world=%d count=%d",
                     options.scenario.name, plan.protocol.name,
                     plan.algorithm.name, self.world, options.count)
            fn = self._build(options, plan, arithcfg)
            self._cache[key] = fn
        return fn

    # -- construction -----------------------------------------------------

    def _build(self, options: CallOptions, plan: Plan, arithcfg) -> Callable:
        body, n_in = self._body(options, plan, arithcfg)
        return self._finalize(body, n_in)

    def _finalize(self, body, n_in: int, wrap=None) -> Callable:
        """shard_map + jit finalization shared by the per-call and
        call-sequence paths; `wrap` adapts the body's calling convention
        (single (1, n)-shard result by default, tuples for sequences)."""
        spec = PartitionSpec(self.axis_name)
        # vma checking is disabled because the pallas-lowered bodies carry
        # explicit vma annotations the checker cannot yet propagate through.
        shmapped = jax.shard_map(
            (wrap or _squeeze_wrap)(body, n_in),
            mesh=self.mesh,
            in_specs=(spec,) * n_in,
            out_specs=spec,
            check_vma=False,
        )
        return jax.jit(shmapped)

    def lower_streamed(
        self,
        options: CallOptions,
        plan: Plan,
        producer: Callable | None = None,
        consumer: Callable | None = None,
    ) -> Callable:
        """Streamed-operand collective (reference OP0_STREAM/RES_STREAM
        routing through any collective, ccl_offload_control.c:628-636 and
        the depacketizer's strm!=0 kernel-stream path,
        tcp_depacketizer.cpp:106-117): the operand comes from a traced
        on-device producer and/or the result is routed through a traced
        consumer, fused into the same compiled program."""
        from ..ops.streams import splice_consumer, splice_producer

        arithcfg = None
        if options.data_type != DataType.none:
            arithcfg = _arithcfg_for(self.arith_table, options)
        # the endpoint callables themselves are part of the key: holding a
        # strong reference prevents id-reuse after GC from resurrecting a
        # stale compiled program when an endpoint is re-registered
        key = (options.signature(), plan, self.axis_name,
               self.use_pallas_ring, self.pallas_ring_overlap,
               self.overlap_serialize, "streamed", producer, consumer)
        fn = self._cache.get(key)
        if fn is None:
            body, n_in = self._body(options, plan, arithcfg)
            if producer is not None:
                if n_in != 1:
                    raise ValueError(
                        f"OP0_STREAM unsupported for {options.scenario.name}")
                # scatter-class inputs hold world stacked blocks per rank
                in_elems = options.count
                if options.scenario in (Operation.scatter,
                                        Operation.reduce_scatter,
                                        Operation.alltoall):
                    in_elems *= self.world
                body = splice_producer(body, producer, in_elems)
            if consumer is not None:
                body = splice_consumer(body, consumer)
            fn = self._finalize(body, n_in)
            self._cache[key] = fn
        return fn

    def _body(self, options: CallOptions, plan: Plan,
              arithcfg) -> tuple[Callable, int]:
        body: Callable
        axis, world = self.axis_name, self.world
        op = options.scenario
        root = options.root_src_dst

        if plan.algorithm == Algorithm.SYNTHESIZED:
            # A search-produced schedule from the committed library:
            # the certified hop-DAG is regenerated at this call's count
            # and lowered through the same wire primitives (ppermute
            # hops, blockwise int8 encode/decode, reduce-lane folds)
            # the Python bodies use — schedules as data end to end.
            # int8-wire entries carry their encode/decode lanes inside
            # the DAG, so the per-hop Wire built below stays off here.
            # TIERED entries (spec.tiers) validate their hop annotation
            # against the RankMap ring permutations and compile every
            # hop as ONE global ppermute over those pairs — inner hops
            # stay within a slice, outer hops cross — the same
            # ring=(pos, perm) embedding the HIER branch below rides.
            from . import synthesis

            return synthesis.lower_plan(plan, options, world, axis)

        if plan.algorithm == Algorithm.HIER_RS_AR_AG:
            # Striped two-tier allreduce: every hop is a GLOBAL permute
            # (inner hops stay within a slice, outer hops cross), so the
            # same body lowers on a flat axis, the DCN tuple axis, and
            # the analyzers' single-axis trace seam. Per-tier wires come
            # from the plan's frozen tier dtypes, resolved against the
            # arith table exactly like the flat wire path.
            from . import hierarchical

            func = ReduceFunction(options.function)

            def tier_wire(dt: DataType) -> schedules.Wire:
                cfg = (self.arith_table.get((options.data_type, dt))
                       if dt not in (DataType.none, options.data_type)
                       else None)
                lane = None
                if arithcfg is not None:
                    lane = arithcfg.arith_lanes[int(func)]
                return schedules.Wire(cfg, lane)

            rm = hierarchical.RankMap(plan.inner_world, plan.outer_world,
                                      "outer_major")
            tw = hierarchical.TierWire(tier_wire(plan.inner_wire_dtype),
                                       tier_wire(plan.outer_wire_dtype))
            body = functools.partial(
                hierarchical.hierarchical_allreduce_striped_schedule,
                func=func, axis=axis, rankmap=rm, wire=tw,
                stripes=plan.stripes)
            return body, 1

        func = ReduceFunction(options.function) if op in (
            Operation.combine,
            Operation.reduce,
            Operation.allreduce,
            Operation.reduce_scatter,
        ) else None
        # Reductions whose arithconfig reduces in the compressed domain
        # (arith_is_compressed, arithconfig.hpp:55-57) cast the operand to
        # the wire dtype once and run the whole schedule there, avoiding a
        # decompress/recompress pair at every hop.
        compressed_domain = bool(
            func is not None
            and arithcfg is not None
            and options.compression_flags & CompressionFlags.ETH_COMPRESSED
            and arithcfg.arith_is_compressed
            and wire_dtype(arithcfg) is not None
        )
        wire = self._wire(options, arithcfg, func, compressed_domain)
        common = dict(axis=axis, world=world, wire=wire)

        if op == Operation.copy:
            body, n_in = functools.partial(schedules.copy_schedule, **common), 1
        elif op == Operation.combine:
            body = functools.partial(schedules.combine_schedule, func=func, **common)
            n_in = 2
        elif op in (Operation.send, Operation.recv):
            # On the SPMD path send/recv lower to one sendrecv program
            # executed by the whole axis (src/dst from the descriptor).
            src = options.root_src_dst & 0xFFFF
            dst = (options.root_src_dst >> 16) & 0xFFFF
            body = functools.partial(
                schedules.sendrecv_schedule, src=src, dst=dst, **common
            )
            n_in = 1
        elif op == Operation.bcast:
            if plan.algorithm == Algorithm.RNDZV_BIN_TREE:
                body = functools.partial(
                    schedules.bcast_bin_tree_schedule, root=root, **common
                )
            else:
                body = functools.partial(
                    schedules.bcast_flat_schedule, root=root, **common
                )
            n_in = 1
        elif op == Operation.scatter:
            body = functools.partial(schedules.scatter_schedule, root=root, **common)
            n_in = 1
        elif op == Operation.gather:
            if plan.algorithm == Algorithm.EAGER_RING:
                body = functools.partial(
                    schedules.gather_ring_schedule, root=root, **common
                )
            else:
                body = functools.partial(
                    schedules.gather_flat_schedule,
                    root=root,
                    fanin=plan.tree_fanin,
                    **common,
                )
            n_in = 1
        elif op == Operation.allgather:
            body = functools.partial(schedules.allgather_ring_schedule, **common)
            n_in = 1
        elif op == Operation.reduce:
            if plan.algorithm == Algorithm.EAGER_RING:
                body = functools.partial(
                    schedules.reduce_ring_schedule, root=root, func=func, **common
                )
            elif plan.algorithm == Algorithm.RNDZV_BIN_TREE:
                body = functools.partial(
                    schedules.reduce_bin_tree_schedule, root=root, func=func, **common
                )
            else:
                body = functools.partial(
                    schedules.reduce_flat_schedule, root=root, func=func, **common
                )
            n_in = 1
        elif op == Operation.reduce_scatter:
            if plan.algorithm == Algorithm.RNDZV_REDUCE_SCATTER:
                # Composition: reduce-to-0 then scatter (.c:1768-1781);
                # the reduce stage's tree shape comes from plan.stages.
                reduce_body = self._reduce_body(plan.stages[0], 0, func, common)

                def _rs_composed(x, *, _c=common, _rb=reduce_body):
                    return schedules.scatter_schedule(_rb(x), root=0, **_c)

                body = _rs_composed
            else:
                body = functools.partial(
                    schedules.reduce_scatter_ring_schedule, func=func, **common
                )
            n_in = 1
        elif op == Operation.allreduce:
            if plan.algorithm == Algorithm.RNDZV_REDUCE_BCAST:
                # Composition: reduce-to-0 then broadcast (.c:1878-1887);
                # both stage shapes were re-selected by plan.py with the
                # live tuning registers.
                reduce_body = self._reduce_body(plan.stages[0], 0, func, common)
                bcast_bin = plan.stages[1].algorithm == Algorithm.RNDZV_BIN_TREE

                def _ar_composed(x, *, _c=common, _rb=reduce_body,
                                 _bin=bcast_bin):
                    red = _rb(x)
                    if _bin:
                        return schedules.bcast_bin_tree_schedule(red, root=0, **_c)
                    return schedules.bcast_flat_schedule(red, root=0, **_c)

                body = _ar_composed
            else:
                elem_bytes = 1
                if options.data_type != DataType.none:
                    from ..constants import dtype_nbytes

                    elem_bytes = dtype_nbytes(options.data_type)
                eth_active = bool(
                    arithcfg is not None
                    and options.compression_flags & CompressionFlags.ETH_COMPRESSED
                    and wire_dtype(arithcfg) is not None
                )
                # the dtype the fused kernel would run in: the wire dtype
                # under compressed-domain execution, the payload dtype
                # otherwise. On real TPU, dtypes Mosaic rejects (f16) must
                # take the lax schedule — XLA carries f16 natively, so the
                # requested wire compression keeps its bandwidth meaning
                # (the kernel-level _compiled_f16_detour would silently
                # widen the wire back to fp32).
                from ..ops.pallas_kernels import _mosaic_rejects, _on_tpu

                from ..constants import to_numpy_dtype

                ring_dtype = (
                    wire_dtype(arithcfg) if compressed_domain
                    else (to_numpy_dtype(options.data_type)
                          if options.data_type != DataType.none else None)
                )
                mosaic_ok = not (
                    ring_dtype is not None
                    and _mosaic_rejects(ring_dtype)
                    and _on_tpu()
                )
                if (
                    self.use_pallas_ring
                    # per-hop compression with uncompressed-domain arithmetic
                    # cannot be fused into the single-dtype ring kernel —
                    # this also routes the blockwise-quantized wire (whose
                    # hops carry a scale side-channel) to the lax quantized
                    # ring below, where the fused dequant-reduce-requant
                    # kernels live
                    and (not eth_active or compressed_domain)
                    and mosaic_ok
                    # the degraded live-subset mode lowers through the lax
                    # ring, where the source mask is part of the traced
                    # body the certifier lifts (the VMEM kernel has no
                    # masked variant)
                    and not plan.live_ranks
                ):
                    from ..ops.ring_allreduce import (
                        NUM_RING_SLOTS,
                        ring_allreduce_pallas_bidir,
                    )

                    # Kernel-resource chunking: the VMEM-resident kernel
                    # caps per-launch payload, so larger buffers run it per
                    # segment. The kernel's neighbor-barrier/credit
                    # semaphores and comm buffers are keyed per SEGMENT
                    # SLOT (collective_id per slot, ring_allreduce
                    # NUM_RING_SLOTS), so consecutive segments
                    # double-buffer and overlap like the reference's
                    # segmenter/rx-ring; only slot reuse is ordered
                    # (segmented_apply overlap_slots). The serialized
                    # baseline stays reachable for A/B measurement via
                    # ACCL_PALLAS_RING_SERIALIZE=1. (Protocol
                    # segmentation — plan.seg_count — stays plan-owned and
                    # governs the lax path.)
                    seg_elems = max(self.PALLAS_RING_MAX_BYTES // elem_bytes, 1)

                    def one_seg(y, slot=0, *, _c=common, _f=func):
                        return ring_allreduce_pallas_bidir(
                            y, axis_name=_c["axis"], world=_c["world"],
                            func=_f, slot=slot,
                        )

                    def _pallas_ring_body(x, *, _c=common, _seg=seg_elems,
                                          _overlap=self.pallas_ring_overlap):
                        y = _c["wire"].send(x)  # wire compression outside
                        if _overlap:
                            out = schedules.segmented_apply(
                                one_seg, y, _seg,
                                overlap_slots=NUM_RING_SLOTS,
                            )
                        else:
                            out = schedules.segmented_apply(
                                one_seg, y, _seg, serialize=True
                            )
                        return _c["wire"].recv(out, x.dtype)

                    body = _pallas_ring_body
                else:
                    body = functools.partial(
                        schedules.allreduce_ring_schedule,
                        func=func,
                        seg_count=plan.seg_count,
                        # the serial dispatch->compute twin: stripe
                        # chains of an OVERLAP plan barrier-ordered
                        # (plain rx-geometry segmentation is untouched
                        # — only cost-model-striped plans have a twin)
                        serialize=(self.overlap_serialize
                                   and plan.stripes > 1),
                        # degraded live-subset mode: the declared
                        # survivor set masks non-members' operands to
                        # zeros at the source (None = every rank
                        # contributes, the ordinary ring)
                        live_ranks=(plan.live_ranks or None),
                        **common,
                    )
            n_in = 1
        elif op == Operation.alltoall:
            if plan.algorithm == Algorithm.FLAT_ALLTOALLV:
                body = functools.partial(
                    schedules.alltoallv_schedule,
                    peer_counts=plan.peer_counts, **common)
            else:
                body = functools.partial(schedules.alltoall_schedule,
                                         **common)
            n_in = 1
        elif op == Operation.barrier:
            body = functools.partial(schedules.barrier_schedule, **common)
            n_in = 1
        else:
            raise ValueError(f"cannot lower scenario {op!r}")

        if compressed_domain:
            inner, wd = body, wire_dtype(arithcfg)

            def _domain_cast_body(*args, _inner=inner, _wd=wd):
                orig = args[0].dtype
                out = _inner(*(a.astype(_wd) for a in args))
                return out.astype(orig)

            body = _domain_cast_body
        return body, n_in

    def _reduce_body(self, stage_plan: Plan, root: int, func, common):
        """The reduce stage of a composed collective, shaped by its
        re-selected plan (flat vs binomial, .c:1531 vs .c:1603)."""
        if stage_plan.algorithm == Algorithm.RNDZV_BIN_TREE:
            return functools.partial(
                schedules.reduce_bin_tree_schedule, root=root, func=func, **common
            )
        if stage_plan.algorithm == Algorithm.EAGER_RING:
            return functools.partial(
                schedules.reduce_ring_schedule, root=root, func=func, **common
            )
        return functools.partial(
            schedules.reduce_flat_schedule, root=root, func=func, **common
        )

    # -- call sequences ----------------------------------------------------

    def compile_sequence(self, seq) -> Callable:
        """Lower a SequencePlan into ONE compiled device program: every
        step's schedule body composed over the batch's buffer table inside
        a single jit(shard_map(...)). Cached under the batch's composite
        signature alongside the per-call entries, so re-recording the same
        shapes+dataflow compiles nothing."""
        key = seq.cache_key(self.axis_name, self.use_pallas_ring,
                            self.pallas_ring_overlap,
                            self.overlap_serialize)
        fn = self._cache.get(key)
        if fn is None:
            from ..utils.logging import Log

            Log.info(
                "compiling sequence of %d steps: %s world=%d",
                len(seq.steps),
                "+".join(s.options.scenario.name for s in seq.steps),
                self.world,
            )
            body, n_in = seq.build(self)
            fn = self._finalize_sequence(body, n_in)
            self._cache[key] = fn
        return fn

    def _finalize_sequence(self, body, n_in: int) -> Callable:
        # kept as a distinct seam (tests pin it to detect re-traces)
        return self._finalize(body, n_in, wrap=_tuple_wrap)

    # -- convenience: full pipeline from descriptor ------------------------

    def lower(self, options: CallOptions, plan: Plan) -> Callable:
        arithcfg = None
        if options.data_type != DataType.none:
            arithcfg = _arithcfg_for(self.arith_table, options)
        return self.compile(options, plan, arithcfg)


class AxisOnlyMesh:
    """The minimal mesh surface `ScheduleCompiler._body` consumes (axis
    size lookup); tracing under make_jaxpr's axis env needs no
    devices."""

    def __init__(self, axis_name: str, world: int):
        self.shape = {axis_name: world}


def analysis_body(options: CallOptions, plan: Plan, world: int,
                  axis_name: str = "ccl",
                  arith_table: dict | None = None) -> tuple[Callable, int]:
    """The IR-extraction hook for the static analyzers: build the SAME
    schedule body the compiler would lower — nothing re-modeled — for
    abstract evaluation under an axis environment. Pallas lowering is
    forced off (the lax family expresses the identical wire pattern
    through ppermute, which is the surface the analyses read); the
    protocol pass collects the traced body's ppermute perms and the
    semantic certifier lifts its full hop DAG from it."""
    comp = ScheduleCompiler(AxisOnlyMesh(axis_name, world), axis_name,
                            arith_table=arith_table,
                            use_pallas_ring=False)
    arithcfg = None
    if options.data_type != DataType.none:
        arithcfg = _arithcfg_for(comp.arith_table, options)
    return comp._body(options, plan, arithcfg)


def _arithcfg_for(table, options: CallOptions):
    dt = options.data_type
    if options.compress_dtype != DataType.none:
        # The caller named a wire dtype (prepare_call's compressed-operand
        # resolution): the row must match exactly.
        return table.get((dt, options.compress_dtype))
    if options.compression_flags & CompressionFlags.ETH_COMPRESSED:
        for (unc, cmp_), cfg in table.items():
            if unc == dt and unc != cmp_:
                return cfg
    return table.get((dt, dt))


def _squeeze_wrap(body, n_in):
    """shard_map hands each rank a (1, n) shard of the stacked (world, n)
    operand; schedules work on flat (n,) buffers."""

    def wrapped(*args):
        flat = [a.reshape(a.shape[-1]) for a in args]
        out = body(*flat)
        return out.reshape(1, out.shape[-1])

    return wrapped


def _tuple_wrap(body, n_in):
    """The call-sequence calling convention: the fused body returns one
    flat buffer per written address; each reshapes back to a (1, n)
    shard."""

    def wrapped(*args):
        flat = [a.reshape(a.shape[-1]) for a in args]
        outs = body(*flat)
        return tuple(o.reshape(1, o.shape[-1]) for o in outs)

    return wrapped
