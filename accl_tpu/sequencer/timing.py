"""Per-hop timing model: the cclo_sim slot, TPU-idiomatically.

The reference ships a second, cycle-accurate simulation target whose job
is to answer "how long does this schedule take?" before hardware runs it
(test/model/simulator/cclo_sim.cpp:25-80 driving the RTL through XSI,
xsi_dut.cpp:1-172). An RTL clock makes no sense for XLA programs, so the
TPU-native fill for that slot is an analytic alpha-beta cost model over
the SAME algorithm structures the two executors run
(sequencer/schedules.py / native runtime do_*):

    T(call) = alpha * messages_on_critical_path
            + bytes_on_critical_path / beta

with per-link parameters calibrated from measured sweeps (the emulator
benchmark CSV or the TPU profile). Rendezvous messages count their
address handshake as an extra message, exactly the extra wire round trip
the protocol pays.

Two uses:
  - predict(): expected seconds for a planned call — schedule selection
    can be evaluated as a PERFORMANCE choice, not just a control-flow
    choice;
  - tuning_crossovers(): the model's own switch-over points for the five
    tuning registers (accl.cpp:1198-1208 defaults), so the defaults are
    validated against measurements instead of taken on faith.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

from ..constants import (
    DataType,
    Operation,
    QUANT_BLOCK_ELEMS,
    QUANT_SCALE_BYTES,
    STREAM_SEG_BYTES,
    dtype_nbytes,
    logp_allgather_max_bytes,
    logp_allreduce_max_bytes,
)
from .plan import Algorithm, Plan, Protocol


def wire_elem_bytes(elem_bytes: int, wire: DataType) -> float:
    """Effective bytes-per-element ON THE WIRE for a hop under the given
    wire dtype: cast lanes travel at the cast width, the blockwise int8
    lanes at 1 B plus the amortized per-block fp32 scale, and
    DataType.none at the payload width. This is the width predict() and
    the crossover scan charge — ETH_COMPRESSED calls must not be billed
    uncompressed bytes (they would never show the compression win the
    wire actually delivers)."""
    if wire == DataType.none:
        return float(elem_bytes)
    wb = float(dtype_nbytes(wire))
    if wire == DataType.int8:
        wb += QUANT_SCALE_BYTES / QUANT_BLOCK_ELEMS
    return wb


@dataclasses.dataclass(frozen=True)
class LinkParams:
    """alpha: seconds of fixed cost per message on the critical path
    (dispatch + header + matching); beta: sustained payload bytes/second
    of one link direction."""

    alpha: float
    beta: float

    def seconds(self, messages: float, nbytes: float) -> float:
        return self.alpha * messages + nbytes / self.beta


@dataclasses.dataclass(frozen=True)
class ComputeFit:
    """The measured busy-core term of the compute-communication overlap
    pipeline: seconds the compute stage spliced next to a collective
    needs to materialize `nbytes` of operand (the gradient bytes of a
    train step's backward). `alpha` is the fixed per-step cost
    (dispatch + bookkeeping of the compute stage), `rate` the sustained
    operand bytes produced per second. Calibrated from telemetry spans
    (telemetry.feedback.calibrate_compute_from_trace) the same way
    LinkParams is calibrated from hop spans — the compute term is a
    measured quantity, never an assumption. The fit is per workload
    family (bytes-of-gradient is a proxy for the model's backward cost
    at a fixed batch shape); re-calibrate when the workload changes."""

    alpha: float
    rate: float

    def seconds(self, nbytes: float) -> float:
        return self.alpha + nbytes / self.rate


def _nonneg_lstsq2(rows: list, y_vals: list) -> tuple[float, float]:
    """The shared two-parameter fit of the link and compute
    calibrations: column-scaled least squares (well-conditioned across
    the 1 KB-1 GB dynamic range) clamped non-negative (a degenerate
    sweep clamps at zero rather than producing a negative cost)."""
    import numpy as np

    A = np.array(rows, float)
    y = np.array(y_vals, float)
    scale = A.max(axis=0)
    scale[scale == 0] = 1.0
    x, *_ = np.linalg.lstsq(A / scale, y, rcond=None)
    x = np.maximum(x / scale, 0.0)
    return float(x[0]), float(x[1])


def calibrate_compute(samples: list[tuple[float, float]]) -> ComputeFit:
    """Least-squares fit of (alpha, 1/rate) from samples of
    (operand_bytes, measured_seconds) of the compute stage — the same
    non-negative clamped solve `calibrate` uses for the link."""
    alpha, inv_rate = _nonneg_lstsq2([[1.0, b] for b, _ in samples],
                                     [t for _, t in samples])
    if inv_rate <= 0:
        inv_rate = 1e-12  # latency-flat samples: effectively infinite rate
    return ComputeFit(alpha=alpha, rate=1.0 / inv_rate)


@dataclasses.dataclass(frozen=True)
class TierLinks:
    """Per-tier link parameters of a two-tier world: `inner` is the
    fast intra-slice link (ICI / local POE), `outer` the slow
    cross-slice link (DCN / TCP). Each tier is calibrated
    independently — telemetry.feedback.calibrate_tiers_from_trace
    refits each from its own tier-tagged spans — so the hierarchical
    predictions charge every phase's wire bytes to the link it actually
    crosses (HiCCL's per-tier-model posture)."""

    inner: LinkParams
    outer: LinkParams

    def of(self, tier: str) -> LinkParams:
        if tier == "inner":
            return self.inner
        if tier == "outer":
            return self.outer
        raise ValueError(f"unknown tier {tier!r}")


def emulator_link(model: dict[str, Any]) -> LinkParams:
    """The emulator-tier LinkParams of a timing-model document: the
    bcast per-collective row (the root-serialized collective whose
    aggregate and critical-path shapes coincide, so its alpha/beta are
    genuine per-message/per-byte host costs), with fallback to the
    legacy single-"link" key. The ONE resolution rule shared by
    ACCL.autotune, bench.py --check, and tools/accl_synth — a schema
    change lands here or nowhere."""
    lk = (model.get("link_per_collective", {}).get("bcast")
          or model.get("link"))
    if not lk:
        raise ValueError("timing model has neither link_per_collective "
                         "nor link; re-run tools/timing_model.py")
    return LinkParams(alpha=lk["alpha_us"] * 1e-6,
                      beta=lk["beta_gbps"] * 1e9)


def _segs(nbytes: int, rx_buf_bytes: int) -> int:
    return max(1, math.ceil(nbytes / max(rx_buf_bytes, 1)))


# The native runtime streams ring/tree hop payloads as jumbo-segment
# messages (runtime.cpp egr_send callers): one message latency per hop
# regardless of the rx-buffer geometry. Single-sourced with the executor
# in constants.py (tests/test_timing.py pins them to the C++ source).
_STREAM_SEG = STREAM_SEG_BYTES


def _logp_allreduce(world: int, nbytes: int) -> bool:
    """Mirror of the native hop-shape auto rule (runtime.cpp
    logp_max_bytes): power-of-two worlds run recursive halving-doubling
    while the payload is under the crossover bytes per hop saved. The
    crossover arithmetic lives in constants.logp_allreduce_max_bytes —
    the single source pinned against runtime.cpp — so a retune cannot
    desynchronize this model from the executor it predicts."""
    if world & (world - 1):
        return False
    return nbytes <= logp_allreduce_max_bytes(world)


def _logp_allgather(world: int, total_bytes: int) -> bool:
    """Native logp_ag_max_bytes rule: recursive doubling for small total
    payloads on power-of-two worlds (crossover single-sourced in
    constants.logp_allgather_max_bytes, like _logp_allreduce)."""
    if world & (world - 1):
        return False
    return total_bytes <= logp_allgather_max_bytes(world)


def _logp_forced(world: int, auto: bool, logp_shape: bool | None) -> bool:
    """Resolve the logp-vs-ring hop shape: the auto crossover rule by
    default, or the caller's override mirroring the native executor's
    ACCL_RT_SHAPE forcing (which, like the native rule, still requires
    a power-of-two world)."""
    if logp_shape is None:
        return auto
    return logp_shape and not (world & (world - 1))


def coefficients(
    op: Operation,
    plan: Plan,
    count: int,
    elem_bytes: int,
    world: int,
    *,
    rx_buf_bytes: int,
    logp_shape: bool | None = None,
) -> tuple[float, float]:
    """(messages, bytes) on the CRITICAL PATH of the planned schedule —
    the busiest serialized sequence of hops, mirroring the structures in
    schedules.py / the native do_* bodies. Rendezvous messages count 2
    (address notification + one-sided write). Bytes are WIRE bytes: a
    plan with an active wire_dtype charges the compressed element width
    (+ scale side-channel for the quantized lanes), and its segment
    counts follow the compressed payload too. `logp_shape` overrides the
    allreduce/allgather logp-vs-ring auto rule (True/False = the native
    ACCL_RT_SHAPE=logp/ring forcing; None = auto) so forced-shape sweep
    rows are costed on the schedule that actually ran."""
    n = count * wire_elem_bytes(elem_bytes, plan.wire_dtype)
    P = world
    if P <= 1 or plan.algorithm == Algorithm.NONE:
        return 0.0, 0.0
    alg = plan.algorithm
    if alg == Algorithm.SYNTHESIZED:
        # the cost shape lives with the library entry: per-step send
        # sizes of the synthesized hop-DAG, wire bytes included (the
        # int8 entries carry their own encode/decode lanes)
        from .synthesis import cost_shape, entry_for_key

        return cost_shape(entry_for_key(plan.synth_key).spec, count,
                          elem_bytes, aggregate=False)
    if alg == Algorithm.HIER_RS_AR_AG:
        # single-link fallback (the flat-link callers: refit sampling,
        # facade prediction): all phases summed over all stripes, both
        # tiers charged to the one link. The calibrated per-tier,
        # pipelined prediction is predict_tiered.
        return _hier_flat_cost(plan, count, elem_bytes, aggregate=False)
    s = _segs(n, rx_buf_bytes)  # eager segments per full-payload message

    if alg == Algorithm.EAGER_SENDRECV:
        return s, n
    if alg == Algorithm.RNDZV_SENDRECV:
        return 2, n
    if alg == Algorithm.EAGER_FLAT:
        # root serializes P-1 sends of n each (scatter's `count` is
        # already per-chunk by the descriptor convention, so n covers
        # both bcast and scatter)
        return (P - 1) * _segs(n, rx_buf_bytes), (P - 1) * n
    if alg == Algorithm.EAGER_RING:
        # daisy chain: P-1 sequential whole-payload streamed hops
        if op == Operation.allgather and \
                _logp_forced(P, _logp_allgather(P, P * n), logp_shape):
            # native recursive doubling: log2(P) steps, same volume
            return math.log2(P), (P - 1) * n
        return (P - 1) * _segs(n, _STREAM_SEG), (P - 1) * n
    if alg == Algorithm.EAGER_RING_RS_AG:
        S = max(plan.stripes, 1)
        if S > 1:
            # stripe-overlapped plan, SERIAL shape: the S independent
            # RS+AG chains run back to back (the dispatch->compute
            # form), so messages multiply by S while total wire bytes
            # stay 2n(P-1)/P. The pipelined (overlapped) form is
            # predict_overlapped — this is deliberately the cost of
            # NOT overlapping, so serial callers (the eager twin, the
            # crossover scan's baseline) are charged honestly. Striped
            # plans never take the logp shape: the stripes exist to
            # pipeline the ring.
            chunk = (n / S) / P
            return S * 2 * (P - 1) * _segs(int(chunk), _STREAM_SEG), \
                2 * (P - 1) * (n / P)
        chunk = n / P
        if _logp_forced(P, _logp_allreduce(P, n), logp_shape):
            # native recursive halving-doubling: 2*log2(P) exchange
            # steps carrying n(1-1/P) bytes per phase
            return 2 * math.log2(P), 2 * (P - 1) * chunk
        # ring: 2(P-1) steps of the 1/P chunk, streamed whole
        return 2 * (P - 1) * _segs(int(chunk), _STREAM_SEG), \
            2 * (P - 1) * chunk
    if alg == Algorithm.RNDZV_FLAT_TREE:
        if op in (Operation.gather, Operation.reduce):
            # handshakes overlap; P-1 one-sided writes serialize into the
            # root's link
            return 2.0, (P - 1) * n
        # bcast/scatter: root serializes P-1 rendezvous sends
        return 2 * (P - 1), (P - 1) * n
    if alg == Algorithm.RNDZV_BIN_TREE:
        r = math.ceil(math.log2(P)) if P > 1 else 0
        return 2 * r, r * n
    if alg == Algorithm.RNDZV_RING:
        # the native executor streams the allgather ring eagerly at every
        # size now (no per-hop address handshake), so a rendezvous-size
        # allgather costs ring hops, not 2x handshake messages
        if op == Operation.allgather:
            if _logp_forced(P, _logp_allgather(P, P * n), logp_shape):
                return math.log2(P), (P - 1) * n
            return (P - 1) * _segs(n, _STREAM_SEG), (P - 1) * n
        return 2 * (P - 1), (P - 1) * n
    if alg in (Algorithm.RNDZV_REDUCE_BCAST,
               Algorithm.RNDZV_REDUCE_SCATTER):
        # compositions carry their per-stage plans (plan.py resolves them
        # with the same tuning registers): sum the stages back to back
        if alg == Algorithm.RNDZV_REDUCE_BCAST:
            stage_ops = (Operation.reduce, Operation.bcast)
            stage_counts = (count, count)
        else:
            stage_ops = (Operation.reduce, Operation.scatter)
            stage_counts = (count * world, count)
        tm = tb = 0.0
        for sub_op, sub_count, sub_plan in zip(stage_ops, stage_counts,
                                               plan.stages):
            m, b = coefficients(sub_op, sub_plan, sub_count, elem_bytes,
                                world, rx_buf_bytes=rx_buf_bytes)
            tm += m
            tb += b
        return tm, tb
    if alg == Algorithm.FLAT_ALLTOALL:
        # pairwise rotation (.c:2140-2211): P-1 steps, each shipping one
        # `count`-element peer chunk per rank; eager exchanges stream
        # whole chunks (jumbo segments) since r5. Bytes are WIRE bytes
        # (n already charges wire_elem_bytes), so the int8 lane's
        # ~3.94x reduction shows up here — this is the shape the
        # ALLTOALL_COMPRESS_MIN_COUNT crossover scans.
        per = 2 if plan.protocol == Protocol.RENDEZVOUS else \
            _segs(n, _STREAM_SEG)
        return (P - 1) * per, (P - 1) * n
    if alg == Algorithm.FLAT_ALLTOALLV:
        # capacity-bounded rotation: same P-1 steps, but every hop moves
        # vmax = max(peer_counts) elements (the SPMD-uniform hop shape
        # schedules.alltoallv_schedule pads to), not the full slot
        nv = max(plan.peer_counts) * wire_elem_bytes(elem_bytes,
                                                     plan.wire_dtype)
        per = 2 if plan.protocol == Protocol.RENDEZVOUS else \
            _segs(int(nv), _STREAM_SEG)
        return (P - 1) * per, (P - 1) * nv
    if alg == Algorithm.BARRIER_GATHER_SCATTER:
        return 2 * (P - 1), 0.0
    raise ValueError(f"no cost shape for {alg}")


def coefficients_aggregate(
    op: Operation,
    plan: Plan,
    count: int,
    elem_bytes: int,
    world: int,
    *,
    rx_buf_bytes: int,
    logp_shape: bool | None = None,
) -> tuple[float, float]:
    """(messages, bytes) SUMMED OVER ALL RANKS — the cost shape a
    serialized host actually pays. The emulator runs its whole world on
    one CI core (accl_log/REPORT.md r5 analysis), so wall time tracks
    the total work moved through the machine, not the critical path:
    fitting this shape per collective put the fitted beta at the
    measured ~1.4-2 GB/s transport rate and the median error under
    1.15x, where the critical-path shape was 1.9-3x off. The
    critical-path `coefficients` remain the model for parallel hardware
    (the TPU tier and the tuning-register crossovers). Bytes are WIRE
    bytes and `logp_shape` forces the logp-vs-ring hop shape (see
    `coefficients`)."""
    n = count * wire_elem_bytes(elem_bytes, plan.wire_dtype)
    P = world
    if P <= 1 or plan.algorithm == Algorithm.NONE:
        return 0.0, 0.0
    alg = plan.algorithm
    if alg == Algorithm.SYNTHESIZED:
        from .synthesis import cost_shape, entry_for_key

        return cost_shape(entry_for_key(plan.synth_key).spec, count,
                          elem_bytes, aggregate=True)
    if alg == Algorithm.HIER_RS_AR_AG:
        return _hier_flat_cost(plan, count, elem_bytes, aggregate=True)
    r = math.ceil(math.log2(P)) if P > 1 else 0

    if alg in (Algorithm.EAGER_SENDRECV, Algorithm.RNDZV_SENDRECV,
               Algorithm.EAGER_FLAT, Algorithm.RNDZV_FLAT_TREE,
               Algorithm.BARRIER_GATHER_SCATTER):
        # root-serialized (or point-to-point) shapes: the critical path
        # IS the aggregate
        return coefficients(op, plan, count, elem_bytes, world,
                            rx_buf_bytes=rx_buf_bytes)
    if alg == Algorithm.EAGER_RING:
        if op == Operation.allgather:
            if _logp_forced(P, _logp_allgather(P, P * n), logp_shape):
                return P * r, P * (P - 1) * n
            return P * (P - 1) * _segs(n, _STREAM_SEG), P * (P - 1) * n
        if op == Operation.reduce:
            # fused recv-reduce-send chain: each non-root sends its
            # combined partial exactly once
            return (P - 1) * _segs(n, _STREAM_SEG), (P - 1) * n
        if op == Operation.reduce_scatter:
            # every rank relays P-1 chunk messages around the ring
            return P * (P - 1) * _segs(n, _STREAM_SEG), P * (P - 1) * n
        # gather daisy chain to root: rank at distance k relays k messages
        return P * (P - 1) / 2 * _segs(n, _STREAM_SEG), P * (P - 1) / 2 * n
    if alg == Algorithm.EAGER_RING_RS_AG:
        S = max(plan.stripes, 1)
        if S > 1:
            # striped serial shape summed over all ranks (see the
            # critical-path branch): S x the message count, same bytes
            chunk = (n / S) / P
            return S * 2 * P * (P - 1) * _segs(int(chunk), _STREAM_SEG), \
                2 * (P - 1) * n
        chunk = n / P
        if _logp_forced(P, _logp_allreduce(P, n), logp_shape):
            return 2 * P * r, 2 * (P - 1) * n
        return 2 * P * (P - 1) * _segs(int(chunk), _STREAM_SEG), \
            2 * (P - 1) * n
    if alg == Algorithm.RNDZV_BIN_TREE:
        # every non-root gets exactly one payload (bcast) / sends one
        # partial (reduce): handshake + write per edge
        return 2 * (P - 1), (P - 1) * n
    if alg == Algorithm.RNDZV_RING:
        if op == Operation.allgather:
            if _logp_forced(P, _logp_allgather(P, P * n), logp_shape):
                return P * r, P * (P - 1) * n
            return P * (P - 1) * _segs(n, _STREAM_SEG), P * (P - 1) * n
        return 2 * P * (P - 1), P * (P - 1) * n
    if alg in (Algorithm.RNDZV_REDUCE_BCAST,
               Algorithm.RNDZV_REDUCE_SCATTER):
        if alg == Algorithm.RNDZV_REDUCE_BCAST:
            stage_ops = (Operation.reduce, Operation.bcast)
            stage_counts = (count, count)
        else:
            stage_ops = (Operation.reduce, Operation.scatter)
            stage_counts = (count * world, count)
        tm = tb = 0.0
        for sub_op, sub_count, sub_plan in zip(stage_ops, stage_counts,
                                               plan.stages):
            m, b = coefficients_aggregate(sub_op, sub_plan, sub_count,
                                          elem_bytes, world,
                                          rx_buf_bytes=rx_buf_bytes)
            tm += m
            tb += b
        return tm, tb
    if alg == Algorithm.FLAT_ALLTOALL:
        # eager exchanges stream whole chunks (jumbo segments) since r5
        per = 2 if plan.protocol == Protocol.RENDEZVOUS else \
            _segs(n, _STREAM_SEG)
        return P * (P - 1) * per, P * (P - 1) * n
    if alg == Algorithm.FLAT_ALLTOALLV:
        nv = max(plan.peer_counts) * wire_elem_bytes(elem_bytes,
                                                     plan.wire_dtype)
        per = 2 if plan.protocol == Protocol.RENDEZVOUS else \
            _segs(int(nv), _STREAM_SEG)
        return P * (P - 1) * per, P * (P - 1) * nv
    raise ValueError(f"no aggregate cost shape for {alg}")


def _hier_flat_cost(plan: Plan, count: int, elem_bytes: int, *,
                    aggregate: bool) -> tuple[float, float]:
    """All stripes of all phases summed onto ONE link — the cost shape
    coefficients/coefficients_aggregate expose for HIER plans to
    single-link consumers."""
    S = max(plan.stripes, 1)
    tm = tb = 0.0
    for _tier, m, b in hier_phase_costs(plan, count, elem_bytes,
                                        aggregate=aggregate):
        tm += S * m
        tb += S * b
    return tm, tb


def hier_phase_costs(
    plan: Plan,
    count: int,
    elem_bytes: int,
    *,
    aggregate: bool = False,
) -> list[tuple[str, float, float]]:
    """(tier, messages, bytes) of the three phases of ONE STRIPE of the
    striped hierarchical allreduce (Algorithm.HIER_RS_AR_AG):

        1. inner reduce-scatter  — (L-1) ring hops of the 1/L chunk
        2. outer allreduce       — 2(P-1) ring hops of the 1/(L*P) chunk
        3. inner allgather       — (L-1) ring hops of the 1/L chunk

    Bytes are WIRE bytes PER TIER: phase 1/3 charge the inner wire
    dtype, phase 2 the outer one — this is the accounting that lets
    `select_tier_wires` see int8-on-DCN as a win without pretending ICI
    compressed too. aggregate=True sums over all ranks (the
    serialized-host regime); default is the per-link critical path."""
    L, P = max(plan.inner_world, 1), max(plan.outer_world, 1)
    S = max(plan.stripes, 1)
    stripe = -(-count // S)  # ceil
    padded = stripe + (-stripe) % L
    chunk = padded // L  # elements of one inner chunk == the outer shard
    n_i = chunk * wire_elem_bytes(elem_bytes, plan.inner_wire_dtype)
    shard_pad = chunk + (-chunk) % P
    n_o = (shard_pad // P) * wire_elem_bytes(elem_bytes,
                                             plan.outer_wire_dtype)
    m_rs = (L - 1) * _segs(int(n_i), _STREAM_SEG)
    b_rs = (L - 1) * n_i
    m_ar = 2 * (P - 1) * _segs(int(n_o), _STREAM_SEG)
    b_ar = 2 * (P - 1) * n_o
    if aggregate:
        # every rank runs every phase; a serialized host pays all of it
        world = L * P
        return [("inner", world * m_rs, world * b_rs),
                ("outer", world * m_ar, world * b_ar),
                ("inner", world * m_rs, world * b_rs)]
    return [("inner", m_rs, b_rs), ("outer", m_ar, b_ar),
            ("inner", m_rs, b_rs)]


def predict_tiered(
    links: TierLinks,
    plan: Plan,
    count: int,
    elem_bytes: int,
    *,
    aggregate: bool = False,
) -> float:
    """Expected seconds for a striped hierarchical allreduce plan with
    each phase charged to ITS OWN tier link, software pipelining
    included: the S stripes' chains overlap across the two link
    resources, so

        T = t_rs + t_ar + t_ag + (S - 1) * max(t_rs + t_ag, t_ar)

    — fill + drain of the pipeline plus S-1 repetitions of the
    bottleneck tier (the inner link runs both RS and AG, the outer link
    runs the shard allreduce; whichever is busier paces the steady
    state). aggregate=True models the serialized host, where nothing
    overlaps: T = S * sum(phases)."""
    phases = hier_phase_costs(plan, count, elem_bytes, aggregate=aggregate)
    t = [links.of(tier).seconds(m, b) for tier, m, b in phases]
    S = max(plan.stripes, 1)
    if aggregate:
        return S * sum(t)
    inner_busy = t[0] + t[2]
    outer_busy = t[1]
    return sum(t) + (S - 1) * max(inner_busy, outer_busy)


def best_stripes(
    links: TierLinks,
    count: int,
    elem_bytes: int,
    inner_world: int,
    outer_world: int,
    *,
    inner_wire: DataType = DataType.none,
    outer_wire: DataType = DataType.none,
    candidates: tuple[int, ...] = (1, 2, 4, 8),
    aggregate: bool = False,
) -> int:
    """The cost model's stripe count for a hierarchical allreduce: the
    S minimizing the pipelined prediction (ties break toward fewer
    stripes — less padding, smaller program). This is the ONLY source
    of Plan.stripes, so S is a measured-model decision, never a
    hardcoded constant."""
    best_s, best_t = 1, float("inf")
    for s in candidates:
        if s > max(count, 1):
            continue
        plan = Plan(Protocol.EAGER, Algorithm.HIER_RS_AR_AG, count, 1,
                    inner_world=inner_world, outer_world=outer_world,
                    stripes=s, inner_wire_dtype=inner_wire,
                    outer_wire_dtype=outer_wire)
        t = predict_tiered(links, plan, count, elem_bytes,
                           aggregate=aggregate)
        if t < best_t - 1e-15:
            best_s, best_t = s, t
    return best_s


def predict_synth_tiered(
    links: TierLinks,
    plan: Plan,
    count: int,
    elem_bytes: int,
    *,
    aggregate: bool = False,
) -> float:
    """Per-tier prediction for a SYNTHESIZED plan whose library entry
    is TIERED (synthesis.SynthSpec.tiers): every hop charged against
    its own TierLinks entry — the hier_phase_costs accounting
    generalized to tier-annotated hop-DAGs. The flat
    coefficients/predict path keeps charging both tiers to one link
    for single-link consumers (facade prediction, refit sampling);
    this is the calibrated form selection arbitrates with inside the
    HIER_ALLREDUCE_MIN_COUNT window."""
    from .synthesis import entry_for_key, predict_spec_tiered

    return predict_spec_tiered(links, entry_for_key(plan.synth_key).spec,
                               count, elem_bytes, aggregate=aggregate)


def predict_overlapped(
    params: LinkParams,
    plan: Plan,
    count: int,
    elem_bytes: int,
    world: int,
    *,
    compute_s: float,
    rx_buf_bytes: int,
    serial: bool = False,
) -> float:
    """Busy-link vs busy-core pipelined prediction for a
    stripe-overlapped eager ring allreduce (Plan.stripes = S on
    EAGER_RING_RS_AG) running next to the compute stage that produces
    its operand — the PR 8 fill + drain + (S-1)*max(...) pipeline shape
    generalized with a measured per-stripe compute term:

        T_overlap = c + lam + (S - 1) * max(c, o)
        T_serial  = compute_s + S * lam        (serial=True)

    where c = compute_s / S is the per-stripe busy-CORE term (the
    measured ComputeFit evaluation, split across stripes the way the
    backward materializes gradient stripes), lam the full critical-path
    latency of ONE stripe's RS+AG chain (every per-message fixed cost
    included — this is the pipeline's fill and drain), and o the
    per-stripe steady-state busy-LINK term: the stripe's wire bytes
    plus ONE per-message fixed cost. In steady state the sequencer
    injects one stripe at a time (one fixed cost each) while the
    remaining 2(P-1)-1 hop latencies of that stripe pipeline behind
    neighbouring stripes' compute and wire — alpha is dispatch +
    header + matching work (see LinkParams), not link occupancy, so
    independent chains amortize it; only the drain (the last stripe,
    with nothing left to hide behind) pays the whole chain latency.

    serial=True is the dispatch->compute form: all compute, then the S
    stripe chains back to back — the cost of the bitwise-identical
    serial twin (the same shape `coefficients` charges striped plans).
    """
    S = max(plan.stripes, 1)
    stripe = -(-count // S)
    sp = Plan(Protocol.EAGER, Algorithm.EAGER_RING_RS_AG, stripe, 1,
              wire_dtype=plan.wire_dtype)
    # logp_shape=False: a striped plan always lowers the ring chains
    # (the stripes exist to pipeline them), so the per-stripe cost
    # must never flip to the recursive halving-doubling shape the
    # unstriped auto rule would pick at small stripe payloads —
    # matching the striped branch of `coefficients` exactly
    m, b = coefficients(Operation.allreduce, sp, stripe, elem_bytes,
                        world, rx_buf_bytes=rx_buf_bytes,
                        logp_shape=False)
    lam = params.seconds(m, b)
    if serial or S == 1:
        return compute_s + S * lam
    occ = params.seconds(min(m, 1.0), b)
    c = compute_s / S
    return c + lam + (S - 1) * max(c, occ)


def best_overlap_stripes(
    params: LinkParams,
    count: int,
    elem_bytes: int,
    world: int,
    *,
    compute_s: float,
    rx_buf_bytes: int,
    candidates: tuple[int, ...] = (1, 2, 4, 8),
) -> int:
    """The cost model's stripe count for an overlapped gradient
    allreduce: the S minimizing the pipelined prediction (ties break
    toward fewer stripes — less padding, smaller program). Like
    best_stripes for the hierarchical composition, this is the ONLY
    source of an overlap plan's Plan.stripes, so S is a measured-model
    decision, never a hardcoded constant."""
    best_s, best_t = 1, float("inf")
    for s in candidates:
        if s > 1 and s * world > max(count, 1):
            continue  # every stripe must hold at least one world chunk
        plan = Plan(Protocol.EAGER, Algorithm.EAGER_RING_RS_AG, count, 1,
                    stripes=s)
        t = predict_overlapped(params, plan, count, elem_bytes, world,
                               compute_s=compute_s,
                               rx_buf_bytes=rx_buf_bytes)
        if t < best_t - 1e-15:
            best_s, best_t = s, t
    return best_s


def predict(
    params: LinkParams,
    op: Operation,
    plan: Plan,
    count: int,
    elem_bytes: int,
    world: int,
    *,
    rx_buf_bytes: int,
    aggregate: bool = False,
) -> float:
    """Expected seconds for the planned call on a link with `params`.
    aggregate=True uses the serialized-host cost shape (emulator tier);
    default is the critical path (parallel hardware)."""
    fn = coefficients_aggregate if aggregate else coefficients
    m, b = fn(op, plan, count, elem_bytes, world,
              rx_buf_bytes=rx_buf_bytes)
    return params.seconds(m, b)


def sequence_coefficients(
    calls: list[tuple[Operation, Plan, int, int]],
    world: int,
    *,
    rx_buf_bytes: int,
    aggregate: bool = False,
) -> tuple[float, float]:
    """(messages, bytes) for a recorded call sequence: the per-call cost
    shapes summed back to back (stages of a sequence serialize on their
    data dependencies, like the composed-collective shapes above).
    `calls` entries are (op, plan, count, elem_bytes)."""
    fn = coefficients_aggregate if aggregate else coefficients
    tm = tb = 0.0
    for op, plan, count, elem_bytes in calls:
        m, b = fn(op, plan, count, elem_bytes, world,
                  rx_buf_bytes=rx_buf_bytes)
        tm += m
        tb += b
    return tm, tb


def predict_sequence(
    params: LinkParams,
    calls: list[tuple[Operation, Plan, int, int]],
    world: int,
    *,
    rx_buf_bytes: int,
    aggregate: bool = False,
    dispatch_alpha: float = 0.0,
    fused: bool = True,
    compute_s: float = 0.0,
) -> float:
    """Expected seconds for a recorded sequence of calls.

    The wire work is identical either way; what fusion buys is the host
    seam: an eager sequence pays one program dispatch (plus the HBM
    materialization XLA cannot fuse across) PER CALL, a fused sequence
    pays exactly one for the whole batch. `dispatch_alpha` is that
    per-dispatch host cost (the timing model's dispatch_alpha_us tier
    or a measured per-call floor); fused=False models the eager chain
    so callers can evaluate fusion as a PERFORMANCE choice:

        gain = predict_sequence(..., fused=False) - predict_sequence(...)
             = (len(calls) - 1) * dispatch_alpha

    `compute_s` is the measured busy-core term of a compute stage
    recorded next to the collectives (a ComputeFit evaluation — the
    train step's backward spliced as a stream endpoint). A FUSED
    sequence containing a stripe-overlapped allreduce (Plan.stripes >
    1 on EAGER_RING_RS_AG) overlaps that compute with the wire through
    the busy-link vs busy-core pipeline (predict_overlapped); every
    other form — serial dispatch->compute, or no striped plan — pays
    compute + wire back to back (`coefficients` already charges a
    striped plan's serial chains S x their messages)."""
    olap = 0.0
    overlapped = False
    rest = []
    for call in calls:
        op, plan, count, elem_bytes = call
        if (fused and not aggregate and not overlapped and compute_s > 0
                and op == Operation.allreduce
                and plan.algorithm == Algorithm.EAGER_RING_RS_AG
                and plan.stripes > 1):
            olap = predict_overlapped(
                params, plan, count, elem_bytes, world,
                compute_s=compute_s, rx_buf_bytes=rx_buf_bytes)
            overlapped = True
            continue
        rest.append(call)
    tm, tb = sequence_coefficients(rest, world, rx_buf_bytes=rx_buf_bytes,
                                   aggregate=aggregate)
    n_dispatch = 1 if fused else max(len(calls), 1)
    t = params.seconds(tm, tb) + dispatch_alpha * n_dispatch + olap
    if not overlapped:
        t += compute_s
    return t


def predict_prepared(
    params: LinkParams,
    steps,
    plans,
    world: int,
    *,
    rx_buf_bytes: int,
    aggregate: bool = True,
    dispatch_alpha: float = 0.0,
) -> float:
    """Expected seconds for ONE dispatch of a prepared descriptor batch
    — the admission-control price of a tenant's steady-state step.

    `steps` are the batch's resolved CallOptions and `plans` the Plans
    they froze to (a _PreparedSequence's `desc.steps` / `plans`); steps
    whose plan never resolved (stream endpoints spliced at the seams)
    carry no wire cost and are skipped. Aggregate cost shape by default
    — the regime the shipped emulator fit calibrates, and the shape the
    per-step dispatch telemetry already predicts with."""
    calls = []
    for opts, plan in zip(steps, plans):
        if plan is None:
            continue
        calls.append((opts.scenario, plan, int(opts.count),
                      dtype_nbytes(opts.data_type)))
    if not calls:
        raise ValueError("prepared batch has no priceable steps "
                         "(every plan is None)")
    return predict_sequence(params, calls, world,
                            rx_buf_bytes=rx_buf_bytes,
                            aggregate=aggregate,
                            dispatch_alpha=dispatch_alpha, fused=True)


def calibrate(samples: list[tuple[float, float, float]]) -> LinkParams:
    """Least-squares fit of (alpha, 1/beta) from samples of
    (messages, bytes, measured_seconds): t ~= alpha*m + bytes*inv_beta.
    Non-negative solution (a degenerate sweep clamps at zero rather than
    producing a negative latency)."""
    alpha, inv_beta = _nonneg_lstsq2([[m, b] for m, b, _ in samples],
                                     [t for _, _, t in samples])
    if inv_beta <= 0:
        inv_beta = 1e-12  # pure-latency sweep: effectively infinite beta
    if alpha <= 0:
        alpha = 1e-9
    return LinkParams(alpha=alpha, beta=1.0 / inv_beta)


def tuning_crossovers(params: LinkParams, *, world: int = 8,
                      elem_bytes: int = 4,
                      rx_buf_bytes: int = 4096,
                      wire_dtype: DataType = DataType.none,
                      tier_links: "TierLinks | None" = None,
                      topology: tuple[int, int] | None = None,
                      compute_fit: "ComputeFit | None" = None) -> dict:
    """The model's own switch-over points for the five tuning registers
    (reference defaults accl.cpp:1198-1208: gather fan-in capped above
    32 KB, bcast flat <= 3 ranks, reduce flat <= 4 ranks or <= 32 KB).

    - bcast ranks: flat costs (P-1) serialized sends, the binary tree
      ceil(log2 P) rounds — the crossover is STRUCTURAL (P-1 vs log2 P),
      independent of alpha/beta: flat wins up to the largest P with
      P-1 <= ceil(log2 P).
    - reduce/gather byte thresholds: flat trees pay one round of latency
      but serialize (P-1) payloads into the root's link; trees pay
      log2(P) rounds of latency for log2(P) payloads. Crossover bytes =
      where the extra serialized payload time equals the saved round
      latency.

    `wire_dtype` evaluates the crossovers under an active compression
    lane: the latency-vs-serialization tradeoffs happen in WIRE bytes,
    but the registers are compared against UNCOMPRESSED payload bytes
    (select_algorithm's bytes_count), so byte thresholds scale up by
    elem_bytes / wire_elem_bytes — e.g. the int8 lanes stretch the
    flat-tree regime ~3.94x further in payload bytes. This is how
    autotune() moves its crossovers when the quantized lanes are on.

    Scope caveat: a wire_dtype tune is a declaration that the workload's
    collectives ride that wire. The byte registers are global (the
    reference's registers are too) and the rendezvous branches that
    consult them are reachable only by UNCOMPRESSED calls in this port
    (is_rendezvous requires NO_COMPRESSION) — so a session mixing
    compressed and uncompressed traffic should tune from its dominant
    regime; the minority shape sees registers calibrated for the other
    wire, exactly as with the reference's hand-picked globals.
    """
    P = world
    a, b = params.alpha, params.beta
    # payload-bytes per wire-byte: register thresholds live in payload
    # bytes while the latency/serialization arithmetic is wire bytes
    wire_ratio = elem_bytes / wire_elem_bytes(elem_bytes, wire_dtype)

    bcast_max = 1
    while (bcast_max + 1) - 1 <= math.ceil(math.log2(bcast_max + 1)):
        bcast_max += 1

    r = math.ceil(math.log2(P))
    # flat reduce: 2 latency + (P-1)n/b ; binomial: 2r latency + r*n/b
    denom = (P - 1 - r) / b
    reduce_cross = ((2 * r - 2) * a / denom * wire_ratio
                    if denom > 0 else float("inf"))
    # flat gather (unbounded fan-in) vs fan-in-capped binomial: same shape
    gather_cross = reduce_cross

    # rank crossover at a large representative payload (1 MB, where the
    # rank register governs — small payloads are the count register's
    # job): the last world where the flat tree's serialized payload still
    # beats the tree's extra latency rounds
    n_big = float(1 << 20)
    reduce_ranks = 1
    for pq in range(2, 65):
        rq = math.ceil(math.log2(pq))
        if 2 * a + (pq - 1) * n_big / b <= 2 * rq * a + rq * n_big / b:
            reduce_ranks = pq
        else:
            break

    # allreduce: ring RS+AG (the measured default) vs the reference's
    # rendezvous reduce+bcast composition (.c:1878-1887), arbitrated by
    # THIS model per (size, world) — the largest payload where the
    # composition still predicts faster (0: ring wins everywhere, the
    # emulator-measured outcome). Scanned through the real selection
    # rules so the stage shapes match what would actually run.
    from ..constants import Operation, TuningParams
    from .plan import select_algorithm

    comp_best = 0
    force_comp = TuningParams(allreduce_composition_max_count=1 << 62)
    ring_only = TuningParams()
    max_eager = rx_buf_bytes
    nbytes = max_eager * 2
    if wire_dtype != DataType.none:
        # compressed calls never take the rendezvous path (is_rendezvous
        # requires NO_COMPRESSION), so the reduce+bcast composition is
        # unreachable under an active wire: the ring is the only shape
        nbytes = (1 << 24) + 1
    while nbytes <= (1 << 24):
        count = max(nbytes // elem_bytes, 1)
        kw: dict = dict(max_eager_size=max_eager,
                        eager_rx_buf_size=rx_buf_bytes)
        t_comp = predict(params, Operation.allreduce,
                         select_algorithm(Operation.allreduce, count,
                                          elem_bytes, P, tuning=force_comp,
                                          **kw),
                         count, elem_bytes, P, rx_buf_bytes=rx_buf_bytes)
        t_ring = predict(params, Operation.allreduce,
                         select_algorithm(Operation.allreduce, count,
                                          elem_bytes, P, tuning=ring_only,
                                          **kw),
                         count, elem_bytes, P, rx_buf_bytes=rx_buf_bytes)
        if t_comp < t_ring:
            comp_best = nbytes
        nbytes *= 2

    # Synthesized-schedule crossovers: for each op with committed
    # library entries at this world, the largest payload where the best
    # fp32 synthesized schedule still predicts faster than the whole
    # hand-written zoo (synthesis.hand_written_best forces the
    # tuning-reachable alternatives too). 0 = no entry or never wins —
    # the register stays off and selection is unchanged. int8-wire
    # entries are deliberately excluded: select_algorithm never
    # auto-substitutes them (they are not rank-consistent — see the
    # synthesized branch in plan.select_algorithm), so the register
    # must describe exactly the fp32 window selection will honor.
    # Tiered entries are excluded too: their windows are PER-TIER
    # predictions against the striped composition, selected through
    # the HIER_ALLREDUCE_MIN_COUNT window's arbitration — scoring them
    # on this uniform link would claim a win the calibration never
    # measured.
    from . import synthesis as _synth

    synth_regs: dict[str, int] = {}
    for op_key, scen in (("allreduce", Operation.allreduce),
                         ("allgather", Operation.allgather),
                         ("reduce_scatter", Operation.reduce_scatter)):
        entries = [e for e in _synth.library().values()
                   if e.spec.op == op_key and e.spec.world == P
                   and not e.spec.wire and not e.spec.tiers
                   and e.spec.grid == "std"]
        best_bytes = 0
        if entries:
            sbytes = 1 << 10
            while sbytes <= (1 << 24):
                cnt = max(sbytes // elem_bytes, 1)
                t_synth = min(
                    _synth.predict_spec(params, e.spec, cnt, elem_bytes)
                    for e in entries)
                t_hand = _synth.hand_written_best(
                    params, scen, cnt, elem_bytes, P,
                    rx_buf_bytes=rx_buf_bytes)
                if t_synth < t_hand:
                    best_bytes = sbytes
                sbytes *= 2
        synth_regs[f"synth_{op_key}_max_bytes"] = best_bytes

    # Latency-window synthesized-schedule crossover: the end of the
    # CONTIGUOUS-FROM-BOTTOM winning run of the committed latency-grid
    # allreduce entries (synthesis.SIZE_GRID_LAT, 1-64 KiB — the
    # decode regime where the alpha term dominates) against the same
    # hand-written zoo. A MAX register like the synth trio, but the
    # scan STOPS at the first losing cell instead of keeping the
    # largest win: select_algorithm treats every payload under the
    # register as latency-window territory, so a loss below a win must
    # not be overclaimed. 0 = no lat entry or the smallest cell loses
    # — the register stays off and selection is bit-for-bit unchanged.
    lat_entries = [e for e in _synth.library().values()
                   if e.spec.op == "allreduce" and e.spec.world == P
                   and not e.spec.wire and not e.spec.tiers
                   and e.spec.grid == "lat"]
    lat_best = 0
    for sbytes in (_synth.SIZE_GRID_LAT if lat_entries else ()):
        cnt = max(sbytes // elem_bytes, 1)
        t_synth = min(
            _synth.predict_spec(params, e.spec, cnt, elem_bytes)
            for e in lat_entries)
        t_hand = _synth.hand_written_best(
            params, Operation.allreduce, cnt, elem_bytes, P,
            rx_buf_bytes=rx_buf_bytes)
        if t_synth >= t_hand:
            break  # a loss ends the contiguous-from-bottom window
        lat_best = sbytes
    synth_regs["synth_latency_max_bytes"] = lat_best

    # Quantized-alltoall crossover: the start of the CONTIGUOUS winning
    # suffix — the smallest alltoall payload (descriptor bytes_count =
    # count * elem_bytes, the register's comparison unit) such that the
    # int8 blockwise wire predicts faster than the exact fp32 wire by
    # more than `select_wire`'s min_gain bar at that size and every
    # LARGER swept size. A MIN register like the hier one: the
    # compressed wire's win is the bandwidth regime (~3.94x fewer wire
    # bytes per hop), while on the latency floor the prediction barely
    # moves and the exact wire is kept rather than paying quantization
    # error for nothing. Scanned through the real selection rules so
    # the costed plans are what would actually run; 0 = never clears
    # the gain bar on this link, the register stays off and selection
    # is bit-for-bit unchanged.
    from ..constants import CompressionFlags

    a2a_min = 0
    a2a_min_gain = 0.05
    a2a_tuning = TuningParams()
    nb = 1 << 10
    while nb <= (1 << 24):
        cnt = max(nb // elem_bytes, 1)
        akw: dict = dict(max_eager_size=rx_buf_bytes,
                         eager_rx_buf_size=rx_buf_bytes,
                         tuning=a2a_tuning)
        p_fp32 = select_algorithm(Operation.alltoall, cnt, elem_bytes, P,
                                  **akw)
        p_int8 = select_algorithm(Operation.alltoall, cnt, elem_bytes, P,
                                  CompressionFlags.ETH_COMPRESSED,
                                  compress_dtype=DataType.int8, **akw)
        t_fp32 = predict(params, Operation.alltoall, p_fp32, cnt,
                         elem_bytes, P, rx_buf_bytes=rx_buf_bytes)
        t_int8 = predict(params, Operation.alltoall, p_int8, cnt,
                         elem_bytes, P, rx_buf_bytes=rx_buf_bytes)
        if t_int8 < t_fp32 and (t_fp32 - t_int8) > a2a_min_gain * t_fp32:
            if a2a_min == 0:
                a2a_min = nb  # candidate start of the suffix
        else:
            a2a_min = 0  # loss above a win: suffix restarts
        nb *= 2

    # Hierarchical-allreduce crossover: with per-tier links and a
    # declared (inner, outer) topology, the START of the CONTIGUOUS
    # winning SUFFIX — the smallest payload such that the striped
    # two-tier composition (best stripe count per size) predicts faster
    # than the flat ring at that size and every LARGER swept size. The
    # register is a MIN threshold ([min, inf) window) because the
    # composition's win is the bandwidth regime: it moves 1/L of the
    # bytes on the slow tier but pays more message latencies, so it
    # loses the latency floor and wins from some size up. A win set
    # that does not extend to the top of the sweep cannot be expressed
    # by the single threshold and is NOT overclaimed (same contiguity
    # posture as the synth windows). The flat ring over a two-tier
    # world is paced by its SLOWEST links — every ring step includes
    # the cross-slice edges — so the flat side is charged to the outer
    # link. 0 = no tier calibration / no topology / never wins: the
    # register stays off and selection is bit-for-bit unchanged.
    hier_min = 0
    if tier_links is not None and topology is not None:
        L_in, P_out = topology
        if L_in > 1 and P_out > 1 and L_in * P_out == P:
            hkw: dict = dict(max_eager_size=rx_buf_bytes,
                             eager_rx_buf_size=rx_buf_bytes)
            nb = 1 << 10
            while nb <= (1 << 24):
                cnt = max(nb // elem_bytes, 1)
                s_best = best_stripes(tier_links, cnt, elem_bytes, L_in,
                                      P_out)
                hplan = Plan(Protocol.EAGER, Algorithm.HIER_RS_AR_AG,
                             cnt, 1, inner_world=L_in, outer_world=P_out,
                             stripes=s_best)
                t_hier = predict_tiered(tier_links, hplan, cnt,
                                        elem_bytes)
                flat = select_algorithm(
                    Operation.allreduce, cnt, elem_bytes, P,
                    tuning=ring_only, **hkw)
                t_flat = predict(tier_links.outer, Operation.allreduce,
                                 flat, cnt, elem_bytes, P,
                                 rx_buf_bytes=rx_buf_bytes)
                if t_hier < t_flat:
                    if hier_min == 0:
                        hier_min = nb  # candidate start of the suffix
                else:
                    hier_min = 0  # loss above a win: suffix restarts
                nb *= 2

    # Compute-communication overlap crossover: with a measured compute
    # term (ComputeFit, calibrated from telemetry spans of the workload's
    # compute stage), the START of the CONTIGUOUS winning SUFFIX — the
    # smallest streamed-allreduce payload such that the stripe-overlapped
    # schedule (best S per size, the argmin) predicts faster than the
    # serial dispatch->compute form at the SAME stripe count — the
    # bitwise-identical twin, compute then S chains back to back — by
    # more than `overlap_min_gain` of the serial time, at that size and
    # every LARGER swept size. Scanned under the SHAPED link when a
    # per-tier calibration exists (tier_links.outer — the slow-wire
    # regime the overlap claim lives in, the same link stripe selection
    # uses) else this link. A MIN register like the hier one; 0 = no
    # compute calibration or overlap never clears the bar, the register
    # stays off and selection is bit-for-bit the serial form.
    overlap_min = 0
    overlap_min_gain = 0.05
    if compute_fit is not None:
        olink = tier_links.outer if tier_links is not None else params
        nb = 1 << 10
        while nb <= (1 << 24):
            cnt = max(nb // elem_bytes, 1)
            comp_s = compute_fit.seconds(nb)
            s_best = best_overlap_stripes(
                olink, cnt, elem_bytes, P, compute_s=comp_s,
                rx_buf_bytes=rx_buf_bytes)
            oplan = Plan(Protocol.EAGER, Algorithm.EAGER_RING_RS_AG,
                         cnt, 1, stripes=s_best)
            t_on = predict_overlapped(olink, oplan, cnt, elem_bytes, P,
                                      compute_s=comp_s,
                                      rx_buf_bytes=rx_buf_bytes)
            t_serial = predict_overlapped(olink, oplan, cnt, elem_bytes,
                                          P, compute_s=comp_s,
                                          rx_buf_bytes=rx_buf_bytes,
                                          serial=True)
            if (s_best > 1 and t_on < t_serial
                    and (t_serial - t_on) > overlap_min_gain * t_serial):
                if overlap_min == 0:
                    overlap_min = nb  # candidate start of the suffix
            else:
                overlap_min = 0  # loss above a win: suffix restarts
            nb *= 2

    return {
        "alltoall_compress_min_bytes": a2a_min,
        "hier_allreduce_min_bytes": hier_min,
        "overlap_min_bytes": overlap_min,
        "bcast_flat_tree_max_ranks": bcast_max,
        "reduce_flat_tree_max_count_bytes": reduce_cross,
        "gather_flat_tree_max_count_bytes": gather_cross,
        "reduce_flat_tree_max_ranks": reduce_ranks,
        "allreduce_composition_max_bytes": comp_best,
        "world": P,
        "wire_dtype": wire_dtype.name,
        **synth_regs,
    }
