"""Version-compatibility shims for the jax API surface we depend on.

The framework targets current jax (`jax.shard_map` with `check_vma`,
`lax.axis_size`); older toolchains still ship the experimental entry
point (`jax.experimental.shard_map.shard_map` with `check_rep`) and no
axis_size. Installing the shims keeps every call site — including tests
that drive `jax.shard_map` directly — on one spelling without forking
the codebase per jax version.

This module itself imports NO jax: `install()` is called from the
modules that already pay for jax (sequencer.lowering, models, parallel),
and from the package root only when jax is already loaded — so
`import accl_tpu` stays light for constants/descriptor-only consumers.
"""

from __future__ import annotations

_installed = False


def install() -> None:
    """Install the shims (idempotent). Imports jax."""
    global _installed
    if _installed:
        return
    _installed = True
    import jax

    _install_shard_map_shim(jax)
    _install_axis_size_shim(jax)


def install_if_jax_loaded() -> None:
    """Install only when the process has already imported jax — the
    package-root hook: free where jax is resident (test suites, the
    container's sitecustomize), weightless everywhere else."""
    import sys

    if "jax" in sys.modules:
        install()


def _install_shard_map_shim(jax) -> None:
    if hasattr(jax, "shard_map"):
        return

    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        # check_vma (current jax) maps onto check_rep (older jax): both
        # gate the varying-across-mesh analysis the pallas-lowered bodies
        # cannot satisfy.
        if check_vma is not None:
            kw.setdefault("check_rep", bool(check_vma))
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )

    jax.shard_map = shard_map


def _install_axis_size_shim(jax) -> None:
    from jax import lax

    if hasattr(lax, "axis_size"):
        return

    def axis_size(axis_name):
        import jax._src.core as _core

        frame = _core.axis_frame(axis_name)
        # older jax returns the bare int; newer frame objects carry .size
        return getattr(frame, "size", frame)

    lax.axis_size = axis_size
