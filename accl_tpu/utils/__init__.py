from .logging import Log, log  # noqa: F401
