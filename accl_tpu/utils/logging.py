"""Leveled, thread-safe logger shared by the driver and test paths.

Reference: test/log/log.hpp:29-48 — a leveled logger threaded through the
emulator and HLS-sim code paths; here a thin wrapper over the stdlib with
the same level vocabulary, honoring ACCL_LOG_LEVEL.
"""

from __future__ import annotations

import logging
import os

_LEVELS = {
    "verbose": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def _make_logger() -> logging.Logger:
    logger = logging.getLogger("accl_tpu")
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(
            logging.Formatter("[ACCL %(levelname)s %(asctime)s] %(message)s",
                              "%H:%M:%S")
        )
        logger.addHandler(h)
    level = os.environ.get("ACCL_LOG_LEVEL", "warning").lower()
    logger.setLevel(_LEVELS.get(level, logging.WARNING))
    return logger


Log = _make_logger()


def log(level: str, msg: str, *args):
    Log.log(_LEVELS.get(level, logging.INFO), msg, *args)
