"""Arithmetic / compression configuration.

Maps an (uncompressed dtype, compressed dtype) operand pair to the kernel
lanes that implement elementwise reduction and cast-compression. In the
reference these lanes are AXIS TDEST values steering data through the
reduce_ops and hp_compression HLS plugins
(reference: driver/xrt/include/accl/arithconfig.hpp:30-119,
kernels/plugins/reduce_ops/reduce_ops.cpp:75-107); here they are indices
into the Pallas kernel registry in accl_tpu.ops.
"""

from __future__ import annotations

import dataclasses

from .constants import DataType, dtype_nbytes


@dataclasses.dataclass(frozen=True)
class ArithConfig:
    """One row of the arithmetic configuration table.

    Same field semantics as the reference ArithConfig
    (arithconfig.hpp:33-41): element sizes for the (un)compressed domains,
    log2 of the element-count ratio, compressor/decompressor kernel lanes,
    whether reduction runs in the compressed domain, and the per-function
    arithmetic kernel lanes (indexed by ReduceFunction).
    """

    uncompressed_elem_bytes: int
    compressed_elem_bytes: int
    elem_ratio_log: int
    compressor_lane: int
    decompressor_lane: int
    arith_is_compressed: bool
    arith_lanes: tuple[int, ...]

    def addr(self) -> int:
        """Exchange-memory offset where this config was written (set by the
        driver at initialize time, arithconfig.hpp:73-79)."""
        if not hasattr(self, "_exchmem_addr"):
            raise RuntimeError("Arithmetic config address requested before set")
        return self._exchmem_addr  # type: ignore[attr-defined]

    def set_exchmem(self, address: int) -> None:
        object.__setattr__(self, "_exchmem_addr", address)

    # Exchange-memory row layout: 8 words mirroring the reference write
    # order (arithconfig.hpp:73-79 writes elem bytes, ratio, lanes,
    # compressed-domain flag, then the per-function arith lanes): [unc
    # bytes, cmp bytes, ratio_log, compressor, decompressor, is_compressed,
    # lane_sum, lane_max].
    WORDS_PER_ROW = 8

    def exchmem_words(self) -> list[int]:
        return [
            self.uncompressed_elem_bytes,
            self.compressed_elem_bytes,
            self.elem_ratio_log,
            self.compressor_lane,
            self.decompressor_lane,
            int(self.arith_is_compressed),
            self.arith_lanes[0],
            self.arith_lanes[1],
        ]

    @classmethod
    def from_exchmem_words(cls, words: list[int]) -> "ArithConfig":
        return cls(
            uncompressed_elem_bytes=words[0],
            compressed_elem_bytes=words[1],
            elem_ratio_log=words[2],
            compressor_lane=words[3],
            decompressor_lane=words[4],
            arith_is_compressed=bool(words[5]),
            arith_lanes=(words[6], words[7]),
        )


# Kernel lane numbering (see accl_tpu/ops/reduce_ops.py):
#   arith lanes 0-4: SUM for fp32, fp64, i32, i64, fp16  — reference
#     reduce_ops.cpp TDEST 0-4
#   arith lanes 5-9: MAX for the same dtypes              — TDEST 5-9
#   arith lanes 10/11: SUM/MAX bf16 (TPU-native extension)
#   compressor lanes: 0 = fp32->fp16, 1 = fp16->fp32 (hp_compression analog),
#     2 = fp32->bf16, 3 = bf16->fp32 (TPU-native extension),
#     4 = fp32->int8 blockwise quantize, 5 = int8->fp32 blockwise
#     dequantize (EQuARX-style quantized wire: int8 payload + one fp32
#     scale per QUANT_BLOCK_ELEMS block, accl_tpu/ops/compression.py)
#
# Default table mirrors DEFAULT_ARITH_CONFIG (arithconfig.hpp:102-119) and
# adds bf16 rows.
DEFAULT_ARITH_CONFIG: dict[tuple[DataType, DataType], ArithConfig] = {
    (DataType.float16, DataType.float16): ArithConfig(2, 2, 0, 0, 0, False, (4, 9)),
    (DataType.float32, DataType.float16): ArithConfig(4, 2, 0, 0, 1, True, (4, 9)),
    (DataType.float32, DataType.float32): ArithConfig(4, 4, 0, 0, 0, False, (0, 5)),
    (DataType.float64, DataType.float64): ArithConfig(8, 8, 0, 0, 0, False, (1, 6)),
    (DataType.int32, DataType.int32): ArithConfig(4, 4, 0, 0, 0, False, (2, 7)),
    (DataType.int64, DataType.int64): ArithConfig(8, 8, 0, 0, 0, False, (3, 8)),
    # TPU-native: bf16 wire compression and bf16-domain arithmetic.
    (DataType.bfloat16, DataType.bfloat16): ArithConfig(2, 2, 0, 2, 2, False, (10, 11)),
    (DataType.float32, DataType.bfloat16): ArithConfig(4, 2, 0, 2, 3, True, (10, 11)),
    # Quantized wire: int8 payload + per-block fp32 scales on the hop,
    # arithmetic stays in the UNCOMPRESSED fp32 domain (a sum of int8
    # codes is meaningless across blocks), so arith_is_compressed=False
    # and the ring schedules fuse dequantize->reduce->requantize per hop.
    (DataType.float32, DataType.int8): ArithConfig(4, 1, 0, 4, 5, False, (0, 5)),
}


# compressor/decompressor lane ids of the blockwise-quantized wire; the
# Wire datapath keys its (payload, scales) hop form off these
QUANT_COMPRESSOR_LANE = 4
QUANT_DECOMPRESSOR_LANE = 5


def validate_arith_config(table: dict[tuple[DataType, DataType], ArithConfig]):
    """Sanity-check a user-provided table the way initialize() does before
    writing configs to exchange memory."""
    for (unc, cmp_), cfg in table.items():
        if cfg.uncompressed_elem_bytes != dtype_nbytes(unc):
            raise ValueError(f"{unc}: uncompressed_elem_bytes mismatch")
        if cfg.compressed_elem_bytes != dtype_nbytes(cmp_):
            raise ValueError(f"{cmp_}: compressed_elem_bytes mismatch")
        if len(cfg.arith_lanes) < 2:
            raise ValueError("arith_lanes must cover SUM and MAX")
    return table
