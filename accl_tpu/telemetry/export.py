"""Trace export: event-schema validation, Chrome trace-event JSON, and
the predicted-vs-measured residual table.

The trace document (tracer.Tracer.to_trace / accl_log/trace.json) is the
one exchange format; this module turns it into

  - Chrome trace-event JSON (Perfetto / chrome://tracing loadable): one
    named track (tid) per span `track`, complete events with
    microsecond timestamps, span args carried through verbatim;
  - a residual table: every span that carries both a prediction
    (args.predicted_s) and a measurement (dur_ns or args.measured_s)
    contributes |predicted - measured| / measured — the
    mechanically-honest "how wrong is the model" number the r4/r5
    verdicts asked for.

EVENT_SCHEMA is the jsonschema contract the CI telemetry step validates
emitted traces against; tools/accl_trace.py --selftest runs it over the
committed golden trace so the schema and the emitters cannot drift
silently.
"""

from __future__ import annotations

import json
import pathlib

from .tracer import SCHEMA_VERSION

# jsonschema document for one trace file. Span args are an open object
# (emitters attach detail freely) but the keys the residual/feedback
# machinery consumes are typed, so a drifted emitter fails validation
# instead of silently skewing the calibration.
EVENT_SCHEMA: dict = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "title": "accl-tpu trace",
    "type": "object",
    "required": ["schema", "spans"],
    "properties": {
        "schema": {"const": SCHEMA_VERSION},
        # meta stays open, but the observability keys the always-on
        # layer embeds are typed: a drifted registry snapshot or
        # sentinel report fails validation instead of silently shipping
        # a malformed metrics section in every exported trace
        "meta": {
            "type": "object",
            "properties": {
                "metrics": {
                    "type": "object",
                    "required": ["counters", "gauges", "histograms"],
                    "properties": {
                        "counters": {"type": "object"},
                        "gauges": {"type": "object"},
                        # per-series histogram rows are fully typed:
                        # the quantile keys MUST mirror
                        # metrics.QUANTILES via metrics.quantile_key
                        # (test_metrics pins the two against each
                        # other), so adding a quantile without typing
                        # it here fails CI instead of shipping an
                        # untyped tail readout in every trace
                        "histograms": {
                            "type": "object",
                            "additionalProperties": {
                                "type": "array",
                                "items": {
                                    "type": "object",
                                    "required": ["labels", "count",
                                                 "sum", "window"],
                                    "properties": {
                                        "labels": {"type": "object"},
                                        "count": {"type": "integer"},
                                        "sum": {"type": "number"},
                                        "window": {"type": "integer"},
                                        "min": {"type": "number"},
                                        "max": {"type": "number"},
                                        "p50": {"type": "number"},
                                        "p95": {"type": "number"},
                                        "p99": {"type": "number"},
                                        "p99_9": {"type": "number"},
                                    },
                                    "additionalProperties": False,
                                },
                            },
                        },
                    },
                },
                "drift_sentinel": {
                    "type": "object",
                    "required": ["verdict", "flagged"],
                    "properties": {
                        "window": {"type": "integer"},
                        "verdict": {"type": "object"},
                        "flagged": {"type": "array",
                                    "items": {"type": "string"}},
                        "stragglers": {"type": "array"},
                    },
                },
                # per-rank wire-health counter snapshot (the stats2
                # surface: CRC/dup drops, selective-retransmit ack/nack
                # traffic, fault-injection tallies) — the escalation
                # policy's evidence for lossy-link vs dead-rank. Typed
                # so a drifted counter rendering fails validation.
                "wire_health": {
                    "type": "object",
                    "required": ["per_rank", "totals"],
                    "properties": {
                        "per_rank": {
                            "type": "object",
                            "additionalProperties": {
                                "type": "object",
                                "additionalProperties": {
                                    "type": "integer"},
                            },
                        },
                        "totals": {
                            "type": "object",
                            "additionalProperties": {"type": "integer"},
                        },
                    },
                },
            },
        },
        "spans": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["name", "cat", "track", "ts_ns", "dur_ns"],
                "properties": {
                    "name": {"type": "string"},
                    "cat": {
                        "type": "string",
                        # "compute": a timed compute stage next to the
                        # collectives (args.compute_bytes carries the
                        # operand bytes it materializes) — the
                        # ComputeFit calibration samples of the
                        # overlap pipeline (feedback.compute_samples).
                        # "error": the sticky-retcode marker the flight
                        # recorder emits at dump-on-error time
                        # (telemetry.recorder — args.retcode is the
                        # failing call's sticky error word)
                        "enum": ["call", "step", "phase", "sequence",
                                 "native", "compute", "error"],
                    },
                    "track": {"type": "string"},
                    "ts_ns": {"type": "integer", "minimum": 0},
                    "dur_ns": {"type": "integer", "minimum": 0},
                    "args": {
                        "type": "object",
                        "properties": {
                            "op": {"type": "string"},
                            "count": {"type": "integer"},
                            "bytes": {"type": "integer"},
                            "world": {"type": "integer"},
                            "algorithm": {"type": "string"},
                            "protocol": {"type": "string"},
                            "retcode": {"type": "integer"},
                            "detail": {"type": "integer"},
                            "predicted_s": {"type": "number"},
                            "measured_s": {"type": "number"},
                            "coef_messages": {"type": "number"},
                            "coef_bytes": {"type": "number"},
                            "signature": {"type": "string"},
                            "step": {"type": "integer"},
                            "rank": {"type": "integer"},
                            "d_passes": {"type": "integer"},
                            "d_parks": {"type": "integer"},
                            "d_seek_hit": {"type": "integer"},
                            "d_seek_miss": {"type": "integer"},
                            "compute_bytes": {"type": "integer"},
                            # the deadline-miss marker (resilience
                            # host-side verdicts, recorder
                            # .on_deadline_miss): a cat "error" span
                            # with no sticky retcode carries these
                            "deadline_missed": {"type": "boolean"},
                            "deadline_s": {"type": "number"},
                            "suspect_rank": {"type": "integer"},
                        },
                        "additionalProperties": True,
                    },
                },
            },
        },
    },
}


def validate_trace(trace: dict) -> None:
    """Raise jsonschema.ValidationError when the trace violates the
    event schema (the CI telemetry gate)."""
    import jsonschema

    jsonschema.validate(trace, EVENT_SCHEMA)


def to_chrome(trace: dict) -> dict:
    """Chrome trace-event JSON: one pid, one tid per span track (named
    via thread_name metadata so Perfetto labels the rows), complete (X)
    events in microseconds. Zero-duration spans (recorded sequence
    steps) are stretched to 1 ns so they stay clickable."""
    tracks: list[str] = []
    index: dict[str, int] = {}
    for sp in trace.get("spans", []):
        t = sp["track"]
        if t not in index:
            index[t] = len(tracks)
            tracks.append(t)
    events = [
        {
            "ph": "M",
            "pid": 0,
            "tid": i,
            "name": "thread_name",
            "args": {"name": t},
        }
        for i, t in enumerate(tracks)
    ]
    for sp in trace.get("spans", []):
        events.append({
            "ph": "X",
            "pid": 0,
            "tid": index[sp["track"]],
            "name": sp["name"],
            "cat": sp["cat"],
            "ts": sp["ts_ns"] / 1e3,
            "dur": max(sp["dur_ns"], 1) / 1e3,
            "args": sp.get("args", {}),
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"schema": trace.get("schema", SCHEMA_VERSION),
                      "meta": trace.get("meta", {})},
    }


def measured_seconds(span: dict) -> float:
    """A span's measured wall seconds: explicit args.measured_s when the
    emitter recorded one (native spans), else the span duration.
    Partially-populated spans (hand-built fixtures, truncated dumps)
    degrade to 0.0 — "no measurement" — rather than raising."""
    args = span.get("args") or {}
    try:
        if "measured_s" in args:
            return float(args["measured_s"])
        return float(span.get("dur_ns", 0)) / 1e9
    except (TypeError, ValueError):
        return 0.0


def residual_rows(trace: dict) -> list[dict]:
    """All spans carrying BOTH a prediction and a nonzero measurement,
    as rows of (name, track, predicted_s, measured_s, rel_err). Robust
    against empty and partially-populated traces: a span with no
    `predicted_s`, a non-numeric prediction, or a zero/absent
    measurement contributes no row (it has no residual to claim) —
    never an exception."""
    rows = []
    for sp in trace.get("spans", []):
        if not isinstance(sp, dict):
            continue
        args = sp.get("args") or {}
        if "predicted_s" not in args:
            continue
        if args.get("dispatch_only"):
            # an async span closed at dispatch: its duration is the
            # host seam, not the collective the prediction models —
            # comparing them would corrupt the residual table
            continue
        if sp.get("cat") == "error":
            # dump-on-error markers (sticky retcodes, deadline misses)
            # carry the failing call's predicted/elapsed pair as
            # DIAGNOSTIC detail — a wedged wait's elapsed time is not a
            # measurement of the collective, and one miss would skew
            # every residual median (and any band armed from it)
            continue
        meas = measured_seconds(sp)
        if meas <= 0:
            continue
        try:
            pred = float(args["predicted_s"])
        except (TypeError, ValueError):
            continue
        rows.append({
            "name": sp.get("name", "?"),
            "track": sp.get("track", "?"),
            "predicted_s": pred,
            "measured_s": meas,
            "rel_err": abs(pred - meas) / meas,
        })
    return rows


def median(xs: list[float]) -> float:
    if not xs:
        return float("nan")
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def residual_summary(rows: list[dict]) -> dict:
    """Aggregate the residual table: overall and per-op median relative
    error (|predicted - measured| / measured). An empty table (a trace
    from a run with no predictions, or drained before any call
    completed) yields the well-typed empty summary — `median_rel_err`
    is None, never NaN (NaN round-trips as Infinity-adjacent garbage
    through strict JSON consumers) and never an exception."""
    if not rows:
        return {"rows": 0, "median_rel_err": None,
                "per_op_median_rel_err": {}}
    by_op: dict[str, list[float]] = {}
    for r in rows:
        by_op.setdefault(r["name"], []).append(r["rel_err"])
    return {
        "rows": len(rows),
        "median_rel_err": median([r["rel_err"] for r in rows]),
        "per_op_median_rel_err": {
            op: median(errs) for op, errs in sorted(by_op.items())
        },
    }


# The wire-health counters of the stats2 surface that describe FAULT
# REPAIR activity — damage actually observed and absorbed (corrupt
# frames dropped, duplicates deduped, frames actually resent).  This is
# the resilience manager's lossy-vs-dark evidence, and deliberately
# EXCLUDES the nack/ack traffic counters: a survivor nacks a dead
# rank's silence (and a stalled healthy peer) too, so "someone is
# waiting" counters climb in BOTH cases and cannot distinguish them.
# Kept here — next to the export that renders them — so the exporter
# and the consumer read one list.
WIRE_FAULT_KEYS = (
    "crc_drops", "dup_drops", "retx_sent", "retx_miss",
)


def wire_health_report(stats_by_rank: dict) -> dict:
    """Normalize per-rank wire-health snapshots (EmuRank.wire_stats /
    TPUDevice.wire_stats dicts keyed by rank) into the trace-meta
    `wire_health` shape: string-keyed per-rank rows plus a totals row.
    Non-integer values and unknown keys pass through int-coerced /
    verbatim so a newer native counter never breaks an older exporter;
    an empty input yields the well-typed empty report."""
    per_rank: dict = {}
    totals: dict = {}
    for rank in sorted(stats_by_rank):
        row = {}
        for k, v in (stats_by_rank[rank] or {}).items():
            try:
                iv = int(v)
            except (TypeError, ValueError):
                continue
            row[str(k)] = iv
            totals[str(k)] = totals.get(str(k), 0) + iv
        per_rank[str(rank)] = row
    return {"per_rank": per_rank, "totals": totals}


def wire_health_rows(stats_by_rank: dict) -> list[dict]:
    """Flat per-rank rows (rank + every counter) for table rendering —
    the accl_trace/bench printers' shape."""
    rep = wire_health_report(stats_by_rank)
    return [{"rank": rank, **row}
            for rank, row in sorted(rep["per_rank"].items(),
                                    key=lambda kv: int(kv[0]))]


def write_trace(path, trace: dict) -> None:
    pathlib.Path(path).write_text(json.dumps(trace, indent=1))


def read_trace(path) -> dict:
    return json.loads(pathlib.Path(path).read_text())
