"""In-process span tracer: the host half of the telemetry subsystem.

The CCLO keeps its observability next to the data plane — hardware
performance counters and per-call duration registers the host reads back
after the fact (SURVEY.md L2/L4; the native runtime's trace ring is that
posture rebuilt, runtime.cpp record_span). This module is the HOST side
of the same contract: a thread-safe, bounded, drop-oldest ring of span
events that the facade, the sequence machinery, and the device backends
emit into, and that tools/accl_trace.py / bench.py --trace export as
Chrome trace-event JSON (telemetry.export).

One stable event schema (SPAN v1) spans every emitter:

    {"name": str,      # operation / phase label ("allreduce", "lint")
     "cat": str,       # "call" | "step" | "phase" | "sequence" | "native"
     "track": str,     # render track: "facade", "device", "emu/r3", ...
     "ts_ns": int,     # start, perf_counter_ns domain (native spans are
                       #   rebased into it at drain time)
     "dur_ns": int,    # duration (0 = instant marker, e.g. a recorded
                       #   sequence step whose time is inside the fused
                       #   program)
     "args": {...}}    # schema'd detail keys: op, count, bytes, world,
                       #   algorithm, protocol, retcode, detail,
                       #   predicted_s, measured_s, coef_messages,
                       #   coef_bytes, signature, step, rank, d_passes,
                       #   d_parks, d_seek_hit, d_seek_miss, ...

Tracing is OFF by default and costs one predicate per instrumented site
when off (`span()` returns a shared no-op object before any argument
handling): the bench smoke path gates that disabled overhead under 1%.
Enable with ACCL_TELEMETRY=1 in the environment or telemetry.enable().

The tracer is also the ONE emission seam of the always-on observability
layer (telemetry.metrics / telemetry.recorder): observers registered
with `add_observer()` receive every emitted event at span-emission time
— whether or not the ring itself is collecting — so the streaming
metrics registry and the flight recorder stay live without a trace ever
being drained. `span()` returns a live span whenever the tracer is
`active` (ring enabled OR observers installed); the ring only retains
events when `enabled`.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

SCHEMA_VERSION = "accl-tpu-trace-v1"

# default host ring capacity (spans); the ring drops OLDEST on overflow
# and counts the drops — mirroring the native ring's contract
DEFAULT_CAPACITY = 65536


class _NullSpan:
    """Shared no-op span: the disabled-tracing fast path. Reentrant and
    stateless, so one instance serves every call site."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **_kw) -> "_NullSpan":
        return self


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    """Context manager measuring one span; emitted into the tracer ring
    on exit. `set()` attaches args discovered mid-span (e.g. the plan a
    device resolved after dispatch)."""

    __slots__ = ("_tracer", "name", "cat", "track", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, track: str,
                 args: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.track = track
        self.args = args
        self._t0 = 0

    def __enter__(self) -> "_LiveSpan":
        self._t0 = time.perf_counter_ns()
        return self

    def set(self, **kw) -> "_LiveSpan":
        self.args.update(kw)
        return self

    def __exit__(self, exc_type, _exc, _tb) -> bool:
        dur = time.perf_counter_ns() - self._t0
        if exc_type is not None:
            self.args.setdefault("error", exc_type.__name__)
        self._tracer.emit(self.name, self.cat, self.track,
                          ts_ns=self._t0, dur_ns=dur, args=self.args)
        return False


class Tracer:
    """Thread-safe bounded span ring (drop-oldest, counted drops)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool | None = None):
        if enabled is None:
            enabled = os.environ.get("ACCL_TELEMETRY", "0") not in (
                "", "0", "false", "off")
        self._enabled = bool(enabled)
        self.capacity = int(capacity)
        self._spans: deque = deque()
        self._mu = threading.Lock()
        self.drops = 0
        # observers are stored as an immutable tuple so the hot-path
        # read (`span()`'s predicate, `emit()`'s fan-out) is lock-free;
        # installs/removals copy-on-write under the ring lock
        self._observers: tuple = ()
        self.observer_errors = 0

    # -- switching ---------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def active(self) -> bool:
        """True when spans are worth building: the ring is collecting
        OR an observability observer (metrics registry, flight
        recorder) is installed. Emitters gate arg attachment on this,
        not on `enabled`, so live metrics see the plan/prediction keys
        even when nobody is recording a full trace."""
        return self._enabled or bool(self._observers)

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    # -- observers (the always-on observability seam) ----------------------

    def add_observer(self, fn) -> None:
        """Register a callable fed every emitted event (idempotent)."""
        with self._mu:
            if fn not in self._observers:
                self._observers = self._observers + (fn,)

    def remove_observer(self, fn) -> None:
        with self._mu:
            self._observers = tuple(o for o in self._observers if o is not fn)

    def _observe(self, ev: dict) -> None:
        for obs in self._observers:
            try:
                obs(ev)
            except Exception:
                # an observer bug must never take down the data plane;
                # counted so a broken observer is visible, not silent
                self.observer_errors += 1

    # -- emission ----------------------------------------------------------

    def span(self, name: str, cat: str = "call", track: str = "host",
             **args) -> "_NullSpan | _LiveSpan":
        """Start a span context manager. An inactive tracer (ring off,
        no observers) returns the shared no-op before touching the
        arguments."""
        if not (self._enabled or self._observers):
            return _NULL_SPAN
        return _LiveSpan(self, name, cat, track, args)

    def emit(self, name: str, cat: str, track: str, *, ts_ns: int,
             dur_ns: int, args: dict | None = None) -> None:
        """Record one already-measured span (the direct form used when
        draining native rings or replaying recorded timings). Observers
        see every event at emission; the ring retains it only when
        enabled."""
        if not (self._enabled or self._observers):
            return
        ev = {
            "name": name,
            "cat": cat,
            "track": track,
            "ts_ns": int(ts_ns),
            "dur_ns": int(dur_ns),
            "args": dict(args or {}),
        }
        if self._observers:
            self._observe(ev)
        if not self._enabled:
            return
        with self._mu:
            if len(self._spans) >= self.capacity:
                self._spans.popleft()
                self.drops += 1
            self._spans.append(ev)

    def extend(self, events: list[dict]) -> None:
        """Bulk-append pre-shaped span events (ring discipline applies;
        observers see each event exactly as emit() would feed them)."""
        if not (self._enabled or self._observers):
            return
        if self._observers:
            for ev in events:
                self._observe(ev)
        if not self._enabled:
            return
        with self._mu:
            for ev in events:
                if len(self._spans) >= self.capacity:
                    self._spans.popleft()
                    self.drops += 1
                self._spans.append(ev)

    # -- readout -----------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """Non-destructive copy of the current ring contents."""
        with self._mu:
            return list(self._spans)

    def drain(self) -> list[dict]:
        """Remove and return every buffered span."""
        with self._mu:
            out = list(self._spans)
            self._spans.clear()
            return out

    def clear(self) -> None:
        with self._mu:
            self._spans.clear()
            self.drops = 0

    def to_trace(self, meta: dict | None = None) -> dict:
        """Package the current spans as a schema-versioned trace document
        (the on-disk / exchange format every exporter consumes).
        Observers exposing a `trace_meta()` hook (the metrics registry
        snapshot + drift-sentinel report) contribute to the meta, so
        every exported trace carries the live metrics next to its
        spans."""
        m = {"drops": self.drops}
        for obs in self._observers:
            tm = getattr(obs, "trace_meta", None)
            if tm is not None:
                try:
                    m.update(tm())
                except Exception:
                    self.observer_errors += 1
        if meta:
            m.update(meta)
        return {"schema": SCHEMA_VERSION, "meta": m, "spans": self.snapshot()}


_tracer = Tracer()


def get_tracer() -> Tracer:
    """The process-wide tracer every built-in emitter uses."""
    return _tracer


def enable() -> None:
    _tracer.enable()


def disable() -> None:
    _tracer.disable()
