"""Streaming metrics registry + the drift sentinel: the always-on half
of the telemetry subsystem.

The trace ring (tracer.py) answers "what happened, span by span" after
the fact; this module answers "what is happening, right now" while the
data plane runs. It is fed at span-EMISSION time (the facade call path,
the sequence dispatch phases, and the native drain all funnel through
``Tracer.emit``/``extend``, which hands every event to its installed
observers) — never at trace-drain time, so the numbers are live even
when nobody ever exports a trace:

  - a **streaming metrics registry**: counters, gauges, and bounded
    streaming-quantile histograms (p50/p95/p99/p99.9 over a sliding
    sample window plus exact cumulative count/sum/min/max) keyed by
    ``(op, algorithm, protocol, world)`` labels, with Prometheus-style
    text exposition (``expose_text``) and a JSON snapshot that rides
    the SPAN v1 trace meta (``Tracer.to_trace`` embeds it);

  - the **drift sentinel**: rolling-window predicted-vs-measured
    residuals per op (the span ``predicted_s`` key next to its
    measurement — the same pair the residual table reads), a frozen
    reference band armed from the first in-regime samples, and a
    band-leave verdict — the SENSING half of the always-on autotuning
    loop (detection + report; online register actuation is the
    follow-up).  Per-rank measurements from the ``emu/r<rank>`` tracks
    additionally feed a straggler attribution (max-over-ranks vs
    median skew per (op, count) wave).

Everything here is bounded: histogram windows, sentinel windows, and
the label space (collectives x algorithms x protocols x worlds) are all
small by construction, so an always-on registry cannot grow without
limit in a long-lived process. ``bench.py --obs-gate`` measures the
per-event observe cost against the per-call median latency (< 3% on
the traced hot path) and proves the sentinel flags an injected
WAN-shaper regime change while staying quiet on a stable control run.
"""

from __future__ import annotations

import math
import os
import threading
from collections import deque
from typing import Any, Callable, Iterable

from .export import measured_seconds, median as _median

# label key order is FIXED: the registry keys series by this tuple so
# exposition and snapshots are deterministic across runs
LABEL_KEYS = ("op", "algorithm", "protocol", "world")

# Cardinality-guarded label keys: every other label in this module draws
# from a closed set (collectives x algorithms x protocols x worlds), but
# a TENANT id is caller-supplied — an abusive or buggy tenant-id stream
# must not be able to mint unbounded series in an always-on registry or
# blow up the Prometheus exposition. Values past the cap collapse into
# the `other` overflow bucket (their observations still count — only
# the per-value attribution is lost) and the overflow is itself counted
# (accl_label_overflow_total), so saturation is visible, never silent.
GUARDED_LABEL_KEYS = ("tenant",)
LABEL_OVERFLOW_BUCKET = "other"
DEFAULT_LABEL_VALUE_CAP = 64


def _label_value_cap() -> int:
    """Env-tunable per-key cardinality cap (ACCL_METRICS_LABEL_CAP);
    clamped to >= 1 so at least one real value is always attributable."""
    raw = os.environ.get("ACCL_METRICS_LABEL_CAP", "")
    try:
        cap = int(raw) if raw else DEFAULT_LABEL_VALUE_CAP
    except ValueError:
        cap = DEFAULT_LABEL_VALUE_CAP
    return max(cap, 1)

DEFAULT_HISTOGRAM_WINDOW = 512
# p99.9 rides the same 512-sample window as the rest: nearest-rank over
# 512 samples makes it the window maximum until ~1000 samples would fit,
# which is exactly the honest tail readout an interactive-serving gate
# wants (the worst step seen in the last window, stabilizing as windows
# grow) — not a fabricated interpolation past the data
QUANTILES = (0.5, 0.95, 0.99, 0.999)


def quantile_key(q: float) -> str:
    """Snapshot/JSON key for a quantile: p50, p95, p99, p99_9 — the
    fractional part joins with '_' so 0.999 cannot collide with 0.99
    (int(q*100) maps both to 99)."""
    return "p" + f"{q * 100:g}".replace(".", "_")

LabelsKey = tuple[tuple[str, str], ...]


def _labels_key(labels: dict[str, Any]) -> LabelsKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _quantile(sorted_xs: list[float], q: float) -> float:
    """Nearest-rank quantile (ceil(q*n)-1) over a sorted window."""
    if not sorted_xs:
        return float("nan")
    idx = max(math.ceil(q * len(sorted_xs)) - 1, 0)
    return sorted_xs[min(idx, len(sorted_xs) - 1)]


class Counter:
    """Monotonic counter (float increments allowed: byte totals)."""

    __slots__ = ("value", "_mu")

    def __init__(self) -> None:
        self.value = 0.0
        self._mu = threading.Lock()

    def inc(self, v: float = 1.0) -> None:
        with self._mu:
            self.value += v


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Bounded streaming-quantile histogram: exact cumulative
    count/sum/min/max plus a sliding window of the last `window`
    samples from which p50/p95/p99/p99.9 are computed on demand. Bounded by
    construction — an always-on series can never grow past its window
    no matter how long the process lives."""

    __slots__ = ("count", "sum", "min", "max", "_window", "_mu")

    def __init__(self, window: int = DEFAULT_HISTOGRAM_WINDOW) -> None:
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._window: deque[float] = deque(maxlen=max(int(window), 1))
        self._mu = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._mu:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            self._window.append(v)

    def quantiles(self) -> dict[float, float]:
        with self._mu:
            xs = sorted(self._window)
        return {q: _quantile(xs, q) for q in QUANTILES}

    def snapshot(self) -> dict[str, Any]:
        with self._mu:
            xs = sorted(self._window)
        out: dict[str, Any] = {
            "count": self.count,
            "sum": self.sum,
            "window": len(xs),
        }
        if xs:
            out["min"] = self.min
            out["max"] = self.max
            for q in QUANTILES:
                out[quantile_key(q)] = _quantile(xs, q)
        return out


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: LabelsKey, extra: tuple[tuple[str, str], ...] = ()) -> str:
    items = key + extra
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in items)
    return "{" + body + "}"


class MetricsRegistry:
    """Thread-safe named-series registry. Series are created lazily on
    first touch and keyed by (metric name, sorted label tuple).

    Caller-supplied label keys (``GUARDED_LABEL_KEYS``, i.e. `tenant`)
    are cardinality-guarded: the first `label_value_cap` distinct
    values get their own series, every later value lands in the
    ``other`` overflow bucket and bumps ``accl_label_overflow_total``
    — so a hostile tenant-id stream bounds the registry instead of
    growing it."""

    def __init__(self, histogram_window: int = DEFAULT_HISTOGRAM_WINDOW,
                 label_value_cap: int | None = None):
        self._mu = threading.Lock()
        self._histogram_window = histogram_window
        self._label_value_cap = (max(int(label_value_cap), 1)
                                 if label_value_cap is not None
                                 else _label_value_cap())
        self._guarded_values: dict[str, set[str]] = {}
        self._counters: dict[tuple[str, LabelsKey], Counter] = {}
        self._gauges: dict[tuple[str, LabelsKey], Gauge] = {}
        self._histograms: dict[tuple[str, LabelsKey], Histogram] = {}

    # -- label cardinality guard -------------------------------------------

    def _guard_labels(self, labels: dict[str, Any]) -> dict[str, Any]:
        """Map guarded label values past the cap onto the overflow
        bucket. Admission is first-come: the set of attributed values
        freezes once full, so the series space is bounded for the
        process lifetime no matter what ids arrive later."""
        overflowed: list[str] = []
        for k in GUARDED_LABEL_KEYS:
            if k not in labels:
                continue
            v = str(labels[k])
            if v == LABEL_OVERFLOW_BUCKET:
                continue
            seen = self._guarded_values.get(k)
            if seen is not None and v in seen:
                continue
            with self._mu:
                seen = self._guarded_values.setdefault(k, set())
                if v in seen:
                    continue
                if len(seen) < self._label_value_cap:
                    seen.add(v)
                    continue
            labels = {**labels, k: LABEL_OVERFLOW_BUCKET}
            overflowed.append(k)
        # outside _mu: counter() re-acquires the registry lock on a
        # first-touch miss
        for k in overflowed:
            self.counter("accl_label_overflow_total", label=k).inc()
        return labels

    def guarded_values(self, key: str) -> frozenset[str]:
        """The attributed value set for a guarded label key (what got a
        series of its own before the cap)."""
        with self._mu:
            return frozenset(self._guarded_values.get(key, ()))

    # -- series access -----------------------------------------------------

    def counter(self, name: str, **labels: Any) -> Counter:
        labels = self._guard_labels(labels)
        key = (name, _labels_key(labels))
        c = self._counters.get(key)
        if c is None:
            with self._mu:
                c = self._counters.setdefault(key, Counter())
        return c

    def gauge(self, name: str, **labels: Any) -> Gauge:
        labels = self._guard_labels(labels)
        key = (name, _labels_key(labels))
        g = self._gauges.get(key)
        if g is None:
            with self._mu:
                g = self._gauges.setdefault(key, Gauge())
        return g

    def histogram(self, name: str, **labels: Any) -> Histogram:
        labels = self._guard_labels(labels)
        key = (name, _labels_key(labels))
        h = self._histograms.get(key)
        if h is None:
            with self._mu:
                h = self._histograms.setdefault(
                    key, Histogram(self._histogram_window))
        return h

    def clear(self) -> None:
        with self._mu:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._guarded_values.clear()

    # -- readout -----------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """JSON-ready registry state — the document Tracer.to_trace
        embeds in the SPAN v1 meta (``meta["metrics"]``)."""
        with self._mu:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            histograms = list(self._histograms.items())

        def rows(items: Iterable, render: Callable) -> dict[str, list]:
            by_name: dict[str, list] = {}
            for (name, key), series in sorted(items, key=lambda kv: kv[0]):
                row = {"labels": dict(key)}
                row.update(render(series))
                by_name.setdefault(name, []).append(row)
            return by_name

        return {
            "counters": rows(counters, lambda c: {"value": c.value}),
            "gauges": rows(gauges, lambda g: {"value": g.value}),
            "histograms": rows(histograms, lambda h: h.snapshot()),
        }

    def expose_text(self) -> str:
        """Prometheus text exposition (counters and gauges as-is;
        histograms as summary-style quantile series plus _sum/_count)."""
        lines: list[str] = []
        with self._mu:
            counters = sorted(self._counters.items(), key=lambda kv: kv[0])
            gauges = sorted(self._gauges.items(), key=lambda kv: kv[0])
            histograms = sorted(self._histograms.items(),
                                key=lambda kv: kv[0])
        seen: set[str] = set()
        for (name, key), c in counters:
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} counter")
            lines.append(f"{name}{_fmt_labels(key)} {c.value:g}")
        for (name, key), g in gauges:
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{_fmt_labels(key)} {g.value:g}")
        for (name, key), h in histograms:
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} summary")
            for q, v in h.quantiles().items():
                lines.append(
                    f"{name}{_fmt_labels(key, (('quantile', f'{q:g}'),))}"
                    f" {v:g}")
            lines.append(f"{name}_sum{_fmt_labels(key)} {h.sum:g}")
            lines.append(f"{name}_count{_fmt_labels(key)} {h.count}")
        return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# drift sentinel
# ---------------------------------------------------------------------------

DEFAULT_SENTINEL_WINDOW = 64
DEFAULT_SENTINEL_MIN_SAMPLES = 8
DEFAULT_SENTINEL_BAND_FACTOR = 3.0
# the absolute floor under the band: a reference armed on a near-perfect
# fit (residuals ~0.02) must not flag ordinary mesh jitter as drift
DEFAULT_SENTINEL_BAND_FLOOR = 0.25


class DriftSentinel:
    """Rolling predicted-vs-measured residual watcher per op.

    Band semantics (docs/observability.md): for each op the sentinel
    keeps a bounded window of relative residuals ``|predicted_s -
    measured_s| / measured_s``. The first ``min_samples`` residuals arm
    a FROZEN reference (their median — the shipped calibration's honest
    error in the current regime); from then on the op is *out of band*
    when the rolling median exceeds ``max(reference * band_factor,
    reference + band_floor)``. A regime change (congestion, throttle,
    tenant interference — the WAN shaper emulates all three) inflates
    every measurement against the stale prediction, the rolling median
    crosses the band within one window, and ``flagged()`` names the op;
    a stable run keeps drawing residuals from the reference
    distribution and stays quiet. Detection + report only: re-deriving
    and applying registers from the verdict is the actuation follow-up
    (ROADMAP item 5's second half).

    Per-rank feeds (the native ``emu/r<rank>`` tracks) drive a
    straggler attribution: per (op, count) the per-rank median
    measurement, the max-over-ranks vs median-of-ranks skew, and the
    argmax rank.
    """

    def __init__(self, window: int = DEFAULT_SENTINEL_WINDOW,
                 min_samples: int = DEFAULT_SENTINEL_MIN_SAMPLES,
                 band_factor: float = DEFAULT_SENTINEL_BAND_FACTOR,
                 band_floor: float = DEFAULT_SENTINEL_BAND_FLOOR):
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.band_factor = float(band_factor)
        self.band_floor = float(band_floor)
        self._mu = threading.Lock()
        self._residuals: dict[str, deque[float]] = {}
        self._reference: dict[str, float] = {}
        self._n_seen: dict[str, int] = {}
        # (op, count) -> rank -> bounded deque of measured seconds
        self._rank_meas: dict[tuple[str, int], dict[int, deque[float]]] = {}

    # -- feeding -----------------------------------------------------------

    def feed(self, op: str, predicted_s: float, measured_s: float) -> None:
        if measured_s <= 0:
            return
        rel = abs(float(predicted_s) - float(measured_s)) / float(measured_s)
        with self._mu:
            dq = self._residuals.get(op)
            if dq is None:
                dq = self._residuals[op] = deque(maxlen=self.window)
            dq.append(rel)
            self._n_seen[op] = self._n_seen.get(op, 0) + 1
            if op not in self._reference and len(dq) >= self.min_samples:
                self._reference[op] = _median(list(dq))

    def feed_rank(self, op: str, count: int, rank: int,
                  measured_s: float) -> None:
        if measured_s <= 0:
            return
        with self._mu:
            ranks = self._rank_meas.setdefault((op, int(count)), {})
            dq = ranks.get(int(rank))
            if dq is None:
                dq = ranks[int(rank)] = deque(maxlen=self.window)
            dq.append(float(measured_s))

    def set_reference(self, op: str, median_rel_err: float) -> None:
        """Pin an op's reference residual explicitly (e.g. from a
        committed calibration's known error) instead of self-arming."""
        with self._mu:
            self._reference[op] = float(median_rel_err)

    def reset(self) -> None:
        with self._mu:
            self._residuals.clear()
            self._reference.clear()
            self._n_seen.clear()
            self._rank_meas.clear()

    # -- verdicts ----------------------------------------------------------

    def band_hi(self, reference: float) -> float:
        return max(reference * self.band_factor,
                   reference + self.band_floor)

    def verdict(self) -> dict[str, dict[str, Any]]:
        """Per-op drift verdict: rolling median residual vs the frozen
        reference band. ``armed=False`` ops (fewer than ``min_samples``
        residuals seen) carry no in/out-of-band claim."""
        out: dict[str, dict[str, Any]] = {}
        with self._mu:
            items = [(op, list(dq)) for op, dq in self._residuals.items()]
            refs = dict(self._reference)
            seen = dict(self._n_seen)
        for op, xs in sorted(items):
            row: dict[str, Any] = {
                "n": seen.get(op, len(xs)),
                "window": len(xs),
                "median_rel_err": _median(xs),
            }
            ref = refs.get(op)
            if ref is None:
                row["armed"] = False
            else:
                hi = self.band_hi(ref)
                row.update(armed=True, reference=ref, band_hi=hi,
                           in_band=row["median_rel_err"] <= hi)
            out[op] = row
        return out

    def flagged(self) -> list[str]:
        """Ops whose rolling residual has left the band — the sentinel's
        one-line answer."""
        return [op for op, row in self.verdict().items()
                if row.get("armed") and not row["in_band"]]

    def straggler_report(self) -> list[dict[str, Any]]:
        """Per (op, count): per-rank median measured seconds, the
        max-over-ranks vs median-of-ranks skew, and which rank is the
        straggler. Needs >= 2 ranks reporting."""
        with self._mu:
            waves = [(key, {r: list(dq) for r, dq in ranks.items()})
                     for key, ranks in self._rank_meas.items()]
        out = []
        for (op, count), ranks in sorted(waves):
            if len(ranks) < 2:
                continue
            per_rank = {r: _median(xs) for r, xs in sorted(ranks.items())}
            med = _median(list(per_rank.values()))
            worst_rank = max(per_rank, key=lambda r: per_rank[r])
            out.append({
                "op": op,
                "count": count,
                "ranks": len(per_rank),
                "per_rank_median_s": per_rank,
                "median_s": med,
                "max_s": per_rank[worst_rank],
                "skew": per_rank[worst_rank] / med if med > 0
                else float("nan"),
                "straggler_rank": worst_rank,
            })
        return out

    def report(self) -> dict[str, Any]:
        """The JSON block bench --obs-gate / --check and the trace meta
        carry: verdict + flags + straggler attribution."""
        return {
            "window": self.window,
            "min_samples": self.min_samples,
            "band_factor": self.band_factor,
            "band_floor": self.band_floor,
            "verdict": self.verdict(),
            "flagged": self.flagged(),
            "stragglers": self.straggler_report(),
        }


# ---------------------------------------------------------------------------
# the span -> metrics rule (the observer Tracer.emit feeds)
# ---------------------------------------------------------------------------


def _series_labels(ev: dict[str, Any], args: dict[str, Any]) -> dict[str, Any]:
    return {
        "op": args.get("op") or ev.get("name", "?"),
        "algorithm": args.get("algorithm", "?"),
        "protocol": args.get("protocol", "?"),
        "world": args.get("world", 0),
    }


class MetricsObserver:
    """The Tracer observer: lifts every emitted SPAN v1 event into
    registry updates and sentinel feeds. One instance per (registry,
    sentinel) pair; ``install()`` wires the process-wide one."""

    def __init__(self, registry: MetricsRegistry | None = None,
                 sentinel: DriftSentinel | None = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.sentinel = sentinel if sentinel is not None else DriftSentinel()

    def __call__(self, ev: dict[str, Any]) -> None:
        reg = self.registry
        cat = ev.get("cat", "")
        args = ev.get("args") or {}
        if cat in ("call", "native"):
            labels = _series_labels(ev, args)
            reg.counter("accl_calls_total", **labels).inc()
            nbytes = args.get("bytes")
            if nbytes:
                reg.counter("accl_bytes_total", **labels).inc(float(nbytes))
            meas = measured_seconds(ev)
            if meas > 0 and not args.get("dispatch_only"):
                reg.histogram("accl_call_seconds", **labels).observe(meas)
                pred = args.get("predicted_s")
                if isinstance(pred, (int, float)):
                    self.sentinel.feed(labels["op"], float(pred), meas)
                if cat == "native" and "rank" in args:
                    self.sentinel.feed_rank(labels["op"],
                                            int(args.get("count", 0)),
                                            int(args["rank"]), meas)
            rc = args.get("retcode", 0)
            if rc:
                reg.counter("accl_errors_total", op=labels["op"],
                            retcode=rc).inc()
        elif cat == "step":
            # fused-batch steps execute inside ONE dispatch and never
            # appear as calls: the step counter is what keeps the op
            # mix of steady-state sequence traffic visible live
            reg.counter("accl_steps_total",
                        **_series_labels(ev, args)).inc()
        elif cat == "phase":
            meas = measured_seconds(ev)
            if meas > 0:
                reg.histogram("accl_phase_seconds",
                              phase=ev.get("name", "?")).observe(meas)
        elif cat == "sequence":
            reg.counter("accl_sequences_total").inc()
            meas = measured_seconds(ev)
            if meas > 0 and not args.get("dispatch_only"):
                reg.histogram("accl_sequence_seconds").observe(meas)
        elif cat == "error":
            reg.counter("accl_errors_total", op=ev.get("name", "?"),
                        retcode=args.get("retcode", 0)).inc()

    def trace_meta(self) -> dict[str, Any]:
        """Contribution to Tracer.to_trace's meta: the live registry
        snapshot + sentinel report ride every exported trace."""
        return {"metrics": self.registry.snapshot(),
                "drift_sentinel": self.sentinel.report()}


def replay_trace(trace: dict[str, Any],
                 observer: MetricsObserver | None = None) -> MetricsObserver:
    """Rebuild registry + sentinel state from an already-exported trace
    document (tools/accl_trace.py --metrics): the offline twin of the
    live observer, running the SAME span -> metrics rule."""
    obs = observer if observer is not None else MetricsObserver()
    for sp in trace.get("spans", []):
        if isinstance(sp, dict):
            obs(sp)
    return obs


# ---------------------------------------------------------------------------
# process-wide instance
# ---------------------------------------------------------------------------

_observer = MetricsObserver()


def get_observer() -> MetricsObserver:
    return _observer


def get_registry() -> MetricsRegistry:
    """The process-wide registry the installed observer feeds."""
    return _observer.registry


def get_sentinel() -> DriftSentinel:
    """The process-wide drift sentinel."""
    return _observer.sentinel


def install(tracer: Any) -> None:
    """Attach the process-wide metrics observer to a tracer (idempotent)."""
    tracer.add_observer(_observer)


def uninstall(tracer: Any) -> None:
    tracer.remove_observer(_observer)
