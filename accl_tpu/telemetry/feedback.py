"""The measured-vs-predicted feedback loop.

timing.predict answers "how long should this call take"; the trace ring
answers "how long did it take". This module closes the loop (the HiCCL
posture: a timing model continuously calibrated from measured
collectives is what makes algorithm selection trustworthy):

  - calibrate_from_trace(): spans that carry their aggregate cost
    coefficients (telemetry.native attaches coef_messages/coef_bytes at
    drain time) become timing.calibrate samples, yielding refit
    LinkParams;
  - residual_improvement(): the mechanically-honest scoreboard — median
    |predicted - measured| / measured under the shipped default link vs
    under the refit, over the same spans;
  - autotune_from_trace(): hands the refit link to ACCL.autotune, so
    the tuning registers the device actually consults move with the
    measurements.

default_link() loads the shipped calibration the same way ACCL.autotune
does (accl_log/timing_model.json, bcast per-collective fit), so "the
shipped defaults" in every residual comparison means exactly what
autotune would have used.
"""

from __future__ import annotations

import json
import pathlib
import time

from ..sequencer.timing import (
    ComputeFit,
    LinkParams,
    TierLinks,
    calibrate,
    calibrate_compute,
)
from .export import measured_seconds, median, residual_rows, residual_summary

_MODEL_PATH = (pathlib.Path(__file__).resolve().parents[2]
               / "accl_log" / "timing_model.json")


# (path, kind) -> (mtime_ns | None, last_stat_monotonic, value)
_default_link_cache: dict = {}
_MODEL_CACHE_MAX = 64
# how long a cache entry may serve without re-stat()ing the model file:
# the freshness bound of the staleness fix below. This sits on the
# per-call plan-selection hot path, so the mtime check is amortized —
# at most one stat() per path per TTL, a pure dict hit otherwise.
_STAT_TTL_S = 0.5


def _mtime_ns(p: pathlib.Path) -> int | None:
    try:
        return p.stat().st_mtime_ns
    except OSError:
        return None


def _model_cache_get(p: pathlib.Path, kind: str, load):
    """Freshness-checked cache for loaded timing-model sections. A
    `timing_model.json` OVERWRITTEN later in the same process (bench
    gates rewrite link_tiers / compute_fit; a live refitter will
    rewrite the link) bumps the file's mtime and is re-read within
    _STAT_TTL_S, where the old per-path cache served the stale model
    for the rest of the process. A missing file caches its negative
    result under mtime None, so the file appearing later is still
    picked up."""
    key = (str(p), kind)
    now = time.monotonic()
    ent = _default_link_cache.get(key)
    if ent is not None and now - ent[1] < _STAT_TTL_S:
        return ent[2]
    mtime = _mtime_ns(p)
    if ent is not None and ent[0] == mtime:
        _default_link_cache[key] = (mtime, now, ent[2])
        return ent[2]
    value = load(p)
    if len(_default_link_cache) >= _MODEL_CACHE_MAX:
        _default_link_cache.clear()
    _default_link_cache[key] = (mtime, now, value)
    return value


def default_link(path=None) -> LinkParams | None:
    """The shipped emulator-tier LinkParams (the same selection rule as
    ACCL.autotune: per-collective bcast fit, legacy single-link
    fallback). None when no timing model is committed. Results (hits
    AND misses) are cached with an mtime freshness check — live span
    emission calls this once per traced call (a dict hit; at most one
    stat per _STAT_TTL_S), while a refit that overwrites the model
    file mid-process bumps the mtime and is picked up within the
    TTL."""
    p = pathlib.Path(path) if path else _MODEL_PATH
    return _model_cache_get(p, "link", _load_link)


def _load_link(p: pathlib.Path) -> LinkParams | None:
    # a malformed or partially-written model (hand-edited, interrupted
    # fit) degrades to "no default link", never to a per-call crash in
    # the traced hot path
    try:
        model = json.loads(p.read_text())
        lk = (model.get("link_per_collective", {}).get("bcast")
              or model.get("link"))
        if not lk:
            return None
        return LinkParams(alpha=lk["alpha_us"] * 1e-6,
                          beta=lk["beta_gbps"] * 1e9)
    except (OSError, ValueError, KeyError, TypeError, AttributeError):
        return None


def hop_samples(trace: dict,
                tier: str | None = None) -> list[tuple[float, float, float]]:
    """(messages, bytes, measured_seconds) samples from every span that
    carries its aggregate cost coefficients and a positive measurement —
    the exact input shape timing.calibrate fits. `tier="inner"|"outer"`
    keeps only spans tagged with that tier (args["tier"], SPAN
    v1-compatible detail key), the labeled-sample source for the
    per-tier refit. `tier=None` — the flat fit — keeps only UNTAGGED
    spans: a tier-tagged span's measurement belongs to that tier's
    link, and pooling two links with different alpha/beta into one fit
    would average them into a model of neither (the exact failure the
    tier labels exist to prevent)."""
    samples = []
    for sp in trace.get("spans", []):
        if not isinstance(sp, dict):
            continue
        args = sp.get("args") or {}
        if "coef_messages" not in args or "coef_bytes" not in args:
            continue
        if args.get("tier") != tier:
            continue
        try:
            m = float(args["coef_messages"])
            b = float(args["coef_bytes"])
        except (TypeError, ValueError):
            continue  # partially-populated span: no calibratable cost
        if m <= 0 and b <= 0:
            continue  # cost-free spans (world==1 degenerate calls)
        t = measured_seconds(sp)
        if t <= 0:
            continue
        samples.append((m, b, t))
    return samples


def calibrate_from_trace(trace: dict, tier: str | None = None) -> LinkParams:
    """Refit LinkParams from a trace's measured hop spans (optionally
    only the spans tagged with one `tier`). Raises ValueError when the
    trace carries no calibratable spans (a trace from a run with
    tracing off, or pure host-phase spans)."""
    samples = hop_samples(trace, tier=tier)
    if len(samples) < 2:
        where = f" tagged tier={tier!r}" if tier else ""
        raise ValueError(
            f"trace has {len(samples)} calibratable span(s){where}; "
            "need >= 2 (native spans with coef_messages/coef_bytes — "
            "run with ACCL_RT_TRACE=1 and drain through "
            "telemetry.native)")
    return calibrate(samples)


def calibrate_tiers_from_trace(trace: dict) -> TierLinks:
    """The per-tier form of calibrate_from_trace: each tier of a
    two-tier world refit INDEPENDENTLY from its own tier-tagged spans
    (args["tier"] == "inner" / "outer" — the emulated 2-tier bench
    world tags inner-POE and outer-TCP calls at drain time). This is
    what makes the hierarchical predictions honest: the DCN link's
    alpha/beta are fit from DCN measurements only, never averaged with
    ICI's."""
    return TierLinks(inner=calibrate_from_trace(trace, tier="inner"),
                     outer=calibrate_from_trace(trace, tier="outer"))


def default_tier_links(path=None) -> TierLinks | None:
    """The shipped per-tier calibration: the timing model document's
    `link_tiers` section ({"inner": {alpha_us, beta_gbps}, "outer":
    {...}}, written by bench.py --hier-gate's per-tier refit). None
    when the model carries no tier fit — callers (autotune, stripe
    selection) must then leave hierarchical selection off rather than
    invent a slow-tier model."""
    p = pathlib.Path(path) if path else _MODEL_PATH
    # negative results cached too (per mtime): this sits on the
    # per-call plan selection path (an in-window select_algorithm with
    # no caller tier_links lands here), and re-reading the model file
    # on every call is hot-path disk I/O for the same None
    return _model_cache_get(p, "tiers", _load_tier_links)


def _load_tier_links(p: pathlib.Path) -> TierLinks | None:
    try:
        model = json.loads(p.read_text())
        tiers = model.get("link_tiers")
        return TierLinks(
            inner=LinkParams(alpha=tiers["inner"]["alpha_us"] * 1e-6,
                             beta=tiers["inner"]["beta_gbps"] * 1e9),
            outer=LinkParams(alpha=tiers["outer"]["alpha_us"] * 1e-6,
                             beta=tiers["outer"]["beta_gbps"] * 1e9),
        )
    except (OSError, ValueError, KeyError, TypeError, AttributeError):
        return None


def compute_samples(trace: dict) -> list[tuple[float, float]]:
    """(operand_bytes, measured_seconds) samples from every span that
    carries a `compute_bytes` arg and a positive measurement — the
    busy-core term of the overlap pipeline (timing.ComputeFit), fitted
    from spans exactly like the link is fitted from hop spans. The
    overlap gate emits these by timing the train step's compute stage
    at two model sizes and tagging each span with the gradient bytes
    that stage materializes."""
    samples = []
    for sp in trace.get("spans", []):
        if not isinstance(sp, dict):
            continue
        args = sp.get("args") or {}
        if "compute_bytes" not in args:
            continue
        try:
            b = float(args["compute_bytes"])
        except (TypeError, ValueError):
            continue
        t = measured_seconds(sp)
        if b <= 0 or t <= 0:
            continue
        samples.append((b, t))
    return samples


def calibrate_compute_from_trace(trace: dict) -> ComputeFit:
    """Refit the overlap pipeline's compute term from a trace's
    compute-tagged spans. Raises ValueError below two samples (a
    one-point fit cannot separate the fixed cost from the rate)."""
    samples = compute_samples(trace)
    if len(samples) < 2:
        raise ValueError(
            f"trace has {len(samples)} compute span(s); need >= 2 "
            "(spans with args.compute_bytes at distinct sizes — the "
            "overlap gate's compute-calibration sweep emits them)")
    return calibrate_compute(samples)


def default_compute_fit(path=None) -> ComputeFit | None:
    """The shipped compute-term calibration: the timing model
    document's `compute_fit` section ({alpha_us, grad_gbps}, written
    by bench.py --overlap-gate's refit). None when no fit is committed
    — callers (autotune, overlap stripe selection) must then leave the
    overlap register off rather than invent a compute model. Results
    are cached per (path, mtime) — this sits on the per-call plan
    selection path, and a fit written later in the same process bumps
    the mtime and is picked up."""
    p = pathlib.Path(path) if path else _MODEL_PATH
    return _model_cache_get(p, "compute", _load_compute_fit)


def _load_compute_fit(p: pathlib.Path) -> ComputeFit | None:
    try:
        model = json.loads(p.read_text())
        cf = model["compute_fit"]
        return ComputeFit(
            alpha=cf["alpha_us"] * 1e-6, rate=cf["grad_gbps"] * 1e9)
    except (OSError, ValueError, KeyError, TypeError, AttributeError):
        return None


def _rel_errs(trace: dict, link: LinkParams) -> list[float]:
    errs = []
    for m, b, t in hop_samples(trace):
        pred = link.seconds(m, b)
        errs.append(abs(pred - t) / t)
    return errs


def residual_improvement(trace: dict,
                         default: LinkParams | None = None) -> dict:
    """Median relative residual under the shipped default link vs under
    the trace's own refit, over the same calibratable spans. The bench
    --trace gate requires refit <= default: if refitting on the very
    measurements cannot beat the shipped constants, the feedback loop
    is broken (or the cost shapes regressed)."""
    if default is None:
        default = default_link()
    refit = calibrate_from_trace(trace)
    out = {
        "samples": len(hop_samples(trace)),
        "refit": {"alpha_us": refit.alpha * 1e6,
                  "beta_gbps": refit.beta / 1e9},
        "median_rel_err_refit": median(_rel_errs(trace, refit)),
    }
    if default is not None:
        out["default"] = {"alpha_us": default.alpha * 1e6,
                          "beta_gbps": default.beta / 1e9}
        out["median_rel_err_default"] = median(_rel_errs(trace, default))
        out["improved"] = (out["median_rel_err_refit"]
                           <= out["median_rel_err_default"])
    return out


def autotune_from_trace(accl, trace: dict, **autotune_kw):
    """Close the loop into the tuning registers: refit LinkParams from
    the trace and apply ACCL.autotune with them. Returns the applied
    TuningParams (the registers the device now consults per call)."""
    link = calibrate_from_trace(trace)
    return accl.autotune(link=link, **autotune_kw)


def residual_report(trace: dict) -> dict:
    """The residual section bench.py --trace embeds in its JSON: the
    span-level residual summary (spans carrying predicted_s) plus the
    default-vs-refit improvement over the calibratable samples."""
    rows = residual_rows(trace)
    report = {"span_residuals": residual_summary(rows)}
    try:
        report["calibration"] = residual_improvement(trace)
    except ValueError as e:
        report["calibration"] = {"error": str(e)}
    return report
