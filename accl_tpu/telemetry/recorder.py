"""Flight recorder: a bounded in-memory ring of the last N spans per
track, with dump-on-error wired into the sticky-retcode path.

The reference driver's ``dump_rx_buffers`` / ``dump_communicator``
debug surfaces exist because the interesting state is gone by the time
a human attaches a debugger; the ACCL+ paper motivates exactly that
"debug after dispatch" pain. The flight recorder is that posture for
spans: it rides the same span-emission seam the metrics registry does
(a ``Tracer`` observer — facade calls, sequence phases, per-step
markers, drained native spans), keeps only the most recent N per
track, and when a call completes with a sticky nonzero retcode
(``errors.notify_sticky_retcode``, called from ``request.py``'s
completion path and the native ``EmuRank.wait``) freezes the rings
into a self-contained SPAN v1 post-mortem document — schema-valid,
the failing call's error marker span appended (cat ``"error"``, the
op name, its sticky retcode), the live metrics snapshot + drift
verdict embedded in its meta — WITHOUT full tracing ever having been
enabled.

The last post-mortem is always retained in memory
(``last_error_trace()``); set ``ACCL_FLIGHT_DIR`` to also write each
one to ``<dir>/flight_last_error.json`` (file writes are opt-in so
fault-injection test suites do not spray artifacts).
"""

from __future__ import annotations

import json
import os
import pathlib
import threading
import time
from collections import deque
from typing import Any

from .tracer import SCHEMA_VERSION, get_tracer

DEFAULT_TRACK_CAPACITY = 256


class FlightRecorder:
    """Thread-safe per-track bounded span rings (drop-oldest)."""

    def __init__(self, track_capacity: int | None = None):
        if track_capacity is None:
            try:
                track_capacity = int(os.environ.get("ACCL_FLIGHT_CAP", "0"))
            except ValueError:
                track_capacity = 0
            if track_capacity <= 0:
                track_capacity = DEFAULT_TRACK_CAPACITY
        self.track_capacity = int(track_capacity)
        self._mu = threading.Lock()
        self._tracks: dict[str, deque[dict[str, Any]]] = {}
        self._last_error_trace: dict[str, Any] | None = None

    # -- observer ----------------------------------------------------------

    def __call__(self, ev: dict[str, Any]) -> None:
        track = ev.get("track", "?")
        with self._mu:
            dq = self._tracks.get(track)
            if dq is None:
                dq = self._tracks[track] = deque(
                    maxlen=self.track_capacity)
            dq.append(ev)

    # -- readout -----------------------------------------------------------

    def snapshot(self) -> list[dict[str, Any]]:
        """Every retained span, globally time-ordered."""
        with self._mu:
            spans = [ev for dq in self._tracks.values() for ev in dq]
        spans.sort(key=lambda ev: ev.get("ts_ns", 0))
        return spans

    def clear(self) -> None:
        with self._mu:
            self._tracks.clear()
            self._last_error_trace = None

    def to_trace(self, *, reason: str,
                 extra_meta: dict[str, Any] | None = None) -> dict[str, Any]:
        """Freeze the rings into a self-contained SPAN v1 document; the
        live metrics snapshot + sentinel verdict ride the meta so the
        post-mortem carries its own context."""
        meta: dict[str, Any] = {
            "flight_recorder": True,
            "reason": reason,
            "track_capacity": self.track_capacity,
        }
        try:
            from .metrics import get_observer

            meta.update(get_observer().trace_meta())
        except Exception:  # a metrics failure must not lose the dump
            pass
        if extra_meta:
            meta.update(extra_meta)
        return {"schema": SCHEMA_VERSION, "meta": meta,
                "spans": self.snapshot()}

    # -- dump-on-error -----------------------------------------------------

    def freeze_error(self, reason: str) -> dict[str, Any]:
        """Retain (and optionally write) the post-mortem for one sticky
        error."""
        doc = self.to_trace(reason=reason)
        with self._mu:
            self._last_error_trace = doc
        self._maybe_write(doc)
        return doc

    def _maybe_write(self, doc: dict[str, Any]) -> None:
        out = os.environ.get("ACCL_FLIGHT_DIR")
        if not out:
            return
        try:
            d = pathlib.Path(out)
            d.mkdir(parents=True, exist_ok=True)
            (d / "flight_last_error.json").write_text(
                json.dumps(doc, indent=1))
        except OSError:
            pass  # a full disk must not mask the real error

    def last_error_trace(self) -> dict[str, Any] | None:
        with self._mu:
            return self._last_error_trace


# ---------------------------------------------------------------------------
# process-wide instance
# ---------------------------------------------------------------------------

_recorder = FlightRecorder()
_armed = False


def get_recorder() -> FlightRecorder:
    return _recorder


def install(tracer: Any) -> None:
    global _armed
    tracer.add_observer(_recorder)
    _armed = True


def uninstall(tracer: Any) -> None:
    global _armed
    tracer.remove_observer(_recorder)
    _armed = False


def armed() -> bool:
    """True when the process-wide recorder rides the span stream (the
    sticky-retcode hook is a no-op otherwise)."""
    return _armed


def on_sticky_retcode(function_name: str, retcode: int, *,
                      detail: int = 0, rank: int | None = None,
                      count: int | None = None) -> dict[str, Any] | None:
    """Module-level dump-on-error entry (errors.notify_sticky_retcode
    forwards here). No-op unless the recorder is armed. The error
    marker span is EMITTED through the process tracer — every observer
    sees it (the metrics error counter increments, the recorder ring
    retains it) — then the rings freeze into the retained post-mortem
    document."""
    if not _armed:
        return None
    args: dict[str, Any] = {"retcode": int(retcode)}
    if detail:
        args["detail"] = int(detail)
    if rank is not None:
        args["rank"] = int(rank)
    if count is not None:
        args["count"] = int(count)
    get_tracer().emit(
        function_name, "error",
        "errors" if rank is None else f"emu/r{rank}",
        ts_ns=time.perf_counter_ns(), dur_ns=0, args=args)
    return _recorder.freeze_error(
        f"sticky retcode 0x{int(retcode):x} from {function_name}")


def on_deadline_miss(op: str, *, rank: int | None = None,
                     count: int | None = None,
                     predicted_s: float | None = None,
                     deadline_s: float | None = None,
                     elapsed_s: float | None = None,
                     suspect_rank: int | None = None,
                     retcode: int = 0) -> dict[str, Any] | None:
    """Host-side dump-on-error twin of ``on_sticky_retcode``: a missed
    model-derived deadline (resilience.DeadlinePolicy's verdict) is an
    error event even when NO sticky native retcode exists — a silent
    hang inside the old fixed-timeout tolerance window used to leave no
    artifact at all.  Emits the marker span through the tracer (cat
    "error", ``deadline_missed: true`` — the metrics error counter sees
    it) and freezes the rings into the retained post-mortem.  No-op
    unless the recorder is armed; never raises."""
    if not _armed:
        return None
    args: dict[str, Any] = {"deadline_missed": True,
                            "retcode": int(retcode)}
    if rank is not None:
        args["rank"] = int(rank)
    if count is not None:
        args["count"] = int(count)
    if predicted_s is not None:
        args["predicted_s"] = float(predicted_s)
    if deadline_s is not None:
        args["deadline_s"] = float(deadline_s)
    if elapsed_s is not None:
        args["measured_s"] = float(elapsed_s)
    if suspect_rank is not None:
        args["suspect_rank"] = int(suspect_rank)
    get_tracer().emit(
        op, "error", "errors" if rank is None else f"emu/r{rank}",
        ts_ns=time.perf_counter_ns(), dur_ns=0, args=args)
    return _recorder.freeze_error(f"deadline missed on {op}")


def last_error_trace() -> dict[str, Any] | None:
    return _recorder.last_error_trace()
