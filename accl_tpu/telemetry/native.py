"""Native trace-ring drain: device-resident spans -> telemetry events.

The native runtime records one accl_rt_span_t per completed call in a
per-rank ring (ACCL_RT_TRACE=1, runtime.cpp record_span); EmuRank
.trace_read drains the raw structs through ctypes. This module lifts
those raw records into the SPAN v1 event schema (tracer.py), attaching
the things only the host knows:

  - the Operation name behind the opcode;
  - the Plan the shared selection rules would resolve for that call (so
    the span names its algorithm honestly — the native runtime applies
    the SAME rules, plan.py's single-rule-set contract);
  - the aggregate cost coefficients (messages, wire bytes) of that plan
    from timing.coefficients_aggregate — the shape the serialized
    emulator host actually pays — which is what lets
    feedback.calibrate_from_trace turn measured spans into
    timing.calibrate samples;
  - the timing.predict estimate under a given LinkParams, so every
    native span carries its prediction next to its measurement.

Per-rank tracks are named "emu/r<rank>" — the one-track-per-rank layout
the Chrome export renders.
"""

from __future__ import annotations

import time

from ..constants import Operation, TuningParams, dtype_nbytes, DataType
from ..sequencer.plan import select_algorithm
from ..sequencer.timing import LinkParams, coefficients_aggregate

# THE eager/rx geometry of the emulator sweeps — the single source
# (tools/bench_emulator.py imports these as MAX_EAGER/RX_BUF): the
# default config under which native spans are re-planned when the
# caller does not say otherwise. Retuning here moves the sweep, the
# protocol labeler, and every telemetry cost computation together.
DEFAULT_MAX_EAGER = 4096
DEFAULT_RX_BUF = 4096


def span_cost(
    op: Operation,
    count: int,
    elem_bytes: int,
    world: int,
    *,
    max_eager_size: int = DEFAULT_MAX_EAGER,
    rx_buf_bytes: int = DEFAULT_RX_BUF,
    tuning: TuningParams | None = None,
    logp_shape: bool | None = None,
):
    """(plan, messages, wire_bytes) for one native call under the shared
    selection rules and the AGGREGATE cost shape (the serialized-host
    regime the emulator tier is calibrated on). Returns (None, 0, 0)
    for calls with no data-plane cost shape (config/nop). `logp_shape`
    mirrors a forced ACCL_RT_SHAPE in the measured executor (True =
    logp, False = ring, None = the shared auto rule) so forced-shape
    sweeps are costed on the schedule that actually ran."""
    if op in (Operation.config, Operation.nop):
        return None, 0.0, 0.0
    plan = select_algorithm(
        op, count, elem_bytes, world,
        max_eager_size=max_eager_size,
        eager_rx_buf_size=rx_buf_bytes,
        tuning=tuning if tuning is not None else TuningParams.default(),
    )
    m, b = coefficients_aggregate(op, plan, count, elem_bytes, world,
                                  rx_buf_bytes=rx_buf_bytes,
                                  logp_shape=logp_shape)
    return plan, m, b


def aggregate_wire_gbps(
    op_name: str,
    nbytes: int,
    world: int,
    seconds: float,
    *,
    max_eager_size: int = DEFAULT_MAX_EAGER,
    rx_buf_bytes: int = DEFAULT_RX_BUF,
    tuning: TuningParams | None = None,
    logp_shape: bool | None = None,
) -> float:
    """Aggregate wire-bytes bandwidth of one measured sweep row: the
    TOTAL bytes the planned schedule moves across all ranks
    (timing.coefficients_aggregate) divided by the measured seconds —
    the volume-honest column the r5 verdict asked the emulator sweep
    tables to carry (payload GB/s understates collectives that move
    (P-1)x their payload)."""
    if seconds <= 0 or nbytes <= 0:
        return float("nan")
    op = Operation[op_name]
    count = max(nbytes // 4, 1)
    _plan, _m, agg_bytes = span_cost(
        op, count, 4, world, max_eager_size=max_eager_size,
        rx_buf_bytes=rx_buf_bytes, tuning=tuning, logp_shape=logp_shape)
    return agg_bytes / seconds / 1e9


def native_event(
    raw: dict,
    *,
    world: int,
    track: str | None = None,
    link: LinkParams | None = None,
    max_eager_size: int = DEFAULT_MAX_EAGER,
    rx_buf_bytes: int = DEFAULT_RX_BUF,
    tuning: TuningParams | None = None,
    ts_base_ns: int | None = None,
    logp_shape: bool | None = None,
    tier: str | None = None,
) -> dict:
    """Lift one raw EmuRank.trace_read record into a SPAN v1 event.

    `ts_base_ns` rebases the runtime-relative native clock into the
    host perf_counter_ns domain (pass the host ns that corresponds to
    the runtime's creation; default anchors 0 at drain time minus the
    span's own end, which keeps relative order within a rank).
    `tier` tags the span with the two-tier link it crossed
    (args["tier"] = "inner" | "outer", a SPAN v1-compatible detail
    key): Chrome-trace tracks split by it and
    feedback.calibrate_tiers_from_trace refits each tier from exactly
    its own labeled samples."""
    op = Operation(raw["opcode"])
    count = int(raw["count"])
    nbytes = int(raw["bytes"])
    elem_bytes = max(nbytes // count, 1) if count else 4
    plan, m, b = span_cost(
        op, count, elem_bytes, world, max_eager_size=max_eager_size,
        rx_buf_bytes=rx_buf_bytes, tuning=tuning, logp_shape=logp_shape)
    dur = max(int(raw["end_ns"]) - int(raw["start_ns"]), 0)
    if ts_base_ns is None:
        ts_base_ns = time.perf_counter_ns() - int(raw["end_ns"])
    args = {
        "op": op.name,
        "count": count,
        "bytes": nbytes,
        "world": world,
        "rank": int(raw.get("rank", 0)),
        "retcode": int(raw["retcode"]),
        "detail": int(raw["detail"]),
        "measured_s": dur / 1e9,
        "d_passes": int(raw["d_passes"]),
        "d_parks": int(raw["d_parks"]),
        "d_seek_hit": int(raw["d_seek_hit"]),
        "d_seek_miss": int(raw["d_seek_miss"]),
    }
    if tier is not None:
        args["tier"] = tier
    if plan is not None:
        args["algorithm"] = plan.algorithm.name
        args["protocol"] = plan.protocol.name
        args["coef_messages"] = float(m)
        args["coef_bytes"] = float(b)
        if link is not None:
            args["predicted_s"] = link.seconds(m, b)
    return {
        "name": op.name,
        "cat": "native",
        "track": track or f"emu/r{raw.get('rank', 0)}",
        "ts_ns": ts_base_ns + int(raw["start_ns"]),
        "dur_ns": dur,
        "args": args,
    }


def drain_world(
    emu_world,
    *,
    link: LinkParams | None = None,
    max_eager_size: int = DEFAULT_MAX_EAGER,
    rx_buf_bytes: int = DEFAULT_RX_BUF,
    tuning: TuningParams | None = None,
    tracer=None,
    logp_shape: bool | None = None,
    tier: str | None = None,
    track_prefix: str = "emu",
) -> tuple[list[dict], int]:
    """Drain every rank of an EmuWorld into SPAN v1 events (one track
    per rank). Returns (events, total_dropped); when `tracer` is given
    the events are also appended to its ring. `tier` tags every
    drained span (a whole EmuWorld plays one tier of an emulated
    two-tier world — inner POE groups or the outer TCP group);
    `track_prefix` keeps the tiers' tracks apart in the export."""
    events: list[dict] = []
    dropped = 0
    now = time.perf_counter_ns()
    for rank in emu_world.ranks:
        if rank is None:
            continue
        raw, d = rank.trace_read()
        dropped += d
        # anchor each rank's runtime-relative clock so the LAST span
        # ends "now" — ranks stay mutually ordered well enough for a
        # human timeline, and exactly ordered within each rank
        base = now - max((int(r["end_ns"]) for r in raw), default=0)
        for r in raw:
            events.append(native_event(
                r, world=len(emu_world.ranks),
                track=f"{track_prefix}/r{r.get('rank', 0)}",
                link=link, max_eager_size=max_eager_size,
                rx_buf_bytes=rx_buf_bytes, tuning=tuning,
                ts_base_ns=base, logp_shape=logp_shape, tier=tier))
    if tracer is not None:
        tracer.extend(events)
    return events, dropped


def default_wire_dtype() -> DataType:
    """Uncompressed wire (native spans never ride compression lanes in
    the sweeps this module serves)."""
    return DataType.none


__all__ = [
    "span_cost",
    "aggregate_wire_gbps",
    "native_event",
    "drain_world",
    "DEFAULT_MAX_EAGER",
    "DEFAULT_RX_BUF",
    "dtype_nbytes",
]
