"""accl-tpu telemetry: tracing and metrics across every executor.

Observability lives next to the data plane (the ACCL posture: hardware
performance counters and per-call duration registers the host reads back
after the fact) and one schema threads through every layer:

  - the NATIVE trace ring (runtime.cpp record_span, ACCL_RT_TRACE=1)
    records per-call spans — opcode, bytes, start/end ns, retcode,
    deferred-mismatch detail, sequencer-counter deltas — drained through
    ctypes (EmuRank.trace_read) and lifted into events by
    telemetry.native;
  - the HOST tracer (telemetry.tracer) collects facade call spans and
    the fused-sequence record -> lint -> compile -> dispatch phases,
    every span carrying its timing.predict estimate where one exists;
  - telemetry.export renders Chrome trace-event JSON (one track per
    rank/executor, Perfetto-loadable) and the predicted-vs-measured
    residual table, validated against EVENT_SCHEMA (jsonschema);
  - telemetry.feedback closes the loop: measured spans ->
    timing.calibrate samples -> refit LinkParams -> ACCL.autotune.

Entry points: bench.py --trace emits the full trace + residual section;
tools/accl_trace.py exports/validates/selftests standalone. Host
tracing is off by default (ACCL_TELEMETRY=1 or telemetry.enable());
the disabled path is one predicate per site, gated <1% on the bench
smoke path. See docs/observability.md for the schema table and the
calibration-loop walkthrough.
"""

from .tracer import (  # noqa: F401
    DEFAULT_CAPACITY,
    SCHEMA_VERSION,
    Tracer,
    disable,
    enable,
    get_tracer,
)
from .export import (  # noqa: F401
    EVENT_SCHEMA,
    read_trace,
    residual_rows,
    residual_summary,
    to_chrome,
    validate_trace,
    write_trace,
)
from .feedback import (  # noqa: F401
    autotune_from_trace,
    calibrate_compute_from_trace,
    calibrate_from_trace,
    calibrate_tiers_from_trace,
    default_compute_fit,
    default_link,
    default_tier_links,
    residual_improvement,
    residual_report,
)
from . import native  # noqa: F401
